"""Structured JSONL query audit log.

Reference roles: the http/kafka event-listener plugins' durable sink plus
airlift's size-rotated log management (io.airlift.log) — the
machine-readable per-query trail an external audit/billing pipeline tails.
One line per `QueryCompletedEvent`, written through the filesystem SPI
(`audit.log-path`) with size-based rotation (`audit.rotate-bytes` /
`audit.rotate-keep`): `<path>` is always the live segment, `<path>.1` the
most recent rotated one.

Each line carries what an SRE pages on and what a billing pipeline meters:
query id, terminal state + error code classification, resource group, wall
seconds, device-gate wait, peak memory, row count, and the counter
snapshot of the execution (the QueryStatistics payload) — the same facts
`system.runtime.queries` shows, but durable and append-only.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Optional

from trino_tpu.filesystem import filesystem_for, strip_scheme
from trino_tpu.runtime.events import EventListener

log = logging.getLogger("trino_tpu.audit")

#: process-wide monotonic audit sequence: every appended line carries the
#: next value, so external tails detect gaps (a dropped line is visible)
#: and the decision ledger cross-references in-flight decisions against
#: shed/kill/drain events by (query_id, seq) — a decision whose
#: `audit_seq` watermark is below a kill line's seq was made BEFORE the
#: kill landed
_seq_lock = threading.Lock()
_seq = 0


def _next_sequence() -> int:
    global _seq
    with _seq_lock:
        _seq += 1
        return _seq


def sequence_watermark() -> int:
    """Highest audit sequence issued so far (0 before any line)."""
    with _seq_lock:
        return _seq


class QueryAuditLog(EventListener):
    """JSONL sink for query completions (see module doc).  Thread-safe:
    concurrent engine lanes deliver completions from their own statement
    threads, so append+rotate serialize under one lock.  Failures are the
    event manager's problem (it warns once per listener/event pair) —
    a dead audit disk never breaks queries."""

    def __init__(self, path: str, rotate_bytes: int = 64 * 1024 * 1024,
                 rotate_keep: int = 2, clock=time.time):
        self.path = strip_scheme(path)
        self.fs = filesystem_for(path)
        self.rotate_bytes = int(rotate_bytes)
        self.rotate_keep = max(1, int(rotate_keep))
        self.clock = clock
        self._lock = threading.Lock()
        # surface unwritable locations at STARTUP, not at first completion
        # (the manager swallows per-event errors)
        self.fs.append(self.path, b"")

    @classmethod
    def from_config(cls, cfg=None) -> "Optional[QueryAuditLog]":
        """Listener wired from the typed config's `audit.*` section
        (None when `audit.log-path` is unset)."""
        if cfg is None:
            from trino_tpu.config import get_config

            cfg = get_config()
        if not cfg.audit.log_path:
            return None
        return cls(
            cfg.audit.log_path,
            rotate_bytes=cfg.audit.rotate_bytes,
            rotate_keep=cfg.audit.rotate_keep,
        )

    # -- event sink -----------------------------------------------------------

    def query_completed(self, e) -> None:
        from trino_tpu.telemetry.metrics import audit_events_counter

        stats = getattr(e, "statistics", None)
        doc = {
            "seq": _next_sequence(),
            "ts": self.clock(),
            "query_id": e.query_id,
            "state": e.state,
            "error_code": e.error_code,
            "error_type": e.error_type,
            "group": getattr(stats, "group", None),
            "queued_s": getattr(stats, "queued_s", 0.0),
            "wall_s": round(e.wall_s, 6),
            "gate_wait_s": getattr(stats, "gate_wait_s", 0.0),
            "peak_memory_bytes": getattr(stats, "peak_memory_bytes", 0),
            "rows": e.rows,
            "counters": dict(getattr(stats, "counters", None) or {}),
        }
        line = (json.dumps(doc, sort_keys=True) + "\n").encode()
        with self._lock:
            size = self.fs.size(self.path)
            if (
                self.rotate_bytes > 0
                and size > 0
                and size + len(line) > self.rotate_bytes
            ):
                self._rotate_locked()
            self.fs.append(self.path, line)
        audit_events_counter().inc()

    def _rotate_locked(self) -> None:  # lint: allow(unguarded-state)
        """Caller holds self._lock.  Shift segments newest-first through
        the SPI rename primitive (O(1) locally via os.replace; an
        object-store implementation pays its copy there, not here):
        <path> -> <path>.1, <path>.1 -> <path>.2, ...; the oldest falls
        off at rotate_keep."""
        from trino_tpu.telemetry.metrics import audit_rotations_counter

        for i in range(self.rotate_keep - 1, 0, -1):
            src = f"{self.path}.{i}"
            if self.fs.exists(src):
                self.fs.rename(src, f"{self.path}.{i + 1}")
        self.fs.rename(self.path, f"{self.path}.1")
        # drop any segment beyond the keep budget
        drop = f"{self.path}.{self.rotate_keep + 1}"
        if self.fs.exists(drop):
            self.fs.delete(drop)
        audit_rotations_counter().inc()


def attach_audit_log(runner, listener: Optional[QueryAuditLog] = None):
    """Attach the audit listener to a runner's event pipeline (idempotent;
    config-driven when no listener is passed — a no-op returning None
    without `audit.log-path`)."""
    if listener is None:
        listener = QueryAuditLog.from_config()
        if listener is None:
            return None
    if any(isinstance(l, QueryAuditLog) for l in runner.events.listeners):
        return next(
            l for l in runner.events.listeners
            if isinstance(l, QueryAuditLog)
        )
    runner.events.add(listener)
    return listener
