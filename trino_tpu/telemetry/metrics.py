"""Process-wide metrics registry: counters, gauges, histograms.

Reference roles: the airlift metrics the reference exports over JMX
(TaskManager/QueryManager stats beans) plus the jmx_exporter-style Prometheus
text rendering; this module is the SINGLE home for the engine's formerly
scattered counters (MeshProfile.counters, spmd.TRACE_CACHE hit/miss/retrace,
buffer-pool bytes/hits, per-query wall histograms).

Shape:

  * `REGISTRY.counter/gauge/histogram(name, help, labelnames)` registers
    once and returns the existing metric on re-registration — callers bump
    without caring who registered;
  * `gauge_fn` registers a PULL metric: a callback evaluated at
    snapshot/render time (how TRACE_CACHE and the buffer pool surface
    without import cycles or double bookkeeping);
  * `render_prometheus()` emits the text exposition format served at
    GET /v1/metrics on coordinator and worker;
  * everything is host-side integers/floats — bumping a metric can never
    introduce a device sync (the verify/residency contract).
"""

from __future__ import annotations

import math
import threading
from collections import OrderedDict
from typing import Callable, Optional, Sequence

_PREFIX = "trino_tpu_"

#: default histogram buckets (seconds): query walls from sub-ms to minutes
DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
    30.0, 60.0, 120.0, 300.0,
)


#: guards per-metric series-dict RESIZE against concurrent scrapes: HTTP
#: handler threads render /v1/metrics while the query thread bumps.  Bumping
#: an EXISTING series never resizes its dict and stays lock-free (the hot
#: path); only first-touch inserts and the scrape-side copies take the lock.
_SERIES_LOCK = threading.Lock()


def _escape_label(v: str) -> str:
    return str(v).replace("\\", r"\\").replace("\n", r"\n").replace('"', r'\"')


def _format_labels(labelnames: Sequence[str], labelvalues: Sequence) -> str:
    if not labelnames:
        return ""
    inner = ",".join(
        f'{n}="{_escape_label(v)}"' for n, v in zip(labelnames, labelvalues)
    )
    return "{" + inner + "}"


def _format_value(v) -> str:
    if isinstance(v, float):
        if math.isinf(v):
            return "+Inf" if v > 0 else "-Inf"
        return repr(v)
    return str(v)


class _Child:
    """One (metric, label values) series."""

    __slots__ = ("metric", "labelvalues")

    def __init__(self, metric: "Metric", labelvalues: tuple):
        self.metric = metric
        self.labelvalues = labelvalues

    def inc(self, n=1) -> None:
        self.metric._inc(self.labelvalues, n)

    def set(self, v) -> None:
        self.metric._set(self.labelvalues, v)

    def observe(self, v) -> None:
        self.metric._observe(self.labelvalues, v)

    def value(self):
        return self.metric.value(self.labelvalues)


class Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str = "", labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._values: dict = {}  # labelvalues tuple -> number

    # -- label plumbing -------------------------------------------------------

    def labels(self, *values, **kv) -> _Child:
        if kv:
            values = tuple(kv[n] for n in self.labelnames)
        lv = tuple(str(v) for v in values)
        if len(lv) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, got {lv}"
            )
        return _Child(self, lv)

    # -- unlabeled shortcuts --------------------------------------------------

    def inc(self, n=1) -> None:
        self._inc((), n)

    def set(self, v) -> None:
        self._set((), v)

    def observe(self, v) -> None:
        self._observe((), v)

    def value(self, labelvalues: tuple = ()):
        return self._values.get(labelvalues, 0)

    # -- storage (the engine runs one statement at a time, so bump-vs-bump
    # needs no lock; _SERIES_LOCK covers resize-vs-scrape only) ---------------

    def _inc(self, lv: tuple, n) -> None:
        try:
            self._values[lv] += n  # existing series: no resize, no lock
        except KeyError:
            with _SERIES_LOCK:
                self._values[lv] = self._values.get(lv, 0) + n

    def _set(self, lv: tuple, v) -> None:
        if lv in self._values:
            self._values[lv] = v  # overwrite: no resize, no lock
            return
        with _SERIES_LOCK:
            self._values[lv] = v

    def _observe(self, lv: tuple, v) -> None:
        raise TypeError(f"{self.kind} metric {self.name} has no observe()")

    def touch(self, *labelvalues) -> None:
        """Pre-register a series at 0 so it renders before the first bump
        ('registered once, bumped everywhere' — scrapes see the full
        vocabulary, not just counters that happened to fire)."""
        lv = tuple(str(v) for v in labelvalues)
        with _SERIES_LOCK:
            self._values.setdefault(lv, 0)

    # -- export ---------------------------------------------------------------

    def series(self) -> list:
        """[(suffix, labelnames, labelvalues, value)] for rendering."""
        with _SERIES_LOCK:
            items = list(self._values.items())
        return [("", self.labelnames, lv, v) for lv, v in sorted(items)]


class Counter(Metric):
    kind = "counter"

    def _set(self, lv, v):
        raise TypeError(f"counter {self.name} cannot be set(); use inc()")


class Gauge(Metric):
    kind = "gauge"


class CallbackGauge(Metric):
    """Pull-style metric: `fn` is evaluated at render/snapshot time and
    returns either a scalar (unlabeled) or {labelvalues tuple: value}.
    `kind_hint` lets a monotonically-increasing source render as a counter
    (TRACE_CACHE.hits is a counter even though we read it by callback)."""

    def __init__(self, name, help="", labelnames=(), fn: Callable = None,
                 kind_hint: str = "gauge"):
        super().__init__(name, help, labelnames)
        self.fn = fn
        self.kind = kind_hint

    def _inc(self, lv, n):
        raise TypeError(f"callback metric {self.name} is read-only")

    _set = _inc

    def series(self) -> list:
        try:
            out = self.fn()
        except Exception:
            return []
        if not isinstance(out, dict):
            return [("", self.labelnames, (), out)]
        return [
            ("", self.labelnames, tuple(str(x) for x in (lv if isinstance(lv, tuple) else (lv,))), v)
            for lv, v in sorted(out.items())
        ]

    def value(self, labelvalues: tuple = ()):
        for _, _, lv, v in self.series():
            if lv == tuple(str(x) for x in labelvalues):
                return v
        return 0


class Histogram(Metric):
    kind = "histogram"

    def __init__(self, name, help="", labelnames=(), buckets=None):
        super().__init__(name, help, labelnames)
        bs = tuple(sorted(buckets if buckets is not None else DEFAULT_BUCKETS))
        if not bs or bs[-1] != float("inf"):
            bs = bs + (float("inf"),)
        self.buckets = bs
        # labelvalues -> [counts per bucket, sum, count]
        self._obs: dict = {}

    def _observe(self, lv: tuple, v) -> None:
        st = self._obs.get(lv)
        if st is None:
            with _SERIES_LOCK:  # first observe for this series: dict insert
                st = self._obs.setdefault(
                    lv, [[0] * len(self.buckets), 0.0, 0]
                )
        counts, _, _ = st
        for i, b in enumerate(self.buckets):
            if v <= b:
                counts[i] += 1
                break
        st[1] += v
        st[2] += 1

    def _inc(self, lv, n):
        raise TypeError(f"histogram {self.name} has no inc(); use observe()")

    _set = _inc

    def value(self, labelvalues: tuple = ()):
        st = self._obs.get(tuple(labelvalues))
        return 0 if st is None else st[2]

    def series(self) -> list:
        out = []
        with _SERIES_LOCK:
            items = list(self._obs.items())
        for lv, (counts, total, n) in sorted(items):
            cum = 0
            for b, c in zip(self.buckets, counts):
                cum += c
                le = "+Inf" if math.isinf(b) else _format_value(float(b))
                out.append(
                    ("_bucket", self.labelnames + ("le",), lv + (le,), cum)
                )
            out.append(("_sum", self.labelnames, lv, total))
            out.append(("_count", self.labelnames, lv, n))
        return out


class MetricsRegistry:
    def __init__(self):
        self._metrics: OrderedDict[str, Metric] = OrderedDict()
        self._lock = threading.Lock()

    def _register(self, cls, name: str, help: str, labelnames, **kw) -> Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise TypeError(
                        f"metric {name} already registered as {m.kind}"
                    )
                return m
            m = cls(name, help, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help="", labelnames=()) -> Counter:
        return self._register(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()) -> Gauge:
        return self._register(Gauge, name, help, labelnames)

    def gauge_fn(self, name, help, fn, labelnames=(),
                 kind_hint: str = "gauge") -> CallbackGauge:
        return self._register(
            CallbackGauge, name, help, labelnames, fn=fn, kind_hint=kind_hint
        )

    def histogram(self, name, help="", labelnames=(), buckets=None) -> Histogram:
        return self._register(
            Histogram, name, help, labelnames, buckets=buckets
        )

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    # -- export ---------------------------------------------------------------

    def snapshot(self) -> dict:
        """{metric name (+series suffix/labels): value} — the flat form
        bench.py records into BENCH_EXTRA.json and compare_bench.py diffs."""
        out: dict = {}
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            for suffix, lnames, lvalues, v in m.series():
                key = m.name + suffix + _format_labels(lnames, lvalues)
                out[key] = v
        return out

    def rows(self) -> list:
        """[(name, kind, labels, value)] — the system.metrics table feed."""
        out = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            for suffix, lnames, lvalues, v in m.series():
                out.append(
                    (
                        m.name + suffix,
                        m.kind,
                        _format_labels(lnames, lvalues).strip("{}"),
                        float(v),
                    )
                )
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition format (served at GET /v1/metrics)."""
        lines = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for suffix, lnames, lvalues, v in m.series():
                lines.append(
                    m.name
                    + suffix
                    + _format_labels(lnames, lvalues)
                    + " "
                    + _format_value(v)
                )
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Drop all registered metrics (tests only)."""
        with self._lock:
            self._metrics.clear()
        _register_engine_metrics(self)


#: the process-wide registry (one engine process per host, like a worker JVM)
REGISTRY = MetricsRegistry()


# -- engine metric vocabulary -------------------------------------------------

#: MeshProfile counter names pre-registered so /v1/metrics exposes the full
#: vocabulary (exchange/speculation counters included) before any query runs;
#: names track verify/residency.ALLOWED_COUNTERS plus the violation counters
#: that must stay zero.
MESH_COUNTER_NAMES = (
    "host_restack",
    "host_gather",
    "result_gather",
    "state_gather",
    "scan_cache_hit",
    "scan_cache_miss",
    "scan_bucketize",
    "dynamic_filter_sync",
    "spool_read",
    "spool_write",
    "exchange_elided",
    "repartition_collective",
    "join_overflow_check",
    "join_capacity_sync",
    "join_speculative_retry",
    "join_capacity_proven",
    "collective_async",
    "memory_wave",
    "spill_bytes",
)


#: wave-capable operator vocabulary for trino_tpu_memory_waves_total,
#: pre-registered so the compare_bench zero-when-unconstrained gate reads
#: real zeros, not absent series
MEMORY_WAVE_OPERATORS = ("join", "aggregation", "window", "sort")


#: (kind, purpose) label pairs pre-registered on the per-collective byte
#: counter so scrapes see the attribution vocabulary before any query runs;
#: runner/exchange call sites bump through MeshProfile.add_collective.
COLLECTIVE_VOCABULARY = (
    ("all_to_all", "repartition"),
    ("all_gather", "broadcast"),
    ("reduce", "dynamic_filter"),
    ("gather", "capacity_sizing"),
    ("gather", "result_gather"),
    ("gather", "host_gather"),
)


#: decimal-sum kernel path vocabulary (ops/aggregation._sum128 + the
#: window frame sums), pre-registered so the zero-runtime-check gate in
#: tools/compare_bench.py reads real zeros, not absent series
DECIMAL_FASTPATHS = ("proven", "runtime_check", "limb")


#: join capacity-sizing outcome vocabulary (verify/capacity.py +
#: parallel/runner._sized_expansion): proven = a capacity certificate
#: licensed a fixed-capacity expand (no sizing gather, no overflow flag),
#: runtime_check = the speculative/sizing fallback ran its runtime
#: protocol.  Pre-registered so the compare_bench check_licenses gate
#: reads real zeros, not absent series.
JOIN_CAPACITY_OUTCOMES = ("proven", "runtime_check", "declined")


#: plan-decision vocabulary (telemetry/decisions.py), pre-registered on
#: BOTH exposition endpoints (coordinator and worker /v1/metrics render
#: the same process registry) so scrapes see the full (kind, outcome,
#: hindsight) grid at zero before the first statement decides anything.
#: `pending` counts recordings at decision time; the hindsight verdicts
#: count at finalize.
PLAN_DECISION_SERIES = (
    ("join_distribution", ("broadcast", "partitioned", "colocated")),
    ("join_capacity", ("licensed", "declined", "runtime_check")),
    ("dictionary_placement", ("coded_colocate",)),
    ("schedule_license", ("async", "sync")),
    ("wave", ("waves",)),
    ("exchange", ("repartition", "broadcast", "gather", "merge", "elide")),
    ("recovery", ("retry", "replan", "fail")),
)

PLAN_DECISION_HINDSIGHT = ("pending", "vindicated", "regret", "unmeasured")


#: membership transition vocabulary, pre-registered so scrapes see
#: join/drain/death at 0 before any transition fires
MEMBERSHIP_EVENT_KINDS = ("join", "drain", "death", "rejoin", "shrink_replan")


#: task-recovery classification vocabulary (the FTE retry-vs-replan-vs-
#: fail table in runtime/lifecycle), pre-registered so the chaos gate
#: reads real zeros for the outcomes that must NOT fire
TASK_RETRY_OUTCOMES = ("retry", "replan", "fail")


#: resource groups pre-registered on the serving metrics so scrapes see
#: the admission vocabulary before the first statement; the dispatcher
#: touches further groups at construction
DEFAULT_SERVE_GROUPS = ("global", "system.prewarm")


#: prewarm-run vocabulary, pre-registered so scrapes see every
#: (trigger, outcome) cell at 0 before the first replay fires
PREWARM_REASONS = ("start", "grow", "manual")
PREWARM_OUTCOMES = ("warm", "unclosed", "failed", "empty")

#: prewarm executor state -> trino_tpu_prewarm_state gauge code
PREWARM_STATE_CODES = {
    "IDLE": 0, "RUNNING": 1, "WARM": 2, "UNCLOSED": 3, "FAILED": 4,
}


#: device-gate histogram buckets (seconds): gate waits/holds are the
#: per-step time-slice granularity — sub-100µs uncontended, up to whole
#: fragment walls when a long build holds the gate against other lanes
GATE_SECONDS_BUCKETS = (
    0.00001, 0.0001, 0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0,
)


def _compile_events_total():
    from trino_tpu.telemetry.compile_events import OBSERVATORY

    return OBSERVATORY.count


def _trace_cache_series(stat: str):
    def read():
        from trino_tpu.parallel.spmd import TRACE_CACHE

        return getattr(TRACE_CACHE, stat)

    return read


def _pool_series(stat_suffix: str):
    def read():
        from trino_tpu.runtime.buffer_pool import POOL

        s = POOL.stats()
        return {
            ("host",): s[f"host_{stat_suffix}"],
            ("device",): s[f"device_{stat_suffix}"],
        }

    return read


def _register_engine_metrics(reg: MetricsRegistry) -> None:
    """Register the engine-wide vocabulary once (import time + reset)."""
    mesh = reg.counter(
        _PREFIX + "mesh_events_total",
        "mesh execution events by counter name (MeshProfile counters: "
        "transfers, cache hits, exchange elision, speculation)",
        labelnames=("counter",),
    )
    for name in MESH_COUNTER_NAMES:
        mesh.touch(name)
    completed = reg.counter(
        _PREFIX + "queries_total",
        "completed queries by state and error type",
        labelnames=("state", "error_type"),
    )
    completed.touch("FINISHED", "")
    completed.touch("FAILED", "USER_ERROR")
    completed.touch("FAILED", "INTERNAL_ERROR")
    completed.touch("FAILED", "RESOURCE_ERROR")
    completed.touch("CANCELED", "USER_ERROR")
    reg.histogram(
        _PREFIX + "query_wall_seconds",
        "end-to-end statement wall time",
    )
    reg.histogram(
        _PREFIX + "query_queued_seconds",
        "seconds a statement waited in its resource group's admission "
        "queue before an engine lane ran it (runtime/dispatcher); "
        "observed on admission, cancel, expiry, and shed",
    )
    queued = reg.gauge(
        _PREFIX + "queries_queued",
        "statements waiting in each resource group's admission queue",
        labelnames=("group",),
    )
    running = reg.gauge(
        _PREFIX + "queries_running",
        "statements running on engine lanes per resource group",
        labelnames=("group",),
    )
    shed = reg.counter(
        _PREFIX + "queries_shed_total",
        "statements shed because a resource group's queue was full "
        "(HTTP 429 + Retry-After before the body is read — a retryable "
        "client error, never a hang)",
        labelnames=("group",),
    )
    for g in DEFAULT_SERVE_GROUPS:
        queued.touch(g)
        running.touch(g)
        shed.touch(g)
    reg.counter(
        _PREFIX + "query_retraces_total",
        "SPMD retraces attributed to completed distributed queries "
        "(bumped per query by the stage executor; zero warm)",
    )
    reg.counter(
        _PREFIX + "memory_kills_total",
        "queries killed by the low-memory killer (largest reservation "
        "reclaimed when the shared pool blocks)",
    )
    waves = reg.counter(
        _PREFIX + "memory_waves_total",
        "partition waves executed under memory pressure, by operator "
        "(runtime/spill: an over-budget build/agg/window/sort degrades to "
        "k hash-partition waves instead of dying; zero when unconstrained)",
        labelnames=("operator",),
    )
    for op in MEMORY_WAVE_OPERATORS:
        waves.touch(op)
    reg.counter(
        _PREFIX + "spill_bytes_total",
        "bytes spilled host-side through the filesystem SPI by "
        "partition-wave execution (the FTE SpoolManager npz format; zero "
        "when unconstrained)",
    )
    reg.counter(
        _PREFIX + "memory_revocations_total",
        "memory revocations: a registered wave-capable operator asked to "
        "spill and release its reservation before the low-memory killer "
        "fires (the revoke tier of the exceed -> revoke -> wave -> kill "
        "escalation ladder)",
    )
    reg.counter(
        _PREFIX + "breaker_trips_total",
        "circuit-breaker transitions to OPEN on the multi-host HTTP tier",
    )
    reg.gauge_fn(
        _PREFIX + "breaker_state",
        "per-worker circuit breaker state (0 closed, 1 half-open, 2 open)",
        _breaker_series,
        labelnames=("worker",),
    )
    membership = reg.counter(
        _PREFIX + "membership_events_total",
        "cluster membership transitions by kind (runtime/membership: "
        "worker join/drain/death, rejoin after death, and mesh-shrink "
        "re-plans of running queries)",
        labelnames=("kind",),
    )
    for kind in MEMBERSHIP_EVENT_KINDS:
        membership.touch(kind)
    reg.gauge(
        _PREFIX + "worker_alive",
        "per-worker liveness from the heartbeat failure detector "
        "(1 = ACTIVE/DRAINING, 0 = DEAD)",
        labelnames=("worker",),
    )
    retries = reg.counter(
        _PREFIX + "task_retries_total",
        "task-level recovery classifications under fault-tolerant "
        "execution, by outcome (retry = same plan, lost tasks re-run from "
        "spooled intermediates; replan = mesh signature truly changed, "
        "re-fragment at the shrunk W; fail = user/semantic error, never "
        "retried)",
        labelnames=("outcome",),
    )
    for outcome in TASK_RETRY_OUTCOMES:
        retries.touch(outcome)
    reg.counter(
        _PREFIX + "spooled_fragments_total",
        "fragment outputs spooled through the filesystem SPI keyed by "
        "(query_id, fragment_id, attempt_id); zero when "
        "fault_tolerant_execution is off and retry_policy is not TASK",
    )
    prewarm = reg.counter(
        _PREFIX + "prewarm_runs_total",
        "prewarm-executor replays by trigger reason and outcome "
        "(runtime/prewarm: warm = closed key set, unclosed = the verify "
        "replay still compiled, failed = a statement raised)",
        labelnames=("reason", "outcome"),
    )
    for reason in PREWARM_REASONS:
        for outcome in PREWARM_OUTCOMES:
            prewarm.touch(reason, outcome)
    reg.counter(
        _PREFIX + "prewarm_statements_total",
        "statement executions performed by prewarm replays",
    )
    reg.gauge(
        _PREFIX + "prewarm_state",
        "prewarm executor state (0 idle, 1 running, 2 warm, 3 unclosed, "
        "4 failed)",
    )
    reg.counter(
        _PREFIX + "drain_force_kills_total",
        "tasks force-canceled because worker.drain-task-wait expired "
        "during a graceful drain (the bounded-drain escalation)",
    )
    # device-gate / lane contention telemetry (runtime/dispatcher
    # device_slice): wait is observed on CONTENDED acquires only, hold on
    # holds during which another lane waited — the uncontended single-lane
    # step stays one clock read (zero-cost-when-idle, the pressure-counter
    # contract), so an idle scrape sees both series present at 0
    reg.histogram(
        _PREFIX + "device_gate_wait_seconds",
        "seconds an engine lane waited to acquire the device time-slice "
        "gate (contended acquires only; uncontended steps never observe)",
        buckets=GATE_SECONDS_BUCKETS,
    )
    reg.histogram(
        _PREFIX + "device_gate_hold_seconds",
        "seconds the device gate was held while another lane waited "
        "(the contention-relevant holds; uncontended holds are not timed)",
        buckets=GATE_SECONDS_BUCKETS,
    )
    reg.gauge_fn(
        _PREFIX + "device_gate_occupied",
        "which engine lane currently holds the device time-slice gate "
        "(1 on the holding lane's series; empty when the gate is idle)",
        _gate_occupancy_series,
        labelnames=("lane",),
    )
    reg.gauge_fn(
        _PREFIX + "device_gate_waiters",
        "engine lanes currently blocked waiting for the device gate",
        _gate_waiters,
    )
    # query performance observatory (telemetry/profile_store +
    # telemetry/audit): pre-registered AND touched so scrapes see the
    # archive/audit vocabulary as real zeros before the first statement
    # completes (the project convention since PR 4)
    reg.counter(
        _PREFIX + "profiles_archived_total",
        "per-query profile artifacts archived by the profile store "
        "(telemetry/profile_store; written through the filesystem SPI "
        "off the hot path after FINISHING)",
    ).touch()
    reg.counter(
        _PREFIX + "profiles_pruned_total",
        "archived profile artifacts deleted by the retention sweep "
        "(profile.retention-max-age / profile.retention-max-count)",
    ).touch()
    reg.counter(
        _PREFIX + "audit_events_total",
        "query-completion lines appended to the JSONL audit log "
        "(telemetry/audit.QueryAuditLog)",
    ).touch()
    reg.counter(
        _PREFIX + "audit_rotations_total",
        "audit-log size-based rotations (audit.rotate-bytes)",
    ).touch()
    reg.histogram(
        _PREFIX + "compile_seconds",
        "wall seconds per SPMD trace+XLA-compile (compile observatory "
        "events; see system.runtime.compilations)",
    )
    reg.gauge_fn(
        _PREFIX + "compile_events_total",
        "trace-cache misses recorded by the compile observatory "
        "(zero new events on warm replays)",
        _compile_events_total,
        kind_hint="counter",
    )
    fastpath = reg.counter(
        _PREFIX + "decimal_fastpath_total",
        "decimal-sum kernel path selections at TRACE time (ops/aggregation "
        "+ ops/window): proven = statically licensed single-plane i64 sum "
        "(range certificate or precision proof, no runtime check), "
        "runtime_check = a lax.cond fits probe was compiled in, limb = "
        "unconditional limb-plane arithmetic",
        labelnames=("path",),
    )
    for p in DECIMAL_FASTPATHS:
        fastpath.touch(p)
    joincap = reg.counter(
        _PREFIX + "join_capacity_total",
        "join expand-capacity decisions (parallel/runner._sized_expansion): "
        "proven = compiled at a capacity-certificate-licensed fixed "
        "capacity with ZERO runtime sizing (no gather, no overflow flag, "
        "no retry; verify/capacity.py), runtime_check = the speculative/"
        "sizing fallback ran its runtime protocol",
        labelnames=("outcome",),
    )
    for o in JOIN_CAPACITY_OUTCOMES:
        joincap.touch(o)
    decisions = reg.counter(
        _PREFIX + "plan_decisions_total",
        "plan-decision ledger entries (telemetry/decisions.py) by decision "
        "kind, chosen outcome, and hindsight verdict: pending counts at "
        "decision time; vindicated/regret/unmeasured count once the runner "
        "joins each decision with its measured outcome",
        labelnames=("kind", "outcome", "hindsight"),
    )
    for kind, outcomes in PLAN_DECISION_SERIES:
        for o in outcomes:
            for h in PLAN_DECISION_HINDSIGHT:
                decisions.touch(kind, o, h)
    reg.counter(
        _PREFIX + "collective_async_total",
        "independent child fragments pre-dispatched asynchronously under a "
        "collective-schedule license (verify/schedule.py): exchange "
        "dispatch overlapped the consumer fragment's host work",
    ).touch()
    collective = reg.counter(
        _PREFIX + "collective_bytes_total",
        "bytes moved by mesh collectives/gathers, by collective kind and "
        "purpose (the per-collective split of MeshProfile collective_bytes)",
        labelnames=("kind", "purpose"),
    )
    for kind, purpose in COLLECTIVE_VOCABULARY:
        collective.touch(kind, purpose)
    for stat, hint in (
        ("hits", "counter"),
        ("misses", "counter"),
        ("retraces", "counter"),
        ("evictions", "counter"),
    ):
        reg.gauge_fn(
            _PREFIX + f"trace_cache_{stat}_total",
            f"process-wide compiled-SPMD-program cache {stat}",
            _trace_cache_series(stat),
            kind_hint=hint,
        )
    reg.gauge_fn(
        _PREFIX + "trace_cache_entries",
        "live compiled programs in the trace cache",
        _trace_cache_entries,
    )
    for suffix, help_txt in (
        ("bytes", "buffer-pool resident bytes per tier"),
        ("hits", "buffer-pool hits per tier"),
        ("misses", "buffer-pool misses per tier"),
    ):
        reg.gauge_fn(
            _PREFIX + f"buffer_pool_{suffix}",
            help_txt,
            _pool_series(suffix),
            labelnames=("tier",),
            kind_hint="counter" if suffix != "bytes" else "gauge",
        )


def _trace_cache_entries():
    from trino_tpu.parallel.spmd import TRACE_CACHE

    return TRACE_CACHE.stats()["entries"]


def _breaker_series():
    from trino_tpu.runtime.retry import BREAKER_STATE_CODES, BREAKERS

    return {
        (worker,): BREAKER_STATE_CODES[state]
        for worker, state in BREAKERS.states().items()
    }


def _gate_occupancy_series():
    from trino_tpu.runtime import dispatcher

    holder = dispatcher.gate_holder()
    return {} if holder < 0 else {(str(holder),): 1}


def _gate_waiters():
    from trino_tpu.runtime import dispatcher

    return dispatcher.gate_waiters()


def mesh_events_counter() -> Counter:
    """The labeled mesh-event counter MeshProfile.bump mirrors into."""
    return REGISTRY.counter(_PREFIX + "mesh_events_total")


def decimal_fastpath_counter() -> Counter:
    """Trace-time decimal-sum path selections, labeled path=proven|
    runtime_check|limb.  Bumped when a kernel TRACES (path choice is
    static per compiled program): warm replays add nothing, so a warm run
    with runtime_check deltas == 0 proves the workload runs entirely on
    statically-licensed sums."""
    return REGISTRY.counter(_PREFIX + "decimal_fastpath_total")


def join_capacity_counter() -> Counter:
    """Join expand-capacity decisions, labeled outcome=proven|runtime_check.
    A warm licensed workload bumps ONLY proven — compare_bench
    check_licenses gates runtime_check == 0 over the benched warm runs."""
    return REGISTRY.counter(_PREFIX + "join_capacity_total")


def collective_async_counter() -> Counter:
    """Schedule-licensed asynchronous child-fragment pre-dispatches."""
    return REGISTRY.counter(_PREFIX + "collective_async_total")


def plan_decisions_counter() -> Counter:
    """Plan-decision ledger entries, labeled (kind, outcome, hindsight).
    compare_bench check_decisions gates regret == 0 over the warm benched
    set."""
    return REGISTRY.counter(_PREFIX + "plan_decisions_total")


def queries_counter() -> Counter:
    return REGISTRY.counter(_PREFIX + "queries_total")


def query_retraces_counter() -> Counter:
    return REGISTRY.counter(_PREFIX + "query_retraces_total")


def query_wall_histogram() -> Histogram:
    return REGISTRY.histogram(_PREFIX + "query_wall_seconds")


def query_queued_histogram() -> Histogram:
    """Admission-queue wait per statement (runtime/dispatcher)."""
    return REGISTRY.histogram(_PREFIX + "query_queued_seconds")


def queries_queued_gauge() -> Gauge:
    """Queued statements per resource group (dispatcher-maintained)."""
    return REGISTRY.gauge(_PREFIX + "queries_queued")


def queries_running_gauge() -> Gauge:
    """Running statements per resource group (dispatcher-maintained)."""
    return REGISTRY.gauge(_PREFIX + "queries_running")


def queries_shed_counter() -> Counter:
    """Statements shed on a full resource-group queue (HTTP 429)."""
    return REGISTRY.counter(_PREFIX + "queries_shed_total")


def memory_kills_counter() -> Counter:
    """Victims chosen by the LowMemoryKiller (runtime/lifecycle)."""
    return REGISTRY.counter(_PREFIX + "memory_kills_total")


def memory_waves_counter() -> Counter:
    """Partition waves executed under memory pressure, labeled by the
    wave-capable operator (runtime/spill)."""
    return REGISTRY.counter(_PREFIX + "memory_waves_total")


def spill_bytes_counter() -> Counter:
    """Bytes spilled through the filesystem SPI by partition-wave
    execution (runtime/spill SpillManager)."""
    return REGISTRY.counter(_PREFIX + "spill_bytes_total")


def memory_revocations_counter() -> Counter:
    """Revoke-tier activations: an operator spilled + released before the
    killer fired (runtime/spill MemoryEscalation)."""
    return REGISTRY.counter(_PREFIX + "memory_revocations_total")


def breaker_trips_counter() -> Counter:
    return REGISTRY.counter(_PREFIX + "breaker_trips_total")


def membership_events_counter() -> Counter:
    """Cluster membership transitions (runtime/membership)."""
    return REGISTRY.counter(_PREFIX + "membership_events_total")


def worker_alive_gauge() -> Gauge:
    """Per-worker liveness set by the heartbeat failure detector."""
    return REGISTRY.gauge(_PREFIX + "worker_alive")


def task_retries_counter() -> Counter:
    """Task-level recovery classifications (runtime FTE), labeled
    outcome=retry (same plan, lost tasks only) | replan (mesh signature
    truly changed: re-fragment at the shrunk W) | fail (user/semantic —
    never retried).  The chaos gate reads this: a retryable worker kill
    under fault_tolerant_execution must bump retry and leave replan/fail
    untouched."""
    return REGISTRY.counter(_PREFIX + "task_retries_total")


def spooled_fragments_counter() -> Counter:
    """Fragment outputs spooled through the filesystem SPI keyed by
    (query_id, fragment_id, attempt_id) — the replayable intermediates a
    recovery pass resumes from instead of re-running finished stages."""
    return REGISTRY.counter(_PREFIX + "spooled_fragments_total")


def compile_seconds_histogram() -> Histogram:
    """Per-event compile wall (bumped by the compile observatory)."""
    return REGISTRY.histogram(_PREFIX + "compile_seconds")


def collective_bytes_counter() -> Counter:
    """The labeled per-collective byte counter MeshProfile.add_collective
    mirrors into."""
    return REGISTRY.counter(_PREFIX + "collective_bytes_total")


def prewarm_runs_counter() -> Counter:
    """Prewarm replays by (reason, outcome) — runtime/prewarm."""
    return REGISTRY.counter(_PREFIX + "prewarm_runs_total")


def prewarm_statements_counter() -> Counter:
    return REGISTRY.counter(_PREFIX + "prewarm_statements_total")


def prewarm_state_gauge() -> Gauge:
    """Executor state as a code (PREWARM_STATE_CODES)."""
    return REGISTRY.gauge(_PREFIX + "prewarm_state")


def drain_force_kills_counter() -> Counter:
    """Tasks force-canceled by the bounded-drain escalation."""
    return REGISTRY.counter(_PREFIX + "drain_force_kills_total")


def gate_wait_histogram() -> Histogram:
    """Contended device-gate acquire waits (runtime/dispatcher)."""
    return REGISTRY.histogram(_PREFIX + "device_gate_wait_seconds")


def gate_hold_histogram() -> Histogram:
    """Device-gate holds during which another lane waited."""
    return REGISTRY.histogram(_PREFIX + "device_gate_hold_seconds")


def profiles_archived_counter() -> Counter:
    """Profile artifacts archived (telemetry/profile_store)."""
    return REGISTRY.counter(_PREFIX + "profiles_archived_total")


def profiles_pruned_counter() -> Counter:
    """Artifacts deleted by the retention sweep."""
    return REGISTRY.counter(_PREFIX + "profiles_pruned_total")


def audit_events_counter() -> Counter:
    """Lines appended to the JSONL audit log (telemetry/audit)."""
    return REGISTRY.counter(_PREFIX + "audit_events_total")


def audit_rotations_counter() -> Counter:
    """Audit-log size-based rotations."""
    return REGISTRY.counter(_PREFIX + "audit_rotations_total")


_register_engine_metrics(REGISTRY)
