"""Query performance observatory: the persistent per-query profile archive.

Reference roles: QueryInfo JSON persisted per query (the reference writes
the full QueryStats tree to disk and serves it at /v1/query/{id}), plus the
event-listener pipeline that makes completed-query statistics durable —
what makes the reference's perf work *navigable*: any two runs of a
statement can be diffed, weeks apart, without re-measuring from memory.

This engine had the opposite shape until now: every profile surface was
last-query-only (`runner.last_mesh_profile`, a 64-query span ring), so the
ROADMAP item-2 Q3 drift (1.62x -> 4.46x across seven PRs) could be SEEN in
BENCH_EXTRA walls but not ATTRIBUTED — there was literally nothing to diff
against.  This module closes that:

  * `build_artifact` assembles ONE structured JSON artifact per completed
    statement: wall + per-phase decomposition (trace/compute/collective/
    transfer/other from the MeshProfile, plus the device-gate wait and a
    signed `unattributed` remainder so **phases always sum to wall_s
    exactly** — the invariant `tools/profile_diff.py` relies on), the
    per-fragment stats with `collective_bytes_by`, counters, trace-cache
    stats, the span tree, compile events attributed to the query,
    admission info (group, queued seconds), and peak memory — keyed by
    (query_id, sql_hash, mesh signature, bucket set);
  * `ProfileStore` persists artifacts through the filesystem SPI
    (`profile.archive-dir`), OFF the hot path (a single named background
    writer thread; the statement thread only assembles the dict), keeps a
    bounded in-memory ring for `system.runtime.query_profiles` and
    `GET /v1/query/{id}/profile`, and runs the retention sweep
    (`profile.retention-max-age` / `profile.retention-max-count`) with an
    injectable clock;
  * `tools/profile_diff.py` consumes two artifacts and decomposes the
    wall delta into compile vs compute vs collective vs transfer vs
    gate-wait per fragment — drift attribution instead of drift rumor.
"""

from __future__ import annotations

import hashlib
import json
import queue
import threading
import time
from collections import OrderedDict
from typing import Callable, Optional

from trino_tpu.filesystem import filesystem_for, strip_scheme

#: artifact schema version (bump on incompatible shape changes so
#: profile_diff can refuse apples-vs-oranges comparisons loudly)
ARTIFACT_VERSION = 1

#: phase vocabulary of the artifact-level decomposition: the MeshProfile
#: phases, the device-gate wait, and the signed remainder that makes the
#: set sum to wall_s exactly (host planning/serialization and, for purely
#: local executions, all device work land in `unattributed`)
ARTIFACT_PHASES = (
    "trace", "compute", "collective", "transfer", "other",
    "gate_wait", "unattributed",
)

#: spans stored per artifact (profiles are diff inputs, not trace
#: replacements; the full tree stays on GET /v1/query/{id}/trace)
MAX_SPANS = 512
#: compile events stored per artifact
MAX_COMPILE_EVENTS = 256


def sql_hash(sql: str) -> str:
    """Stable statement fingerprint (whitespace-normalized)."""
    norm = " ".join(sql.split()).lower()
    return hashlib.blake2s(norm.encode()).hexdigest()[:16]


def _artifact_key(query_id: str, shash: str, mesh: str, buckets) -> str:
    mesh_fp = hashlib.blake2s(
        (str(mesh) + str(sorted(buckets or ()))).encode()
    ).hexdigest()[:8]
    return f"{query_id}-{shash[:12]}-{mesh_fp}"


def build_artifact(
    query_id: str,
    sql: str,
    state: str,
    wall_s: float,
    rows: int = 0,
    mesh_profile=None,
    tracer=None,
    gate_wait_s: float = 0.0,
    peak_memory_bytes: int = 0,
    admission=None,
    mesh: str = "local",
    compile_events=None,
    error_code=None,
    created_at: Optional[float] = None,
    decisions=None,
) -> dict:
    """Assemble one archived profile artifact (plain JSON-able dict).

    The phase decomposition invariant: ``sum(artifact['phases'].values())
    == artifact['wall_s']`` EXACTLY, because `unattributed` is defined as
    the signed remainder — time the profile did not see (host planning,
    result serialization, local device work) is named, never vanished,
    and `profile_diff`'s per-phase attributions therefore sum to the wall
    delta by construction."""
    phases = {p: 0.0 for p in ARTIFACT_PHASES}
    fragments = []
    counters: dict = {}
    trace_cache: dict = {}
    collective_by: dict = {}
    if mesh_profile is not None:
        prof = mesh_profile.to_json()
        fragments = prof["fragments"]
        counters = dict(prof["counters"])
        trace_cache = dict(prof["trace_cache"])
        collective_by = dict(prof["collective_bytes_by"])
        for k, v in mesh_profile.phase_totals().items():
            if k in phases:
                phases[k] = float(v)
            else:  # future phase names never silently drop
                phases[k] = phases.get(k, 0.0) + float(v)
    phases["gate_wait"] = round(float(gate_wait_s), 9)
    tracked = sum(v for k, v in phases.items() if k != "unattributed")
    phases["unattributed"] = wall_s - tracked
    events = []
    buckets: set = set()
    compile_s = 0.0
    for ev in compile_events or ():
        if ev.query_id != query_id:
            continue
        if ev.bucket is not None:
            buckets.add(int(ev.bucket))
        compile_s += ev.wall_s
        if len(events) < MAX_COMPILE_EVENTS:
            events.append(
                {
                    "step": ev.step,
                    "bucket": ev.bucket,
                    "fragment": ev.fragment,
                    "wall_s": round(ev.wall_s, 6),
                    "key_fp": ev.key_fp,
                }
            )
    spans = []
    if tracer is not None and getattr(tracer, "enabled", False):
        spans = tracer.flat_spans()[:MAX_SPANS]
    group, queued_s = (admission or (None, 0.0))
    shash = sql_hash(sql)
    return {
        "version": ARTIFACT_VERSION,
        "key": _artifact_key(query_id, shash, mesh, buckets),
        "query_id": query_id,
        "sql": sql[:2000],
        "sql_hash": shash,
        "state": state,
        "error_code": error_code,
        "created_at": (
            time.time() if created_at is None else float(created_at)
        ),
        "rows": rows,
        "wall_s": wall_s,
        "mesh": str(mesh),
        "buckets": sorted(buckets),
        "phases": phases,
        "fragments": fragments,
        "counters": counters,
        "trace_cache": trace_cache,
        "collective_bytes_by": collective_by,
        "compile": {"events": events, "compile_s": round(compile_s, 6)},
        "admission": {"group": group, "queued_s": round(queued_s, 6)},
        "gate": {"wait_s": round(float(gate_wait_s), 9)},
        "peak_memory_bytes": int(peak_memory_bytes),
        "spans": spans,
        "decisions": decisions,
    }


def artifact_from_runner(runner, ctx, sql: str, state: str, wall_s: float,
                         rows: int = 0, error_code=None) -> dict:
    """Assemble the artifact for a just-completed statement from the
    engine surfaces the runner already holds (called by
    LocalQueryRunner.execute after FINISHING; the heavy half — the SPI
    write — happens on the store's writer thread, not here)."""
    from trino_tpu.runtime.lifecycle import current_admission
    from trino_tpu.telemetry.compile_events import OBSERVATORY

    mesh = "local"
    wm = getattr(runner, "wm", None)
    if wm is not None:
        try:
            from trino_tpu.parallel.spmd import mesh_key

            mesh = str(mesh_key(wm))
        except Exception:
            mesh = f"mesh[{getattr(wm, 'n', '?')}]"
    return build_artifact(
        query_id=ctx.query_id,
        sql=sql,
        state=state,
        wall_s=wall_s,
        rows=rows,
        mesh_profile=ctx.mesh_profile,
        tracer=ctx.tracer,
        gate_wait_s=ctx.gate_wait_s,
        peak_memory_bytes=ctx.peak_memory,
        admission=current_admission(),
        mesh=mesh,
        compile_events=OBSERVATORY.events(),
        error_code=error_code,
        decisions=(
            ctx.decisions.to_json()
            if getattr(ctx, "decisions", None) is not None
            else None
        ),
    )


class ProfileStore:
    """Bounded in-memory ring + filesystem-SPI archive of profile
    artifacts.  Thread-safe: statement threads on concurrent engine lanes
    call `archive()` simultaneously; one background writer drains the
    queue so the SPI write never sits on the statement hot path.  Every
    write goes through `FileSystem.write` (atomic publish), so concurrent
    completions produce K distinct, never-torn JSON files."""

    def __init__(
        self,
        archive_dir: str = "",
        retention_max_age_s: float = 0.0,
        retention_max_count: int = 0,
        ring_limit: int = 256,
        clock: Callable[[], float] = time.time,
        synchronous: bool = False,
    ):
        self.archive_dir = strip_scheme(archive_dir) if archive_dir else ""
        self.fs = filesystem_for(archive_dir) if archive_dir else None
        self.retention_max_age_s = float(retention_max_age_s)
        self.retention_max_count = int(retention_max_count)
        self.clock = clock
        #: tests/bench: write on the caller thread instead of the queue
        self.synchronous = synchronous
        self._lock = threading.Lock()
        #: artifact key -> artifact (insertion-ordered recency ring)
        self._ring: OrderedDict = OrderedDict()
        self._ring_limit = int(ring_limit)
        #: query_id -> artifact key (the /v1/query/{id}/profile resolver)
        self._by_query: OrderedDict = OrderedDict()
        self._queue: "queue.Queue" = queue.Queue()
        self._writer: Optional[threading.Thread] = None
        #: background-writer SPI failures (monotonic; flush() reports a
        #: drain that ERRORED as False — refs to files that never landed
        #: must not read as a usable diff baseline)
        self._write_errors = 0

    @classmethod
    def from_config(cls, cfg=None) -> "ProfileStore":
        """Store wired from the typed config's `profile.*` section."""
        if cfg is None:
            from trino_tpu.config import get_config

            cfg = get_config()
        p = cfg.profile
        return cls(
            archive_dir=p.archive_dir,
            retention_max_age_s=p.retention_max_age_s,
            retention_max_count=p.retention_max_count,
            ring_limit=p.ring_limit,
        )

    # -- archive ---------------------------------------------------------------

    def archive(self, artifact: dict) -> dict:
        """Record one artifact; returns its ref {key, query_id, sql_hash,
        path}.  The ring insert is O(1) under the lock; the SPI write is
        handed to the background writer (or done inline when
        `synchronous`, the test/bench mode)."""
        from trino_tpu.telemetry.metrics import profiles_archived_counter

        key = artifact["key"]
        path = self._path(key)
        with self._lock:
            self._ring[key] = artifact
            self._by_query[artifact["query_id"]] = key
            while len(self._ring) > self._ring_limit:
                self._ring.popitem(last=False)
            while len(self._by_query) > self._ring_limit:
                self._by_query.popitem(last=False)
        profiles_archived_counter().inc()
        if self.fs is not None:
            if self.synchronous:
                self._write(artifact, path)
            else:
                self._ensure_writer()
                self._queue.put((artifact, path))
        return {
            "key": key,
            "query_id": artifact["query_id"],
            "sql_hash": artifact["sql_hash"],
            "path": path,
        }

    def _path(self, key: str) -> Optional[str]:
        if not self.archive_dir:
            return None
        import os

        return os.path.join(self.archive_dir, f"{key}.json")

    def _write(self, artifact: dict, path: str) -> None:
        data = json.dumps(artifact, sort_keys=True).encode()
        self.fs.write(path, data)

    def _ensure_writer(self) -> None:
        with self._lock:
            if self._writer is not None and self._writer.is_alive():
                return
            self._writer = threading.Thread(
                target=self._drain, name="profile-archiver", daemon=True
            )
            self._writer.start()

    def _drain(self) -> None:
        while True:
            artifact, path = self._queue.get()
            try:
                self._write(artifact, path)
            except Exception:
                import logging

                with self._lock:
                    self._write_errors += 1
                logging.getLogger("trino_tpu.profile_store").warning(
                    "failed to archive profile %s", path, exc_info=True
                )
            finally:
                self._queue.task_done()

    def flush(self, timeout_s: float = 10.0) -> bool:
        """Block until every queued artifact is on disk (tests/bench);
        True only when the queue drained inside the timeout AND no write
        errored since the call started — a drain that merely DISCARDED
        failed writes is not a flush."""
        if self.fs is None or self.synchronous:
            return True
        with self._lock:
            errors_before = self._write_errors
        deadline = time.monotonic() + timeout_s
        drained = False
        while time.monotonic() < deadline:
            if self._queue.unfinished_tasks == 0:
                drained = True
                break
            time.sleep(0.005)
        drained = drained or self._queue.unfinished_tasks == 0
        with self._lock:
            errors_after = self._write_errors
        return drained and errors_after == errors_before

    # -- lookup ----------------------------------------------------------------

    def get(self, query_id_or_key: str) -> Optional[dict]:
        """Artifact by engine query id or artifact key: the memory ring
        first, then the archive directory (a fresh process can serve
        profiles the previous incarnation archived)."""
        with self._lock:
            key = self._by_query.get(query_id_or_key, query_id_or_key)
            art = self._ring.get(key)
        if art is not None:
            return art
        if self.fs is None:
            return None
        path = self._path(key)
        if path is not None and self.fs.exists(path):
            return json.loads(self.fs.read(path).decode())
        # engine query id of a previous incarnation: scan by prefix,
        # NEWEST artifact first (query_N sequences restart per process, so
        # several incarnations' files can share a prefix)
        candidates = []
        for p in self.fs.list(self.archive_dir):
            name = p.rsplit("/", 1)[-1]
            if name.startswith(f"{query_id_or_key}-") and name.endswith(".json"):
                try:
                    candidates.append((self.fs.mtime(p), p))
                except OSError:
                    continue
        if candidates:
            return json.loads(self.fs.read(max(candidates)[1]).decode())
        return None

    def refs(self) -> list:
        """[{key, query_id, sql_hash, path}] of ring artifacts, oldest
        first (the bench BENCH_EXTRA `profile_artifacts` feed)."""
        with self._lock:
            return [
                {
                    "key": a["key"],
                    "query_id": a["query_id"],
                    "sql_hash": a["sql_hash"],
                    "path": self._path(a["key"]),
                }
                for a in self._ring.values()
            ]

    def rows(self) -> list:
        """system.runtime.query_profiles feed: (query_id, sql_hash, state,
        wall_s, mesh, group, gate_wait_s, compile_s, peak_memory_bytes,
        archived_path) per ring artifact."""
        with self._lock:
            arts = list(self._ring.values())
        return [
            (
                a["query_id"],
                a["sql_hash"],
                a["state"],
                round(a["wall_s"], 6),
                a["mesh"],
                a["admission"]["group"],
                a["gate"]["wait_s"],
                a["compile"]["compile_s"],
                a["peak_memory_bytes"],
                self._path(a["key"]),
            )
            for a in arts
        ]

    def decision_rows(self) -> list:
        """system.runtime.plan_decisions feed: one row per recorded plan
        decision across ring artifacts (telemetry/decisions), oldest
        artifact first — (query_id, decision_id, kind, site, choice,
        alternative, inputs, audit_seq, exchange_bytes, bytes_by,
        fragment_wall_s, hindsight, hindsight_detail)."""
        import json as _json

        with self._lock:
            arts = list(self._ring.values())
        out = []
        for a in arts:
            led = a.get("decisions") or {}
            for d in led.get("decisions", ()):
                out.append(
                    (
                        a["query_id"],
                        d["decision_id"],
                        d["kind"],
                        d["site"],
                        d["choice"],
                        d["alternative"],
                        _json.dumps(d["inputs"], sort_keys=True),
                        d["audit_seq"],
                        d["exchange_bytes"],
                        _json.dumps(d["bytes_by"], sort_keys=True),
                        d["measured"].get("fragment_wall_s"),
                        d["hindsight"],
                        d["hindsight_detail"],
                    )
                )
        return out

    # -- retention -------------------------------------------------------------

    def sweep(self, now_s: Optional[float] = None) -> list:
        """Delete expired artifacts from the archive directory: older than
        `retention_max_age_s` (by SPI mtime against the injectable clock),
        then oldest-first down to `retention_max_count`.  Returns deleted
        paths; only `.json` files under the archive dir are ever touched
        (the sweep must not eat a co-located spool)."""
        if self.fs is None:
            return []
        from trino_tpu.telemetry.metrics import profiles_pruned_counter

        now_s = self.clock() if now_s is None else now_s
        entries = []
        for p in self.fs.list(self.archive_dir):
            if not p.endswith(".json"):
                continue
            try:
                entries.append((self.fs.mtime(p), p))
            except OSError:
                continue  # vanished under us
        entries.sort()
        deleted = []
        if self.retention_max_age_s > 0:
            for mt, p in list(entries):
                if now_s - mt > self.retention_max_age_s:
                    self.fs.delete(p)
                    deleted.append(p)
                    entries.remove((mt, p))
        if self.retention_max_count > 0:
            while len(entries) > self.retention_max_count:
                mt, p = entries.pop(0)
                self.fs.delete(p)
                deleted.append(p)
        if deleted:
            profiles_pruned_counter().inc(len(deleted))
        return deleted


def attach_profile_store(runner, store: Optional[ProfileStore] = None):
    """Attach a ProfileStore to a runner (and through clone_for_dispatch
    to every engine lane).  With no explicit store, builds one from the
    typed config — a no-op returning None when `profile.archive-dir` is
    unset and no store was passed (archiving stays zero-cost-off by
    default, the idle-cost contract)."""
    if store is None:
        existing = getattr(runner, "profile_store", None)
        if existing is not None:
            return existing  # idempotent config-driven re-attach
        from trino_tpu.config import get_config

        if not get_config().profile.archive_dir:
            return None
        store = ProfileStore.from_config()
    runner.profile_store = store
    return store
