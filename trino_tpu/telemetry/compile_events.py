"""Compile observatory: every SPMD trace-cache miss as a structured event.

Reference roles: the reference's per-operator OperatorStats record *where*
time went, and its event stream records *which* tasks did what — but an
XLA-backed engine has a cost class the reference never had: trace + XLA
compile stalls, keyed by (step semantics, shape bucket, mesh).  Cold walls
are compile-dominated (Q6 SF10 mesh-8: 76.6 s cold vs 12.7 s warm) and
`TRACE_CACHE.trace_s` was one undifferentiated number, so nothing could say
WHICH keys cost what or what a prewarm pass should compile.

This module is the single home for that attribution:

  * `OBSERVATORY` — a process-wide ring of `CompileEvent`s.  `TraceCache.get`
    opens an event on every miss (key fingerprint, step label, mesh
    signature, owning query); the launch site that detects the trace closes
    it with the measured wall seconds, shape bucket, and owning fragment
    (`parallel/runner._call`), mirroring each close into the
    `trino_tpu_compile_seconds` histogram.  A warm replay records ZERO new
    events — an assertable fact, not an assumption.
  * the **prewarm manifest** — the deduplicated (step, bucket, mesh) key set
    a workload has needed, with per-key compile seconds.  This is the
    enumeration input for ROADMAP item 3's AOT prewarm: compile exactly
    these keys at server start / after mesh resize instead of paying them at
    first query.  `LocalQueryRunner.compile_manifest()` and
    `tools/prewarm_manifest.py` expose it.
  * `system.runtime.compilations` — the ring as a SQL table
    (connectors/system.py), so compile cost is queryable from the engine's
    own prompt like every other runtime surface.

Everything here is host-side bookkeeping on the compile (miss) path only:
a cache hit never touches the lock, so the observatory cannot perturb the
warm path `verify.device_residency` gates.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Optional

from trino_tpu.runtime.lifecycle import current_query
from trino_tpu.telemetry.spans import now

#: recent-event ring size (the system.runtime.compilations window)
RING_LIMIT = 512
#: distinct compile keys the manifest tracks before evicting oldest
MANIFEST_LIMIT = 4096


def key_fingerprint(key) -> str:
    """Stable short fingerprint of a trace-cache key (manifest identity)."""
    return hashlib.blake2s(repr(key).encode()).hexdigest()[:16]


def _parse_key(key) -> tuple:
    """(step label, mesh signature) best-effort from a trace-cache key.

    `cached_spmd_step` keys are ("spmd", collective, out_replicated,
    mesh_key, <caller key...>) where the caller key leads with a string tag
    ("chain", "fused_exchange", "locate", ...) — the step label of the
    compile event."""
    step: str = "?"
    mesh: tuple = ()
    rest = key if isinstance(key, tuple) else (key,)
    if len(rest) >= 4 and rest[0] == "spmd":
        if isinstance(rest[3], tuple):
            mesh = rest[3]
        rest = rest[4:]
    for el in rest:
        if isinstance(el, str):
            step = el
            break
    return step, mesh


@dataclass
class CompileEvent:
    """One trace-cache miss: a program this process had to trace+compile."""

    seq: int
    step: str
    key_fp: str
    #: truncated repr of the full cache key (debug/manifest readability)
    key: str
    #: mesh signature the program was compiled for (workers, device ids)
    mesh: tuple
    #: trailing row capacity of the launch's first stacked batch (the pow2
    #: shape bucket); None until the launch site closes the event
    bucket: Optional[int] = None
    query_id: str = ""
    fragment: Optional[int] = None
    #: trace + XLA compile wall seconds (attributed at close)
    wall_s: float = 0.0
    #: telemetry.now() timestamp of the miss
    at_s: float = 0.0
    closed: bool = False


class CompileObservatory:
    """Process-wide compile-event ring + prewarm manifest (see module doc).

    Protocol: `open_miss(key)` on every trace-cache miss; the launch site
    that detects its call traced closes ALL open events with
    `close_open(dt, ...)` — the engine dispatches one launch at a time, so
    every open event belongs to the imminent traced launch (the miss fires
    when the program is BUILT, which precedes the instrumented call).  A
    traced call with no open event (a jit retrace under an existing key)
    synthesizes a `retrace` event so compile seconds never vanish from the
    record."""

    def __init__(self, ring_limit: int = RING_LIMIT,
                 manifest_limit: int = MANIFEST_LIMIT):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=ring_limit)
        #: events awaiting wall attribution by their launch site
        self._open: list = []
        #: key_fp -> manifest entry (insertion-ordered for bounded eviction)
        self._manifest: OrderedDict = OrderedDict()
        self._manifest_limit = manifest_limit
        #: events ever opened (monotonic — the warm-replay-zero assertion)
        self.count = 0
        #: total attributed compile wall seconds (monotonic)
        self.total_wall_s = 0.0

    # -- recording ------------------------------------------------------------

    def mark(self) -> int:
        """Watermark for close_since (the current event count)."""
        with self._lock:
            return self.count

    def open_miss(self, key) -> CompileEvent:
        """Record a trace-cache miss (called by TraceCache.get)."""
        step, mesh = _parse_key(key)
        ctx = current_query()
        ev = CompileEvent(
            seq=0,
            step=step,
            key_fp=key_fingerprint(key),
            key=repr(key)[:240],
            mesh=mesh,
            query_id=ctx.query_id if ctx is not None else "",
            at_s=now(),
        )
        with self._lock:
            self.count += 1
            ev.seq = self.count
            self._ring.append(ev)
            self._open.append(ev)
            self._note_open(ev)
        return ev

    def abort(self, ev: CompileEvent) -> None:
        """Withdraw an open event whose build raised (nothing compiled):
        remove it from the pending set so the next traced launch doesn't
        inherit its attribution.  The ring keeps the row (wall 0.0,
        closed=False) — the attempt is part of the record."""
        with self._lock:
            if ev in self._open:
                self._open.remove(ev)

    def close_open(self, wall_s: float, bucket: Optional[int] = None,
                   fragment: Optional[int] = None, mesh: tuple = ()) -> list:
        """Attribute `wall_s` to every open event; returns them.
        Synthesizes a `retrace` event when a traced call opened none (jax
        retraced an existing key on a new shape/aux signature)."""
        with self._lock:
            events, self._open = self._open, []
            if not events:
                ctx = current_query()
                self.count += 1
                ev = CompileEvent(
                    seq=self.count,
                    step="retrace",
                    key_fp="",
                    key="",
                    mesh=mesh,
                    query_id=ctx.query_id if ctx is not None else "",
                    at_s=now(),
                )
                self._ring.append(ev)
                self._note_open(ev)
                events = [ev]
            share = wall_s / len(events)
            for ev in events:
                ev.wall_s = share
                ev.closed = True
                if ev.bucket is None:
                    ev.bucket = bucket
                if ev.fragment is None:
                    ev.fragment = fragment
                self.total_wall_s += share
                self._note_close(ev)
        from trino_tpu.telemetry.metrics import compile_seconds_histogram

        hist = compile_seconds_histogram()
        for ev in events:
            hist.observe(ev.wall_s)
        return events

    # -- manifest (the AOT prewarm enumeration) -------------------------------

    def _note_open(self, ev: CompileEvent) -> None:  # lint: allow(unguarded-state)
        # caller holds self._lock (open_miss / close_open)
        fp = ev.key_fp or f"retrace:{ev.step}"
        entry = self._manifest.get(fp)
        if entry is None:
            entry = self._manifest[fp] = {
                "key_fp": fp,
                "step": ev.step,
                "mesh": str(ev.mesh),
                "key": ev.key,
                "buckets": set(),
                "count": 0,
                "compile_s": 0.0,
            }
            while len(self._manifest) > self._manifest_limit:
                self._manifest.popitem(last=False)
        entry["count"] += 1

    def _note_close(self, ev: CompileEvent) -> None:  # lint: allow(unguarded-state)
        # caller holds self._lock (close_open)
        fp = ev.key_fp or f"retrace:{ev.step}"
        entry = self._manifest.get(fp)
        if entry is None:  # evicted under manifest pressure
            return
        entry["compile_s"] += ev.wall_s
        if ev.bucket is not None:
            entry["buckets"].add(int(ev.bucket))

    def manifest(self) -> list:
        """The deduplicated compile-key set this process has needed, most
        expensive first: [{key_fp, step, mesh, key, buckets, count,
        compile_s}].  The prewarm input for ROADMAP item 3."""
        with self._lock:
            entries = [
                dict(e, buckets=sorted(e["buckets"]),
                     compile_s=round(e["compile_s"], 4))
                for e in self._manifest.values()
            ]
        return sorted(entries, key=lambda e: (-e["compile_s"], e["step"]))

    # -- export ---------------------------------------------------------------

    def events(self) -> list:
        """Recent events, oldest first (the ring window)."""
        with self._lock:
            return list(self._ring)

    def events_above(self, watermark: int) -> list:
        """Events recorded after a `mark()` watermark (closure forensics:
        a prewarmed replay that still compiles names the leaking steps
        instead of just counting them).  Bounded by the ring window — the
        COUNT above the watermark is always `count - watermark` even when
        the ring has rotated past some of the events."""
        with self._lock:
            return [e for e in self._ring if e.seq > watermark]

    def rows(self) -> list:
        """system.runtime.compilations feed: (seq, step, bucket, mesh,
        query_id, fragment, wall_s, key_fp, key) per recent event."""
        return [
            (
                e.seq, e.step, e.bucket, str(e.mesh), e.query_id,
                e.fragment, round(e.wall_s, 6), e.key_fp, e.key,
            )
            for e in self.events()
        ]

    def clear(self) -> None:
        """Drop all recorded state (tests only)."""
        with self._lock:
            self._ring.clear()
            self._open = []
            self._manifest.clear()
            self.count = 0
            self.total_wall_s = 0.0


#: the process-wide observatory (one engine process per host)
OBSERVATORY = CompileObservatory()
