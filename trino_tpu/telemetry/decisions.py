"""Per-query plan-decision ledger: what the planner chose, what it cost.

Reference roles: the stats/feedback tier the reference engine sketches
(SURVEY §3.5 — recording optimizer choices with runtime outcomes so a
history-fed cost model has ground truth), plus the `reorderedJoin` /
`replicatedJoin` flags QueryStats exposes — generalized here to EVERY
consequential choice the planner or runtime makes:

  * join distribution (broadcast / partitioned / colocated),
  * capacity source (licensed / declined / runtime_check, with the
    certificate kind and the economy verdict),
  * dictionary-coding placement lift (versioned varchar keys co-locating
    like integers),
  * the collective-schedule license (async pre-dispatch vs lazy order),
  * wave-count spill/degrade escalation,
  * mechanical exchange placements (aggregation repartition, window
    partitioning, semi-join shape).

Each choice is recorded AT DECISION TIME with a stable `decision_id`, the
inputs it saw (estimated rows, license width, economy verdict), and the
alternative it rejected.  Post-execution, `LocalQueryRunner.execute`
joins every decision with its measured outcome — the collective bytes the
choice moved (attributed through `MeshProfile.add_collective` under a
`decision_scope`), per-fragment phase wall on the span/MeshProfile clock,
learned capacity widths — and stamps a `hindsight` verdict:

  * `vindicated`  — the measured outcome was no worse than the recorded
    estimate for the rejected alternative,
  * `regret`      — the measured outcome exceeded the rejected
    alternative's estimate by `decision_regret_ratio` (and moved at least
    `decision_regret_min_bytes`, so tiny dimension broadcasts never flag),
  * `unmeasured`  — the decision never observed an outcome (plan-time
    only, or the query failed before the choice executed).

The ledger is lane-safe by the same contract as the tracer / mesh
profile: one ledger per QueryContext, resolved through the lifecycle
contextvar — never a shared runner attribute.  Byte attribution adds no
host syncs: every observation is host-side integer bookkeeping on values
the profile already held (verify.device_residency stays green).

The ledger lands in the profile artifact (`decisions` key), feeds
`system.runtime.plan_decisions`, `GET /v1/query/{id}/decisions`, the
`trino_tpu_plan_decisions_total{kind,outcome,hindsight}` counter, and the
`check_decisions` bench gate (completeness: every exchange byte and every
licensed/declined join maps to exactly one decision).
"""

from __future__ import annotations

import contextvars
import threading
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Optional

#: decision-kind vocabulary (the {kind} label of plan_decisions_total)
DECISION_KINDS = (
    "join_distribution",
    "join_capacity",
    "dictionary_placement",
    "schedule_license",
    "wave",
    "exchange",
    # task-recovery classification (runtime/lifecycle RECOVERY table):
    # retry (same plan, lost tasks only) vs replan (mesh signature truly
    # changed) vs fail (user/semantic — never retried), recorded with the
    # error code and mesh evidence the classifier saw
    "recovery",
)

#: hindsight vocabulary (the {hindsight} label)
HINDSIGHT = ("vindicated", "regret", "unmeasured")

#: exchange-plane collective kinds the completeness gate covers: every
#: byte of these kinds must attribute to exactly one decision (gathers
#: are host pulls, reduces are dynamic-filter summaries — neither is a
#: *placement* choice)
EXCHANGE_KINDS = ("all_to_all", "all_gather")


@dataclass
class Decision:
    """One recorded choice.  `measured` accumulates runtime observations
    (collective bytes by kind/purpose, fragments touched, learned
    widths); `hindsight` is stamped once by `finalize`."""

    decision_id: str
    kind: str
    site: str
    choice: str
    alternative: str
    inputs: dict = field(default_factory=dict)
    #: audit-log watermark at decision time: shed/kill/drain audit lines
    #: with (query_id, seq > audit_seq) happened AFTER this choice
    audit_seq: Optional[int] = None
    measured: dict = field(default_factory=dict)
    #: (kind, purpose) -> bytes attributed to this decision
    bytes_by: dict = field(default_factory=dict)
    #: fragment ids whose collectives attributed here (phase-wall join key)
    fragments: list = field(default_factory=list)
    hindsight: str = "unmeasured"
    hindsight_detail: str = ""

    @property
    def exchange_bytes(self) -> int:
        return sum(
            b for (k, _), b in self.bytes_by.items() if k in EXCHANGE_KINDS
        )

    def to_json(self) -> dict:
        return {
            "decision_id": self.decision_id,
            "kind": self.kind,
            "site": self.site,
            "choice": self.choice,
            "alternative": self.alternative,
            "inputs": dict(self.inputs),
            "audit_seq": self.audit_seq,
            "measured": dict(self.measured),
            "bytes_by": {
                f"{k}/{p}": b for (k, p), b in sorted(self.bytes_by.items())
            },
            "exchange_bytes": self.exchange_bytes,
            "fragments": sorted(set(self.fragments)),
            "hindsight": self.hindsight,
            "hindsight_detail": self.hindsight_detail,
        }


class DecisionLedger:
    """Per-query decision ledger (one per QueryContext; see module doc).
    Thread-safe: the dispatcher's engine lanes each own a ledger, but a
    statement's planner thread and any helper threads may record into the
    same one."""

    def __init__(self, query_id: str):
        self.query_id = query_id
        self._lock = threading.Lock()
        self._next = 0
        self.decisions: list[Decision] = []
        self._by_id: dict[str, Decision] = {}
        #: exchange-plane bytes observed with NO active decision scope:
        #: (kind, purpose) -> bytes.  check_decisions asserts this empty —
        #: an unattributed collective is a choice the ledger missed.
        self.unattributed: dict = {}
        self.finalized = False

    # -- decision time --------------------------------------------------------

    def record(self, kind: str, site: str, choice: str,
               alternative: str = "", inputs: Optional[dict] = None) -> str:
        """Record one choice; returns its stable decision_id.  Called at
        the moment the choice is made (planner rule or runtime branch),
        never retroactively — the inputs dict is what the decider SAW."""
        from trino_tpu.telemetry.metrics import plan_decisions_counter

        with self._lock:
            did = f"d{self._next:03d}"
            self._next += 1
            d = Decision(
                decision_id=did,
                kind=kind,
                site=site,
                choice=choice,
                alternative=alternative,
                inputs=dict(inputs or {}),
                audit_seq=_audit_watermark(),
            )
            self.decisions.append(d)
            self._by_id[did] = d
        plan_decisions_counter().labels(kind, choice, "pending").inc()
        return did

    # -- outcome join ---------------------------------------------------------

    def observe(self, decision_id: Optional[str], **measured) -> None:
        """Merge runtime measurements into a decision (numeric values the
        runtime already holds host-side — never a device sync)."""
        if decision_id is None:
            return
        with self._lock:
            d = self._by_id.get(decision_id)
            if d is None:
                return
            d.measured.update(measured)

    def observe_collective(self, decision_id: Optional[str], fid: int,
                           nbytes: int, kind: str, purpose: str) -> None:
        """Attribute one collective's bytes (called by
        MeshProfile.add_collective under the ambient decision scope)."""
        with self._lock:
            d = self._by_id.get(decision_id) if decision_id else None
            if d is None:
                if kind in EXCHANGE_KINDS:
                    key = (kind, purpose)
                    self.unattributed[key] = (
                        self.unattributed.get(key, 0) + int(nbytes)
                    )
                return
            key = (kind, purpose)
            d.bytes_by[key] = d.bytes_by.get(key, 0) + int(nbytes)
            d.fragments.append(int(fid))

    # -- hindsight ------------------------------------------------------------

    def finalize(self, n_workers: int = 1, regret_ratio: float = 2.0,
                 min_bytes: int = 1 << 20, fragment_phases=None) -> None:
        """Stamp every decision's hindsight verdict from its measured
        outcome vs the recorded estimate of the rejected alternative.
        Idempotent (the runner calls it once, before archiving)."""
        from trino_tpu.telemetry.metrics import plan_decisions_counter

        with self._lock:
            if self.finalized:
                return
            self.finalized = True
            decisions = list(self.decisions)
        w = max(1, int(n_workers))
        for d in decisions:
            if fragment_phases:
                wall = sum(
                    fragment_phases.get(f, 0.0) for f in set(d.fragments)
                )
                if wall:
                    d.measured["fragment_wall_s"] = round(wall, 6)
            verdict, detail = self._hindsight(d, w, regret_ratio, min_bytes)
            d.hindsight = verdict
            d.hindsight_detail = detail
            plan_decisions_counter().labels(d.kind, d.choice, verdict).inc()

    @staticmethod
    def _hindsight(d: Decision, w: int, ratio: float, floor: int):
        measured_any = bool(d.bytes_by or d.measured)
        if d.kind == "join_distribution":
            if d.choice == "broadcast":
                moved = sum(
                    b for (k, _), b in d.bytes_by.items()
                    if k == "all_gather"
                )
                if not moved:
                    return "unmeasured", "no broadcast bytes observed"
                # the rejected partitioned plan ships ONE build copy
                # (moved/W — all_gather replicated it W times) plus the
                # probe side once, unless the probe was already placed
                alt = moved // w + int(d.measured.get("probe_move_bytes", 0))
                if moved <= floor:
                    return "vindicated", f"moved {moved}B <= {floor}B floor"
                if moved > ratio * max(1, alt):
                    return (
                        "regret",
                        f"broadcast moved {moved}B; partitioned estimate "
                        f"{alt}B (> {ratio}x)",
                    )
                return "vindicated", f"moved {moved}B vs estimate {alt}B"
            moved = sum(
                b for (k, _), b in d.bytes_by.items() if k == "all_to_all"
            )
            build = int(d.measured.get("build_bytes", 0))
            if not measured_any:
                return "unmeasured", ""
            alt = w * build  # the rejected broadcast ships W build copies
            if build and moved > floor and moved > ratio * max(1, alt):
                return (
                    "regret",
                    f"partitioned moved {moved}B; broadcast estimate {alt}B",
                )
            return "vindicated", f"moved {moved}B vs broadcast {alt}B"
        if d.kind == "join_capacity":
            oc = int(d.inputs.get("licensed_cap", 0))
            if d.choice == "licensed":
                live = int(d.measured.get("live_cap", 0))
                if not live:
                    return (
                        ("vindicated", "executed at licensed width")
                        if measured_any else ("unmeasured", "")
                    )
                if oc > 1024 and oc > ratio * live:
                    return (
                        "regret",
                        f"licensed width {oc} > {ratio}x measured live "
                        f"{live}",
                    )
                return "vindicated", f"width {oc} vs live {live}"
            if d.choice == "declined":
                cap = int(d.measured.get("runtime_cap", 0))
                if not cap:
                    return "unmeasured", "runtime width not recorded"
                if oc and cap >= oc:
                    return (
                        "regret",
                        f"declined width {oc} but runtime sized {cap} "
                        "(decline bought nothing)",
                    )
                return "vindicated", f"runtime sized {cap} < licensed {oc}"
            # runtime_check: no license existed, nothing was rejected
            return (
                ("vindicated", "runtime sizing (no license rejected)")
                if measured_any else ("unmeasured", "")
            )
        # plan-only / mechanical kinds: vindicated once an outcome landed
        if measured_any:
            return "vindicated", ""
        return "unmeasured", ""

    # -- export ---------------------------------------------------------------

    def to_json(self) -> dict:
        with self._lock:
            return {
                "query_id": self.query_id,
                "decisions": [d.to_json() for d in self.decisions],
                "unattributed_bytes_by": {
                    f"{k}/{p}": b
                    for (k, p), b in sorted(self.unattributed.items())
                },
                "finalized": self.finalized,
            }


# -- ambient resolution (lane safety) -----------------------------------------

#: innermost-wins stack of active decision ids (the runtime pushes one
#: around each exchange application; nested fragment pulls push their own)
_SCOPE: contextvars.ContextVar[tuple] = contextvars.ContextVar(
    "trino_tpu_decision_scope", default=()
)


def current_ledger() -> Optional[DecisionLedger]:
    """The executing statement's ledger via the lifecycle contextvar
    (None outside a statement — verify sweeps, bare helpers)."""
    from trino_tpu.runtime.lifecycle import current_query

    ctx = current_query()
    if ctx is None:
        return None
    return getattr(ctx, "decisions", None)


def ensure_ledger(ctx) -> DecisionLedger:
    """The context's ledger, created on first use (execute attaches one
    eagerly; this covers bare contexts in tests)."""
    led = getattr(ctx, "decisions", None)
    if led is None:
        led = ctx.decisions = DecisionLedger(ctx.query_id)
    return led


def record_decision(kind: str, site: str, choice: str,
                    alternative: str = "",
                    inputs: Optional[dict] = None) -> Optional[str]:
    """Record into the current statement's ledger; None (and no-op) when
    no statement is executing — planner helpers stay callable bare."""
    led = current_ledger()
    if led is None:
        return None
    return led.record(kind, site, choice, alternative, inputs)


def current_decision() -> Optional[str]:
    stack = _SCOPE.get()
    return stack[-1] if stack else None


@contextmanager
def decision_scope(decision_id: Optional[str]):
    """Attribute collectives issued inside to `decision_id` (innermost
    scope wins; None is a transparent no-op so call sites need no
    branching)."""
    if decision_id is None:
        yield
        return
    token = _SCOPE.set(_SCOPE.get() + (decision_id,))
    try:
        yield
    finally:
        _SCOPE.reset(token)


def observe_collective(fid: int, nbytes: int, kind: str,
                       purpose: str) -> None:
    """MeshProfile.add_collective hook: attribute the bytes to the
    ambient decision (or the ledger's unattributed bucket).  Host-side
    integer bookkeeping only — never a device sync."""
    led = current_ledger()
    if led is None:
        return
    led.observe_collective(current_decision(), fid, nbytes, kind, purpose)


def observe_decision(decision_id: Optional[str], **measured) -> None:
    """Merge measurements into a decision of the current ledger."""
    led = current_ledger()
    if led is not None:
        led.observe(decision_id, **measured)


def _audit_watermark() -> Optional[int]:
    """Current audit-log sequence watermark, for (query_id, seq)
    cross-referencing (telemetry/audit.py); None when no audit log is
    attached."""
    from trino_tpu.telemetry import audit

    return audit.sequence_watermark()
