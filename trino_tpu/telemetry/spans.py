"""Structured span tracer: one tree of timed spans per query.

Reference roles: io.opentelemetry spans threaded through DispatchManager ->
SqlQueryExecution -> exchange (the reference wires a Tracer through every
layer and tags spans with QueryId/StageId), and the Chrome-trace JSON the
trace is exported as loads directly in Perfetto / chrome://tracing.

Design constraints:

  * zero overhead when off — the shared NULL_TRACER's `span()` returns one
    preallocated no-op context manager and `record()` is a pass; hot paths
    additionally guard on `tracer.enabled` before building attribute dicts;
  * no host syncs — spans time HOST wall only (`now()` below); device work
    is attributed exactly the way MeshProfile already attributes it (the
    phase of the launch that dispatched it), so enabling tracing cannot add
    transfers and `verify.device_residency` holds with tracing on;
  * spans nest by runtime containment: the tracer keeps an open-span stack,
    `span()` pushes/pops, `record()` appends an already-closed child to the
    innermost open span (the shape `parallel/runner.py::_call` needs — it
    knows the duration only after the launch returned).
"""

from __future__ import annotations

import itertools
import json
import time
from typing import Optional

#: THE phase-timing clock.  Every engine-side wall measurement (spans,
#: MeshProfile phases, stage self-time) reads this one callable so span and
#: profile timestamps are directly comparable; tools/lint_tpu.py flags raw
#: `time.perf_counter()` phase timing added to device code outside here.
now = time.perf_counter


class Span:
    """One timed node of the query trace tree."""

    __slots__ = ("span_id", "parent_id", "name", "start_s", "end_s",
                 "attrs", "children")

    def __init__(self, span_id: int, parent_id: int, name: str,
                 start_s: float, attrs: Optional[dict] = None):
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start_s = start_s
        self.end_s: Optional[float] = None
        self.attrs = attrs if attrs is not None else {}
        self.children: list[Span] = []

    def duration_s(self) -> float:
        end = self.end_s if self.end_s is not None else now()
        return max(0.0, end - self.start_s)

    def to_dict(self) -> dict:
        return {
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_s": round(self.start_s, 6),
            "duration_ms": round(self.duration_s() * 1e3, 3),
            "attrs": dict(self.attrs),
            "children": [c.to_dict() for c in self.children],
        }


class _OpenSpan:
    """Context manager returned by SpanTracer.span()."""

    __slots__ = ("tracer", "sp")

    def __init__(self, tracer: "SpanTracer", sp: Span):
        self.tracer = tracer
        self.sp = sp

    def __enter__(self) -> Span:
        return self.sp

    def __exit__(self, et, ev, tb) -> bool:
        self.sp.end_s = now()
        if et is not None:
            self.sp.attrs["error"] = et.__name__
        stack = self.tracer._stack
        if stack and stack[-1] is self.sp:
            stack.pop()
        return False


class _NullCtx:
    """Shared no-op context manager (the off-path of span())."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *a) -> bool:
        return False


_NULL_CTX = _NullCtx()


class SpanTracer:
    """Per-query span tree.  Not thread-safe: the engine serializes one
    statement at a time (the coordinator's engine lock), matching the
    reference's per-query trace context."""

    enabled = True

    def __init__(self, query_id: str = ""):
        self.query_id = query_id
        self.root: Optional[Span] = None
        self._stack: list[Span] = []
        self._ids = itertools.count(1)
        self.t0 = now()

    # -- recording ------------------------------------------------------------

    def span(self, name: str, **attrs) -> _OpenSpan:
        """Open a nested span; use as a context manager."""
        parent = self._stack[-1] if self._stack else None
        sp = Span(
            next(self._ids),
            parent.span_id if parent is not None else 0,
            name,
            now(),
            attrs,
        )
        if parent is not None:
            parent.children.append(sp)
        elif self.root is None:
            self.root = sp
        else:  # second top-level span: keep one tree, attach to the root
            sp.parent_id = self.root.span_id
            self.root.children.append(sp)
        self._stack.append(sp)
        return _OpenSpan(self, sp)

    def record(self, name: str, start_s: float, end_s: float,
               attrs: Optional[dict] = None) -> Optional[Span]:
        """Append an already-measured leaf span under the innermost open
        span (launch sites know their duration only after the fact).
        Returns the span so the caller can attach() children to it (compile
        stalls nest under their launch)."""
        parent = self._stack[-1] if self._stack else self.root
        sp = Span(
            next(self._ids),
            parent.span_id if parent is not None else 0,
            name,
            start_s,
            attrs,
        )
        sp.end_s = end_s
        if parent is not None:
            parent.children.append(sp)
        elif self.root is None:
            self.root = sp
        return sp

    def attach(self, parent: Span, name: str, start_s: float, end_s: float,
               attrs: Optional[dict] = None) -> Span:
        """Graft an already-closed span under an explicit parent (compile
        child spans of a launch; worker span trees merged under the
        coordinator's fragment span by the multi-host scheduler)."""
        sp = Span(next(self._ids), parent.span_id, name, start_s, attrs)
        sp.end_s = end_s
        parent.children.append(sp)
        return sp

    def graft(self, parent: Span, tree: dict, offset_s: float = 0.0) -> Span:
        """Merge a foreign span tree (Span.to_dict form — e.g. a worker
        task's spans pulled over HTTP) under `parent`, re-issuing span ids
        from THIS tracer so the merged trace has one id space.  `offset_s`
        shifts the foreign clock onto ours: worker `now()` readings are
        per-process perf counters with unrelated epochs, so the caller
        anchors the foreign root at a locally-observed instant (task
        submission) and every descendant keeps its relative position."""
        start = float(tree["start_s"]) + offset_s
        sp = self.attach(
            parent, tree["name"], start,
            start + float(tree.get("duration_ms", 0.0)) / 1e3,
            dict(tree.get("attrs") or {}),
        )
        for child in tree.get("children", ()):
            self.graft(sp, child, offset_s)
        return sp

    # -- export ---------------------------------------------------------------

    def _walk(self):
        def rec(sp):
            yield sp
            for c in sp.children:
                yield from rec(c)

        if self.root is not None:
            yield from rec(self.root)

    def flat_spans(self) -> list:
        """Depth-first flattened spans as plain dicts (the
        system.runtime.spans feed)."""
        out = []
        for sp in self._walk():
            out.append(
                {
                    "query_id": self.query_id,
                    "span_id": sp.span_id,
                    "parent_id": sp.parent_id,
                    "name": sp.name,
                    "start_ms": round((sp.start_s - self.t0) * 1e3, 3),
                    "duration_ms": round(sp.duration_s() * 1e3, 3),
                    "attributes": json.dumps(sp.attrs, default=str)
                    if sp.attrs
                    else "",
                }
            )
        return out

    def to_chrome_trace(self) -> dict:
        """Chrome-trace JSON (the 'traceEvents' array form): loads in
        Perfetto (ui.perfetto.dev) and chrome://tracing.  Complete ('X')
        events; ts/dur in microseconds relative to query admission."""
        events = []
        for sp in self._walk():
            events.append(
                {
                    "ph": "X",
                    "name": sp.name,
                    "cat": "query",
                    "ts": round((sp.start_s - self.t0) * 1e6, 1),
                    "dur": round(sp.duration_s() * 1e6, 1),
                    "pid": 1,
                    "tid": 1,
                    "args": {k: str(v) for k, v in sp.attrs.items()},
                }
            )
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"query_id": self.query_id},
        }

    def render_text(self) -> str:
        """Indented span tree (the EXPLAIN ANALYZE VERBOSE rendering)."""
        lines = [f"Query trace (spans, query_id={self.query_id}):"]

        def rec(sp: Span, depth: int) -> None:
            attrs = ""
            if sp.attrs:
                attrs = " " + " ".join(
                    f"{k}={v}" for k, v in sp.attrs.items()
                )
            lines.append(
                "  " * (depth + 1)
                + f"{sp.name} {sp.duration_s() * 1e3:.2f}ms{attrs}"
            )
            for c in sp.children:
                rec(c, depth + 1)

        if self.root is not None:
            rec(self.root, 0)
        return "\n".join(lines)


class NullTracer:
    """The off state: every operation is a no-op; `span()` hands back one
    shared context manager so the off-path allocates nothing."""

    enabled = False
    query_id = ""
    root = None

    def span(self, name: str, **attrs) -> _NullCtx:
        return _NULL_CTX

    def record(self, name, start_s, end_s, attrs=None) -> None:
        pass

    def attach(self, parent, name, start_s, end_s, attrs=None) -> None:
        pass

    def graft(self, parent, tree, offset_s=0.0) -> None:
        pass

    def flat_spans(self) -> list:
        return []

    def to_chrome_trace(self) -> dict:
        return {"traceEvents": [], "displayTimeUnit": "ms"}

    def render_text(self) -> str:
        return "Query trace: tracing disabled (SET SESSION query_trace = true)"


#: the shared off-tracer (identity-comparable: `tracer is NULL_TRACER`)
NULL_TRACER = NullTracer()
