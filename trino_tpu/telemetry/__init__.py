"""Unified query telemetry: span tracer + process-wide metrics registry.

Reference roles: the OpenTelemetry Tracer the reference threads from
dispatch through exchange, QueryMonitor/QueryStatistics (the per-query
stats payload event listeners receive), and the JMX/airlift metrics beans
served here as Prometheus text at GET /v1/metrics.

  * `spans` — per-query span trees (query -> analyze -> optimize ->
    fragment -> schedule -> per-fragment SPMD launches), exportable as
    Chrome-trace/Perfetto JSON; zero-overhead NULL_TRACER when off.
  * `metrics` — counters/gauges/histograms registered once and bumped
    everywhere; the single home for the engine's formerly scattered
    counters (MeshProfile, trace cache, buffer pool).
"""

from trino_tpu.telemetry.metrics import (
    REGISTRY,
    CallbackGauge,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from trino_tpu.telemetry.spans import NULL_TRACER, NullTracer, Span, SpanTracer, now

__all__ = [
    "REGISTRY",
    "CallbackGauge",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "NULL_TRACER",
    "NullTracer",
    "Span",
    "SpanTracer",
    "now",
]
