"""Host-side string machinery: LIKE translation and dictionary-table helpers.

Reference role: core/trino-main/.../likematcher/LikeMatcher.java and
operator/scalar/Like*.java — but evaluated once per *dictionary value* instead
of once per row, then gathered on device by code.
"""

from __future__ import annotations

import re
from functools import lru_cache


@lru_cache(maxsize=4096)
def like_to_regex(pattern: str, escape: str | None = None) -> "re.Pattern":
    """Translate a SQL LIKE pattern into an anchored python regex."""
    out = []
    i = 0
    n = len(pattern)
    while i < n:
        ch = pattern[i]
        if escape and ch == escape:
            if i + 1 >= n:
                raise ValueError(
                    f"LIKE pattern must not end with escape character: {pattern!r}"
                )
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
        i += 1
    return re.compile("^" + "".join(out) + "$", flags=re.DOTALL)


def like_prefix(pattern: str, escape: str | None = None) -> str | None:
    """If the pattern is 'prefix%' with no other wildcards, return the prefix
    (enables an O(log n) dictionary range instead of a full regex table)."""
    if escape and escape in pattern:
        return None
    if pattern.endswith("%") and "%" not in pattern[:-1] and "_" not in pattern:
        return pattern[:-1]
    return None
