"""Scalar function library (reference: operator/scalar/* — 139 files — plus the
per-type operators in type/*Operators.java).

Each handler runs at trace time: it receives compiled argument Vals and emits
jnp ops.  String functions evaluate over dictionaries host-side and emit
constant lookup tables (see expr/strings.py).
"""

from __future__ import annotations

import math
from typing import Callable

import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.columnar import StringDictionary
from trino_tpu.expr.compiler import ExprCompiler, Val, _and_valid, _valid_arr
from trino_tpu.expr.ir import Call
from trino_tpu.expr.strings import like_to_regex, like_prefix

FUNCTIONS: dict[str, Callable] = {}


def register(name: str):
    def deco(fn):
        FUNCTIONS[name] = fn
        return fn

    return deco


def dispatch(ctx: ExprCompiler, call: Call) -> Val:
    from trino_tpu.expr.ir import Lambda

    fn = FUNCTIONS.get(call.name)
    if fn is None:
        raise NotImplementedError(f"scalar function not implemented: {call.name}")
    # lambda arguments pass through unevaluated — the handler binds their
    # parameters over array elements and evaluates the body itself
    vals = [
        a if isinstance(a, Lambda) else ctx.value(a) for a in call.args
    ]
    return fn(ctx, call, *vals)


# ---------------------------------------------------------------------------
# numeric coercion helpers


def _dec_scale(t: T.Type) -> int | None:
    return t.scale if isinstance(t, T.DecimalType) else None


def _is_long_dec(t: T.Type) -> bool:
    return isinstance(t, T.DecimalType) and t.is_long


def _to_planes(v: Val, to_scale: int):
    """Any integer/decimal Val -> (hi, lo) i128 planes rescaled to to_scale
    (types/int128.py limb convention)."""
    from trino_tpu.types import int128 as i128

    t = v.type
    if _is_long_dec(t):
        d = jnp.asarray(v.data, jnp.int64)
        if d.ndim == 0:  # null-fill scalar
            h = jnp.int64(0)
            l = jnp.int64(0)
        elif d.ndim == 1:
            # 1-D data under a long type: short-valued rows (window sums
            # computed in i64); literals always carry [1, 2] planes
            h, l = i128.widen64(d)
        else:
            h, l = d[..., 0], d[..., 1]
        return i128.rescale128(h, l, t.scale, to_scale)
    s = t.scale if isinstance(t, T.DecimalType) else 0
    h, l = i128.widen64(jnp.asarray(v.data, jnp.int64))
    return i128.rescale128(h, l, s, to_scale)


def _planes_val(h, l, rt: T.Type, valid) -> Val:
    """Stack (hi, lo) planes into a long-decimal Val ([..., 2]).

    Scalar planes keep an explicit leading row axis ([1, 2]): a bare (2,)
    array is indistinguishable from two SHORT-valued rows downstream
    (ExprCompiler.column widens 1-D data under a long type row-wise), so a
    constant-folded long product must never collapse to 1-D."""
    h = jnp.asarray(h, jnp.int64)
    l = jnp.asarray(l, jnp.int64)
    h, l = jnp.broadcast_arrays(h, l)
    if jnp.ndim(h) == 0:
        h = h[None]
        l = l[None]
    return Val(jnp.stack([h, l], axis=-1), valid, rt)


def _to_float(v: Val):
    """Numeric value as f64 data."""
    if _is_long_dec(v.type):
        from trino_tpu.types import int128 as i128

        h, l = _to_planes(v, v.type.scale)
        return i128.to_float128(h, l) / float(v.type.scale_factor)
    d = jnp.asarray(v.data)
    if isinstance(v.type, T.DecimalType):
        return d.astype(jnp.float64) / float(v.type.scale_factor)
    return d.astype(jnp.float64)


def _align_numeric(a: Val, b: Val):
    """Coerce two numeric values to a common device representation.

    Returns (a_data, b_data, result_type_hint) where decimal operands are
    rescaled to a shared scale (integer math), or both lifted to f64.
    """
    ta, tb = a.type, b.type
    if T.is_string_kind(ta) or T.is_string_kind(tb):
        raise TypeError("string arithmetic")
    fa = ta.name in ("real", "double")
    fb = tb.name in ("real", "double")
    da, db = isinstance(ta, T.DecimalType), isinstance(tb, T.DecimalType)
    if fa or fb:
        return _to_float(a), _to_float(b), T.DOUBLE
    if da or db:
        sa = ta.scale if da else 0
        sb = tb.scale if db else 0
        s = max(sa, sb)
        ad = jnp.asarray(a.data, dtype=jnp.int64) * (10 ** (s - sa))
        bd = jnp.asarray(b.data, dtype=jnp.int64) * (10 ** (s - sb))
        return ad, bd, T.DecimalType(18, s)
    # integer kinds (and date/timestamp, which are integers on device)
    dt = np.promote_types(ta.np_dtype, tb.np_dtype)
    return (
        jnp.asarray(a.data).astype(dt),
        jnp.asarray(b.data).astype(dt),
        ta if ta.np_dtype == dt else tb,
    )


def _rescale_decimal(data, from_scale: int, to_scale: int):
    if from_scale == to_scale:
        return data
    if to_scale > from_scale:
        return data * (10 ** (to_scale - from_scale))
    # round half AWAY FROM ZERO on downscale, symmetric in sign: the old
    # `(data + sign*(f//2)) // f` floor-divides the bumped NEGATIVE value
    # one whole unit too low (-0.01 at scale 0 became -1, not 0 — caught
    # by tests/test_constant_fold_diff.py)
    f = 10 ** (from_scale - to_scale)
    return jnp.sign(data) * ((jnp.abs(data) + f // 2) // f)


def _result_as(call_type: T.Type, data, valid) -> Val:
    return Val(data, valid, call_type)


# ---------------------------------------------------------------------------
# arithmetic


def _arith(ctx, call, a, b, int_op, float_op):
    valid = _and_valid(a.valid, b.valid)
    rt = call.type
    if (
        (_is_long_dec(rt) or _is_long_dec(a.type) or _is_long_dec(b.type))
        and rt.name not in ("real", "double")
        and a.type.name not in ("real", "double")
        and b.type.name not in ("real", "double")
        and int_op in (jnp.add, jnp.subtract)
    ):
        # exact two-limb path (reference: Int128Math.add/subtract)
        from trino_tpu.types import int128 as i128

        s = rt.scale if isinstance(rt, T.DecimalType) else 0
        ah, al = _to_planes(a, s)
        bh, bl = _to_planes(b, s)
        op = i128.add128 if int_op is jnp.add else i128.sub128
        h, l = op(ah, al, bh, bl)
        if isinstance(rt, T.DecimalType) and not rt.is_long:
            # short declared result from long operands: the caller asserts
            # the value fits, so the low limb carries it exactly (same
            # contract as $mul and _finalize) — planes under a short type
            # would corrupt every downstream row-shape assumption
            return Val(l, valid, rt)
        return _planes_val(h, l, rt, valid)
    ad, bd, hint = _align_numeric(a, b)
    if rt.name in ("real", "double") or hint is T.DOUBLE:
        out = float_op(jnp.asarray(ad, jnp.float64), jnp.asarray(bd, jnp.float64))
        return Val(out, valid, T.DOUBLE if rt.name not in ("real",) else rt)
    out = int_op(ad, bd)
    if isinstance(rt, T.DecimalType) and isinstance(hint, T.DecimalType):
        out = _rescale_decimal(out, hint.scale, rt.scale)
    return Val(out, valid, rt)


@register("$add")
def _add(ctx, call, a, b):
    return _arith(ctx, call, a, b, jnp.add, jnp.add)


@register("$sub")
def _sub(ctx, call, a, b):
    return _arith(ctx, call, a, b, jnp.subtract, jnp.subtract)


@register("$mul")
def _mul(ctx, call, a, b):
    rt = call.type
    valid = _and_valid(a.valid, b.valid)
    sa, sb = _dec_scale(a.type), _dec_scale(b.type)
    if _is_long_dec(a.type) or _is_long_dec(b.type) or _is_long_dec(rt):
        if a.type.name in ("real", "double") or b.type.name in ("real", "double"):
            return Val(_to_float(a) * _to_float(b), valid, T.DOUBLE)
        from trino_tpu.types import int128 as i128

        if _is_long_dec(a.type) and _is_long_dec(b.type):
            raise NotImplementedError(
                "multiplication of two long decimals"
            )
        ls = _dec_scale(a.type) or 0
        ss = _dec_scale(b.type) or 0
        if not _is_long_dec(a.type) and not _is_long_dec(b.type):
            # short x short with a long result: one exact 64x64->128
            h, l = i128.mul64x64(
                jnp.asarray(a.data, jnp.int64), jnp.asarray(b.data, jnp.int64)
            )
        else:
            # one side rides as planes, the other as a plain i64 multiplier
            long_v, short_v = (a, b) if _is_long_dec(a.type) else (b, a)
            ls = _dec_scale(long_v.type) or 0
            ss = _dec_scale(short_v.type) or 0
            h, l = _to_planes(long_v, ls)
            sd = jnp.asarray(short_v.data, jnp.int64)
            h, l = i128.mul128_by_i64vec(h, l, sd)
        prod_scale = ls + ss
        out_scale = rt.scale if isinstance(rt, T.DecimalType) else prod_scale
        h, l = i128.rescale128(h, l, prod_scale, out_scale)
        if isinstance(rt, T.DecimalType) and not rt.is_long:
            return Val(l, valid, rt)
        return _planes_val(h, l, rt, valid)
    if sa is not None or sb is not None:
        if a.type.name in ("real", "double") or b.type.name in ("real", "double"):
            return Val(_to_float(a) * _to_float(b), valid, T.DOUBLE)
        ad = jnp.asarray(a.data, jnp.int64)
        bd = jnp.asarray(b.data, jnp.int64)
        prod_scale = (sa or 0) + (sb or 0)
        out = ad * bd
        if isinstance(rt, T.DecimalType):
            out = _rescale_decimal(out, prod_scale, rt.scale)
            return Val(out, valid, rt)
        return Val(out, valid, T.DecimalType(18, prod_scale))
    return _arith(ctx, call, a, b, jnp.multiply, jnp.multiply)


@register("$div")
def _div(ctx, call, a, b):
    # Decimal/integer division both produce exact SQL semantics; div-by-zero
    # yields null (TRY semantics; strict mode is a session property).
    valid = _and_valid(a.valid, b.valid)
    rt = call.type
    sa, sb = _dec_scale(a.type), _dec_scale(b.type)
    if _is_long_dec(a.type) or _is_long_dec(b.type):
        if rt.name in ("real", "double") or b.type.name in ("real", "double"):
            bz = _to_float(b) == 0.0
            valid = _and_valid(valid, jnp.logical_not(bz))
            return Val(
                _to_float(a) / jnp.where(bz, 1.0, _to_float(b)), valid, T.DOUBLE
            )
        if _is_long_dec(b.type):
            raise NotImplementedError("division by a long decimal")
        from trino_tpu.types import int128 as i128

        out_scale = rt.scale if isinstance(rt, T.DecimalType) else 0
        # numerator scaled so quotient lands at out_scale (reference:
        # Int128Math.divideRoundUp shift arithmetic)
        h, l = _to_planes(a, out_scale + (sb or 0))
        bd = jnp.asarray(b.data, jnp.int64)
        bz = bd == 0
        valid = _and_valid(valid, jnp.logical_not(bz))
        den = jnp.where(bz, 1, bd)
        neg_d = den < 0
        den_abs = jnp.abs(den)
        qh, ql, r = i128.divmod128_by_vec(h, l, den_abs)
        round_up = (2 * jnp.abs(r)) >= den_abs
        neg_q = (h < 0) ^ neg_d
        bump = jnp.where(round_up, jnp.where(neg_q, -1, 1), 0)
        nqh, nql = i128.neg128(qh, ql)
        qh = jnp.where(neg_d, nqh, qh)
        ql = jnp.where(neg_d, nql, ql)
        qh, ql = i128.add128(qh, ql, bump >> 63, bump)
        if isinstance(rt, T.DecimalType) and not rt.is_long:
            return Val(ql, valid, rt)
        return _planes_val(qh, ql, rt, valid)
    bzero = jnp.asarray(b.data) == 0
    valid = _and_valid(valid, jnp.logical_not(bzero))
    if rt.name in ("real", "double"):
        ad, bd = _to_float(a), _to_float(b)
        out = ad / jnp.where(bzero, 1.0, bd)
        return Val(out, valid, rt)
    if isinstance(rt, T.DecimalType):
        # Trino short-decimal division: rescale numerator by 10^(s_out - sa + sb)
        ad = jnp.asarray(a.data, jnp.int64)
        bd = jnp.asarray(b.data, jnp.int64)
        shift = rt.scale - (sa or 0) + (sb or 0)
        num = ad * (10 ** max(shift, 0))
        den = jnp.where(bzero, 1, bd) * (10 ** max(-shift, 0))
        # truncating division + round half away from zero (SQL), NOT floor-div
        sign = jnp.sign(num) * jnp.sign(den)
        q = jnp.abs(num) // jnp.abs(den)
        r = jnp.abs(num) - q * jnp.abs(den)
        adj = jnp.where(2 * r >= jnp.abs(den), 1, 0)
        return Val(sign * (q + adj), valid, rt)
    # integer division truncates toward zero (SQL), unlike python floor-div.
    # Formulated as floor-div + mixed-sign adjustment rather than via abs():
    # jnp.abs(INT64_MIN) wraps to itself, so the abs form silently corrupts
    # quotients at the int64 edge (caught by tests/test_constant_fold_diff.py)
    ad = jnp.asarray(a.data, jnp.int64)
    bd = jnp.where(bzero, 1, jnp.asarray(b.data, jnp.int64))
    qf = ad // bd
    rem = ad - qf * bd
    adjust = jnp.logical_and(rem != 0, (ad < 0) ^ (bd < 0)).astype(jnp.int64)
    return Val((qf + adjust).astype(rt.np_dtype), valid, rt)


@register("$mod")
def _mod(ctx, call, a, b):
    valid = _and_valid(a.valid, b.valid)
    if _is_long_dec(a.type) or _is_long_dec(b.type) or _is_long_dec(call.type):
        if _is_long_dec(b.type):
            raise NotImplementedError("mod by a long decimal")
        from trino_tpu.types import int128 as i128

        s = max(_dec_scale(a.type) or 0, _dec_scale(b.type) or 0)
        h, l = _to_planes(a, s)
        sb = _dec_scale(b.type) or 0
        pb = b.type.precision if isinstance(b.type, T.DecimalType) else 19
        if pb + (s - sb) > 18:
            # rescaled divisor could overflow i64 (static type bound)
            raise NotImplementedError(
                "mod with a divisor wider than 18 digits at the common scale"
            )
        bd = jnp.asarray(b.data, jnp.int64) * (10 ** (s - sb))
        bz = bd == 0
        valid = _and_valid(valid, jnp.logical_not(bz))
        den = jnp.abs(jnp.where(bz, 1, bd))
        _, _, r = i128.divmod128_by_vec(h, l, den)  # sign follows dividend
        rt = call.type
        out_s = rt.scale if isinstance(rt, T.DecimalType) else s
        rh, rl = i128.rescale128(*i128.widen64(r), s, out_s)
        if isinstance(rt, T.DecimalType) and rt.is_long:
            return _planes_val(rh, rl, rt, valid)
        return Val(rl, valid, rt)
    bzero = jnp.asarray(b.data) == 0
    valid = _and_valid(valid, ~bzero)
    ad, bd, hint = _align_numeric(a, b)
    bd = jnp.where(bzero, 1, bd)
    # SQL mod: sign follows dividend
    out = jnp.sign(ad) * (jnp.abs(ad) % jnp.abs(bd))
    return Val(out, valid, call.type)


@register("$neg")
def _neg(ctx, call, a):
    if _is_long_dec(a.type):
        from trino_tpu.types import int128 as i128

        h, l = _to_planes(a, a.type.scale)
        return _planes_val(*i128.neg128(h, l), call.type, a.valid)
    return Val(jnp.negative(jnp.asarray(a.data)), a.valid, call.type)


# ---------------------------------------------------------------------------
# comparisons (dictionary-aware)


def _cmp_operands(ctx, a: Val, b: Val):
    """Align two values for comparison; returns (ad, bd) arrays."""
    if a.dictionary is not None or b.dictionary is not None:
        da, db = a.dictionary, b.dictionary
        if da is not None and db is not None:
            if da is db or da == db:
                return jnp.asarray(a.data, jnp.int32), jnp.asarray(b.data, jnp.int32)
            from trino_tpu.columnar.dictionary import union_dictionaries

            m, ra, rb = union_dictionaries(da, db)
            ad = jnp.take(jnp.asarray(ra), jnp.asarray(a.data, jnp.int32), mode="clip")
            bd = jnp.take(jnp.asarray(rb), jnp.asarray(b.data, jnp.int32), mode="clip")
            return ad, bd
        raise TypeError("comparison between string and non-string")
    ad, bd, _ = _align_numeric(a, b)
    return ad, bd


def _string_literal_of(v: Val) -> str | None:
    """If v is a single-value-dictionary scalar (a string literal), return it."""
    if v.dictionary is not None and len(v.dictionary) == 1 and jnp.ndim(v.data) == 0:
        return v.dictionary.values[0]
    return None


def _dict_range_cmp(op: str, col: Val, lit: str):
    """Order comparison of a dictionary column against a string literal using
    the order-preserving property: translate to a code-range test."""
    d = col.dictionary
    codes = jnp.asarray(col.data, jnp.int32)
    if op == "$lt":
        return codes < d.lower_bound(lit)
    if op == "$le":
        return codes < d.upper_bound(lit)
    if op == "$gt":
        return codes >= d.upper_bound(lit)
    if op == "$ge":
        return codes >= d.lower_bound(lit)
    raise AssertionError(op)


def _cmp_long(op: str, a: Val, b: Val, valid) -> Val:
    """Comparison over two-limb long decimals (either side may be short)."""
    from trino_tpu.types import int128 as i128

    s = max(_dec_scale(a.type) or 0, _dec_scale(b.type) or 0)
    ah, al = _to_planes(a, s)
    bh, bl = _to_planes(b, s)
    eq = i128.eq128(ah, al, bh, bl)
    lt = i128.lt128(ah, al, bh, bl)
    out = {
        "$eq": eq,
        "$ne": ~eq,
        "$lt": lt,
        "$le": lt | eq,
        "$gt": ~(lt | eq),
        "$ge": ~lt,
    }[op]
    return Val(out, valid, T.BOOLEAN)


def _comparison(op: str, jop):
    def handler(ctx, call, a, b):
        valid = _and_valid(a.valid, b.valid)
        if _is_long_dec(a.type) or _is_long_dec(b.type):
            return _cmp_long(op, a, b, valid)
        # string-vs-literal fast paths
        la, lb = _string_literal_of(a), _string_literal_of(b)
        if a.dictionary is not None and lb is not None and la is None:
            if op in ("$eq", "$ne"):
                code = a.dictionary.code_of(lb)
                r = jnp.asarray(a.data, jnp.int32) == code
                return Val(r if op == "$eq" else ~r, valid, T.BOOLEAN)
            return Val(_dict_range_cmp(op, a, lb), valid, T.BOOLEAN)
        if b.dictionary is not None and la is not None and lb is None:
            flip = {"$lt": "$gt", "$le": "$ge", "$gt": "$lt", "$ge": "$le"}
            if op in ("$eq", "$ne"):
                code = b.dictionary.code_of(la)
                r = jnp.asarray(b.data, jnp.int32) == code
                return Val(r if op == "$eq" else ~r, valid, T.BOOLEAN)
            return Val(_dict_range_cmp(flip[op], b, la), valid, T.BOOLEAN)
        ad, bd = _cmp_operands(ctx, a, b)
        return Val(jop(ad, bd), valid, T.BOOLEAN)

    return handler


FUNCTIONS["$eq"] = _comparison("$eq", jnp.equal)
FUNCTIONS["$ne"] = _comparison("$ne", jnp.not_equal)
FUNCTIONS["$lt"] = _comparison("$lt", jnp.less)
FUNCTIONS["$le"] = _comparison("$le", jnp.less_equal)
FUNCTIONS["$gt"] = _comparison("$gt", jnp.greater)
FUNCTIONS["$ge"] = _comparison("$ge", jnp.greater_equal)


# ---------------------------------------------------------------------------
# math


def _unary_float(jfn):
    def handler(ctx, call, a):
        return Val(jfn(_to_float(a)), a.valid, T.DOUBLE)

    return handler


FUNCTIONS["sqrt"] = _unary_float(jnp.sqrt)
FUNCTIONS["cbrt"] = _unary_float(jnp.cbrt)
FUNCTIONS["exp"] = _unary_float(jnp.exp)
FUNCTIONS["ln"] = _unary_float(jnp.log)
FUNCTIONS["log10"] = _unary_float(jnp.log10)
FUNCTIONS["log2"] = _unary_float(jnp.log2)
FUNCTIONS["sin"] = _unary_float(jnp.sin)
FUNCTIONS["cos"] = _unary_float(jnp.cos)
FUNCTIONS["tan"] = _unary_float(jnp.tan)
FUNCTIONS["degrees"] = _unary_float(jnp.degrees)
FUNCTIONS["radians"] = _unary_float(jnp.radians)
FUNCTIONS["sign"] = lambda ctx, call, a: Val(
    jnp.sign(jnp.asarray(a.data)), a.valid, call.type
)


@register("abs")
def _abs(ctx, call, a):
    if _is_long_dec(a.type):
        from trino_tpu.types import int128 as i128

        h, l = _to_planes(a, a.type.scale)
        nh, nl = i128.neg128(h, l)
        neg = h < 0
        return _planes_val(
            jnp.where(neg, nh, h), jnp.where(neg, nl, l), call.type, a.valid
        )
    return Val(jnp.abs(jnp.asarray(a.data)), a.valid, call.type)


@register("power")
def _power(ctx, call, a, b):
    return Val(
        jnp.power(_to_float(a), _to_float(b)), _and_valid(a.valid, b.valid), T.DOUBLE
    )


@register("pow")
def _pow(ctx, call, a, b):
    return _power(ctx, call, a, b)


@register("mod")
def _mod_fn(ctx, call, a, b):
    return _mod(ctx, call, a, b)


def _floor_ceil_long(a: Val, out_t: T.Type, is_ceil: bool) -> Val:
    """floor/ceil of a long decimal to scale 0 over limb planes."""
    from trino_tpu.types import int128 as i128

    h, l = _to_planes(a, a.type.scale)
    qh, ql, any_r = i128.truncdiv_pow10(h, l, a.type.scale)
    if is_ceil:
        adj = jnp.logical_and(any_r, h >= 0).astype(jnp.int64)
    else:
        adj = -jnp.logical_and(any_r, h < 0).astype(jnp.int64)
    qh, ql = i128.add128(qh, ql, adj >> 63, adj)
    if isinstance(out_t, T.DecimalType) and out_t.is_long:
        return _planes_val(qh, ql, out_t, a.valid)
    return Val(ql, a.valid, out_t)


@register("floor")
def _floor(ctx, call, a):
    if _is_long_dec(a.type):
        out_t = (
            call.type
            if isinstance(call.type, T.DecimalType)
            else T.DecimalType(max(a.type.precision - a.type.scale, 19), 0)
        )
        return _floor_ceil_long(a, out_t, is_ceil=False)
    if isinstance(a.type, T.DecimalType):
        # jnp // on ints is floor division, exactly SQL floor-to-scale-0
        d = jnp.asarray(a.data, jnp.int64) // a.type.scale_factor
        return Val(d, a.valid, T.DecimalType(18, 0))
    if a.type.name in ("double", "real"):
        return Val(jnp.floor(_to_float(a)), a.valid, T.DOUBLE)
    return a


@register("ceil")
@register("ceiling")
def _ceil(ctx, call, a):
    if _is_long_dec(a.type):
        out_t = (
            call.type
            if isinstance(call.type, T.DecimalType)
            else T.DecimalType(max(a.type.precision - a.type.scale, 19), 0)
        )
        return _floor_ceil_long(a, out_t, is_ceil=True)
    if isinstance(a.type, T.DecimalType):
        d = -((-jnp.asarray(a.data, jnp.int64)) // a.type.scale_factor)
        return Val(d, a.valid, T.DecimalType(18, 0))
    if a.type.name in ("double", "real"):
        return Val(jnp.ceil(_to_float(a)), a.valid, T.DOUBLE)
    return a


@register("round")
def _round(ctx, call, a, nd=None):
    digits = 0
    if nd is not None:
        digits = int(np.asarray(nd.data))  # literal digits only
    if _is_long_dec(a.type):
        from trino_tpu.types import int128 as i128

        s = a.type.scale
        h, l = _to_planes(a, s)
        h, l = i128.rescale128(h, l, s, min(s, digits))  # round half away
        out_t = call.type
        out_s = out_t.scale if isinstance(out_t, T.DecimalType) else digits
        h, l = i128.rescale128(h, l, min(s, digits), out_s)
        if isinstance(out_t, T.DecimalType) and out_t.is_long:
            return _planes_val(h, l, out_t, a.valid)
        if not isinstance(out_t, T.DecimalType):
            out_t = T.DecimalType(19, out_s)
            return _planes_val(h, l, out_t, a.valid)
        return Val(l, a.valid, out_t)
    if isinstance(a.type, T.DecimalType):
        from trino_tpu.expr.functions import _rescale_decimal

        s = a.type.scale
        out_t = call.type
        out_s = out_t.scale if isinstance(out_t, T.DecimalType) else digits
        d = _rescale_decimal(jnp.asarray(a.data, jnp.int64), s, min(s, digits))
        d = _rescale_decimal(d, min(s, digits), out_s)
        return Val(d, a.valid, out_t if isinstance(out_t, T.DecimalType) else T.DecimalType(18, out_s))
    f = _to_float(a)
    m = 10.0 ** digits
    # SQL rounds half away from zero; jnp.round is half-to-even
    out = jnp.sign(f) * jnp.floor(jnp.abs(f) * m + 0.5) / m
    if call.type.name in ("bigint", "integer") and digits == 0:
        return Val(out.astype(call.type.np_dtype), a.valid, call.type)
    return Val(out, a.valid, T.DOUBLE)


def _minmax(jop):
    def handler(ctx, call, *vals):
        valid = None
        for v in vals:
            valid = _and_valid(valid, v.valid)
        if any(_is_long_dec(v.type) for v in vals):
            from trino_tpu.types import int128 as i128

            want_max = jop is jnp.maximum
            s = max((_dec_scale(v.type) or 0) for v in vals)
            ah, al = _to_planes(vals[0], s)
            for v in vals[1:]:
                bh, bl = _to_planes(v, s)
                lt = i128.lt128(ah, al, bh, bl)
                take_b = lt if want_max else ~lt
                ah = jnp.where(take_b, bh, ah)
                al = jnp.where(take_b, bl, al)
            rt = call.type
            if isinstance(rt, T.DecimalType) and not rt.is_long:
                return Val(al, valid, rt)
            return _planes_val(ah, al, rt, valid)
        dicts = [v.dictionary for v in vals if v.dictionary is not None]
        if dicts:
            # recode everything into one union dictionary up front so codes
            # stay comparable and the result dictionary matches its codes
            out_dict = dicts[0]
            for d in dicts[1:]:
                if d is not out_dict and d != out_dict:
                    out_dict = StringDictionary.from_unsorted(out_dict.values + d.values)
            datas = [ctx._recode(v, out_dict) for v in vals]
        else:
            out_dict = None
            base = vals[0]
            datas = [_align_numeric(v, base)[0] for v in vals]
        acc = datas[0]
        for d in datas[1:]:
            acc = jop(acc, d)
        return Val(acc, valid, call.type, out_dict)

    return handler


FUNCTIONS["greatest"] = _minmax(jnp.maximum)
FUNCTIONS["least"] = _minmax(jnp.minimum)


# ---------------------------------------------------------------------------
# date/time (civil calendar math on day numbers; Howard Hinnant's algorithms)


def _civil_from_days(days):
    z = jnp.asarray(days, jnp.int64) + 719468
    era = jnp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y, m, d


def _days_from_civil(y, m, d):
    y = y - (m <= 2)
    era = jnp.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def _as_days(v: Val):
    if v.type is T.TIMESTAMP:
        return jnp.asarray(v.data, jnp.int64) // 86_400_000_000
    if v.type is T.TIMESTAMP_TZ:
        return _tz_local_micros(v) // 86_400_000_000
    return jnp.asarray(v.data, jnp.int64)


def _tz_local_micros(v: Val):
    """Wall-clock micros in the value's own zone (packed tz layout)."""
    p = jnp.asarray(v.data, jnp.int64)
    millis = T.unpack_tz_millis(p)
    off = T.unpack_tz_offset(p)
    return (millis + off * 60_000) * 1000


def _day_micros(v: Val):
    """Micros since local midnight for timestamp / timestamptz values."""
    if v.type is T.TIMESTAMP_TZ:
        us = _tz_local_micros(v)
    else:
        us = jnp.asarray(v.data, jnp.int64)
    return us % 86_400_000_000


@register("year")
def _year(ctx, call, a):
    y, _, _ = _civil_from_days(_as_days(a))
    return Val(y, a.valid, T.BIGINT)


@register("month")
def _month(ctx, call, a):
    _, m, _ = _civil_from_days(_as_days(a))
    return Val(m, a.valid, T.BIGINT)


@register("day")
@register("day_of_month")
def _day(ctx, call, a):
    _, _, d = _civil_from_days(_as_days(a))
    return Val(d, a.valid, T.BIGINT)


@register("quarter")
def _quarter(ctx, call, a):
    _, m, _ = _civil_from_days(_as_days(a))
    return Val((m - 1) // 3 + 1, a.valid, T.BIGINT)


@register("day_of_week")
@register("dow")
def _dow(ctx, call, a):
    d = _as_days(a)
    return Val((d + 3) % 7 + 1, a.valid, T.BIGINT)  # 1=Monday..7=Sunday


@register("day_of_year")
@register("doy")
def _doy(ctx, call, a):
    d = _as_days(a)
    y, _, _ = _civil_from_days(d)
    jan1 = _days_from_civil(y, jnp.asarray(1), jnp.asarray(1))
    return Val(d - jan1 + 1, a.valid, T.BIGINT)


@register("date_add_days")
def _date_add_days(ctx, call, a, n):
    return Val(
        jnp.asarray(a.data, jnp.int64) + jnp.asarray(n.data, jnp.int64),
        _and_valid(a.valid, n.valid),
        call.type,
    )


@register("date_add_months")
def _date_add_months(ctx, call, a, n):
    y, m, d = _civil_from_days(_as_days(a))
    months = y * 12 + (m - 1) + jnp.asarray(n.data, jnp.int64)
    ny, nm = months // 12, months % 12 + 1
    # clamp day to last day of target month
    last = _days_from_civil(
        jnp.where(nm == 12, ny + 1, ny), jnp.where(nm == 12, 1, nm + 1), jnp.asarray(1)
    ) - _days_from_civil(ny, nm, jnp.asarray(1))
    nd = jnp.minimum(d, last)
    days = _days_from_civil(ny, nm, nd)
    valid = _and_valid(a.valid, n.valid)
    if a.type is T.TIMESTAMP:
        # keep the time-of-day: shift only the calendar day component
        tod = jnp.asarray(a.data, jnp.int64) % 86_400_000_000
        return Val(days * 86_400_000_000 + tod, valid, call.type)
    if a.type is T.TIMESTAMP_TZ:
        p = jnp.asarray(a.data, jnp.int64)
        off = T.unpack_tz_offset(p)
        local_ms = T.unpack_tz_millis(p) + off * 60_000
        tod_ms = local_ms % 86_400_000
        utc_ms = days * 86_400_000 + tod_ms - off * 60_000
        return Val(utc_ms * T.TZ_SHIFT + (off + T.TZ_OFFSET_BIAS), valid, call.type)
    return Val(days, valid, call.type)


@register("date_trunc_month")
def _date_trunc_month(ctx, call, a):
    y, m, _ = _civil_from_days(_as_days(a))
    return Val(_days_from_civil(y, m, jnp.asarray(1)), a.valid, T.DATE)


@register("date_trunc_year")
def _date_trunc_year(ctx, call, a):
    y, _, _ = _civil_from_days(_as_days(a))
    return Val(_days_from_civil(y, jnp.asarray(1), jnp.asarray(1)), a.valid, T.DATE)


def _add_months_days(days, k):
    """Day-number + k months with month-end clamping (shared by date_add
    and date_diff's complete-period check)."""
    y, m, d = _civil_from_days(days)
    months = y * 12 + (m - 1) + k
    ny, nm = months // 12, months % 12 + 1
    last = _days_from_civil(
        jnp.where(nm == 12, ny + 1, ny),
        jnp.where(nm == 12, 1, nm + 1),
        jnp.asarray(1),
    ) - _days_from_civil(ny, nm, jnp.asarray(1))
    return _days_from_civil(ny, nm, jnp.minimum(d, last))


def _temporal_micros(v: Val):
    """(local micros, kind) for date/timestamp/timestamptz values.
    kind: 'date' | 'ts' | 'tz'."""
    if v.type is T.TIMESTAMP_TZ:
        return _tz_local_micros(v), "tz"
    if v.type is T.TIMESTAMP:
        return jnp.asarray(v.data, jnp.int64), "ts"
    return jnp.asarray(v.data, jnp.int64) * 86_400_000_000, "date"


def _temporal_pack(us, kind, v: Val):
    """Local micros back to the value's representation."""
    if kind == "tz":
        off = T.unpack_tz_offset(jnp.asarray(v.data, jnp.int64))
        utc_millis = us // 1000 - off * 60_000
        return utc_millis * T.TZ_SHIFT + (off + T.TZ_OFFSET_BIAS)
    if kind == "ts":
        return us
    return us // 86_400_000_000


@register("date_trunc")
def _date_trunc(ctx, call, unit, v):
    """date_trunc(unit, date|timestamp|timestamptz) preserving the input
    type (reference: scalar/DateTimeFunctions truncate family)."""
    u = _literal_str(unit, "date_trunc").lower()
    us, kind = _temporal_micros(v)
    is_ts = kind != "date"
    days = us // 86_400_000_000
    if u in ("second", "minute", "hour"):
        if not is_ts:
            return Val(v.data, v.valid, call.type, v.dictionary)
        step = {"second": 1_000_000, "minute": 60_000_000, "hour": 3_600_000_000}[u]
        return Val(_temporal_pack((us // step) * step, kind, v), v.valid, call.type)
    if u == "day":
        out_days = days
    elif u == "week":
        # ISO weeks start Monday; 1970-01-01 was a Thursday
        out_days = days - (days + 3) % 7
    elif u in ("month", "year", "quarter"):
        y, m, _ = _civil_from_days(days)
        if u == "month":
            out_days = _days_from_civil(y, m, jnp.asarray(1))
        elif u == "quarter":
            qm = ((m - 1) // 3) * 3 + 1
            out_days = _days_from_civil(y, qm, jnp.asarray(1))
        else:
            out_days = _days_from_civil(y, jnp.asarray(1), jnp.asarray(1))
    else:
        raise NotImplementedError(f"date_trunc unit {u!r}")
    return Val(
        _temporal_pack(out_days * 86_400_000_000, kind, v), v.valid, call.type
    )


_TIME_UNIT_US = {
    "millisecond": 1000,
    "second": 1_000_000,
    "minute": 60_000_000,
    "hour": 3_600_000_000,
    "day": 86_400_000_000,
    "week": 7 * 86_400_000_000,
}


@register("date_add")
def _date_add_general(ctx, call, unit, n, v):
    """date_add(unit, value, date|timestamp|timestamptz) (reference:
    DateTimeFunctions.addFieldValue*)."""
    u = _literal_str(unit, "date_add").lower().rstrip("s")
    k = jnp.asarray(n.data, jnp.int64)
    valid = _and_valid(v.valid, n.valid)
    us, kind = _temporal_micros(v)
    if u in ("month", "quarter", "year"):
        mult = {"month": 1, "quarter": 3, "year": 12}[u]
        rem = us % 86_400_000_000
        out_days = _add_months_days(us // 86_400_000_000, k * mult)
        return Val(
            _temporal_pack(out_days * 86_400_000_000 + rem, kind, v),
            valid,
            call.type,
        )
    step = _TIME_UNIT_US.get(u)
    if step is None:
        raise NotImplementedError(f"date_add unit {u!r}")
    if kind == "date" and step < 86_400_000_000:
        raise TypeError(f"date_add({u!r}) on a DATE value")
    return Val(_temporal_pack(us + k * step, kind, v), valid, call.type)


@register("date_diff")
def _date_diff_general(ctx, call, unit, a, b):
    """date_diff(unit, from, to) = complete units between (reference:
    DateTimeFunctions.diffDate/diffTimestamp — Joda field-difference
    semantics: partial trailing units do not count, truncation toward 0)."""
    u = _literal_str(unit, "date_diff").lower().rstrip("s")
    va, _ = _temporal_micros(a)
    vb, _ = _temporal_micros(b)
    valid = _and_valid(a.valid, b.valid)
    if u in ("month", "quarter", "year"):
        da = va // 86_400_000_000
        db = vb // 86_400_000_000
        ya, ma, _dda = _civil_from_days(da)
        yb, mb, _ddb = _civil_from_days(db)
        months = (yb * 12 + mb) - (ya * 12 + ma)
        # complete-period check honoring month-end clamping: the candidate
        # count stands only if from + months <= to (sign-symmetric); this
        # keeps date_add and date_diff mutually consistent (Jan 31 + 1
        # month = Feb 29 -> diff(Jan 31, Feb 29) = 1)
        shifted = _add_months_days(da, months)
        months = (
            months
            - jnp.where(jnp.logical_and(months > 0, shifted > db), 1, 0)
            + jnp.where(jnp.logical_and(months < 0, shifted < db), 1, 0)
        )
        div = {"month": 1, "quarter": 3, "year": 12}[u]
        if div > 1:
            out = jnp.sign(months) * (jnp.abs(months) // div)
        else:
            out = months
        return Val(out, valid, call.type)
    step = _TIME_UNIT_US.get(u)
    if step is None:
        raise NotImplementedError(f"date_diff unit {u!r}")
    diff = vb - va
    # truncate toward zero: -30min is 0 complete hours, not -1
    out = jnp.sign(diff) * (jnp.abs(diff) // step)
    return Val(out, valid, call.type)


# ---------------------------------------------------------------------------
# time-of-day + timestamp with time zone
# (reference: operator/scalar/DateTimeFunctions.java + spi DateTimeEncoding)


@register("hour")
def _hour(ctx, call, a):
    return Val(_day_micros(a) // 3_600_000_000, a.valid, T.BIGINT)


@register("minute")
def _minute(ctx, call, a):
    return Val(_day_micros(a) // 60_000_000 % 60, a.valid, T.BIGINT)


@register("second")
def _second(ctx, call, a):
    return Val(_day_micros(a) // 1_000_000 % 60, a.valid, T.BIGINT)


@register("millisecond")
def _millisecond(ctx, call, a):
    return Val(_day_micros(a) // 1000 % 1000, a.valid, T.BIGINT)


@register("$tz_instant")
def _tz_instant(ctx, call, a):
    """packed tz -> UTC instant micros (TIMESTAMP in the UTC session zone)."""
    millis = T.unpack_tz_millis(jnp.asarray(a.data, jnp.int64))
    return Val(millis * 1000, a.valid, T.TIMESTAMP)


def _zone_offset_of(zone: Val, name: str) -> int:
    return T.zone_offset_minutes(_literal_str(zone, name))


@register("at_timezone")
def _at_timezone(ctx, call, v, zone):
    """`v AT TIME ZONE z`: same instant, displayed in zone z (reference:
    scalar/AtTimeZone.java).  Named-zone offsets resolve at plan time (the
    offset in force now), fixed offsets are exact."""
    off = _zone_offset_of(zone, "AT TIME ZONE")
    if v.type is T.TIMESTAMP_TZ:
        millis = T.unpack_tz_millis(jnp.asarray(v.data, jnp.int64))
    elif v.type is T.TIMESTAMP:
        # session zone is UTC: the local timestamp IS the instant
        millis = jnp.asarray(v.data, jnp.int64) // 1000
    elif v.type is T.DATE:
        millis = jnp.asarray(v.data, jnp.int64) * 86_400_000
    else:
        raise TypeError(f"AT TIME ZONE on {v.type.name}")
    packed = millis * T.TZ_SHIFT + (off + T.TZ_OFFSET_BIAS)
    return Val(packed, v.valid, T.TIMESTAMP_TZ)


@register("with_timezone")
def _with_timezone(ctx, call, v, zone):
    """with_timezone(timestamp, zone): wall time v interpreted IN zone
    (reference: scalar/WithTimeZone.java)."""
    off = _zone_offset_of(zone, "with_timezone")
    local_millis = jnp.asarray(v.data, jnp.int64) // 1000
    utc = local_millis - off * 60_000
    return Val(
        utc * T.TZ_SHIFT + (off + T.TZ_OFFSET_BIAS), v.valid, T.TIMESTAMP_TZ
    )


@register("$tz_add_micros")
def _tz_add_micros(ctx, call, v, delta):
    """timestamptz + day-second interval: shift the UTC instant, keep the
    zone offset (reference: DateTimeOperators tz + interval)."""
    p = jnp.asarray(v.data, jnp.int64)
    off = p % T.TZ_SHIFT
    millis = T.unpack_tz_millis(p) + jnp.asarray(delta.data, jnp.int64) // 1000
    return Val(
        millis * T.TZ_SHIFT + off, _and_valid(v.valid, delta.valid), T.TIMESTAMP_TZ
    )


@register("from_unixtime")
def _from_unixtime(ctx, call, secs, zone=None):
    off = _zone_offset_of(zone, "from_unixtime") if zone is not None else 0
    millis = (jnp.asarray(secs.data, jnp.float64) * 1000.0).astype(jnp.int64)
    if call.type is T.TIMESTAMP:
        return Val(millis * 1000, secs.valid, T.TIMESTAMP)
    return Val(
        millis * T.TZ_SHIFT + (off + T.TZ_OFFSET_BIAS),
        secs.valid,
        T.TIMESTAMP_TZ,
    )


@register("to_unixtime")
def _to_unixtime(ctx, call, v):
    if v.type is T.TIMESTAMP_TZ:
        millis = T.unpack_tz_millis(jnp.asarray(v.data, jnp.int64))
        return Val(millis.astype(jnp.float64) / 1000.0, v.valid, T.DOUBLE)
    return Val(
        jnp.asarray(v.data, jnp.float64) / 1_000_000.0, v.valid, T.DOUBLE
    )


@register("timezone_minute")
def _timezone_minute(ctx, call, v):
    off = T.unpack_tz_offset(jnp.asarray(v.data, jnp.int64))
    return Val(jnp.sign(off) * (jnp.abs(off) % 60), v.valid, T.BIGINT)


@register("timezone_hour")
def _timezone_hour(ctx, call, v):
    off = T.unpack_tz_offset(jnp.asarray(v.data, jnp.int64))
    return Val(off // 60 + jnp.where(off < 0, (off % 60 != 0), 0), v.valid, T.BIGINT)


# ---------------------------------------------------------------------------
# strings (dictionary tables)


def _require_dict(v: Val, what: str) -> StringDictionary:
    if v.dictionary is None:
        raise TypeError(f"{what} requires a string (dictionary) value")
    return v.dictionary


def _literal_str(v: Val, what: str) -> str:
    s = _string_literal_of(v)
    if s is None:
        raise NotImplementedError(f"{what}: pattern/argument must be a literal")
    return s


@register("like")
def _like(ctx, call, value, pattern, escape=None):
    d = _require_dict(value, "LIKE")
    pat = _literal_str(pattern, "LIKE")
    esc = _literal_str(escape, "LIKE escape") if escape is not None else None
    codes = jnp.asarray(value.data, jnp.int32)
    pfx = like_prefix(pat, esc)
    if pfx is not None:
        lo, hi = d.prefix_range(pfx)
        return Val((codes >= lo) & (codes < hi), value.valid, T.BOOLEAN)
    rx = like_to_regex(pat, esc)
    table = jnp.asarray(d.predicate_table(lambda s: rx.match(s) is not None))
    return Val(jnp.take(table, codes, mode="clip"), value.valid, T.BOOLEAN)


@register("regexp_like")
def _regexp_like(ctx, call, value, pattern):
    """reference: operator/scalar/JoniRegexpFunctions.java regexpLike —
    evaluated once per dictionary entry, broadcast to codes."""
    import re

    d = _require_dict(value, "regexp_like")
    rx = re.compile(_literal_str(pattern, "regexp_like"))
    table = jnp.asarray(d.predicate_table(lambda s: rx.search(s) is not None))
    codes = jnp.asarray(value.data, jnp.int32)
    return Val(jnp.take(table, codes, mode="clip"), value.valid, T.BOOLEAN)


@register("regexp_extract")
def _regexp_extract(ctx, call, value, pattern, group=None):
    """regexp_extract(s, p[, group]); NULL when the pattern has no match."""
    import re

    d = _require_dict(value, "regexp_extract")
    rx = re.compile(_literal_str(pattern, "regexp_extract"))
    g = int(np.asarray(group.data)) if group is not None else 0
    outs, hits = [], []
    for s in d.values:
        m = rx.search(s)
        if m is None:
            outs.append("")
            hits.append(False)
        else:
            outs.append(m.group(g) or "")
            hits.append(True)
    nd = StringDictionary.from_unsorted(outs)
    ix = nd.index
    table = jnp.asarray(
        np.fromiter((ix[o] for o in outs), dtype=np.int32, count=len(outs))
    )
    hit_table = jnp.asarray(np.asarray(hits, dtype=bool))
    codes = jnp.asarray(value.data, jnp.int32)
    out_codes = jnp.take(table, codes, mode="clip")
    hit = jnp.take(hit_table, codes, mode="clip")
    valid = hit if value.valid is None else jnp.logical_and(value.valid, hit)
    return Val(out_codes, valid, call.type, nd)


@register("regexp_replace")
def _regexp_replace(ctx, call, value, pattern, repl=None):
    import re

    rx = re.compile(_literal_str(pattern, "regexp_replace"))
    r = _literal_str(repl, "regexp_replace") if repl is not None else ""
    # SQL backreferences use $1; python re uses \1
    r = re.sub(r"\$(\d+)", r"\\\1", r)
    return _string_map(
        ctx, call, value, lambda s: rx.sub(r, s), "regexp_replace"
    )


def _string_map(ctx, call, value: Val, fn, what: str) -> Val:
    """Map a python string fn over the dictionary -> new dictionary + table."""
    d = _require_dict(value, what)
    outs = [fn(s) for s in d.values]
    nd = StringDictionary.from_unsorted(outs)
    ix = nd.index
    table = jnp.asarray(
        np.fromiter((ix[o] for o in outs), dtype=np.int32, count=len(outs))
    )
    codes = jnp.take(table, jnp.asarray(value.data, jnp.int32), mode="clip")
    return Val(codes, value.valid, call.type, nd)


@register("substr")
@register("substring")
def _substr(ctx, call, value, start, length=None):
    s0 = int(np.asarray(start.data))
    ln = int(np.asarray(length.data)) if length is not None else None

    def fn(s: str) -> str:
        # SQL substr is 1-based; start=0, non-positive length, or a negative
        # start before the beginning all yield '' (ref: StringFunctions.java:280,327)
        if s0 == 0 or (ln is not None and ln <= 0):
            return ""
        if s0 > 0:
            begin = s0 - 1
        else:
            begin = len(s) + s0
            if begin < 0:
                return ""
        return s[begin : begin + ln] if ln is not None else s[begin:]

    return _string_map(ctx, call, value, fn, "substr")


@register("upper")
def _upper(ctx, call, value):
    return _string_map(ctx, call, value, str.upper, "upper")


@register("lower")
def _lower(ctx, call, value):
    return _string_map(ctx, call, value, str.lower, "lower")


@register("trim")
def _trim(ctx, call, value, chars=None):
    cs = _literal_str(chars, "trim") if chars is not None else None
    return _string_map(ctx, call, value, lambda s: s.strip(cs), "trim")


@register("ltrim")
def _ltrim(ctx, call, value, chars=None):
    cs = _literal_str(chars, "ltrim") if chars is not None else None
    return _string_map(ctx, call, value, lambda s: s.lstrip(cs), "ltrim")


@register("rtrim")
def _rtrim(ctx, call, value, chars=None):
    cs = _literal_str(chars, "rtrim") if chars is not None else None
    return _string_map(ctx, call, value, lambda s: s.rstrip(cs), "rtrim")


@register("reverse")
def _reverse(ctx, call, value):
    return _string_map(ctx, call, value, lambda s: s[::-1], "reverse")


@register("replace")
def _replace(ctx, call, value, find, repl=None):
    f = _literal_str(find, "replace")
    r = _literal_str(repl, "replace") if repl is not None else ""
    return _string_map(ctx, call, value, lambda s: s.replace(f, r), "replace")


@register("length")
def _length(ctx, call, value):
    d = _require_dict(value, "length")
    table = jnp.asarray(np.fromiter((len(s) for s in d.values), np.int64, len(d)))
    return Val(
        jnp.take(table, jnp.asarray(value.data, jnp.int32), mode="clip"),
        value.valid,
        T.BIGINT,
    )


@register("strpos")
@register("position")
def _strpos(ctx, call, value, sub):
    d = _require_dict(value, "strpos")
    s = _literal_str(sub, "strpos")
    table = jnp.asarray(np.fromiter((v.find(s) + 1 for v in d.values), np.int64, len(d)))
    return Val(
        jnp.take(table, jnp.asarray(value.data, jnp.int32), mode="clip"),
        value.valid,
        T.BIGINT,
    )


@register("concat")
@register("$concat")
def _concat(ctx, call, *vals):
    # SQL: concat with any NULL argument is NULL
    if any(v.is_literal_null for v in vals):
        return Val(np.int32(0), False, call.type)
    # Supported shapes: any mix where at most ONE argument is a non-literal
    # dictionary column (covers 'lit' || col || 'lit' chains).
    col_ix = [
        i
        for i, v in enumerate(vals)
        if v.dictionary is not None and _string_literal_of(v) is None
    ]
    if not col_ix:
        s = "".join(_literal_str(v, "concat") for v in vals)
        d = StringDictionary([s])
        return Val(np.int32(0), None, call.type, d)
    if len(col_ix) == 2:
        # two dictionary columns: materialize the bounded cross-product
        # dictionary (|da| x |db| pairs) once at trace time; the row value
        # is a single table gather (reference role: ConcatFunction, but
        # amortized over dictionary cardinality, not rows)
        i0, i1 = col_ix
        a, b = vals[i0], vals[i1]
        da, db = a.dictionary, b.dictionary
        if len(da) * len(db) > (1 << 20):
            raise NotImplementedError(
                "concat of two string columns with dictionary product "
                f"{len(da)}x{len(db)} exceeds the materialization bound"
            )
        pre = "".join(_literal_str(v, "concat") for v in vals[:i0])
        mid = "".join(_literal_str(v, "concat") for v in vals[i0 + 1 : i1])
        post = "".join(_literal_str(v, "concat") for v in vals[i1 + 1 :])
        pairs = [
            pre + va + mid + vb + post for va in da.values for vb in db.values
        ]
        merged = StringDictionary.from_unsorted(pairs)
        ix = merged.index
        table = np.fromiter(
            (ix[p] for p in pairs), dtype=np.int32, count=len(pairs)
        )
        nb = len(db)
        flat = jnp.asarray(a.data, jnp.int32) * nb + jnp.asarray(b.data, jnp.int32)
        data = jnp.take(jnp.asarray(table), flat, mode="clip")
        valid = None
        for v in vals:
            valid = _and_valid(valid, v.valid)
        return Val(data, valid, call.type, merged)
    if len(col_ix) > 1:
        raise NotImplementedError("concat of 3+ string columns")
    i = col_ix[0]
    pre = "".join(_literal_str(v, "concat") for v in vals[:i])
    post = "".join(_literal_str(v, "concat") for v in vals[i + 1 :])
    valid = None
    for v in vals:
        valid = _and_valid(valid, v.valid)
    out = _string_map(ctx, call, vals[i], lambda s: pre + s + post, "concat")
    return Val(out.data, valid, call.type, out.dictionary)


@register("starts_with")
def _starts_with(ctx, call, value, prefix):
    d = _require_dict(value, "starts_with")
    p = _literal_str(prefix, "starts_with")
    codes = jnp.asarray(value.data, jnp.int32)
    lo, hi = d.prefix_range(p)
    return Val((codes >= lo) & (codes < hi), value.valid, T.BOOLEAN)


@register("hamming_distance")
def _unsupported(ctx, call, *vals):  # pragma: no cover - explicitness
    raise NotImplementedError(call.name)


# ---------------------------------------------------------------------------
# casts


def compile_cast(ctx: ExprCompiler, v: Val, to: T.Type) -> Val:
    frm = v.type
    if frm == to or frm.name == to.name:
        return Val(v.data, v.valid, to, v.dictionary)
    if to == T.UNKNOWN:
        return v
    if T.is_string_kind(to):
        if v.dictionary is not None:
            return Val(v.data, v.valid, to, v.dictionary)
        # numeric/date -> varchar must happen host-side; only literals allowed
        if jnp.ndim(v.data) == 0 and not isinstance(v.data, jnp.ndarray):
            s = _render_scalar(v)
            d = StringDictionary([s])
            return Val(np.int32(0), v.valid, to, d)
        raise NotImplementedError(f"cast {frm.name} -> varchar on columns")
    if T.is_string_kind(frm):
        # varchar -> numeric/date via dictionary table
        d = _require_dict(v, "cast from varchar")
        if isinstance(to, T.DecimalType) and to.is_long:
            # long-decimal target: the scaled value needs up to 128 bits, so
            # the parse table is two int64 limb planes (types/int128.split_py)
            # — a single int64 table would overflow on assignment for >18
            # digit values and silently NULL a representable number
            from trino_tpu.types.int128 import split_py

            table2 = np.zeros((len(d), 2), dtype=np.int64)
            ok = np.ones(len(d), dtype=bool)
            bound = 10**to.precision
            for i, s in enumerate(d.values):
                try:
                    x = _parse_scalar(s, to)
                    if not -bound < x < bound:
                        raise ValueError("out of decimal range")
                    table2[i, 0], table2[i, 1] = split_py(x)
                except (ValueError, ArithmeticError):
                    ok[i] = False
            codes = jnp.asarray(v.data, jnp.int32)
            data = jnp.take(jnp.asarray(table2), codes, axis=0, mode="clip")
            valid = _and_valid(
                v.valid, jnp.take(jnp.asarray(ok), codes, mode="clip")
            )
            return Val(data, valid, to)
        table = np.zeros(len(d), dtype=to.np_dtype)
        ok = np.ones(len(d), dtype=bool)
        for i, s in enumerate(d.values):
            try:
                table[i] = _parse_scalar(s, to)
            except (ValueError, ArithmeticError):
                ok[i] = False
        codes = jnp.asarray(v.data, jnp.int32)
        data = jnp.take(jnp.asarray(table), codes, mode="clip")
        valid = _and_valid(v.valid, jnp.take(jnp.asarray(ok), codes, mode="clip"))
        return Val(data, valid, to)
    if isinstance(to, T.DecimalType):
        if to.is_long:
            # short/long/integer -> long decimal: limb planes at the target
            # scale (reference: Int128Math.rescale)
            if frm.name in ("double", "real"):
                from trino_tpu.types import int128 as i128

                f = _to_float(v) * to.scale_factor
                r = jnp.sign(f) * jnp.floor(jnp.abs(f) + 0.5)
                # f64 has 53 bits: hi limb from float division is exact
                # enough only within 2**53; beyond that the cast is lossy
                # exactly like the reference's double->decimal
                h = jnp.floor(r / float(i128.TWO64)).astype(jnp.int64)
                lf = r - h.astype(jnp.float64) * float(i128.TWO64)
                # lf is the UNSIGNED low limb in [0, 2**64): values with the
                # top bit set exceed int64 max, so shift into signed range
                # before converting to recover the bit pattern
                l = jnp.where(lf >= float(1 << 63), lf - float(i128.TWO64), lf).astype(
                    jnp.int64
                )
                return _planes_val(h, l, to, v.valid)
            h, l = _to_planes(v, to.scale)
            return _planes_val(h, l, to, v.valid)
        if _is_long_dec(frm):
            # long -> short decimal: rescale in limbs, then take the low
            # limb (values that fit precision 18 live entirely in it)
            from trino_tpu.types import int128 as i128

            h, l = _to_planes(v, to.scale)
            fits = jnp.logical_or(
                jnp.logical_and(h == 0, l >= 0),
                jnp.logical_and(h == -1, l < 0),
            )
            return Val(l, _and_valid(v.valid, fits), to)
        if isinstance(frm, T.DecimalType):
            # short -> short decimal rescale, NULL when the value can
            # overflow the DECLARED precision (checked before the upscale
            # multiply so the check itself cannot wrap); statically skipped
            # when the source precision provably fits
            d = jnp.asarray(v.data, jnp.int64)
            valid = v.valid
            delta = to.scale - frm.scale
            if delta >= 0:
                lim = (10**to.precision - 1) // (10**delta)
                if 10**frm.precision - 1 > lim:
                    valid = _and_valid(
                        valid, jnp.logical_and(d >= -lim, d <= lim)
                    )
                out = d * (10**delta)
            else:
                out = _rescale_decimal(d, frm.scale, to.scale)
                f = 10 ** (-delta)
                lim = 10**to.precision - 1
                if (10**frm.precision - 1 + f // 2) // f > lim:
                    valid = _and_valid(
                        valid, jnp.logical_and(out >= -lim, out <= lim)
                    )
            return Val(out, valid, to)
        if frm.name in ("double", "real"):
            f = _to_float(v) * to.scale_factor
            r = jnp.sign(f) * jnp.floor(jnp.abs(f) + 0.5)
            # NULL on overflow of the declared precision (or NaN): .astype
            # of an out-of-range float is undefined garbage, and the cast
            # family's contract is null-never-wrap
            bound = float(min(10**to.precision, (1 << 63) - 1))
            fits = jnp.logical_and(
                jnp.logical_not(jnp.isnan(f)), jnp.abs(r) < bound
            )
            return Val(
                r.astype(jnp.int64), _and_valid(v.valid, fits), to
            )
        # integer -> short decimal: same NULL-on-precision-overflow
        # contract, checked before the scale multiply; statically skipped
        # when the integer width provably fits the target precision
        d = jnp.asarray(v.data, jnp.int64)
        valid = v.valid
        digits = T.INT_DIGITS.get(frm.name)
        lim = (10**to.precision - 1) // to.scale_factor
        if digits is None or 10**digits - 1 > lim:
            valid = _and_valid(valid, jnp.logical_and(d >= -lim, d <= lim))
        return Val(d * to.scale_factor, valid, to)
    if _is_long_dec(frm):
        # long decimal -> double/bigint
        if to.name in ("double", "real"):
            return Val(_to_float(v), v.valid, to)
        if to.name in ("bigint", "integer", "smallint", "tinyint"):
            h, l = _to_planes(v, 0)
            # range check (reference: Int128Math overflow on narrowing cast):
            # the value fits i64 iff the high limb is pure sign extension of
            # the low limb; narrower targets additionally bound the low limb.
            # Out-of-range values become NULL (the engine's lazy device
            # pipeline cannot raise data-dependently inside jit) instead of
            # silently wrapping to the unrelated low limb.
            fits = jnp.logical_or(
                jnp.logical_and(h == 0, l >= 0),
                jnp.logical_and(h == -1, l < 0),
            )
            if to.name != "bigint":
                info = np.iinfo(to.np_dtype)
                fits = jnp.logical_and(
                    fits,
                    jnp.logical_and(l >= int(info.min), l <= int(info.max)),
                )
            return Val(l.astype(to.np_dtype), _and_valid(v.valid, fits), to)
        raise NotImplementedError(f"cast {frm.name} -> {to.name}")
    if to.name in ("double", "real"):
        return Val(_to_float(v).astype(to.np_dtype), v.valid, to)
    if to.name in ("bigint", "integer", "smallint", "tinyint"):
        if isinstance(frm, T.DecimalType):
            r = _rescale_decimal(jnp.asarray(v.data, jnp.int64), frm.scale, 0)
            valid = v.valid
            if to.name != "bigint":
                # same NULL-on-overflow contract as the long-decimal cast:
                # a short decimal can still exceed int/smallint/tinyint
                info = np.iinfo(to.np_dtype)
                valid = _and_valid(
                    valid,
                    jnp.logical_and(
                        r >= int(info.min), r <= int(info.max)
                    ),
                )
            return Val(r.astype(to.np_dtype), valid, to)
        if frm.name in ("double", "real"):
            f = _to_float(v)
            r = jnp.sign(f) * jnp.floor(jnp.abs(f) + 0.5)
            # NULL on overflow/NaN, matching the decimal- and long-decimal
            # cast contract above (the folder's _from_py nulls identically)
            info = np.iinfo(to.np_dtype)
            fits = jnp.logical_and(
                jnp.logical_not(jnp.isnan(f)),
                jnp.logical_and(
                    r >= float(int(info.min)), r <= float(int(info.max))
                ),
            )
            return Val(
                r.astype(to.np_dtype), _and_valid(v.valid, fits), to
            )
        if (
            jnp.issubdtype(jnp.asarray(v.data).dtype, jnp.integer)
            and np.iinfo(to.np_dtype).bits
            < np.iinfo(jnp.asarray(v.data).dtype).bits
        ):
            # narrowing integer cast: NULL on overflow — .astype would wrap
            # two's-complement (cast(2**40 as integer) must not be 0); the
            # arithmetic ops wrap by contract, CASTS never do
            d = jnp.asarray(v.data, jnp.int64)
            info = np.iinfo(to.np_dtype)
            fits = jnp.logical_and(
                d >= int(info.min), d <= int(info.max)
            )
            return Val(d.astype(to.np_dtype), _and_valid(v.valid, fits), to)
        return Val(jnp.asarray(v.data).astype(to.np_dtype), v.valid, to)
    if to is T.DATE and frm is T.TIMESTAMP:
        return Val(jnp.asarray(v.data, jnp.int64) // 86_400_000_000, v.valid, to)
    if to is T.TIME and frm is T.TIMESTAMP:
        return Val(
            jnp.asarray(v.data, jnp.int64) % 86_400_000_000, v.valid, to
        )
    if to is T.TIMESTAMP and frm is T.TIME:
        return Val(jnp.asarray(v.data, jnp.int64), v.valid, to)
    if to is T.TIMESTAMP and frm is T.DATE:
        return Val(jnp.asarray(v.data, jnp.int64) * 86_400_000_000, v.valid, to)
    # timestamptz conversions (session zone = UTC; reference:
    # DateTimeOperators cast family over packed values)
    if frm is T.TIMESTAMP_TZ and to is T.TIMESTAMP:
        # keep the wall clock in the value's zone (reference: cast drops the
        # zone, not the offset), matching the tz->date path below
        p = jnp.asarray(v.data, jnp.int64)
        local = T.unpack_tz_millis(p) + T.unpack_tz_offset(p) * 60_000
        return Val(local * 1000, v.valid, to)
    if frm is T.TIMESTAMP_TZ and to is T.DATE:
        p = jnp.asarray(v.data, jnp.int64)
        local = (T.unpack_tz_millis(p) + T.unpack_tz_offset(p) * 60_000) * 1000
        return Val(local // 86_400_000_000, v.valid, to)
    if to is T.TIMESTAMP_TZ and frm is T.TIMESTAMP:
        millis = jnp.asarray(v.data, jnp.int64) // 1000
        return Val(millis * T.TZ_SHIFT + T.TZ_OFFSET_BIAS, v.valid, to)
    if to is T.TIMESTAMP_TZ and frm is T.DATE:
        millis = jnp.asarray(v.data, jnp.int64) * 86_400_000
        return Val(millis * T.TZ_SHIFT + T.TZ_OFFSET_BIAS, v.valid, to)
    if to is T.BOOLEAN:
        return Val(jnp.asarray(v.data) != 0, v.valid, to)
    if frm is T.BOOLEAN:
        return Val(jnp.asarray(v.data).astype(to.np_dtype), v.valid, to)
    raise NotImplementedError(f"cast {frm.name} -> {to.name}")


def _render_scalar(v: Val) -> str:
    if isinstance(v.type, T.DecimalType):
        x = int(np.asarray(v.data))
        s = v.type.scale
        if s == 0:
            return str(x)
        sign = "-" if x < 0 else ""
        x = abs(x)
        return f"{sign}{x // 10**s}.{x % 10**s:0{s}d}"
    return str(np.asarray(v.data))


def _parse_scalar(s: str, to: T.Type):
    s = s.strip()
    if to.name in ("bigint", "integer", "smallint", "tinyint"):
        return int(s)
    if to.name in ("double", "real"):
        return float(s)
    if isinstance(to, T.DecimalType):
        from decimal import Decimal

        from decimal import Context

        _c = Context(prec=60)
        return int(Decimal(s).scaleb(to.scale, context=_c).to_integral_value(context=_c))
    if to is T.DATE:
        import datetime

        y, m, d = map(int, s.split("-"))
        return (datetime.date(y, m, d) - datetime.date(1970, 1, 1)).days
    if to is T.BOOLEAN:
        return s.lower() in ("true", "t", "1")
    if to is T.TIME:
        return T.parse_time_micros(s)
    if to is T.TIMESTAMP:
        import datetime

        txt = s.replace("T", " ")
        if " " in txt:
            d, tm = txt.split(" ", 1)
        else:
            d, tm = txt, "00:00:00"
        y, m, dd = map(int, d.split("-"))
        days = (datetime.date(y, m, dd) - datetime.date(1970, 1, 1)).days
        return days * 86_400_000_000 + T.parse_time_micros(tm)
    raise ValueError(f"cannot parse {s!r} as {to.name}")


# ---------------------------------------------------------------------------
# string breadth (reference: operator/scalar/StringFunctions.java,
# SplitPart, PadFunctions, TranslateFunction)


@register("split_part")
def _split_part(ctx, call, value, delim, index):
    dl = _literal_str(delim, "split_part")
    ix = int(np.asarray(index.data))

    def fn(s: str) -> str:
        if ix < 1:
            return ""
        parts = s.split(dl) if dl else [s]
        return parts[ix - 1] if ix <= len(parts) else ""

    return _string_map(ctx, call, value, fn, "split_part")


@register("lpad")
def _lpad(ctx, call, value, size, pad=None):
    n = int(np.asarray(size.data))
    p = _literal_str(pad, "lpad") if pad is not None else " "

    def fn(s: str) -> str:
        if len(s) >= n:
            return s[:n]
        fill = (p * n)[: n - len(s)] if p else ""
        return fill + s

    return _string_map(ctx, call, value, fn, "lpad")


@register("rpad")
def _rpad(ctx, call, value, size, pad=None):
    n = int(np.asarray(size.data))
    p = _literal_str(pad, "rpad") if pad is not None else " "

    def fn(s: str) -> str:
        if len(s) >= n:
            return s[:n]
        fill = (p * n)[: n - len(s)] if p else ""
        return s + fill

    return _string_map(ctx, call, value, fn, "rpad")


@register("translate")
def _translate(ctx, call, value, frm, to):
    f = _literal_str(frm, "translate")
    t = _literal_str(to, "translate")
    table = {}
    for i, ch in enumerate(f):
        if ord(ch) not in table:  # first occurrence wins (TranslateFunction)
            table[ord(ch)] = t[i] if i < len(t) else None
    return _string_map(
        ctx, call, value, lambda s: s.translate(table), "translate"
    )


@register("codepoint")
def _codepoint(ctx, call, value):
    d = _require_dict(value, "codepoint")
    table = jnp.asarray(
        np.fromiter(
            (ord(s[0]) if s else 0 for s in d.values),
            dtype=np.int64,
            count=len(d.values),
        )
    )
    out = jnp.take(table, jnp.asarray(value.data, jnp.int32), mode="clip")
    return Val(out, value.valid, call.type)


@register("chr")
def _chr(ctx, call, value):
    # literal-only: a column of arbitrary codepoints would need a
    # data-dependent dictionary, which trace-time compilation cannot build
    if jnp.ndim(value.data) != 0 or isinstance(value.data, jnp.ndarray):
        raise NotImplementedError("chr() supports only literal arguments")
    n = int(np.asarray(value.data))
    d = StringDictionary([chr(n)])
    return Val(np.int32(0), value.valid, call.type, d)


@register("normalize")
def _normalize(ctx, call, value, form=None):
    import unicodedata

    f = _literal_str(form, "normalize") if form is not None else "NFC"
    return _string_map(
        ctx, call, value, lambda s: unicodedata.normalize(f, s), "normalize"
    )


@register("levenshtein_distance")
def _levenshtein(ctx, call, value, target):
    t = _literal_str(target, "levenshtein_distance")
    d = _require_dict(value, "levenshtein_distance")

    def lev(a: str, b: str) -> int:
        if len(a) < len(b):
            a, b = b, a
        prev = list(range(len(b) + 1))
        for i, ca in enumerate(a, 1):
            cur = [i]
            for j, cb in enumerate(b, 1):
                cur.append(
                    min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + (ca != cb))
                )
            prev = cur
        return prev[-1]

    table = jnp.asarray(
        np.fromiter(
            (lev(s, t) for s in d.values), dtype=np.int64, count=len(d.values)
        )
    )
    out = jnp.take(table, jnp.asarray(value.data, jnp.int32), mode="clip")
    return Val(out, value.valid, call.type)


# -- url family (reference: operator/scalar/UrlFunctions.java) ---------------


def _url_part(part: str):
    from urllib.parse import unquote, urlparse

    def get(s: str) -> str:
        try:
            u = urlparse(s)
            if part == "host":
                return u.hostname or ""
            if part == "protocol":
                return u.scheme or ""
            if part == "path":
                return u.path or ""
            if part == "query":
                return u.query or ""
            if part == "fragment":
                return u.fragment or ""
            if part == "port":
                return str(u.port) if u.port is not None else ""
        except ValueError:
            return ""
        return ""

    return get


def _make_url_extract(part: str, name: str):
    @register(name)
    def fn(ctx, call, value, _part=part, _name=name):
        if _part == "port":
            d = _require_dict(value, _name)
            get = _url_part(_part)
            vals = [get(s) for s in d.values]
            table = jnp.asarray(
                np.fromiter(
                    (int(v) if v else -1 for v in vals),
                    dtype=np.int64,
                    count=len(vals),
                )
            )
            out = jnp.take(table, jnp.asarray(value.data, jnp.int32), mode="clip")
            return Val(out, _and_valid(value.valid, out >= 0), call.type)
        return _string_map(ctx, call, value, _url_part(_part), _name)

    return fn


for _p in ("host", "protocol", "path", "query", "fragment", "port"):
    _make_url_extract(_p, f"url_extract_{_p}")


@register("url_encode")
def _url_encode(ctx, call, value):
    from urllib.parse import quote_plus

    return _string_map(ctx, call, value, lambda s: quote_plus(s), "url_encode")


@register("url_decode")
def _url_decode(ctx, call, value):
    from urllib.parse import unquote_plus

    return _string_map(ctx, call, value, lambda s: unquote_plus(s), "url_decode")


# -- math breadth (reference: operator/scalar/MathFunctions.java) ------------


def _unary_math(name, fn):
    @register(name)
    def impl(ctx, call, v, _fn=fn):
        return Val(_fn(_to_float(v)), v.valid, call.type)

    return impl


_unary_math("asin", jnp.arcsin)
_unary_math("acos", jnp.arccos)
_unary_math("atan", jnp.arctan)
_unary_math("sinh", jnp.sinh)
_unary_math("cosh", jnp.cosh)
_unary_math("tanh", jnp.tanh)


@register("atan2")
def _atan2(ctx, call, y, x):
    return Val(
        jnp.arctan2(_to_float(y), _to_float(x)),
        _and_valid(y.valid, x.valid),
        call.type,
    )


@register("log")
def _log(ctx, call, base, x):
    b = _to_float(base)
    v = _to_float(x)
    return Val(
        jnp.log(v) / jnp.log(b), _and_valid(base.valid, x.valid), call.type
    )


@register("truncate")
def _truncate(ctx, call, v):
    f = _to_float(v)
    return Val(jnp.sign(f) * jnp.floor(jnp.abs(f)), v.valid, call.type)


@register("e")
def _e(ctx, call):
    return Val(jnp.float64(np.e), None, call.type)


@register("pi")
def _pi(ctx, call):
    return Val(jnp.float64(np.pi), None, call.type)


@register("nan")
def _nan(ctx, call):
    return Val(jnp.float64(np.nan), None, call.type)


@register("infinity")
def _infinity(ctx, call):
    return Val(jnp.float64(np.inf), None, call.type)


@register("is_nan")
def _is_nan(ctx, call, v):
    return Val(jnp.isnan(_to_float(v)), v.valid, call.type)


@register("is_finite")
def _is_finite(ctx, call, v):
    return Val(jnp.isfinite(_to_float(v)), v.valid, call.type)


@register("is_infinite")
def _is_infinite(ctx, call, v):
    return Val(jnp.isinf(_to_float(v)), v.valid, call.type)


@register("width_bucket")
def _width_bucket(ctx, call, v, lo, hi, n):
    x = _to_float(v)
    a = _to_float(lo)
    b = _to_float(hi)
    k = jnp.asarray(n.data, jnp.float64)
    # equal bounds / non-positive bucket count -> NULL (the reference
    # raises; errors are not expressible row-wise in a traced program)
    ok = jnp.logical_and(b != a, k > 0)
    denom = jnp.where(ok, b - a, 1.0)
    raw = jnp.floor((x - a) / denom * k) + 1
    out = jnp.clip(jnp.where(ok, raw, 0.0), 0, jnp.maximum(k, 0) + 1).astype(jnp.int64)
    valid = _and_valid(_and_valid(v.valid, lo.valid), _and_valid(hi.valid, n.valid))
    return Val(out, _and_valid(valid, ok), call.type)


# -- bitwise (reference: operator/scalar/BitwiseFunctions.java) --------------


def _binary_bitwise(name, fn):
    @register(name)
    def impl(ctx, call, a, b, _fn=fn):
        out = _fn(jnp.asarray(a.data, jnp.int64), jnp.asarray(b.data, jnp.int64))
        return Val(out, _and_valid(a.valid, b.valid), call.type)

    return impl


_binary_bitwise("bitwise_and", jnp.bitwise_and)
_binary_bitwise("bitwise_or", jnp.bitwise_or)
_binary_bitwise("bitwise_xor", jnp.bitwise_xor)
_binary_bitwise("bitwise_left_shift", lambda a, b: a << b)
_binary_bitwise("bitwise_right_shift_arithmetic", lambda a, b: a >> b)


@register("bitwise_not")
def _bitwise_not(ctx, call, a):
    return Val(~jnp.asarray(a.data, jnp.int64), a.valid, call.type)


@register("bit_count")
def _bit_count(ctx, call, a, bits=None):
    x = jnp.asarray(a.data, jnp.uint64)
    if bits is not None:
        nb = int(np.asarray(bits.data))
        if nb < 64:
            x = x & ((np.uint64(1) << np.uint64(nb)) - np.uint64(1))
    from jax import lax

    n = lax.population_count(x).astype(jnp.int64)
    return Val(n, a.valid, call.type)


@register("typeof")
def _typeof(ctx, call, v):
    d = StringDictionary([v.type.name])
    return Val(np.int32(0), None, call.type, d)


@register("version")
def _version(ctx, call):
    d = StringDictionary(["trino-tpu 0.4"])
    return Val(np.int32(0), None, call.type, d)


# array/json/map function handlers register themselves on import
from trino_tpu.expr import arrays as _arrays  # noqa: E402,F401
from trino_tpu.expr import maps as _maps  # noqa: E402,F401


def _render_tz(millis: int, offset_minutes: int) -> str:
    """Render a packed timestamptz as local-time text with its offset."""
    import datetime

    dt = datetime.datetime(1970, 1, 1) + datetime.timedelta(
        milliseconds=millis + offset_minutes * 60_000
    )
    sign = "+" if offset_minutes >= 0 else "-"
    om = abs(offset_minutes)
    return f"{dt.isoformat(sep=' ')} {sign}{om // 60:02d}:{om % 60:02d}"


@register("concat_ws")
def _concat_ws_eager(ctx, call, sep, *parts):
    """concat_ws(sep, v1, ..., vn) for MANY string columns — eager host
    render per row (EAGER_FUNCS), because the compiled concat chain would
    materialize cross-product dictionaries.  Reference:
    operator/scalar/ConcatWsFunction.java (NULL values skipped, NULL
    separator -> NULL).  The <=2-column case is rewritten by the analyzer
    into compiled IF/concat forms and never reaches here."""
    import jax

    cap = ctx.capacity
    if any(
        isinstance(jnp.asarray(a.data), jax.core.Tracer)
        for a in (sep,) + tuple(parts)
    ):
        raise NotImplementedError(
            "concat_ws is not supported in this expression context"
        )

    def _strings_of(v):
        if v.is_literal_null:
            return [None] * cap
        d = np.asarray(jnp.broadcast_to(jnp.asarray(v.data), (cap,)))  # lint: allow(host-sync-asarray)
        va = (
            np.asarray(jnp.broadcast_to(jnp.asarray(v.valid), (cap,)))  # lint: allow(host-sync-asarray)
            if v.valid is not None
            else np.ones(cap, dtype=bool)
        )
        vals = v.dictionary.values if v.dictionary is not None else None
        out = []
        for i in range(cap):
            if not va[i]:
                out.append(None)
            elif vals is not None:
                c = int(d[i])
                out.append(vals[c] if 0 <= c < len(vals) else "")
            else:
                out.append(str(d[i]))
        return out

    sep_s = _strings_of(sep)
    part_s = [_strings_of(p) for p in parts]
    outs, valid = [], np.ones(cap, dtype=bool)
    for i in range(cap):
        if sep_s[i] is None:
            valid[i] = False
            outs.append("")
            continue
        outs.append(sep_s[i].join(p[i] for p in part_s if p[i] is not None))
    from trino_tpu.columnar import StringDictionary

    nd = StringDictionary.from_unsorted(outs)
    codes = jnp.asarray(np.asarray(nd.encode(outs), np.int32))
    return Val(codes, None if valid.all() else jnp.asarray(valid), call.type, nd)


@register("format")
def _format(ctx, call, fmt, *args):
    """format(fmt, args...) — reference: operator/scalar/FormatFunction.java
    (Java format-directive subset: %s %d %x %X %o %f %e %g with -,0 flags,
    width, precision).  Eager host render per row (EAGER_FUNCS): projections
    containing it run unjitted."""
    import datetime
    import re

    import jax

    f = _literal_str(fmt, "format")
    cap = ctx.capacity
    if any(
        isinstance(jnp.asarray(a.data), jax.core.Tracer) or a.lengths is not None
        for a in args
    ):
        raise NotImplementedError(
            "format is not supported in this expression context"
        )
    if re.search(r"%\d+\$", f):
        raise NotImplementedError("format: %n$ argument indexes")

    # translate the Java-style directives into one Python .format template
    pieces, specs = [], []
    last = 0
    for m in re.finditer(r"%([-+0, #]*)(\d*)(?:\.(\d+))?([a-zA-Z%])", f):
        pieces.append(f[last : m.start()].replace("{", "{{").replace("}", "}}"))
        last = m.end()
        flags, width, prec, conv = m.groups()
        if conv == "%":
            pieces.append("%")
            continue
        if conv not in "sdxXofeEgG":
            raise NotImplementedError(f"format: unsupported directive %{conv}")
        spec = ""
        if "-" in flags:
            spec += "<"
        elif conv == "s" and width:
            spec += ">"  # Java right-aligns %Ns; Python left-aligns strings
        if "+" in flags:
            spec += "+"
        elif " " in flags:
            spec += " "
        if "#" in flags:
            spec += "#"
        if "0" in flags and "-" not in flags:
            spec += "0"
        spec += width
        if "," in flags and conv in "dfeEgG":
            spec += ","
        if prec:
            spec += "." + prec
        spec += {"s": "s", "d": "d", "x": "x", "X": "X", "o": "o"}.get(
            conv, conv
        )
        pieces.append("{%d:%s}" % (len(specs), spec))
        specs.append(conv)
    pieces.append(f[last:].replace("{", "{{").replace("}", "}}"))
    template = "".join(pieces)
    if len(specs) != len(args):
        raise NotImplementedError(
            f"format: {len(specs)} directives but {len(args)} arguments"
        )

    # per-row python values + per-arg validity (a null arg renders as
    # 'null' under %s, like the reference's Java formatter; numeric
    # directives null the row)
    avalids = []
    cols = []
    for a in args:
        if a.is_literal_null:
            avalids.append(np.zeros(cap, dtype=bool))
            cols.append([None] * cap)
            continue
        d = np.asarray(jnp.broadcast_to(jnp.asarray(a.data), (cap,)))  # lint: allow(host-sync-asarray)
        avalids.append(
            np.asarray(jnp.broadcast_to(jnp.asarray(a.valid), (cap,)))  # lint: allow(host-sync-asarray)
            if a.valid is not None
            else np.ones(cap, dtype=bool)
        )
        t = a.type
        if a.dictionary is not None:
            vals = a.dictionary.values
            cols.append(
                [vals[int(c)] if 0 <= int(c) < len(vals) else "" for c in d]
            )
        elif isinstance(t, T.DecimalType) and t.scale > 0:
            q = 10 ** t.scale
            cols.append(
                [
                    f"{'-' if int(c) < 0 else ''}"
                    f"{abs(int(c)) // q}.{abs(int(c)) % q:0{t.scale}d}"
                    for c in d
                ]
            )
        elif t.name == "timestamp with time zone":
            cols.append(
                [
                    _render_tz(int(T.unpack_tz_millis(np.int64(c))),
                               int(T.unpack_tz_offset(np.int64(c))))
                    for c in d
                ]
            )
        elif t.name == "date":
            epoch = datetime.date(1970, 1, 1)
            cols.append(
                [
                    (epoch + datetime.timedelta(days=int(c))).isoformat()
                    for c in d
                ]
            )
        elif t.name == "timestamp":
            ep = datetime.datetime(1970, 1, 1)
            cols.append(
                [
                    (ep + datetime.timedelta(microseconds=int(c))).isoformat(
                        sep=" "
                    )
                    for c in d
                ]
            )
        elif t.name == "boolean":
            cols.append([("true" if c else "false") for c in d])
        elif d.dtype.kind == "f":
            cols.append([float(c) for c in d])
        else:
            cols.append([int(c) for c in d])

    outs = []
    valid = np.ones(cap, dtype=bool)
    for i in range(cap):
        row = []
        for j, conv in enumerate(specs):
            if not avalids[j][i]:
                if conv == "s":
                    row.append("null")
                    continue
                valid[i] = False
                break
            v = cols[j][i]
            if conv in "dxXo" and not isinstance(v, int):
                v = int(float(v))
            elif conv in "feEgG" and not isinstance(v, float):
                v = float(v)
            elif conv == "s":
                v = str(v)
            row.append(v)
        outs.append(template.format(*row) if valid[i] else "")
    from trino_tpu.columnar import StringDictionary

    nd = StringDictionary.from_unsorted(outs)
    codes = jnp.asarray(np.asarray(nd.encode(outs), np.int32))
    return Val(codes, None if valid.all() else jnp.asarray(valid), call.type, nd)
