"""Constant folding over the typed IR (reference role:
iterative/rule/SimplifyExpressions + the interpreter for constant subtrees).
"""

from __future__ import annotations

from decimal import Decimal

from trino_tpu import types as T
from trino_tpu.expr.ir import Call, Expr, Form, Literal, SpecialForm


def _lit_value(e: Expr):
    if isinstance(e, Literal):
        return e.value
    raise ValueError("not a literal")


def try_fold(e: Expr, _memo: dict = None) -> Expr:
    """Best-effort: fold arithmetic/comparison/cast over literal children.

    Memoized by sub-Expr identity: rewrites (concat_ws, CASE chains) emit
    DAGs where the same object is referenced many times — a plain recursion
    would be exponential in the sharing depth."""
    if _memo is None:
        _memo = {}
    hit = _memo.get(id(e))
    if hit is not None:
        return hit
    out = _try_fold_uncached(e, _memo)
    _memo[id(e)] = out
    return out


def _try_fold_uncached(e: Expr, _memo: dict) -> Expr:
    kids = [try_fold(k, _memo) for k in e.children()]
    if kids:
        e = e.with_children(kids)
    if isinstance(e, Literal):
        return e
    # Short-circuit form folding BEFORE the all-literal gate: IF/AND/OR can
    # collapse on a literal condition alone, which is what keeps rewrites
    # like concat_ws's threaded accumulator from reaching the compiler as a
    # dictionary-doubling IF chain when the inputs are constants.
    if isinstance(e, SpecialForm):
        folded = _fold_form(e, kids)
        if folded is not None:
            return folded
    if not all(isinstance(k, Literal) for k in kids):
        return e
    try:
        if isinstance(e, Call):
            vals = [k.value for k in kids]
            if any(v is None for v in vals) and e.name != "format":
                # format renders null arguments as 'null' text under %s
                # (Java formatter semantics), so it must not null-fold
                return Literal(None, e.type)
            if e.name in ("concat", "$concat") and all(
                isinstance(v, str) for v in vals
            ):
                return Literal("".join(vals), e.type)
            if e.name == "$neg":
                return _from_py(-vals[0], e.type, wrap_ints=True)
            if e.name in ("$add", "$sub", "$mul", "$div"):
                from decimal import Context, localcontext

                a, b = _to_py(kids[0]), _to_py(kids[1])

                def _int_div():
                    # exact truncate-toward-zero, matching the device
                    # integer division (float a/b corrupts above 2**53)
                    if not b:
                        return None
                    q = abs(a) // abs(b)
                    return q if (a >= 0) == (b >= 0) else -q

                # decimal(38) products/sums need up to ~77 digits before
                # the result rescale: the DEFAULT 28-digit context would
                # silently round what the device's Int128 limbs carry
                # exactly (caught by tests/test_constant_fold_diff.py)
                with localcontext(Context(prec=80)):
                    out = {
                        "$add": lambda: a + b,
                        "$sub": lambda: a - b,
                        "$mul": lambda: a * b,
                        "$div": lambda: (
                            _int_div()
                            if T.is_integer_kind(e.type)
                            else (a / b if b else None)
                        ),
                    }[e.name]()
                # integer arithmetic wraps (matching the device column
                # path's two's-complement overflow); only CASTS null
                return _from_py(out, e.type, wrap_ints=True)
            if e.name in ("$eq", "$ne", "$lt", "$le", "$gt", "$ge"):
                a, b = _to_py(kids[0]), _to_py(kids[1])
                out = {
                    "$eq": a == b, "$ne": a != b, "$lt": a < b,
                    "$le": a <= b, "$gt": a > b, "$ge": a >= b,
                }[e.name]
                return Literal(out, T.BOOLEAN)
            if e.name == "date_add_days":
                return Literal(int(vals[0]) + int(vals[1]), e.type)
            if e.name == "date_add_months":
                import datetime

                d = datetime.date(1970, 1, 1) + datetime.timedelta(days=int(vals[0]))
                months = d.year * 12 + d.month - 1 + int(vals[1])
                y, m = divmod(months, 12)
                m += 1
                import calendar

                day = min(d.day, calendar.monthrange(y, m)[1])
                nd = datetime.date(y, m, day)
                return Literal((nd - datetime.date(1970, 1, 1)).days, e.type)
        if isinstance(e, SpecialForm) and e.form == Form.CAST:
            v = kids[0].value
            if v is None:
                return Literal(None, e.type)
            frm = kids[0].type
            if frm is T.DATE and e.type is T.TIMESTAMP:
                return Literal(int(v) * 86_400_000_000, e.type)
            if frm is T.TIMESTAMP and e.type is T.DATE:
                return Literal(int(v) // 86_400_000_000, e.type)
            if frm is T.TIMESTAMP_TZ or e.type is T.TIMESTAMP_TZ:
                # packed-tz bits are not interchangeable with plain temporal
                # encodings; fold the conversions explicitly
                if frm is T.TIMESTAMP_TZ and e.type is T.TIMESTAMP:
                    local = T.unpack_tz_millis(int(v)) + T.unpack_tz_offset(
                        int(v)
                    ) * 60_000
                    return Literal(local * 1000, e.type)
                if frm is T.TIMESTAMP_TZ and e.type is T.DATE:
                    local = T.unpack_tz_millis(int(v)) + T.unpack_tz_offset(
                        int(v)
                    ) * 60_000
                    return Literal(local // 86_400_000, e.type)
                if frm is T.TIMESTAMP and e.type is T.TIMESTAMP_TZ:
                    return Literal(T.pack_tz(int(v) // 1000, 0), e.type)
                if frm is T.DATE and e.type is T.TIMESTAMP_TZ:
                    return Literal(T.pack_tz(int(v) * 86_400_000, 0), e.type)
                return e
            return _from_py(_to_py(kids[0]), e.type)
    except (ValueError, TypeError, ArithmeticError):
        return e
    return e


def _fold_form(e: SpecialForm, kids: list):
    """Kleene/short-circuit folding over partially-literal form args.
    Returns a replacement Expr or None (no simplification)."""
    f = e.form
    if f == Form.IS_NULL and isinstance(kids[0], Literal):
        return Literal(kids[0].value is None, T.BOOLEAN)
    if f == Form.NOT and isinstance(kids[0], Literal):
        v = kids[0].value
        return Literal(None if v is None else (not bool(v)), T.BOOLEAN)
    if f == Form.IF and isinstance(kids[0], Literal):
        cond = kids[0].value
        if cond:
            return kids[1]
        return kids[2] if len(kids) > 2 else Literal(None, e.type)
    if f in (Form.AND, Form.OR):
        dominant = False if f == Form.AND else True
        keep, saw_null = [], False
        for k in kids:
            if isinstance(k, Literal):
                if k.value is None:
                    saw_null = True
                elif bool(k.value) == dominant:
                    return Literal(dominant, T.BOOLEAN)
                # neutral literal: drop
            else:
                keep.append(k)
        if not keep:
            return Literal(None if saw_null else (not dominant), T.BOOLEAN)
        if saw_null:
            return None  # NULL arm must survive for kleene eval
        if len(keep) == 1:
            return keep[0]
        if len(keep) < len(kids):
            return SpecialForm(f, keep, T.BOOLEAN)
        return None
    if f == Form.COALESCE:
        out = []
        for k in kids:
            if isinstance(k, Literal) and k.value is None:
                continue
            out.append(k)
            if isinstance(k, Literal):
                break
        if not out:
            return Literal(None, e.type)
        if len(out) == 1 and out[0].type == e.type:
            return out[0]
        if len(out) < len(kids):
            return SpecialForm(Form.COALESCE, out, e.type)
        return None
    return None


def _to_py(lit: Literal):
    if isinstance(lit.type, T.DecimalType) and not isinstance(lit.value, Decimal):
        return Decimal(str(lit.value))
    return lit.value


def _from_py(v, t: T.Type, wrap_ints: bool = False) -> Literal:
    if v is None:
        return Literal(None, t)
    if isinstance(t, T.DecimalType):
        from decimal import ROUND_HALF_UP, Context, localcontext

        with localcontext(Context(prec=80)):
            # quantize to the DECLARED scale, half away from zero: the
            # device rescales at every op (_rescale_decimal), so a folded
            # literal carrying extra fractional digits would diverge one
            # unit on every downstream round
            d = Decimal(str(v)).quantize(
                Decimal(1).scaleb(-t.scale), rounding=ROUND_HALF_UP
            )
            if d == 0:
                d = abs(d)  # no -0: integer device units carry no sign bit
            if not wrap_ints:
                # CAST path: NULL on overflow of the declared precision,
                # matching compile_cast (arithmetic folds keep the exact
                # value; the numeric-safety verifier owns flagging device
                # wrap there)
                scaled = abs(int(d.scaleb(t.scale)))
                if scaled >= 10**t.precision:
                    return Literal(None, t)
        return Literal(d, t)
    if T.is_integer_kind(t):
        import numpy as np

        if isinstance(v, (float, Decimal)):
            # float/decimal -> integer rounds HALF AWAY FROM ZERO, matching
            # the device cast kernels (sign * floor(|x| + 0.5) and the
            # symmetric _rescale_decimal); plain int() truncation would
            # diverge on every x.5 and every x.9
            from decimal import ROUND_HALF_UP

            try:
                v = int(
                    Decimal(str(v)).quantize(Decimal(1), rounding=ROUND_HALF_UP)
                )
            except ArithmeticError:
                return Literal(None, t)  # nan/inf: null, like the kernel
        iv = int(v)
        info = np.iinfo(t.np_dtype)
        if not int(info.min) <= iv <= int(info.max):
            if wrap_ints:
                # arithmetic overflow wraps two's-complement, exactly like
                # the unfolded device column path
                m = 1 << info.bits
                iv = ((iv + (m >> 1)) % m) - (m >> 1)
            else:
                # casts NULL on overflow, matching compile_cast (and
                # np.int64(huge) would crash the compiler otherwise)
                return Literal(None, t)
        return Literal(iv, t)
    if t.name in ("double", "real"):
        return Literal(float(v), t)
    if t is T.DATE and isinstance(v, str):
        import datetime

        y, m, d = (int(x) for x in v.strip().split("-"))
        return Literal(
            (datetime.date(y, m, d) - datetime.date(1970, 1, 1)).days, t
        )
    if t is T.DATE and isinstance(v, int):
        return Literal(v, t)
    if t is T.TIME and isinstance(v, str):
        return Literal(T.parse_time_micros(v), t)
    if isinstance(v, str) and t.np_dtype.kind in "iu" and not T.is_string_kind(t):
        # no host parse rule for this target: leave the cast unfolded
        raise ValueError(f"unfoldable cast to {t.name}")
    return Literal(v, t)
