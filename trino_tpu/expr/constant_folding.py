"""Constant folding over the typed IR (reference role:
iterative/rule/SimplifyExpressions + the interpreter for constant subtrees).
"""

from __future__ import annotations

from decimal import Decimal

from trino_tpu import types as T
from trino_tpu.expr.ir import Call, Expr, Form, Literal, SpecialForm


def _lit_value(e: Expr):
    if isinstance(e, Literal):
        return e.value
    raise ValueError("not a literal")


def try_fold(e: Expr) -> Expr:
    """Best-effort: fold arithmetic/comparison/cast over literal children."""
    kids = [try_fold(k) for k in e.children()]
    if kids:
        e = e.with_children(kids)
    if isinstance(e, Literal):
        return e
    if not all(isinstance(k, Literal) for k in kids):
        return e
    try:
        if isinstance(e, Call):
            vals = [k.value for k in kids]
            if any(v is None for v in vals) and e.name != "format":
                # format renders null arguments as 'null' text under %s
                # (Java formatter semantics), so it must not null-fold
                return Literal(None, e.type)
            if e.name == "$neg":
                return Literal(-vals[0], e.type)
            if e.name in ("$add", "$sub", "$mul", "$div"):
                a, b = _to_py(kids[0]), _to_py(kids[1])
                out = {
                    "$add": lambda: a + b,
                    "$sub": lambda: a - b,
                    "$mul": lambda: a * b,
                    "$div": lambda: a / b if b else None,
                }[e.name]()
                return _from_py(out, e.type)
            if e.name in ("$eq", "$ne", "$lt", "$le", "$gt", "$ge"):
                a, b = _to_py(kids[0]), _to_py(kids[1])
                out = {
                    "$eq": a == b, "$ne": a != b, "$lt": a < b,
                    "$le": a <= b, "$gt": a > b, "$ge": a >= b,
                }[e.name]
                return Literal(out, T.BOOLEAN)
            if e.name == "date_add_days":
                return Literal(int(vals[0]) + int(vals[1]), e.type)
            if e.name == "date_add_months":
                import datetime

                d = datetime.date(1970, 1, 1) + datetime.timedelta(days=int(vals[0]))
                months = d.year * 12 + d.month - 1 + int(vals[1])
                y, m = divmod(months, 12)
                m += 1
                import calendar

                day = min(d.day, calendar.monthrange(y, m)[1])
                nd = datetime.date(y, m, day)
                return Literal((nd - datetime.date(1970, 1, 1)).days, e.type)
        if isinstance(e, SpecialForm) and e.form == Form.CAST:
            v = kids[0].value
            if v is None:
                return Literal(None, e.type)
            frm = kids[0].type
            if frm is T.DATE and e.type is T.TIMESTAMP:
                return Literal(int(v) * 86_400_000_000, e.type)
            if frm is T.TIMESTAMP and e.type is T.DATE:
                return Literal(int(v) // 86_400_000_000, e.type)
            if frm is T.TIMESTAMP_TZ or e.type is T.TIMESTAMP_TZ:
                # packed-tz bits are not interchangeable with plain temporal
                # encodings; fold the conversions explicitly
                if frm is T.TIMESTAMP_TZ and e.type is T.TIMESTAMP:
                    return Literal(T.unpack_tz_millis(int(v)) * 1000, e.type)
                if frm is T.TIMESTAMP_TZ and e.type is T.DATE:
                    local = T.unpack_tz_millis(int(v)) + T.unpack_tz_offset(
                        int(v)
                    ) * 60_000
                    return Literal(local // 86_400_000, e.type)
                if frm is T.TIMESTAMP and e.type is T.TIMESTAMP_TZ:
                    return Literal(T.pack_tz(int(v) // 1000, 0), e.type)
                if frm is T.DATE and e.type is T.TIMESTAMP_TZ:
                    return Literal(T.pack_tz(int(v) * 86_400_000, 0), e.type)
                return e
            return _from_py(_to_py(kids[0]), e.type)
    except (ValueError, TypeError, ArithmeticError):
        return e
    return e


def _to_py(lit: Literal):
    if isinstance(lit.type, T.DecimalType) and not isinstance(lit.value, Decimal):
        return Decimal(str(lit.value))
    return lit.value


def _from_py(v, t: T.Type) -> Literal:
    if v is None:
        return Literal(None, t)
    if isinstance(t, T.DecimalType):
        return Literal(Decimal(str(v)), t)
    if T.is_integer_kind(t):
        return Literal(int(v), t)
    if t.name in ("double", "real"):
        return Literal(float(v), t)
    if t is T.DATE and isinstance(v, str):
        import datetime

        y, m, d = (int(x) for x in v.strip().split("-"))
        return Literal(
            (datetime.date(y, m, d) - datetime.date(1970, 1, 1)).days, t
        )
    if t is T.DATE and isinstance(v, int):
        return Literal(v, t)
    return Literal(v, t)
