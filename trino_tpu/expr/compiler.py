"""Trace-time expression compiler: IR -> jnp ops over a Batch.

Reference role: sql/gen/PageFunctionCompiler.java:166,369 (compileProjection /
compileFilter) and ExpressionCompiler.java:57.  Where the reference emits JVM
bytecode that loops over positions, this compiler runs *inside the jit trace*
of a fragment: every expression becomes a vectorized jnp computation over whole
columns, XLA fuses the lot, and dictionary-dependent parts (string predicates,
string projections) are resolved to constant lookup tables at trace time.

Null semantics follow SQL three-valued logic: functions are null-in/null-out
unless registered otherwise; AND/OR are Kleene; filters keep rows where the
predicate is TRUE (not null).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.columnar import Batch, Column, StringDictionary
from trino_tpu.expr import ir
from trino_tpu.expr.ir import Call, Expr, Form, InputRef, Literal, SpecialForm


@dataclass
class Val:
    """A value during compilation: array or scalar data + validity.

    valid is None (no nulls), a bool array, or the python literal False
    (definitely-null, for NULL literals).
    """

    data: object
    valid: object
    type: T.Type
    dictionary: Optional[StringDictionary] = None
    #: array values: data is [capacity, K], lengths int32 [capacity]
    lengths: object = None

    @property
    def is_literal_null(self) -> bool:
        return self.valid is False


def _and_valid(a, b):
    if a is False or b is False:
        return False
    if a is None:
        return b
    if b is None:
        return a
    return jnp.logical_and(a, b)


def _valid_arr(v, shape):
    if isinstance(shape, int):
        shape = (shape,)
    if v is None:
        return jnp.ones(shape, dtype=bool)
    if v is False:
        return jnp.zeros(shape, dtype=bool)
    return jnp.broadcast_to(v, shape)


class ExprCompiler:
    """Compiles expressions against a concrete input Batch (at trace time)."""

    def __init__(self, batch: Batch):
        self.batch = batch
        self.capacity = batch.capacity
        # DAG memo: rewrites (e.g. concat_ws's threaded accumulator) reference
        # the same sub-Expr OBJECT many times; without this, trace cost is
        # exponential in the sharing depth.  Keyed on the lambda context too,
        # because the same Expr compiles differently inside a lambda body.
        self._memo: dict = {}

    # -- public entry points -------------------------------------------------

    def bshape(self) -> tuple:
        """Broadcast shape for boolean/branch forms: [capacity] normally,
        the [capacity, K] element matrix inside array-lambda bodies."""
        s = getattr(self, "_lambda_shape", None)
        return s if s is not None else (self.capacity,)

    def value(self, expr: Expr) -> Val:
        from trino_tpu.expr.ir import LambdaParam

        if isinstance(expr, LambdaParam):
            env = getattr(self, "_lambda_env", None)
            if not env or expr.name not in env:
                raise NotImplementedError(
                    f"unbound lambda parameter {expr.name}"
                )
            return env[expr.name]
        if isinstance(expr, InputRef):
            c = self.batch.columns[expr.channel]
            if (
                getattr(self, "_lambda_matrix", False)
                and c.lengths is None
                and jnp.ndim(c.data) == 1
            ):
                # captured column inside an array-lambda body: add the
                # trailing element axis so it broadcasts against the
                # [capacity, K] element matrix
                valid = None if c.valid is None else c.valid[:, None]
                return Val(c.data[:, None], valid, expr.type, c.dictionary)
            return Val(c.data, c.valid, expr.type, c.dictionary, c.lengths)
        if isinstance(expr, Literal):
            return self._literal(expr)
        if isinstance(expr, (SpecialForm, Call)):
            env = getattr(self, "_lambda_env", None)
            key = (
                id(expr),
                id(env),
                getattr(self, "_lambda_shape", None),
            )
            hit = self._memo.get(key)
            # the entry pins BOTH id()-keyed objects (expr and lambda env):
            # id() keys are only valid while the object is alive, and this
            # memo outlives one compile call — a recycled address must miss,
            # not return a stale Val from a freed scope
            if hit is not None and hit[0] is expr and hit[1] is env:
                return hit[2]
            if isinstance(expr, SpecialForm):
                v = self._form(expr)
            else:
                from trino_tpu.expr.functions import dispatch

                v = dispatch(self, expr)
            self._memo[key] = (expr, env, v)
            return v
        raise NotImplementedError(f"cannot compile {expr!r}")

    def column(self, expr: Expr) -> Column:
        """Evaluate to a full-capacity Column."""
        v = self.value(expr)
        if v.lengths is not None:
            k = v.data.shape[-1]
            data = jnp.broadcast_to(
                jnp.asarray(v.data, dtype=v.type.np_dtype), (self.capacity, k)
            )
            lengths = jnp.broadcast_to(
                jnp.asarray(v.lengths, jnp.int32), (self.capacity,)
            )
            valid = None
            if v.valid is False:
                valid = jnp.zeros(self.capacity, dtype=bool)
            elif v.valid is not None:
                valid = jnp.broadcast_to(v.valid, (self.capacity,))
            return Column(data, v.type, valid, v.dictionary, lengths)
        if isinstance(v.type, T.DecimalType) and v.type.is_long:
            # two-limb planes: [capacity, 2]
            d = jnp.asarray(v.data, jnp.int64)
            if jnp.ndim(d) == 0:  # null literal fill
                d = jnp.zeros((1, 2), jnp.int64)
            elif jnp.ndim(d) == 1:
                # 1-D data under a long type: short-VALUED rows (e.g. a
                # window sum computed in i64) — widen each row to planes
                from trino_tpu.types.int128 import widen64

                h, l = widen64(d)
                d = jnp.stack([h, l], axis=-1)
            data = jnp.broadcast_to(d, (self.capacity, 2))
            valid = None
            if v.valid is False:
                valid = jnp.zeros(self.capacity, dtype=bool)
            elif v.valid is not None:
                valid = jnp.broadcast_to(v.valid, (self.capacity,))
            return Column(data, v.type, valid)
        data = jnp.broadcast_to(
            jnp.asarray(v.data, dtype=v.type.np_dtype), (self.capacity,)
        )
        valid = None
        if v.valid is False:
            valid = jnp.zeros(self.capacity, dtype=bool)
        elif v.valid is not None:
            valid = jnp.broadcast_to(v.valid, (self.capacity,))
        return Column(data, v.type, valid, v.dictionary)

    def filter_mask(self, expr: Expr):
        """bool[capacity]: predicate is TRUE (nulls drop, per SQL WHERE)."""
        v = self.value(expr)
        data = jnp.broadcast_to(jnp.asarray(v.data, dtype=bool), (self.capacity,))
        if v.valid is False:
            return jnp.zeros(self.capacity, dtype=bool)
        if v.valid is None:
            return data
        return jnp.logical_and(data, v.valid)

    # -- literals ------------------------------------------------------------

    def _literal(self, lit: Literal) -> Val:
        if lit.value is None:
            return Val(lit.type.null_device_value(), False, lit.type)
        if T.is_string_kind(lit.type) and isinstance(lit.value, str):
            # Bare string literal with no column context: single-value dict.
            d = StringDictionary([lit.value])
            return Val(np.int32(0), None, lit.type, d)
        if isinstance(lit.type, T.DecimalType):
            from decimal import Decimal

            from decimal import Context

            ctx = Context(prec=60)  # default 28-digit context rounds 29+
            scaled = int(
                ctx.multiply(
                    Decimal(str(lit.value)), Decimal(lit.type.scale_factor)
                ).to_integral_value(context=ctx)
            )
            if lit.type.is_long:
                from trino_tpu.types.int128 import split_py

                return Val(
                    np.asarray([split_py(scaled)], np.int64),  # [1, 2] planes
                    None,
                    lit.type,
                )
            return Val(np.int64(scaled), None, lit.type)
        return Val(lit.type.np_dtype.type(lit.value), None, lit.type)

    # -- special forms -------------------------------------------------------

    def _form(self, f: SpecialForm) -> Val:
        h = getattr(self, "_form_" + f.form.value)
        return h(f)

    def _form_and(self, f: SpecialForm) -> Val:
        vals = [self.value(a) for a in f.args]
        # Kleene AND over n terms: FALSE dominates, else NULL if any null.
        shp = self.bshape()
        value = jnp.ones(shp, dtype=bool)
        any_false = jnp.zeros(shp, dtype=bool)
        all_valid = jnp.ones(shp, dtype=bool)
        for v in vals:
            va = _valid_arr(v.valid, shp)
            d = jnp.broadcast_to(jnp.asarray(v.data, dtype=bool), shp)
            value = jnp.logical_and(value, jnp.where(va, d, True))
            any_false = jnp.logical_or(any_false, jnp.logical_and(va, ~d))
            all_valid = jnp.logical_and(all_valid, va)
        valid = jnp.logical_or(all_valid, any_false)
        return Val(value, valid, T.BOOLEAN)

    def _form_or(self, f: SpecialForm) -> Val:
        shp = self.bshape()
        vals = [self.value(a) for a in f.args]
        value = jnp.zeros(shp, dtype=bool)
        any_true = jnp.zeros(shp, dtype=bool)
        all_valid = jnp.ones(shp, dtype=bool)
        for v in vals:
            va = _valid_arr(v.valid, shp)
            d = jnp.broadcast_to(jnp.asarray(v.data, dtype=bool), shp)
            value = jnp.logical_or(value, jnp.where(va, d, False))
            any_true = jnp.logical_or(any_true, jnp.logical_and(va, d))
            all_valid = jnp.logical_and(all_valid, va)
        valid = jnp.logical_or(all_valid, any_true)
        return Val(value, valid, T.BOOLEAN)

    def _form_not(self, f: SpecialForm) -> Val:
        v = self.value(f.args[0])
        return Val(jnp.logical_not(jnp.asarray(v.data, dtype=bool)), v.valid, T.BOOLEAN)

    def _form_is_null(self, f: SpecialForm) -> Val:
        v = self.value(f.args[0])
        # Array/map values carry [capacity, K] data but PER-ROW validity
        # (lengths is set), and long decimals carry [capacity, 2] limb
        # planes — IS NULL is a row predicate, so keep the row shape.  Only
        # a lambda matrix context (ndim>1, lengths None, not a long
        # decimal) has genuinely 2-D validity.
        if (
            jnp.ndim(v.data) > 1
            and v.lengths is None
            and not (isinstance(v.type, T.DecimalType) and v.type.is_long)
        ):
            shp = jnp.shape(v.data)
        else:
            shp = self.bshape()
        return Val(~_valid_arr(v.valid, shp), None, T.BOOLEAN)

    def _form_if(self, f: SpecialForm) -> Val:
        cond, then, els = f.args
        return self._case_fold([(cond, then)], els, f.type)

    def _form_case(self, f: SpecialForm) -> Val:
        args = list(f.args)
        default = args.pop() if len(args) % 2 == 1 else Literal(None, f.type)
        pairs = [(args[i], args[i + 1]) for i in range(0, len(args), 2)]
        return self._case_fold(pairs, default, f.type)

    def _case_fold(self, pairs, default: Expr, out_type: T.Type) -> Val:
        shp = self.bshape()
        branches = [self.value(v) for _, v in pairs] + [self.value(default)]
        if isinstance(out_type, T.DecimalType) and out_type.is_long:
            return self._case_fold_long(pairs, branches, out_type, shp)
        out_dict = self._merge_branch_dicts(branches, out_type)
        acc = branches[-1]
        acc_data = jnp.broadcast_to(
            jnp.asarray(self._recode(acc, out_dict), dtype=out_type.np_dtype), shp
        )
        acc_valid = _valid_arr(acc.valid, shp)
        for (cond_e, _), v in zip(reversed(pairs), reversed(branches[:-1])):
            c = self.value(cond_e)
            ctrue = jnp.logical_and(
                jnp.broadcast_to(jnp.asarray(c.data, dtype=bool), shp),
                _valid_arr(c.valid, shp),
            )
            vdata = jnp.broadcast_to(
                jnp.asarray(self._recode(v, out_dict), dtype=out_type.np_dtype), shp
            )
            acc_data = jnp.where(ctrue, vdata, acc_data)
            acc_valid = jnp.where(ctrue, _valid_arr(v.valid, shp), acc_valid)
        return Val(acc_data, acc_valid, out_type, out_dict)

    def _case_fold_long(self, pairs, branches, out_type: T.Type, shp) -> Val:
        """CASE/IF over long-decimal branches: select on limb planes."""
        from trino_tpu.expr.functions import _to_planes

        def planes(v):
            h, l = _to_planes(v, out_type.scale)
            return (
                jnp.broadcast_to(jnp.asarray(h, jnp.int64), shp),
                jnp.broadcast_to(jnp.asarray(l, jnp.int64), shp),
            )

        acc = branches[-1]
        acc_h, acc_l = planes(acc)
        acc_valid = _valid_arr(acc.valid, shp)
        for (cond_e, _), v in zip(reversed(pairs), reversed(branches[:-1])):
            c = self.value(cond_e)
            ctrue = jnp.logical_and(
                jnp.broadcast_to(jnp.asarray(c.data, dtype=bool), shp),
                _valid_arr(c.valid, shp),
            )
            vh, vl = planes(v)
            acc_h = jnp.where(ctrue, vh, acc_h)
            acc_l = jnp.where(ctrue, vl, acc_l)
            acc_valid = jnp.where(ctrue, _valid_arr(v.valid, shp), acc_valid)
        return Val(
            jnp.stack([acc_h, acc_l], axis=-1), acc_valid, out_type
        )

    def _merge_branch_dicts(self, vals, out_type):
        if not T.is_string_kind(out_type):
            return None
        dicts = [v.dictionary for v in vals if v.dictionary is not None]
        if not dicts:
            return None
        merged = dicts[0]
        for d in dicts[1:]:
            if d is not merged and d != merged:
                if len(merged) + len(d) > (1 << 20):
                    # same materialization bound as concat's cross-product
                    # path: fail fast instead of letting an IF chain double
                    # its dictionary into the gigabytes
                    raise NotImplementedError(
                        "string branch dictionary merge exceeds the "
                        f"materialization bound ({len(merged)}+{len(d)})"
                    )
                merged = StringDictionary.from_unsorted(merged.values + d.values)
        return merged

    def _recode(self, v: Val, out_dict):
        if out_dict is None or v.dictionary is None or v.dictionary == out_dict:
            return v.data
        table = jnp.asarray(
            np.fromiter(
                (out_dict.index[x] for x in v.dictionary.values),
                dtype=np.int32,
                count=len(v.dictionary),
            )
        )
        return jnp.take(table, jnp.asarray(v.data, dtype=jnp.int32), mode="clip")

    def _form_coalesce(self, f: SpecialForm) -> Val:
        shp = self.bshape()
        vals = [self.value(a) for a in f.args]
        if isinstance(f.type, T.DecimalType) and f.type.is_long:
            # limb planes fold like _case_fold_long: a 1-D broadcast over
            # [capacity, 2] data is shape-invalid
            from trino_tpu.expr.functions import _to_planes

            def planes(v):
                h, l = _to_planes(v, f.type.scale)
                return (
                    jnp.broadcast_to(jnp.asarray(h, jnp.int64), shp),
                    jnp.broadcast_to(jnp.asarray(l, jnp.int64), shp),
                )

            acc = vals[-1]
            acc_h, acc_l = planes(acc)
            acc_valid = _valid_arr(acc.valid, shp)
            for v in reversed(vals[:-1]):
                va = _valid_arr(v.valid, shp)
                vh, vl = planes(v)
                acc_h = jnp.where(va, vh, acc_h)
                acc_l = jnp.where(va, vl, acc_l)
                acc_valid = jnp.logical_or(va, acc_valid)
            return Val(
                jnp.stack([acc_h, acc_l], axis=-1), acc_valid, f.type
            )
        out_dict = self._merge_branch_dicts(vals, f.type)
        acc = vals[-1]
        acc_data = jnp.broadcast_to(
            jnp.asarray(self._recode(acc, out_dict), dtype=f.type.np_dtype), shp
        )
        acc_valid = _valid_arr(acc.valid, shp)
        for v in reversed(vals[:-1]):
            va = _valid_arr(v.valid, shp)
            d = jnp.broadcast_to(
                jnp.asarray(self._recode(v, out_dict), dtype=f.type.np_dtype), shp
            )
            acc_data = jnp.where(va, d, acc_data)
            acc_valid = jnp.logical_or(va, acc_valid)
        return Val(acc_data, acc_valid, f.type, out_dict)

    def _form_nullif(self, f: SpecialForm) -> Val:
        a = self.value(f.args[0])
        eq = self.value(ir.comparison("=", f.args[0], f.args[1]))
        shp = self.bshape()
        eq_true = jnp.logical_and(
            jnp.broadcast_to(jnp.asarray(eq.data, dtype=bool), shp),
            _valid_arr(eq.valid, shp),
        )
        valid = jnp.logical_and(_valid_arr(a.valid, shp), ~eq_true)
        return Val(a.data, valid, f.type, a.dictionary)

    def _form_in(self, f: SpecialForm) -> Val:
        value, *items = f.args
        eqs = [ir.comparison("=", value, it) for it in items]
        return self._form_or(SpecialForm(Form.OR, eqs, T.BOOLEAN))

    def _form_between(self, f: SpecialForm) -> Val:
        v, lo, hi = f.args
        return self._form_and(
            SpecialForm(
                Form.AND,
                [ir.comparison(">=", v, lo), ir.comparison("<=", v, hi)],
                T.BOOLEAN,
            )
        )

    def _form_cast(self, f: SpecialForm) -> Val:
        from trino_tpu.expr.functions import compile_cast

        v = self.value(f.args[0])
        return compile_cast(self, v, f.type)

    def _form_try(self, f: SpecialForm) -> Val:
        # Device arithmetic never traps; TRY is the identity with null-on-error
        # semantics folded into the ops themselves (e.g. div-by-zero -> null).
        return self.value(f.args[0])

    # -- arrays --------------------------------------------------------------

    def _form_array(self, f: SpecialForm) -> Val:
        """ARRAY[e1, ...] -> padded rectangular [capacity, K] + lengths.

        Reference: spi/block/ArrayBlock.java holds offsets into a flat
        elements block; the device layout is rectangular so every downstream
        op stays statically shaped.  NULL elements are not representable in
        the rectangular layout (tracked per-array, not per-element)."""
        vals = [self.value(a) for a in f.args]
        et = f.type.element
        if any(v.is_literal_null for v in vals):
            raise NotImplementedError("NULL array elements")
        dictionary = None
        if any(v.dictionary is not None for v in vals):
            from trino_tpu.columnar.dictionary import union_many

            dictionary, tables = union_many([v.dictionary for v in vals])
            vals = [
                v
                if tbl is None
                else Val(
                    jnp.take(
                        jnp.asarray(tbl),
                        jnp.asarray(v.data, jnp.int32),
                        mode="clip",
                    ),
                    v.valid,
                    v.type,
                    dictionary,
                )
                for v, tbl in zip(vals, tables)
            ]
        cap = self.capacity
        cols = [
            jnp.broadcast_to(jnp.asarray(v.data, et.np_dtype), (cap,))
            for v in vals
        ]
        data = jnp.stack(cols, axis=1) if cols else jnp.zeros((cap, 0), et.np_dtype)
        # a NULL item would need element validity; instead the whole array is
        # null when any element is null (strict, documented deviation)
        valid = None
        for v in vals:
            valid = _and_valid(valid, v.valid)
        lengths = jnp.full((cap,), len(vals), jnp.int32)
        return Val(data, valid, f.type, dictionary, lengths)

    def _form_subscript(self, f: SpecialForm) -> Val:
        """array[i], 1-based; out-of-range yields NULL (the reference throws;
        trapping is not expressible in a vectorized XLA program)."""
        base = self.value(f.args[0])
        idx = self.value(f.args[1])
        if isinstance(base.type, T.MapType):
            from trino_tpu.expr.maps import map_element_at

            return map_element_at(self, f, base, idx)
        if base.lengths is None:
            raise NotImplementedError("subscript on non-array value")
        cap = self.capacity
        if base.data.shape[-1] == 0:  # zero-capacity arrays: always NULL
            return Val(jnp.zeros(cap, f.type.np_dtype), False, f.type)
        data2 = jnp.broadcast_to(
            jnp.asarray(base.data), (cap, base.data.shape[-1])
        )
        lens = jnp.broadcast_to(jnp.asarray(base.lengths, jnp.int32), (cap,))
        i = jnp.broadcast_to(jnp.asarray(idx.data, jnp.int64), (cap,))
        in_range = jnp.logical_and(i >= 1, i <= lens.astype(jnp.int64))
        pos = jnp.clip(i - 1, 0, max(data2.shape[1] - 1, 0))
        out = jnp.take_along_axis(data2, pos[:, None], axis=1)[:, 0]
        valid = _and_valid(_and_valid(base.valid, idx.valid), in_range)
        return Val(out, valid, f.type, base.dictionary)
