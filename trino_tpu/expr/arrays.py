"""Array and JSON scalar functions.

Reference roles: core/trino-main/.../operator/scalar/ArrayFunctions +
ArrayContains/ArrayPositionFunction/ArrayDistinctFunction/ArraySortFunction,
scalar/SplitFunction.java, and the json path family (JsonExtract.java,
operator/scalar/json/*).  Arrays are rectangular [capacity, K] device blocks
with per-row lengths (see columnar/column.py); string work follows the
engine's dictionary discipline — computed once per distinct dictionary value
host-side, gathered on device by code.
"""

from __future__ import annotations

import json

import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.columnar import StringDictionary
from trino_tpu.expr.compiler import Val, _and_valid
from trino_tpu.expr.functions import (
    FUNCTIONS,
    _literal_str,
    _require_dict,
    register,
)


def _arr2d(ctx, v: Val):
    """Broadcast an array Val to ([cap, K], lengths[cap])."""
    if v.lengths is None:
        raise NotImplementedError("expected an array value")
    cap = ctx.capacity
    k = v.data.shape[-1]
    data = jnp.broadcast_to(jnp.asarray(v.data), (cap, k))
    lens = jnp.broadcast_to(jnp.asarray(v.lengths, jnp.int32), (cap,))
    return data, lens


def _elem_mask(data, lens):
    """bool [cap, K]: which padded slots hold real elements."""
    k = data.shape[1]
    return jnp.arange(k, dtype=jnp.int32)[None, :] < lens[:, None]


@register("cardinality")
def _cardinality(ctx, call, v):
    if v.lengths is None:
        raise NotImplementedError("cardinality of non-array value")
    cap = ctx.capacity
    lens = jnp.broadcast_to(jnp.asarray(v.lengths, jnp.int64), (cap,))
    return Val(lens, v.valid, call.type)


@register("element_at")
def _element_at(ctx, call, arr, idx):
    """element_at(array, i): 1-based, negative i counts from the end, NULL
    out of range (reference: ElementAtFunction; unlike subscript, which the
    reference makes throw).  Dispatches to the map lookup for map values."""
    if isinstance(arr.type, T.MapType):
        from trino_tpu.expr.maps import map_element_at

        return map_element_at(ctx, call, arr, idx)
    data, lens = _arr2d(ctx, arr)
    k = data.shape[1]
    if k == 0:
        return Val(jnp.zeros(ctx.capacity, call.type.np_dtype), False, call.type)
    i = jnp.broadcast_to(jnp.asarray(idx.data, jnp.int64), (ctx.capacity,))
    ln = lens.astype(jnp.int64)
    eff = jnp.where(i < 0, ln + i + 1, i)  # -1 -> last element
    in_range = jnp.logical_and(eff >= 1, eff <= ln)
    pos = jnp.clip(eff - 1, 0, k - 1)
    out = jnp.take_along_axis(data, pos[:, None], axis=1)[:, 0]
    valid = _and_valid(_and_valid(arr.valid, idx.valid), in_range)
    return Val(out, valid, call.type, arr.dictionary)


@register("contains")
def _contains(ctx, call, arr, needle):
    data, lens = _arr2d(ctx, arr)
    em = _elem_mask(data, lens)
    if arr.dictionary is not None:
        # resolve the needle against the array's dictionary host-side
        s = _literal_str(needle, "contains")
        code = arr.dictionary.index.get(s, -1)
        hit = jnp.logical_and(em, data == code).any(axis=1)
    else:
        nv = jnp.asarray(needle.data)
        hit = jnp.logical_and(em, data == nv[..., None]).any(axis=1)
    valid = _and_valid(arr.valid, needle.valid)
    return Val(hit, valid, call.type)


@register("array_position")
def _array_position(ctx, call, arr, needle):
    data, lens = _arr2d(ctx, arr)
    em = _elem_mask(data, lens)
    if arr.dictionary is not None:
        s = _literal_str(needle, "array_position")
        code = arr.dictionary.index.get(s, -1)
        eq = jnp.logical_and(em, data == code)
    else:
        eq = jnp.logical_and(em, data == jnp.asarray(needle.data)[..., None])
    k = data.shape[1]
    pos = jnp.arange(1, k + 1, dtype=jnp.int64)[None, :]
    first = jnp.min(jnp.where(eq, pos, k + 1), axis=1)
    out = jnp.where(first > k, 0, first)
    valid = _and_valid(arr.valid, needle.valid)
    return Val(out, valid, call.type)


def _masked_reduce(data, lens, fill, red):
    em = _elem_mask(data, lens)
    out = red(jnp.where(em, data, fill), axis=1)
    return out, lens > 0


@register("array_max")
def _array_max(ctx, call, arr):
    data, lens = _arr2d(ctx, arr)
    if arr.dictionary is not None:
        out, nonempty = _masked_reduce(data, lens, -1, jnp.max)
    elif np.issubdtype(np.dtype(data.dtype), np.floating):
        out, nonempty = _masked_reduce(data, lens, -jnp.inf, jnp.max)
    else:
        out, nonempty = _masked_reduce(
            data, lens, jnp.iinfo(data.dtype).min, jnp.max
        )
    valid = _and_valid(arr.valid, nonempty)
    return Val(out, valid, call.type, arr.dictionary)


@register("array_min")
def _array_min(ctx, call, arr):
    data, lens = _arr2d(ctx, arr)
    if arr.dictionary is not None:
        big = len(arr.dictionary.values)
        out, nonempty = _masked_reduce(data, lens, big, jnp.min)
    elif np.issubdtype(np.dtype(data.dtype), np.floating):
        out, nonempty = _masked_reduce(data, lens, jnp.inf, jnp.min)
    else:
        out, nonempty = _masked_reduce(
            data, lens, jnp.iinfo(data.dtype).max, jnp.min
        )
    valid = _and_valid(arr.valid, nonempty)
    return Val(out, valid, call.type, arr.dictionary)


def _sorted_rows(data, lens, descending=False):
    """Per-row sort with padding pushed past the live elements."""
    em = _elem_mask(data, lens)
    if np.issubdtype(np.dtype(data.dtype), np.floating):
        hi = jnp.inf if not descending else -jnp.inf
    else:
        hi = (
            jnp.iinfo(data.dtype).max
            if not descending
            else jnp.iinfo(data.dtype).min
        )
    keyed = jnp.where(em, data, hi)
    s = jnp.sort(keyed, axis=1)
    if descending:
        s = s[:, ::-1]
    return s, em


@register("array_sort")
def _array_sort(ctx, call, arr):
    data, lens = _arr2d(ctx, arr)
    s, _ = _sorted_rows(data, lens)
    return Val(s, arr.valid, call.type, arr.dictionary, lens)


@register("array_distinct")
def _array_distinct(ctx, call, arr):
    """Distinct elements; sorted order (reference keeps first-seen order —
    documented deviation, element sets are equal)."""
    data, lens = _arr2d(ctx, arr)
    s, _ = _sorted_rows(data, lens)
    k = data.shape[1]
    pos_in = jnp.arange(k, dtype=jnp.int32)[None, :]
    live = pos_in < lens[:, None]
    new = jnp.concatenate(
        [jnp.ones((s.shape[0], 1), bool), s[:, 1:] != s[:, :-1]], axis=1
    )
    keep = jnp.logical_and(live, new)
    # stable compact within each row
    target = jnp.cumsum(keep, axis=1) - 1
    out_lens = keep.sum(axis=1).astype(jnp.int32)
    idx = jnp.where(keep, target, k)
    out = jnp.zeros_like(s)
    rows = jnp.arange(s.shape[0])[:, None]
    out = out.at[rows, jnp.clip(idx, 0, k - 1)].set(
        jnp.where(keep, s, 0), mode="drop"
    )
    # the scatter above drops idx==k writes only via clip+mode; rewrite dead
    # slots deterministically to zero
    em_out = jnp.arange(k, dtype=jnp.int32)[None, :] < out_lens[:, None]
    out = jnp.where(em_out, out, 0)
    return Val(out, arr.valid, call.type, arr.dictionary, out_lens)


@register("sequence")
def _sequence(ctx, call, start, stop, step=None):
    """sequence(start, stop[, step]) with literal bounds (the rectangular
    layout needs a static K)."""
    s0 = int(np.asarray(start.data))
    s1 = int(np.asarray(stop.data))
    st = int(np.asarray(step.data)) if step is not None else 1
    if st == 0:
        raise ValueError("sequence step cannot be zero")
    vals = np.arange(s0, s1 + (1 if st > 0 else -1), st, dtype=np.int64)
    k = max(1, len(vals))
    row = np.zeros(k, np.int64)
    row[: len(vals)] = vals
    cap = ctx.capacity
    data = jnp.broadcast_to(jnp.asarray(row), (cap, k))
    lens = jnp.full((cap,), len(vals), jnp.int32)
    return Val(data, _and_valid(start.valid, stop.valid), call.type, None, lens)


@register("repeat")
def _repeat(ctx, call, elem, count):
    n = int(np.asarray(count.data))
    if n < 0:
        n = 0
    k = max(1, n)
    cap = ctx.capacity
    e = jnp.broadcast_to(jnp.asarray(elem.data), (cap,))
    data = jnp.broadcast_to(e[:, None], (cap, k))
    em = jnp.arange(k, dtype=jnp.int32)[None, :] < n
    data = jnp.where(em, data, 0)
    lens = jnp.full((cap,), n, jnp.int32)
    return Val(data, elem.valid, call.type, elem.dictionary, lens)


@register("split")
def _split(ctx, call, value, delim, limit=None):
    """split(string, delimiter[, limit]) -> array(varchar).

    Computed once per dictionary value (SplitFunction.java's row loop becomes
    a dictionary-table build), gathered on device by code."""
    d = _require_dict(value, "split")
    sep = _literal_str(delim, "split")
    lim = int(np.asarray(limit.data)) if limit is not None else None
    pieces_per = [
        (s.split(sep, lim - 1) if lim else s.split(sep)) for s in d.values
    ]
    all_pieces = sorted({p for ps in pieces_per for p in ps})
    nd = StringDictionary(all_pieces)
    ix = nd.index
    k = max(1, max((len(ps) for ps in pieces_per), default=1))
    table = np.zeros((len(d.values), k), np.int32)
    lens_t = np.zeros(len(d.values), np.int32)
    for i, ps in enumerate(pieces_per):
        lens_t[i] = len(ps)
        for j, p in enumerate(ps):
            table[i, j] = ix[p]
    codes = jnp.asarray(value.data, jnp.int32)
    data = jnp.take(jnp.asarray(table), codes, axis=0, mode="clip")
    lens = jnp.take(jnp.asarray(lens_t), codes, mode="clip")
    cap = ctx.capacity
    data = jnp.broadcast_to(data, (cap, k))
    lens = jnp.broadcast_to(lens, (cap,))
    return Val(data, value.valid, call.type, nd, lens)


# ---------------------------------------------------------------------------
# JSON (reference: operator/scalar/json/JsonExtract.java + JsonPath subset)


def _parse_json_path(path: str):
    """Subset of JSONPath the reference's JsonExtract supports: $, .key,
    ['key'], [index]."""
    if not path.startswith("$"):
        raise ValueError(f"invalid JSON path: {path!r}")
    i, n, steps = 1, len(path), []
    while i < n:
        c = path[i]
        if c == ".":
            j = i + 1
            while j < n and path[j] not in ".[":
                j += 1
            steps.append(path[i + 1 : j])
            i = j
        elif c == "[":
            j = path.index("]", i)
            tok = path[i + 1 : j].strip()
            if tok[:1] in ("'", '"'):
                steps.append(tok[1:-1])
            else:
                steps.append(int(tok))
            i = j + 1
        else:
            raise ValueError(f"invalid JSON path: {path!r}")
    return steps


@register("slice")
def _slice_array(ctx, call, arr, start, length):
    """slice(array, start, length), 1-based; negative start counts from the
    end (reference: ArraySliceFunction)."""
    data, lens = _arr2d(ctx, arr)
    cap, k = data.shape
    if k == 0:
        return Val(data, arr.valid, call.type, arr.dictionary, lens)
    s = jnp.broadcast_to(jnp.asarray(start.data, jnp.int64), (cap,))
    n = jnp.broadcast_to(jnp.asarray(length.data, jnp.int64), (cap,))
    ln = lens.astype(jnp.int64)
    begin = jnp.where(s < 0, ln + s, s - 1)  # 0-based
    begin_c = jnp.clip(begin, 0, k)
    take = jnp.clip(jnp.minimum(n, ln - begin_c), 0, k)
    idx = begin_c[:, None] + jnp.arange(k, dtype=jnp.int64)[None, :]
    out = jnp.take_along_axis(data, jnp.clip(idx, 0, k - 1), axis=1)
    new_lens = jnp.where(begin < 0, 0, take).astype(jnp.int32)
    valid = _and_valid(_and_valid(arr.valid, start.valid), length.valid)
    # start=0 / negative length: the reference raises INVALID_FUNCTION_
    # ARGUMENT; row-wise errors aren't expressible, so those rows are NULL
    valid = _and_valid(valid, jnp.logical_and(s != 0, n >= 0))
    return Val(out, valid, call.type, arr.dictionary, new_lens)


@register("$array_concat")
def array_concat(ctx, call, a: Val, b: Val) -> Val:
    """array || array (reference: ArrayConcatFunction)."""
    da, la = _arr2d(ctx, a)
    db, lb = _arr2d(ctx, b)
    da, db, dictionary = _unify_array_dicts(a, da, b, db)
    ka, kb = da.shape[1], db.shape[1]
    k = ka + kb
    dt = call.type.element.np_dtype
    out = jnp.pad(jnp.asarray(da, dt), ((0, 0), (0, kb)))
    idx = jnp.arange(k, dtype=jnp.int32)[None, :]
    from_b = jnp.logical_and(
        idx >= la[:, None], idx < (la + lb)[:, None]
    )
    b_pos = jnp.clip(idx - la[:, None], 0, max(kb - 1, 0))
    db_p = jnp.pad(jnp.asarray(db, dt), ((0, 0), (0, k - kb)))
    out = jnp.where(from_b, jnp.take_along_axis(db_p, b_pos, axis=1), out)
    return Val(
        out, _and_valid(a.valid, b.valid), call.type, dictionary, la + lb
    )


def _unify_array_dicts(a: Val, da, b: Val, db):
    """Merge two array Vals' dictionaries and recode both data planes.
    Returns (da, db, merged dictionary)."""
    from trino_tpu.columnar.dictionary import union_many

    dictionary = a.dictionary
    if a.dictionary is not None or b.dictionary is not None:
        dictionary, (ta, tb) = union_many([a.dictionary, b.dictionary])
        if ta is not None:
            da = jnp.take(jnp.asarray(ta), jnp.asarray(da, jnp.int32), mode="clip")
        if tb is not None:
            db = jnp.take(jnp.asarray(tb), jnp.asarray(db, jnp.int32), mode="clip")
    return da, db, dictionary


def _membership(ctx, a: Val, b: Val):
    """(hit [cap, Ka], a-codes in the MERGED dictionary, a-lengths, merged
    dictionary): which live elements of a appear among b's live elements."""
    da, la = _arr2d(ctx, a)
    db, lb = _arr2d(ctx, b)
    da, db, dictionary = _unify_array_dicts(a, da, b, db)
    emb = _elem_mask(db, lb)
    hit = jnp.any(
        jnp.logical_and(emb[:, None, :], da[:, :, None] == db[:, None, :]),
        axis=2,
    )
    return jnp.logical_and(hit, _elem_mask(da, la)), da, la, dictionary


def _first_occurrence(da, mask):
    """Among masked slots, keep only each value's FIRST occurrence per row."""
    k = da.shape[1]
    eq_prior = jnp.logical_and(
        da[:, :, None] == da[:, None, :],
        jnp.arange(k)[None, None, :] < jnp.arange(k)[None, :, None],
    )
    dup = jnp.any(jnp.logical_and(eq_prior, mask[:, None, :]), axis=2)
    return jnp.logical_and(mask, jnp.logical_not(dup))


def _compact_row_subset(data, keep, dictionary, valid, out_type):
    order = jnp.argsort(jnp.logical_not(keep), axis=1, stable=True)
    out = jnp.take_along_axis(data, order, axis=1)
    lens = jnp.sum(keep, axis=1).astype(jnp.int32)
    return Val(out, valid, out_type, dictionary, lens)


@register("arrays_overlap")
def _arrays_overlap(ctx, call, a, b):
    hit, _, _, _ = _membership(ctx, a, b)
    return Val(
        jnp.any(hit, axis=1), _and_valid(a.valid, b.valid), call.type
    )


@register("array_intersect")
def _array_intersect(ctx, call, a, b):
    """Distinct elements of a present in b (reference:
    ArrayIntersectFunction; output order is a's first-occurrence order)."""
    hit, da, _la, dictionary = _membership(ctx, a, b)
    keep = _first_occurrence(da, hit)
    return _compact_row_subset(
        da, keep, dictionary, _and_valid(a.valid, b.valid), call.type
    )


@register("array_except")
def _array_except(ctx, call, a, b):
    hit, da, la, dictionary = _membership(ctx, a, b)
    ema = _elem_mask(da, la)
    keep = _first_occurrence(da, jnp.logical_and(ema, jnp.logical_not(hit)))
    return _compact_row_subset(
        da, keep, dictionary, _and_valid(a.valid, b.valid), call.type
    )


@register("array_union")
def _array_union(ctx, call, a, b):
    concat = FUNCTIONS["$array_concat"](ctx, call, a, b)
    return FUNCTIONS["array_distinct"](ctx, call, concat)


@register("zip_with")
def _zip_with(ctx, call, a, b, lam):
    """zip_with(a1, a2, (x, y) -> e); rows with mismatched lengths are NULL
    (the reference pads the shorter side with NULL elements, which the
    rectangular layout cannot represent — documented deviation)."""
    da, la = _arr2d(ctx, a)
    db, lb = _arr2d(ctx, b)
    k = max(da.shape[1], db.shape[1], 1)
    dap = jnp.pad(da, ((0, 0), (0, k - da.shape[1])))
    dbp = jnp.pad(db, ((0, 0), (0, k - db.shape[1])))
    xa = Val(dap, None, a.type.element, a.dictionary)
    xb = Val(dbp, None, b.type.element, b.dictionary)
    res = _eval_lambda(ctx, lam, [xa, xb])
    et = call.type.element
    out = jnp.broadcast_to(jnp.asarray(res.data, et.np_dtype), (dap.shape[0], k))
    valid = _and_valid(_and_valid(a.valid, b.valid), la == lb)
    return Val(out, valid, call.type, res.dictionary, la)


# -- lambda functions --------------------------------------------------------
# (reference: operator/scalar/ArrayTransformFunction, ArrayFilterFunction,
# ArrayAnyMatchFunction family, ReduceFunction)
#
# TPU-first evaluation: the lambda body compiles ONCE over the whole padded
# [capacity, K] element matrix — every scalar op broadcasts elementwise, so
# transform/filter are single fused device passes with no per-row loops.


def _eval_lambda(ctx, lam, args: list, matrix: bool = True) -> Val:
    """Evaluate a lambda body with parameters bound.  `matrix=True` marks
    [capacity, K] element-matrix evaluation: captured columns gain a
    trailing broadcast axis and boolean/branch forms broadcast to the
    element-matrix shape (see ExprCompiler.value / bshape)."""
    prev = getattr(ctx, "_lambda_env", None)
    prev_matrix = getattr(ctx, "_lambda_matrix", False)
    prev_shape = getattr(ctx, "_lambda_shape", None)
    env = dict(prev or {})
    for name, v in zip(lam.params, args):
        env[name] = v
    ctx._lambda_env = env
    ctx._lambda_matrix = matrix
    ctx._lambda_shape = (
        tuple(jnp.shape(args[0].data)) if matrix and args else None
    )
    try:
        return ctx.value(lam.body)
    finally:
        ctx._lambda_env = prev
        ctx._lambda_matrix = prev_matrix
        ctx._lambda_shape = prev_shape


@register("transform")
def _transform(ctx, call, arr, lam):
    data, lens = _arr2d(ctx, arr)
    elem = Val(data, None, arr.type.element, arr.dictionary)
    res = _eval_lambda(ctx, lam, [elem])
    et = call.type.element
    out = jnp.broadcast_to(jnp.asarray(res.data, et.np_dtype), data.shape)
    # per-element nulls aren't representable in the rectangular layout: a
    # null-producing element keeps its fill value (documented deviation)
    return Val(out, arr.valid, call.type, res.dictionary, lens)


@register("filter")
def _filter_array(ctx, call, arr, lam):
    data, lens = _arr2d(ctx, arr)
    em = _elem_mask(data, lens)
    elem = Val(data, None, arr.type.element, arr.dictionary)
    res = _eval_lambda(ctx, lam, [elem])
    keep = jnp.broadcast_to(jnp.asarray(res.data, bool), data.shape)
    if res.valid is False:
        keep = jnp.zeros(data.shape, bool)  # NULL predicate drops elements
    elif res.valid is not None:
        keep = jnp.logical_and(keep, jnp.broadcast_to(res.valid, data.shape))
    keep = jnp.logical_and(keep, em)
    # stable per-row compaction of kept elements to the front
    order = jnp.argsort(jnp.logical_not(keep), axis=1, stable=True)
    out = jnp.take_along_axis(data, order, axis=1)
    new_lens = jnp.sum(keep, axis=1).astype(jnp.int32)
    return Val(out, arr.valid, call.type, arr.dictionary, new_lens)


def _match_reduce(ctx, call, arr, lam, combine):
    """Three-valued match semantics (reference: ArrayAnyMatchFunction):
    any = TRUE if any true, NULL if none true but some null, else FALSE;
    all = FALSE if any false, NULL if none false but some null, else TRUE."""
    data, lens = _arr2d(ctx, arr)
    em = _elem_mask(data, lens)
    elem = Val(data, None, arr.type.element, arr.dictionary)
    res = _eval_lambda(ctx, lam, [elem])
    m = jnp.broadcast_to(jnp.asarray(res.data, bool), data.shape)
    if res.valid is False:
        pv = jnp.zeros(data.shape, bool)
    elif res.valid is None:
        pv = jnp.ones(data.shape, bool)
    else:
        pv = jnp.broadcast_to(res.valid, data.shape)
    has_null = jnp.any(jnp.logical_and(em, jnp.logical_not(pv)), axis=1)
    if combine == "any":
        hit = jnp.any(jnp.logical_and(em, jnp.logical_and(m, pv)), axis=1)
        out = hit
        known = jnp.logical_or(hit, jnp.logical_not(has_null))
    else:
        miss = jnp.any(
            jnp.logical_and(em, jnp.logical_and(jnp.logical_not(m), pv)),
            axis=1,
        )
        out = jnp.logical_not(miss)
        known = jnp.logical_or(miss, jnp.logical_not(has_null))
    return Val(out, _and_valid(arr.valid, known), call.type)


@register("any_match")
def _any_match(ctx, call, arr, lam):
    return _match_reduce(ctx, call, arr, lam, "any")


@register("all_match")
def _all_match(ctx, call, arr, lam):
    return _match_reduce(ctx, call, arr, lam, "all")


@register("none_match")
def _none_match(ctx, call, arr, lam):
    v = _match_reduce(ctx, call, arr, lam, "any")
    return Val(jnp.logical_not(v.data), v.valid, call.type)


@register("reduce")
def _reduce_array(ctx, call, arr, init, comb, final):
    """reduce(array, init, (s, x) -> ..., s -> ...): the fold unrolls over
    the (static) padded width K, each step a fused [capacity] update."""
    data, lens = _arr2d(ctx, arr)
    cap, k = data.shape
    state = Val(
        jnp.broadcast_to(jnp.asarray(init.data), (cap,)),
        init.valid,
        init.type,
        init.dictionary,
    )
    for j in range(k):
        xj = Val(data[:, j], None, arr.type.element, arr.dictionary)
        new = _eval_lambda(ctx, comb, [state, xj], matrix=False)
        live = lens > j
        # the state follows the COMBINATOR's type (it may widen, e.g.
        # bigint init + double elements); cast the carried state, never
        # truncate the new value
        nd = jnp.asarray(new.data)
        merged = jnp.where(live, nd, jnp.asarray(state.data, nd.dtype))
        from trino_tpu.expr.compiler import _valid_arr as _va

        cap_shape = (cap,)
        mv = jnp.where(
            live, _va(new.valid, cap_shape), _va(state.valid, cap_shape)
        )
        state = Val(merged, mv, new.type, new.dictionary)
    out = _eval_lambda(ctx, final, [state], matrix=False)
    return Val(
        jnp.broadcast_to(jnp.asarray(out.data), (cap,)),
        _and_valid(arr.valid, out.valid),
        call.type,
        out.dictionary,
    )


def _json_walk(doc, steps):
    for s in steps:
        if isinstance(s, int):
            if not isinstance(doc, list) or s >= len(doc) or s < -len(doc):
                return None, False
            doc = doc[s]
        else:
            if not isinstance(doc, dict) or s not in doc:
                return None, False
            doc = doc[s]
    return doc, True


def _json_table(value: Val, path: Val, name: str, render):
    """Evaluate a JSON path once per dictionary value; returns (outs, hits)."""
    d = _require_dict(value, name)
    steps = _parse_json_path(_literal_str(path, name))
    outs, hits = [], []
    for s in d.values:
        try:
            doc = json.loads(s)
            v, ok = _json_walk(doc, steps)
            r = render(v, ok)
        except (ValueError, TypeError, OverflowError):
            r = None
        if r is None:
            outs.append("")
            hits.append(False)
        else:
            outs.append(r)
            hits.append(True)
    return d, outs, hits


def _dict_gather(value: Val, outs, hits, out_type):
    nd = StringDictionary.from_unsorted(outs)
    ix = nd.index
    table = jnp.asarray(
        np.fromiter((ix[o] for o in outs), dtype=np.int32, count=len(outs))
    )
    hit_table = jnp.asarray(np.asarray(hits, dtype=bool))
    codes = jnp.asarray(value.data, jnp.int32)
    out_codes = jnp.take(table, codes, mode="clip")
    hit = jnp.take(hit_table, codes, mode="clip")
    valid = _and_valid(value.valid, hit)
    return Val(out_codes, valid, out_type, nd)


@register("json_extract_scalar")
def _json_extract_scalar(ctx, call, value, path):
    def render(v, ok):
        if not ok or isinstance(v, (dict, list)) or v is None:
            return None
        if isinstance(v, bool):
            return "true" if v else "false"
        if isinstance(v, float) and v == int(v):
            return json.dumps(v)
        return str(v)

    _, outs, hits = _json_table(value, path, "json_extract_scalar", render)
    return _dict_gather(value, outs, hits, call.type)


@register("json_extract")
def _json_extract(ctx, call, value, path):
    def render(v, ok):
        if not ok:
            return None
        return json.dumps(v, separators=(",", ":"))

    _, outs, hits = _json_table(value, path, "json_extract", render)
    return _dict_gather(value, outs, hits, call.type)


@register("json_array_length")
def _json_array_length(ctx, call, value):
    d = _require_dict(value, "json_array_length")
    lens, hits = [], []
    for s in d.values:
        try:
            doc = json.loads(s)
        except (ValueError, TypeError):
            doc = None
        if isinstance(doc, list):
            lens.append(len(doc))
            hits.append(True)
        else:
            lens.append(0)
            hits.append(False)
    lt = jnp.asarray(np.asarray(lens, np.int64))
    ht = jnp.asarray(np.asarray(hits, bool))
    codes = jnp.asarray(value.data, jnp.int32)
    out = jnp.take(lt, codes, mode="clip")
    hit = jnp.take(ht, codes, mode="clip")
    return Val(out, _and_valid(value.valid, hit), call.type)


@register("json_size")
def _json_size(ctx, call, value, path):
    def render(v, ok):
        if not ok:
            return None
        if isinstance(v, (dict, list)):
            return str(len(v))
        return "0"

    _, outs, hits = _json_table(value, path, "json_size", render)
    v = _dict_gather(value, outs, hits, T.VARCHAR)
    # decode the small digit dictionary into ints
    table = jnp.asarray(
        np.asarray([int(x) if x else 0 for x in v.dictionary.values], np.int64)
    )
    out = jnp.take(table, jnp.asarray(v.data, jnp.int32), mode="clip")
    return Val(out, v.valid, call.type)


@register("json_parse")
@register("json_format")
def _json_identity(ctx, call, value):
    """JSON is carried as canonical text (the engine's JSON runtime type is
    dictionary-encoded varchar), so parse/format are identity on valid text."""
    return Val(value.data, value.valid, call.type, value.dictionary)


@register("array_join")
def _array_join(ctx, call, arr, sep, *rest):
    """array_join(arr, sep [, null_replacement]) — reference:
    operator/scalar/ArrayJoin.java.  Eager host render: rectangular arrays
    carry no per-element nulls (documented deviation), so the optional
    null_replacement is accepted and unused."""
    import jax

    data, lens = _arr2d(ctx, arr)
    if isinstance(data, jax.core.Tracer):
        # host rendering can't trace; FilterProjectOperator runs projections
        # containing array_join unjitted (EAGER_FUNCS), other jitted
        # contexts (join residuals, ...) get a clean error instead of a
        # TracerArrayConversionError
        raise NotImplementedError(
            "array_join is not supported in this expression context"
        )
    s = _literal_str(sep, "array_join")
    if rest:
        _literal_str(rest[0], "array_join")  # validate; elements can't be null
    d = np.asarray(data)
    ln = np.asarray(lens)
    et = arr.type.element if isinstance(arr.type, T.ArrayType) else None
    if arr.dictionary is not None:
        vals = arr.dictionary.values

        def render(c):
            return vals[int(c)] if 0 <= int(c) < len(vals) else ""

    elif et is not None and et.name == "boolean":

        def render(c):
            return "true" if c else "false"

    elif isinstance(et, T.DecimalType) and et.scale > 0:
        q = 10 ** et.scale

        def render(c):
            v = int(c)
            sign = "-" if v < 0 else ""
            return f"{sign}{abs(v) // q}.{abs(v) % q:0{et.scale}d}"

    elif et is not None and et.name == "date":
        import datetime

        def render(c):
            return (
                datetime.date(1970, 1, 1) + datetime.timedelta(days=int(c))
            ).isoformat()

    elif et is not None and et.name == "timestamp":
        import datetime

        def render(c):
            dt = datetime.datetime(1970, 1, 1) + datetime.timedelta(
                microseconds=int(c)
            )
            return dt.isoformat(sep=" ")

    elif et is not None and et.name == "timestamp with time zone":
        raise NotImplementedError(
            "array_join over timestamp with time zone arrays"
        )

    elif d.dtype.kind == "f":

        def render(c):
            return str(float(c))

    else:

        def render(c):
            return str(int(c))

    joined = [s.join(render(c) for c in d[i, : ln[i]]) for i in range(d.shape[0])]
    nd = StringDictionary.from_unsorted(joined)
    codes = jnp.asarray(np.asarray(nd.encode(joined), np.int32))
    return Val(codes, arr.valid, call.type, nd)
