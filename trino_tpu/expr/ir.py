"""Typed row-expression IR (reference: sql/relational/RowExpression.java).

Produced by the analyzer/planner, consumed by the trace-time compiler and by
optimizer rules (constant folding, predicate pushdown, dynamic-filter
extraction).  Deliberately small: InputRef / Literal / Call / SpecialForm.
"""

from __future__ import annotations

import enum
from typing import Any, Sequence

from trino_tpu.types import Type, BOOLEAN


#: render budget for expression __repr__: a shared DAG would otherwise
#: expand to an exponential-size string in EXPLAIN / plan rendering
_REPR_BUDGET = 2000


def _render(e: "Expr", budget: list) -> str:
    if budget[0] <= 0:
        return "\u2026"
    budget[0] -= 1
    return e._render(budget)


#: hash-consing table: flat structural key -> small int id.  Composite keys
#: reference children by interned id, so a key stays FLAT (O(node arity))
#: even when the expression is a deeply shared DAG — a naive recursive key
#: would expand the DAG into an exponential-size tree (concat_ws's threaded
#: accumulator, CASE chains).  Process-level, like the jitted-step caches
#: that consume these keys.
_KEY_IDS: dict = {}


class Expr:
    type: Type

    def children(self) -> Sequence["Expr"]:
        return ()

    def with_children(self, children: Sequence["Expr"]) -> "Expr":
        assert not children
        return self

    # structural equality for optimizer rules
    def _compute_key(self):
        raise NotImplementedError

    def key(self):
        """Flat structural key (cached; children appear as interned ids)."""
        k = getattr(self, "_key", None)
        if k is None:
            k = self._compute_key()
            self._key = k
        return k

    def key_id(self) -> int:
        """Interned id of this node's structural key."""
        i = getattr(self, "_key_id", None)
        if i is None:
            k = self.key()
            i = _KEY_IDS.get(k)
            if i is None:
                i = len(_KEY_IDS)
                _KEY_IDS[k] = i
            self._key_id = i
        return i

    def __eq__(self, other):
        return isinstance(other, Expr) and self.key() == other.key()

    def __hash__(self):
        return hash(self.key())

    def _render(self, budget: list) -> str:
        raise NotImplementedError

    def __repr__(self):
        return _render(self, [_REPR_BUDGET])


class InputRef(Expr):
    """Reference to an input channel of the operator's input batch."""

    __slots__ = ("channel", "type")

    def __init__(self, channel: int, type: Type):
        self.channel = channel
        self.type = type

    def _compute_key(self):
        return ("input", self.channel, self.type.name)

    def _render(self, budget):
        return f"#{self.channel}:{self.type.name}"


class SymbolRef(Expr):
    """Named symbol reference used in logical plans (reference:
    sql/planner/Symbol.java); rewritten to InputRef channels by the local
    execution planner."""

    __slots__ = ("name", "type")

    def __init__(self, name: str, type: Type):
        self.name = name
        self.type = type

    def _compute_key(self):
        return ("sym", self.name, self.type.name)

    def _render(self, budget):
        return f"${self.name}:{self.type.name}"


class Literal(Expr):
    """Constant. `value` is the *logical* host python value — Decimal/int/float
    for decimals (scaled at compile time), day numbers for dates, python str
    for strings (resolved against column dictionaries at trace time)."""

    __slots__ = ("value", "type")

    def __init__(self, value: Any, type: Type):
        self.value = value
        self.type = type

    @property
    def is_null(self) -> bool:
        return self.value is None

    def _compute_key(self):
        v = self.value
        if isinstance(v, (list, dict)):  # array/map literals: hashable form
            v = repr(v)
        return ("lit", v, self.type.name)

    def _render(self, budget):
        return f"{self.value!r}:{self.type.name}"


class LambdaParam(Expr):
    """A bound lambda parameter reference (reference:
    sql/relational/LambdaDefinitionExpression's argument slots).  The
    compiler resolves it from the active lambda environment."""

    __slots__ = ("name", "type")

    def __init__(self, name: str, type: Type):
        self.name = name
        self.type = type

    def _compute_key(self):
        return ("lparam", self.name, self.type.name)

    def _render(self, budget):
        return f"λ{self.name}:{self.type.name}"


class Lambda(Expr):
    """x -> body (reference: sql/tree/LambdaExpression ->
    LambdaDefinitionExpression)."""

    __slots__ = ("params", "body", "type")

    def __init__(self, params: Sequence[str], body: Expr, type: Type):
        self.params = tuple(params)
        self.body = body
        self.type = type  # the BODY's result type

    def children(self):
        return (self.body,)

    def with_children(self, children):
        return Lambda(self.params, children[0], self.type)

    def _compute_key(self):
        return ("lambda", self.params, self.body.key_id(), self.type.name)

    def _render(self, budget):
        return f"({', '.join(self.params)}) -> {_render(self.body, budget)}"


class Call(Expr):
    """Scalar function call, name-resolved (e.g. '$add', 'substr', 'year')."""

    __slots__ = ("name", "args", "type")

    def __init__(self, name: str, args: Sequence[Expr], type: Type):
        self.name = name
        self.args = tuple(args)
        self.type = type

    def children(self):
        return self.args

    def with_children(self, children):
        return Call(self.name, tuple(children), self.type)

    def _compute_key(self):
        return ("call", self.name, tuple(a.key_id() for a in self.args), self.type.name)

    def _render(self, budget):
        return f"{self.name}({', '.join(_render(a, budget) for a in self.args)})"


class Form(enum.Enum):
    AND = "and"
    OR = "or"
    NOT = "not"
    IF = "if"                  # if(cond, then, else)
    CASE = "case"              # searched case: [c1, v1, c2, v2, ..., default]
    COALESCE = "coalesce"
    IN = "in"                  # in(value, item1, item2, ...)
    BETWEEN = "between"        # between(v, lo, hi)
    IS_NULL = "is_null"
    CAST = "cast"
    TRY = "try"
    NULLIF = "nullif"
    ROW = "row"
    DEREFERENCE = "dereference"
    ARRAY = "array"            # array(e1, e2, ...) constructor
    SUBSCRIPT = "subscript"    # subscript(array, index) — 1-based


class SpecialForm(Expr):
    __slots__ = ("form", "args", "type")

    def __init__(self, form: Form, args: Sequence[Expr], type: Type = BOOLEAN):
        self.form = form
        self.args = tuple(args)
        self.type = type

    def children(self):
        return self.args

    def with_children(self, children):
        return SpecialForm(self.form, tuple(children), self.type)

    def _compute_key(self):
        return ("form", self.form.value, tuple(a.key_id() for a in self.args), self.type.name)

    def _render(self, budget):
        return f"{self.form.value}({', '.join(_render(a, budget) for a in self.args)})"


# -- convenience constructors used throughout the planner --------------------


def substitute_symbols(expr: "Expr", mapping: dict) -> "Expr":
    """Replace SymbolRefs by name with mapped expressions (bottom-up).
    The mapping value is used as-is — callers wrap in CAST when the
    replacement's type differs from the symbol's."""

    def fn(x):
        if isinstance(x, SymbolRef) and x.name in mapping:
            return mapping[x.name]
        return x

    return visit(expr, fn)


def and_(*args: Expr) -> Expr:
    flat = []
    for a in args:
        if isinstance(a, SpecialForm) and a.form == Form.AND:
            flat.extend(a.args)
        elif isinstance(a, Literal) and a.value is True:
            continue
        else:
            flat.append(a)
    if not flat:
        return Literal(True, BOOLEAN)
    if len(flat) == 1:
        return flat[0]
    return SpecialForm(Form.AND, flat, BOOLEAN)


def or_(*args: Expr) -> Expr:
    if len(args) == 1:
        return args[0]
    return SpecialForm(Form.OR, list(args), BOOLEAN)


def not_(a: Expr) -> Expr:
    return SpecialForm(Form.NOT, [a], BOOLEAN)


def comparison(op: str, left: Expr, right: Expr) -> Expr:
    return Call({"=": "$eq", "<>": "$ne", "!=": "$ne", "<": "$lt",
                 "<=": "$le", ">": "$gt", ">=": "$ge"}[op], [left, right], BOOLEAN)


def visit(expr: Expr, fn, _memo: dict = None) -> Expr:
    """Bottom-up rewrite: fn applied to every node after its children.

    Memoized by node identity: planner rewrites produce DAGs where the same
    sub-Expr object is referenced many times (concat_ws's threaded
    accumulator, CASE chains); an unmemoized walk is exponential in the
    sharing depth AND un-shares the DAG for every downstream pass."""
    if _memo is None:
        _memo = {}
    hit = _memo.get(id(expr))
    if hit is not None:
        return hit
    out = expr
    kids = expr.children()
    if kids:
        out = expr.with_children([visit(k, fn, _memo) for k in kids])
    out = fn(out)
    _memo[id(expr)] = out
    return out


def collect_input_channels(
    expr: Expr, acc: set | None = None, _seen: set | None = None
) -> set:
    if acc is None:
        acc = set()
    if _seen is None:
        _seen = set()
    if id(expr) in _seen:
        return acc
    _seen.add(id(expr))
    if isinstance(expr, InputRef):
        acc.add(expr.channel)
    for k in expr.children():
        collect_input_channels(k, acc, _seen)
    return acc
