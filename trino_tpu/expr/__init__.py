"""Expression subsystem: typed IR + trace-time JAX compiler.

Reference roles:
  - sql/relational/RowExpression.java  -> ir.Expr hierarchy
  - sql/gen/PageFunctionCompiler.java  -> compiler.compile_projection / compile_filter
  - operator/scalar/* (139 files)      -> functions.FUNCTIONS registry
  - likematcher/LikeMatcher.java       -> strings.like_to_predicate (dictionary tables)

Where the reference generates JVM bytecode per expression at query setup, this
engine *traces* the expression into the fragment's XLA computation: the
compiled fragment is one fused device program, and string predicates become
dictionary lookup tables baked in as constants at trace time.
"""

from trino_tpu.expr.ir import (
    Expr,
    InputRef,
    Literal,
    Call,
    SpecialForm,
    Form,
)
from trino_tpu.expr.compiler import ExprCompiler, Val

__all__ = [
    "Expr",
    "InputRef",
    "Literal",
    "Call",
    "SpecialForm",
    "Form",
    "ExprCompiler",
    "Val",
]
