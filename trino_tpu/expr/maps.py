"""MAP scalar functions.

Reference roles: core/trino-main/.../operator/scalar/MapConstructor.java,
MapKeys/MapValues/MapCardinality, MapSubscriptOperator.java,
MapConcatFunction.java, MapElementAtFunction.

Device layout (see types.MapType): a map column is [capacity, 2*K] with the
key plane in slots [0:K] and the value plane in [K:2K]; `lengths` is the
per-row entry count.  All lookups are vectorized equality scans over the key
plane — K is small (pow2-bucketed at construction), so a scan beats building
per-row hash structures on a systolic-array machine.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.expr.compiler import Val, _and_valid
from trino_tpu.expr.functions import register


def _map2d(ctx, v: Val):
    """Broadcast a map Val to (keys [cap,K], values [cap,K], lengths[cap])."""
    if v.lengths is None or not isinstance(v.type, T.MapType):
        raise NotImplementedError("expected a map value")
    cap = ctx.capacity
    two_k = v.data.shape[-1]
    k = two_k // 2
    data = jnp.broadcast_to(jnp.asarray(v.data), (cap, two_k))
    lens = jnp.broadcast_to(jnp.asarray(v.lengths, jnp.int32), (cap,))
    return data[:, :k], data[:, k:], lens


def _entry_mask(k: int, lens):
    return jnp.arange(k, dtype=jnp.int32)[None, :] < lens[:, None]


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def make_map(ctx, call, keys: Val, values: Val) -> Val:
    """MAP(ARRAY[...], ARRAY[...]) — reference: MapConstructor.java.
    Rows where key/value array lengths differ become NULL maps (the
    reference throws; vectorized programs signal via null)."""
    mt = call.type
    cap = ctx.capacity
    kk = keys.data.shape[-1] if keys.lengths is not None else 0
    kv = values.data.shape[-1] if values.lengths is not None else 0
    k = _pow2(max(kk, kv, 1))
    kd = jnp.broadcast_to(jnp.asarray(keys.data), (cap, kk)) if kk else jnp.zeros((cap, 0), mt.np_dtype)
    vd = jnp.broadcast_to(jnp.asarray(values.data), (cap, kv)) if kv else jnp.zeros((cap, 0), mt.np_dtype)
    klens = (
        jnp.broadcast_to(jnp.asarray(keys.lengths, jnp.int32), (cap,))
        if keys.lengths is not None
        else jnp.zeros(cap, jnp.int32)
    )
    vlens = (
        jnp.broadcast_to(jnp.asarray(values.lengths, jnp.int32), (cap,))
        if values.lengths is not None
        else jnp.zeros(cap, jnp.int32)
    )
    # merge dictionaries when both planes are strings (single shared dict)
    dictionary = None
    if keys.dictionary is not None and values.dictionary is not None:
        from trino_tpu.columnar.dictionary import union_many

        dictionary, (tk, tv) = union_many([keys.dictionary, values.dictionary])
        if tk is not None:
            kd = jnp.take(jnp.asarray(tk), jnp.asarray(kd, jnp.int32), mode="clip")
        if tv is not None:
            vd = jnp.take(jnp.asarray(tv), jnp.asarray(vd, jnp.int32), mode="clip")
    elif keys.dictionary is not None:
        dictionary = keys.dictionary
    elif values.dictionary is not None:
        dictionary = values.dictionary
    dt = mt.np_dtype
    kd = jnp.pad(jnp.asarray(kd, dt), ((0, 0), (0, k - kk)))
    vd = jnp.pad(jnp.asarray(vd, dt), ((0, 0), (0, k - kv)))
    data = jnp.concatenate([kd, vd], axis=1)
    valid = _and_valid(keys.valid, values.valid)
    valid = _and_valid(valid, klens == vlens)
    return Val(data, valid, mt, dictionary, klens)


@register("map")
def _map_ctor(ctx, call, keys, values):
    return make_map(ctx, call, keys, values)


@register("map_keys")
def _map_keys(ctx, call, m):
    kd, _, lens = _map2d(ctx, m)
    d = m.dictionary if T.is_string_kind(m.type.key) else None
    at = call.type  # array(K)
    return Val(jnp.asarray(kd, at.element.np_dtype), m.valid, at, d, lens)


@register("map_values")
def _map_values(ctx, call, m):
    _, vd, lens = _map2d(ctx, m)
    d = m.dictionary if T.is_string_kind(m.type.value) else None
    at = call.type
    return Val(jnp.asarray(vd, at.element.np_dtype), m.valid, at, d, lens)


def _encode_key(ctx, m: Val, key: Val):
    """Key lookup value in the map's key-plane representation."""
    if T.is_string_kind(m.type.key) and m.dictionary is not None:
        # resolve the probe key against the map's dictionary
        if key.dictionary is m.dictionary:
            return jnp.asarray(key.data, m.data.dtype), key.valid
        if key.dictionary is not None:
            table = np.asarray(
                [m.dictionary.index.get(s, -1) for s in key.dictionary.values],
                dtype=np.int64,
            )
            code = jnp.take(
                jnp.asarray(table), jnp.asarray(key.data, jnp.int32), mode="clip"
            )
            return code, _and_valid(key.valid, code >= 0)
        raise NotImplementedError("string key without dictionary")
    return jnp.asarray(key.data, m.data.dtype), key.valid


def map_element_at(ctx, call, m: Val, key: Val) -> Val:
    """element_at(map, key) / map[key] — reference: MapSubscriptOperator
    (subscript throws on missing key; element_at yields NULL — vectorized,
    both yield NULL)."""
    kd, vd, lens = _map2d(ctx, m)
    k = kd.shape[1]
    cap = ctx.capacity
    if k == 0:
        return Val(jnp.zeros(cap, call.type.np_dtype), False, call.type)
    probe, pvalid = _encode_key(ctx, m, key)
    probe = jnp.broadcast_to(probe, (cap,))
    em = _entry_mask(k, lens)
    hit = jnp.logical_and(em, kd == probe[:, None])
    found = jnp.any(hit, axis=1)
    pos = jnp.argmax(hit, axis=1)
    out = jnp.take_along_axis(vd, pos[:, None], axis=1)[:, 0]
    valid = _and_valid(_and_valid(m.valid, pvalid), found)
    d = m.dictionary if T.is_string_kind(m.type.value) else None
    return Val(jnp.asarray(out, call.type.np_dtype), valid, call.type, d)


@register("map_concat")
def _map_concat(ctx, call, *maps):
    """map_concat(m1, m2, ...): later maps win on duplicate keys
    (reference: MapConcatFunction.java)."""
    if len(maps) < 2:
        return maps[0]
    acc = maps[0]
    for nxt in maps[1:]:
        acc = _concat2(ctx, call, acc, nxt)
    return acc


def _concat2(ctx, call, a: Val, b: Val) -> Val:
    mt = call.type
    ka, va, la = _map2d(ctx, a)
    kb, vb, lb = _map2d(ctx, b)
    # unify dictionaries if string-typed planes are involved
    dictionary = a.dictionary
    if a.dictionary is not None or b.dictionary is not None:
        from trino_tpu.columnar.dictionary import union_many

        dictionary, (ta, tb) = union_many([a.dictionary, b.dictionary])
        if ta is not None:
            if T.is_string_kind(mt.key):
                ka = jnp.take(jnp.asarray(ta), jnp.asarray(ka, jnp.int32), mode="clip")
            if T.is_string_kind(mt.value):
                va = jnp.take(jnp.asarray(ta), jnp.asarray(va, jnp.int32), mode="clip")
        if tb is not None:
            if T.is_string_kind(mt.key):
                kb = jnp.take(jnp.asarray(tb), jnp.asarray(kb, jnp.int32), mode="clip")
            if T.is_string_kind(mt.value):
                vb = jnp.take(jnp.asarray(tb), jnp.asarray(vb, jnp.int32), mode="clip")
    na, nb = ka.shape[1], kb.shape[1]
    ema = _entry_mask(na, la)
    emb = _entry_mask(nb, lb)
    # drop entries of `a` whose key also appears (live) in `b` — b wins
    dup = jnp.any(
        jnp.logical_and(
            emb[:, None, :], ka[:, :, None] == kb[:, None, :]
        ),
        axis=2,
    )
    keep_a = jnp.logical_and(ema, jnp.logical_not(dup))
    # compact kept `a` entries to the front: stable argsort of ~keep
    order = jnp.argsort(jnp.logical_not(keep_a), axis=1, stable=True)
    ka_s = jnp.take_along_axis(ka, order, axis=1)
    va_s = jnp.take_along_axis(va, order, axis=1)
    n_keep = jnp.sum(keep_a, axis=1).astype(jnp.int32)
    k = _pow2(max(na + nb, 1))
    dt = mt.np_dtype
    pad_a = ((0, 0), (0, k - na))
    pad_b = ((0, 0), (0, k - nb))
    keys = jnp.pad(jnp.asarray(ka_s, dt), pad_a)
    vals = jnp.pad(jnp.asarray(va_s, dt), pad_a)
    kb_p = jnp.pad(jnp.asarray(kb, dt), pad_b)
    vb_p = jnp.pad(jnp.asarray(vb, dt), pad_b)
    # scatter b's entries right after a's kept prefix, per row
    idx = jnp.arange(k, dtype=jnp.int32)[None, :]
    from_b = jnp.logical_and(
        idx >= n_keep[:, None], idx < (n_keep + lb)[:, None]
    )
    b_pos = jnp.clip(idx - n_keep[:, None], 0, k - 1)
    keys = jnp.where(from_b, jnp.take_along_axis(kb_p, b_pos, axis=1), keys)
    vals = jnp.where(from_b, jnp.take_along_axis(vb_p, b_pos, axis=1), vals)
    data = jnp.concatenate([keys, vals], axis=1)
    lengths = n_keep + lb
    valid = _and_valid(a.valid, b.valid)
    return Val(data, valid, mt, dictionary, lengths)
