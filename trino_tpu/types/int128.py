"""Two-limb i128 device arithmetic for long decimals (precision 19-38).

Reference: core/trino-spi/.../spi/type/Int128.java + Int128Math.java — the
reference stores long decimals as two 64-bit limbs and implements exact
add/subtract/compare/divide on them; this is the TPU-native equivalent over
jnp int64 planes.

Representation: a long-decimal value v is (hi, lo) with
    v = hi * 2**64 + (lo interpreted as unsigned 64-bit)
hi is the signed high limb, lo carries the raw low 64 bits in an int64 (the
bit pattern of the unsigned value — XLA integer adds wrap two's-complement,
which is exactly mod-2**64 arithmetic).  A long-decimal Column/Val stores
the planes stacked on the last axis: data[..., 0] = hi, data[..., 1] = lo.

All kernels are shape-polymorphic elementwise jnp ops, so they fuse into
the surrounding fragment under jit on CPU and TPU alike.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

import jax

# numpy scalars, NOT jnp arrays: module-level device arrays become captured
# buffers of every jitted program that closes over them, breaking executable
# reuse across operator instances ("supplied N buffers but expected N+1")
_SIGN = np.int64(-(2**63))  # sign-flip constant for unsigned cmp
_MASK32 = np.int64(0xFFFFFFFF)

#: python-side constants
TWO64 = 1 << 64


# -- host (python int) conversions -------------------------------------------


def split_py(v: int) -> tuple:
    """Python int -> (hi, lo) limb ints suitable for int64 storage."""
    lo = v & (TWO64 - 1)
    hi = (v - lo) >> 64
    if lo >= 1 << 63:
        lo -= TWO64  # store as int64 bit pattern
    return int(hi), int(lo)


def join_py(hi: int, lo: int) -> int:
    """(hi, lo) int64 limbs -> python int."""
    return (int(hi) << 64) + (int(lo) & (TWO64 - 1))


# -- device helpers -----------------------------------------------------------


def _ult(a, b):
    """Unsigned < over int64 bit patterns (sign-bit flip trick)."""
    return (a ^ _SIGN) < (b ^ _SIGN)


def widen64(v):
    """int64 value -> (hi, lo) planes of the same i128 value."""
    v = jnp.asarray(v, jnp.int64)
    return v >> 63, v  # arithmetic shift: hi is all sign bits


def add128(ah, al, bh, bl):
    lo = al + bl  # wraps mod 2**64
    carry = _ult(lo, al).astype(jnp.int64)
    return ah + bh + carry, lo


def neg128(h, l):
    lo = -l  # two's complement of the low limb (wraps)
    hi = ~h + (l == 0).astype(jnp.int64)
    return hi, lo


def sub128(ah, al, bh, bl):
    nh, nl = neg128(bh, bl)
    return add128(ah, al, nh, nl)


def eq128(ah, al, bh, bl):
    return jnp.logical_and(ah == bh, al == bl)


def lt128(ah, al, bh, bl):
    return jnp.logical_or(
        ah < bh, jnp.logical_and(ah == bh, _ult(al, bl))
    )


def is_neg128(h, l):
    return h < 0


def mul128_by_u32(h, l, c: int):
    """(h, l) * c for a small nonnegative python constant c <= 2**31
    ((2**32-1) * 2**31 < 2**63, so the chunk products stay exact).
    Used for decimal rescaling by powers of ten (applied in <=10**9 steps)."""
    assert 0 <= c <= (1 << 31)
    cc = jnp.int64(c)
    l0 = l & _MASK32
    l1 = (l >> 32) & _MASK32  # logical: mask after arithmetic shift
    p0 = l0 * cc  # < 2**63: exact
    p1 = l1 * cc
    lo_lo = p0 & _MASK32
    carry = (p0 >> 32) + (p1 & _MASK32)  # nonneg
    lo_hi = carry & _MASK32
    lo = lo_lo | (lo_hi << 32)
    hi_carry = (carry >> 32) + ((p1 >> 32) & _MASK32)
    return h * cc + hi_carry, lo


def divmod128_by_u31(h, l, c: int):
    """Exact (quotient, remainder) of the SIGNED (h, l) value by a python
    constant 0 < c < 2**31, truncating toward zero.  Schoolbook long
    division over four 32-bit chunks (valid because the running remainder
    stays < c < 2**31, so r*2**32 + chunk < 2**63)."""
    assert 0 < c < (1 << 31)
    neg = h < 0
    ph, pl = neg128(h, l)
    h_ = jnp.where(neg, ph, h)
    l_ = jnp.where(neg, pl, l)
    cc = jnp.int64(c)
    chunks = [
        (h_ >> 32) & _MASK32,
        h_ & _MASK32,
        (l_ >> 32) & _MASK32,
        l_ & _MASK32,
    ]
    r = jnp.zeros_like(h_)
    qs = []
    for ch in chunks:
        acc = (r << 32) | ch
        qs.append(acc // cc)
        r = acc % cc
    qh = (qs[0] << 32) | qs[1]
    ql = (qs[2] << 32) | qs[3]
    nqh, nql = neg128(qh, ql)
    return (
        jnp.where(neg, nqh, qh),
        jnp.where(neg, nql, ql),
        jnp.where(neg, -r, r),
    )


def mul128_by_vec31(h, l, c):
    """(h, l) * c for a NONNEGATIVE int64 vector c < 2**31 (same chunk math
    as mul128_by_u32 with a data-dependent multiplier)."""
    c = jnp.asarray(c, jnp.int64)
    l0 = l & _MASK32
    l1 = (l >> 32) & _MASK32
    p0 = l0 * c  # < 2**63: exact
    p1 = l1 * c
    lo_lo = p0 & _MASK32
    carry = (p0 >> 32) + (p1 & _MASK32)
    lo_hi = carry & _MASK32
    lo = lo_lo | (lo_hi << 32)
    hi_carry = (carry >> 32) + ((p1 >> 32) & _MASK32)
    return h * c + hi_carry, lo


def mul64x64(a, b):
    """Exact (hi, lo) planes of a * b for two int64 vectors (the hot case:
    short-decimal x short-decimal with a long result, e.g. TPC-H Q1's
    extendedprice * (1 - discount)).  Schoolbook 32-bit chunks, ~18 ops —
    far cheaper than routing one side through the generic 128-bit path."""
    a = jnp.asarray(a, jnp.int64)
    b = jnp.asarray(b, jnp.int64)
    neg = (a < 0) ^ (b < 0)
    aa = jnp.abs(a)
    ab = jnp.abs(b)
    a0 = aa & _MASK32
    a1 = (aa >> 32) & _MASK32  # < 2**31 for |a| < 2**63
    b0 = ab & _MASK32
    b1 = (ab >> 32) & _MASK32
    p00 = a0 * b0  # may wrap: bit pattern IS the unsigned product mod 2**64
    p01 = a0 * b1  # < 2**63: exact
    p10 = a1 * b0
    p11 = a1 * b1
    t = ((p00 >> 32) & _MASK32) + (p01 & _MASK32) + (p10 & _MASK32)
    lo = (p00 & _MASK32) | ((t & _MASK32) << 32)
    hi = p11 + (p01 >> 32) + (p10 >> 32) + (t >> 32)
    nh, nl = neg128(hi, lo)
    return jnp.where(neg, nh, hi), jnp.where(neg, nl, lo)


def mul128_by_i64vec(h, l, c):
    """(h, l) * c for an arbitrary int64 vector c (mod 2**128): split |c|
    into three chunks (31+31+1 bits, each < 2**31 so the 32x31 chunk
    products stay exact in i64), combine shifted partials, apply the sign."""
    c = jnp.asarray(c, jnp.int64)
    neg = (h < 0) ^ (c < 0)
    ph, pl = neg128(h, l)
    h_ = jnp.where(h < 0, ph, h)
    l_ = jnp.where(h < 0, pl, l)
    ca = jnp.abs(c)
    m31 = jnp.int64((1 << 31) - 1)
    c0 = ca & m31
    c1 = (ca >> 31) & m31
    c2 = ca >> 62  # 0 or 1 (|c| < 2**63)
    h0, l0v = mul128_by_vec31(h_, l_, c0)
    h1, l1v = mul128_by_vec31(h_, l_, c1)
    h1, l1v = mul128_by_u32(h1, l1v, 1 << 31)  # partial << 31
    h2, l2v = mul128_by_vec31(h_, l_, c2)
    h2, l2v = mul128_by_u32(h2, l2v, 1 << 31)  # partial << 62
    h2, l2v = mul128_by_u32(h2, l2v, 1 << 31)
    rh, rl = add128(h0, l0v, h1, l1v)
    rh, rl = add128(rh, rl, h2, l2v)
    nh, nl = neg128(rh, rl)
    return jnp.where(neg, nh, rh), jnp.where(neg, nl, rl)


def divmod128_by_vec(h, l, c):
    """Exact (q_hi, q_lo, remainder) of signed (h, l) by a POSITIVE int64
    vector c (any magnitude up to 2**63-1), truncating toward zero.
    Restoring binary long division over the 128 dividend bits: the running
    remainder stays < c so it fits one int64 plane (unsigned compares via
    the sign-flip trick).  lax.fori_loop keeps the program small."""
    import jax as _jax

    c = jnp.asarray(c, jnp.int64)
    neg = h < 0
    ph, pl = neg128(h, l)
    h_ = jnp.where(neg, ph, h)
    l_ = jnp.where(neg, pl, l)

    def body(i, state):
        rem, qh, ql = state
        bit_idx = 127 - i
        from_hi = bit_idx >= 64
        idx = jnp.where(from_hi, bit_idx - 64, bit_idx)
        word = jnp.where(from_hi, h_, l_)
        bit = (word >> idx) & 1
        rem2 = (rem << 1) | bit  # bit pattern; may exceed 2**63 (unsigned)
        ge = jnp.logical_not(_ult(rem2, c))  # unsigned rem2 >= c
        rem3 = jnp.where(ge, rem2 - c, rem2)
        qbit = ge.astype(jnp.int64)
        qh2 = jnp.where(from_hi, (qh << 1) | qbit, qh)
        ql2 = jnp.where(from_hi, ql, (ql << 1) | qbit)
        return rem3, qh2, ql2

    rem0 = jnp.zeros_like(h_)
    rem, qh, ql = _jax.lax.fori_loop(
        0, 128, body, (rem0, jnp.zeros_like(h_), jnp.zeros_like(l_))
    )
    nqh, nql = neg128(qh, ql)
    return (
        jnp.where(neg, nqh, qh),
        jnp.where(neg, nql, ql),
        jnp.where(neg, -rem, rem),
    )


def truncdiv_pow10(h, l, k: int):
    """(q_hi, q_lo, any_remainder) of truncate-toward-zero division by
    10**k, k >= 0 (stepped through <=10**9 chunks)."""
    any_r = None
    while k > 0:
        step = min(k, 9)
        h, l, r = divmod128_by_u31(h, l, 10**step)
        nz = r != 0
        any_r = nz if any_r is None else jnp.logical_or(any_r, nz)
        k -= step
    if any_r is None:
        any_r = jnp.zeros(jnp.shape(h), dtype=bool)
    return h, l, any_r


def rescale128(h, l, from_scale: int, to_scale: int):
    """Multiply/divide by 10**(to-from) with round-half-away-from-zero on
    downscale (SQL decimal semantics)."""
    if to_scale == from_scale:
        return h, l
    if to_scale > from_scale:
        k = to_scale - from_scale
        while k > 0:
            step = min(k, 9)
            h, l = mul128_by_u32(h, l, 10**step)
            k -= step
        return h, l
    k = from_scale - to_scale
    # divide by 10**k in <=10**9 steps, rounding only on the last step
    while k > 9:
        h, l, _ = divmod128_by_u31(h, l, 10**9)
        k -= 9
    c = 10**k
    q_h, q_l, r = divmod128_by_u31(h, l, c)
    round_up = (2 * jnp.abs(r)) >= c
    sign_neg = is_neg128(h, l)
    bump = round_up.astype(jnp.int64)
    bh, bl = jnp.where(sign_neg, -bump, bump) >> 63, jnp.where(
        sign_neg, -bump, bump
    )
    return add128(q_h, q_l, bh, bl)


def segment_sum128(h, l, gid, num_segments: int, valid=None, hi_direct=False):
    """Exact segmented i128 sum via 32-bit plane sums (each plane sum fits
    i64 for < 2**31 rows), recombined with carries.

    hi_direct: the caller proves |hi| * rows < 2**62 (e.g. from the decimal
    precision bound), so the high limb sums in ONE pass without chunking —
    three segment sums instead of four, and half the mask/shift traffic."""
    if valid is not None:
        h = jnp.where(valid, h, 0)
        l = jnp.where(valid, l, 0)
    l0 = l & _MASK32
    l1 = (l >> 32) & _MASK32
    s_l0 = jax.ops.segment_sum(l0, gid, num_segments)
    s_l1 = jax.ops.segment_sum(l1, gid, num_segments)
    c1 = (s_l0 >> 32) + s_l1  # nonneg
    lo = (s_l0 & _MASK32) | ((c1 & _MASK32) << 32)
    carry = c1 >> 32  # nonneg
    if hi_direct:
        s_h = jax.ops.segment_sum(h, gid, num_segments)
        return s_h + carry, lo
    h0 = h & _MASK32
    h1 = h >> 32  # signed top chunk
    s_h0 = jax.ops.segment_sum(h0, gid, num_segments)
    s_h1 = jax.ops.segment_sum(h1, gid, num_segments)
    c2 = carry + s_h0  # nonneg
    hi = ((s_h1 + (c2 >> 32)) << jnp.int64(32)) | (c2 & _MASK32)
    return hi, lo


#: recombine2/recombine4 are the shared carry recombiners for chunk-plane
#: sums; segment_sum128's inline version above folds the lo-side carry into
#: the hi chunks rather than re-deriving it, so it stays hand-written


def sum128_widened(d, gid, num_segments: int, valid=None):
    """Exact segmented i128 sum of SHORT (int64) inputs: two plane sums."""
    if valid is not None:
        d = jnp.where(valid, d, 0)
    d0 = d & _MASK32  # in [0, 2**32)
    d1 = d >> 32  # signed top chunk in [-2**31, 2**31)
    s0 = jax.ops.segment_sum(d0, gid, num_segments)
    s1 = jax.ops.segment_sum(d1, gid, num_segments)
    return recombine2(s0, s1)


def segment_minmax128(h, l, gid, num_segments: int, valid, is_max: bool):
    """Segmented lexicographic min/max over i128 planes: reduce the high
    limb first, then the low limb among rows matching the winning high."""
    big = jnp.int64(np.iinfo(np.int64).max)
    small = jnp.int64(np.iinfo(np.int64).min)
    lu = l ^ _SIGN  # low limb in signed-comparable (unsigned) order
    if is_max:
        h_m = jnp.where(valid, h, small)
        win_h = jax.ops.segment_max(h_m, gid, num_segments)
        on_win = jnp.logical_and(valid, h == jnp.take(win_h, gid, mode="clip"))
        l_m = jnp.where(on_win, lu, small)
        win_l = jax.ops.segment_max(l_m, gid, num_segments)
    else:
        h_m = jnp.where(valid, h, big)
        win_h = jax.ops.segment_min(h_m, gid, num_segments)
        on_win = jnp.logical_and(valid, h == jnp.take(win_h, gid, mode="clip"))
        l_m = jnp.where(on_win, lu, big)
        win_l = jax.ops.segment_min(l_m, gid, num_segments)
    return win_h, win_l ^ _SIGN


def recombine2(s_lo, s_hi32):
    """(hi, lo) from plane sums of a 32-bit chunk split of SHORT values:
    s_lo = sum of low 32-bit chunks (nonneg), s_hi32 = sum of signed top
    chunks.  Value = s_hi32 * 2**32 + s_lo as i128."""
    a = s_hi32 << 32
    lo = a + s_lo
    carry = _ult(lo, a).astype(jnp.int64)
    return (s_hi32 >> 32) + carry, lo


def recombine4(s_l0, s_l1, s_h0, s_h1):
    """(hi, lo) from the four 32-bit chunk-plane sums of LONG (two-limb)
    values (s_h1 is the signed top chunk)."""
    c1 = (s_l0 >> 32) + s_l1  # nonneg
    lo = (s_l0 & _MASK32) | ((c1 & _MASK32) << 32)
    c2 = (c1 >> 32) + s_h0  # nonneg
    hi = ((s_h1 + (c2 >> 32)) << jnp.int64(32)) | (c2 & _MASK32)
    return hi, lo


def to_float128(h, l):
    """Approximate float64 of the i128 value (for stats/debug only)."""
    lo_u = jnp.where(l < 0, l.astype(jnp.float64) + float(TWO64), l.astype(jnp.float64))
    return h.astype(jnp.float64) * float(TWO64) + lo_u
