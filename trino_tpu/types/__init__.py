"""SQL type system.

Role analog of the reference engine's ``spi/type/Type.java`` hierarchy
(reference: core/trino-spi/src/main/java/io/trino/spi/type/Type.java), but
designed around device representation: every SQL type maps to a fixed-width
numpy/JAX dtype so that whole columns are dense device arrays.  Variable-width
values (VARCHAR/CHAR/VARBINARY) are dictionary-encoded at ingest with
*order-preserving* codes (see columnar.dictionary), so comparisons and sorts on
the device operate on i32 codes directly.

DECIMAL(p, s) with p <= 18 is a scaled i64 ("short decimal"), exactly like the
reference's long-encoded short decimals (spi/type/DecimalType.java) — this keeps
TPC-H money arithmetic in fast integer ops instead of f64.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "Type",
    "BOOLEAN",
    "TINYINT",
    "SMALLINT",
    "INTEGER",
    "BIGINT",
    "REAL",
    "DOUBLE",
    "DATE",
    "TIMESTAMP",
    "TIMESTAMP_TZ",
    "INTERVAL_DAY",
    "parse_time_micros",
    "INTERVAL_YEAR_MONTH",
    "TIME",
    "pack_tz",
    "unpack_tz_millis",
    "unpack_tz_offset",
    "zone_offset_minutes",
    "UNKNOWN",
    "DecimalType",
    "VarcharType",
    "CharType",
    "VarbinaryType",
    "VARCHAR",
    "VARBINARY",
    "ArrayType",
    "MapType",
    "RowType",
    "parse_type",
    "common_super_type",
    "is_numeric",
    "is_integer_kind",
    "is_string_kind",
]


class Type:
    """Base SQL type. Immutable; equality by (name, params)."""

    #: SQL display name, e.g. 'bigint', 'decimal(12,2)'
    name: str = "unknown"
    #: numpy dtype of the device representation
    np_dtype: np.dtype = np.dtype(np.int64)
    #: whether ORDER BY / comparisons are defined
    orderable: bool = True
    comparable: bool = True

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return self.name

    def __eq__(self, other) -> bool:
        return isinstance(other, Type) and self.name == other.name

    def __hash__(self) -> int:
        return hash(self.name)

    @property
    def is_dictionary_encoded(self) -> bool:
        return isinstance(self, (VarcharType, CharType, VarbinaryType))

    def null_device_value(self):
        """Fill value used in device arrays under a null mask."""
        if np.issubdtype(self.np_dtype, np.floating):
            return self.np_dtype.type(0.0)
        if self.np_dtype == np.dtype(bool):
            return False
        return self.np_dtype.type(0)


class _Simple(Type):
    def __init__(self, name: str, np_dtype, orderable: bool = True):
        self.name = name
        self.np_dtype = np.dtype(np_dtype)
        self.orderable = orderable
        self.comparable = True


BOOLEAN = _Simple("boolean", bool)
TINYINT = _Simple("tinyint", np.int8)
SMALLINT = _Simple("smallint", np.int16)
INTEGER = _Simple("integer", np.int32)
BIGINT = _Simple("bigint", np.int64)
REAL = _Simple("real", np.float32)
DOUBLE = _Simple("double", np.float64)
#: days since 1970-01-01, i32 (reference: spi/type/DateType.java)
DATE = _Simple("date", np.int32)
#: microseconds since epoch, i64 (reference: spi/type/TimestampType.java, p=6)
TIMESTAMP = _Simple("timestamp", np.int64)
#: packed UTC-millis + zone offset, i64 (reference: spi/type/
#: TimestampWithTimeZoneType.java + DateTimeEncoding.packDateTimeWithZone:
#: millis << 12 | zoneKey).  Our 12-bit zone key is the fixed UTC offset in
#: minutes biased by +2048 (zone rules are applied host-side when a value is
#: created, so each device value carries the offset that was in force at its
#: instant — rendering and extract are pure device arithmetic).
TIMESTAMP_TZ = _Simple("timestamp with time zone", np.int64)
#: bias/encoding constants for TIMESTAMP_TZ packing
TZ_OFFSET_BIAS = 2048
TZ_SHIFT = 4096  # 12 bits


def pack_tz(utc_millis: int, offset_minutes: int) -> int:
    return utc_millis * TZ_SHIFT + (offset_minutes + TZ_OFFSET_BIAS)


def unpack_tz_millis(packed):
    """UTC instant millis (device-safe: works on arrays)."""
    return packed // TZ_SHIFT


def unpack_tz_offset(packed):
    """Zone offset minutes (device-safe)."""
    return packed % TZ_SHIFT - TZ_OFFSET_BIAS
#: interval day-to-second, microseconds, i64
INTERVAL_DAY = _Simple("interval day to second", np.int64)
#: time of day, microseconds since midnight, i64
#: (reference: spi/type/TimeType.java, p=6 equivalent)
TIME = _Simple("time", np.int64)
#: interval year-to-month, whole months, i64
#: (reference: type/IntervalYearMonthType.java over int months)
INTERVAL_YEAR_MONTH = _Simple("interval year to month", np.int64)


class _Unknown(Type):
    """The type of a bare NULL literal (reference: spi UnknownType)."""

    def __init__(self):
        self.name = "unknown"
        self.np_dtype = np.dtype(np.int64)
        self.orderable = True
        self.comparable = True


UNKNOWN = _Unknown()


class DecimalType(Type):
    """Decimal: scaled i64 for precision <= 18 (short), two-limb i128 planes
    stacked on the last axis ([..., 2] int64: hi, lo-bits) for 19-38 (long).

    Reference: spi/type/DecimalType.java — long-encoded short decimals and
    Int128-encoded long decimals (spi/type/Int128.java); the limb math lives
    in types/int128.py.
    """

    def __init__(self, precision: int = 38, scale: int = 0):
        if precision > 38:
            raise ValueError(f"decimal precision {precision} exceeds 38")
        self.precision = precision
        self.scale = scale
        self.name = f"decimal({precision},{scale})"
        self.np_dtype = np.dtype(np.int64)
        self.orderable = True
        self.comparable = True

    @property
    def is_long(self) -> bool:
        """True when the device representation is two i64 limbs."""
        return self.precision > 18

    @property
    def scale_factor(self) -> int:
        return 10 ** self.scale


class VarcharType(Type):
    """Dictionary-encoded string: device value is an i32 code.

    Codes are *order preserving* within a single dictionary (see
    columnar.dictionary.StringDictionary), so <, >, ORDER BY work on codes when
    both sides share a dictionary; general cross-dictionary comparison re-encodes.
    Reference: spi/type/VarcharType.java.
    """

    UNBOUNDED = 2**31 - 1

    def __init__(self, length: int | None = None):
        self.length = VarcharType.UNBOUNDED if length is None else length
        self.name = (
            "varchar"
            if self.length == VarcharType.UNBOUNDED
            else f"varchar({self.length})"
        )
        self.np_dtype = np.dtype(np.int32)
        self.orderable = True
        self.comparable = True


VARCHAR = VarcharType()


class CharType(Type):
    """CHAR(n); same device representation as varchar (reference: spi/type/CharType.java)."""

    def __init__(self, length: int):
        self.length = length
        self.name = f"char({length})"
        self.np_dtype = np.dtype(np.int32)
        self.orderable = True
        self.comparable = True


class VarbinaryType(Type):
    def __init__(self):
        self.name = "varbinary"
        self.np_dtype = np.dtype(np.int32)
        self.orderable = False
        self.comparable = True


VARBINARY = VarbinaryType()


class ArrayType(Type):
    """Fixed-capacity array-of-T (round-1: host-side only semantics)."""

    def __init__(self, element: Type):
        self.element = element
        self.name = f"array({element.name})"
        self.np_dtype = element.np_dtype
        self.orderable = False
        self.comparable = True


class MapType(Type):
    """map(K, V) in a packed rectangular device layout.

    Reference: spi/type/MapType.java + spi/block/MapBlock.java (keys block +
    values block + per-row offsets).  Device layout: `data` is
    [capacity, 2*K] with keys in slots [0:K] and values in slots [K:2K];
    `lengths` counts entries per row (<= K).  Static shapes keep XLA happy;
    K grows by pow2 buckets at construction.  If both sides are strings they
    share ONE merged dictionary (so a single Column.dictionary covers both
    planes); otherwise the dictionary belongs to whichever side is a string.
    """

    def __init__(self, key: Type, value: Type):
        self.key = key
        self.value = value
        self.name = f"map({key.name}, {value.name})"
        kd, vd = np.dtype(key.np_dtype), np.dtype(value.np_dtype)
        if kd.kind == "f" or vd.kind == "f":
            self.np_dtype = np.dtype(np.float64)
        else:
            self.np_dtype = np.dtype(np.int64)
        self.orderable = False
        self.comparable = True


class RowType(Type):
    def __init__(self, fields: list[tuple[str | None, Type]]):
        self.fields = tuple(fields)
        inner = ", ".join(
            (f"{n} {t.name}" if n else t.name) for n, t in self.fields
        )
        self.name = f"row({inner})"
        self.np_dtype = np.dtype(np.int64)
        self.orderable = False
        self.comparable = True


# ---------------------------------------------------------------------------
# type algebra helpers


def zone_offset_minutes(zone: str, utc_millis: int | None = None) -> int:
    """Resolve a zone name / '+HH:MM' offset to minutes east of UTC.

    Named zones use stdlib zoneinfo when tzdata is present; the offset is
    evaluated at `utc_millis` (DST-correct for that instant), defaulting to
    the current time.  Reference: spi/type/TimeZoneKey.java.
    """
    z = zone.strip()
    if z.upper() in ("UTC", "Z", "GMT"):
        return 0
    if z and z[0] in "+-":
        sign = -1 if z[0] == "-" else 1
        body = z[1:]
        if ":" in body:
            h, m = body.split(":")
        else:
            h, m = body, "0"
        return sign * (int(h) * 60 + int(m or 0))
    import datetime

    try:
        from zoneinfo import ZoneInfo

        tz = ZoneInfo(z)
    except Exception as e:  # no tzdata or unknown zone
        raise ValueError(f"unknown time zone: {zone!r}") from e
    if utc_millis is None:
        dt = datetime.datetime.now(tz)
    else:
        dt = datetime.datetime.fromtimestamp(utc_millis / 1000.0, tz)
    off = dt.utcoffset()
    return int(off.total_seconds() // 60) if off is not None else 0


_SIMPLE_BY_NAME = {
    t.name: t
    for t in (
        BOOLEAN,
        TINYINT,
        SMALLINT,
        INTEGER,
        BIGINT,
        REAL,
        DOUBLE,
        DATE,
        TIMESTAMP,
        TIMESTAMP_TZ,
        TIME,
        INTERVAL_DAY,
        INTERVAL_YEAR_MONTH,
        UNKNOWN,
    )
}
_SIMPLE_BY_NAME["timestamptz"] = TIMESTAMP_TZ
#: the JSON type rides the varchar representation (json path functions
#: parse per dictionary value; reference: spi JsonType over Slice)
_SIMPLE_BY_NAME["json"] = VARCHAR
_SIMPLE_BY_NAME["varchar"] = VARCHAR
_SIMPLE_BY_NAME["varbinary"] = VARBINARY
_SIMPLE_BY_NAME["string"] = VARCHAR  # convenience alias


def parse_time_micros(text: str) -> int:
    """'HH:MM:SS(.fff)?' -> microseconds since midnight, range-checked
    (reference: TimeType parsing rejects out-of-range components)."""
    parts = text.strip().split(":")
    h = int(parts[0]) if parts and parts[0] else 0
    mi = int(parts[1]) if len(parts) > 1 else 0
    sec = float(parts[2]) if len(parts) > 2 else 0.0
    if not (0 <= h < 24 and 0 <= mi < 60 and 0.0 <= sec < 60.0):
        raise ValueError(f"invalid TIME value: {text!r}")
    return (h * 3600 + mi * 60) * 1_000_000 + int(round(sec * 1_000_000))


def parse_type(text: str) -> Type:
    """Parse a SQL type name, e.g. 'decimal(12,2)', 'varchar(25)'."""
    s = text.strip().lower()
    if s.endswith(" without time zone"):
        s = s[: -len(" without time zone")].strip()
    if s in _SIMPLE_BY_NAME:
        return _SIMPLE_BY_NAME[s]
    if s.startswith("decimal"):
        if "(" in s:
            inner = s[s.index("(") + 1 : s.rindex(")")]
            parts = [p.strip() for p in inner.split(",")]
            p = int(parts[0])
            sc = int(parts[1]) if len(parts) > 1 else 0
            return DecimalType(p, sc)
        return DecimalType(38, 0)
    if s.startswith("varchar("):
        return VarcharType(int(s[8:-1]))
    if s.startswith("char("):
        return CharType(int(s[5:-1]))
    if s == "char":
        return CharType(1)
    if s.startswith("array(") or s.startswith("array<"):
        return ArrayType(parse_type(s[6:-1]))
    if s.startswith("map(") or s.startswith("map<"):
        inner = s[4:-1]
        depth = 0
        for i, ch in enumerate(inner):
            if ch in "(<":
                depth += 1
            elif ch in ")>":
                depth -= 1
            elif ch == "," and depth == 0:
                return MapType(parse_type(inner[:i]), parse_type(inner[i + 1:]))
        raise ValueError(f"bad map type: {text!r}")
    raise ValueError(f"unknown type: {text!r}")


_NUMERIC_ORDER = {
    "tinyint": 0,
    "smallint": 1,
    "integer": 2,
    "bigint": 3,
    "real": 5,
    "double": 6,
}


def is_integer_kind(t: Type) -> bool:
    return t.name in ("tinyint", "smallint", "integer", "bigint")


def is_numeric(t: Type) -> bool:
    return t.name in _NUMERIC_ORDER or isinstance(t, DecimalType)


def is_string_kind(t: Type) -> bool:
    return isinstance(t, (VarcharType, CharType))


#: max decimal digits of each integer type (reference: TypeCoercion's
#: bigint-as-decimal(19,0) etc.)
INT_DIGITS = {"tinyint": 3, "smallint": 5, "integer": 10, "bigint": 19}


def common_super_type(a: Type, b: Type) -> Type:
    """Least common type for binary operations / UNION / CASE branches.

    Mirrors the coercion lattice of the reference's TypeCoercion
    (core/trino-main/.../type/TypeCoercion.java), restricted to the types the
    engine implements.
    """
    if a == b:
        return a
    if a == UNKNOWN:
        return b
    if b == UNKNOWN:
        return a
    if is_string_kind(a) and is_string_kind(b):
        return VARCHAR
    da, db = isinstance(a, DecimalType), isinstance(b, DecimalType)
    if da or db:
        if da and db:
            scale = max(a.scale, b.scale)
            intd = max(a.precision - a.scale, b.precision - b.scale)
            return DecimalType(min(intd + scale, 38), scale)
        other = b if da else a
        dec = a if da else b
        if other.name in ("tinyint", "smallint", "integer", "bigint"):
            intd = max(dec.precision - dec.scale, INT_DIGITS[other.name])
            return DecimalType(min(max(intd + dec.scale, 18), 38), dec.scale)
        if other.name in ("real", "double"):
            return DOUBLE
        raise TypeError(f"no common type for {a} and {b}")
    if a.name in _NUMERIC_ORDER and b.name in _NUMERIC_ORDER:
        return a if _NUMERIC_ORDER[a.name] >= _NUMERIC_ORDER[b.name] else b
    if {a.name, b.name} == {"date", "timestamp"}:
        return TIMESTAMP
    if TIMESTAMP_TZ.name in (a.name, b.name) and {a.name, b.name} <= {
        "date",
        "timestamp",
        TIMESTAMP_TZ.name,
    }:
        return TIMESTAMP_TZ
    raise TypeError(f"no common type for {a} and {b}")
