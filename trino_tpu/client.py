"""HTTP protocol client (reference: client/trino-client —
StatementClientV1.java:65; advance() follows nextUri at :334-340)."""

from __future__ import annotations

import json
import time
import urllib.request
from typing import Optional

from trino_tpu.server import protocol


class QueryFailed(RuntimeError):
    def __init__(self, error: dict):
        super().__init__(error.get("message", "query failed"))
        self.error = error


class QueryShed(QueryFailed):
    """The coordinator shed the statement before reading it (HTTP 429:
    resource-group queue full under overload) — RETRYABLE after
    `retry_after_s` (the server's Retry-After header).  Reference:
    StatementClientV1's handling of 429/503 with Retry-After."""

    retryable = True

    def __init__(self, error: dict, retry_after_s: float):
        super().__init__(error)
        self.retry_after_s = retry_after_s


class Client:
    def __init__(self, base_url: str = "http://127.0.0.1:8080"):
        self.base_url = base_url.rstrip("/")

    def _request(self, method: str, path: str, body: Optional[bytes] = None) -> dict:
        from urllib.error import HTTPError

        req = urllib.request.Request(
            self.base_url + path, data=body, method=method
        )
        try:
            with urllib.request.urlopen(req) as resp:
                return json.loads(resp.read().decode())
        except HTTPError as e:
            if e.code == 429:
                try:
                    err = json.loads(e.read().decode()).get("error") or {}
                except (ValueError, OSError):
                    err = {"message": "shed: resource group queue is full"}
                try:
                    retry_after = float(e.headers.get("Retry-After", 1))
                except (TypeError, ValueError):
                    retry_after = 1.0
                raise QueryShed(err, retry_after) from None
            raise

    def execute(self, sql: str, shed_retries: int = 0):
        """Submit and drain a statement; returns (column_names, rows).
        `shed_retries` > 0 re-submits a shed statement after the server's
        Retry-After, up to that many times — the client half of the
        load-shedding contract.  Covers BOTH shed surfaces: the pre-body
        HTTP 429, and the race window where the queue filled between the
        coordinator's probe and the statement thread's enqueue (the query
        then fails through the poll loop with a retryable
        QUERY_QUEUE_FULL error object)."""
        while True:
            try:
                return self._execute_once(sql)
            except QueryShed as e:
                if shed_retries <= 0:
                    raise
                shed_retries -= 1
                time.sleep(e.retry_after_s)

    def _execute_once(self, sql: str):
        out = self._request("POST", "/v1/statement", sql.encode())
        columns: list = []
        rows: list = []
        while True:
            err = out.get("error")
            if err:
                if err.get("errorName") == "QUERY_QUEUE_FULL" or err.get(
                    "retryable"
                ):
                    raise QueryShed(
                        err, float(err.get("retryAfterSeconds") or 1.0)
                    )
                raise QueryFailed(err)
            if "columns" in out:
                columns = out["columns"]
            if "data" in out:
                rows.extend(protocol.decode_rows(out["data"], columns))
            nxt = out.get("nextUri")
            if nxt is None:
                break
            out = self._request("GET", nxt)
        return [c["name"] for c in columns], rows
