"""HTTP protocol client (reference: client/trino-client —
StatementClientV1.java:65; advance() follows nextUri at :334-340)."""

from __future__ import annotations

import json
import time
import urllib.request
from typing import Optional

from trino_tpu.server import protocol


class QueryFailed(RuntimeError):
    def __init__(self, error: dict):
        super().__init__(error.get("message", "query failed"))
        self.error = error


class Client:
    def __init__(self, base_url: str = "http://127.0.0.1:8080"):
        self.base_url = base_url.rstrip("/")

    def _request(self, method: str, path: str, body: Optional[bytes] = None) -> dict:
        req = urllib.request.Request(
            self.base_url + path, data=body, method=method
        )
        with urllib.request.urlopen(req) as resp:
            return json.loads(resp.read().decode())

    def execute(self, sql: str):
        """Submit and drain a statement; returns (column_names, rows)."""
        out = self._request("POST", "/v1/statement", sql.encode())
        columns: list = []
        rows: list = []
        while True:
            if out.get("error"):
                raise QueryFailed(out["error"])
            if "columns" in out:
                columns = out["columns"]
            if "data" in out:
                rows.extend(protocol.decode_rows(out["data"], columns))
            nxt = out.get("nextUri")
            if nxt is None:
                break
            out = self._request("GET", nxt)
        return [c["name"] for c in columns], rows
