"""Concurrent-serving bench probe (the BENCH_EXTRA `serve` section, gated
by tools/compare_bench.py `check_serve`).

The serving contract under measurement: K concurrent clients replaying a
TPC-H mix through the dispatcher (runtime/dispatcher) must

  * all answer the serial oracle's rows (or be counted as errors — the
    gate fails on any),
  * record latency percentiles and queries/sec (the `serve` headline),
  * and, on the MESH path, compile NOTHING once warm: the whole mix is
    traced by one serial warm-up pass, and concurrent serving afterwards
    shares that one trace-cache key set — `warm_compile_events == 0` is
    the shared-trace-cache contract (near-zero marginal compile cost per
    added client), asserted through the compile observatory.

A final `chaos` phase (gated by `check_chaos`) turns fault_tolerant
execution on, kills a worker mid-Q18 while the mix serves concurrently,
and asserts the recovery contract: the killed statement completes from
spooled intermediates with only the lost stage re-run, and zero
mesh-shrink re-plans.

Run standalone (prints one JSON line):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m trino_tpu.bench_serve

or through `bench.py --serve`, which runs it in a sanitized child and
merges the result into BENCH_EXTRA.json's top-level `serve` section.
"""

from __future__ import annotations

import threading
import time


#: the TPC-H mix concurrent clients replay (aggregation, scan-filter,
#: join — the three fragment shapes a dashboard workload cycles through)
MIX_QUERIES = (1, 6, 3)


def _percentile(walls: list, p: float):
    if not walls:
        return None
    i = min(len(walls) - 1, int(p * len(walls)))
    return round(walls[i], 4)


def _serve_once(dispatcher, mix: list, oracle: dict,
                clients: int, rounds: int) -> dict:
    """Drive K client threads through the dispatcher; returns the stats
    block (walls, qps, correctness, shed/queue counters)."""
    from trino_tpu.runtime.dispatcher import QueryShedError

    walls: list = []
    errors: list = []
    mismatches = [0]
    shed = [0]
    lock = threading.Lock()

    def client(i: int) -> None:
        for j in range(rounds):
            sql = mix[(i + j) % len(mix)]
            t0 = time.perf_counter()
            try:
                ticket = dispatcher.enqueue()
                ticket.wait()
                res = dispatcher.run_admitted(
                    ticket, lambda r: r.execute(sql)
                )
            except QueryShedError:
                with lock:
                    shed[0] += 1
                continue
            except Exception as e:  # classified failures fail the gate
                with lock:
                    errors.append(f"{type(e).__name__}: {e}"[:200])
                continue
            wall = time.perf_counter() - t0
            ok = sorted(map(str, res.rows)) == oracle[sql]
            with lock:
                walls.append(wall)
                if not ok:
                    mismatches[0] += 1

    t_start = time.perf_counter()
    threads = [
        threading.Thread(target=client, args=(i,), daemon=True,
                         name=f"serve-client-{i}")
        for i in range(clients)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
    elapsed = time.perf_counter() - t_start
    hung = sum(1 for t in threads if t.is_alive())
    walls.sort()
    groups = {s["name"]: s for s in dispatcher.stats()}
    return {
        "clients": clients,
        "rounds": rounds,
        "lanes": dispatcher.lanes,
        "queries_total": len(walls),
        "qps": round(len(walls) / max(elapsed, 1e-9), 3),
        "wall_s": round(elapsed, 4),
        "p50_s": _percentile(walls, 0.50),
        "p95_s": _percentile(walls, 0.95),
        "p99_s": _percentile(walls, 0.99),
        "shed_total": shed[0],
        "queued_total": groups.get("global", {}).get(
            "dispatcher_queued_total", 0
        ),
        "errors": errors[:5],
        "rows_match": (
            hung == 0
            and mismatches[0] == 0
            and not errors
            and len(walls) + shed[0] == clients * rounds
        ),
    }


def _mix_and_oracle(runner) -> tuple:
    from trino_tpu.connectors.tpch.queries import QUERIES

    mix = [QUERIES[q] for q in MIX_QUERIES]
    oracle = {
        sql: sorted(map(str, runner.execute(sql).rows)) for sql in mix
    }
    return mix, oracle


def _recovery_metrics() -> dict:
    """Point-in-time recovery counter values (chaos evidence is the
    before/after delta): task retries by outcome, spooled fragments,
    spool rehydration reads, and mesh-shrink full re-plans."""
    from trino_tpu.telemetry.metrics import (
        TASK_RETRY_OUTCOMES,
        membership_events_counter,
        mesh_events_counter,
        spooled_fragments_counter,
        task_retries_counter,
    )

    retries = task_retries_counter()
    return {
        "task_retries": {
            o: retries.labels(o).value() for o in TASK_RETRY_OUTCOMES
        },
        "spooled_fragments": spooled_fragments_counter().value(),
        "spool_hits": mesh_events_counter().labels("spool_read").value(),
        "full_replans": membership_events_counter().labels(
            "shrink_replan"
        ).value(),
    }


def _run_chaos(dist, dm, mix: list, oracle: dict, clients: int,
               rounds: int, p99_mesh) -> dict:
    """The `serve.chaos` section: kill a worker mid-Q18 while K clients
    serve the mix concurrently, with fault_tolerant_execution on.  The
    recovery contract under measurement: the killed statement completes
    from spooled intermediates (spool_hits delta > 0), only the lost
    stage re-runs (task_retries.retry >= 1), and the mesh is never
    re-planned for a retryable kill (full_replans delta == 0)."""
    from trino_tpu.connectors.tpch.queries import QUERIES
    from trino_tpu.runtime.retry import FAILURE_INJECTOR, InjectedFailure

    q18 = QUERIES[18]
    dist.properties.set("fault_tolerant_execution", True)
    try:
        # serial oracle + warm-up at the spooled-execution keys
        oracle = dict(oracle)
        oracle[q18] = sorted(map(str, dist.execute(q18).rows))
        mix = [q18] + list(mix)  # client 0 opens with Q18
        base = _recovery_metrics()

        fired = [0]
        orig_fail = FAILURE_INJECTOR.maybe_fail

        def chaos_kill(point: str) -> None:
            # one worker "death" mid-Q18: fires in client 0's FIRST
            # statement (Q18), at the finish hook of a stage whose
            # children already completed and spooled — the retry must
            # resume from those spooled outputs, never re-plan
            if (
                not fired[0]
                and point.startswith("stage:")
                and point.endswith(":finish")
                and not point.startswith("stage:0:")
                and threading.current_thread().name == "serve-client-0"
            ):
                fired[0] += 1
                raise InjectedFailure(f"chaos: worker killed at {point}")
            return orig_fail(point)

        FAILURE_INJECTOR.maybe_fail = chaos_kill
        try:
            chaos = _serve_once(dm, mix, oracle, clients, rounds)
        finally:
            FAILURE_INJECTOR.maybe_fail = orig_fail
        after = _recovery_metrics()
    finally:
        dist.properties.set("fault_tolerant_execution", False)
    chaos["query"] = "Q18"
    chaos["injected_kills"] = fired[0]
    chaos["task_retries"] = {
        o: after["task_retries"][o] - base["task_retries"][o]
        for o in after["task_retries"]
    }
    for key in ("spooled_fragments", "spool_hits", "full_replans"):
        chaos[key] = after[key] - base[key]
    chaos["p99_degradation_ratio"] = (
        round(chaos["p99_s"] / p99_mesh, 3)
        if chaos.get("p99_s") and p99_mesh else None
    )
    return chaos


def run_serve(schema: str = "tiny", clients: int = 8, rounds: int = 3,
              lanes: int = 4) -> dict:
    """The `serve` section: a local concurrent phase (host planning /
    serialization overlap across engine lanes) and a mesh phase (one
    execution lane over the 8-worker device mesh, concurrent admission,
    zero-compile warm serving asserted through the observatory)."""
    from trino_tpu.parallel import DistributedQueryRunner
    from trino_tpu.runtime.dispatcher import QueryDispatcher
    from trino_tpu.runtime.resource_groups import (
        ResourceGroupConfig,
        ResourceGroupManager,
    )
    from trino_tpu.runtime.runner import LocalQueryRunner
    from trino_tpu.telemetry.compile_events import OBSERVATORY

    out: dict = {"schema": schema}

    # -- local lanes phase ----------------------------------------------------
    local = LocalQueryRunner(catalog="tpch", schema=schema, target_splits=8)
    # profile archive riding the serve bench: every concurrently served
    # statement's artifact lands in the store (lanes share it through
    # clone_for_dispatch), and the section records the artifact refs —
    # serving perf is diffable (tools/profile_diff) run-over-run
    import tempfile

    from trino_tpu.telemetry.profile_store import (
        ProfileStore,
        attach_profile_store,
    )

    import os as _os

    archive_dir = _os.environ.get("BENCH_PROFILE_DIR") or _os.path.join(
        tempfile.gettempdir(), "trino_tpu_profile_archive", "serve"
    )
    store = attach_profile_store(
        local, ProfileStore(archive_dir=archive_dir)
    )
    mix, oracle = _mix_and_oracle(local)  # serial warm-up + oracle
    mgr = ResourceGroupManager(
        ResourceGroupConfig(
            "global", hard_concurrency=lanes,
            max_queued=max(16, 2 * clients),
        )
    )
    d = QueryDispatcher(local, mgr, lanes=lanes)
    out["local"] = _serve_once(d, mix, oracle, clients, rounds)
    out["profile_artifacts"] = {
        "archive_dir": archive_dir,
        # a failed flush is recorded: refs to files that never landed
        # must not read as a usable diff baseline
        "flushed": store.flush(),
        "count": len(store.refs()),
        "recent": [
            {k: r[k] for k in ("key", "query_id", "sql_hash")}
            for r in store.refs()[-len(mix):]
        ],
    }

    # -- mesh phase (shared trace cache => zero warm compile events) -----------
    dist = DistributedQueryRunner(n_workers=8, schema=schema)
    mix, oracle = _mix_and_oracle(dist)  # traces every key the mix needs
    # settle speculative-join capacity learning before the watermark: a
    # capacity-learning statement legitimately compiles its fused expand
    # once more on its NEXT run (Q3's key set closes on run 2 — PR 6)
    from trino_tpu.runtime.prewarm import replay_statements

    replay_statements(dist, mix)
    watermark = OBSERVATORY.mark()
    mgr_m = ResourceGroupManager(
        ResourceGroupConfig(
            "global", hard_concurrency=1, max_queued=max(16, 2 * clients)
        )
    )
    dm = QueryDispatcher(dist, mgr_m, lanes=1)  # mesh runner: one lane
    mesh = _serve_once(dm, mix, oracle, clients, rounds)
    mesh["warm_compile_events"] = OBSERVATORY.mark() - watermark
    out["mesh"] = mesh

    # -- chaos phase (task-level fault tolerance under serve load) -------------
    out["chaos"] = _run_chaos(
        dist, dm, mix, oracle, clients, rounds, mesh.get("p99_s")
    )
    return out


def main() -> None:
    import json
    import os

    import jax

    jax.config.update("jax_enable_x64", True)
    schema = os.environ.get("BENCH_SERVE_SCHEMA", "tiny")
    clients = int(os.environ.get("BENCH_SERVE_CLIENTS", 8))
    rounds = int(os.environ.get("BENCH_SERVE_ROUNDS", 3))
    print(json.dumps(run_serve(schema=schema, clients=clients,
                               rounds=rounds)), flush=True)


if __name__ == "__main__":
    main()
