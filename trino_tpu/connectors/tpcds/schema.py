"""TPC-DS schema: 24 tables, column definitions, scaled row counts.

Reference role: the table/column metadata plugin/trino-tpcds exposes
(TpcdsMetadata.java); definitions follow the public TPC-DS specification
(v2.x).  `identifier` columns are bigint surrogate keys; money is
decimal(7,2); business ids are fixed-width strings.
"""

from __future__ import annotations

import math

from trino_tpu import types as T

# compact type aliases used in the declarations below
_SK = "bigint"          # surrogate key
_ID = "varchar(16)"     # business id
_MONEY = "decimal(7,2)"
_QTY = "integer"
_DATE = "date"
_FLAG = "varchar(1)"


TABLES: dict[str, list[tuple[str, str]]] = {
    "store_sales": [
        ("ss_sold_date_sk", _SK), ("ss_sold_time_sk", _SK), ("ss_item_sk", _SK),
        ("ss_customer_sk", _SK), ("ss_cdemo_sk", _SK), ("ss_hdemo_sk", _SK),
        ("ss_addr_sk", _SK), ("ss_store_sk", _SK), ("ss_promo_sk", _SK),
        ("ss_ticket_number", "bigint"), ("ss_quantity", _QTY),
        ("ss_wholesale_cost", _MONEY), ("ss_list_price", _MONEY),
        ("ss_sales_price", _MONEY), ("ss_ext_discount_amt", _MONEY),
        ("ss_ext_sales_price", _MONEY), ("ss_ext_wholesale_cost", _MONEY),
        ("ss_ext_list_price", _MONEY), ("ss_ext_tax", _MONEY),
        ("ss_coupon_amt", _MONEY), ("ss_net_paid", _MONEY),
        ("ss_net_paid_inc_tax", _MONEY), ("ss_net_profit", _MONEY),
    ],
    "store_returns": [
        ("sr_returned_date_sk", _SK), ("sr_return_time_sk", _SK),
        ("sr_item_sk", _SK), ("sr_customer_sk", _SK), ("sr_cdemo_sk", _SK),
        ("sr_hdemo_sk", _SK), ("sr_addr_sk", _SK), ("sr_store_sk", _SK),
        ("sr_reason_sk", _SK), ("sr_ticket_number", "bigint"),
        ("sr_return_quantity", _QTY), ("sr_return_amt", _MONEY),
        ("sr_return_tax", _MONEY), ("sr_return_amt_inc_tax", _MONEY),
        ("sr_fee", _MONEY), ("sr_return_ship_cost", _MONEY),
        ("sr_refunded_cash", _MONEY), ("sr_reversed_charge", _MONEY),
        ("sr_store_credit", _MONEY), ("sr_net_loss", _MONEY),
    ],
    "catalog_sales": [
        ("cs_sold_date_sk", _SK), ("cs_sold_time_sk", _SK),
        ("cs_ship_date_sk", _SK), ("cs_bill_customer_sk", _SK),
        ("cs_bill_cdemo_sk", _SK), ("cs_bill_hdemo_sk", _SK),
        ("cs_bill_addr_sk", _SK), ("cs_ship_customer_sk", _SK),
        ("cs_ship_cdemo_sk", _SK), ("cs_ship_hdemo_sk", _SK),
        ("cs_ship_addr_sk", _SK), ("cs_call_center_sk", _SK),
        ("cs_catalog_page_sk", _SK), ("cs_ship_mode_sk", _SK),
        ("cs_warehouse_sk", _SK), ("cs_item_sk", _SK), ("cs_promo_sk", _SK),
        ("cs_order_number", "bigint"), ("cs_quantity", _QTY),
        ("cs_wholesale_cost", _MONEY), ("cs_list_price", _MONEY),
        ("cs_sales_price", _MONEY), ("cs_ext_discount_amt", _MONEY),
        ("cs_ext_sales_price", _MONEY), ("cs_ext_wholesale_cost", _MONEY),
        ("cs_ext_list_price", _MONEY), ("cs_ext_tax", _MONEY),
        ("cs_coupon_amt", _MONEY), ("cs_ext_ship_cost", _MONEY),
        ("cs_net_paid", _MONEY), ("cs_net_paid_inc_tax", _MONEY),
        ("cs_net_paid_inc_ship", _MONEY), ("cs_net_paid_inc_ship_tax", _MONEY),
        ("cs_net_profit", _MONEY),
    ],
    "catalog_returns": [
        ("cr_returned_date_sk", _SK), ("cr_returned_time_sk", _SK),
        ("cr_item_sk", _SK), ("cr_refunded_customer_sk", _SK),
        ("cr_refunded_cdemo_sk", _SK), ("cr_refunded_hdemo_sk", _SK),
        ("cr_refunded_addr_sk", _SK), ("cr_returning_customer_sk", _SK),
        ("cr_returning_cdemo_sk", _SK), ("cr_returning_hdemo_sk", _SK),
        ("cr_returning_addr_sk", _SK), ("cr_call_center_sk", _SK),
        ("cr_catalog_page_sk", _SK), ("cr_ship_mode_sk", _SK),
        ("cr_warehouse_sk", _SK), ("cr_reason_sk", _SK),
        ("cr_order_number", "bigint"), ("cr_return_quantity", _QTY),
        ("cr_return_amount", _MONEY), ("cr_return_tax", _MONEY),
        ("cr_return_amt_inc_tax", _MONEY), ("cr_fee", _MONEY),
        ("cr_return_ship_cost", _MONEY), ("cr_refunded_cash", _MONEY),
        ("cr_reversed_charge", _MONEY), ("cr_store_credit", _MONEY),
        ("cr_net_loss", _MONEY),
    ],
    "web_sales": [
        ("ws_sold_date_sk", _SK), ("ws_sold_time_sk", _SK),
        ("ws_ship_date_sk", _SK), ("ws_item_sk", _SK),
        ("ws_bill_customer_sk", _SK), ("ws_bill_cdemo_sk", _SK),
        ("ws_bill_hdemo_sk", _SK), ("ws_bill_addr_sk", _SK),
        ("ws_ship_customer_sk", _SK), ("ws_ship_cdemo_sk", _SK),
        ("ws_ship_hdemo_sk", _SK), ("ws_ship_addr_sk", _SK),
        ("ws_web_page_sk", _SK), ("ws_web_site_sk", _SK),
        ("ws_ship_mode_sk", _SK), ("ws_warehouse_sk", _SK),
        ("ws_promo_sk", _SK), ("ws_order_number", "bigint"),
        ("ws_quantity", _QTY), ("ws_wholesale_cost", _MONEY),
        ("ws_list_price", _MONEY), ("ws_sales_price", _MONEY),
        ("ws_ext_discount_amt", _MONEY), ("ws_ext_sales_price", _MONEY),
        ("ws_ext_wholesale_cost", _MONEY), ("ws_ext_list_price", _MONEY),
        ("ws_ext_tax", _MONEY), ("ws_coupon_amt", _MONEY),
        ("ws_ext_ship_cost", _MONEY), ("ws_net_paid", _MONEY),
        ("ws_net_paid_inc_tax", _MONEY), ("ws_net_paid_inc_ship", _MONEY),
        ("ws_net_paid_inc_ship_tax", _MONEY), ("ws_net_profit", _MONEY),
    ],
    "web_returns": [
        ("wr_returned_date_sk", _SK), ("wr_returned_time_sk", _SK),
        ("wr_item_sk", _SK), ("wr_refunded_customer_sk", _SK),
        ("wr_refunded_cdemo_sk", _SK), ("wr_refunded_hdemo_sk", _SK),
        ("wr_refunded_addr_sk", _SK), ("wr_returning_customer_sk", _SK),
        ("wr_returning_cdemo_sk", _SK), ("wr_returning_hdemo_sk", _SK),
        ("wr_returning_addr_sk", _SK), ("wr_web_page_sk", _SK),
        ("wr_reason_sk", _SK), ("wr_order_number", "bigint"),
        ("wr_return_quantity", _QTY), ("wr_return_amt", _MONEY),
        ("wr_return_tax", _MONEY), ("wr_return_amt_inc_tax", _MONEY),
        ("wr_fee", _MONEY), ("wr_return_ship_cost", _MONEY),
        ("wr_refunded_cash", _MONEY), ("wr_reversed_charge", _MONEY),
        ("wr_account_credit", _MONEY), ("wr_net_loss", _MONEY),
    ],
    "inventory": [
        ("inv_date_sk", _SK), ("inv_item_sk", _SK), ("inv_warehouse_sk", _SK),
        ("inv_quantity_on_hand", _QTY),
    ],
    "date_dim": [
        ("d_date_sk", _SK), ("d_date_id", _ID), ("d_date", _DATE),
        ("d_month_seq", "integer"), ("d_week_seq", "integer"),
        ("d_quarter_seq", "integer"), ("d_year", "integer"), ("d_dow", "integer"),
        ("d_moy", "integer"), ("d_dom", "integer"), ("d_qoy", "integer"),
        ("d_fy_year", "integer"), ("d_fy_quarter_seq", "integer"),
        ("d_fy_week_seq", "integer"), ("d_day_name", "varchar(9)"),
        ("d_quarter_name", "varchar(6)"), ("d_holiday", _FLAG),
        ("d_weekend", _FLAG), ("d_following_holiday", _FLAG),
        ("d_first_dom", "integer"), ("d_last_dom", "integer"),
        ("d_same_day_ly", "integer"), ("d_same_day_lq", "integer"),
        ("d_current_day", _FLAG), ("d_current_week", _FLAG),
        ("d_current_month", _FLAG), ("d_current_quarter", _FLAG),
        ("d_current_year", _FLAG),
    ],
    "time_dim": [
        ("t_time_sk", _SK), ("t_time_id", _ID), ("t_time", "integer"),
        ("t_hour", "integer"), ("t_minute", "integer"), ("t_second", "integer"),
        ("t_am_pm", "varchar(2)"), ("t_shift", "varchar(20)"),
        ("t_sub_shift", "varchar(20)"), ("t_meal_time", "varchar(20)"),
    ],
    "item": [
        ("i_item_sk", _SK), ("i_item_id", _ID), ("i_rec_start_date", _DATE),
        ("i_rec_end_date", _DATE), ("i_item_desc", "varchar(200)"),
        ("i_current_price", _MONEY), ("i_wholesale_cost", _MONEY),
        ("i_brand_id", "integer"), ("i_brand", "varchar(50)"),
        ("i_class_id", "integer"), ("i_class", "varchar(50)"),
        ("i_category_id", "integer"), ("i_category", "varchar(50)"),
        ("i_manufact_id", "integer"), ("i_manufact", "varchar(50)"),
        ("i_size", "varchar(20)"), ("i_formulation", "varchar(20)"),
        ("i_color", "varchar(20)"), ("i_units", "varchar(10)"),
        ("i_container", "varchar(10)"), ("i_manager_id", "integer"),
        ("i_product_name", "varchar(50)"),
    ],
    "customer": [
        ("c_customer_sk", _SK), ("c_customer_id", _ID),
        ("c_current_cdemo_sk", _SK), ("c_current_hdemo_sk", _SK),
        ("c_current_addr_sk", _SK), ("c_first_shipto_date_sk", _SK),
        ("c_first_sales_date_sk", _SK), ("c_salutation", "varchar(10)"),
        ("c_first_name", "varchar(20)"), ("c_last_name", "varchar(30)"),
        ("c_preferred_cust_flag", _FLAG), ("c_birth_day", "integer"),
        ("c_birth_month", "integer"), ("c_birth_year", "integer"),
        ("c_birth_country", "varchar(20)"), ("c_login", "varchar(13)"),
        ("c_email_address", "varchar(50)"), ("c_last_review_date_sk", _SK),
    ],
    "customer_address": [
        ("ca_address_sk", _SK), ("ca_address_id", _ID),
        ("ca_street_number", "varchar(10)"), ("ca_street_name", "varchar(60)"),
        ("ca_street_type", "varchar(15)"), ("ca_suite_number", "varchar(10)"),
        ("ca_city", "varchar(60)"), ("ca_county", "varchar(30)"),
        ("ca_state", "varchar(2)"), ("ca_zip", "varchar(10)"),
        ("ca_country", "varchar(20)"), ("ca_gmt_offset", "decimal(5,2)"),
        ("ca_location_type", "varchar(20)"),
    ],
    "customer_demographics": [
        ("cd_demo_sk", _SK), ("cd_gender", _FLAG),
        ("cd_marital_status", _FLAG), ("cd_education_status", "varchar(20)"),
        ("cd_purchase_estimate", "integer"), ("cd_credit_rating", "varchar(10)"),
        ("cd_dep_count", "integer"), ("cd_dep_employed_count", "integer"),
        ("cd_dep_college_count", "integer"),
    ],
    "household_demographics": [
        ("hd_demo_sk", _SK), ("hd_income_band_sk", _SK),
        ("hd_buy_potential", "varchar(15)"), ("hd_dep_count", "integer"),
        ("hd_vehicle_count", "integer"),
    ],
    "income_band": [
        ("ib_income_band_sk", _SK), ("ib_lower_bound", "integer"),
        ("ib_upper_bound", "integer"),
    ],
    "promotion": [
        ("p_promo_sk", _SK), ("p_promo_id", _ID), ("p_start_date_sk", _SK),
        ("p_end_date_sk", _SK), ("p_item_sk", _SK), ("p_cost", "decimal(15,2)"),
        ("p_response_target", "integer"), ("p_promo_name", "varchar(50)"),
        ("p_channel_dmail", _FLAG), ("p_channel_email", _FLAG),
        ("p_channel_catalog", _FLAG), ("p_channel_tv", _FLAG),
        ("p_channel_radio", _FLAG), ("p_channel_press", _FLAG),
        ("p_channel_event", _FLAG), ("p_channel_demo", _FLAG),
        ("p_channel_details", "varchar(100)"), ("p_purpose", "varchar(15)"),
        ("p_discount_active", _FLAG),
    ],
    "reason": [
        ("r_reason_sk", _SK), ("r_reason_id", _ID),
        ("r_reason_desc", "varchar(100)"),
    ],
    "ship_mode": [
        ("sm_ship_mode_sk", _SK), ("sm_ship_mode_id", _ID),
        ("sm_type", "varchar(30)"), ("sm_code", "varchar(10)"),
        ("sm_carrier", "varchar(20)"), ("sm_contract", "varchar(20)"),
    ],
    "store": [
        ("s_store_sk", _SK), ("s_store_id", _ID), ("s_rec_start_date", _DATE),
        ("s_rec_end_date", _DATE), ("s_closed_date_sk", _SK),
        ("s_store_name", "varchar(50)"), ("s_number_employees", "integer"),
        ("s_floor_space", "integer"), ("s_hours", "varchar(20)"),
        ("s_manager", "varchar(40)"), ("s_market_id", "integer"),
        ("s_geography_class", "varchar(100)"), ("s_market_desc", "varchar(100)"),
        ("s_market_manager", "varchar(40)"), ("s_division_id", "integer"),
        ("s_division_name", "varchar(50)"), ("s_company_id", "integer"),
        ("s_company_name", "varchar(50)"), ("s_street_number", "varchar(10)"),
        ("s_street_name", "varchar(60)"), ("s_street_type", "varchar(15)"),
        ("s_suite_number", "varchar(10)"), ("s_city", "varchar(60)"),
        ("s_county", "varchar(30)"), ("s_state", "varchar(2)"),
        ("s_zip", "varchar(10)"), ("s_country", "varchar(20)"),
        ("s_gmt_offset", "decimal(5,2)"), ("s_tax_precentage", "decimal(5,2)"),
    ],
    "call_center": [
        ("cc_call_center_sk", _SK), ("cc_call_center_id", _ID),
        ("cc_rec_start_date", _DATE), ("cc_rec_end_date", _DATE),
        ("cc_closed_date_sk", _SK), ("cc_open_date_sk", _SK),
        ("cc_name", "varchar(50)"), ("cc_class", "varchar(50)"),
        ("cc_employees", "integer"), ("cc_sq_ft", "integer"),
        ("cc_hours", "varchar(20)"), ("cc_manager", "varchar(40)"),
        ("cc_mkt_id", "integer"), ("cc_mkt_class", "varchar(50)"),
        ("cc_mkt_desc", "varchar(100)"), ("cc_market_manager", "varchar(40)"),
        ("cc_division", "integer"), ("cc_division_name", "varchar(50)"),
        ("cc_company", "integer"), ("cc_company_name", "varchar(50)"),
        ("cc_street_number", "varchar(10)"), ("cc_street_name", "varchar(60)"),
        ("cc_street_type", "varchar(15)"), ("cc_suite_number", "varchar(10)"),
        ("cc_city", "varchar(60)"), ("cc_county", "varchar(30)"),
        ("cc_state", "varchar(2)"), ("cc_zip", "varchar(10)"),
        ("cc_country", "varchar(20)"), ("cc_gmt_offset", "decimal(5,2)"),
        ("cc_tax_percentage", "decimal(5,2)"),
    ],
    "catalog_page": [
        ("cp_catalog_page_sk", _SK), ("cp_catalog_page_id", _ID),
        ("cp_start_date_sk", _SK), ("cp_end_date_sk", _SK),
        ("cp_department", "varchar(50)"), ("cp_catalog_number", "integer"),
        ("cp_catalog_page_number", "integer"), ("cp_description", "varchar(100)"),
        ("cp_type", "varchar(100)"),
    ],
    "warehouse": [
        ("w_warehouse_sk", _SK), ("w_warehouse_id", _ID),
        ("w_warehouse_name", "varchar(20)"), ("w_warehouse_sq_ft", "integer"),
        ("w_street_number", "varchar(10)"), ("w_street_name", "varchar(60)"),
        ("w_street_type", "varchar(15)"), ("w_suite_number", "varchar(10)"),
        ("w_city", "varchar(60)"), ("w_county", "varchar(30)"),
        ("w_state", "varchar(2)"), ("w_zip", "varchar(10)"),
        ("w_country", "varchar(20)"), ("w_gmt_offset", "decimal(5,2)"),
    ],
    "web_page": [
        ("wp_web_page_sk", _SK), ("wp_web_page_id", _ID),
        ("wp_rec_start_date", _DATE), ("wp_rec_end_date", _DATE),
        ("wp_creation_date_sk", _SK), ("wp_access_date_sk", _SK),
        ("wp_autogen_flag", _FLAG), ("wp_customer_sk", _SK),
        ("wp_url", "varchar(100)"), ("wp_type", "varchar(50)"),
        ("wp_char_count", "integer"), ("wp_link_count", "integer"),
        ("wp_image_count", "integer"), ("wp_max_ad_count", "integer"),
    ],
    "web_site": [
        ("web_site_sk", _SK), ("web_site_id", _ID),
        ("web_rec_start_date", _DATE), ("web_rec_end_date", _DATE),
        ("web_name", "varchar(50)"), ("web_open_date_sk", _SK),
        ("web_close_date_sk", _SK), ("web_class", "varchar(50)"),
        ("web_manager", "varchar(40)"), ("web_mkt_id", "integer"),
        ("web_mkt_class", "varchar(50)"), ("web_mkt_desc", "varchar(100)"),
        ("web_market_manager", "varchar(40)"), ("web_company_id", "integer"),
        ("web_company_name", "varchar(50)"), ("web_street_number", "varchar(10)"),
        ("web_street_name", "varchar(60)"), ("web_street_type", "varchar(15)"),
        ("web_suite_number", "varchar(10)"), ("web_city", "varchar(60)"),
        ("web_county", "varchar(30)"), ("web_state", "varchar(2)"),
        ("web_zip", "varchar(10)"), ("web_country", "varchar(20)"),
        ("web_gmt_offset", "decimal(5,2)"), ("web_tax_percentage", "decimal(5,2)"),
    ],
}

#: SF1 row counts from the spec; facts scale linearly, starred dimensions are
#: fixed regardless of SF (the spec scales them in coarse steps; fixed is the
#: SF1 value)
SF1_ROWS = {
    "store_sales": 2_880_404,
    "store_returns": 287_514,
    "catalog_sales": 1_441_548,
    "catalog_returns": 144_067,
    "web_sales": 719_384,
    "web_returns": 71_763,
    "inventory": 11_745_000,
    "customer": 100_000,
    "customer_address": 50_000,
    "item": 18_000,
    "catalog_page": 11_718,
    "web_page": 60,
    "web_site": 30,
    "store": 12,
    "call_center": 6,
    "warehouse": 5,
    "promotion": 300,
    "reason": 35,
    "ship_mode": 20,
    "income_band": 20,
    "household_demographics": 7_200,
    "customer_demographics": 1_920_800,
    "date_dim": 73_049,
    "time_dim": 86_400,
}

_FIXED = {
    "date_dim", "time_dim", "income_band", "household_demographics",
    "customer_demographics", "ship_mode", "reason",
}
_SLOW = {  # dimensions that grow sub-linearly with SF (sqrt here)
    "customer", "customer_address", "item", "catalog_page", "web_page",
    "web_site", "store", "call_center", "warehouse", "promotion",
}


def scaled_rows(table: str, sf: float) -> int:
    base = SF1_ROWS[table]
    if table in _FIXED:
        return base
    if table in _SLOW:
        return max(2, int(base * math.sqrt(min(sf, 1.0)) if sf < 1 else base * math.sqrt(sf)))
    return max(1, int(base * sf))


SCHEMAS = {"tiny": 0.01, "sf1": 1.0, "sf10": 10.0, "sf100": 100.0}


def schema_scale(schema: str) -> float:
    if schema in SCHEMAS:
        return SCHEMAS[schema]
    if schema.startswith("sf"):
        try:
            return float(schema[2:].replace("_", "."))
        except ValueError:
            pass
    raise KeyError(f"unknown tpcds schema: {schema}")


def column_types(table: str):
    return [(name, T.parse_type(t)) for name, t in TABLES[table]]
