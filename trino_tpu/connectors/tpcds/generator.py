"""Vectorized, counter-based TPC-DS data generator.

Reference role: the dsdgen port behind plugin/trino-tpcds (TpcdsRecordSet).
Same design as the tpch generator: every value is a pure function of
(table, column, row index) via splitmix64 — any split generates
independently in O(rows) numpy.  Spec-shaped where queries depend on it:
surrogate-key structure (1-based, julian-day date_dim keys), FK consistency,
the sales calendar (1998-2002), the demographics cross-products, fixed
vocabularies (categories, day names, buy potentials), and sales<->returns
linkage (every return row copies its parent sale's item/ticket keys).
Value *distributions* are uniform rather than dsdgen's — documented
divergence; correctness is checked against the pandas oracle over the same
data.
"""

from __future__ import annotations

import datetime
from functools import lru_cache

import numpy as np

from trino_tpu import types as T
from trino_tpu.columnar.dictionary import PatternDictionary, StringDictionary
from trino_tpu.connectors.api import ColumnData
from trino_tpu.connectors.tpcds.schema import TABLES, column_types, scaled_rows
from trino_tpu.connectors.tpch.generator import randint, _rand64

# julian day number of 1900-01-01: date_dim's first d_date_sk (spec value)
JULIAN_1900 = 2_415_022
_D1900 = datetime.date(1900, 1, 1)
_EPOCH = datetime.date(1970, 1, 1)

#: sales calendar: the window fact sold-date keys draw from (5 years)
SALES_START = JULIAN_1900 + (datetime.date(1998, 1, 2) - _D1900).days
SALES_DAYS = 365 * 5

#: inclusive randint bounds for `_generic`'s fallthrough numeric/date
#: columns.  `column_range` publishes these same tuples as EXACT range
#: claims consumed by the numeric/capacity verifiers — one definition,
#: so the generated values and the claims can never desync.
GENERIC_DECIMAL_SHORT = (0, 100_00)  # precision <= 7, scaled units
GENERIC_DECIMAL_LONG = (0, 1000_00)
GENERIC_INTEGER = (1, 100)
GENERIC_BIGINT = (1, 1000)
#: epoch-day window generic DATE columns draw from
GENERIC_DATE_BASE = (datetime.date(1998, 1, 2) - _EPOCH).days
GENERIC_DATE = (GENERIC_DATE_BASE, GENERIC_DATE_BASE + SALES_DAYS)

# -- fixed vocabularies (spec-visible values queries filter on) --------------

CATEGORIES = (
    "Books", "Children", "Electronics", "Home", "Jewelry",
    "Men", "Music", "Shoes", "Sports", "Women",
)
EDUCATION = (
    "2 yr Degree", "4 yr Degree", "Advanced Degree", "College",
    "Primary", "Secondary", "Unknown",
)
MARITAL = ("D", "M", "S", "U", "W")
CREDIT_RATING = ("Good", "High Risk", "Low Risk", "Unknown")
BUY_POTENTIAL = (">10000", "0-500", "1001-5000", "10001-20000", "501-1000", "Unknown")
DAY_NAMES = ("Sunday", "Monday", "Tuesday", "Wednesday", "Thursday", "Friday", "Saturday")
STORE_NAMES = ("able", "anti", "ation", "bar", "cally", "eing", "ese", "n st", "ought", "pri")
SIZES = ("N/A", "economy", "extra large", "large", "medium", "petite", "small")
UNITS = ("Box", "Bunch", "Bundle", "Carton", "Case", "Dozen", "Each", "Gram",
         "Gross", "Lb", "N/A", "Ounce", "Oz", "Pallet", "Pound", "Tbl", "Ton", "Unknown")
CONTAINERS = ("Unknown",)
STATES = ("AL", "AR", "AZ", "CA", "CO", "FL", "GA", "IA", "IL", "IN", "KS",
          "KY", "LA", "MI", "MN", "MO", "MS", "NC", "ND", "NE", "NY", "OH",
          "OK", "SC", "SD", "TN", "TX", "VA", "WA", "WI")
CITIES = ("Antioch", "Bethel", "Centerville", "Clifton", "Concord", "Edgewood",
          "Fairview", "Five Points", "Glendale", "Greenfield", "Greenville",
          "Jamestown", "Lakeside", "Lakeview", "Lebanon", "Liberty", "Midway",
          "Mount Olive", "Mount Zion", "Oak Grove", "Oak Hill", "Oakdale",
          "Oakland", "Pleasant Grove", "Pleasant Hill", "Riverdale",
          "Riverside", "Salem", "Shiloh", "Springdale", "Springfield",
          "Sulphur Springs", "Union", "Unionville", "Walnut Grove",
          "White Oak", "Wildwood", "Woodland", "Woodville")
COUNTIES = ("Barrow County", "Bronx County", "Daviess County", "Fairfield County",
            "Franklin Parish", "Huron County", "Luce County", "Mobile County",
            "Richland County", "Walker County", "Williamson County", "Ziebach County")
STREET_NAMES = ("1st", "2nd", "3rd", "4th", "5th", "6th", "7th", "8th", "9th",
                "10th", "Adams", "Birch", "Broadway", "Cedar", "Center", "Cherry",
                "Chestnut", "Church", "College", "Davis", "Dogwood", "East",
                "Elm", "First", "Forest", "Fourth", "Franklin", "Green", "Highland",
                "Hill", "Hillcrest", "Jackson", "Jefferson", "Johnson", "Lake",
                "Laurel", "Lee", "Lincoln", "Locust", "Madison", "Main", "Maple",
                "Meadow", "Mill", "Miller", "North", "Oak", "Park", "Pine",
                "Poplar", "Railroad", "Ridge", "River", "Second", "Sixth",
                "Smith", "South", "Spring", "Spruce", "Sunset", "Sycamore",
                "Third", "Valley", "View", "Walnut", "Washington", "West",
                "Williams", "Willow", "Wilson", "Woodland")
STREET_TYPES = ("Ave", "Avenue", "Blvd", "Boulevard", "Circle", "Court", "Ct",
                "Dr", "Drive", "Lane", "Ln", "Parkway", "Pkwy", "RD", "Rd",
                "Road", "ST", "St", "Street", "Way", "Wy")
FIRST_NAMES = ("Aaron", "Alice", "Amy", "Anna", "Anthony", "Barbara", "Betty",
               "Brian", "Carol", "Charles", "Christopher", "Daniel", "David",
               "Donald", "Donna", "Dorothy", "Edward", "Elizabeth", "Emily",
               "Eric", "George", "Helen", "James", "Jason", "Jennifer", "Jerry",
               "Jessica", "John", "Jose", "Joseph", "Karen", "Kenneth", "Kevin",
               "Kimberly", "Larry", "Laura", "Linda", "Lisa", "Margaret",
               "Maria", "Mark", "Mary", "Matthew", "Melissa", "Michael",
               "Michelle", "Nancy", "Patricia", "Paul", "Rachel", "Raymond",
               "Richard", "Robert", "Ronald", "Ruth", "Sandra", "Sarah",
               "Scott", "Sharon", "Stephen", "Steven", "Susan", "Thomas",
               "Timothy", "Virginia", "William")
LAST_NAMES = ("Adams", "Allen", "Anderson", "Bailey", "Baker", "Bell", "Brooks",
              "Brown", "Campbell", "Carter", "Clark", "Collins", "Cook",
              "Cooper", "Cox", "Davis", "Edwards", "Evans", "Foster", "Garcia",
              "Gonzalez", "Gray", "Green", "Hall", "Harris", "Henderson",
              "Hernandez", "Hill", "Howard", "Hughes", "Jackson", "James",
              "Jenkins", "Johnson", "Jones", "Kelly", "King", "Lee", "Lewis",
              "Long", "Lopez", "Martin", "Martinez", "Miller", "Mitchell",
              "Moore", "Morgan", "Morris", "Murphy", "Nelson", "Parker",
              "Perez", "Perry", "Peterson", "Phillips", "Powell", "Price",
              "Ramirez", "Reed", "Richardson", "Rivera", "Roberts", "Robinson",
              "Rodriguez", "Rogers", "Ross", "Russell", "Sanchez", "Sanders",
              "Scott", "Simmons", "Smith", "Stewart", "Taylor", "Thomas",
              "Thompson", "Torres", "Turner", "Walker", "Ward", "Washington",
              "Watson", "White", "Williams", "Wilson", "Wood", "Wright", "Young")
SALUTATIONS = ("Dr.", "Miss", "Mr.", "Mrs.", "Ms.", "Sir")
SHIFT = ("first", "second", "third")
MEAL = ("breakfast", "dinner", "lunch")
LOCATION_TYPES = ("apartment", "condo", "single family")
SHIP_TYPES = ("EXPRESS", "LIBRARY", "NEXT DAY", "OVERNIGHT", "REGULAR", "TWO DAY")
SHIP_CARRIERS = ("AIRBORNE", "ALLIANCE", "BARIAN", "BOXBUNDLES", "DHL", "DIAMOND",
                 "FEDEX", "GERMA", "GREAT EASTERN", "HARMSTORF", "LATVIAN", "MSC",
                 "ORIENTAL", "PRIVATECARRIER", "RUPEKSA", "TBS", "UPS", "USPS",
                 "ZHOU", "ZOUROS")


def _dict(values) -> StringDictionary:
    return StringDictionary(tuple(sorted(set(values))))


def _codes(d: StringDictionary, values, stream: str, idx) -> np.ndarray:
    """Random code column over an (unsorted) conceptual value list, mapped to
    the sorted dictionary's codes."""
    order = {v: i for i, v in enumerate(d.values)}
    lut = np.array([order[v] for v in values], dtype=np.int32)
    return lut[randint(stream, idx, 0, len(values) - 1)]


@lru_cache(maxsize=64)
def _pat(prefix: str, width: int, n: int) -> PatternDictionary:
    def fn(i: int) -> str:
        return f"{prefix}{i + 1:0{width}d}"

    return PatternDictionary(fn, n, (prefix, width))


# -- FK domains by column-name suffix ---------------------------------------

_FK_SUFFIX = [
    ("_item_sk", "item"),
    ("_customer_sk", "customer"),
    ("_cdemo_sk", "customer_demographics"),
    ("_hdemo_sk", "household_demographics"),
    ("_addr_sk", "customer_address"),
    ("_store_sk", "store"),
    ("_promo_sk", "promotion"),
    ("_call_center_sk", "call_center"),
    ("_catalog_page_sk", "catalog_page"),
    ("_ship_mode_sk", "ship_mode"),
    ("_warehouse_sk", "warehouse"),
    ("_web_page_sk", "web_page"),
    ("_web_site_sk", "web_site"),
    ("_reason_sk", "reason"),
    ("_income_band_sk", "income_band"),
]

_FACTS = {
    "store_sales", "store_returns", "catalog_sales", "catalog_returns",
    "web_sales", "web_returns", "inventory",
}

#: returns table -> (sales table, per-sale prefix mapping)
_RETURN_PARENT = {
    "store_returns": ("store_sales", "ss", "sr"),
    "catalog_returns": ("catalog_sales", "cs", "cr"),
    "web_returns": ("web_sales", "ws", "wr"),
}


class TpcdsGenerator:
    def __init__(self, sf: float):
        self.sf = sf

    def row_count(self, table: str) -> int:
        return scaled_rows(table, self.sf)

    # -- public: one column for a row range ----------------------------------

    def column(self, table: str, col: str, start: int, count: int) -> ColumnData:
        idx = np.arange(start, start + count, dtype=np.int64)
        t = dict(column_types(table))[col]
        special = getattr(self, f"_t_{table}", None)
        if special is not None:
            out = special(col, idx, t)
            if out is not None:
                return out
        return self._generic(table, col, idx, t)

    def dictionary(self, table: str, col: str):
        """Global dictionary for a string column (trace-stable across splits)."""
        cd = self.column(table, col, 0, 1)
        return cd.dictionary

    def column_range(self, table: str, col: str):
        """Exact (low, high) LOGICAL-unit value range of a GENERICALLY
        generated column, or None when no sound claim exists.  Mirrors
        `column()`'s dispatch: a column a `_t_<table>` special handles
        makes no generic claim (probed with one row — cheap, and exact
        because the dispatch is per-column, not per-row).  These ranges
        are the generator's own rules (randint bounds are inclusive), so
        they are admissible proof sources for the numeric/capacity
        verifiers — the same standing as the key-range stats above."""
        t = dict(column_types(table))[col]
        special = getattr(self, f"_t_{table}", None)
        if special is not None:
            try:
                if special(col, np.arange(1, dtype=np.int64), t) is not None:
                    return None
            except Exception:
                return None
        if col.endswith(("_sk", "_id")):
            return None  # key columns: explicit stats rules in the connector
        for suffix, _ref in _FK_SUFFIX:
            if col.endswith(suffix):
                return None
        if isinstance(t, T.DecimalType):
            lo, hi = (
                GENERIC_DECIMAL_SHORT if t.precision <= 7
                else GENERIC_DECIMAL_LONG
            )
            return (lo, hi / t.scale_factor)
        if t.name == "integer":
            return GENERIC_INTEGER
        if t.name == "bigint":
            return GENERIC_BIGINT
        if t is T.DATE:
            return GENERIC_DATE
        return None

    # -- generic rules --------------------------------------------------------

    def _generic(self, table: str, col: str, idx, t) -> ColumnData:
        stream = f"{table}.{col}"
        n = self.row_count(table)
        # primary surrogate key: 1-based row number
        if col.endswith("_sk") and self._is_primary_key(table, col):
            return ColumnData(idx + 1, None)
        if col.endswith("_date_sk"):
            return self._date_fk(table, stream, idx)
        if col.endswith("_time_sk"):
            vals = randint(stream, idx, 0, 86_399)
            return self._nullable(stream, vals, table, idx)
        for suffix, ref in _FK_SUFFIX:
            if col.endswith(suffix):
                vals = randint(stream, idx, 1, self.row_count(ref))
                return self._nullable(stream, vals, table, idx)
        if col.endswith("_id") and T.is_string_kind(t):
            # business identifiers are strings (e.g. i_item_id); integer
            # *_id columns (s_market_id, s_division_id) fall through to the
            # numeric branches below
            prefix = col[: col.index("_")].upper() + "-"
            d = _pat(prefix, 12, max(n, 1))
            return ColumnData(idx.astype(np.int32), None, d)
        if isinstance(t, T.DecimalType):
            lo, hi = (
                GENERIC_DECIMAL_SHORT if t.precision <= 7
                else GENERIC_DECIMAL_LONG
            )
            return ColumnData(randint(stream, idx, lo, hi), None)
        if t.name == "integer":
            return ColumnData(
                randint(stream, idx, *GENERIC_INTEGER).astype(np.int32), None
            )
        if t.name == "bigint":
            return ColumnData(randint(stream, idx, *GENERIC_BIGINT), None)
        if t is T.DATE:
            return ColumnData(
                randint(
                    stream, idx, GENERIC_DATE[0], GENERIC_DATE[1]
                ).astype(np.int32),
                None,
            )
        if T.is_string_kind(t):
            if col.endswith(("_flag", "_active")) or t.name == "varchar(1)":
                d = _dict(["N", "Y"])
                return ColumnData(_codes(d, ["N", "Y", "N", "N"], stream, idx), None, d)
            d = _dict([f"{col.split('_')[-1]}{i}" for i in range(16)])
            return ColumnData(
                randint(stream, idx, 0, len(d.values) - 1).astype(np.int32), None, d
            )
        raise NotImplementedError(f"tpcds generic column {table}.{col}: {t.name}")

    def _is_primary_key(self, table: str, col: str) -> bool:
        # dimension tables lead with their surrogate key; fact tables have no
        # surrogate PK (their leading *_sk columns are FKs, e.g.
        # ss_sold_date_sk)
        return table not in _FACTS and TABLES[table][0][0] == col

    def _nullable(self, stream: str, vals, table: str, idx, pct: int = 25):
        """Fact-table FKs are ~4% NULL (spec allows nulls in fact FKs).
        The null stream MUST be driven by the global row index `idx`, never a
        slice-local arange — generated data has to be identical under any
        split slicing (round-3 fix: multi-split scans produced different
        masks than single-split scans)."""
        if table not in _FACTS:
            return ColumnData(vals, None)
        valid = randint(stream + ".null", idx + vals, 0, pct) != 0
        return ColumnData(vals, valid)

    def _date_fk(self, table: str, stream: str, idx) -> ColumnData:
        vals = SALES_START + randint(stream, idx, 0, SALES_DAYS - 1)
        return self._nullable(stream, vals, table, idx)

    # -- calendar dimensions --------------------------------------------------

    def _t_date_dim(self, col, idx, t):
        dates = np.datetime64("1900-01-01") + idx.astype("timedelta64[D]")
        # datetime64 integer epochs are 1970-based
        years = dates.astype("datetime64[Y]").astype(np.int64) + 1970
        months0 = dates.astype("datetime64[M]").astype(np.int64) + 70 * 12  # since 1900-01
        moy = months0 % 12 + 1
        dom = (dates - dates.astype("datetime64[M]")).astype(np.int64) + 1
        dow = (idx + 1) % 7  # 1900-01-01 was a Monday; 0=Sunday
        if col == "d_date_sk":
            return ColumnData(idx + JULIAN_1900, None)
        if col == "d_date":
            days70 = (_D1900 - _EPOCH).days
            return ColumnData((idx + days70).astype(np.int32), None)
        if col == "d_year" or col == "d_fy_year":
            return ColumnData(years.astype(np.int32), None)
        if col == "d_moy":
            return ColumnData(moy.astype(np.int32), None)
        if col == "d_dom":
            return ColumnData(dom.astype(np.int32), None)
        if col == "d_dow":
            return ColumnData(dow.astype(np.int32), None)
        if col == "d_month_seq":
            return ColumnData(months0.astype(np.int32), None)
        if col in ("d_week_seq", "d_fy_week_seq"):
            return ColumnData(((idx + 1) // 7 + 1).astype(np.int32), None)
        if col in ("d_quarter_seq", "d_fy_quarter_seq"):
            return ColumnData((months0 // 3 + 1).astype(np.int32), None)
        if col == "d_qoy":
            return ColumnData(((moy - 1) // 3 + 1).astype(np.int32), None)
        if col == "d_day_name":
            d = _dict(DAY_NAMES)
            order = np.array([d.index[v] for v in DAY_NAMES], np.int32)
            return ColumnData(order[dow], None, d)
        if col == "d_quarter_name":
            names = [f"{y}Q{q}" for y in range(1900, 2101) for q in range(1, 5)]
            d = _dict(names)
            qidx = (years - 1900) * 4 + (moy - 1) // 3
            order = np.array([d.index[v] for v in names], np.int32)
            return ColumnData(order[qidx], None, d)
        if col in ("d_holiday", "d_following_holiday", "d_current_day",
                   "d_current_week", "d_current_month", "d_current_quarter",
                   "d_current_year"):
            d = _dict(["N", "Y"])
            return ColumnData(np.full(len(idx), d.index["N"], np.int32), None, d)
        if col == "d_weekend":
            d = _dict(["N", "Y"])
            wk = np.where((dow == 0) | (dow == 6), d.index["Y"], d.index["N"])
            return ColumnData(wk.astype(np.int32), None, d)
        if col == "d_first_dom":
            first = dates.astype("datetime64[M]").astype("datetime64[D]")
            return ColumnData(
                (first - np.datetime64("1900-01-01")).astype(np.int64) + JULIAN_1900,
                None,
            )
        if col == "d_last_dom":
            nxt = (dates.astype("datetime64[M]") + 1).astype("datetime64[D]")
            return ColumnData(
                (nxt - np.datetime64("1900-01-01")).astype(np.int64) + JULIAN_1900 - 1,
                None,
            )
        if col == "d_same_day_ly":
            return ColumnData(idx + JULIAN_1900 - 365, None)
        if col == "d_same_day_lq":
            return ColumnData(idx + JULIAN_1900 - 91, None)
        if col == "d_date_id":
            d = _pat("D-", 12, self.row_count("date_dim"))
            return ColumnData(idx.astype(np.int32), None, d)
        return None

    def _t_time_dim(self, col, idx, t):
        if col == "t_time_sk" or col == "t_time":
            return ColumnData(idx if col == "t_time_sk" else idx.astype(np.int32), None)
        if col == "t_hour":
            return ColumnData((idx // 3600).astype(np.int32), None)
        if col == "t_minute":
            return ColumnData((idx // 60 % 60).astype(np.int32), None)
        if col == "t_second":
            return ColumnData((idx % 60).astype(np.int32), None)
        if col == "t_am_pm":
            d = _dict(["AM", "PM"])
            return ColumnData(
                np.where(idx < 43200, d.index["AM"], d.index["PM"]).astype(np.int32),
                None, d,
            )
        if col == "t_shift":
            d = _dict(SHIFT)
            order = np.array([d.index[v] for v in SHIFT], np.int32)
            return ColumnData(order[(idx // 28800).astype(np.int64) % 3], None, d)
        if col == "t_sub_shift":
            d = _dict(SHIFT)
            order = np.array([d.index[v] for v in SHIFT], np.int32)
            return ColumnData(order[(idx // 9600).astype(np.int64) % 3], None, d)
        if col == "t_meal_time":
            d = _dict(MEAL)
            code = np.where(
                (idx >= 6 * 3600) & (idx < 9 * 3600), d.index["breakfast"],
                np.where(
                    (idx >= 12 * 3600) & (idx < 14 * 3600), d.index["lunch"],
                    np.where((idx >= 18 * 3600) & (idx < 20 * 3600),
                             d.index["dinner"], -1),
                ),
            )
            valid = code >= 0
            return ColumnData(np.maximum(code, 0).astype(np.int32), valid, d)
        return None

    # -- demographics cross-products -----------------------------------------

    def _t_customer_demographics(self, col, idx, t):
        # mixed radix over (gender 2, marital 5, education 7, purchase 20,
        # credit 4, dep 7, dep_emp 7, dep_college 7) = 1,920,800 rows
        i = idx.copy()
        gender = i % 2; i //= 2
        marital = i % 5; i //= 5
        edu = i % 7; i //= 7
        purch = i % 20; i //= 20
        credit = i % 4; i //= 4
        dep = i % 7; i //= 7
        dep_emp = i % 7; i //= 7
        dep_col = i % 7
        if col == "cd_demo_sk":
            return ColumnData(idx + 1, None)
        if col == "cd_gender":
            d = _dict(["F", "M"])
            return ColumnData(gender.astype(np.int32), None, d)
        if col == "cd_marital_status":
            d = _dict(MARITAL)
            return ColumnData(marital.astype(np.int32), None, d)
        if col == "cd_education_status":
            d = _dict(EDUCATION)
            return ColumnData(edu.astype(np.int32), None, d)
        if col == "cd_purchase_estimate":
            return ColumnData(((purch + 1) * 500).astype(np.int32), None)
        if col == "cd_credit_rating":
            d = _dict(CREDIT_RATING)
            return ColumnData(credit.astype(np.int32), None, d)
        if col == "cd_dep_count":
            return ColumnData(dep.astype(np.int32), None)
        if col == "cd_dep_employed_count":
            return ColumnData(dep_emp.astype(np.int32), None)
        if col == "cd_dep_college_count":
            return ColumnData(dep_col.astype(np.int32), None)
        return None

    def _t_household_demographics(self, col, idx, t):
        i = idx.copy()
        band = i % 20; i //= 20
        buy = i % 6; i //= 6
        dep = i % 10; i //= 10
        veh = i % 6
        if col == "hd_demo_sk":
            return ColumnData(idx + 1, None)
        if col == "hd_income_band_sk":
            return ColumnData(band + 1, None)
        if col == "hd_buy_potential":
            d = _dict(BUY_POTENTIAL)
            order = np.array([d.index[v] for v in BUY_POTENTIAL], np.int32)
            return ColumnData(order[buy], None, d)
        if col == "hd_dep_count":
            return ColumnData(dep.astype(np.int32), None)
        if col == "hd_vehicle_count":
            return ColumnData((veh - 1).astype(np.int32), None)
        return None

    def _t_income_band(self, col, idx, t):
        if col == "ib_income_band_sk":
            return ColumnData(idx + 1, None)
        if col == "ib_lower_bound":
            return ColumnData((idx * 10_000 + 1).astype(np.int32), None)
        if col == "ib_upper_bound":
            return ColumnData(((idx + 1) * 10_000).astype(np.int32), None)
        return None

    # -- item / stores / addresses -------------------------------------------

    def _t_item(self, col, idx, t):
        stream = f"item.{col}"
        if col == "i_category":
            d = _dict(CATEGORIES)
            order = np.array([d.index[v] for v in CATEGORIES], np.int32)
            return ColumnData(order[self._item_category(idx)], None, d)
        if col == "i_category_id":
            return ColumnData((self._item_category(idx) + 1).astype(np.int32), None)
        if col == "i_brand_id":
            return ColumnData(self._item_brand_id(idx).astype(np.int32), None)
        if col == "i_brand":
            n = 5004
            d = _pat("Brand#", 8, n)
            return ColumnData(self._item_brand_id(idx).astype(np.int32) % n, None, d)
        if col == "i_class_id":
            return ColumnData((randint(stream, idx, 1, 16)).astype(np.int32), None)
        if col == "i_class":
            d = _dict([f"class{i:02d}" for i in range(1, 17)])
            return ColumnData(
                randint(stream, idx, 0, 15).astype(np.int32), None, d
            )
        if col == "i_manufact_id":
            return ColumnData(randint(stream, idx, 1, 1000).astype(np.int32), None)
        if col == "i_manufact":
            d = _pat("Manufact#", 8, 1000)
            return ColumnData(
                randint(stream, idx, 0, 999).astype(np.int32), None, d
            )
        if col == "i_size":
            d = _dict(SIZES)
            return ColumnData(randint(stream, idx, 0, len(SIZES) - 1).astype(np.int32), None, d)
        if col == "i_units":
            d = _dict(UNITS)
            return ColumnData(randint(stream, idx, 0, len(UNITS) - 1).astype(np.int32), None, d)
        if col == "i_color":
            from trino_tpu.connectors.tpch.generator import COLORS

            d = _dict(COLORS)
            return ColumnData(randint(stream, idx, 0, len(COLORS) - 1).astype(np.int32), None, d)
        if col == "i_product_name":
            d = _pat("Product#", 10, self.row_count("item"))
            return ColumnData(idx.astype(np.int32), None, d)
        if col == "i_item_desc":
            d = _pat("item description ", 10, 1000)
            return ColumnData(randint(stream, idx, 0, 999).astype(np.int32), None, d)
        if col == "i_manager_id":
            return ColumnData(randint(stream, idx, 1, 100).astype(np.int32), None)
        if col == "i_current_price":
            return ColumnData(randint(stream, idx, 99, 99_99), None)
        if col == "i_wholesale_cost":
            return ColumnData(randint(stream, idx, 50, 70_00), None)
        if col in ("i_rec_start_date", "i_rec_end_date"):
            base = (datetime.date(1997, 10, 27) - _EPOCH).days
            return ColumnData(np.full(len(idx), base, np.int32), None)
        return None

    def _item_category(self, idx) -> np.ndarray:
        return randint("item.category", idx, 0, len(CATEGORIES) - 1)

    def _item_brand_id(self, idx) -> np.ndarray:
        # brand id encodes the category like dsdgen's NMMM... shape
        cat = self._item_category(idx) + 1
        m = randint("item.brandm", idx, 1, 1000)
        return cat * 1_000_000 + m

    def _t_store(self, col, idx, t):
        if col == "s_store_name":
            d = _dict(STORE_NAMES)
            order = np.array([d.index[v] for v in STORE_NAMES], np.int32)
            return ColumnData(order[idx % len(STORE_NAMES)], None, d)
        if col == "s_state":
            d = _dict(STATES[:9])
            return ColumnData(
                randint("store.state", idx, 0, 8).astype(np.int32), None, d
            )
        if col in ("s_city",):
            d = _dict(CITIES[:12])
            return ColumnData(randint("store.city", idx, 0, 11).astype(np.int32), None, d)
        if col == "s_county":
            d = _dict(COUNTIES)
            return ColumnData(randint("store.county", idx, 0, len(COUNTIES) - 1).astype(np.int32), None, d)
        if col == "s_zip":
            d = _pat("", 5, 99999)
            return ColumnData(randint("store.zip", idx, 0, 9999).astype(np.int32), None, d)
        if col == "s_gmt_offset":
            return ColumnData(np.full(len(idx), -500, np.int64), None)
        if col == "s_number_employees":
            return ColumnData(randint("store.emp", idx, 200, 300).astype(np.int32), None)
        if col == "s_floor_space":
            return ColumnData(randint("store.fs", idx, 5_000_000, 10_000_000).astype(np.int32), None)
        if col in ("s_rec_start_date", "s_rec_end_date"):
            base = (datetime.date(1997, 3, 13) - _EPOCH).days
            return ColumnData(np.full(len(idx), base, np.int32), None)
        return None

    def _t_customer_address(self, col, idx, t):
        stream = f"customer_address.{col}"
        if col == "ca_state":
            d = _dict(STATES)
            return ColumnData(randint(stream, idx, 0, len(STATES) - 1).astype(np.int32), None, d)
        if col == "ca_city":
            d = _dict(CITIES)
            return ColumnData(randint(stream, idx, 0, len(CITIES) - 1).astype(np.int32), None, d)
        if col == "ca_county":
            d = _dict(COUNTIES)
            return ColumnData(randint(stream, idx, 0, len(COUNTIES) - 1).astype(np.int32), None, d)
        if col == "ca_zip":
            d = _pat("", 5, 99999)
            return ColumnData(randint(stream, idx, 0, 99_998).astype(np.int32), None, d)
        if col == "ca_street_name":
            d = _dict(STREET_NAMES)
            return ColumnData(randint(stream, idx, 0, len(STREET_NAMES) - 1).astype(np.int32), None, d)
        if col == "ca_street_type":
            d = _dict(STREET_TYPES)
            return ColumnData(randint(stream, idx, 0, len(STREET_TYPES) - 1).astype(np.int32), None, d)
        if col == "ca_street_number":
            d = _pat("", 4, 9999)
            return ColumnData(randint(stream, idx, 0, 9998).astype(np.int32), None, d)
        if col == "ca_suite_number":
            d = _pat("Suite ", 3, 100)
            return ColumnData(randint(stream, idx, 0, 99).astype(np.int32), None, d)
        if col == "ca_country":
            d = _dict(["United States"])
            return ColumnData(np.zeros(len(idx), np.int32), None, d)
        if col == "ca_gmt_offset":
            return ColumnData(-randint(stream, idx, 500, 800), None)
        if col == "ca_location_type":
            d = _dict(LOCATION_TYPES)
            return ColumnData(randint(stream, idx, 0, 2).astype(np.int32), None, d)
        return None

    def _t_customer(self, col, idx, t):
        stream = f"customer.{col}"
        if col == "c_first_name":
            d = _dict(FIRST_NAMES)
            return ColumnData(randint(stream, idx, 0, len(FIRST_NAMES) - 1).astype(np.int32), None, d)
        if col == "c_last_name":
            d = _dict(LAST_NAMES)
            return ColumnData(randint(stream, idx, 0, len(LAST_NAMES) - 1).astype(np.int32), None, d)
        if col == "c_salutation":
            d = _dict(SALUTATIONS)
            return ColumnData(randint(stream, idx, 0, len(SALUTATIONS) - 1).astype(np.int32), None, d)
        if col == "c_preferred_cust_flag":
            d = _dict(["N", "Y"])
            return ColumnData(randint(stream, idx, 0, 1).astype(np.int32), None, d)
        if col == "c_birth_day":
            return ColumnData(randint(stream, idx, 1, 28).astype(np.int32), None)
        if col == "c_birth_month":
            return ColumnData(randint(stream, idx, 1, 12).astype(np.int32), None)
        if col == "c_birth_year":
            return ColumnData(randint(stream, idx, 1924, 1992).astype(np.int32), None)
        if col == "c_birth_country":
            from trino_tpu.connectors.tpch.generator import NATIONS

            names = [n for n, _ in NATIONS]
            d = _dict(names)
            return ColumnData(randint(stream, idx, 0, len(names) - 1).astype(np.int32), None, d)
        if col == "c_login":
            d = _pat("login", 8, 100_000)
            return ColumnData((idx % 100_000).astype(np.int32), None, d)
        if col == "c_email_address":
            d = _pat("customer", 10, self.row_count("customer"))
            return ColumnData(idx.astype(np.int32), None, d)
        if col in ("c_first_shipto_date_sk", "c_first_sales_date_sk",
                   "c_last_review_date_sk"):
            return ColumnData(
                SALES_START + randint(stream, idx, 0, SALES_DAYS - 1), None
            )
        return None

    def _t_ship_mode(self, col, idx, t):
        if col == "sm_type":
            d = _dict(SHIP_TYPES)
            order = np.array([d.index[v] for v in SHIP_TYPES], np.int32)
            return ColumnData(order[idx % len(SHIP_TYPES)], None, d)
        if col == "sm_carrier":
            d = _dict(SHIP_CARRIERS)
            order = np.array([d.index[v] for v in SHIP_CARRIERS], np.int32)
            return ColumnData(order[idx % len(SHIP_CARRIERS)], None, d)
        return None

    # -- fact tables ----------------------------------------------------------

    def _t_store_sales(self, col, idx, t):
        if col == "ss_ticket_number":
            return ColumnData(idx // 12 + 1, None)
        return None

    def _t_catalog_sales(self, col, idx, t):
        if col == "cs_order_number":
            return ColumnData(idx // 14 + 1, None)
        return None

    def _t_web_sales(self, col, idx, t):
        if col == "ws_order_number":
            return ColumnData(idx // 14 + 1, None)
        return None

    def _t_inventory(self, col, idx, t):
        if col == "inv_date_sk":
            # weekly snapshots over the calendar
            week = idx // (self.row_count("item") * self.row_count("warehouse"))
            return ColumnData(SALES_START + week * 7, None)
        if col == "inv_item_sk":
            return ColumnData(idx % self.row_count("item") + 1, None)
        if col == "inv_warehouse_sk":
            return ColumnData(
                (idx // self.row_count("item")) % self.row_count("warehouse") + 1,
                None,
            )
        return None

    def _t_store_returns(self, col, idx, t):
        return self._return_column("store_returns", col, idx)

    def _t_catalog_returns(self, col, idx, t):
        return self._return_column("catalog_returns", col, idx)

    def _t_web_returns(self, col, idx, t):
        return self._return_column("web_returns", col, idx)

    def _return_column(self, table, col, idx):
        """Return rows copy the linking keys of a deterministic parent sale
        row, so sales<->returns joins behave like the reference's."""
        sales_table, sp, rp = _RETURN_PARENT[table]
        parent = _rand64(f"{table}.parent", idx) % np.uint64(
            max(1, self.row_count(sales_table))
        )
        parent = parent.astype(np.int64)

        def parent_col(name: str):
            # parent indexes are scattered; generate per-value via the pure
            # column functions (vectorized over the parent index array)
            t2 = dict(column_types(sales_table))[name]
            special = getattr(self, f"_t_{sales_table}", None)
            out = special(name, parent, t2) if special is not None else None
            if out is None:
                out = self._generic(sales_table, name, parent, t2)
            return out

        link = {
            f"{rp}_item_sk": f"{sp}_item_sk",
            f"{rp}_ticket_number": f"{sp}_ticket_number",
            f"{rp}_order_number": f"{sp}_order_number",
            f"{rp}_customer_sk": f"{sp}_customer_sk",
            f"{rp}_returning_customer_sk": (
                f"{sp}_bill_customer_sk" if sp != "ss" else None
            ),
            f"{rp}_refunded_customer_sk": (
                f"{sp}_bill_customer_sk" if sp != "ss" else None
            ),
        }
        src = link.get(col)
        if src:
            return parent_col(src)
        if col == f"{rp}_returned_date_sk":
            sold = parent_col(f"{sp}_sold_date_sk")
            lag = randint(f"{table}.lag", idx, 1, 90)
            vals = np.asarray(sold.values) + lag
            return ColumnData(vals, sold.valid)
        return None


@lru_cache(maxsize=8)
def generator(sf: float) -> TpcdsGenerator:
    return TpcdsGenerator(sf)
