"""TPC-DS benchmark queries (reference: the public spec query templates as
shipped under testing/trino-benchmark-queries/.../tpcds/*.sql).

Adaptations for this engine's dialect (noted per reference behavior, not
semantics): aggregate ORDER BY keys are aliased, `${database}.${schema}.`
prefixes dropped.  Q64 is baseline config #4 (BASELINE.md).
"""

QUERIES = {
    1: """
with customer_total_return as (
    select sr_customer_sk as ctr_customer_sk,
           sr_store_sk as ctr_store_sk,
           sum(sr_return_amt) as ctr_total_return
    from store_returns, date_dim
    where sr_returned_date_sk = d_date_sk and d_year = 2000
    group by sr_customer_sk, sr_store_sk
)
select c_customer_id
from customer_total_return ctr1, store, customer
where ctr1.ctr_total_return > (
        select avg(ctr_total_return) * 1.2
        from customer_total_return ctr2
        where ctr1.ctr_store_sk = ctr2.ctr_store_sk)
  and s_store_sk = ctr1.ctr_store_sk
  and s_state = 'TN'
  and ctr1.ctr_customer_sk = c_customer_sk
order by c_customer_id
limit 100
""",
    3: """
select dt.d_year, item.i_brand_id as brand_id, item.i_brand as brand,
       sum(ss_ext_sales_price) as sum_agg
from date_dim dt, store_sales, item
where dt.d_date_sk = store_sales.ss_sold_date_sk
  and store_sales.ss_item_sk = item.i_item_sk
  and item.i_manufact_id = 128
  and dt.d_moy = 11
group by dt.d_year, item.i_brand_id, item.i_brand
order by dt.d_year, sum_agg desc, brand_id
limit 100
""",
    7: """
select i_item_id,
       avg(ss_quantity) as agg1,
       avg(ss_list_price) as agg2,
       avg(ss_coupon_amt) as agg3,
       avg(ss_sales_price) as agg4
from store_sales, customer_demographics, date_dim, item, promotion
where ss_sold_date_sk = d_date_sk
  and ss_item_sk = i_item_sk
  and ss_cdemo_sk = cd_demo_sk
  and ss_promo_sk = p_promo_sk
  and cd_gender = 'M'
  and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and (p_channel_email = 'N' or p_channel_event = 'N')
  and d_year = 2000
group by i_item_id
order by i_item_id
limit 100
""",
    42: """
select dt.d_year, item.i_category_id, item.i_category,
       sum(ss_ext_sales_price) as total_sales
from date_dim dt, store_sales, item
where dt.d_date_sk = store_sales.ss_sold_date_sk
  and store_sales.ss_item_sk = item.i_item_sk
  and item.i_manager_id = 1
  and dt.d_moy = 11
  and dt.d_year = 2000
group by dt.d_year, item.i_category_id, item.i_category
order by total_sales desc, dt.d_year, item.i_category_id, item.i_category
limit 100
""",
    52: """
select dt.d_year, item.i_brand_id as brand_id, item.i_brand as brand,
       sum(ss_ext_sales_price) as ext_price
from date_dim dt, store_sales, item
where dt.d_date_sk = store_sales.ss_sold_date_sk
  and store_sales.ss_item_sk = item.i_item_sk
  and item.i_manager_id = 1
  and dt.d_moy = 11
  and dt.d_year = 2000
group by dt.d_year, item.i_brand_id, item.i_brand
order by dt.d_year, ext_price desc, brand_id
limit 100
""",
    55: """
select i_brand_id as brand_id, i_brand as brand,
       sum(ss_ext_sales_price) as ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id = 28
  and d_moy = 11
  and d_year = 1999
group by i_brand_id, i_brand
order by ext_price desc, brand_id
limit 100
""",
    68: """
select c_last_name, c_first_name, ca_city, bought_city, ss_ticket_number,
       extended_price, extended_tax, list_price
from (
    select ss_ticket_number, ss_customer_sk, ca_city as bought_city,
           sum(ss_ext_sales_price) as extended_price,
           sum(ss_ext_list_price) as list_price,
           sum(ss_ext_tax) as extended_tax
    from store_sales, date_dim, store, household_demographics, customer_address
    where store_sales.ss_sold_date_sk = date_dim.d_date_sk
      and store_sales.ss_store_sk = store.s_store_sk
      and store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
      and store_sales.ss_addr_sk = customer_address.ca_address_sk
      and date_dim.d_dom between 1 and 2
      and (household_demographics.hd_dep_count = 4
           or household_demographics.hd_vehicle_count = 3)
      and date_dim.d_year in (1999, 2000, 2001)
      and store.s_city in ('Fairview', 'Midway')
    group by ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city
) dn, customer, customer_address current_addr
where ss_customer_sk = c_customer_sk
  and customer.c_current_addr_sk = current_addr.ca_address_sk
  and current_addr.ca_city <> bought_city
order by c_last_name, ss_ticket_number
limit 100
""",
    96: """
select count(*) as cnt
from store_sales, household_demographics, time_dim, store
where ss_sold_time_sk = time_dim.t_time_sk
  and ss_hdemo_sk = household_demographics.hd_demo_sk
  and ss_store_sk = s_store_sk
  and time_dim.t_hour = 20
  and time_dim.t_minute >= 30
  and household_demographics.hd_dep_count = 7
  and store.s_store_name = 'ese'
""",
    64: """
with cs_ui as (
    select cs_item_sk,
           sum(cs_ext_list_price) as sale,
           sum(cr_refunded_cash + cr_reversed_charge + cr_store_credit) as refund
    from catalog_sales, catalog_returns
    where cs_item_sk = cr_item_sk
      and cs_order_number = cr_order_number
    group by cs_item_sk
    having sum(cs_ext_list_price) >
           2 * sum(cr_refunded_cash + cr_reversed_charge + cr_store_credit)
),
cross_sales as (
    select i_product_name as product_name, i_item_sk as item_sk,
           s_store_name as store_name, s_zip as store_zip,
           ad1.ca_street_number as b_street_number,
           ad1.ca_street_name as b_street_name,
           ad1.ca_city as b_city, ad1.ca_zip as b_zip,
           ad2.ca_street_number as c_street_number,
           ad2.ca_street_name as c_street_name,
           ad2.ca_city as c_city, ad2.ca_zip as c_zip,
           d1.d_year as syear, d2.d_year as fsyear, d3.d_year as s2year,
           count(*) as cnt,
           sum(ss_wholesale_cost) as s1,
           sum(ss_list_price) as s2,
           sum(ss_coupon_amt) as s3
    from store_sales, store_returns, cs_ui,
         date_dim d1, date_dim d2, date_dim d3,
         store, customer, customer_demographics cd1, customer_demographics cd2,
         promotion, household_demographics hd1, household_demographics hd2,
         customer_address ad1, customer_address ad2,
         income_band ib1, income_band ib2, item
    where ss_store_sk = s_store_sk
      and ss_sold_date_sk = d1.d_date_sk
      and ss_customer_sk = c_customer_sk
      and ss_cdemo_sk = cd1.cd_demo_sk
      and ss_hdemo_sk = hd1.hd_demo_sk
      and ss_addr_sk = ad1.ca_address_sk
      and ss_item_sk = i_item_sk
      and ss_item_sk = sr_item_sk
      and ss_ticket_number = sr_ticket_number
      and ss_item_sk = cs_ui.cs_item_sk
      and c_current_cdemo_sk = cd2.cd_demo_sk
      and c_current_hdemo_sk = hd2.hd_demo_sk
      and c_current_addr_sk = ad2.ca_address_sk
      and c_first_sales_date_sk = d2.d_date_sk
      and c_first_shipto_date_sk = d3.d_date_sk
      and ss_promo_sk = p_promo_sk
      and hd1.hd_income_band_sk = ib1.ib_income_band_sk
      and hd2.hd_income_band_sk = ib2.ib_income_band_sk
      and cd1.cd_marital_status <> cd2.cd_marital_status
      and i_color in ('purple', 'burlywood', 'indian', 'spring', 'floral', 'medium')
      and i_current_price between 64 and 64 + 10
      and i_current_price between 64 + 1 and 64 + 15
    group by i_product_name, i_item_sk, s_store_name, s_zip,
             ad1.ca_street_number, ad1.ca_street_name, ad1.ca_city, ad1.ca_zip,
             ad2.ca_street_number, ad2.ca_street_name, ad2.ca_city, ad2.ca_zip,
             d1.d_year, d2.d_year, d3.d_year
)
select cs1.product_name, cs1.store_name, cs1.store_zip,
       cs1.b_street_number, cs1.b_street_name, cs1.b_city, cs1.b_zip,
       cs1.c_street_number, cs1.c_street_name, cs1.c_city, cs1.c_zip,
       cs1.syear as syear1, cs1.cnt as cnt1, cs1.s1 as s11, cs1.s2 as s21, cs1.s3 as s31,
       cs2.s1 as s12, cs2.s2 as s22, cs2.s3 as s32, cs2.syear as syear2, cs2.cnt as cnt2
from cross_sales cs1, cross_sales cs2
where cs1.item_sk = cs2.item_sk
  and cs1.syear = 1999
  and cs2.syear = 1999 + 1
  and cs2.cnt <= cs1.cnt
  and cs1.store_name = cs2.store_name
  and cs1.store_zip = cs2.store_zip
order by cs1.product_name, cs1.store_name, cnt2, s12, s22
""",
    6: """
SELECT
  a.ca_state STATE
, count(*) cnt
FROM
  customer_address a
, customer c
, store_sales s
, date_dim d
, item i
WHERE (a.ca_address_sk = c.c_current_addr_sk)
   AND (c.c_customer_sk = s.ss_customer_sk)
   AND (s.ss_sold_date_sk = d.d_date_sk)
   AND (s.ss_item_sk = i.i_item_sk)
   AND (d.d_month_seq = (
      SELECT DISTINCT d_month_seq
      FROM
        date_dim
      WHERE (d_year = 2001)
         AND (d_moy = 1)
   ))
   AND (i.i_current_price > (1.2 * (
         SELECT avg(j.i_current_price)
         FROM
           item j
         WHERE (j.i_category = i.i_category)
      )))
GROUP BY a.ca_state
HAVING (count(*) >= 10)
ORDER BY cnt ASC, a.ca_state ASC
LIMIT 100
""",
    12: """
SELECT
  i_item_id
, i_item_desc
, i_category
, i_class
, i_current_price
, sum(ws_ext_sales_price) itemrevenue
, ((sum(ws_ext_sales_price) * 100) / sum(sum(ws_ext_sales_price)) OVER (PARTITION BY i_class)) revenueratio
FROM
  web_sales
, item
, date_dim
WHERE (ws_item_sk = i_item_sk)
   AND (i_category IN ('Sports', 'Books', 'Home'))
   AND (ws_sold_date_sk = d_date_sk)
   AND (CAST(d_date AS DATE) BETWEEN CAST('1999-02-22' AS DATE) AND (CAST('1999-02-22' AS DATE) + INTERVAL  '30' DAY))
GROUP BY i_item_id, i_item_desc, i_category, i_class, i_current_price
ORDER BY i_category ASC, i_class ASC, i_item_id ASC, i_item_desc ASC, revenueratio ASC
LIMIT 100
""",
    13: """
SELECT
  avg(ss_quantity)
, avg(ss_ext_sales_price)
, avg(ss_ext_wholesale_cost)
, sum(ss_ext_wholesale_cost)
FROM
  store_sales
, store
, customer_demographics
, household_demographics
, customer_address
, date_dim
WHERE (s_store_sk = ss_store_sk)
   AND (ss_sold_date_sk = d_date_sk)
   AND (d_year = 2001)
   AND (((ss_hdemo_sk = hd_demo_sk)
         AND (cd_demo_sk = ss_cdemo_sk)
         AND (cd_marital_status = 'M')
         AND (cd_education_status = 'Advanced Degree')
         AND (ss_sales_price BETWEEN 100.00 AND 150.00)
         AND (hd_dep_count = 3))
      OR ((ss_hdemo_sk = hd_demo_sk)
         AND (cd_demo_sk = ss_cdemo_sk)
         AND (cd_marital_status = 'S')
         AND (cd_education_status = 'College')
         AND (ss_sales_price BETWEEN 50.00 AND 100.00)
         AND (hd_dep_count = 1))
      OR ((ss_hdemo_sk = hd_demo_sk)
         AND (cd_demo_sk = ss_cdemo_sk)
         AND (cd_marital_status = 'W')
         AND (cd_education_status = '2 yr Degree')
         AND (ss_sales_price BETWEEN 150.00 AND 200.00)
         AND (hd_dep_count = 1)))
   AND (((ss_addr_sk = ca_address_sk)
         AND (ca_country = 'United States')
         AND (ca_state IN ('TX'      , 'OH'      , 'TX'))
         AND (ss_net_profit BETWEEN 100 AND 200))
      OR ((ss_addr_sk = ca_address_sk)
         AND (ca_country = 'United States')
         AND (ca_state IN ('OR'      , 'NM'      , 'KY'))
         AND (ss_net_profit BETWEEN 150 AND 300))
      OR ((ss_addr_sk = ca_address_sk)
         AND (ca_country = 'United States')
         AND (ca_state IN ('VA'      , 'TX'      , 'MS'))
         AND (ss_net_profit BETWEEN 50 AND 250)))
""",
    15: """
SELECT
  ca_zip
, sum(cs_sales_price)
FROM
  catalog_sales
, customer
, customer_address
, date_dim
WHERE (cs_bill_customer_sk = c_customer_sk)
   AND (c_current_addr_sk = ca_address_sk)
   AND ((substr(ca_zip, 1, 5) IN ('85669'   , '86197'   , '88274'   , '83405'   , '86475'   , '85392'   , '85460'   , '80348'   , '81792'))
      OR (ca_state IN ('CA'   , 'WA'   , 'GA'))
      OR (cs_sales_price > 500))
   AND (cs_sold_date_sk = d_date_sk)
   AND (d_qoy = 2)
   AND (d_year = 2001)
GROUP BY ca_zip
ORDER BY ca_zip ASC
LIMIT 100
""",
    19: """
SELECT
  i_brand_id brand_id
, i_brand brand
, i_manufact_id
, i_manufact
, sum(ss_ext_sales_price) ext_price
FROM
  date_dim
, store_sales
, item
, customer
, customer_address
, store
WHERE (d_date_sk = ss_sold_date_sk)
   AND (ss_item_sk = i_item_sk)
   AND (i_manager_id = 8)
   AND (d_moy = 11)
   AND (d_year = 1998)
   AND (ss_customer_sk = c_customer_sk)
   AND (c_current_addr_sk = ca_address_sk)
   AND (substr(ca_zip, 1, 5) <> substr(s_zip, 1, 5))
   AND (ss_store_sk = s_store_sk)
GROUP BY i_brand, i_brand_id, i_manufact_id, i_manufact
ORDER BY ext_price DESC, i_brand ASC, i_brand_id ASC, i_manufact_id ASC, i_manufact ASC
LIMIT 100
""",
    20: """
SELECT
  i_item_id
, i_item_desc
, i_category
, i_class
, i_current_price
, sum(cs_ext_sales_price) itemrevenue
, ((sum(cs_ext_sales_price) * 100) / sum(sum(cs_ext_sales_price)) OVER (PARTITION BY i_class)) revenueratio
FROM
  catalog_sales
, item
, date_dim
WHERE (cs_item_sk = i_item_sk)
   AND (i_category IN ('Sports', 'Books', 'Home'))
   AND (cs_sold_date_sk = d_date_sk)
   AND (CAST(d_date AS DATE) BETWEEN CAST('1999-02-22' AS DATE) AND (CAST('1999-02-22' AS DATE) + INTERVAL  '30' DAY))
GROUP BY i_item_id, i_item_desc, i_category, i_class, i_current_price
ORDER BY i_category ASC, i_class ASC, i_item_id ASC, i_item_desc ASC, revenueratio ASC
LIMIT 100
""",
    21: """
SELECT *
FROM
  (
   SELECT
     w_warehouse_name
   , i_item_id
   , sum((CASE WHEN (CAST(d_date AS DATE) < CAST('2000-03-11' AS DATE)) THEN inv_quantity_on_hand ELSE 0 END)) inv_before
   , sum((CASE WHEN (CAST(d_date AS DATE) >= CAST('2000-03-11' AS DATE)) THEN inv_quantity_on_hand ELSE 0 END)) inv_after
   FROM
     inventory
   , warehouse
   , item
   , date_dim
   WHERE (i_current_price BETWEEN 0.99 AND 1.49)
      AND (i_item_sk = inv_item_sk)
      AND (inv_warehouse_sk = w_warehouse_sk)
      AND (inv_date_sk = d_date_sk)
      AND (d_date BETWEEN (CAST('2000-03-11' AS DATE) - INTERVAL  '30' DAY) AND (CAST('2000-03-11' AS DATE) + INTERVAL  '30' DAY))
   GROUP BY w_warehouse_name, i_item_id
)  x
WHERE ((CASE WHEN (inv_before > 0) THEN (CAST(inv_after AS DECIMAL(7,2)) / inv_before) ELSE null END) BETWEEN (2.00 / 3.00) AND (3.00 / 2.00))
ORDER BY w_warehouse_name ASC, i_item_id ASC
LIMIT 100
""",
    25: """
SELECT
  i_item_id
, i_item_desc
, s_store_id
, s_store_name
, sum(ss_net_profit) store_sales_profit
, sum(sr_net_loss) store_returns_loss
, sum(cs_net_profit) catalog_sales_profit
FROM
  store_sales
, store_returns
, catalog_sales
, date_dim d1
, date_dim d2
, date_dim d3
, store
, item
WHERE (d1.d_moy = 4)
   AND (d1.d_year = 2001)
   AND (d1.d_date_sk = ss_sold_date_sk)
   AND (i_item_sk = ss_item_sk)
   AND (s_store_sk = ss_store_sk)
   AND (ss_customer_sk = sr_customer_sk)
   AND (ss_item_sk = sr_item_sk)
   AND (ss_ticket_number = sr_ticket_number)
   AND (sr_returned_date_sk = d2.d_date_sk)
   AND (d2.d_moy BETWEEN 4 AND 10)
   AND (d2.d_year = 2001)
   AND (sr_customer_sk = cs_bill_customer_sk)
   AND (sr_item_sk = cs_item_sk)
   AND (cs_sold_date_sk = d3.d_date_sk)
   AND (d3.d_moy BETWEEN 4 AND 10)
   AND (d3.d_year = 2001)
GROUP BY i_item_id, i_item_desc, s_store_id, s_store_name
ORDER BY i_item_id ASC, i_item_desc ASC, s_store_id ASC, s_store_name ASC
LIMIT 100
""",
    26: """
SELECT
  i_item_id
, avg(cs_quantity) agg1
, avg(cs_list_price) agg2
, avg(cs_coupon_amt) agg3
, avg(cs_sales_price) agg4
FROM
  catalog_sales
, customer_demographics
, date_dim
, item
, promotion
WHERE (cs_sold_date_sk = d_date_sk)
   AND (cs_item_sk = i_item_sk)
   AND (cs_bill_cdemo_sk = cd_demo_sk)
   AND (cs_promo_sk = p_promo_sk)
   AND (cd_gender = 'M')
   AND (cd_marital_status = 'S')
   AND (cd_education_status = 'College')
   AND ((p_channel_email = 'N')
      OR (p_channel_event = 'N'))
   AND (d_year = 2000)
GROUP BY i_item_id
ORDER BY i_item_id ASC
LIMIT 100
""",
    29: """
SELECT
  i_item_id
, i_item_desc
, s_store_id
, s_store_name
, sum(ss_quantity) store_sales_quantity
, sum(sr_return_quantity) store_returns_quantity
, sum(cs_quantity) catalog_sales_quantity
FROM
  store_sales
, store_returns
, catalog_sales
, date_dim d1
, date_dim d2
, date_dim d3
, store
, item
WHERE (d1.d_moy = 9)
   AND (d1.d_year = 1999)
   AND (d1.d_date_sk = ss_sold_date_sk)
   AND (i_item_sk = ss_item_sk)
   AND (s_store_sk = ss_store_sk)
   AND (ss_customer_sk = sr_customer_sk)
   AND (ss_item_sk = sr_item_sk)
   AND (ss_ticket_number = sr_ticket_number)
   AND (sr_returned_date_sk = d2.d_date_sk)
   AND (d2.d_moy BETWEEN 9 AND (9 + 3))
   AND (d2.d_year = 1999)
   AND (sr_customer_sk = cs_bill_customer_sk)
   AND (sr_item_sk = cs_item_sk)
   AND (cs_sold_date_sk = d3.d_date_sk)
   AND (d3.d_year IN (1999, (1999 + 1), (1999 + 2)))
GROUP BY i_item_id, i_item_desc, s_store_id, s_store_name
ORDER BY i_item_id ASC, i_item_desc ASC, s_store_id ASC, s_store_name ASC
LIMIT 100
""",
    37: """
SELECT
  i_item_id
, i_item_desc
, i_current_price
FROM
  item
, inventory
, date_dim
, catalog_sales
WHERE (i_current_price BETWEEN 68 AND (68 + 30))
   AND (inv_item_sk = i_item_sk)
   AND (d_date_sk = inv_date_sk)
   AND (CAST(d_date AS DATE) BETWEEN CAST('2000-02-01' AS DATE) AND (CAST('2000-02-01' AS DATE) + INTERVAL  '60' DAY))
   AND (i_manufact_id IN (677, 940, 694, 808))
   AND (inv_quantity_on_hand BETWEEN 100 AND 500)
   AND (cs_item_sk = i_item_sk)
GROUP BY i_item_id, i_item_desc, i_current_price
ORDER BY i_item_id ASC
LIMIT 100
""",
    43: """
SELECT
  s_store_name
, s_store_id
, sum((CASE WHEN (d_day_name = 'Sunday') THEN ss_sales_price ELSE null END)) sun_sales
, sum((CASE WHEN (d_day_name = 'Monday') THEN ss_sales_price ELSE null END)) mon_sales
, sum((CASE WHEN (d_day_name = 'Tuesday') THEN ss_sales_price ELSE null END)) tue_sales
, sum((CASE WHEN (d_day_name = 'Wednesday') THEN ss_sales_price ELSE null END)) wed_sales
, sum((CASE WHEN (d_day_name = 'Thursday') THEN ss_sales_price ELSE null END)) thu_sales
, sum((CASE WHEN (d_day_name = 'Friday') THEN ss_sales_price ELSE null END)) fri_sales
, sum((CASE WHEN (d_day_name = 'Saturday') THEN ss_sales_price ELSE null END)) sat_sales
FROM
  date_dim
, store_sales
, store
WHERE (d_date_sk = ss_sold_date_sk)
   AND (s_store_sk = ss_store_sk)
   AND (s_gmt_offset = -5)
   AND (d_year = 2000)
GROUP BY s_store_name, s_store_id
ORDER BY s_store_name ASC, s_store_id ASC, sun_sales ASC, mon_sales ASC, tue_sales ASC, wed_sales ASC, thu_sales ASC, fri_sales ASC, sat_sales ASC
LIMIT 100
""",
    46: """
SELECT
  c_last_name
, c_first_name
, ca_city
, bought_city
, ss_ticket_number
, amt
, profit
FROM
  (
   SELECT
     ss_ticket_number
   , ss_customer_sk
   , ca_city bought_city
   , sum(ss_coupon_amt) amt
   , sum(ss_net_profit) profit
   FROM
     store_sales
   , date_dim
   , store
   , household_demographics
   , customer_address
   WHERE (store_sales.ss_sold_date_sk = date_dim.d_date_sk)
      AND (store_sales.ss_store_sk = store.s_store_sk)
      AND (store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk)
      AND (store_sales.ss_addr_sk = customer_address.ca_address_sk)
      AND ((household_demographics.hd_dep_count = 4)
         OR (household_demographics.hd_vehicle_count = 3))
      AND (date_dim.d_dow IN (6   , 0))
      AND (date_dim.d_year IN (1999   , (1999 + 1)   , (1999 + 2)))
      AND (store.s_city IN ('Fairview'   , 'Midway'   , 'Fairview'   , 'Fairview'   , 'Fairview'))
   GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city
)  dn
, customer
, customer_address current_addr
WHERE (ss_customer_sk = c_customer_sk)
   AND (customer.c_current_addr_sk = current_addr.ca_address_sk)
   AND (current_addr.ca_city <> bought_city)
ORDER BY c_last_name ASC, c_first_name ASC, ca_city ASC, bought_city ASC, ss_ticket_number ASC
LIMIT 100
""",
    48: """
SELECT sum(ss_quantity)
FROM
  store_sales
, store
, customer_demographics
, customer_address
, date_dim
WHERE (s_store_sk = ss_store_sk)
   AND (ss_sold_date_sk = d_date_sk)
   AND (d_year = 2000)
   AND (((cd_demo_sk = ss_cdemo_sk)
         AND (cd_marital_status = 'M')
         AND (cd_education_status = '4 yr Degree')
         AND (ss_sales_price BETWEEN 100.00 AND 150.00))
      OR ((cd_demo_sk = ss_cdemo_sk)
         AND (cd_marital_status = 'D')
         AND (cd_education_status = '2 yr Degree')
         AND (ss_sales_price BETWEEN 50.00 AND 100.00))
      OR ((cd_demo_sk = ss_cdemo_sk)
         AND (cd_marital_status = 'S')
         AND (cd_education_status = 'College')
         AND (ss_sales_price BETWEEN 150.00 AND 200.00)))
   AND (((ss_addr_sk = ca_address_sk)
         AND (ca_country = 'United States')
         AND (ca_state IN ('CO'      , 'OH'      , 'TX'))
         AND (ss_net_profit BETWEEN 0 AND 2000))
      OR ((ss_addr_sk = ca_address_sk)
         AND (ca_country = 'United States')
         AND (ca_state IN ('OR'      , 'MN'      , 'KY'))
         AND (ss_net_profit BETWEEN 150 AND 3000))
      OR ((ss_addr_sk = ca_address_sk)
         AND (ca_country = 'United States')
         AND (ca_state IN ('VA'      , 'CA'      , 'MS'))
         AND (ss_net_profit BETWEEN 50 AND 25000)))
""",
    50: """
SELECT
  s_store_name
, s_company_id
, s_street_number
, s_street_name
, s_street_type
, s_suite_number
, s_city
, s_county
, s_state
, s_zip
, sum((CASE WHEN ((sr_returned_date_sk - ss_sold_date_sk) <= 30) THEN 1 ELSE 0 END)) "30 days"
, sum((CASE WHEN ((sr_returned_date_sk - ss_sold_date_sk) > 30)
   AND ((sr_returned_date_sk - ss_sold_date_sk) <= 60) THEN 1 ELSE 0 END)) "31-60 days"
, sum((CASE WHEN ((sr_returned_date_sk - ss_sold_date_sk) > 60)
   AND ((sr_returned_date_sk - ss_sold_date_sk) <= 90) THEN 1 ELSE 0 END)) "61-90 days"
, sum((CASE WHEN ((sr_returned_date_sk - ss_sold_date_sk) > 90)
   AND ((sr_returned_date_sk - ss_sold_date_sk) <= 120) THEN 1 ELSE 0 END)) "91-120 days"
, sum((CASE WHEN ((sr_returned_date_sk - ss_sold_date_sk) > 120) THEN 1 ELSE 0 END)) ">120 days"
FROM
  store_sales
, store_returns
, store
, date_dim d1
, date_dim d2
WHERE (d2.d_year = 2001)
   AND (d2.d_moy = 8)
   AND (ss_ticket_number = sr_ticket_number)
   AND (ss_item_sk = sr_item_sk)
   AND (ss_sold_date_sk = d1.d_date_sk)
   AND (sr_returned_date_sk = d2.d_date_sk)
   AND (ss_customer_sk = sr_customer_sk)
   AND (ss_store_sk = s_store_sk)
GROUP BY s_store_name, s_company_id, s_street_number, s_street_name, s_street_type, s_suite_number, s_city, s_county, s_state, s_zip
ORDER BY s_store_name ASC, s_company_id ASC, s_street_number ASC, s_street_name ASC, s_street_type ASC, s_suite_number ASC, s_city ASC, s_county ASC, s_state ASC, s_zip ASC
LIMIT 100
""",
    53: """
SELECT *
FROM
  (
   SELECT
     i_manufact_id
   , sum(ss_sales_price) sum_sales
   , avg(sum(ss_sales_price)) OVER (PARTITION BY i_manufact_id) avg_quarterly_sales
   FROM
     item
   , store_sales
   , date_dim
   , store
   WHERE (ss_item_sk = i_item_sk)
      AND (ss_sold_date_sk = d_date_sk)
      AND (ss_store_sk = s_store_sk)
      AND (d_month_seq IN (1200   , (1200 + 1)   , (1200 + 2)   , (1200 + 3)   , (1200 + 4)   , (1200 + 5)   , (1200 + 6)   , (1200 + 7)   , (1200 + 8)   , (1200 + 9)   , (1200 + 10)   , (1200 + 11)))
      AND (((i_category IN ('Books'         , 'Children'         , 'Electronics'))
            AND (i_class IN ('personal'         , 'portable'         , 'reference'         , 'self-help'))
            AND (i_brand IN ('scholaramalgamalg #14'         , 'scholaramalgamalg #7'         , 'exportiunivamalg #9'         , 'scholaramalgamalg #9')))
         OR ((i_category IN ('Women'         , 'Music'         , 'Men'))
            AND (i_class IN ('accessories'         , 'classical'         , 'fragrances'         , 'pants'))
            AND (i_brand IN ('amalgimporto #1'         , 'edu packscholar #1'         , 'exportiimporto #1'         , 'importoamalg #1'))))
   GROUP BY i_manufact_id, d_qoy
)  tmp1
WHERE ((CASE WHEN (avg_quarterly_sales > 0) THEN (abs((CAST(sum_sales AS DECIMAL(38,4)) - avg_quarterly_sales)) / avg_quarterly_sales) ELSE null END) > 0.1)
ORDER BY avg_quarterly_sales ASC, sum_sales ASC, i_manufact_id ASC
LIMIT 100
""",
    59: """
WITH
  wss AS (
   SELECT
     d_week_seq
   , ss_store_sk
   , sum((CASE WHEN (d_day_name = 'Sunday') THEN ss_sales_price ELSE null END)) sun_sales
   , sum((CASE WHEN (d_day_name = 'Monday') THEN ss_sales_price ELSE null END)) mon_sales
   , sum((CASE WHEN (d_day_name = 'Tuesday') THEN ss_sales_price ELSE null END)) tue_sales
   , sum((CASE WHEN (d_day_name = 'Wednesday') THEN ss_sales_price ELSE null END)) wed_sales
   , sum((CASE WHEN (d_day_name = 'Thursday') THEN ss_sales_price ELSE null END)) thu_sales
   , sum((CASE WHEN (d_day_name = 'Friday') THEN ss_sales_price ELSE null END)) fri_sales
   , sum((CASE WHEN (d_day_name = 'Saturday') THEN ss_sales_price ELSE null END)) sat_sales
   FROM
     store_sales
   , date_dim
   WHERE (d_date_sk = ss_sold_date_sk)
   GROUP BY d_week_seq, ss_store_sk
)
SELECT
  s_store_name1
, s_store_id1
, d_week_seq1
, (sun_sales1 / sun_sales2)
, (mon_sales1 / mon_sales2)
, (tue_sales1 / tue_sales2)
, (wed_sales1 / wed_sales2)
, (thu_sales1 / thu_sales2)
, (fri_sales1 / fri_sales2)
, (sat_sales1 / sat_sales2)
FROM
  (
   SELECT
     s_store_name s_store_name1
   , wss.d_week_seq d_week_seq1
   , s_store_id s_store_id1
   , sun_sales sun_sales1
   , mon_sales mon_sales1
   , tue_sales tue_sales1
   , wed_sales wed_sales1
   , thu_sales thu_sales1
   , fri_sales fri_sales1
   , sat_sales sat_sales1
   FROM
     wss
   , store
   , date_dim d
   WHERE (d.d_week_seq = wss.d_week_seq)
      AND (ss_store_sk = s_store_sk)
      AND (d_month_seq BETWEEN 1212 AND (1212 + 11))
)  y
, (
   SELECT
     s_store_name s_store_name2
   , wss.d_week_seq d_week_seq2
   , s_store_id s_store_id2
   , sun_sales sun_sales2
   , mon_sales mon_sales2
   , tue_sales tue_sales2
   , wed_sales wed_sales2
   , thu_sales thu_sales2
   , fri_sales fri_sales2
   , sat_sales sat_sales2
   FROM
     wss
   , store
   , date_dim d
   WHERE (d.d_week_seq = wss.d_week_seq)
      AND (ss_store_sk = s_store_sk)
      AND (d_month_seq BETWEEN (1212 + 12) AND (1212 + 23))
)  x
WHERE (s_store_id1 = s_store_id2)
   AND (d_week_seq1 = (d_week_seq2 - 52))
ORDER BY s_store_name1 ASC, s_store_id1 ASC, d_week_seq1 ASC
LIMIT 100
""",
    62: """
SELECT
  substr(w_warehouse_name, 1, 20)
, sm_type
, web_name
, sum((CASE WHEN ((ws_ship_date_sk - ws_sold_date_sk) <= 30) THEN 1 ELSE 0 END)) "30 days"
, sum((CASE WHEN ((ws_ship_date_sk - ws_sold_date_sk) > 30)
   AND ((ws_ship_date_sk - ws_sold_date_sk) <= 60) THEN 1 ELSE 0 END)) "31-60 days"
, sum((CASE WHEN ((ws_ship_date_sk - ws_sold_date_sk) > 60)
   AND ((ws_ship_date_sk - ws_sold_date_sk) <= 90) THEN 1 ELSE 0 END)) "61-90 days"
, sum((CASE WHEN ((ws_ship_date_sk - ws_sold_date_sk) > 90)
   AND ((ws_ship_date_sk - ws_sold_date_sk) <= 120) THEN 1 ELSE 0 END)) "91-120 days"
, sum((CASE WHEN ((ws_ship_date_sk - ws_sold_date_sk) > 120) THEN 1 ELSE 0 END)) ">120 days"
FROM
  web_sales
, warehouse
, ship_mode
, web_site
, date_dim
WHERE (d_month_seq BETWEEN 1200 AND (1200 + 11))
   AND (ws_ship_date_sk = d_date_sk)
   AND (ws_warehouse_sk = w_warehouse_sk)
   AND (ws_ship_mode_sk = sm_ship_mode_sk)
   AND (ws_web_site_sk = web_site_sk)
GROUP BY substr(w_warehouse_name, 1, 20), sm_type, web_name
ORDER BY substr(w_warehouse_name, 1, 20) ASC, sm_type ASC, web_name ASC
LIMIT 100
""",
    63: """
SELECT *
FROM
  (
   SELECT
     i_manager_id
   , sum(ss_sales_price) sum_sales
   , avg(sum(ss_sales_price)) OVER (PARTITION BY i_manager_id) avg_monthly_sales
   FROM
     item
   , store_sales
   , date_dim
   , store
   WHERE (ss_item_sk = i_item_sk)
      AND (ss_sold_date_sk = d_date_sk)
      AND (ss_store_sk = s_store_sk)
      AND (d_month_seq IN (1200   , (1200 + 1)   , (1200 + 2)   , (1200 + 3)   , (1200 + 4)   , (1200 + 5)   , (1200 + 6)   , (1200 + 7)   , (1200 + 8)   , (1200 + 9)   , (1200 + 10)   , (1200 + 11)))
      AND (((i_category IN ('Books'         , 'Children'         , 'Electronics'))
            AND (i_class IN ('personal'         , 'portable'         , 'refernece'         , 'self-help'))
            AND (i_brand IN ('scholaramalgamalg #14'         , 'scholaramalgamalg #7'         , 'exportiunivamalg #9'         , 'scholaramalgamalg #9')))
         OR ((i_category IN ('Women'         , 'Music'         , 'Men'))
            AND (i_class IN ('accessories'         , 'classical'         , 'fragrances'         , 'pants'))
            AND (i_brand IN ('amalgimporto #1'         , 'edu packscholar #1'         , 'exportiimporto #1'         , 'importoamalg #1'))))
   GROUP BY i_manager_id, d_moy
)  tmp1
WHERE ((CASE WHEN (avg_monthly_sales > 0) THEN (abs((sum_sales - avg_monthly_sales)) / avg_monthly_sales) ELSE null END) > 0.1)
ORDER BY i_manager_id ASC, avg_monthly_sales ASC, sum_sales ASC
LIMIT 100
""",
    65: """
SELECT
  s_store_name
, i_item_desc
, sc.revenue
, i_current_price
, i_wholesale_cost
, i_brand
FROM
  store
, item
, (
   SELECT
     ss_store_sk
   , avg(revenue) ave
   FROM
     (
      SELECT
        ss_store_sk
      , ss_item_sk
      , sum(ss_sales_price) revenue
      FROM
        store_sales
      , date_dim
      WHERE (ss_sold_date_sk = d_date_sk)
         AND (d_month_seq BETWEEN 1176 AND (1176 + 11))
      GROUP BY ss_store_sk, ss_item_sk
   )  sa
   GROUP BY ss_store_sk
)  sb
, (
   SELECT
     ss_store_sk
   , ss_item_sk
   , sum(ss_sales_price) revenue
   FROM
     store_sales
   , date_dim
   WHERE (ss_sold_date_sk = d_date_sk)
      AND (d_month_seq BETWEEN 1176 AND (1176 + 11))
   GROUP BY ss_store_sk, ss_item_sk
)  sc
WHERE (sb.ss_store_sk = sc.ss_store_sk)
   AND (sc.revenue <= (0.1 * sb.ave))
   AND (s_store_sk = sc.ss_store_sk)
   AND (i_item_sk = sc.ss_item_sk)
ORDER BY s_store_name ASC, i_item_desc ASC,
   -- additional columns to assure results stability for larger scale factors; this is a deviation from TPC-DS specification
   i_brand ASC, sc.revenue ASC
LIMIT 100
""",
    73: """
SELECT
  c_last_name
, c_first_name
, c_salutation
, c_preferred_cust_flag
, ss_ticket_number
, cnt
FROM
  (
   SELECT
     ss_ticket_number
   , ss_customer_sk
   , count(*) cnt
   FROM
     store_sales
   , date_dim
   , store
   , household_demographics
   WHERE (store_sales.ss_sold_date_sk = date_dim.d_date_sk)
      AND (store_sales.ss_store_sk = store.s_store_sk)
      AND (store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk)
      AND (date_dim.d_dom BETWEEN 1 AND 2)
      AND ((household_demographics.hd_buy_potential = '>10000')
         OR (household_demographics.hd_buy_potential = 'Unknown'))
      AND (household_demographics.hd_vehicle_count > 0)
      AND ((CASE WHEN (household_demographics.hd_vehicle_count > 0) THEN (CAST(household_demographics.hd_dep_count AS DECIMAL(7,2)) / household_demographics.hd_vehicle_count) ELSE null END) > 1)
      AND (date_dim.d_year IN (1999   , (1999 + 1)   , (1999 + 2)))
      AND (store.s_county IN ('Williamson County'   , 'Franklin Parish'   , 'Bronx County'   , 'Orange County'))
   GROUP BY ss_ticket_number, ss_customer_sk
)  dj
, customer
WHERE (ss_customer_sk = c_customer_sk)
   AND (cnt BETWEEN 1 AND 5)
ORDER BY cnt DESC, c_last_name ASC,
   -- additional column to assure results stability for larger scale factors; this is a deviation from TPC-DS specification
   ss_ticket_number ASC
""",
    79: """
SELECT
  c_last_name
, c_first_name
, substr(s_city, 1, 30)
, ss_ticket_number
, amt
, profit
FROM
  (
   SELECT
     ss_ticket_number
   , ss_customer_sk
   , store.s_city
   , sum(ss_coupon_amt) amt
   , sum(ss_net_profit) profit
   FROM
     store_sales
   , date_dim
   , store
   , household_demographics
   WHERE (store_sales.ss_sold_date_sk = date_dim.d_date_sk)
      AND (store_sales.ss_store_sk = store.s_store_sk)
      AND (store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk)
      AND ((household_demographics.hd_dep_count = 6)
         OR (household_demographics.hd_vehicle_count > 2))
      AND (date_dim.d_dow = 1)
      AND (date_dim.d_year IN (1999   , (1999 + 1)   , (1999 + 2)))
      AND (store.s_number_employees BETWEEN 200 AND 295)
   GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk, store.s_city
)  ms
, customer
WHERE (ss_customer_sk = c_customer_sk)
ORDER BY c_last_name ASC, c_first_name ASC, substr(s_city, 1, 30) ASC, profit ASC
LIMIT 100
""",
    82: """
SELECT
  i_item_id
, i_item_desc
, i_current_price
FROM
  item
, inventory
, date_dim
, store_sales
WHERE (i_current_price BETWEEN 62 AND (62 + 30))
   AND (inv_item_sk = i_item_sk)
   AND (d_date_sk = inv_date_sk)
   AND (CAST(d_date AS DATE) BETWEEN CAST('2000-05-25' AS DATE) AND (CAST('2000-05-25' AS DATE) + INTERVAL  '60' DAY))
   AND (i_manufact_id IN (129, 270, 821, 423))
   AND (inv_quantity_on_hand BETWEEN 100 AND 500)
   AND (ss_item_sk = i_item_sk)
GROUP BY i_item_id, i_item_desc, i_current_price
ORDER BY i_item_id ASC
LIMIT 100
""",
    88: """
SELECT *
FROM
  (
   SELECT count(*) h8_30_to_9
   FROM
     store_sales
   , household_demographics
   , time_dim
   , store
   WHERE (ss_sold_time_sk = time_dim.t_time_sk)
      AND (ss_hdemo_sk = household_demographics.hd_demo_sk)
      AND (ss_store_sk = s_store_sk)
      AND (time_dim.t_hour = 8)
      AND (time_dim.t_minute >= 30)
      AND (((household_demographics.hd_dep_count = 4)
            AND (household_demographics.hd_vehicle_count <= (4 + 2)))
         OR ((household_demographics.hd_dep_count = 2)
            AND (household_demographics.hd_vehicle_count <= (2 + 2)))
         OR ((household_demographics.hd_dep_count = 0)
            AND (household_demographics.hd_vehicle_count <= (0 + 2))))
      AND (store.s_store_name = 'ese')
)  s1
, (
   SELECT count(*) h9_to_9_30
   FROM
     store_sales
   , household_demographics
   , time_dim
   , store
   WHERE (ss_sold_time_sk = time_dim.t_time_sk)
      AND (ss_hdemo_sk = household_demographics.hd_demo_sk)
      AND (ss_store_sk = s_store_sk)
      AND (time_dim.t_hour = 9)
      AND (time_dim.t_minute < 30)
      AND (((household_demographics.hd_dep_count = 4)
            AND (household_demographics.hd_vehicle_count <= (4 + 2)))
         OR ((household_demographics.hd_dep_count = 2)
            AND (household_demographics.hd_vehicle_count <= (2 + 2)))
         OR ((household_demographics.hd_dep_count = 0)
            AND (household_demographics.hd_vehicle_count <= (0 + 2))))
      AND (store.s_store_name = 'ese')
)  s2
, (
   SELECT count(*) h9_30_to_10
   FROM
     store_sales
   , household_demographics
   , time_dim
   , store
   WHERE (ss_sold_time_sk = time_dim.t_time_sk)
      AND (ss_hdemo_sk = household_demographics.hd_demo_sk)
      AND (ss_store_sk = s_store_sk)
      AND (time_dim.t_hour = 9)
      AND (time_dim.t_minute >= 30)
      AND (((household_demographics.hd_dep_count = 4)
            AND (household_demographics.hd_vehicle_count <= (4 + 2)))
         OR ((household_demographics.hd_dep_count = 2)
            AND (household_demographics.hd_vehicle_count <= (2 + 2)))
         OR ((household_demographics.hd_dep_count = 0)
            AND (household_demographics.hd_vehicle_count <= (0 + 2))))
      AND (store.s_store_name = 'ese')
)  s3
, (
   SELECT count(*) h10_to_10_30
   FROM
     store_sales
   , household_demographics
   , time_dim
   , store
   WHERE (ss_sold_time_sk = time_dim.t_time_sk)
      AND (ss_hdemo_sk = household_demographics.hd_demo_sk)
      AND (ss_store_sk = s_store_sk)
      AND (time_dim.t_hour = 10)
      AND (time_dim.t_minute < 30)
      AND (((household_demographics.hd_dep_count = 4)
            AND (household_demographics.hd_vehicle_count <= (4 + 2)))
         OR ((household_demographics.hd_dep_count = 2)
            AND (household_demographics.hd_vehicle_count <= (2 + 2)))
         OR ((household_demographics.hd_dep_count = 0)
            AND (household_demographics.hd_vehicle_count <= (0 + 2))))
      AND (store.s_store_name = 'ese')
)  s4
, (
   SELECT count(*) h10_30_to_11
   FROM
     store_sales
   , household_demographics
   , time_dim
   , store
   WHERE (ss_sold_time_sk = time_dim.t_time_sk)
      AND (ss_hdemo_sk = household_demographics.hd_demo_sk)
      AND (ss_store_sk = s_store_sk)
      AND (time_dim.t_hour = 10)
      AND (time_dim.t_minute >= 30)
      AND (((household_demographics.hd_dep_count = 4)
            AND (household_demographics.hd_vehicle_count <= (4 + 2)))
         OR ((household_demographics.hd_dep_count = 2)
            AND (household_demographics.hd_vehicle_count <= (2 + 2)))
         OR ((household_demographics.hd_dep_count = 0)
            AND (household_demographics.hd_vehicle_count <= (0 + 2))))
      AND (store.s_store_name = 'ese')
)  s5
, (
   SELECT count(*) h11_to_11_30
   FROM
     store_sales
   , household_demographics
   , time_dim
   , store
   WHERE (ss_sold_time_sk = time_dim.t_time_sk)
      AND (ss_hdemo_sk = household_demographics.hd_demo_sk)
      AND (ss_store_sk = s_store_sk)
      AND (time_dim.t_hour = 11)
      AND (time_dim.t_minute < 30)
      AND (((household_demographics.hd_dep_count = 4)
            AND (household_demographics.hd_vehicle_count <= (4 + 2)))
         OR ((household_demographics.hd_dep_count = 2)
            AND (household_demographics.hd_vehicle_count <= (2 + 2)))
         OR ((household_demographics.hd_dep_count = 0)
            AND (household_demographics.hd_vehicle_count <= (0 + 2))))
      AND (store.s_store_name = 'ese')
)  s6
, (
   SELECT count(*) h11_30_to_12
   FROM
     store_sales
   , household_demographics
   , time_dim
   , store
   WHERE (ss_sold_time_sk = time_dim.t_time_sk)
      AND (ss_hdemo_sk = household_demographics.hd_demo_sk)
      AND (ss_store_sk = s_store_sk)
      AND (time_dim.t_hour = 11)
      AND (time_dim.t_minute >= 30)
      AND (((household_demographics.hd_dep_count = 4)
            AND (household_demographics.hd_vehicle_count <= (4 + 2)))
         OR ((household_demographics.hd_dep_count = 2)
            AND (household_demographics.hd_vehicle_count <= (2 + 2)))
         OR ((household_demographics.hd_dep_count = 0)
            AND (household_demographics.hd_vehicle_count <= (0 + 2))))
      AND (store.s_store_name = 'ese')
)  s7
, (
   SELECT count(*) h12_to_12_30
   FROM
     store_sales
   , household_demographics
   , time_dim
   , store
   WHERE (ss_sold_time_sk = time_dim.t_time_sk)
      AND (ss_hdemo_sk = household_demographics.hd_demo_sk)
      AND (ss_store_sk = s_store_sk)
      AND (time_dim.t_hour = 12)
      AND (time_dim.t_minute < 30)
      AND (((household_demographics.hd_dep_count = 4)
            AND (household_demographics.hd_vehicle_count <= (4 + 2)))
         OR ((household_demographics.hd_dep_count = 2)
            AND (household_demographics.hd_vehicle_count <= (2 + 2)))
         OR ((household_demographics.hd_dep_count = 0)
            AND (household_demographics.hd_vehicle_count <= (0 + 2))))
      AND (store.s_store_name = 'ese')
)  s8
""",
    89: """
SELECT *
FROM
  (
   SELECT
     i_category
   , i_class
   , i_brand
   , s_store_name
   , s_company_name
   , d_moy
   , sum(ss_sales_price) sum_sales
   , avg(sum(ss_sales_price)) OVER (PARTITION BY i_category, i_brand, s_store_name, s_company_name) avg_monthly_sales
   FROM
     item
   , store_sales
   , date_dim
   , store
   WHERE (ss_item_sk = i_item_sk)
      AND (ss_sold_date_sk = d_date_sk)
      AND (ss_store_sk = s_store_sk)
      AND (d_year IN (1999))
      AND (((i_category IN ('Books'         , 'Electronics'         , 'Sports'))
            AND (i_class IN ('computers'         , 'stereo'         , 'football')))
         OR ((i_category IN ('Men'         , 'Jewelry'         , 'Women'))
            AND (i_class IN ('shirts'         , 'birdal'         , 'dresses'))))
   GROUP BY i_category, i_class, i_brand, s_store_name, s_company_name, d_moy
)  tmp1
WHERE ((CASE WHEN (avg_monthly_sales <> 0) THEN (abs((sum_sales - avg_monthly_sales)) / avg_monthly_sales) ELSE null END) > 0.1)
ORDER BY (sum_sales - avg_monthly_sales) ASC, s_store_name ASC
LIMIT 100
""",
    90: """
SELECT (CAST(amc AS DECIMAL(15,4)) / CAST(pmc AS DECIMAL(15,4))) am_pm_ratio
FROM
  (
   SELECT count(*) amc
   FROM
     web_sales
   , household_demographics
   , time_dim
   , web_page
   WHERE (ws_sold_time_sk = time_dim.t_time_sk)
      AND (ws_ship_hdemo_sk = household_demographics.hd_demo_sk)
      AND (ws_web_page_sk = web_page.wp_web_page_sk)
      AND (time_dim.t_hour BETWEEN 8 AND (8 + 1))
      AND (household_demographics.hd_dep_count = 6)
      AND (web_page.wp_char_count BETWEEN 5000 AND 5200)
)  at
, (
   SELECT count(*) pmc
   FROM
     web_sales
   , household_demographics
   , time_dim
   , web_page
   WHERE (ws_sold_time_sk = time_dim.t_time_sk)
      AND (ws_ship_hdemo_sk = household_demographics.hd_demo_sk)
      AND (ws_web_page_sk = web_page.wp_web_page_sk)
      AND (time_dim.t_hour BETWEEN 19 AND (19 + 1))
      AND (household_demographics.hd_dep_count = 6)
      AND (web_page.wp_char_count BETWEEN 5000 AND 5200)
)  pt
ORDER BY am_pm_ratio ASC
LIMIT 100
""",
    91: """
SELECT
  cc_call_center_id Call_Center
, cc_name Call_Center_Name
, cc_manager Manager
, sum(cr_net_loss) Returns_Loss
FROM
  call_center
, catalog_returns
, date_dim
, customer
, customer_address
, customer_demographics
, household_demographics
WHERE (cr_call_center_sk = cc_call_center_sk)
   AND (cr_returned_date_sk = d_date_sk)
   AND (cr_returning_customer_sk = c_customer_sk)
   AND (cd_demo_sk = c_current_cdemo_sk)
   AND (hd_demo_sk = c_current_hdemo_sk)
   AND (ca_address_sk = c_current_addr_sk)
   AND (d_year = 1998)
   AND (d_moy = 11)
   AND (((cd_marital_status = 'M')
         AND (cd_education_status = 'Unknown'))
      OR ((cd_marital_status = 'W')
         AND (cd_education_status = 'Advanced Degree')))
   AND (hd_buy_potential LIKE 'Unknown%')
   AND (ca_gmt_offset = -7)
GROUP BY cc_call_center_id, cc_name, cc_manager, cd_marital_status, cd_education_status
ORDER BY sum(cr_net_loss) DESC
""",
    93: """
SELECT
  ss_customer_sk
, sum(act_sales) sumsales
FROM
  (
   SELECT
     ss_item_sk
   , ss_ticket_number
   , ss_customer_sk
   , (CASE WHEN (sr_return_quantity IS NOT NULL) THEN ((ss_quantity - sr_return_quantity) * ss_sales_price) ELSE (ss_quantity * ss_sales_price) END) act_sales
   FROM
     (store_sales
   LEFT JOIN store_returns ON (sr_item_sk = ss_item_sk)
      AND (sr_ticket_number = ss_ticket_number))
   , reason
   WHERE (sr_reason_sk = r_reason_sk)
      AND (r_reason_desc = 'reason 28')
)  t
GROUP BY ss_customer_sk
ORDER BY sumsales ASC, ss_customer_sk ASC
LIMIT 100
""",
    98: """
SELECT
  i_item_id
, i_item_desc
, i_category
, i_class
, i_current_price
, sum(ss_ext_sales_price) itemrevenue
, ((sum(ss_ext_sales_price) * 100) / sum(sum(ss_ext_sales_price)) OVER (PARTITION BY i_class)) revenueratio
FROM
  store_sales
, item
, date_dim
WHERE (ss_item_sk = i_item_sk)
   AND (i_category IN ('Sports', 'Books', 'Home'))
   AND (ss_sold_date_sk = d_date_sk)
   AND (CAST(d_date AS DATE) BETWEEN CAST('1999-02-22' AS DATE) AND (CAST('1999-02-22' AS DATE) + INTERVAL  '30' DAY))
GROUP BY i_item_id, i_item_desc, i_category, i_class, i_current_price
ORDER BY i_category ASC, i_class ASC, i_item_id ASC, i_item_desc ASC, revenueratio ASC
""",
    99: """
SELECT
  substr(w_warehouse_name, 1, 20)
, sm_type
, cc_name
, sum((CASE WHEN ((cs_ship_date_sk - cs_sold_date_sk) <= 30) THEN 1 ELSE 0 END)) "30 days"
, sum((CASE WHEN ((cs_ship_date_sk - cs_sold_date_sk) > 30)
   AND ((cs_ship_date_sk - cs_sold_date_sk) <= 60) THEN 1 ELSE 0 END)) "31-60 days"
, sum((CASE WHEN ((cs_ship_date_sk - cs_sold_date_sk) > 60)
   AND ((cs_ship_date_sk - cs_sold_date_sk) <= 90) THEN 1 ELSE 0 END)) "61-90 days"
, sum((CASE WHEN ((cs_ship_date_sk - cs_sold_date_sk) > 90)
   AND ((cs_ship_date_sk - cs_sold_date_sk) <= 120) THEN 1 ELSE 0 END)) "91-120 days"
, sum((CASE WHEN ((cs_ship_date_sk - cs_sold_date_sk) > 120) THEN 1 ELSE 0 END)) ">120 days"
FROM
  catalog_sales
, warehouse
, ship_mode
, call_center
, date_dim
WHERE (d_month_seq BETWEEN 1200 AND (1200 + 11))
   AND (cs_ship_date_sk = d_date_sk)
   AND (cs_warehouse_sk = w_warehouse_sk)
   AND (cs_ship_mode_sk = sm_ship_mode_sk)
   AND (cs_call_center_sk = cc_call_center_sk)
GROUP BY substr(w_warehouse_name, 1, 20), sm_type, cc_name
ORDER BY substr(w_warehouse_name, 1, 20) ASC, sm_type ASC, cc_name ASC
LIMIT 100
""",

    2: """
with wscs as (
    select ws_sold_date_sk as sold_date_sk, ws_ext_sales_price as sales_price
    from web_sales
    union all
    select cs_sold_date_sk as sold_date_sk, cs_ext_sales_price as sales_price
    from catalog_sales
),
wswscs as (
    select d_week_seq,
           sum(case when d_day_name = 'Sunday' then sales_price else null end) sun_sales,
           sum(case when d_day_name = 'Monday' then sales_price else null end) mon_sales,
           sum(case when d_day_name = 'Tuesday' then sales_price else null end) tue_sales,
           sum(case when d_day_name = 'Wednesday' then sales_price else null end) wed_sales,
           sum(case when d_day_name = 'Thursday' then sales_price else null end) thu_sales,
           sum(case when d_day_name = 'Friday' then sales_price else null end) fri_sales,
           sum(case when d_day_name = 'Saturday' then sales_price else null end) sat_sales
    from wscs, date_dim
    where d_date_sk = sold_date_sk
    group by d_week_seq
)
select d_week_seq1,
       round(sun_sales1 / sun_sales2, 2),
       round(mon_sales1 / mon_sales2, 2),
       round(tue_sales1 / tue_sales2, 2),
       round(wed_sales1 / wed_sales2, 2),
       round(thu_sales1 / thu_sales2, 2),
       round(fri_sales1 / fri_sales2, 2),
       round(sat_sales1 / sat_sales2, 2)
from (select wswscs.d_week_seq d_week_seq1, sun_sales sun_sales1,
             mon_sales mon_sales1, tue_sales tue_sales1, wed_sales wed_sales1,
             thu_sales thu_sales1, fri_sales fri_sales1, sat_sales sat_sales1
      from wswscs, date_dim
      where date_dim.d_week_seq = wswscs.d_week_seq and d_year = 2001) y,
     (select wswscs.d_week_seq d_week_seq2, sun_sales sun_sales2,
             mon_sales mon_sales2, tue_sales tue_sales2, wed_sales wed_sales2,
             thu_sales thu_sales2, fri_sales fri_sales2, sat_sales sat_sales2
      from wswscs, date_dim
      where date_dim.d_week_seq = wswscs.d_week_seq and d_year = 2002) z
where d_week_seq1 = d_week_seq2 - 53
order by d_week_seq1
""",
    4: """
WITH
  year_total AS (
   SELECT
     c_customer_id customer_id
   , c_first_name customer_first_name
   , c_last_name customer_last_name
   , c_preferred_cust_flag customer_preferred_cust_flag
   , c_birth_country customer_birth_country
   , c_login customer_login
   , c_email_address customer_email_address
   , d_year dyear
   , sum(((((ss_ext_list_price - ss_ext_wholesale_cost) - ss_ext_discount_amt) + ss_ext_sales_price) / 2)) year_total
   , 's' sale_type
   FROM
     customer
   , store_sales
   , date_dim
   WHERE (c_customer_sk = ss_customer_sk)
      AND (ss_sold_date_sk = d_date_sk)
   GROUP BY c_customer_id, c_first_name, c_last_name, c_preferred_cust_flag, c_birth_country, c_login, c_email_address, d_year
UNION ALL    SELECT
     c_customer_id customer_id
   , c_first_name customer_first_name
   , c_last_name customer_last_name
   , c_preferred_cust_flag customer_preferred_cust_flag
   , c_birth_country customer_birth_country
   , c_login customer_login
   , c_email_address customer_email_address
   , d_year dyear
   , sum(((((cs_ext_list_price - cs_ext_wholesale_cost) - cs_ext_discount_amt) + cs_ext_sales_price) / 2)) year_total
   , 'c' sale_type
   FROM
     customer
   , catalog_sales
   , date_dim
   WHERE (c_customer_sk = cs_bill_customer_sk)
      AND (cs_sold_date_sk = d_date_sk)
   GROUP BY c_customer_id, c_first_name, c_last_name, c_preferred_cust_flag, c_birth_country, c_login, c_email_address, d_year
UNION ALL    SELECT
     c_customer_id customer_id
   , c_first_name customer_first_name
   , c_last_name customer_last_name
   , c_preferred_cust_flag customer_preferred_cust_flag
   , c_birth_country customer_birth_country
   , c_login customer_login
   , c_email_address customer_email_address
   , d_year dyear
   , sum(((((ws_ext_list_price - ws_ext_wholesale_cost) - ws_ext_discount_amt) + ws_ext_sales_price) / 2)) year_total
   , 'w' sale_type
   FROM
     customer
   , web_sales
   , date_dim
   WHERE (c_customer_sk = ws_bill_customer_sk)
      AND (ws_sold_date_sk = d_date_sk)
   GROUP BY c_customer_id, c_first_name, c_last_name, c_preferred_cust_flag, c_birth_country, c_login, c_email_address, d_year
) 
SELECT
  t_s_secyear.customer_id
, t_s_secyear.customer_first_name
, t_s_secyear.customer_last_name
, t_s_secyear.customer_preferred_cust_flag
FROM
  year_total t_s_firstyear
, year_total t_s_secyear
, year_total t_c_firstyear
, year_total t_c_secyear
, year_total t_w_firstyear
, year_total t_w_secyear
WHERE (t_s_secyear.customer_id = t_s_firstyear.customer_id)
   AND (t_s_firstyear.customer_id = t_c_secyear.customer_id)
   AND (t_s_firstyear.customer_id = t_c_firstyear.customer_id)
   AND (t_s_firstyear.customer_id = t_w_firstyear.customer_id)
   AND (t_s_firstyear.customer_id = t_w_secyear.customer_id)
   AND (t_s_firstyear.sale_type = 's')
   AND (t_c_firstyear.sale_type = 'c')
   AND (t_w_firstyear.sale_type = 'w')
   AND (t_s_secyear.sale_type = 's')
   AND (t_c_secyear.sale_type = 'c')
   AND (t_w_secyear.sale_type = 'w')
   AND (t_s_firstyear.dyear = 2001)
   AND (t_s_secyear.dyear = (2001 + 1))
   AND (t_c_firstyear.dyear = 2001)
   AND (t_c_secyear.dyear = (2001 + 1))
   AND (t_w_firstyear.dyear = 2001)
   AND (t_w_secyear.dyear = (2001 + 1))
   AND (t_s_firstyear.year_total > 0)
   AND (t_c_firstyear.year_total > 0)
   AND (t_w_firstyear.year_total > 0)
   AND ((CASE WHEN (t_c_firstyear.year_total > 0) THEN (t_c_secyear.year_total / t_c_firstyear.year_total) ELSE null END) > (CASE WHEN (t_s_firstyear.year_total > 0) THEN (t_s_secyear.year_total / t_s_firstyear.year_total) ELSE null END))
   AND ((CASE WHEN (t_c_firstyear.year_total > 0) THEN (t_c_secyear.year_total / t_c_firstyear.year_total) ELSE null END) > (CASE WHEN (t_w_firstyear.year_total > 0) THEN (t_w_secyear.year_total / t_w_firstyear.year_total) ELSE null END))
ORDER BY t_s_secyear.customer_id ASC, t_s_secyear.customer_first_name ASC, t_s_secyear.customer_last_name ASC, t_s_secyear.customer_preferred_cust_flag ASC
LIMIT 100
""",
    9: """
SELECT
  (CASE WHEN ((
      SELECT count(*)
      FROM
        store_sales
      WHERE (ss_quantity BETWEEN 1 AND 20)
   ) > 74129) THEN (
   SELECT avg(ss_ext_discount_amt)
   FROM
     store_sales
   WHERE (ss_quantity BETWEEN 1 AND 20)
) ELSE (
   SELECT avg(ss_net_paid)
   FROM
     store_sales
   WHERE (ss_quantity BETWEEN 1 AND 20)
) END) bucket1
, (CASE WHEN ((
      SELECT count(*)
      FROM
        store_sales
      WHERE (ss_quantity BETWEEN 21 AND 40)
   ) > 122840) THEN (
   SELECT avg(ss_ext_discount_amt)
   FROM
     store_sales
   WHERE (ss_quantity BETWEEN 21 AND 40)
) ELSE (
   SELECT avg(ss_net_paid)
   FROM
     store_sales
   WHERE (ss_quantity BETWEEN 21 AND 40)
) END) bucket2
, (CASE WHEN ((
      SELECT count(*)
      FROM
        store_sales
      WHERE (ss_quantity BETWEEN 41 AND 60)
   ) > 56580) THEN (
   SELECT avg(ss_ext_discount_amt)
   FROM
     store_sales
   WHERE (ss_quantity BETWEEN 41 AND 60)
) ELSE (
   SELECT avg(ss_net_paid)
   FROM
     store_sales
   WHERE (ss_quantity BETWEEN 41 AND 60)
) END) bucket3
, (CASE WHEN ((
      SELECT count(*)
      FROM
        store_sales
      WHERE (ss_quantity BETWEEN 61 AND 80)
   ) > 10097) THEN (
   SELECT avg(ss_ext_discount_amt)
   FROM
     store_sales
   WHERE (ss_quantity BETWEEN 61 AND 80)
) ELSE (
   SELECT avg(ss_net_paid)
   FROM
     store_sales
   WHERE (ss_quantity BETWEEN 61 AND 80)
) END) bucket4
, (CASE WHEN ((
      SELECT count(*)
      FROM
        store_sales
      WHERE (ss_quantity BETWEEN 81 AND 100)
   ) > 165306) THEN (
   SELECT avg(ss_ext_discount_amt)
   FROM
     store_sales
   WHERE (ss_quantity BETWEEN 81 AND 100)
) ELSE (
   SELECT avg(ss_net_paid)
   FROM
     store_sales
   WHERE (ss_quantity BETWEEN 81 AND 100)
) END) bucket5
FROM
  reason
WHERE (r_reason_sk = 1)
""",
    11: """
WITH
  year_total AS (
   SELECT
     c_customer_id customer_id
   , c_first_name customer_first_name
   , c_last_name customer_last_name
   , c_preferred_cust_flag customer_preferred_cust_flag
   , c_birth_country customer_birth_country
   , c_login customer_login
   , c_email_address customer_email_address
   , d_year dyear
   , sum((ss_ext_list_price - ss_ext_discount_amt)) year_total
   , 's' sale_type
   FROM
     customer
   , store_sales
   , date_dim
   WHERE (c_customer_sk = ss_customer_sk)
      AND (ss_sold_date_sk = d_date_sk)
   GROUP BY c_customer_id, c_first_name, c_last_name, c_preferred_cust_flag, c_birth_country, c_login, c_email_address, d_year
UNION ALL    SELECT
     c_customer_id customer_id
   , c_first_name customer_first_name
   , c_last_name customer_last_name
   , c_preferred_cust_flag customer_preferred_cust_flag
   , c_birth_country customer_birth_country
   , c_login customer_login
   , c_email_address customer_email_address
   , d_year dyear
   , sum((ws_ext_list_price - ws_ext_discount_amt)) year_total
   , 'w' sale_type
   FROM
     customer
   , web_sales
   , date_dim
   WHERE (c_customer_sk = ws_bill_customer_sk)
      AND (ws_sold_date_sk = d_date_sk)
   GROUP BY c_customer_id, c_first_name, c_last_name, c_preferred_cust_flag, c_birth_country, c_login, c_email_address, d_year
) 
SELECT
  t_s_secyear.customer_id
, t_s_secyear.customer_first_name
, t_s_secyear.customer_last_name
, t_s_secyear.customer_preferred_cust_flag
, t_s_secyear.customer_birth_country
, t_s_secyear.customer_login
FROM
  year_total t_s_firstyear
, year_total t_s_secyear
, year_total t_w_firstyear
, year_total t_w_secyear
WHERE (t_s_secyear.customer_id = t_s_firstyear.customer_id)
   AND (t_s_firstyear.customer_id = t_w_secyear.customer_id)
   AND (t_s_firstyear.customer_id = t_w_firstyear.customer_id)
   AND (t_s_firstyear.sale_type = 's')
   AND (t_w_firstyear.sale_type = 'w')
   AND (t_s_secyear.sale_type = 's')
   AND (t_w_secyear.sale_type = 'w')
   AND (t_s_firstyear.dyear = 2001)
   AND (t_s_secyear.dyear = (2001 + 1))
   AND (t_w_firstyear.dyear = 2001)
   AND (t_w_secyear.dyear = (2001 + 1))
   AND (t_s_firstyear.year_total > 0)
   AND (t_w_firstyear.year_total > 0)
   AND ((CASE WHEN (t_w_firstyear.year_total > 0) THEN (t_w_secyear.year_total / t_w_firstyear.year_total) ELSE DECIMAL '0.0' END) > (CASE WHEN (t_s_firstyear.year_total > 0) THEN (t_s_secyear.year_total / t_s_firstyear.year_total) ELSE DECIMAL '0.0' END))
ORDER BY t_s_secyear.customer_id ASC, t_s_secyear.customer_first_name ASC, t_s_secyear.customer_last_name ASC, t_s_secyear.customer_preferred_cust_flag ASC
LIMIT 100
""",
    17: """
SELECT
  i_item_id
, i_item_desc
, s_state
, count(ss_quantity) store_sales_quantitycount
, avg(ss_quantity) store_sales_quantityave
, stddev_samp(ss_quantity) store_sales_quantitystdev
, (stddev_samp(ss_quantity) / avg(ss_quantity)) store_sales_quantitycov
, count(sr_return_quantity) store_returns_quantitycount
, avg(sr_return_quantity) store_returns_quantityave
, stddev_samp(sr_return_quantity) store_returns_quantitystdev
, (stddev_samp(sr_return_quantity) / avg(sr_return_quantity)) store_returns_quantitycov
, count(cs_quantity) catalog_sales_quantitycount
, avg(cs_quantity) catalog_sales_quantityave
, stddev_samp(cs_quantity) catalog_sales_quantitystdev
, (stddev_samp(cs_quantity) / avg(cs_quantity)) catalog_sales_quantitycov
FROM
  store_sales
, store_returns
, catalog_sales
, date_dim d1
, date_dim d2
, date_dim d3
, store
, item
WHERE (d1.d_quarter_name = '2001Q1')
   AND (d1.d_date_sk = ss_sold_date_sk)
   AND (i_item_sk = ss_item_sk)
   AND (s_store_sk = ss_store_sk)
   AND (ss_customer_sk = sr_customer_sk)
   AND (ss_item_sk = sr_item_sk)
   AND (ss_ticket_number = sr_ticket_number)
   AND (sr_returned_date_sk = d2.d_date_sk)
   AND (d2.d_quarter_name IN ('2001Q1', '2001Q2', '2001Q3'))
   AND (sr_customer_sk = cs_bill_customer_sk)
   AND (sr_item_sk = cs_item_sk)
   AND (cs_sold_date_sk = d3.d_date_sk)
   AND (d3.d_quarter_name IN ('2001Q1', '2001Q2', '2001Q3'))
GROUP BY i_item_id, i_item_desc, s_state
ORDER BY i_item_id ASC, i_item_desc ASC, s_state ASC
LIMIT 100
""",
    23: """
WITH
  frequent_ss_items AS (
   SELECT
     substr(i_item_desc, 1, 30) itemdesc
   , i_item_sk item_sk
   , d_date solddate
   , count(*) cnt
   FROM
     store_sales
   , date_dim
   , item
   WHERE (ss_sold_date_sk = d_date_sk)
      AND (ss_item_sk = i_item_sk)
      AND (d_year IN (2000   , (2000 + 1)   , (2000 + 2)   , (2000 + 3)))
   GROUP BY substr(i_item_desc, 1, 30), i_item_sk, d_date
   HAVING (count(*) > 4)
) 
, max_store_sales AS (
   SELECT max(csales) tpcds_cmax
   FROM
     (
      SELECT
        c_customer_sk
      , sum((ss_quantity * ss_sales_price)) csales
      FROM
        store_sales
      , customer
      , date_dim
      WHERE (ss_customer_sk = c_customer_sk)
         AND (ss_sold_date_sk = d_date_sk)
         AND (d_year IN (2000      , (2000 + 1)      , (2000 + 2)      , (2000 + 3)))
      GROUP BY c_customer_sk
   ) 
) 
, best_ss_customer AS (
   SELECT
     c_customer_sk
   , sum((ss_quantity * ss_sales_price)) ssales
   FROM
     store_sales
   , customer
   WHERE (ss_customer_sk = c_customer_sk)
   GROUP BY c_customer_sk
   HAVING (sum((ss_quantity * ss_sales_price)) > ((50 / DECIMAL '100.0') * (
            SELECT *
            FROM
              max_store_sales
         )))
) 
SELECT sum(sales)
FROM
  (
   SELECT (cs_quantity * cs_list_price) sales
   FROM
     catalog_sales
   , date_dim
   WHERE (d_year = 2000)
      AND (d_moy = 2)
      AND (cs_sold_date_sk = d_date_sk)
      AND (cs_item_sk IN (
      SELECT item_sk
      FROM
        frequent_ss_items
   ))
      AND (cs_bill_customer_sk IN (
      SELECT c_customer_sk
      FROM
        best_ss_customer
   ))
UNION ALL    SELECT (ws_quantity * ws_list_price) sales
   FROM
     web_sales
   , date_dim
   WHERE (d_year = 2000)
      AND (d_moy = 2)
      AND (ws_sold_date_sk = d_date_sk)
      AND (ws_item_sk IN (
      SELECT item_sk
      FROM
        frequent_ss_items
   ))
      AND (ws_bill_customer_sk IN (
      SELECT c_customer_sk
      FROM
        best_ss_customer
   ))
) 
LIMIT 100
""",
    28: """
SELECT *
FROM
  (
   SELECT
     avg(ss_list_price) b1_lp
   , count(ss_list_price) b1_cnt
   , count(DISTINCT ss_list_price) b1_cntd
   FROM
     store_sales
   WHERE (ss_quantity BETWEEN 0 AND 5)
      AND ((ss_list_price BETWEEN 8 AND (8 + 10))
         OR (ss_coupon_amt BETWEEN 459 AND (459 + 1000))
         OR (ss_wholesale_cost BETWEEN 57 AND (57 + 20)))
)  b1
, (
   SELECT
     avg(ss_list_price) b2_lp
   , count(ss_list_price) b2_cnt
   , count(DISTINCT ss_list_price) b2_cntd
   FROM
     store_sales
   WHERE (ss_quantity BETWEEN 6 AND 10)
      AND ((ss_list_price BETWEEN 90 AND (90 + 10))
         OR (ss_coupon_amt BETWEEN 2323 AND (2323 + 1000))
         OR (ss_wholesale_cost BETWEEN 31 AND (31 + 20)))
)  b2
, (
   SELECT
     avg(ss_list_price) b3_lp
   , count(ss_list_price) b3_cnt
   , count(DISTINCT ss_list_price) b3_cntd
   FROM
     store_sales
   WHERE (ss_quantity BETWEEN 11 AND 15)
      AND ((ss_list_price BETWEEN 142 AND (142 + 10))
         OR (ss_coupon_amt BETWEEN 12214 AND (12214 + 1000))
         OR (ss_wholesale_cost BETWEEN 79 AND (79 + 20)))
)  b3
, (
   SELECT
     avg(ss_list_price) b4_lp
   , count(ss_list_price) b4_cnt
   , count(DISTINCT ss_list_price) b4_cntd
   FROM
     store_sales
   WHERE (ss_quantity BETWEEN 16 AND 20)
      AND ((ss_list_price BETWEEN 135 AND (135 + 10))
         OR (ss_coupon_amt BETWEEN 6071 AND (6071 + 1000))
         OR (ss_wholesale_cost BETWEEN 38 AND (38 + 20)))
)  b4
, (
   SELECT
     avg(ss_list_price) b5_lp
   , count(ss_list_price) b5_cnt
   , count(DISTINCT ss_list_price) b5_cntd
   FROM
     store_sales
   WHERE (ss_quantity BETWEEN 21 AND 25)
      AND ((ss_list_price BETWEEN 122 AND (122 + 10))
         OR (ss_coupon_amt BETWEEN 836 AND (836 + 1000))
         OR (ss_wholesale_cost BETWEEN 17 AND (17 + 20)))
)  b5
, (
   SELECT
     avg(ss_list_price) b6_lp
   , count(ss_list_price) b6_cnt
   , count(DISTINCT ss_list_price) b6_cntd
   FROM
     store_sales
   WHERE (ss_quantity BETWEEN 26 AND 30)
      AND ((ss_list_price BETWEEN 154 AND (154 + 10))
         OR (ss_coupon_amt BETWEEN 7326 AND (7326 + 1000))
         OR (ss_wholesale_cost BETWEEN 7 AND (7 + 20)))
)  b6
LIMIT 100
""",
    38: """
select count(*) from (
    select distinct c_last_name, c_first_name, d_date
    from store_sales, date_dim, customer
    where store_sales.ss_sold_date_sk = date_dim.d_date_sk
      and store_sales.ss_customer_sk = customer.c_customer_sk
      and d_month_seq between 1200 and 1200 + 11
    intersect
    select distinct c_last_name, c_first_name, d_date
    from catalog_sales, date_dim, customer
    where catalog_sales.cs_sold_date_sk = date_dim.d_date_sk
      and catalog_sales.cs_bill_customer_sk = customer.c_customer_sk
      and d_month_seq between 1200 and 1200 + 11
    intersect
    select distinct c_last_name, c_first_name, d_date
    from web_sales, date_dim, customer
    where web_sales.ws_sold_date_sk = date_dim.d_date_sk
      and web_sales.ws_bill_customer_sk = customer.c_customer_sk
      and d_month_seq between 1200 and 1200 + 11
) hot_cust
limit 100
""",
    87: """
select count(*) from (
    select distinct c_last_name, c_first_name, d_date
    from store_sales, date_dim, customer
    where store_sales.ss_sold_date_sk = date_dim.d_date_sk
      and store_sales.ss_customer_sk = customer.c_customer_sk
      and d_month_seq between 1200 and 1200 + 11
    except
    select distinct c_last_name, c_first_name, d_date
    from catalog_sales, date_dim, customer
    where catalog_sales.cs_sold_date_sk = date_dim.d_date_sk
      and catalog_sales.cs_bill_customer_sk = customer.c_customer_sk
      and d_month_seq between 1200 and 1200 + 11
    except
    select distinct c_last_name, c_first_name, d_date
    from web_sales, date_dim, customer
    where web_sales.ws_sold_date_sk = date_dim.d_date_sk
      and web_sales.ws_bill_customer_sk = customer.c_customer_sk
      and d_month_seq between 1200 and 1200 + 11
) cool_cust
""",
}
