"""TPC-DS benchmark queries (reference: the public spec query templates as
shipped under testing/trino-benchmark-queries/.../tpcds/*.sql).

Adaptations for this engine's dialect (noted per reference behavior, not
semantics): aggregate ORDER BY keys are aliased, `${database}.${schema}.`
prefixes dropped.  Q64 is baseline config #4 (BASELINE.md).
"""

QUERIES = {
    1: """
with customer_total_return as (
    select sr_customer_sk as ctr_customer_sk,
           sr_store_sk as ctr_store_sk,
           sum(sr_return_amt) as ctr_total_return
    from store_returns, date_dim
    where sr_returned_date_sk = d_date_sk and d_year = 2000
    group by sr_customer_sk, sr_store_sk
)
select c_customer_id
from customer_total_return ctr1, store, customer
where ctr1.ctr_total_return > (
        select avg(ctr_total_return) * 1.2
        from customer_total_return ctr2
        where ctr1.ctr_store_sk = ctr2.ctr_store_sk)
  and s_store_sk = ctr1.ctr_store_sk
  and s_state = 'TN'
  and ctr1.ctr_customer_sk = c_customer_sk
order by c_customer_id
limit 100
""",
    3: """
select dt.d_year, item.i_brand_id as brand_id, item.i_brand as brand,
       sum(ss_ext_sales_price) as sum_agg
from date_dim dt, store_sales, item
where dt.d_date_sk = store_sales.ss_sold_date_sk
  and store_sales.ss_item_sk = item.i_item_sk
  and item.i_manufact_id = 128
  and dt.d_moy = 11
group by dt.d_year, item.i_brand_id, item.i_brand
order by dt.d_year, sum_agg desc, brand_id
limit 100
""",
    7: """
select i_item_id,
       avg(ss_quantity) as agg1,
       avg(ss_list_price) as agg2,
       avg(ss_coupon_amt) as agg3,
       avg(ss_sales_price) as agg4
from store_sales, customer_demographics, date_dim, item, promotion
where ss_sold_date_sk = d_date_sk
  and ss_item_sk = i_item_sk
  and ss_cdemo_sk = cd_demo_sk
  and ss_promo_sk = p_promo_sk
  and cd_gender = 'M'
  and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and (p_channel_email = 'N' or p_channel_event = 'N')
  and d_year = 2000
group by i_item_id
order by i_item_id
limit 100
""",
    42: """
select dt.d_year, item.i_category_id, item.i_category,
       sum(ss_ext_sales_price) as total_sales
from date_dim dt, store_sales, item
where dt.d_date_sk = store_sales.ss_sold_date_sk
  and store_sales.ss_item_sk = item.i_item_sk
  and item.i_manager_id = 1
  and dt.d_moy = 11
  and dt.d_year = 2000
group by dt.d_year, item.i_category_id, item.i_category
order by total_sales desc, dt.d_year, item.i_category_id, item.i_category
limit 100
""",
    52: """
select dt.d_year, item.i_brand_id as brand_id, item.i_brand as brand,
       sum(ss_ext_sales_price) as ext_price
from date_dim dt, store_sales, item
where dt.d_date_sk = store_sales.ss_sold_date_sk
  and store_sales.ss_item_sk = item.i_item_sk
  and item.i_manager_id = 1
  and dt.d_moy = 11
  and dt.d_year = 2000
group by dt.d_year, item.i_brand_id, item.i_brand
order by dt.d_year, ext_price desc, brand_id
limit 100
""",
    55: """
select i_brand_id as brand_id, i_brand as brand,
       sum(ss_ext_sales_price) as ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id = 28
  and d_moy = 11
  and d_year = 1999
group by i_brand_id, i_brand
order by ext_price desc, brand_id
limit 100
""",
    68: """
select c_last_name, c_first_name, ca_city, bought_city, ss_ticket_number,
       extended_price, extended_tax, list_price
from (
    select ss_ticket_number, ss_customer_sk, ca_city as bought_city,
           sum(ss_ext_sales_price) as extended_price,
           sum(ss_ext_list_price) as list_price,
           sum(ss_ext_tax) as extended_tax
    from store_sales, date_dim, store, household_demographics, customer_address
    where store_sales.ss_sold_date_sk = date_dim.d_date_sk
      and store_sales.ss_store_sk = store.s_store_sk
      and store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
      and store_sales.ss_addr_sk = customer_address.ca_address_sk
      and date_dim.d_dom between 1 and 2
      and (household_demographics.hd_dep_count = 4
           or household_demographics.hd_vehicle_count = 3)
      and date_dim.d_year in (1999, 2000, 2001)
      and store.s_city in ('Fairview', 'Midway')
    group by ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city
) dn, customer, customer_address current_addr
where ss_customer_sk = c_customer_sk
  and customer.c_current_addr_sk = current_addr.ca_address_sk
  and current_addr.ca_city <> bought_city
order by c_last_name, ss_ticket_number
limit 100
""",
    96: """
select count(*) as cnt
from store_sales, household_demographics, time_dim, store
where ss_sold_time_sk = time_dim.t_time_sk
  and ss_hdemo_sk = household_demographics.hd_demo_sk
  and ss_store_sk = s_store_sk
  and time_dim.t_hour = 20
  and time_dim.t_minute >= 30
  and household_demographics.hd_dep_count = 7
  and store.s_store_name = 'ese'
""",
    64: """
with cs_ui as (
    select cs_item_sk,
           sum(cs_ext_list_price) as sale,
           sum(cr_refunded_cash + cr_reversed_charge + cr_store_credit) as refund
    from catalog_sales, catalog_returns
    where cs_item_sk = cr_item_sk
      and cs_order_number = cr_order_number
    group by cs_item_sk
    having sum(cs_ext_list_price) >
           2 * sum(cr_refunded_cash + cr_reversed_charge + cr_store_credit)
),
cross_sales as (
    select i_product_name as product_name, i_item_sk as item_sk,
           s_store_name as store_name, s_zip as store_zip,
           ad1.ca_street_number as b_street_number,
           ad1.ca_street_name as b_street_name,
           ad1.ca_city as b_city, ad1.ca_zip as b_zip,
           ad2.ca_street_number as c_street_number,
           ad2.ca_street_name as c_street_name,
           ad2.ca_city as c_city, ad2.ca_zip as c_zip,
           d1.d_year as syear, d2.d_year as fsyear, d3.d_year as s2year,
           count(*) as cnt,
           sum(ss_wholesale_cost) as s1,
           sum(ss_list_price) as s2,
           sum(ss_coupon_amt) as s3
    from store_sales, store_returns, cs_ui,
         date_dim d1, date_dim d2, date_dim d3,
         store, customer, customer_demographics cd1, customer_demographics cd2,
         promotion, household_demographics hd1, household_demographics hd2,
         customer_address ad1, customer_address ad2,
         income_band ib1, income_band ib2, item
    where ss_store_sk = s_store_sk
      and ss_sold_date_sk = d1.d_date_sk
      and ss_customer_sk = c_customer_sk
      and ss_cdemo_sk = cd1.cd_demo_sk
      and ss_hdemo_sk = hd1.hd_demo_sk
      and ss_addr_sk = ad1.ca_address_sk
      and ss_item_sk = i_item_sk
      and ss_item_sk = sr_item_sk
      and ss_ticket_number = sr_ticket_number
      and ss_item_sk = cs_ui.cs_item_sk
      and c_current_cdemo_sk = cd2.cd_demo_sk
      and c_current_hdemo_sk = hd2.hd_demo_sk
      and c_current_addr_sk = ad2.ca_address_sk
      and c_first_sales_date_sk = d2.d_date_sk
      and c_first_shipto_date_sk = d3.d_date_sk
      and ss_promo_sk = p_promo_sk
      and hd1.hd_income_band_sk = ib1.ib_income_band_sk
      and hd2.hd_income_band_sk = ib2.ib_income_band_sk
      and cd1.cd_marital_status <> cd2.cd_marital_status
      and i_color in ('purple', 'burlywood', 'indian', 'spring', 'floral', 'medium')
      and i_current_price between 64 and 64 + 10
      and i_current_price between 64 + 1 and 64 + 15
    group by i_product_name, i_item_sk, s_store_name, s_zip,
             ad1.ca_street_number, ad1.ca_street_name, ad1.ca_city, ad1.ca_zip,
             ad2.ca_street_number, ad2.ca_street_name, ad2.ca_city, ad2.ca_zip,
             d1.d_year, d2.d_year, d3.d_year
)
select cs1.product_name, cs1.store_name, cs1.store_zip,
       cs1.b_street_number, cs1.b_street_name, cs1.b_city, cs1.b_zip,
       cs1.c_street_number, cs1.c_street_name, cs1.c_city, cs1.c_zip,
       cs1.syear as syear1, cs1.cnt as cnt1, cs1.s1 as s11, cs1.s2 as s21, cs1.s3 as s31,
       cs2.s1 as s12, cs2.s2 as s22, cs2.s3 as s32, cs2.syear as syear2, cs2.cnt as cnt2
from cross_sales cs1, cross_sales cs2
where cs1.item_sk = cs2.item_sk
  and cs1.syear = 1999
  and cs2.syear = 1999 + 1
  and cs2.cnt <= cs1.cnt
  and cs1.store_name = cs2.store_name
  and cs1.store_zip = cs2.store_zip
order by cs1.product_name, cs1.store_name, cnt2, s12, s22
""",
}
