"""TPC-DS connector plumbing (reference: plugin/trino-tpcds —
TpcdsConnectorFactory.java / TpcdsMetadata.java / TpcdsSplitManager;
row-range splits mirror TpcdsSplitManager's per-node partitioning)."""

from __future__ import annotations

import math

from trino_tpu.connectors.api import (
    ColumnMeta,
    ColumnStatistics,
    Connector,
    ConnectorMetadata,
    PageSource,
    Split,
    TableHandle,
    TableMetadata,
    TableStatistics,
)
from trino_tpu.connectors.tpcds import schema as ds_schema
from trino_tpu.connectors.tpcds.generator import TpcdsGenerator, generator


class TpcdsMetadata(ConnectorMetadata):
    def list_schemas(self):
        return sorted(ds_schema.SCHEMAS)

    def list_tables(self, schema: str):
        ds_schema.schema_scale(schema)
        return sorted(ds_schema.TABLES)

    def table_metadata(self, schema: str, table: str) -> TableMetadata:
        ds_schema.schema_scale(schema)
        if table not in ds_schema.TABLES:
            raise KeyError(f"tpcds table not found: {table}")
        cols = tuple(
            ColumnMeta(name, t) for name, t in ds_schema.column_types(table)
        )
        return TableMetadata(schema, table, cols)

    def table_statistics(self, schema: str, table: str) -> TableStatistics:
        sf = ds_schema.schema_scale(schema)
        gen = generator(sf)
        rows = gen.row_count(table)
        cols = {}
        pk = ds_schema.TABLES[table][0][0]
        if pk.endswith("_sk"):
            cols[pk] = ColumnStatistics(distinct_count=rows, low=1, high=rows)
        return TableStatistics(row_count=rows, columns=cols)


class TpcdsPageSource(PageSource):
    def __init__(self, gen: TpcdsGenerator, split: Split, columns, page_rows: int):
        self.gen = gen
        self.split = split
        self.columns = list(columns)
        self.page_rows = page_rows

    def row_count(self) -> int:
        return self.split.row_count

    def pages(self):
        t = self.split.table.table
        start, remaining = self.split.row_start, self.split.row_count
        while remaining > 0:
            n = min(self.page_rows, remaining)
            yield [self.gen.column(t, c, start, n) for c in self.columns]
            start += n
            remaining -= n


class TpcdsConnector(Connector):
    name = "tpcds"

    def __init__(self):
        self._metadata = TpcdsMetadata()

    def metadata(self) -> TpcdsMetadata:
        return self._metadata

    def scan_version(self, handle):
        return 0  # generated data is immutable per (schema, table)

    def splits(self, handle: TableHandle, target_splits: int, predicate=None):
        sf = ds_schema.schema_scale(handle.schema)
        n = generator(sf).row_count(handle.table)
        nsplits = max(1, min(target_splits, math.ceil(n / 1024)))
        per = math.ceil(n / nsplits)
        out = []
        for i in range(nsplits):
            a = i * per
            b = min(n, a + per)
            if a >= b:
                break
            out.append(Split(handle, i, row_start=a, row_count=b - a))
        return out

    def page_source(self, split: Split, columns, max_rows_per_page: int = 1 << 20):
        sf = ds_schema.schema_scale(split.table.schema)
        return TpcdsPageSource(generator(sf), split, columns, max_rows_per_page)
