"""TPC-DS connector plumbing (reference: plugin/trino-tpcds —
TpcdsConnectorFactory.java / TpcdsMetadata.java / TpcdsSplitManager;
row-range splits mirror TpcdsSplitManager's per-node partitioning)."""

from __future__ import annotations

import math

from trino_tpu.connectors.api import (
    ColumnMeta,
    ColumnStatistics,
    Connector,
    ConnectorMetadata,
    PageSource,
    Split,
    TableHandle,
    TableMetadata,
    TableStatistics,
)
from trino_tpu.connectors.tpcds import schema as ds_schema
from trino_tpu.connectors.tpcds.generator import TpcdsGenerator, generator


class TpcdsMetadata(ConnectorMetadata):
    def list_schemas(self):
        return sorted(ds_schema.SCHEMAS)

    def list_tables(self, schema: str):
        ds_schema.schema_scale(schema)
        return sorted(ds_schema.TABLES)

    def table_metadata(self, schema: str, table: str) -> TableMetadata:
        ds_schema.schema_scale(schema)
        if table not in ds_schema.TABLES:
            raise KeyError(f"tpcds table not found: {table}")
        cols = tuple(
            ColumnMeta(name, t) for name, t in ds_schema.column_types(table)
        )
        return TableMetadata(schema, table, cols)

    def table_statistics(self, schema: str, table: str) -> TableStatistics:
        """Column stats derived from the generator's own rules (reference:
        plugin/trino-tpcds/.../statistics/ precomputed stats files): surrogate
        PKs are dense 1..n; FKs inherit the referenced dimension's key range;
        date FKs span the SALES window; fact-table FKs are ~4% NULL."""
        from trino_tpu.connectors.tpcds.generator import (
            _FACTS,
            _FK_SUFFIX,
            SALES_DAYS,
            SALES_START,
        )

        sf = ds_schema.schema_scale(schema)
        gen = generator(sf)
        rows = gen.row_count(table)
        cols = {}
        is_fact = table in _FACTS
        nullf = 0.04 if is_fact else 0.0
        pk = ds_schema.TABLES[table][0][0]
        for name, _t in ds_schema.TABLES[table]:
            if name == pk and name.endswith("_sk") and not is_fact:
                # dense surrogate key: the distinct count is a structural
                # fact, admissible as a uniqueness proof.  time_dim's PK
                # is 0-based (generator._t_time_dim returns the raw row
                # index) where every other dimension PK is 1-based
                # (idx + 1); claiming [1, rows] for it was unsound.
                # d_date_sk is julian-based, overridden below.
                lo = 0 if table == "time_dim" else 1
                cols[name] = ColumnStatistics(
                    distinct_count=rows, low=lo, high=lo + rows - 1,
                    exact_distinct=True,
                )
                continue
            if name.endswith("_date_sk"):
                # returns tables lag their parent sale by 1..90 days
                # (generator._return_column), so the returned-date range
                # extends past the sales window — the plain sales-window
                # claim was UNSOUND for *_returned_date_sk (caught by the
                # stats-vs-generator validation test)
                lag = 90 if name.endswith("_returned_date_sk") else 0
                cols[name] = ColumnStatistics(
                    distinct_count=min(rows, SALES_DAYS + lag),
                    low=SALES_START + (1 if lag else 0),
                    high=SALES_START + SALES_DAYS - 1 + lag,
                    null_fraction=nullf,
                )
                continue
            if name.endswith("_time_sk"):
                cols[name] = ColumnStatistics(
                    distinct_count=min(rows, 86_400), low=0, high=86_399,
                    null_fraction=nullf,
                )
                continue
            for suffix, ref in _FK_SUFFIX:
                if name.endswith(suffix):
                    ref_rows = gen.row_count(ref)
                    cols[name] = ColumnStatistics(
                        distinct_count=min(rows, ref_rows),
                        low=1,
                        high=ref_rows,
                        null_fraction=nullf,
                    )
                    break
            if name in cols:
                continue
            # generic-rule ranges: exact by construction (the generator's
            # own randint bounds), admissible for numeric/capacity proofs —
            # quantity/price/measure columns stop reading as full-dtype
            rng = gen.column_range(table, name)
            if rng is not None:
                cols[name] = ColumnStatistics(low=rng[0], high=rng[1])
        if table == "date_dim":
            import numpy as np

            base = np.datetime64("1900-01-01")
            from trino_tpu.connectors.tpcds.generator import JULIAN_1900

            # the calendar runs `rows` consecutive days from 1900-01-01;
            # every derived sequence below is an exact function of the row
            # index (see generator._t_date_dim), so these bounds are the
            # generator's own rules, not estimates
            months0_max = int(
                (base + np.timedelta64(max(0, rows - 1), "D"))
                .astype("datetime64[M]")
                .astype(np.int64)
            ) + 70 * 12
            cols["d_date_sk"] = ColumnStatistics(
                # FIX: the dense-PK rule above claimed [1, rows], but
                # d_date_sk is julian-day based (idx + JULIAN_1900) — the
                # old claim was unsound for any proof reading it
                distinct_count=rows, low=JULIAN_1900,
                high=JULIAN_1900 + rows - 1, exact_distinct=True,
            )
            cols["d_year"] = ColumnStatistics(
                distinct_count=201, low=1900, high=2100
            )
            cols["d_fy_year"] = cols["d_year"]
            cols["d_date"] = ColumnStatistics(
                distinct_count=rows, exact_distinct=True,
                low=int((base - np.datetime64("1970-01-01")).astype(int)),
                high=int((base - np.datetime64("1970-01-01")).astype(int)) + rows,
            )
            cols["d_moy"] = ColumnStatistics(distinct_count=12, low=1, high=12)
            cols["d_dom"] = ColumnStatistics(distinct_count=31, low=1, high=31)
            cols["d_dow"] = ColumnStatistics(distinct_count=7, low=0, high=6)
            cols["d_qoy"] = ColumnStatistics(distinct_count=4, low=1, high=4)
            week_hi = rows // 7 + 1
            cols["d_week_seq"] = ColumnStatistics(
                distinct_count=week_hi, low=1, high=week_hi
            )
            cols["d_fy_week_seq"] = cols["d_week_seq"]
            cols["d_month_seq"] = ColumnStatistics(
                distinct_count=months0_max + 1, low=0, high=months0_max
            )
            quarter_hi = months0_max // 3 + 1
            cols["d_quarter_seq"] = ColumnStatistics(
                distinct_count=quarter_hi, low=1, high=quarter_hi
            )
            cols["d_fy_quarter_seq"] = cols["d_quarter_seq"]
        return TableStatistics(row_count=rows, columns=cols)


class TpcdsPageSource(PageSource):
    def __init__(self, gen: TpcdsGenerator, split: Split, columns, page_rows: int):
        self.gen = gen
        self.split = split
        self.columns = list(columns)
        self.page_rows = page_rows

    def row_count(self) -> int:
        return self.split.row_count

    def pages(self):
        t = self.split.table.table
        start, remaining = self.split.row_start, self.split.row_count
        while remaining > 0:
            n = min(self.page_rows, remaining)
            yield [self.gen.column(t, c, start, n) for c in self.columns]
            start += n
            remaining -= n


class TpcdsConnector(Connector):
    name = "tpcds"

    def __init__(self):
        self._metadata = TpcdsMetadata()

    def metadata(self) -> TpcdsMetadata:
        return self._metadata

    def scan_version(self, handle):
        return 0  # generated data is immutable per (schema, table)

    def global_dictionary(self, handle: TableHandle, column: str):
        """tpcds string columns code against one trace-stable dictionary
        per (table, column, scale factor).  String ``*_id`` business keys
        on dimension tables are idx-coded null-free bijections (generic
        rule + d_date_id: code == row index, dictionary size == row
        count), so they carry the `unique` capacity claim."""
        from trino_tpu.connectors.tpcds.generator import _FACTS

        try:
            sf = ds_schema.schema_scale(handle.schema)
            gen = generator(sf)
            d = gen.dictionary(handle.table, column)
        except (KeyError, ValueError):
            return None
        if d is None:
            return None
        unique = (
            handle.table not in _FACTS
            and column.endswith("_id")
            and len(d.values) == gen.row_count(handle.table)
        )
        return d, unique

    def splits(self, handle: TableHandle, target_splits: int, predicate=None):
        sf = ds_schema.schema_scale(handle.schema)
        n = generator(sf).row_count(handle.table)
        nsplits = max(1, min(target_splits, math.ceil(n / 1024)))
        per = math.ceil(n / nsplits)
        out = []
        for i in range(nsplits):
            a = i * per
            b = min(n, a + per)
            if a >= b:
                break
            out.append(Split(handle, i, row_start=a, row_count=b - a))
        return out

    def page_source(self, split: Split, columns, max_rows_per_page: int = 1 << 20):
        sf = ds_schema.schema_scale(split.table.schema)
        return TpcdsPageSource(generator(sf), split, columns, max_rows_per_page)
