"""TPC-DS generator connector (reference: plugin/trino-tpcds —
TpcdsConnectorFactory/TpcdsMetadata/TpcdsRecordSet over the teradata dsdgen
port).  Schema/row-counts follow the public TPC-DS spec; data is produced by
the same counter-based vectorized generator design as the tpch connector
(pure function of (table, column, row)), not a dsdgen port — distributions
are simplified but key structure, FK consistency, calendar dimensions, and
sales/returns linkage are spec-shaped, and the pandas oracle runs over the
identical data.
"""

from trino_tpu.connectors.tpcds.connector import TpcdsConnector
