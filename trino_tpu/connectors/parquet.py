"""Parquet connector: columnar files -> device batches.

Reference roles: lib/trino-parquet (vectorized ParquetReader) +
plugin/trino-hive/.../parquet/ParquetPageSourceFactory.java:106 + the
filesystem SPI (lib/trino-filesystem).  The host-side decode is pyarrow's
vectorized reader; pages are row-group slices projected to the requested
columns and converted to the engine's columnar form (numerics as numpy,
strings dictionary-encoded, short decimals as scaled int64, dates as day
numbers) — which then ride the same buffer-pool/prefetch feed as generated
tables (BASELINE config #5's PageSource -> scan path).

Layout: root_dir/<schema>/<table>.parquet or root_dir/<schema>/<table>/
(directory of part files).  Files are immutable while registered: the scan
version is the (path, mtime, size) set, so the device buffer pool may cache
row groups.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

from trino_tpu import types as T
from trino_tpu.columnar import StringDictionary
from trino_tpu.connectors.api import (
    ColumnData,
    ColumnMeta,
    Connector,
    ConnectorMetadata,
    PageSource,
    Split,
    TableHandle,
    TableMetadata,
    TableStatistics,
)


def _arrow_to_type(at) -> T.Type:
    import pyarrow as pa

    if pa.types.is_boolean(at):
        return T.BOOLEAN
    if pa.types.is_int8(at) or pa.types.is_int16(at):
        return T.SMALLINT
    if pa.types.is_int32(at):
        return T.INTEGER
    if pa.types.is_int64(at):
        return T.BIGINT
    if pa.types.is_float32(at):
        return T.REAL
    if pa.types.is_float64(at):
        return T.DOUBLE
    if pa.types.is_decimal(at):
        if at.precision > 38:
            raise NotImplementedError(
                f"decimal({at.precision},{at.scale}) exceeds precision 38"
            )
        return T.DecimalType(at.precision, at.scale)
    if pa.types.is_date(at):
        return T.DATE
    if pa.types.is_timestamp(at):
        return T.TIMESTAMP
    if pa.types.is_string(at) or pa.types.is_large_string(at):
        return T.VARCHAR
    if pa.types.is_dictionary(at):
        return _arrow_to_type(at.value_type)
    raise NotImplementedError(f"parquet/arrow type {at}")


def _array_to_column_data(arr, t: T.Type) -> ColumnData:
    """One arrow chunk -> engine host column."""
    import pyarrow as pa
    import pyarrow.compute as pc

    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    valid = None
    if arr.null_count:
        valid = np.asarray(pc.is_valid(arr))
    if T.is_string_kind(t):
        # dictionary-encode on host: device kernels operate on rank codes
        dict_arr = arr.dictionary_encode() if not pa.types.is_dictionary(arr.type) else arr
        values = [
            "" if v is None else str(v) for v in dict_arr.dictionary.to_pylist()
        ]
        if not values:  # all-null (or empty) column: one placeholder entry
            values = [""]
        d = StringDictionary.from_unsorted(values)
        remap = np.fromiter(
            (d.index[v] for v in values), dtype=np.int32, count=len(values)
        )
        codes = np.asarray(dict_arr.indices.fill_null(0))
        return ColumnData(remap[np.clip(codes.astype(np.int64), 0, len(remap) - 1)], valid, d)
    if isinstance(t, T.DecimalType) and t.is_long:
        # arrow decimal128 stores each value as 16 little-endian two's
        # complement bytes == exactly our (lo, hi) limb pair; a buffer view
        # avoids any per-row Python arithmetic (the arrow scale matches the
        # engine type's scale by construction of _arrow_to_type)
        buf = arr.buffers()[1]
        words = np.frombuffer(buf, dtype="<i8", count=2 * (arr.offset + len(arr)))
        words = words[2 * arr.offset :].reshape(-1, 2)
        out = np.empty((len(arr), 2), dtype=np.int64)
        out[:, 0] = words[:, 1]  # high limb
        out[:, 1] = words[:, 0]  # low limb bit pattern
        valid = (
            None
            if arr.null_count == 0
            else np.asarray(arr.is_valid())
        )
        if valid is not None:
            out[~valid] = 0
        return ColumnData(out, valid, None)
    if isinstance(t, T.DecimalType):
        # arrow decimal -> unscaled int64 (the engine's cents representation)
        if t.precision <= 15:
            # scaled values stay within float64's exact-integer range
            ints = pc.multiply(
                pc.cast(arr.fill_null(0), pa.float64()), 10.0 ** t.scale
            )
            data = np.rint(np.asarray(ints)).astype(np.int64)
        else:
            # exact path: Decimal objects -> unscaled ints (float64 would
            # corrupt >15-digit values)
            data = np.fromiter(
                (
                    0 if d is None else int(d.scaleb(t.scale))
                    for d in arr.to_pylist()
                ),
                dtype=np.int64,
                count=len(arr),
            )
        return ColumnData(data, valid)
    if t is T.DATE:
        data = np.asarray(arr.fill_null(0).cast(pa.int32()))
        return ColumnData(data.astype(np.int32), valid)
    if t is T.TIMESTAMP:
        us = arr.fill_null(0).cast(pa.timestamp("us")).cast(pa.int64())
        return ColumnData(np.asarray(us), valid)
    fill = False if pa.types.is_boolean(arr.type) else 0
    data = np.asarray(arr.fill_null(fill))
    return ColumnData(np.ascontiguousarray(data), valid)


class _ParquetMetadata(ConnectorMetadata):
    def __init__(self, conn: "ParquetConnector"):
        self.conn = conn

    def list_schemas(self) -> Sequence[str]:
        root = self.conn.root
        return sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d))
        )

    def list_tables(self, schema: str) -> Sequence[str]:
        out = []
        base = os.path.join(self.conn.root, schema)
        if not os.path.isdir(base):
            return []
        for name in os.listdir(base):
            p = os.path.join(base, name)
            if name.endswith(".parquet") and os.path.isfile(p):
                out.append(name[: -len(".parquet")])
            elif os.path.isdir(p):
                out.append(name)
        return sorted(out)

    def table_metadata(self, schema: str, table: str) -> TableMetadata:
        import pyarrow.parquet as pq

        files = self.conn._files(schema, table)
        if not files:
            raise KeyError(f"parquet table not found: {schema}.{table}")
        arrow_schema = pq.read_schema(files[0])
        cols = tuple(
            ColumnMeta(f.name, _arrow_to_type(f.type)) for f in arrow_schema
        )
        return TableMetadata(schema, table, cols)

    def table_statistics(self, schema: str, table: str) -> TableStatistics:
        import pyarrow.parquet as pq

        rows = 0
        for f in self.conn._files(schema, table):
            rows += pq.ParquetFile(f).metadata.num_rows
        return TableStatistics(row_count=rows)


class _ParquetPageSource(PageSource):
    def __init__(self, split: Split, columns, types, page_rows: int):
        self.split = split
        self.columns = list(columns)
        self.types = list(types)
        self.page_rows = page_rows

    def row_count(self) -> int:
        return self.split.row_count

    def pages(self):
        import pyarrow.parquet as pq

        path, row_group = self.split.info
        pf = pq.ParquetFile(path)
        tbl = pf.read_row_group(row_group, columns=self.columns)
        n = tbl.num_rows
        for start in range(0, max(n, 1), self.page_rows):
            chunk = tbl.slice(start, self.page_rows)
            if chunk.num_rows == 0 and start > 0:
                break
            yield [
                _array_to_column_data(chunk.column(i), t)
                for i, t in enumerate(self.types)
            ]


class ParquetConnector(Connector):
    """reference roles: plugin/trino-hive's parquet read path, minus the
    metastore — tables are files under a root directory."""

    name = "parquet"

    def __init__(self, root: str):
        self.root = root
        self._metadata = _ParquetMetadata(self)

    def metadata(self) -> _ParquetMetadata:
        return self._metadata

    def _files(self, schema: str, table: str) -> list:
        base = os.path.join(self.root, schema)
        single = os.path.join(base, table + ".parquet")
        if os.path.isfile(single):
            return [single]
        d = os.path.join(base, table)
        if os.path.isdir(d):
            return sorted(
                os.path.join(d, f)
                for f in os.listdir(d)
                if f.endswith(".parquet")
            )
        return []

    def scan_version(self, handle: TableHandle):
        files = self._files(handle.schema, handle.table)
        try:
            return tuple(
                (f, int(os.path.getmtime(f)), os.path.getsize(f))
                for f in files
            )
        except OSError:
            return None

    def splits(self, handle: TableHandle, target_splits: int, predicate=None):
        """One split per row group (the reference's parquet split unit)."""
        import pyarrow.parquet as pq

        out = []
        seq = 0
        row_start = 0
        for path in self._files(handle.schema, handle.table):
            meta = pq.ParquetFile(path).metadata
            for rg in range(meta.num_row_groups):
                nrows = meta.row_group(rg).num_rows
                out.append(
                    Split(
                        handle,
                        seq,
                        row_start=row_start,
                        row_count=nrows,
                        info=(path, rg),
                    )
                )
                seq += 1
                row_start += nrows
        return out

    def page_source(
        self, split: Split, columns: Sequence[str], max_rows_per_page: int = 1 << 20
    ) -> PageSource:
        meta = self._metadata.table_metadata(
            split.table.schema, split.table.table
        )
        tmap = {c.name: c.type for c in meta.columns}
        types = [tmap[c] for c in columns]
        return _ParquetPageSource(split, columns, types, max_rows_per_page)


def write_table_to_parquet(
    connector: Connector,
    schema: str,
    table: str,
    out_dir: str,
    row_group_rows: int = 1 << 20,
) -> str:
    """Export any connector table to a parquet file (test/bench fixture
    helper; reference role: the writers in lib/trino-parquet)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from trino_tpu.connectors.api import TableHandle

    meta = connector.metadata().table_metadata(schema, table)
    handle = TableHandle("src", schema, table)
    names = [c.name for c in meta.columns]
    arrays: dict = {n: [] for n in names}
    for split in connector.splits(handle, target_splits=1):
        src = connector.page_source(split, names, max_rows_per_page=row_group_rows)
        for page in src.pages():
            for n, cd, cm in zip(names, page, meta.columns):
                arrays[n].append(_column_data_to_arrow(cd, cm.type))
    cols = [pa.concat_arrays(arrays[n]) for n in names]
    tbl = pa.table(dict(zip(names, cols)))
    os.makedirs(os.path.join(out_dir, schema), exist_ok=True)
    path = os.path.join(out_dir, schema, table + ".parquet")
    pq.write_table(tbl, path, row_group_size=row_group_rows)
    return path


def _column_data_to_arrow(cd: ColumnData, t: T.Type):
    import pyarrow as pa

    vals = np.asarray(cd.values)
    mask = None if cd.valid is None else ~np.asarray(cd.valid)
    if cd.dictionary is not None:
        dvals = cd.dictionary.values
        codes = vals.astype(np.int64)
        if not dvals:  # all-null column: empty dictionary, mask covers rows
            return pa.array([None] * len(codes), type=pa.string())
        # null rows carry arbitrary codes: clip (pa.array's mask nulls the
        # masked rows regardless of the clipped placeholder value)
        arr = np.asarray(dvals, dtype=object)[
            np.clip(codes, 0, len(dvals) - 1)
        ]
        return pa.array(arr.tolist(), type=pa.string(), mask=mask)
    if isinstance(t, T.DecimalType):
        import decimal

        ctx = decimal.Context(prec=60)
        if vals.ndim == 2:  # long decimal limb planes
            from trino_tpu.types.int128 import join_py

            dec = [
                decimal.Decimal(join_py(int(h), int(l))).scaleb(
                    -t.scale, context=ctx
                )
                for h, l in vals
            ]
        else:
            dec = [
                decimal.Decimal(int(v)).scaleb(-t.scale, context=ctx)
                for v in vals
            ]
        return pa.array(dec, type=pa.decimal128(t.precision, t.scale), mask=mask)
    if t is T.DATE:
        return pa.array(vals.astype(np.int32), type=pa.date32(), mask=mask)
    if t is T.TIMESTAMP:
        return pa.array(
            vals.astype(np.int64), type=pa.timestamp("us"), mask=mask
        )
    return pa.array(vals, mask=mask)
