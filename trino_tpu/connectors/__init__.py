"""Connector plugins (reference: plugin/* — 53 modules on spi.Plugin).

Round-1 set mirrors the reference's baseline-critical connectors:
  tpch       -> plugin/trino-tpch (on-the-fly TPC-H generation at any SF)
  tpcds      -> plugin/trino-tpcds
  memory     -> plugin/trino-memory (in-RAM pages store, test workhorse)
  blackhole  -> plugin/trino-blackhole (null source/sink for perf tests)
  parquet    -> lib/trino-parquet read path (via pyarrow host decode)
"""

from trino_tpu.connectors.api import (
    Connector,
    ConnectorMetadata,
    ColumnMeta,
    TableMetadata,
    TableHandle,
    Split,
    PageSource,
    TableStatistics,
    CatalogManager,
)

__all__ = [
    "Connector",
    "ConnectorMetadata",
    "ColumnMeta",
    "TableMetadata",
    "TableHandle",
    "Split",
    "PageSource",
    "TableStatistics",
    "CatalogManager",
]
