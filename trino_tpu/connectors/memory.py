"""In-RAM connector (reference: plugin/trino-memory — MemoryPagesStore).

The test workhorse: CREATE TABLE / INSERT land host-side numpy columns;
scans serve them back as pages.  Supports the engine's write path
(page_sink) so CTAS and INSERT tests run against it.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Sequence

import numpy as np

from trino_tpu.types import Type
from trino_tpu.columnar import StringDictionary
from trino_tpu.connectors.api import (
    ColumnData,
    ColumnMeta,
    Connector,
    ConnectorMetadata,
    PageSource,
    Split,
    TableHandle,
    TableMetadata,
    TableStatistics,
)


@dataclass
class _Stored:
    meta: TableMetadata
    columns: list  # list[ColumnData], concatenated
    #: declared hash-bucketing (CREATE TABLE ... WITH (bucketed_by, ...))
    layout: object = None

    @property
    def rows(self) -> int:
        return len(self.columns[0].values) if self.columns else 0


class MemoryMetadata(ConnectorMetadata):
    def __init__(self, store):
        self.store = store

    def list_schemas(self):
        return sorted({s for s, _ in self.store})

    def list_tables(self, schema: str):
        return sorted(t for s, t in self.store if s == schema)

    def table_metadata(self, schema: str, table: str) -> TableMetadata:
        key = (schema, table)
        if key not in self.store:
            raise KeyError(f"memory table not found: {schema}.{table}")
        return self.store[key].meta

    def table_statistics(self, schema: str, table: str) -> TableStatistics:
        """Exact column stats computed from the stored data (reference:
        MemoryMetadata.getTableStatistics + the ANALYZE flow — here stats are
        always fresh because the data is resident)."""
        from trino_tpu.connectors.api import ColumnStatistics

        key = (schema, table)
        if key not in self.store:
            return TableStatistics()
        stored = self.store[key]
        cols = {}
        for meta, cd in zip(stored.meta.columns, stored.columns):
            v = np.asarray(cd.values)
            mask = (
                np.asarray(cd.valid, dtype=bool)
                if cd.valid is not None
                else np.ones(len(v), dtype=bool)
            )
            live = v[mask]
            nullf = 1.0 - (len(live) / len(v)) if len(v) else 0.0
            if len(live) == 0:
                cols[meta.name] = ColumnStatistics(0.0, nullf)
                continue
            ndv = float(len(np.unique(live)))
            lo = hi = None
            if live.dtype.kind in "iuf" and cd.dictionary is None:
                lo, hi = float(live.min()), float(live.max())
            cols[meta.name] = ColumnStatistics(ndv, nullf, lo, hi)
        return TableStatistics(row_count=stored.rows, columns=cols)


class _MemoryPageSource(PageSource):
    def __init__(self, stored: _Stored, split: Split, columns):
        self.stored = stored
        self.split = split
        self.columns = columns

    def row_count(self) -> int:
        return self.split.row_count

    def pages(self):
        a = self.split.row_start
        b = a + self.split.row_count
        ix = [self.stored.meta.column_index(c) for c in self.columns]
        if not self.stored.columns:  # created but never written: zero rows
            from trino_tpu import types as T
            from trino_tpu.columnar import StringDictionary

            out = []
            for i in ix:
                t = self.stored.meta.columns[i].type
                if T.is_string_kind(t):
                    # string columns always carry a dictionary, even empty
                    out.append(
                        ColumnData(
                            np.zeros(0, dtype=np.int32),
                            None,
                            StringDictionary.from_unsorted([""]),
                        )
                    )
                else:
                    out.append(ColumnData(np.zeros(0, dtype=t.np_dtype)))
            yield out
            return
        yield [
            ColumnData(
                self.stored.columns[i].values[a:b],
                None
                if self.stored.columns[i].valid is None
                else self.stored.columns[i].valid[a:b],
                self.stored.columns[i].dictionary,
            )
            for i in ix
        ]


class _MemorySink:
    def __init__(self, stored: _Stored, handle: TableHandle = None):
        self.stored = stored
        self.handle = handle

    def _extend_dictionary(self, column, old_d, new_d, nv):
        """Append-only dictionary merge (ROADMAP item 4a): the stored
        codes NEVER re-map — only the NEW page recodes against the
        extended value list.  The global dictionary service sees the same
        extension (`extend`: a version bump whose old codes keep their
        meaning, remap=False; NO bump at all when the page introduces no
        new values), so placement claims keyed on the assignment survive
        appends that a sorted-union remap used to invalidate."""
        from trino_tpu.columnar.dictionary import UnorderedDictionary

        old_vals = tuple(old_d.values)
        seen = set(old_vals)
        appended = [v for v in new_d.values if v not in seen]
        merged = (
            old_d
            if not appended
            else UnorderedDictionary(old_vals + tuple(appended))
        )
        index = {v: i for i, v in enumerate(merged.values)}
        rb = np.asarray(
            [index[v] for v in new_d.values], dtype=np.int64
        )
        nv = rb[nv.astype(np.int64)]
        if self.handle is not None:
            from trino_tpu.runtime.dictionary_service import (
                DICTIONARY_SERVICE,
            )

            key = (
                self.handle.catalog, self.handle.schema,
                self.handle.table, column,
            )
            try:
                ent = DICTIONARY_SERVICE.extend(key, list(new_d.values))
                if tuple(ent.dictionary.values) == tuple(merged.values):
                    # the service's epoch IS the merge: store its object
                    # so ref_of resolves the stored dictionary by identity
                    merged = ent.dictionary
            except KeyError:
                pass  # never registered: lazy lookup adopts `merged`
        return merged, nv

    def append(self, columns: Sequence[ColumnData]) -> int:
        st = self.stored
        if not st.columns:
            st.columns = list(columns)
        else:
            merged = []
            for meta, old, new in zip(st.meta.columns, st.columns, columns):
                dictionary = old.dictionary
                ov, nv = old.values, new.values
                if (old.dictionary is None) != (new.dictionary is None):
                    raise TypeError(
                        "cannot append a dictionary-encoded page to a plain "
                        "column (or vice versa)"
                    )
                if old.dictionary is not None:
                    if len(new.dictionary) == 0:
                        # an all-NULL page carries an empty dictionary; its
                        # code payload is masked, nothing to recode
                        nv = np.zeros_like(nv)
                    elif len(old.dictionary) == 0:
                        dictionary = new.dictionary
                        ov = np.zeros_like(ov)
                    else:
                        dictionary, nv = self._extend_dictionary(
                            meta.name, old.dictionary, new.dictionary, nv
                        )
                valid = None
                if old.valid is not None or new.valid is not None:
                    valid = np.concatenate(
                        [
                            old.valid
                            if old.valid is not None
                            else np.ones(len(ov), bool),
                            new.valid
                            if new.valid is not None
                            else np.ones(len(nv), bool),
                        ]
                    )
                merged.append(
                    ColumnData(np.concatenate([ov, nv]), valid, dictionary)
                )
            st.columns = merged
        return len(columns[0].values) if columns else 0


class MemoryConnector(Connector):
    name = "memory"

    def __init__(self):
        self.store: dict[tuple, _Stored] = {}
        self._metadata = MemoryMetadata(self.store)

    def metadata(self):
        return self._metadata

    def supports_writes(self) -> bool:
        return True

    def create_table(self, schema: str, table: str, columns: Sequence[ColumnMeta],
                     layout=None):
        self.store[(schema, table)] = _Stored(
            TableMetadata(schema, table, tuple(columns)), [], layout
        )

    def table_layout(self, handle: TableHandle):
        st = self.store.get((handle.schema, handle.table))
        return st.layout if st is not None else None

    def global_dictionary(self, handle: TableHandle, column: str):
        """The stored dictionary IS the global assignment — every split
        reads the same arrays.  INSERT appends extend it append-only
        (`_MemorySink._extend_dictionary` routes through
        DICTIONARY_SERVICE.extend): existing codes never re-map, a page
        of already-known values bumps NOTHING, and new values take the
        next free codes under a remap=False version bump.  No `unique`
        claim: inserted data carries no structural bijection proof."""
        st = self.store.get((handle.schema, handle.table))
        if st is None:
            return None
        for meta, cd in zip(st.meta.columns, st.columns):
            if meta.name == column:
                if cd.dictionary is None:
                    return None
                return cd.dictionary, False
        return None

    def drop_table(self, handle: TableHandle):
        self.store.pop((handle.schema, handle.table), None)

    def page_sink(self, handle: TableHandle, column_names, column_types):
        key = (handle.schema, handle.table)
        if key not in self.store:
            self.create_table(
                handle.schema,
                handle.table,
                [ColumnMeta(n, t) for n, t in zip(column_names, column_types)],
            )
        return _MemorySink(self.store[key], handle)

    def splits(self, handle: TableHandle, target_splits: int, predicate=None):
        st = self.store[(handle.schema, handle.table)]
        n = st.rows
        nsplits = max(1, min(target_splits, math.ceil(n / 4096))) if n else 1
        per = math.ceil(n / nsplits) if n else 0
        out = []
        for i in range(nsplits):
            a = i * per
            b = min(n, a + per)
            out.append(Split(handle, i, row_start=a, row_count=max(0, b - a)))
            if b >= n:
                break
        return out

    def page_source(self, split: Split, columns, max_rows_per_page: int = 1 << 20):
        st = self.store[(split.table.schema, split.table.table)]
        return _MemoryPageSource(st, split, list(columns))

    # -- transaction snapshots (InMemoryTransactionManager role) -------------

    def snapshot(self):
        """Shallow store snapshot: _MemorySink.append replaces column lists
        (never mutates arrays in place), so copying the table map and each
        table's column list captures a consistent point-in-time view."""
        return {
            key: _Stored(st.meta, list(st.columns), st.layout)
            for key, st in self.store.items()
        }

    def restore(self, snap) -> None:
        self.store.clear()
        self.store.update(snap)

    def snapshot_table(self, schema: str, table: str):
        """Table-granular snapshot (lazy transaction isolation: rollback
        touches only written tables)."""
        from trino_tpu.runtime.transactions import MISSING

        st = self.store.get((schema, table))
        if st is None:
            return MISSING
        return _Stored(st.meta, list(st.columns), st.layout)

    def restore_table(self, schema: str, table: str, snap) -> None:
        from trino_tpu.runtime.transactions import MISSING

        if snap is MISSING:
            self.store.pop((schema, table), None)
        else:
            self.store[(schema, table)] = snap
