"""Connector SPI (reference: core/trino-spi/src/main/java/io/trino/spi/connector/).

The plugin boundary: the engine sees tables as (metadata, splits, page
sources).  A PageSource yields host-side numpy column data for a split which
the scan operator turns into device Batches.  Connectors may implement
predicate pushdown (TupleDomain-style min/max pruning) and report row-count
statistics the planner uses for capacity planning — on a shape-static device,
stats are not just cost hints but *allocation* inputs.

Key interface analogs:
  Connector                -> spi/connector/Connector.java
  ConnectorMetadata        -> spi/connector/ConnectorMetadata.java:63
  ConnectorSplitManager    -> splits() here
  ConnectorPageSource      -> spi/connector/ConnectorPageSource.java:24
  TableStatistics          -> spi/statistics/TableStatistics.java
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterator, Optional, Sequence

import numpy as np

from trino_tpu.types import Type
from trino_tpu.columnar import StringDictionary


@dataclass(frozen=True)
class ColumnMeta:
    name: str
    type: Type
    #: whether the generator can bound this column's values per split
    #: (enables min/max split pruning, the TupleDomain analog)
    ordered: bool = False


@dataclass(frozen=True)
class TableMetadata:
    schema: str
    name: str
    columns: tuple[ColumnMeta, ...]

    def column_index(self, name: str) -> int:
        for i, c in enumerate(self.columns):
            if c.name == name:
                return i
        raise KeyError(name)

    def column(self, name: str) -> ColumnMeta:
        return self.columns[self.column_index(name)]


@dataclass(frozen=True)
class TableHandle:
    catalog: str
    schema: str
    table: str


@dataclass(frozen=True)
class ColumnRange:
    """Min/max bound of a column within a split (for pruning)."""

    low: object
    high: object


@dataclass(frozen=True)
class Split:
    """A unit of scan parallelism (reference: spi/connector/ConnectorSplit.java).

    `row_start`/`row_count` describe the slice for generator/memory
    connectors; file connectors put their own info in `info`.
    """

    table: TableHandle
    seq: int
    row_start: int = 0
    row_count: int = 0
    info: object = None
    #: optional per-column (name, (low, high)) ranges for pruning
    ranges: tuple = ()


@dataclass
class ColumnData:
    """Host-side column produced by a PageSource."""

    values: np.ndarray
    valid: Optional[np.ndarray] = None
    dictionary: Optional[StringDictionary] = None


class PageSource:
    """Produces host column data for one split, projected columns only."""

    def pages(self) -> Iterator[list[ColumnData]]:
        raise NotImplementedError

    def row_count(self) -> int:
        raise NotImplementedError


@dataclass(frozen=True)
class ColumnStatistics:
    distinct_count: Optional[float] = None
    null_fraction: float = 0.0
    low: object = None
    high: object = None
    #: distinct_count is STRUCTURALLY exact (dense surrogate keys, literal
    #: enumerations) rather than an estimate or probabilistic upper bound.
    #: Only exact counts are admissible as UNIQUENESS proofs
    #: (verify.capacity.unique_sets): a random FK column of a tiny table
    #: may claim ndv == rows and still collide — that claim must never
    #: license a join fanout certificate.
    exact_distinct: bool = False


@dataclass(frozen=True)
class TableStatistics:
    row_count: Optional[int] = None
    columns: dict = field(default_factory=dict)  # name -> ColumnStatistics


class ConnectorMetadata:
    def list_schemas(self) -> Sequence[str]:
        raise NotImplementedError

    def list_tables(self, schema: str) -> Sequence[str]:
        raise NotImplementedError

    def table_metadata(self, schema: str, table: str) -> TableMetadata:
        raise NotImplementedError

    def table_statistics(self, schema: str, table: str) -> TableStatistics:
        return TableStatistics()


class Connector:
    """One catalog's implementation."""

    name: str = "connector"

    def metadata(self) -> ConnectorMetadata:
        raise NotImplementedError

    def splits(
        self,
        handle: TableHandle,
        target_splits: int,
        predicate=None,
    ) -> Sequence[Split]:
        raise NotImplementedError

    def page_source(
        self,
        split: Split,
        columns: Sequence[str],
        max_rows_per_page: int = 1 << 20,
    ) -> PageSource:
        raise NotImplementedError

    def table_layout(self, handle: TableHandle):
        """Declared hash-bucketed layout of `handle`, or None (reference
        role: ConnectorMetadata.getTableProperties' partitioning handle).
        Consulted by partitioning.LayoutResolver AFTER session-property and
        engine-registry declarations."""
        return None

    def global_dictionary(self, handle: TableHandle, column: str):
        """(dictionary, unique) when every scan of `handle.column` codes its
        data against ONE dictionary that is stable across splits, workers,
        and processes — the registration source for the global dictionary
        service (runtime/dictionary_service).  `unique=True` additionally
        asserts the column is a NULL-FREE BIJECTION over the table's rows
        (dense business keys: dictionary size == row count, every row a
        distinct value) — the structural claim that makes it an
        exact_distinct uniqueness source for capacity certificates; never
        claim it for merely-probably-distinct columns.  Return None (the
        default) for producer-local coding."""
        return None

    def scan_version(self, handle: TableHandle):
        """Cache token for scan results of `handle`: scans of the same split
        + columns + version may be served from the engine's buffer pool.
        Return None (default) if the data can change without a version bump
        — such tables are never cached.  Immutable/generated tables return a
        constant.  (Reference role: the split-level caching contract file
        connectors get from immutable files + OS page cache.)"""
        return None

    # -- write path (memory/blackhole connectors; reference: ConnectorPageSink)

    def supports_writes(self) -> bool:
        return False

    def page_sink(self, handle: TableHandle, column_names, column_types):
        raise NotImplementedError

    def create_table(self, schema: str, table: str, columns) -> None:
        raise NotImplementedError

    def drop_table(self, handle: TableHandle) -> None:
        raise NotImplementedError


def scan_predicate_triples(node) -> "Optional[list]":
    """Connector-pruning triples for a TableScanNode's pushed predicate
    (None when nothing is pushed) — the one conversion both the local and
    the SPMD planner feed into `Connector.splits(predicate=...)`."""
    if node.pushed_predicate is None:
        return None
    return extract_predicate_triples(
        node.pushed_predicate, {s.name: c for s, c in node.assignments}
    )


def extract_predicate_triples(expr, sym_to_col: dict) -> list:
    """Pushed-down predicate -> [(column, op, literal-value)] conjunct
    triples a connector can prune splits/partitions with (reference role:
    TupleDomain extraction in HivePartitionManager).  Conjuncts that don't
    fit the shape are simply omitted — they still filter on device."""
    from trino_tpu.expr.ir import Call, Form, Literal, SpecialForm, SymbolRef, InputRef

    def colname(e):
        if isinstance(e, SymbolRef):
            return sym_to_col.get(e.name)
        return None

    def litval(e):
        if isinstance(e, Literal) and e.value is not None:
            return e.value
        return None

    ops = {"$eq": "=", "$lt": "<", "$le": "<=", "$gt": ">", "$ge": ">="}
    flipped = {"=": "=", "<": ">", "<=": ">=", ">": "<", ">=": "<="}
    out = []

    def walk(e):
        if isinstance(e, SpecialForm) and e.form == Form.AND:
            for a in e.args:
                walk(a)
            return
        if isinstance(e, SpecialForm) and e.form == Form.BETWEEN:
            c = colname(e.args[0])
            lo, hi = litval(e.args[1]), litval(e.args[2])
            if c is not None and lo is not None:
                out.append((c, ">=", lo))
            if c is not None and hi is not None:
                out.append((c, "<=", hi))
            return
        if isinstance(e, SpecialForm) and e.form == Form.IN:
            c = colname(e.args[0])
            vals = [litval(a) for a in e.args[1:]]
            if c is not None and all(v is not None for v in vals):
                out.append((c, "in", tuple(vals)))
            return
        if isinstance(e, Call) and e.name in ops and len(e.args) == 2:
            l, r = e.args
            c, v = colname(l), litval(r)
            if c is not None and v is not None:
                out.append((c, ops[e.name], v))
                return
            c, v = colname(r), litval(l)
            if c is not None and v is not None:
                out.append((c, flipped[ops[e.name]], v))

    walk(expr)
    return out


class CatalogManager:
    """catalog name -> Connector (reference: connector/StaticCatalogManager.java)."""

    def __init__(self):
        self._catalogs: dict[str, Connector] = {}

    def register(self, name: str, connector: Connector) -> None:
        self._catalogs[name] = connector

    def get(self, name: str) -> Connector:
        if name not in self._catalogs:
            raise KeyError(f"catalog not found: {name}")
        return self._catalogs[name]

    def names(self):
        return sorted(self._catalogs)


def default_catalogs() -> CatalogManager:
    """The standard test/bench catalog set."""
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.connectors.blackhole import BlackholeConnector

    cm = CatalogManager()
    cm.register("tpch", TpchConnector())
    cm.register("memory", MemoryConnector())
    cm.register("blackhole", BlackholeConnector())
    try:
        from trino_tpu.connectors.tpcds import TpcdsConnector

        cm.register("tpcds", TpcdsConnector())
    except ImportError:  # pragma: no cover
        pass
    return cm
