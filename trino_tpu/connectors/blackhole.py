"""Null source/sink connector (reference: plugin/trino-blackhole).

Reads produce empty (or synthetic zero-filled) pages; writes are dropped.
Used by perf tests to isolate operator cost from ingest cost.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from trino_tpu.connectors.api import (
    ColumnData,
    ColumnMeta,
    Connector,
    ConnectorMetadata,
    PageSource,
    Split,
    TableHandle,
    TableMetadata,
    TableStatistics,
)


class _BlackholeMetadata(ConnectorMetadata):
    def __init__(self, tables):
        self.tables = tables

    def list_schemas(self):
        return ["default"]

    def list_tables(self, schema: str):
        return sorted(t for s, t in self.tables if s == schema)

    def table_metadata(self, schema, table):
        return self.tables[(schema, table)]

    def table_statistics(self, schema, table):
        return TableStatistics(row_count=0)


class _EmptySource(PageSource):
    def row_count(self):
        return 0

    def pages(self):
        return iter(())


class _NullSink:
    def append(self, columns):
        return len(columns[0].values) if columns else 0


class BlackholeConnector(Connector):
    name = "blackhole"

    def __init__(self):
        self.tables: dict[tuple, TableMetadata] = {}
        self._metadata = _BlackholeMetadata(self.tables)

    def metadata(self):
        return self._metadata

    def supports_writes(self) -> bool:
        return True

    def create_table(self, schema: str, table: str, columns: Sequence[ColumnMeta]):
        self.tables[(schema, table)] = TableMetadata(schema, table, tuple(columns))

    def drop_table(self, handle: TableHandle):
        self.tables.pop((handle.schema, handle.table), None)

    def page_sink(self, handle, column_names, column_types):
        return _NullSink()

    def splits(self, handle: TableHandle, target_splits: int, predicate=None):
        return [Split(handle, 0)]

    def page_source(self, split, columns, max_rows_per_page: int = 1 << 20):
        return _EmptySource()
