"""Iceberg-analog table format: snapshot-versioned parquet tables.

Reference roles: plugin/trino-iceberg — IcebergPageSourceProvider.java:192
(data files resolved through a snapshot's manifest, read by the parquet
page source), TableStatisticsReader, the `$files`/`$history`/`$snapshots`
metadata tables, and snapshot time travel (`t@<snapshot_id>` addressing).

Layout (the metastore-less analog of Iceberg's metadata tree):

    root/<schema>/<table>/
        metadata/v<N>.json    # snapshot log; highest N is current
        data/<uuid>.parquet   # immutable data files

Every write produces a NEW metadata version whose snapshot lists the FULL
file manifest (Iceberg's manifest-list flattened — simpler, same semantics):
appends extend the parent manifest, CREATE/overwrite starts an empty one.
Old snapshots stay readable: `SELECT * FROM "t@<snapshot_id>"` reads the
manifest as of that snapshot, and DML rewrites (runner-level DELETE/UPDATE
lower to overwrite+append) preserve history instead of destroying data.
"""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Optional, Sequence

from trino_tpu import types as T
from trino_tpu.connectors.api import (
    ColumnData,
    ColumnMeta,
    Connector,
    ConnectorMetadata,
    PageSource,
    Split,
    TableHandle,
    TableMetadata,
    TableStatistics,
)

#: metadata-table suffixes (reference: iceberg's $files/$history/$snapshots)
_META_TABLES = ("$files", "$history", "$snapshots")


def _split_name(table: str) -> tuple[str, Optional[int], Optional[str]]:
    """'t@123' -> ('t', 123, None); 't$files' -> ('t', None, '$files')."""
    meta = None
    for suf in _META_TABLES:
        if table.endswith(suf):
            table, meta = table[: -len(suf)], suf
            break
    snap = None
    if "@" in table:
        base, _, tail = table.rpartition("@")
        try:
            snap = int(tail)
            table = base
        except ValueError:
            pass
    return table, snap, meta


class _IcebergMetadata(ConnectorMetadata):
    def __init__(self, conn: "IcebergConnector"):
        self.conn = conn

    def list_schemas(self) -> Sequence[str]:
        root = self.conn.root
        if not os.path.isdir(root):
            return []
        return sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d))
        )

    def list_tables(self, schema: str) -> Sequence[str]:
        base = os.path.join(self.conn.root, schema)
        if not os.path.isdir(base):
            return []
        out = []
        for d in sorted(os.listdir(base)):
            if os.path.isdir(os.path.join(base, d, "metadata")):
                out.append(d)
        return out

    def table_metadata(self, schema: str, table: str) -> TableMetadata:
        base, _snap, meta_suffix = _split_name(table)
        if meta_suffix == "$files":
            return TableMetadata(
                schema, table,
                (
                    ColumnMeta("file_path", T.VARCHAR),
                    ColumnMeta("record_count", T.BIGINT),
                    ColumnMeta("snapshot_id", T.BIGINT),
                ),
            )
        if meta_suffix == "$history":
            return TableMetadata(
                schema, table,
                (
                    ColumnMeta("snapshot_id", T.BIGINT),
                    ColumnMeta("parent_id", T.BIGINT),
                    ColumnMeta("made_current_at", T.BIGINT),
                    ColumnMeta("operation", T.VARCHAR),
                ),
            )
        if meta_suffix == "$snapshots":
            return TableMetadata(
                schema, table,
                (
                    ColumnMeta("snapshot_id", T.BIGINT),
                    ColumnMeta("committed_at", T.BIGINT),
                    ColumnMeta("operation", T.VARCHAR),
                    ColumnMeta("file_count", T.BIGINT),
                    ColumnMeta("total_records", T.BIGINT),
                ),
            )
        md = self.conn._load(schema, base)
        cols = tuple(
            ColumnMeta(c["name"], T.parse_type(c["type"]))
            for c in md["columns"]
        )
        return TableMetadata(schema, table, cols)

    def table_statistics(self, schema: str, table: str) -> TableStatistics:
        base, snap, meta_suffix = _split_name(table)
        if meta_suffix:
            return TableStatistics(row_count=None)
        md = self.conn._load(schema, base)
        s = self.conn._snapshot(md, snap)
        return TableStatistics(
            row_count=sum(f["rows"] for f in s["manifest"])
        )


class _RowsPageSource(PageSource):
    """Materialized metadata-table rows."""

    def __init__(self, columns_data: list):
        self._cols = columns_data

    def row_count(self) -> int:
        return len(self._cols[0].values) if self._cols else 0

    def pages(self):
        yield self._cols


class IcebergConnector(Connector):
    name = "iceberg"

    def __init__(self, root: str):
        from trino_tpu.filesystem import filesystem_for, strip_scheme

        # the filesystem SPI resolves the warehouse location (rejects
        # remote schemes loudly); metadata versions, snapshot commits, and
        # data-file writes go through self.fs — schema/table LISTING still
        # uses local os walks (directory-shape discovery, the remaining
        # seam when an object-store implementation lands)
        self.fs = filesystem_for(root)
        self.root = strip_scheme(root)
        self._metadata = _IcebergMetadata(self)

    def metadata(self) -> _IcebergMetadata:
        return self._metadata

    def supports_writes(self) -> bool:
        return True

    # -- metadata tree --------------------------------------------------------

    def _dir(self, schema: str, table: str) -> str:
        return os.path.join(self.root, schema, table)

    def _meta_dir(self, schema: str, table: str) -> str:
        return os.path.join(self._dir(schema, table), "metadata")

    def _versions(self, schema: str, table: str) -> list[int]:
        d = self._meta_dir(schema, table)
        out = []
        for p in self.fs.list(d):
            f = os.path.basename(p)
            if f.startswith("v") and f.endswith(".json"):
                try:
                    out.append(int(f[1:-5]))
                except ValueError:
                    pass
        return sorted(out)

    def _load(self, schema: str, table: str) -> dict:
        vs = self._versions(schema, table)
        if not vs:
            raise KeyError(f"iceberg table {schema}.{table} does not exist")
        return json.loads(
            self.fs.read(
                os.path.join(self._meta_dir(schema, table), f"v{vs[-1]}.json")
            )
        )

    def _store(self, schema: str, table: str, md: dict) -> None:
        d = self._meta_dir(schema, table)
        self.fs.mkdirs(d)
        vs = self._versions(schema, table)
        v = (vs[-1] + 1) if vs else 1
        # fs.write publishes atomically (temp + rename), the commit contract
        self.fs.write(
            os.path.join(d, f"v{v}.json"), json.dumps(md, indent=1).encode()
        )

    @staticmethod
    def _snapshot(md: dict, snapshot_id: Optional[int]) -> dict:
        snaps = md["snapshots"]
        if snapshot_id is None:
            snapshot_id = md["current_snapshot_id"]
        for s in snaps:
            if s["snapshot_id"] == snapshot_id:
                return s
        raise KeyError(f"snapshot {snapshot_id} not found")

    def _new_snapshot_id(self, md: Optional[dict]) -> int:
        prev = 0
        if md is not None and md["snapshots"]:
            prev = max(s["snapshot_id"] for s in md["snapshots"])
        return prev + 1

    # -- DDL/DML --------------------------------------------------------------

    def create_table(self, schema: str, table: str, columns: Sequence[ColumnMeta]):
        """Fresh table, or (existing table, same shape) an OVERWRITE
        snapshot with an empty manifest — history preserved, so the
        runner's rewrite-style DELETE/UPDATE becomes snapshot-based."""
        try:
            md = self._load(schema, table)
        except KeyError:
            md = None
        sid = self._new_snapshot_id(md)
        snap = {
            "snapshot_id": sid,
            "parent_id": md["current_snapshot_id"] if md else None,
            "timestamp_ms": int(time.time() * 1000),
            "operation": "overwrite" if md else "create",
            "manifest": [],
        }
        new_md = {
            "schema_name": schema,
            "table": table,
            "columns": [
                {"name": c.name, "type": c.type.name} for c in columns
            ],
            "snapshots": (md["snapshots"] if md else []) + [snap],
            "current_snapshot_id": sid,
        }
        self._store(schema, table, new_md)

    def drop_table(self, handle: TableHandle) -> None:
        import shutil

        shutil.rmtree(self._dir(handle.schema, handle.table), ignore_errors=True)

    def page_sink(self, handle: TableHandle, column_names, column_types):
        return _IcebergSink(self, handle, list(column_names), list(column_types))

    def commit_append(self, schema: str, table: str, path: str, rows: int) -> None:
        md = self._load(schema, table)
        cur = self._snapshot(md, None)
        sid = self._new_snapshot_id(md)
        snap = {
            "snapshot_id": sid,
            "parent_id": cur["snapshot_id"],
            "timestamp_ms": int(time.time() * 1000),
            "operation": "append",
            "manifest": list(cur["manifest"]) + [{"path": path, "rows": rows}],
        }
        md["snapshots"].append(snap)
        md["current_snapshot_id"] = sid
        self._store(schema, table, md)

    # -- transaction snapshots ------------------------------------------------

    def snapshot_table(self, schema: str, table: str):
        """Transactions capture the whole metadata document; ROLLBACK
        re-commits it as a new version (data files are immutable, so this
        is exact — the Iceberg `rollback_to_snapshot` procedure's shape)."""
        from trino_tpu.runtime.transactions import MISSING

        try:
            return json.dumps(self._load(schema, table))
        except KeyError:
            return MISSING

    def restore_table(self, schema: str, table: str, snap) -> None:
        from trino_tpu.runtime.transactions import MISSING

        if snap is MISSING:
            self.drop_table(TableHandle(self.name, schema, table))
            return
        self._store(schema, table, json.loads(snap))

    # -- reads ----------------------------------------------------------------

    def scan_version(self, handle: TableHandle):
        base, snap, meta_suffix = _split_name(handle.table)
        if meta_suffix:
            return None
        try:
            md = self._load(handle.schema, base)
        except KeyError:
            return None
        s = self._snapshot(md, snap)
        return (s["snapshot_id"], tuple(f["path"] for f in s["manifest"]))

    def splits(self, handle: TableHandle, target_splits: int, predicate=None):
        import pyarrow.parquet as pq

        base, snap, meta_suffix = _split_name(handle.table)
        if meta_suffix:
            return [Split(handle, 0)]
        md = self._load(handle.schema, base)
        s = self._snapshot(md, snap)
        out = []
        seq = 0
        row_start = 0
        for f in s["manifest"]:
            path = os.path.join(self._dir(handle.schema, base), f["path"])
            meta = pq.ParquetFile(path).metadata
            for rg in range(meta.num_row_groups):
                nrows = meta.row_group(rg).num_rows
                out.append(
                    Split(
                        handle, seq,
                        row_start=row_start, row_count=nrows,
                        info=(path, rg),
                    )
                )
                seq += 1
                row_start += nrows
        if not out:
            out.append(Split(handle, 0, row_start=0, row_count=0, info=None))
        return out

    def page_source(
        self, split: Split, columns: Sequence[str], max_rows_per_page: int = 1 << 20
    ) -> PageSource:
        from trino_tpu.connectors.parquet import _ParquetPageSource

        base, snap, meta_suffix = _split_name(split.table.table)
        if meta_suffix:
            return self._meta_table_source(
                split.table.schema, base, snap, meta_suffix, columns
            )
        if split.info is None:  # empty table
            import numpy as np

            from trino_tpu.columnar import StringDictionary

            meta = self._metadata.table_metadata(split.table.schema, base)
            tmap = {c.name: c.type for c in meta.columns}
            return _RowsPageSource(
                [
                    ColumnData(
                        np.zeros(0, dtype=tmap[c].np_dtype),
                        None,
                        # varchar columns keep the engine's dictionary
                        # invariant even with no rows
                        StringDictionary([])
                        if T.is_string_kind(tmap[c])
                        else None,
                    )
                    for c in columns
                ]
            )
        meta = self._metadata.table_metadata(split.table.schema, base)
        tmap = {c.name: c.type for c in meta.columns}
        types = [tmap[c] for c in columns]
        return _ParquetPageSource(split, columns, types, max_rows_per_page)

    def _meta_table_source(self, schema, base, snap, suffix, columns):
        import numpy as np

        from trino_tpu.columnar import StringDictionary

        md = self._load(schema, base)

        def strcol(vals):
            d = StringDictionary.from_unsorted(vals or [""])
            return ColumnData(d.encode(list(vals)), None, d)

        def intcol(vals, valid=None):
            return ColumnData(
                np.asarray(list(vals), dtype=np.int64),
                None if valid is None else np.asarray(valid, bool),
                None,
            )

        rows: dict = {}
        if suffix == "$files":
            s = self._snapshot(md, snap)
            rows = {
                "file_path": strcol([f["path"] for f in s["manifest"]]),
                "record_count": intcol([f["rows"] for f in s["manifest"]]),
                "snapshot_id": intcol(
                    [s["snapshot_id"]] * len(s["manifest"])
                ),
            }
        elif suffix == "$history":
            snaps = md["snapshots"]
            rows = {
                "snapshot_id": intcol([s["snapshot_id"] for s in snaps]),
                "parent_id": intcol(
                    [s["parent_id"] or 0 for s in snaps],
                    valid=[s["parent_id"] is not None for s in snaps],
                ),
                "made_current_at": intcol(
                    [s["timestamp_ms"] for s in snaps]
                ),
                "operation": strcol([s["operation"] for s in snaps]),
            }
        elif suffix == "$snapshots":
            snaps = md["snapshots"]
            rows = {
                "snapshot_id": intcol([s["snapshot_id"] for s in snaps]),
                "committed_at": intcol([s["timestamp_ms"] for s in snaps]),
                "operation": strcol([s["operation"] for s in snaps]),
                "file_count": intcol([len(s["manifest"]) for s in snaps]),
                "total_records": intcol(
                    [sum(f["rows"] for f in s["manifest"]) for s in snaps]
                ),
            }
        return _RowsPageSource([rows[c] for c in columns])


class _IcebergSink:
    """Each append writes one immutable data file and commits an append
    snapshot (the Iceberg commit protocol collapsed to a single manifest
    rewrite; reference: IcebergPageSink + SnapshotProducer.commit)."""

    def __init__(self, conn: IcebergConnector, handle: TableHandle, names, types):
        self.conn = conn
        self.handle = handle
        self.names = names
        self.types = types

    def append(self, columns: Sequence[ColumnData]) -> int:
        import pyarrow as pa
        import pyarrow.parquet as pq

        from trino_tpu.connectors.parquet import _column_data_to_arrow

        base, _, _ = _split_name(self.handle.table)
        rows = len(columns[0].values) if columns else 0
        if rows == 0:
            return 0
        arrays = [
            _column_data_to_arrow(cd, t) for cd, t in zip(columns, self.types)
        ]
        tbl = pa.table(dict(zip(self.names, arrays)))
        ddir = os.path.join(self.conn._dir(self.handle.schema, base), "data")
        self.conn.fs.mkdirs(ddir)
        fname = f"{uuid.uuid4().hex}.parquet"
        with self.conn.fs.open_output(os.path.join(ddir, fname)) as f:
            pq.write_table(tbl, f)
        self.conn.commit_append(
            self.handle.schema, base, os.path.join("data", fname), rows
        )
        return rows
