"""Vectorized, counter-based TPC-H data generator.

Reference role: the row generators behind plugin/trino-tpch
(TpchRecordSetProvider / io.trino.tpch dbgen port).  Re-designed for a columnar
TPU engine: every value is a pure function of (table, column, row index) via a
splitmix64 hash, so any split's columns generate independently, in O(rows)
vectorized numpy, at any scale factor, with zero shared state.

Distributions/shapes follow the TPC-H spec closely enough for every query to
exercise its intended plan shape (key structure, FK consistency — including
l_suppkey drawn from the part's 4 partsupp suppliers and o_custkey skipping
every third customer — date windows, derived flags).  The correctness oracle is
pandas over these same tables, so engine results are checked end-to-end.
Text columns draw from bounded pools so dictionaries are global per column
(shape- and trace-stable across splits).
"""

from __future__ import annotations

import datetime
from functools import lru_cache

import numpy as np

from trino_tpu.columnar.dictionary import PatternDictionary, StringDictionary
from trino_tpu.connectors.api import ColumnData
from trino_tpu.connectors.tpch.schema import BASE_ROWS, scaled_rows

# ---------------------------------------------------------------------------
# counter-based RNG: splitmix64 over (seed ^ stream, index)

_GOLDEN = np.uint64(0x9E3779B97F4A7C15)


def _mix(x: np.ndarray) -> np.ndarray:
    x = np.asarray(x, dtype=np.uint64)
    with np.errstate(over="ignore"):
        z = x + _GOLDEN
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return z ^ (z >> np.uint64(31))


def _stream(name: str) -> np.uint64:
    h = np.uint64(1469598103934665603)
    with np.errstate(over="ignore"):
        for ch in name.encode():
            h = (h ^ np.uint64(ch)) * np.uint64(1099511628211)
    return h


def _rand64(stream: str, idx: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        return _mix(np.asarray(idx, np.uint64) * np.uint64(0x2545F4914F6CDD1D) + _stream(stream))


def randint(stream: str, idx, lo: int, hi: int) -> np.ndarray:
    """Uniform integer in [lo, hi] inclusive."""
    r = _rand64(stream, idx)
    return (r % np.uint64(hi - lo + 1)).astype(np.int64) + lo


# ---------------------------------------------------------------------------
# fixed vocabularies (spec 4.2.2.13)

REGIONS = ("AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST")
NATIONS = (
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
)
SEGMENTS = ("AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY")
PRIORITIES = ("1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW")
INSTRUCTIONS = ("COLLECT COD", "DELIVER IN PERSON", "NONE", "TAKE BACK RETURN")
MODES = ("AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK")
TYPE_S1 = ("ECONOMY", "LARGE", "MEDIUM", "PROMO", "SMALL", "STANDARD")
TYPE_S2 = ("ANODIZED", "BRUSHED", "BURNISHED", "PLATED", "POLISHED")
TYPE_S3 = ("BRASS", "COPPER", "NICKEL", "STEEL", "TIN")
CONTAINER_S1 = ("JUMBO", "LG", "MED", "SM", "WRAP")
CONTAINER_S2 = ("BAG", "BOX", "CAN", "CASE", "DRUM", "JAR", "PACK", "PKG")
COLORS = (
    "almond", "antique", "aquamarine", "azure", "beige", "bisque", "black",
    "blanched", "blue", "blush", "brown", "burlywood", "burnished", "chartreuse",
    "chiffon", "chocolate", "coral", "cornflower", "cornsilk", "cream", "cyan",
    "dark", "deep", "dim", "dodger", "drab", "firebrick", "floral", "forest",
    "frosted", "gainsboro", "ghost", "goldenrod", "green", "grey", "honeydew",
    "hot", "hunter", "indian", "ivory", "khaki", "lace", "lavender", "lawn",
    "lemon", "light", "lime", "linen", "magenta", "maroon", "medium", "metallic",
    "midnight", "mint", "misty", "moccasin", "navajo", "navy", "olive", "orange",
    "orchid", "pale", "papaya", "peach", "peru", "pink", "plum", "powder",
    "puff", "purple", "red", "rose", "rosy", "royal", "saddle", "salmon",
    "sandy", "seashell", "sienna", "sky", "slate", "smoke", "snow", "spring",
    "steel", "tan", "thistle", "tomato", "turquoise", "violet", "wheat", "white",
    "yellow",
)
_COMMENT_VOCAB = (
    "about", "above", "according", "accounts", "across", "after", "again",
    "against", "along", "alongside", "among", "apart", "asymptotes", "attain",
    "bold", "boost", "braids", "brave", "busily", "busy", "carefully",
    "cautious", "close", "courts", "daring", "deposits", "dependencies",
    "depths", "doggedly", "dolphins", "dugouts", "during", "enticing",
    "escapades", "even", "excuses", "express", "final", "fluffily", "foxes",
    "frays", "furious", "furiously", "gifts", "grouches", "haggle", "hockey",
    "ideas", "instructions", "ironic", "instead", "integrate", "kindle",
    "notornis", "packages", "pains", "patterns", "pending", "permanent",
    "pinto", "platelets", "players", "quick", "quickly", "quiet", "realms",
    "regular", "requests", "sauternes", "sentiments", "silent", "sleep",
    "slyly", "special", "stealthy", "theodolites", "thin", "ruthless",
    "unusual", "wake", "warhorses", "waters",
)

EPOCH = datetime.date(1970, 1, 1)
START_DATE = (datetime.date(1992, 1, 1) - EPOCH).days      # 8035
END_DATE = (datetime.date(1998, 12, 31) - EPOCH).days
CURRENT_DATE = (datetime.date(1995, 6, 17) - EPOCH).days   # spec 4.2.2.12
ORDER_DATE_SPAN = END_DATE - START_DATE - 151              # last orderdate


# ---------------------------------------------------------------------------
# bounded text pools (sorted => global, order-preserving dictionaries)


@lru_cache(maxsize=32)
def _comment_pool(tag: str, size: int, max_words: int, special: str | None = None):
    """Deterministic pool of `size` comment strings; ~1/2000 contain the
    `special` marker phrase when given (for Q13/Q16-style predicates)."""
    rng = np.random.default_rng(abs(hash(("pool", tag))) % (2**32))
    vocab = np.array(_COMMENT_VOCAB)
    out = set()
    while len(out) < size:
        need = size - len(out)
        nwords = rng.integers(3, max_words + 1, size=need)
        picks = rng.integers(0, len(vocab), size=(need, max_words))
        for k in range(need):
            words = vocab[picks[k, : nwords[k]]]
            out.add(" ".join(words))
    pool = sorted(out)[:size]
    if special is not None:
        # overwrite a deterministic slice with marker-bearing comments
        n_special = max(4, size // 500)
        for j in range(n_special):
            i = (j * 997 + 13) % size
            pool[i] = f"{pool[i][:10]}{special}{pool[i][10:20]}"
        pool = sorted(set(pool))
    return tuple(pool)


@lru_cache(maxsize=32)
def _pool_dict(tag: str, size: int, max_words: int, special: str | None = None):
    return StringDictionary(_comment_pool(tag, size, max_words, special))


def _pool_codes(dict_: StringDictionary, stream: str, idx) -> np.ndarray:
    return (_rand64(stream, idx) % np.uint64(len(dict_))).astype(np.int32)


@lru_cache(maxsize=32)
def _address_pool(tag: str, size: int):
    rng = np.random.default_rng(abs(hash(("addr", tag))) % (2**32))
    chars = np.array(list("abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789 ,"))
    out = set()
    while len(out) < size:
        need = size - len(out)
        lens = rng.integers(10, 30, size=need)
        picks = rng.integers(0, len(chars), size=(need, 30))
        for k in range(need):
            out.add("".join(chars[picks[k, : lens[k]]]))
    return StringDictionary(sorted(out)[:size])


def _fixed_dict(values) -> StringDictionary:
    return StringDictionary(sorted(values))


def _choice_codes(d: StringDictionary, values, stream, idx) -> np.ndarray:
    """Pick uniformly among `values` (a subset giving the spec's order),
    returning codes in dictionary d."""
    lookup = np.fromiter((d.code_of(v) for v in values), np.int32, len(values))
    r = (_rand64(stream, idx) % np.uint64(len(values))).astype(np.int64)
    return lookup[r]


def _pattern_dict(prefix: str, width: int, n: int) -> PatternDictionary:
    fmt = prefix + "#%0" + str(width) + "d"

    def fn(i: int) -> str:
        return fmt % (i + 1)

    return PatternDictionary(fn, n, (prefix, width))


# ---------------------------------------------------------------------------
# key-structure helpers (FK consistency)


def _num_valid_custkeys(C: int) -> int:
    return C - C // 3


def _custkey_from_rank(r: np.ndarray) -> np.ndarray:
    """kth customer key skipping multiples of 3: 1,2,4,5,7,8,..."""
    return (r // 2) * 3 + 1 + (r % 2)


def _ps_suppkey(partkey: np.ndarray, i, S: int) -> np.ndarray:
    """Supplier j (0..3) for a part (spec 4.2.3 partsupp bridge)."""
    p = np.asarray(partkey, np.int64)
    return ((p + i * (S // 4 + (p - 1) // S)) % S) + 1


def _retailprice_cents(partkey: np.ndarray) -> np.ndarray:
    p = np.asarray(partkey, np.int64)
    return 90000 + ((p // 10) % 20001) + 100 * (p % 1000)


def _line_count(order_idx: np.ndarray) -> np.ndarray:
    return 1 + (_rand64("l_count", order_idx) % np.uint64(7)).astype(np.int64)


@lru_cache(maxsize=8)
def _lineitem_prefix(num_orders: int) -> np.ndarray:
    lc = _line_count(np.arange(num_orders, dtype=np.int64))
    out = np.zeros(num_orders + 1, dtype=np.int64)
    np.cumsum(lc, out=out[1:])
    return out


@lru_cache(maxsize=1)
def _phone_pool() -> StringDictionary:
    # bounded pool: country code (10..34) x 256 local variants
    vals = []
    for cc in range(10, 35):
        for j in range(256):
            h = int(_mix(np.uint64(cc * 7919 + j)))
            a, b, c = 100 + h % 900, 100 + (h >> 10) % 900, 1000 + (h >> 20) % 9000
            vals.append(f"{cc}-{a}-{b}-{c}")
    return StringDictionary(sorted(set(vals)))


@lru_cache(maxsize=1)
def _phone_cc_ranges() -> np.ndarray:
    """[25, 2] (lo, hi) code ranges per country code in the phone pool."""
    d = _phone_pool()
    out = np.zeros((25, 2), dtype=np.int64)
    for i, cc in enumerate(range(10, 35)):
        out[i] = d.prefix_range(f"{cc}-")
    return out


@lru_cache(maxsize=1)
def _pname_pool() -> StringDictionary:
    # p_name = 2 colors joined (bounded pool, contains every color)
    vals = {f"{a} {b}" for a in COLORS for b in COLORS if a < b}
    return StringDictionary(sorted(vals))


@lru_cache(maxsize=4)
def _complaint_codes(tag: str, size: int, max_words: int, special: str) -> np.ndarray:
    d = _pool_dict(tag, size, max_words, special)
    return np.flatnonzero(
        np.fromiter((special in v for v in d.values), bool, len(d))
    ).astype(np.int32)


# ---------------------------------------------------------------------------
# per-table generators: (sf, row_start, row_count, columns) -> {name: ColumnData}


class TpchGenerator:
    def __init__(self, sf: float):
        self.sf = sf
        self.S = scaled_rows("supplier", sf)
        self.P = scaled_rows("part", sf)
        self.C = scaled_rows("customer", sf)
        self.O = scaled_rows("orders", sf)

    # -- cardinalities -------------------------------------------------------

    def row_count(self, table: str) -> int:
        if table == "lineitem":
            return int(self.lineitem_counts_prefix()[-1])
        if table == "partsupp":
            return self.P * 4
        return scaled_rows(table, self.sf)

    def lineitem_counts_prefix(self) -> np.ndarray:
        """prefix[i] = number of lineitems in orders[0..i); prefix[O] total."""
        return _lineitem_prefix(self.O)

    # -- dictionaries (global per column) ------------------------------------

    @lru_cache(maxsize=512)
    def dictionary(self, table: str, column: str) -> StringDictionary | None:
        d = {
            ("region", "r_name"): lambda: _fixed_dict(REGIONS),
            ("region", "r_comment"): lambda: _pool_dict("r_comment", 8, 10),
            ("nation", "n_name"): lambda: _fixed_dict(n for n, _ in NATIONS),
            ("nation", "n_comment"): lambda: _pool_dict("n_comment", 32, 10),
            ("supplier", "s_name"): lambda: _pattern_dict("Supplier", 9, self.S),
            ("supplier", "s_address"): lambda: _address_pool("s_address", 8192),
            ("supplier", "s_phone"): lambda: self._phone_dict(),
            ("supplier", "s_comment"): lambda: _pool_dict(
                "s_comment", 16384, 12, "Customer Complaints"
            ),
            ("part", "p_name"): lambda: self._pname_dict(),
            ("part", "p_mfgr"): lambda: _pattern_dict("Manufacturer", 1, 5),
            ("part", "p_brand"): lambda: _fixed_dict(
                f"Brand#{m}{n}" for m in range(1, 6) for n in range(1, 6)
            ),
            ("part", "p_type"): lambda: _fixed_dict(
                f"{a} {b} {c}" for a in TYPE_S1 for b in TYPE_S2 for c in TYPE_S3
            ),
            ("part", "p_container"): lambda: _fixed_dict(
                f"{a} {b}" for a in CONTAINER_S1 for b in CONTAINER_S2
            ),
            ("part", "p_comment"): lambda: _pool_dict("p_comment", 8192, 5),
            ("partsupp", "ps_comment"): lambda: _pool_dict("ps_comment", 16384, 20),
            ("customer", "c_name"): lambda: _pattern_dict("Customer", 9, self.C),
            ("customer", "c_address"): lambda: _address_pool("c_address", 16384),
            ("customer", "c_phone"): lambda: self._phone_dict(),
            ("customer", "c_mktsegment"): lambda: _fixed_dict(SEGMENTS),
            ("customer", "c_comment"): lambda: _pool_dict("c_comment", 16384, 14),
            ("orders", "o_orderstatus"): lambda: _fixed_dict("FOP"),
            ("orders", "o_orderpriority"): lambda: _fixed_dict(PRIORITIES),
            ("orders", "o_clerk"): lambda: _pattern_dict(
                "Clerk", 9, max(1000, int(1000 * self.sf))
            ),
            ("orders", "o_comment"): lambda: _pool_dict(
                "o_comment", 32768, 10, "special requests"
            ),
            ("lineitem", "l_returnflag"): lambda: _fixed_dict("ANR"),
            ("lineitem", "l_linestatus"): lambda: _fixed_dict("FO"),
            ("lineitem", "l_shipinstruct"): lambda: _fixed_dict(INSTRUCTIONS),
            ("lineitem", "l_shipmode"): lambda: _fixed_dict(MODES),
            ("lineitem", "l_comment"): lambda: _pool_dict("l_comment", 32768, 6),
        }.get((table, column))
        return d() if d else None

    def _phone_dict(self) -> StringDictionary:
        return _phone_pool()

    def _pname_dict(self) -> StringDictionary:
        return _pname_pool()

    def _phone_codes(self, nationkey: np.ndarray, stream: str, idx) -> np.ndarray:
        """Vectorized: random variant within the customer's country-code range,
        so substring(phone, 1, 2) == nationkey + 10 always holds (Q22)."""
        ranges = _phone_cc_ranges()
        nk = nationkey.astype(np.int64)
        lo, hi = ranges[nk, 0], ranges[nk, 1]
        h = (_rand64(stream, idx) % np.uint64(1 << 32)).astype(np.int64)
        return (lo + h % np.maximum(hi - lo, 1)).astype(np.int32)

    # -- generation ----------------------------------------------------------

    def generate(self, table: str, row_start: int, row_count: int, columns):
        fn = getattr(self, "_gen_" + table)
        return fn(row_start, row_count, list(columns))

    def _money(self, cents: np.ndarray) -> ColumnData:
        return ColumnData(cents.astype(np.int64))

    def _gen_region(self, start, count, columns):
        idx = np.arange(start, start + count, dtype=np.int64)
        out = {}
        for col in columns:
            if col == "r_regionkey":
                out[col] = ColumnData(idx)
            elif col == "r_name":
                d = self.dictionary("region", "r_name")
                out[col] = ColumnData(d.encode([REGIONS[i] for i in idx]), dictionary=d)
            elif col == "r_comment":
                d = self.dictionary("region", "r_comment")
                out[col] = ColumnData(_pool_codes(d, "r_comment", idx), dictionary=d)
        return out

    def _gen_nation(self, start, count, columns):
        idx = np.arange(start, start + count, dtype=np.int64)
        out = {}
        for col in columns:
            if col == "n_nationkey":
                out[col] = ColumnData(idx)
            elif col == "n_name":
                d = self.dictionary("nation", "n_name")
                out[col] = ColumnData(
                    d.encode([NATIONS[i][0] for i in idx]), dictionary=d
                )
            elif col == "n_regionkey":
                out[col] = ColumnData(
                    np.array([NATIONS[i][1] for i in idx], dtype=np.int64)
                )
            elif col == "n_comment":
                d = self.dictionary("nation", "n_comment")
                out[col] = ColumnData(_pool_codes(d, "n_comment", idx), dictionary=d)
        return out

    def _gen_supplier(self, start, count, columns):
        idx = np.arange(start, start + count, dtype=np.int64)
        key = idx + 1
        nk = randint("s_nation", idx, 0, 24)
        out = {}
        for col in columns:
            if col == "s_suppkey":
                out[col] = ColumnData(key)
            elif col == "s_name":
                d = self.dictionary("supplier", "s_name")
                out[col] = ColumnData(idx.astype(np.int32), dictionary=d)
            elif col == "s_address":
                d = self.dictionary("supplier", "s_address")
                out[col] = ColumnData(_pool_codes(d, "s_address", idx), dictionary=d)
            elif col == "s_nationkey":
                out[col] = ColumnData(nk)
            elif col == "s_phone":
                d = self.dictionary("supplier", "s_phone")
                out[col] = ColumnData(
                    self._phone_codes(nk, "s_phone", idx), dictionary=d
                )
            elif col == "s_acctbal":
                out[col] = self._money(randint("s_acctbal", idx, -99999, 999999))
            elif col == "s_comment":
                d = self.dictionary("supplier", "s_comment")
                codes = _pool_codes(d, "s_comment", idx)
                # deterministic ~1/200 suppliers carry the complaint marker
                special = _complaint_codes(
                    "s_comment", 16384, 12, "Customer Complaints"
                )
                marked = key % 199 == 3
                codes = np.where(
                    marked, special[(key % len(special)).astype(np.int64)], codes
                )
                out[col] = ColumnData(codes.astype(np.int32), dictionary=d)
        return out

    def _gen_part(self, start, count, columns):
        idx = np.arange(start, start + count, dtype=np.int64)
        key = idx + 1
        out = {}
        mfgr = 1 + (_rand64("p_mfgr", idx) % np.uint64(5)).astype(np.int64)
        for col in columns:
            if col == "p_partkey":
                out[col] = ColumnData(key)
            elif col == "p_name":
                d = self._pname_dict()
                out[col] = ColumnData(_pool_codes(d, "p_name", idx), dictionary=d)
            elif col == "p_mfgr":
                d = self.dictionary("part", "p_mfgr")
                out[col] = ColumnData((mfgr - 1).astype(np.int32), dictionary=d)
            elif col == "p_brand":
                d = self.dictionary("part", "p_brand")
                brand = 1 + (_rand64("p_brand", idx) % np.uint64(5)).astype(np.int64)
                names = [f"Brand#{m}{n}" for m, n in zip(mfgr, brand)]
                out[col] = ColumnData(d.encode(names), dictionary=d)
            elif col == "p_type":
                d = self.dictionary("part", "p_type")
                out[col] = ColumnData(
                    _pool_codes(d, "p_type", idx), dictionary=d
                )
            elif col == "p_size":
                out[col] = ColumnData(randint("p_size", idx, 1, 50))
            elif col == "p_container":
                d = self.dictionary("part", "p_container")
                out[col] = ColumnData(_pool_codes(d, "p_container", idx), dictionary=d)
            elif col == "p_retailprice":
                out[col] = self._money(_retailprice_cents(key))
            elif col == "p_comment":
                d = self.dictionary("part", "p_comment")
                out[col] = ColumnData(_pool_codes(d, "p_comment", idx), dictionary=d)
        return out

    def _gen_partsupp(self, start, count, columns):
        idx = np.arange(start, start + count, dtype=np.int64)
        partkey = idx // 4 + 1
        j = idx % 4
        out = {}
        for col in columns:
            if col == "ps_partkey":
                out[col] = ColumnData(partkey)
            elif col == "ps_suppkey":
                out[col] = ColumnData(_ps_suppkey(partkey, j, self.S))
            elif col == "ps_availqty":
                out[col] = ColumnData(randint("ps_avail", idx, 1, 9999))
            elif col == "ps_supplycost":
                out[col] = self._money(randint("ps_cost", idx, 100, 100000))
            elif col == "ps_comment":
                d = self.dictionary("partsupp", "ps_comment")
                out[col] = ColumnData(_pool_codes(d, "ps_comment", idx), dictionary=d)
        return out

    def _gen_customer(self, start, count, columns):
        idx = np.arange(start, start + count, dtype=np.int64)
        key = idx + 1
        nk = randint("c_nation", idx, 0, 24)
        out = {}
        for col in columns:
            if col == "c_custkey":
                out[col] = ColumnData(key)
            elif col == "c_name":
                d = self.dictionary("customer", "c_name")
                out[col] = ColumnData(idx.astype(np.int32), dictionary=d)
            elif col == "c_address":
                d = self.dictionary("customer", "c_address")
                out[col] = ColumnData(_pool_codes(d, "c_address", idx), dictionary=d)
            elif col == "c_nationkey":
                out[col] = ColumnData(nk)
            elif col == "c_phone":
                d = self.dictionary("customer", "c_phone")
                out[col] = ColumnData(
                    self._phone_codes(nk, "c_phone", idx), dictionary=d
                )
            elif col == "c_acctbal":
                out[col] = self._money(randint("c_acctbal", idx, -99999, 999999))
            elif col == "c_mktsegment":
                d = self.dictionary("customer", "c_mktsegment")
                out[col] = ColumnData(
                    _pool_codes(d, "c_mktseg", idx), dictionary=d
                )
            elif col == "c_comment":
                d = self.dictionary("customer", "c_comment")
                out[col] = ColumnData(_pool_codes(d, "c_comment", idx), dictionary=d)
        return out

    # -- orders + lineitem (generated from the same per-order streams) -------

    def _order_dates(self, oidx: np.ndarray) -> np.ndarray:
        return START_DATE + (
            _rand64("o_date", oidx) % np.uint64(ORDER_DATE_SPAN)
        ).astype(np.int64)

    def _line_arrays(self, oidx: np.ndarray):
        """Flattened per-line arrays for the given order indices.

        Returns dict of numpy arrays, all length sum(line_count)."""
        lc = _line_count(oidx)
        total = int(lc.sum())
        order_rep = np.repeat(oidx, lc)
        # line number within order: 1..lc
        ln = np.arange(total, dtype=np.int64) - np.repeat(
            np.cumsum(lc) - lc, lc
        ) + 1
        lid = order_rep * 8 + ln  # unique per line, stable across splits
        odate = np.repeat(self._order_dates(oidx), lc)
        partkey = 1 + (_rand64("l_part", lid) % np.uint64(self.P)).astype(np.int64)
        suppkey = _ps_suppkey(partkey, (_rand64("l_supp", lid) % np.uint64(4)).astype(np.int64), self.S)
        qty = 1 + (_rand64("l_qty", lid) % np.uint64(50)).astype(np.int64)
        extprice = qty * _retailprice_cents(partkey)
        disc = (_rand64("l_disc", lid) % np.uint64(11)).astype(np.int64)     # 0.00-0.10
        tax = (_rand64("l_tax", lid) % np.uint64(9)).astype(np.int64)        # 0.00-0.08
        shipdate = odate + 1 + (_rand64("l_ship", lid) % np.uint64(121)).astype(np.int64)
        commitdate = odate + 30 + (_rand64("l_commit", lid) % np.uint64(61)).astype(np.int64)
        receiptdate = shipdate + 1 + (_rand64("l_rcpt", lid) % np.uint64(30)).astype(np.int64)
        return {
            "order_idx": order_rep,
            "orderkey": order_rep + 1,
            "linenumber": ln,
            "lid": lid,
            "partkey": partkey,
            "suppkey": suppkey,
            "quantity": qty * 100,  # decimal(12,2) cents-style
            "extendedprice": extprice,
            "discount": disc,
            "tax": tax,
            "shipdate": shipdate,
            "commitdate": commitdate,
            "receiptdate": receiptdate,
            "lc": lc,
        }

    def _gen_orders(self, start, count, columns):
        oidx = np.arange(start, start + count, dtype=np.int64)
        out = {}
        need_lines = any(
            c in ("o_totalprice", "o_orderstatus") for c in columns
        )
        la = self._line_arrays(oidx) if need_lines else None
        for col in columns:
            if col == "o_orderkey":
                out[col] = ColumnData(oidx + 1)
            elif col == "o_custkey":
                nvalid = _num_valid_custkeys(self.C)
                r = (_rand64("o_cust", oidx) % np.uint64(nvalid)).astype(np.int64)
                out[col] = ColumnData(_custkey_from_rank(r))
            elif col == "o_orderstatus":
                d = self.dictionary("orders", "o_orderstatus")
                shipped = la["shipdate"] <= CURRENT_DATE
                lc = la["lc"]
                seg = np.repeat(np.arange(len(oidx)), lc)
                n_shipped = np.bincount(seg, weights=shipped, minlength=len(oidx))
                status = np.where(
                    n_shipped == lc,
                    d.code_of("F"),
                    np.where(n_shipped == 0, d.code_of("O"), d.code_of("P")),
                )
                out[col] = ColumnData(status.astype(np.int32), dictionary=d)
            elif col == "o_totalprice":
                # sum(extprice * (1+tax) * (1-disc)); cents * basis points
                ep = la["extendedprice"].astype(np.int64)
                line_total = (
                    ep * (100 + la["tax"]) * (100 - la["discount"])
                ) // 10000
                lc = la["lc"]
                seg = np.repeat(np.arange(len(oidx)), lc)
                out[col] = self._money(
                    np.bincount(seg, weights=line_total, minlength=len(oidx)).astype(
                        np.int64
                    )
                )
            elif col == "o_orderdate":
                out[col] = ColumnData(self._order_dates(oidx).astype(np.int32))
            elif col == "o_orderpriority":
                d = self.dictionary("orders", "o_orderpriority")
                out[col] = ColumnData(
                    _choice_codes(d, PRIORITIES, "o_prio", oidx), dictionary=d
                )
            elif col == "o_clerk":
                d = self.dictionary("orders", "o_clerk")
                nclerk = max(1000, int(1000 * self.sf))
                out[col] = ColumnData(
                    (_rand64("o_clerk", oidx) % np.uint64(nclerk)).astype(np.int32),
                    dictionary=d,
                )
            elif col == "o_shippriority":
                out[col] = ColumnData(np.zeros(count, dtype=np.int64))
            elif col == "o_comment":
                d = self.dictionary("orders", "o_comment")
                out[col] = ColumnData(_pool_codes(d, "o_comment", oidx), dictionary=d)
        return out

    def _gen_lineitem(self, start, count, columns):
        """`start`/`count` index ORDERS; emits all their lines."""
        oidx = np.arange(start, start + count, dtype=np.int64)
        la = self._line_arrays(oidx)
        out = {}
        for col in columns:
            if col == "l_orderkey":
                out[col] = ColumnData(la["orderkey"])
            elif col == "l_partkey":
                out[col] = ColumnData(la["partkey"])
            elif col == "l_suppkey":
                out[col] = ColumnData(la["suppkey"])
            elif col == "l_linenumber":
                out[col] = ColumnData(la["linenumber"])
            elif col == "l_quantity":
                out[col] = self._money(la["quantity"])
            elif col == "l_extendedprice":
                out[col] = self._money(la["extendedprice"])
            elif col == "l_discount":
                out[col] = self._money(la["discount"])
            elif col == "l_tax":
                out[col] = self._money(la["tax"])
            elif col == "l_returnflag":
                d = self.dictionary("lineitem", "l_returnflag")
                received = la["receiptdate"] <= CURRENT_DATE
                r5050 = (_rand64("l_rflag", la["lid"]) % np.uint64(2)).astype(bool)
                codes = np.where(
                    received,
                    np.where(r5050, d.code_of("R"), d.code_of("A")),
                    d.code_of("N"),
                )
                out[col] = ColumnData(codes.astype(np.int32), dictionary=d)
            elif col == "l_linestatus":
                d = self.dictionary("lineitem", "l_linestatus")
                codes = np.where(
                    la["shipdate"] > CURRENT_DATE, d.code_of("O"), d.code_of("F")
                )
                out[col] = ColumnData(codes.astype(np.int32), dictionary=d)
            elif col == "l_shipdate":
                out[col] = ColumnData(la["shipdate"].astype(np.int32))
            elif col == "l_commitdate":
                out[col] = ColumnData(la["commitdate"].astype(np.int32))
            elif col == "l_receiptdate":
                out[col] = ColumnData(la["receiptdate"].astype(np.int32))
            elif col == "l_shipinstruct":
                d = self.dictionary("lineitem", "l_shipinstruct")
                out[col] = ColumnData(
                    _choice_codes(d, INSTRUCTIONS, "l_instr", la["lid"]), dictionary=d
                )
            elif col == "l_shipmode":
                d = self.dictionary("lineitem", "l_shipmode")
                out[col] = ColumnData(
                    _choice_codes(d, MODES, "l_mode", la["lid"]), dictionary=d
                )
            elif col == "l_comment":
                d = self.dictionary("lineitem", "l_comment")
                out[col] = ColumnData(
                    _pool_codes(d, "l_comment", la["lid"]), dictionary=d
                )
        return out


@lru_cache(maxsize=8)
def generator_for(sf: float) -> TpchGenerator:
    return TpchGenerator(sf)
