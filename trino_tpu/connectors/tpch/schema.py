"""TPC-H schema definitions (reference: plugin/trino-tpch/.../TpchMetadata.java
and the io.trino.tpch table generators it wraps).

Types follow the TPC-H spec as the reference surfaces them: money columns are
decimal(12,2) (device i64 cents), keys bigint, dates DATE.
"""

from __future__ import annotations

from trino_tpu import types as T
from trino_tpu.connectors.api import ColumnMeta, TableMetadata

MONEY = T.DecimalType(12, 2)

_TABLES = {
    "region": [
        ("r_regionkey", T.BIGINT, True),
        ("r_name", T.VarcharType(25), False),
        ("r_comment", T.VarcharType(152), False),
    ],
    "nation": [
        ("n_nationkey", T.BIGINT, True),
        ("n_name", T.VarcharType(25), False),
        ("n_regionkey", T.BIGINT, False),
        ("n_comment", T.VarcharType(152), False),
    ],
    "supplier": [
        ("s_suppkey", T.BIGINT, True),
        ("s_name", T.VarcharType(25), True),
        ("s_address", T.VarcharType(40), False),
        ("s_nationkey", T.BIGINT, False),
        ("s_phone", T.VarcharType(15), False),
        ("s_acctbal", MONEY, False),
        ("s_comment", T.VarcharType(101), False),
    ],
    "part": [
        ("p_partkey", T.BIGINT, True),
        ("p_name", T.VarcharType(55), False),
        ("p_mfgr", T.VarcharType(25), False),
        ("p_brand", T.VarcharType(10), False),
        ("p_type", T.VarcharType(25), False),
        ("p_size", T.BIGINT, False),
        ("p_container", T.VarcharType(10), False),
        ("p_retailprice", MONEY, False),
        ("p_comment", T.VarcharType(23), False),
    ],
    "partsupp": [
        ("ps_partkey", T.BIGINT, True),
        ("ps_suppkey", T.BIGINT, False),
        ("ps_availqty", T.BIGINT, False),
        ("ps_supplycost", MONEY, False),
        ("ps_comment", T.VarcharType(199), False),
    ],
    "customer": [
        ("c_custkey", T.BIGINT, True),
        ("c_name", T.VarcharType(25), True),
        ("c_address", T.VarcharType(40), False),
        ("c_nationkey", T.BIGINT, False),
        ("c_phone", T.VarcharType(15), False),
        ("c_acctbal", MONEY, False),
        ("c_mktsegment", T.VarcharType(10), False),
        ("c_comment", T.VarcharType(117), False),
    ],
    "orders": [
        ("o_orderkey", T.BIGINT, True),
        ("o_custkey", T.BIGINT, False),
        ("o_orderstatus", T.VarcharType(1), False),
        ("o_totalprice", MONEY, False),
        ("o_orderdate", T.DATE, False),
        ("o_orderpriority", T.VarcharType(15), False),
        ("o_clerk", T.VarcharType(15), True),
        ("o_shippriority", T.BIGINT, False),
        ("o_comment", T.VarcharType(79), False),
    ],
    "lineitem": [
        ("l_orderkey", T.BIGINT, True),
        ("l_partkey", T.BIGINT, False),
        ("l_suppkey", T.BIGINT, False),
        ("l_linenumber", T.BIGINT, False),
        ("l_quantity", MONEY, False),
        ("l_extendedprice", MONEY, False),
        ("l_discount", MONEY, False),
        ("l_tax", MONEY, False),
        ("l_returnflag", T.VarcharType(1), False),
        ("l_linestatus", T.VarcharType(1), False),
        ("l_shipdate", T.DATE, False),
        ("l_commitdate", T.DATE, False),
        ("l_receiptdate", T.DATE, False),
        ("l_shipinstruct", T.VarcharType(25), False),
        ("l_shipmode", T.VarcharType(10), False),
        ("l_comment", T.VarcharType(44), False),
    ],
}

TABLE_NAMES = tuple(_TABLES)

#: base cardinalities at SF1 (spec table 4.2.1; lineitem is derived)
BASE_ROWS = {
    "region": 5,
    "nation": 25,
    "supplier": 10_000,
    "part": 200_000,
    "partsupp": 800_000,
    "customer": 150_000,
    "orders": 1_500_000,
}

SCHEMAS = {
    "tiny": 0.01,
    "sf1": 1.0,
    "sf10": 10.0,
    "sf100": 100.0,
    "sf300": 300.0,
    "sf1000": 1000.0,
}


def schema_scale(schema: str) -> float:
    if schema in SCHEMAS:
        return SCHEMAS[schema]
    if schema.startswith("sf"):
        try:
            return float(schema[2:].replace("_", "."))
        except ValueError:
            pass
    raise KeyError(f"unknown tpch schema: {schema}")


def table_metadata(schema: str, table: str) -> TableMetadata:
    cols = _TABLES[table]
    return TableMetadata(
        schema,
        table,
        tuple(ColumnMeta(n, t, ordered) for n, t, ordered in cols),
    )


def scaled_rows(table: str, sf: float) -> int:
    """Row count for fixed-cardinality tables (not lineitem)."""
    if table in ("region", "nation"):
        return BASE_ROWS[table]
    return max(1, int(BASE_ROWS[table] * sf))
