"""TPC-H generator connector (reference: plugin/trino-tpch — TpchConnectorFactory,
TpchMetadata, TpchRecordSetProvider/TpchPageSourceProvider).

Schemas tiny (SF0.01), sf1, sf10, sf100, ... generate rows on the fly; splits
are row ranges (order ranges for lineitem so each order's lines stay together,
mirroring the reference's per-order generation).
"""

from __future__ import annotations

import math
from typing import Sequence

import numpy as np

from trino_tpu.connectors.api import (
    ColumnData,
    Connector,
    ConnectorMetadata,
    PageSource,
    Split,
    TableHandle,
    TableMetadata,
    TableStatistics,
    ColumnStatistics,
)
from trino_tpu.connectors.tpch import schema as tpch_schema
from trino_tpu.connectors.tpch.generator import TpchGenerator, generator_for


class TpchMetadata(ConnectorMetadata):
    def list_schemas(self):
        return sorted(tpch_schema.SCHEMAS)

    def list_tables(self, schema: str):
        tpch_schema.schema_scale(schema)
        return list(tpch_schema.TABLE_NAMES)

    def table_metadata(self, schema: str, table: str) -> TableMetadata:
        tpch_schema.schema_scale(schema)
        if table not in tpch_schema.TABLE_NAMES:
            raise KeyError(f"tpch table not found: {table}")
        return tpch_schema.table_metadata(schema, table)

    def table_statistics(self, schema: str, table: str) -> TableStatistics:
        """Analytic column statistics for the generated data (reference:
        plugin/trino-tpch/.../statistics/*.json — precomputed per-column
        ndv/min/max the reference ships for the CBO).  Ours derive from the
        generator's own parameters, so they are exact for keys and tight for
        derived columns."""
        sf = tpch_schema.schema_scale(schema)
        gen = generator_for(sf)
        rows = gen.row_count(table)
        from trino_tpu.connectors.tpch.generator import ORDER_DATE_SPAN, START_DATE

        def C(ndv=None, low=None, high=None, nulls=0.0, exact=False):
            # exact=True: the distinct count is a STRUCTURAL fact of the
            # generator (dense idx+1 keys, or a spec-literal enumeration
            # the generator draws from), admissible as a uniqueness or
            # group-count proof; everything else is a bound/estimate and
            # never licenses a capacity certificate (verify.capacity)
            return ColumnStatistics(
                distinct_count=ndv, low=low, high=high, null_fraction=nulls,
                exact_distinct=exact,
            )

        S, P, Ccust, O = gen.S, gen.P, gen.C, gen.O
        od_hi = START_DATE + ORDER_DATE_SPAN
        per_table = {
            "region": {
                "r_regionkey": C(5, 0, 4, exact=True), "r_name": C(5), "r_comment": C(5),
            },
            "nation": {
                "n_nationkey": C(25, 0, 24, exact=True), "n_name": C(25),
                "n_regionkey": C(5, 0, 4), "n_comment": C(25),
            },
            "supplier": {
                "s_suppkey": C(S, 1, S, exact=True), "s_name": C(S), "s_address": C(S),
                "s_nationkey": C(25, 0, 24), "s_phone": C(S),
                "s_acctbal": C(min(S, 1_100_000), -999.99, 9999.99),
                "s_comment": C(S),
            },
            "part": {
                "p_partkey": C(P, 1, P, exact=True), "p_name": C(P),
                "p_mfgr": C(5), "p_brand": C(25), "p_type": C(150),
                "p_size": C(50, 1, 50), "p_container": C(40),
                "p_retailprice": C(min(P, 120_000), 900.0, 2100.0),
                "p_comment": C(P),
            },
            "partsupp": {
                "ps_partkey": C(P, 1, P, exact=True), "ps_suppkey": C(S, 1, S),
                "ps_availqty": C(9999, 1, 9999),
                "ps_supplycost": C(100_000, 1.0, 1000.0),
                "ps_comment": C(rows),
            },
            "customer": {
                "c_custkey": C(Ccust, 1, Ccust, exact=True), "c_name": C(Ccust),
                "c_address": C(Ccust), "c_nationkey": C(25, 0, 24),
                "c_phone": C(Ccust),
                "c_acctbal": C(min(Ccust, 1_100_000), -999.99, 9999.99),
                # spec-literal enumeration (clause 4.2.2.13): 5 segments
                "c_mktsegment": C(5, exact=True), "c_comment": C(Ccust),
            },
            "orders": {
                "o_orderkey": C(O, 1, O, exact=True),
                # 2/3 of customers hold orders (spec 4.2.3)
                "o_custkey": C(max(1, Ccust * 2 // 3), 1, Ccust),
                # o_orderstatus/o_orderpriority: spec-literal enumerations
                "o_orderstatus": C(3, exact=True),
                "o_totalprice": C(O, 800.0, 600_000.0),
                "o_orderdate": C(ORDER_DATE_SPAN, START_DATE, od_hi),
                "o_orderpriority": C(5, exact=True),
                "o_clerk": C(max(1, O // 1000)),
                "o_shippriority": C(1, 0, 0), "o_comment": C(O),
            },
            "lineitem": {
                "l_orderkey": C(O, 1, O, exact=True), "l_partkey": C(P, 1, P),
                "l_suppkey": C(S, 1, S), "l_linenumber": C(7, 1, 7),
                "l_quantity": C(50, 1, 50),
                "l_extendedprice": C(min(rows, 3_800_000), 900.0, 105_000.0),
                "l_discount": C(11, 0.0, 0.10), "l_tax": C(9, 0.0, 0.08),
                # spec-literal enumerations (A/N/R and O/F): the Q1-class
                # group-count certificates hang off these exact counts
                "l_returnflag": C(3, exact=True),
                "l_linestatus": C(2, exact=True),
                "l_shipdate": C(ORDER_DATE_SPAN + 121, START_DATE + 1, od_hi + 121),
                "l_commitdate": C(ORDER_DATE_SPAN + 61, START_DATE + 30, od_hi + 90),
                "l_receiptdate": C(ORDER_DATE_SPAN + 151, START_DATE + 2, od_hi + 151),
                "l_shipinstruct": C(4, exact=True),
                "l_shipmode": C(7, exact=True), "l_comment": C(rows),
            },
        }
        return TableStatistics(
            row_count=rows, columns=per_table.get(table, {})
        )


class TpchPageSource(PageSource):
    def __init__(self, gen: TpchGenerator, split: Split, columns, page_rows: int):
        self.gen = gen
        self.split = split
        self.columns = list(columns)
        self.page_rows = page_rows

    def row_count(self) -> int:
        if self.split.table.table == "lineitem":
            prefix = self.gen.lineitem_counts_prefix()
            a = self.split.row_start
            b = a + self.split.row_count
            return int(prefix[b] - prefix[a])
        return self.split.row_count

    def pages(self):
        t = self.split.table.table
        start, remaining = self.split.row_start, self.split.row_count
        if t == "lineitem":
            # chunk by orders so ~page_rows lines per page (avg 4 lines/order)
            per_page = max(1, self.page_rows // 5)
        else:
            per_page = self.page_rows
        while remaining > 0:
            n = min(per_page, remaining)
            data = self.gen.generate(t, start, n, self.columns)
            yield [data[c] for c in self.columns]
            start += n
            remaining -= n


class TpchConnector(Connector):
    name = "tpch"

    def __init__(self):
        self._metadata = TpchMetadata()

    def metadata(self) -> TpchMetadata:
        return self._metadata

    def scan_version(self, handle):
        return 0  # generated data is immutable per (schema, table)

    #: string columns whose codes are a null-free bijection over the
    #: table's rows (code == row index, dictionary size == row count):
    #: admissible uniqueness sources for capacity certificates
    _UNIQUE_DICTIONARY_COLUMNS = frozenset(
        {("customer", "c_name"), ("supplier", "s_name")}
    )

    def global_dictionary(self, handle: TableHandle, column: str):
        """Every tpch string column is coded against ONE dictionary that is
        a pure function of (table, column, scale factor) — stable across
        splits, workers, and processes — so all of them are globally
        codable."""
        try:
            sf = tpch_schema.schema_scale(handle.schema)
            gen = generator_for(sf)
            d = gen.dictionary(handle.table, column)
        except (KeyError, ValueError):
            return None
        if d is None:
            return None
        unique = (
            handle.table, column
        ) in self._UNIQUE_DICTIONARY_COLUMNS and len(d.values) == gen.row_count(
            handle.table
        )
        return d, unique

    def splits(self, handle: TableHandle, target_splits: int, predicate=None):
        sf = tpch_schema.schema_scale(handle.schema)
        gen = generator_for(sf)
        t = handle.table
        # lineitem/orders splits are order ranges; others row ranges
        n = gen.O if t in ("orders", "lineitem") else gen.row_count(t)
        nsplits = max(1, min(target_splits, math.ceil(n / 1024)))
        per = math.ceil(n / nsplits)
        out = []
        for i in range(nsplits):
            a = i * per
            b = min(n, a + per)
            if a >= b:
                break
            ranges = ()
            if t == "orders":
                ranges = (("o_orderkey", (a + 1, b)),)
            elif t == "lineitem":
                ranges = (("l_orderkey", (a + 1, b)),)
            out.append(
                Split(handle, i, row_start=a, row_count=b - a, ranges=ranges)
            )
        return out

    def page_source(self, split: Split, columns, max_rows_per_page: int = 1 << 20):
        sf = tpch_schema.schema_scale(split.table.schema)
        return TpchPageSource(generator_for(sf), split, columns, max_rows_per_page)
