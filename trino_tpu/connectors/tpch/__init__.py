"""TPC-H generator connector (reference: plugin/trino-tpch — TpchConnectorFactory,
TpchMetadata, TpchRecordSetProvider/TpchPageSourceProvider).

Schemas tiny (SF0.01), sf1, sf10, sf100, ... generate rows on the fly; splits
are row ranges (order ranges for lineitem so each order's lines stay together,
mirroring the reference's per-order generation).
"""

from __future__ import annotations

import math
from typing import Sequence

import numpy as np

from trino_tpu.connectors.api import (
    ColumnData,
    Connector,
    ConnectorMetadata,
    PageSource,
    Split,
    TableHandle,
    TableMetadata,
    TableStatistics,
    ColumnStatistics,
)
from trino_tpu.connectors.tpch import schema as tpch_schema
from trino_tpu.connectors.tpch.generator import TpchGenerator, generator_for


class TpchMetadata(ConnectorMetadata):
    def list_schemas(self):
        return sorted(tpch_schema.SCHEMAS)

    def list_tables(self, schema: str):
        tpch_schema.schema_scale(schema)
        return list(tpch_schema.TABLE_NAMES)

    def table_metadata(self, schema: str, table: str) -> TableMetadata:
        tpch_schema.schema_scale(schema)
        if table not in tpch_schema.TABLE_NAMES:
            raise KeyError(f"tpch table not found: {table}")
        return tpch_schema.table_metadata(schema, table)

    def table_statistics(self, schema: str, table: str) -> TableStatistics:
        sf = tpch_schema.schema_scale(schema)
        gen = generator_for(sf)
        rows = gen.row_count(table)
        cols = {}
        key_col = {
            "region": "r_regionkey",
            "nation": "n_nationkey",
            "supplier": "s_suppkey",
            "part": "p_partkey",
            "customer": "c_custkey",
            "orders": "o_orderkey",
        }.get(table)
        if key_col:
            cols[key_col] = ColumnStatistics(
                distinct_count=rows, low=0 if table in ("region", "nation") else 1,
                high=rows if table not in ("region", "nation") else rows - 1,
            )
        if table == "lineitem":
            cols["l_orderkey"] = ColumnStatistics(
                distinct_count=gen.O, low=1, high=gen.O
            )
        return TableStatistics(row_count=rows, columns=cols)


class TpchPageSource(PageSource):
    def __init__(self, gen: TpchGenerator, split: Split, columns, page_rows: int):
        self.gen = gen
        self.split = split
        self.columns = list(columns)
        self.page_rows = page_rows

    def row_count(self) -> int:
        if self.split.table.table == "lineitem":
            prefix = self.gen.lineitem_counts_prefix()
            a = self.split.row_start
            b = a + self.split.row_count
            return int(prefix[b] - prefix[a])
        return self.split.row_count

    def pages(self):
        t = self.split.table.table
        start, remaining = self.split.row_start, self.split.row_count
        if t == "lineitem":
            # chunk by orders so ~page_rows lines per page (avg 4 lines/order)
            per_page = max(1, self.page_rows // 5)
        else:
            per_page = self.page_rows
        while remaining > 0:
            n = min(per_page, remaining)
            data = self.gen.generate(t, start, n, self.columns)
            yield [data[c] for c in self.columns]
            start += n
            remaining -= n


class TpchConnector(Connector):
    name = "tpch"

    def __init__(self):
        self._metadata = TpchMetadata()

    def metadata(self) -> TpchMetadata:
        return self._metadata

    def scan_version(self, handle):
        return 0  # generated data is immutable per (schema, table)

    def splits(self, handle: TableHandle, target_splits: int, predicate=None):
        sf = tpch_schema.schema_scale(handle.schema)
        gen = generator_for(sf)
        t = handle.table
        # lineitem/orders splits are order ranges; others row ranges
        n = gen.O if t in ("orders", "lineitem") else gen.row_count(t)
        nsplits = max(1, min(target_splits, math.ceil(n / 1024)))
        per = math.ceil(n / nsplits)
        out = []
        for i in range(nsplits):
            a = i * per
            b = min(n, a + per)
            if a >= b:
                break
            ranges = ()
            if t == "orders":
                ranges = (("o_orderkey", (a + 1, b)),)
            elif t == "lineitem":
                ranges = (("l_orderkey", (a + 1, b)),)
            out.append(
                Split(handle, i, row_start=a, row_count=b - a, ranges=ranges)
            )
        return out

    def page_source(self, split: Split, columns, max_rows_per_page: int = 1 << 20):
        sf = tpch_schema.schema_scale(split.table.schema)
        return TpchPageSource(generator_for(sf), split, columns, max_rows_per_page)
