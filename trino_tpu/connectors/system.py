"""System connector: engine state queryable as SQL tables.

Reference: core/trino-main/.../connector/system/ (QuerySystemTable.java,
NodeSystemTable, system.runtime schema) — the observability surface that
makes the engine inspectable from its own SQL prompt.

Tables (schema `runtime`):
  queries          — query history from the event pipeline (wall, state,
                     rows, error + error_type classification)
  spans            — flattened span trees of recently traced queries
                     (query_trace session property; telemetry/spans)
  compilations     — recent SPMD compile events (step, bucket, mesh, wall
                     seconds; telemetry/compile_events ring)
  metrics          — the process metrics registry (telemetry/metrics)
  query_profiles   — the persistent per-query profile archive's memory
                     ring (telemetry/profile_store; wall, gate wait,
                     compile seconds, archived artifact path)
  plan_decisions   — per-query plan-decision ledgers of recently archived
                     statements (telemetry/decisions; choice, rejected
                     alternative, measured bytes, hindsight verdict)
  nodes            — mesh workers and their liveness
  session_properties — property values in effect
  caches           — buffer-pool tiers (bytes, hits, misses)

Schema `metrics` re-exposes the registry as `system.metrics.metrics` (the
Prometheus surface's SQL twin).
"""

from __future__ import annotations

import numpy as np

from trino_tpu import types as T
from trino_tpu.columnar import StringDictionary
from trino_tpu.connectors.api import (
    ColumnData,
    ColumnMeta,
    Connector,
    ConnectorMetadata,
    PageSource,
    Split,
    TableHandle,
    TableMetadata,
    TableStatistics,
)
from trino_tpu.runtime.events import EventListener


class QueryHistory(EventListener):
    """Bounded in-memory query log fed by the event pipeline."""

    def __init__(self, limit: int = 1000):
        self.limit = limit
        self.entries: list[dict] = []
        self._running: dict[str, dict] = {}

    def query_created(self, e):
        row = {
            "query_id": e.query_id,
            "state": "RUNNING",
            "query": e.sql,
            "create_time": e.create_time,
            "end_time": None,
            "wall_s": None,
            "rows": None,
            "error": None,
            "error_type": None,
            "error_code": None,
        }
        self._running[e.query_id] = row
        self.entries.append(row)
        if len(self.entries) > self.limit:
            self.entries = self.entries[-self.limit :]

    def query_completed(self, e):
        row = self._running.pop(e.query_id, None)
        if row is None:
            return
        row["state"] = e.state
        row["end_time"] = e.end_time
        row["rows"] = e.rows
        row["error"] = e.error
        row["error_type"] = getattr(e, "error_type", None)
        # lifecycle kill reason (USER_CANCELED | EXCEEDED_TIME_LIMIT |
        # CLUSTER_OUT_OF_MEMORY) — why a query stopped, not just that it did
        row["error_code"] = getattr(e, "error_code", None)
        row["wall_s"] = e.wall_s


_TABLES = {
    "queries": [
        ("query_id", T.VARCHAR),
        ("state", T.VARCHAR),
        ("query", T.VARCHAR),
        ("create_time", T.DOUBLE),
        ("end_time", T.DOUBLE),
        ("wall_s", T.DOUBLE),
        ("rows", T.BIGINT),
        ("error", T.VARCHAR),
        ("error_type", T.VARCHAR),
        ("error_code", T.VARCHAR),
    ],
    "spans": [
        ("query_id", T.VARCHAR),
        ("span_id", T.BIGINT),
        ("parent_id", T.BIGINT),
        ("name", T.VARCHAR),
        ("start_ms", T.DOUBLE),
        ("duration_ms", T.DOUBLE),
        ("attributes", T.VARCHAR),
    ],
    "compilations": [
        ("seq", T.BIGINT),
        ("step", T.VARCHAR),
        ("bucket", T.BIGINT),
        ("mesh", T.VARCHAR),
        ("query_id", T.VARCHAR),
        ("fragment", T.BIGINT),
        ("wall_s", T.DOUBLE),
        ("key_fp", T.VARCHAR),
        ("key", T.VARCHAR),
    ],
    "metrics": [
        ("name", T.VARCHAR),
        ("kind", T.VARCHAR),
        ("labels", T.VARCHAR),
        ("value", T.DOUBLE),
    ],
    "nodes": [
        ("node_id", T.VARCHAR),
        ("state", T.VARCHAR),
        # seconds since the worker's last successful heartbeat (NULL when
        # the node never heartbeat — e.g. local mesh devices)
        ("heartbeat_age_s", T.DOUBLE),
        # the worker's circuit-breaker state (closed | half_open | open)
        ("breaker_state", T.VARCHAR),
        # the process's prewarm-executor state (runtime/prewarm: IDLE |
        # RUNNING | WARM | UNCLOSED | FAILED; NULL = no executor attached)
        ("prewarm", T.VARCHAR),
    ],
    "resource_groups": [
        ("name", T.VARCHAR),
        ("weight", T.BIGINT),
        ("max_concurrency", T.BIGINT),
        ("max_queued", T.BIGINT),
        ("memory_limit_bytes", T.BIGINT),
        ("memory_reserved_bytes", T.BIGINT),
        ("running", T.BIGINT),
        ("queued", T.BIGINT),
        ("total_admitted", T.BIGINT),
        ("total_queued", T.BIGINT),
        ("shed", T.BIGINT),
    ],
    "query_profiles": [
        ("query_id", T.VARCHAR),
        ("sql_hash", T.VARCHAR),
        ("state", T.VARCHAR),
        ("wall_s", T.DOUBLE),
        ("mesh", T.VARCHAR),
        # resource group the statement was admitted through (NULL for
        # undispatched executions)
        ("resource_group", T.VARCHAR),
        # device time-slice gate wait attributed to the statement
        ("gate_wait_s", T.DOUBLE),
        ("compile_s", T.DOUBLE),
        ("peak_memory_bytes", T.BIGINT),
        # filesystem-SPI location of the archived artifact (NULL when the
        # store runs in-memory only)
        ("archived_path", T.VARCHAR),
    ],
    "plan_decisions": [
        ("query_id", T.VARCHAR),
        ("decision_id", T.VARCHAR),
        ("kind", T.VARCHAR),
        ("site", T.VARCHAR),
        ("choice", T.VARCHAR),
        ("alternative", T.VARCHAR),
        # JSON: the inputs the decider saw (estimated rows, license
        # width, economy verdict)
        ("inputs", T.VARCHAR),
        # audit-log watermark at decision time (telemetry/audit seq)
        ("audit_seq", T.BIGINT),
        # exchange-plane bytes (all_to_all + all_gather) this choice moved
        ("exchange_bytes", T.BIGINT),
        # JSON: {kind/purpose: bytes} full attribution
        ("bytes_by", T.VARCHAR),
        # summed wall of the fragments whose collectives attributed here
        ("fragment_wall_s", T.DOUBLE),
        ("hindsight", T.VARCHAR),
        ("hindsight_detail", T.VARCHAR),
    ],
    "session_properties": [
        ("name", T.VARCHAR),
        ("value", T.VARCHAR),
        ("description", T.VARCHAR),
    ],
    "caches": [
        ("tier", T.VARCHAR),
        ("bytes", T.BIGINT),
        ("hits", T.BIGINT),
        ("misses", T.BIGINT),
    ],
}


class _SystemMetadata(ConnectorMetadata):
    def list_schemas(self):
        return ["metrics", "runtime"]

    def list_tables(self, schema: str):
        if schema == "runtime":
            return sorted(_TABLES)
        if schema == "metrics":
            return ["metrics"]
        return []

    def table_metadata(self, schema: str, table: str) -> TableMetadata:
        if table not in self.list_tables(schema):
            raise KeyError(f"system table not found: {schema}.{table}")
        return TableMetadata(
            schema, table, tuple(ColumnMeta(n, t) for n, t in _TABLES[table])
        )

    def table_statistics(self, schema: str, table: str) -> TableStatistics:
        return TableStatistics(row_count=100)


class _RowsPageSource(PageSource):
    def __init__(self, rows: list, types: list, columns: list, all_names: list):
        self.rows = rows
        self.types = types
        self.columns = columns
        self.all_names = all_names

    def row_count(self) -> int:
        return len(self.rows)

    def pages(self):
        ix = [self.all_names.index(c) for c in self.columns]
        out = []
        for i, t in zip(ix, self.types):
            vals = [r[i] for r in self.rows]
            valid = np.asarray([v is not None for v in vals])
            if T.is_string_kind(t):
                strs = ["" if v is None else str(v) for v in vals]
                d = StringDictionary.from_unsorted(strs or [""])
                codes = np.asarray(
                    [d.index[s] for s in strs], dtype=np.int32
                )
                out.append(
                    ColumnData(codes, None if valid.all() else valid, d)
                )
            else:
                data = np.asarray(
                    [0 if v is None else v for v in vals], dtype=t.np_dtype
                )
                out.append(ColumnData(data, None if valid.all() else valid))
        yield out


class SystemConnector(Connector):
    name = "system"

    def __init__(self, runner=None):
        self.runner = runner  # bound after runner construction
        self._metadata = _SystemMetadata()

    def metadata(self):
        return self._metadata

    def splits(self, handle: TableHandle, target_splits: int, predicate=None):
        n = len(self._rows(handle.table))
        return [Split(handle, 0, row_start=0, row_count=n)]

    def page_source(self, split: Split, columns, max_rows_per_page: int = 1 << 20):
        table = split.table.table
        schema = _TABLES[table]
        all_names = [n for n, _ in schema]
        tmap = dict(schema)
        return _RowsPageSource(
            self._rows(table), [tmap[c] for c in columns], list(columns), all_names
        )

    def _rows(self, table: str) -> list:
        r = self.runner
        if table == "queries":
            hist = getattr(r, "query_history", None)
            if hist is None:
                return []
            return [
                (
                    e["query_id"], e["state"], e["query"], e["create_time"],
                    e["end_time"], e.get("wall_s"), e["rows"], e["error"],
                    e.get("error_type"), e.get("error_code"),
                )
                for e in hist.entries
            ]
        if table == "spans":
            out = []
            for qid, spans in getattr(r, "traces", ()):
                for s in spans:
                    out.append(
                        (
                            s["query_id"] or qid, s["span_id"],
                            s["parent_id"], s["name"], s["start_ms"],
                            s["duration_ms"], s["attributes"],
                        )
                    )
            return out
        if table == "compilations":
            from trino_tpu.telemetry.compile_events import OBSERVATORY

            return OBSERVATORY.rows()
        if table == "metrics":
            from trino_tpu.telemetry import REGISTRY

            return REGISTRY.rows()
        if table == "nodes":
            # cluster membership (runtime/membership) is authoritative when
            # present: worker id, ACTIVE|DRAINING|DEAD, heartbeat age, the
            # worker's breaker state, and the process's prewarm state in
            # one row
            pw = getattr(r, "prewarm", None)
            pstate = pw.state if pw is not None else None
            membership = getattr(r, "membership", None)
            if membership is not None:
                return [
                    row + (pstate,) for row in membership.snapshot()
                ]
            det = getattr(r, "failure_detector", None)
            if det is not None and hasattr(det, "failed_workers"):
                failed = det.failed_workers()
                clk = det.clock()
                return [
                    (
                        w,
                        "DEAD" if w in failed else "ACTIVE",
                        round(clk - det._last[w], 3),
                        None,
                        pstate,
                    )
                    for w in sorted(det._last)
                ]
            import jax

            return [
                (str(d.id), "ACTIVE", None, None, pstate)
                for d in jax.devices()
            ]
        if table == "resource_groups":
            # live admission state: the dispatcher when attached (serving
            # coordinator), else any standalone resource-group manager the
            # runner carries; an embedded runner with neither has no rows
            d = getattr(r, "dispatcher", None)
            stats = (
                d.stats()
                if d is not None
                else getattr(
                    getattr(r, "resource_groups", None), "stats", lambda: []
                )()
            )
            return [
                (
                    s["name"], s.get("weight", 1), s["hard_concurrency"],
                    s.get("max_queued"), s.get("memory_limit_bytes", 0),
                    s.get("memory_reserved_bytes", 0), s["running"],
                    s["queued"], s["total_admitted"], s["total_queued"],
                    s.get("shed_total", 0),
                )
                for s in stats
            ]
        if table == "query_profiles":
            # the profile archive's memory ring (telemetry/profile_store):
            # one row per recently archived statement artifact; empty when
            # no store is attached (profile.archive-dir unset)
            store = getattr(r, "profile_store", None)
            return store.rows() if store is not None else []
        if table == "plan_decisions":
            # the decision ledgers of recently archived statements
            # (telemetry/decisions via the profile ring); empty when no
            # store is attached
            store = getattr(r, "profile_store", None)
            return store.decision_rows() if store is not None else []
        if table == "session_properties":
            return [
                (name, str(value), meta.description)
                for name, value, meta in r.properties.items()
            ]
        if table == "caches":
            from trino_tpu.runtime.buffer_pool import POOL

            s = POOL.stats()
            return [
                ("host", s["host_bytes"], s["host_hits"], s["host_misses"]),
                (
                    "device",
                    s["device_bytes"],
                    s["device_hits"],
                    s["device_misses"],
                ),
            ]
        raise KeyError(table)
