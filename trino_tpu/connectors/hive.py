"""Hive-style partitioned file connector (parquet + ORC).

Reference roles: plugin/trino-hive (HiveMetadata / HivePartitionManager
partition pruning, BackgroundHiveSplitLoader's directory walk,
ParquetPageSourceFactory + OrcPageSourceFactory) and lib/trino-orc's reader
role — the host decode is pyarrow (parquet row groups, ORC stripes), the
metastore is the directory layout itself:

    root/<schema>/<table>/<pcol>=<val>/.../part-*.parquet|.orc

Partition columns live in directory names (values typed by inference:
int-looking -> bigint, date-looking -> date, else varchar).  Split
enumeration prunes partitions against pushed-down predicate conjuncts
(HivePartitionManager.getPartitions analog) BEFORE any file IO, then splits
per parquet row group / per ORC stripe group.  Partition values surface as
constant columns welded onto each page (HivePageSource's prefilled blocks).
"""

from __future__ import annotations

import datetime
import os
import re
from typing import Optional, Sequence

import numpy as np

from trino_tpu import types as T
from trino_tpu.columnar import StringDictionary
from trino_tpu.connectors.api import (
    ColumnData,
    ColumnMeta,
    Connector,
    ConnectorMetadata,
    PageSource,
    Split,
    TableHandle,
    TableMetadata,
    TableStatistics,
)
from trino_tpu.connectors.parquet import _array_to_column_data, _arrow_to_type

_DATA_EXT = (".parquet", ".orc")
_INT_RE = re.compile(r"^-?\d+$")
_DATE_RE = re.compile(r"^\d{4}-\d{2}-\d{2}$")


def _infer_partition_type(values: Sequence[str]) -> T.Type:
    if all(_INT_RE.match(v) for v in values):
        return T.BIGINT
    if all(_DATE_RE.match(v) for v in values):
        return T.DATE
    return T.VARCHAR


def _partition_value(raw: str, t: T.Type):
    """Directory-name string -> logical python value."""
    if t is T.BIGINT:
        return int(raw)
    if t is T.DATE:
        y, m, d = (int(x) for x in raw.split("-"))
        return (datetime.date(y, m, d) - datetime.date(1970, 1, 1)).days
    return raw


class _HiveMetadata(ConnectorMetadata):
    def __init__(self, conn: "HiveConnector"):
        self.conn = conn

    def list_schemas(self) -> Sequence[str]:
        root = self.conn.root
        if not os.path.isdir(root):
            return []
        return sorted(
            d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
        )

    def list_tables(self, schema: str) -> Sequence[str]:
        base = os.path.join(self.conn.root, schema)
        if not os.path.isdir(base):
            return []
        return sorted(
            d for d in os.listdir(base) if os.path.isdir(os.path.join(base, d))
        )

    def table_metadata(self, schema: str, table: str) -> TableMetadata:
        parts = self.conn._partitions(schema, table)
        if not parts:
            raise KeyError(f"hive table not found or empty: {schema}.{table}")
        pcols = parts[0].keys_in_order
        sample = parts[0].files[0]
        file_cols = self.conn._file_schema(sample)
        ptypes = {}
        for k in pcols:
            ptypes[k] = _infer_partition_type(
                [p.values[k] for p in parts]
            )
        cols = tuple(
            list(file_cols)
            + [ColumnMeta(k, ptypes[k]) for k in pcols]
        )
        return TableMetadata(schema, table, cols)

    def table_statistics(self, schema: str, table: str) -> TableStatistics:
        rows = 0
        for p in self.conn._partitions(schema, table):
            for f in p.files:
                rows += _file_rows(f)
        return TableStatistics(row_count=rows)


class _Partition:
    __slots__ = ("keys_in_order", "values", "files")

    def __init__(self, keys_in_order, values, files):
        self.keys_in_order = keys_in_order
        self.values = values  # {pcol: raw string}
        self.files = files


def _file_rows(path: str) -> int:
    if path.endswith(".parquet"):
        import pyarrow.parquet as pq

        return pq.ParquetFile(path).metadata.num_rows
    import pyarrow.orc as po

    return po.ORCFile(path).nrows


class _HivePageSource(PageSource):
    def __init__(self, split: Split, columns, types, page_rows: int):
        self.split = split
        self.columns = list(columns)
        self.types = list(types)
        self.page_rows = page_rows

    def row_count(self) -> int:
        return self.split.row_count

    def pages(self):
        path, piece, pvals, ptypes = self.split.info
        file_cols = [c for c in self.columns if c not in pvals]
        if not file_cols:
            # partition-columns-only projection: no file read at all, emit
            # constant pages sized by the piece's row count (a zero-column
            # arrow table cannot carry the count)
            n = self.split.row_count
            for start in range(0, max(n, 1), self.page_rows):
                rows = min(self.page_rows, n - start)
                if rows <= 0 and start > 0:
                    break
                yield [
                    _constant_column(pvals[c], ptypes[c], max(rows, 0))
                    for c in self.columns
                ]
            return
        tbl = _read_piece(path, piece, file_cols)
        n = tbl.num_rows
        for start in range(0, max(n, 1), self.page_rows):
            chunk = tbl.slice(start, self.page_rows)
            if chunk.num_rows == 0 and start > 0:
                break
            out = []
            for c, t in zip(self.columns, self.types):
                if c in pvals:
                    out.append(
                        _constant_column(pvals[c], ptypes[c], chunk.num_rows)
                    )
                else:
                    out.append(
                        _array_to_column_data(
                            chunk.column(file_cols.index(c)), t
                        )
                    )
            yield out


def _read_piece(path: str, piece, columns):
    if path.endswith(".parquet"):
        import pyarrow.parquet as pq

        pf = pq.ParquetFile(path)
        return pf.read_row_group(piece, columns=columns)
    import pyarrow.orc as po

    f = po.ORCFile(path)
    return f.read_stripe(piece, columns=columns)


def _constant_column(raw: str, t: T.Type, n: int) -> ColumnData:
    """Partition value as a constant column (HivePageSource prefilled
    blocks; RLE on device is just a broadcast)."""
    v = _partition_value(raw, t)
    if t is T.VARCHAR:
        d = StringDictionary.from_unsorted([v])
        return ColumnData(np.zeros(n, np.int32), None, d)
    return ColumnData(np.full(n, v, dtype=t.np_dtype), None)


class HiveConnector(Connector):
    name = "hive"

    def __init__(self, root: str):
        self.root = root
        self._metadata = _HiveMetadata(self)

    def metadata(self) -> _HiveMetadata:
        return self._metadata

    # -- directory walk (BackgroundHiveSplitLoader role) ---------------------

    def _file_schema(self, path: str):
        if path.endswith(".parquet"):
            import pyarrow.parquet as pq

            schema = pq.read_schema(path)
        else:
            import pyarrow.orc as po

            schema = po.ORCFile(path).schema
        return [ColumnMeta(f.name, _arrow_to_type(f.type)) for f in schema]

    def _partitions(self, schema: str, table: str) -> list:
        base = os.path.join(self.root, schema, table)
        if not os.path.isdir(base):
            return []
        out = []

        def walk(d, keys, vals):
            files = []
            subdirs = []
            for name in sorted(os.listdir(d)):
                p = os.path.join(d, name)
                if os.path.isfile(p) and name.endswith(_DATA_EXT):
                    files.append(p)
                elif os.path.isdir(p) and "=" in name:
                    subdirs.append((name, p))
            if files:
                out.append(
                    _Partition(tuple(keys), dict(zip(keys, vals)), files)
                )
            for name, p in subdirs:
                k, _, v = name.partition("=")
                walk(p, keys + [k], vals + [v])

        walk(base, [], [])
        return out

    def scan_version(self, handle: TableHandle):
        try:
            sig = []
            for p in self._partitions(handle.schema, handle.table):
                for f in p.files:
                    sig.append((f, int(os.path.getmtime(f)), os.path.getsize(f)))
            return tuple(sig)
        except OSError:
            return None

    # -- partition pruning (HivePartitionManager.getPartitions) --------------

    def _prune(self, partitions: list, predicate, ptypes: dict) -> list:
        """`predicate` is a list of (column, op, value) conjunct triples the
        engine extracted from the pushed-down predicate; conjuncts on
        non-partition columns are ignored (they filter on device later)."""
        if not predicate:
            return partitions
        kept = []
        for part in partitions:
            ok = True
            for col, op, val in predicate:
                if col not in part.values:
                    continue
                pv = _partition_value(part.values[col], ptypes[col])
                if op == "=":
                    ok = pv == val
                elif op == "in":
                    ok = pv in val
                elif op == "<":
                    ok = pv < val
                elif op == "<=":
                    ok = pv <= val
                elif op == ">":
                    ok = pv > val
                elif op == ">=":
                    ok = pv >= val
                if not ok:
                    break
            if ok:
                kept.append(part)
        return kept

    def splits(self, handle: TableHandle, target_splits: int, predicate=None):
        parts = self._partitions(handle.schema, handle.table)
        if not parts:
            return []
        meta = self._metadata.table_metadata(handle.schema, handle.table)
        tmap = {c.name: c.type for c in meta.columns}
        ptypes = {k: tmap[k] for k in parts[0].keys_in_order}
        keep = {
            id(p) for p in self._prune(parts, predicate, ptypes)
        }
        out = []
        seq = 0
        row_start = 0
        # seq numbers come from the UNPRUNED enumeration so a split's
        # identity (and therefore its buffer-pool cache key) is stable no
        # matter which predicate selected it
        for part in parts:
            for path in part.files:
                for piece, nrows in _pieces(path):
                    if id(part) in keep:
                        out.append(
                            Split(
                                handle,
                                seq,
                                row_start=row_start,
                                row_count=nrows,
                                info=(path, piece, part.values, ptypes),
                            )
                        )
                    seq += 1
                    row_start += nrows
        return out

    def page_source(
        self, split: Split, columns: Sequence[str], max_rows_per_page: int = 1 << 20
    ) -> PageSource:
        meta = self._metadata.table_metadata(
            split.table.schema, split.table.table
        )
        tmap = {c.name: c.type for c in meta.columns}
        types = [tmap[c] for c in columns]
        return _HivePageSource(split, columns, types, max_rows_per_page)


def _pieces(path: str):
    """(piece_index, rows) per split unit: parquet row group / ORC stripe."""
    if path.endswith(".parquet"):
        import pyarrow.parquet as pq

        meta = pq.ParquetFile(path).metadata
        return [
            (rg, meta.row_group(rg).num_rows)
            for rg in range(meta.num_row_groups)
        ]
    import pyarrow.orc as po

    f = po.ORCFile(path)
    return [(i, f.read_stripe(i).num_rows) for i in range(f.nstripes)]


# -- partitioned export helper (writer role of plugin/trino-hive) ------------


def write_partitioned(
    connector: Connector,
    schema: str,
    table: str,
    out_root: str,
    partition_by: Sequence[str],
    fmt: str = "parquet",
    row_group_rows: int = 1 << 20,
) -> int:
    """Export a connector table into hive layout, partitioned by
    `partition_by` columns.  Returns partition count."""
    import pyarrow as pa

    from trino_tpu.connectors.parquet import _column_data_to_arrow

    meta = connector.metadata().table_metadata(schema, table)
    handle = TableHandle("src", schema, table)
    names = [c.name for c in meta.columns]
    tmap = {c.name: c.type for c in meta.columns}
    chunks = []
    for split in connector.splits(handle, target_splits=1):
        src = connector.page_source(split, names, max_rows_per_page=row_group_rows)
        for page in src.pages():
            arrays = {
                n: _column_data_to_arrow(cd, tmap[n])
                for n, cd in zip(names, page)
            }
            chunks.append(pa.table(arrays))
    tbl = pa.concat_tables(chunks)
    data_cols = [n for n in names if n not in partition_by]
    # group by partition values host-side
    import pyarrow.compute as pc

    keys = tbl.select(list(partition_by))
    combos = keys.group_by(list(partition_by)).aggregate([])
    nparts = 0
    for row in combos.to_pylist():
        mask = None
        for k, v in row.items():
            m = pc.equal(tbl.column(k), pa.scalar(v, tbl.column(k).type))
            mask = m if mask is None else pc.and_(mask, m)
        sub = tbl.filter(mask).select(data_cols)
        d = os.path.join(
            out_root, schema, table,
            *[f"{k}={_render(v)}" for k, v in row.items()],
        )
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"part-0.{fmt}")
        if fmt == "parquet":
            import pyarrow.parquet as pq

            pq.write_table(sub, path, row_group_size=row_group_rows)
        elif fmt == "orc":
            import pyarrow.orc as po

            po.write_table(sub, path)
        else:
            raise ValueError(f"unsupported format {fmt}")
        nparts += 1
    return nparts


def _render(v) -> str:
    if isinstance(v, datetime.date):
        return v.isoformat()
    return str(v)
