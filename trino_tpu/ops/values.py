"""Literal-rows operator (reference: operator/ValuesOperator.java)."""

from __future__ import annotations

from typing import Sequence

import jax

from trino_tpu.columnar import batch_from_rows
from trino_tpu.types import Type


class ValuesOperator:
    def __init__(self, types: Sequence[Type], rows: Sequence[Sequence]):
        self.types = list(types)
        self.rows = list(rows)

    def batches(self):
        if not self.rows:
            return
        yield jax.device_put(batch_from_rows(self.types, self.rows))
