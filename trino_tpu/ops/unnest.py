"""UNNEST operator: expand array columns into rows.

Reference: core/trino-main/.../operator/unnest/UnnestOperator.java (+
UnnestBlockBuilder): each input row is replicated once per element of its
unnested array(s); multiple arrays zip, padding the shorter with NULLs;
WITH ORDINALITY appends the 1-based element index.

TPU design: arrays are rectangular [cap, K] blocks (columnar/column.py), so
unnest is a static-shape reshape — replicate row r to K output slots, mask
slot (r, k) live iff k < max(lengths_i[r]).  Output capacity is cap*K; the
driver compacts at the next boundary.  No per-row host loop, no dynamic
shapes: one jitted gather per batch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from trino_tpu import types as T
from trino_tpu.columnar import Batch, Column
from trino_tpu.expr import ExprCompiler
from trino_tpu.expr.ir import Expr

_STEP_CACHE: dict = {}


class UnnestOperator:
    """`exprs` evaluate to array values over the input batch; `replicate` is
    every pass-through input channel."""

    def __init__(self, exprs, with_ordinality: bool = False):
        self.exprs = list(exprs)
        self.with_ordinality = with_ordinality
        key = (
            tuple(e.key() for e in self.exprs),
            with_ordinality,
        )
        #: un-jitted step for callers that wrap it in their own program
        #: (the SPMD executor jits it inside shard_map)
        self.raw_step = self._make_step()
        cached = _STEP_CACHE.get(key)
        if cached is None:
            cached = jax.jit(self.raw_step)
            _STEP_CACHE[key] = cached
        self._step = cached

    def _make_step(self):
        exprs, with_ord = self.exprs, self.with_ordinality

        def step(batch: Batch):
            c = ExprCompiler(batch)
            arrays = []
            for e in exprs:
                v = c.value(e)
                if v.lengths is None:
                    raise NotImplementedError("UNNEST of non-array value")
                k_e = v.data.shape[-1]
                data = jnp.broadcast_to(
                    jnp.asarray(v.data), (batch.capacity, k_e)
                )
                lens = jnp.broadcast_to(
                    jnp.asarray(v.lengths, jnp.int32), (batch.capacity,)
                )
                if v.valid is not None and v.valid is not False:
                    lens = jnp.where(v.valid, lens, 0)
                elif v.valid is False:
                    lens = jnp.zeros_like(lens)
                arrays.append((data, lens, v))
            k = max(1, max(a[0].shape[1] for a in arrays))
            cap = batch.capacity
            pos = jnp.arange(k, dtype=jnp.int32)[None, :]  # [1, K]
            max_lens = arrays[0][1]
            for _, lens, _v in arrays[1:]:
                max_lens = jnp.maximum(max_lens, lens)
            live2 = jnp.logical_and(
                batch.mask()[:, None], pos < max_lens[:, None]
            )  # [cap, K]
            out_mask = live2.reshape(cap * k)
            # replicated source columns: row index repeats K times
            rep = jnp.repeat(jnp.arange(cap, dtype=jnp.int64), k)
            cols = [col.gather(rep) for col in batch.columns]
            # element columns
            for data, lens, v in arrays:
                k_e = data.shape[1]
                if k_e < k:
                    data = jnp.pad(data, ((0, 0), (0, k - k_e)))
                flat = data.reshape(cap * k)
                evalid = (pos < lens[:, None]).reshape(cap * k)
                cols.append(
                    Column(flat, v.type.element, evalid, v.dictionary)
                )
            if with_ord:
                ordv = (pos + 1).astype(jnp.int64)
                cols.append(
                    Column(
                        jnp.broadcast_to(ordv, (cap, k)).reshape(cap * k),
                        T.BIGINT,
                    )
                )
            return cols, out_mask

        return step

    def process(self, stream):
        for batch in stream:
            cols, mask = self._step(batch)
            yield Batch(cols, mask)
