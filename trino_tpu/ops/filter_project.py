"""Filter + project operator (reference: FilterAndProjectOperator +
the generated PageFilter/PageProjection from sql/gen/PageFunctionCompiler).

One jitted step evaluates the predicate and all projections over a batch; XLA
fuses everything into a single device program.  Output stays masked (no
compaction) — downstream operators work on masks; compaction happens only at
exchange/result boundaries.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax

from trino_tpu.columnar import Batch
from trino_tpu.expr import ExprCompiler
from trino_tpu.expr.ir import Call, Expr

#: functions that must evaluate eagerly (host-side per-row rendering):
#: projections containing one run the step unjitted
EAGER_FUNCS = frozenset({"array_join", "format", "concat_ws"})


def _needs_eager(e: Expr, _seen: set = None) -> bool:
    if _seen is None:
        _seen = set()
    if id(e) in _seen:  # shared-DAG guard (see ir.visit)
        return False
    _seen.add(id(e))
    if isinstance(e, Call) and e.name in EAGER_FUNCS:
        return True
    return any(_needs_eager(c, _seen) for c in e.children())


#: process-level jitted-step cache, keyed by expression structure — operator
#: instances are per-query, but identical programs (same exprs) reuse one jit
#: wrapper so repeated queries skip retracing (reference analog: the
#: PageFunctionCompiler's generated-class cache, sql/gen/PageFunctionCompiler
#: .java:103)
_STEP_CACHE: dict = {}


class FilterProjectOperator:
    def __init__(self, predicate: Optional[Expr], projections: Sequence[Expr]):
        self.predicate = predicate
        self.projections = list(projections)
        key = (
            None if predicate is None else predicate.key(),
            tuple(e.key() for e in projections),
        )
        cached = _STEP_CACHE.get(key)
        if cached is None:
            step = self._make_step()
            exprs = ([] if predicate is None else [predicate]) + list(
                projections
            )
            # expressions with host-eager functions (per-row string renders
            # that can't trace) run the same step without jit
            cached = step if any(map(_needs_eager, exprs)) else jax.jit(step)
            _STEP_CACHE[key] = cached
        self._step = cached

    def _make_step(self):
        pred, projs = self.predicate, self.projections

        def step(batch: Batch) -> Batch:
            c = ExprCompiler(batch)
            out = batch
            if pred is not None:
                out = out.filter(c.filter_mask(pred))
            cols = [c.column(e) for e in projs]
            if not cols:
                # zero-column projection (`count(*)` over bare rows): the
                # row count must ride the materialized mask, else capacity
                # collapses to 0
                return Batch(cols, out.mask())
            return Batch(cols, out.row_mask)

        return step

    def fusable_step(self):
        """(raw untraced step, structural key) for fusion INTO a downstream
        operator's jitted program (e.g. the aggregation partial step), or
        (None, None) when the expressions need host-eager evaluation.
        Fusion removes the materialize-then-reload of projection outputs —
        on TPU that is HBM traffic, on CPU cache traffic."""
        exprs = ([] if self.predicate is None else [self.predicate]) + list(
            self.projections
        )
        if any(map(_needs_eager, exprs)):
            return None, None
        key = (
            None if self.predicate is None else self.predicate.key(),
            tuple(e.key() for e in self.projections),
        )
        raw = _STEP_CACHE.get(("raw", key))
        if raw is None:
            # cache the RAW closure too: the consumer bakes it into its own
            # jitted program keyed by `key`, so the closure identity must be
            # stable across queries or every query would retrace
            raw = self._make_step()
            _STEP_CACHE[("raw", key)] = raw
        return raw, key

    def process(self, stream):
        for batch in stream:
            yield self._step(batch)
