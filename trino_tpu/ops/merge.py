"""Ordered merge of sorted shards (the merge-exchange consumer).

Reference: operator/MergeOperator.java + util/MergeSortedPages.java + the
distributed-sort doc (docs/src/main/sphinx/admin/dist-sort.rst): each worker
produces a sorted shard; the single consumer merges them preserving order.

Host substitution: the reference streams pages through a binary-heap merge;
here the shards are dense host columns, so the merge is a vectorized stable
radix pass (np.lexsort) over the concatenated shard keys with the same
direction/NULL/NaN encoding the device sort uses (ops/common.py
_key_with_null_order).  Stability across the concatenation preserves shard
order for ties, which is exactly the heap-merge tie rule.  Dictionary codes
compare like their strings (StringDictionary code == rank) provided all
shards share a dictionary — true for shards of one stacked batch.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from trino_tpu.columnar import Batch, Column
from trino_tpu.ops.common import SortKey


def _np_key_parts(col: Column, ascending: bool, nulls_first: bool):
    """(rank or None, [value_keys least->most significant]) mirroring
    ops/common._key_with_null_order.  Long decimals contribute two keys
    (low limb in unsigned order, then high limb)."""
    data = np.asarray(col.data)
    if data.ndim == 2:  # long-decimal limb planes
        hi = data[:, 0]
        lo = data[:, 1] ^ np.int64(-(2**63))  # unsigned order
        if not ascending:
            hi, lo = ~hi, ~lo
        rank = None
        if col.valid is not None:
            rank = np.where(
                np.asarray(col.valid),
                np.zeros(len(data), dtype=np.int8),
                np.asarray(-2 if nulls_first else 2, np.int8),
            )
        return rank, [lo, hi]
    if data.dtype == np.bool_:
        data = data.astype(np.int8)
    rank = None
    if np.issubdtype(data.dtype, np.floating):
        nan = np.isnan(data)
        value_key = np.where(nan, np.asarray(0, data.dtype), data)
        if not ascending:
            value_key = -value_key
        rank = np.where(nan, 1 if ascending else -1, 0).astype(np.int8)
    else:
        value_key = data if ascending else ~data
    if col.valid is not None:
        base = rank if rank is not None else np.zeros(len(data), dtype=np.int8)
        rank = np.where(
            np.asarray(col.valid), base, np.asarray(-2 if nulls_first else 2, np.int8)
        )
    return rank, [value_key]


def merge_sorted_shards(shards: Sequence[Batch], keys: Sequence[SortKey]) -> Batch:
    """Merge per-worker sorted host shards into one sorted host Batch.
    Shards must be compacted (live rows only) and sorted by `keys`."""
    if not shards:
        raise ValueError("no shards to merge")
    nonempty = [s for s in shards if s.capacity]
    if not nonempty:
        return shards[0]  # zero-row result keeps its (empty) schema
    shards = nonempty
    if len(shards) == 1:
        return shards[0]
    # np.lexsort: last key in the sequence is primary -> feed keys reversed,
    # each as (value, rank) with rank more significant than value
    lex_cols: list[np.ndarray] = []
    for k in reversed(list(keys)):
        parts = [
            _np_key_parts(s.columns[k.channel], k.ascending, k.nulls_first)
            for s in shards
        ]
        n_keys = max(len(p[1]) for p in parts)
        for ki in range(n_keys):
            lex_cols.append(
                np.concatenate([p[1][min(ki, len(p[1]) - 1)] for p in parts])
            )
        if any(p[0] is not None for p in parts):
            lex_cols.append(
                np.concatenate(
                    [
                        p[0]
                        if p[0] is not None
                        else np.zeros(s.capacity, dtype=np.int8)
                        for p, s in zip(parts, shards)
                    ]
                )
            )
    order = np.lexsort(lex_cols) if lex_cols else np.arange(
        sum(s.capacity for s in shards)
    )
    cols = []
    for ch in range(shards[0].width):
        first = shards[0].columns[ch]
        parts = [s.columns[ch] for s in shards]
        lengths = None
        if any(p.lengths is not None for p in parts):
            # array/map channels: right-pad each shard's element plane to
            # the widest K (map channels pad per packed half) and carry the
            # per-row lengths through the permutation
            from trino_tpu.types import MapType

            is_map = isinstance(first.type, MapType)
            kmax = max(
                (np.asarray(p.data).shape[1] for p in parts if p.lengths is not None),
                default=1,
            )
            kmax = max(kmax, 2 if is_map else 1)
            padded = []
            lens_parts = []
            for p, s in zip(parts, shards):
                d = np.asarray(p.data)
                if p.lengths is None or d.ndim == 1:
                    d = np.zeros((s.capacity, kmax), dtype=d.dtype)
                    lens_parts.append(np.zeros(s.capacity, np.int32))
                else:
                    if d.shape[1] < kmax:
                        if is_map:
                            half = d.shape[1] // 2
                            pad = (kmax - d.shape[1]) // 2
                            d = np.concatenate(
                                [
                                    np.pad(d[:, :half], ((0, 0), (0, pad))),
                                    np.pad(d[:, half:], ((0, 0), (0, pad))),
                                ],
                                axis=1,
                            )
                        else:
                            d = np.pad(d, ((0, 0), (0, kmax - d.shape[1])))
                    lens_parts.append(np.asarray(p.lengths, np.int32))
                padded.append(d)
            data = np.concatenate(padded)[order]
            lengths = np.concatenate(lens_parts)[order]
        else:
            data = np.concatenate([np.asarray(p.data) for p in parts])[order]
        if any(p.valid is not None for p in parts):
            valid = np.concatenate(
                [
                    np.asarray(p.valid)
                    if p.valid is not None
                    else np.ones(s.capacity, dtype=bool)
                    for p, s in zip(parts, shards)
                ]
            )[order]
        else:
            valid = None
        cols.append(Column(data, first.type, valid, first.dictionary, lengths))
    mask = np.concatenate([np.asarray(s.mask()) for s in shards])[order]
    return Batch(cols, mask)
