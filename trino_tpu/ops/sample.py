"""Row sampling operator (reference: operator/SampleOperator.java —
BERNOULLI keeps each row with probability p).

Determinism note: the keep/drop decision is a splitmix64 hash of the
row's global position under a per-operator salt, so a given plan samples
reproducibly (the reference draws from a per-driver RNG; reproducible
sampling is the friendlier property for a trace-compiled engine and is
explicitly allowed by the SQL spec's implementation-defined sampling).
"""

from __future__ import annotations

import random

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu.columnar import Batch


@jax.jit
def _sample_step(batch: Batch, offset, ratio) -> Batch:
    """Keep rows where splitmix64(salted position) < ratio.  Salt/offset/
    ratio are TRACED arguments so every sampled query shares ONE compiled
    kernel (the _STEP_CACHE convention, via jit's own signature cache)."""
    cap = batch.capacity
    pos = jnp.arange(cap, dtype=jnp.uint64) + offset
    u = pos
    u = (u ^ (u >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    u = (u ^ (u >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    u = u ^ (u >> jnp.uint64(31))
    # top 53 bits -> uniform [0, 1)
    unif = (u >> jnp.uint64(11)).astype(jnp.float64) / float(1 << 53)
    return batch.filter(unif < ratio)


class SampleOperator:
    def __init__(self, ratio: float):
        self.ratio = float(ratio)
        self.salt = np.uint64(random.getrandbits(63))
        self._offset = 0

    def process(self, stream):
        if self.ratio >= 1.0:
            yield from stream
            return
        ratio = jnp.float64(self.ratio)
        for b in stream:
            if self.ratio <= 0.0:
                yield b.filter(jnp.zeros(b.capacity, dtype=bool))
            else:
                yield _sample_step(
                    b, jnp.uint64(self._offset) + self.salt, ratio
                )
            self._offset += b.capacity
        return
