"""Row sampling operator (reference: operator/SampleOperator.java —
BERNOULLI keeps each row with probability p).

Determinism note: the keep/drop decision is a splitmix64 hash of the
row's arrival position under a salt derived from the operator's plan
position.  The salt is deterministic, so sampling reproduces exactly when
batch arrival order does (task_concurrency=1, or any serial feed); under
the parallel local exchange the arrival order — and therefore the sampled
row SET — may differ between runs while the sampling probability is
unchanged.  (The reference's per-driver RNG is nondeterministic in all
configurations; the SQL spec leaves sampling implementation-defined.)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu.columnar import Batch
from trino_tpu.ops.common import splitmix64


@jax.jit
def _sample_step(batch: Batch, offset, ratio) -> Batch:
    """Keep rows where splitmix64(salted position) < ratio.  Salt/offset/
    ratio are TRACED arguments so every sampled query shares ONE compiled
    kernel (the _STEP_CACHE convention, via jit's own signature cache)."""
    cap = batch.capacity
    pos = jnp.arange(cap, dtype=jnp.uint64) + offset
    u = splitmix64(pos)
    # top 53 bits -> uniform [0, 1)
    unif = (u >> jnp.uint64(11)).astype(jnp.float64) / float(1 << 53)
    return batch.filter(unif < ratio)


class SampleOperator:
    def __init__(self, ratio: float, seed: int = 0):
        self.ratio = float(ratio)
        self.salt = np.uint64(splitmix64(np.uint64(seed * 2 + 1)))
        self._offset = 0

    def process(self, stream):
        if self.ratio >= 1.0:
            yield from stream
            return
        ratio = jnp.float64(self.ratio)
        for b in stream:
            if self.ratio <= 0.0:
                yield b.filter(jnp.zeros(b.capacity, dtype=bool))
            else:
                yield _sample_step(
                    b, jnp.uint64(self._offset) + self.salt, ratio
                )
            self._offset += b.capacity
        return
