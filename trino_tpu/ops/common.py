"""Shared device kernels: multi-key stable sort, segmented grouping.

Reference roles: OrderingCompiler (sql/gen/OrderingCompiler.java) for sort
orders, MultiChannelGroupByHash.getGroupIds (operator/MultiChannelGroupByHash
.java:216) for group-id assignment.  The TPU substitution is sort-based:
iterated stable argsorts (lexicographic) + key-change flags + cumsum group ids
+ segmented reductions — all static-shape, all fusable by XLA.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from trino_tpu.columnar import Batch, Column
from trino_tpu.types import DecimalType


@dataclass(frozen=True)
class SortKey:
    channel: int
    ascending: bool = True
    nulls_first: bool = False


def _key_with_null_order(col: Column, ascending: bool, nulls_first: bool):
    """(rank or None, value key) for one sort key.

    The value key realizes direction without arithmetic negation of ints
    (bitwise complement is INT64_MIN-safe) and without float bitcasts (which
    the TPU x64-rewrite cannot lower): NaN and NULL placement ride a small
    int8 rank sorted in a second stable pass.  NaN orders as largest
    (reference DoubleOperators semantics); NULL placement follows nulls_first.
    """
    data = col.data
    if data.dtype == jnp.bool_:
        data = data.astype(jnp.int8)
    rank = None
    if jnp.issubdtype(data.dtype, jnp.floating):
        nan = jnp.isnan(data)
        value_key = jnp.where(nan, jnp.asarray(0, data.dtype), data)
        if not ascending:
            value_key = -value_key  # finite negation is exact for floats
        rank = jnp.where(nan, 1 if ascending else -1, 0).astype(jnp.int8)
    else:
        value_key = data if ascending else ~data
    if col.valid is not None:
        base = rank if rank is not None else jnp.zeros_like(data, dtype=jnp.int8)
        rank = jnp.where(
            col.valid, base, jnp.asarray(-2 if nulls_first else 2, jnp.int8)
        )
    return rank, value_key


def multi_key_sort_perm(batch: Batch, keys, capacity=None):
    """Stable permutation sorting live rows by `keys` (lexicographic);
    dead rows sort last.  keys: sequence of SortKey."""
    n = batch.capacity
    perm = jnp.arange(n, dtype=jnp.int64)
    # iterate stable sorts from least-significant key to most-significant
    for k in reversed(list(keys)):
        col = batch.columns[k.channel].gather(perm)
        if col.data.ndim == 2 and isinstance(col.type, DecimalType):
            # long decimal: two stable passes — low limb (unsigned order via
            # sign-flip), then high limb; null rank rides the high pass
            from trino_tpu.types.int128 import _SIGN

            lo = col.data[:, 1] ^ _SIGN
            if not k.ascending:
                lo = ~lo
            perm = perm[jnp.argsort(lo, stable=True)]
            hi = jnp.take(
                batch.columns[k.channel].data[:, 0], perm, mode="clip"
            )
            if not k.ascending:
                hi = ~hi
            perm = perm[jnp.argsort(hi, stable=True)]
            if col.valid is not None:
                v = jnp.take(batch.columns[k.channel].valid, perm, mode="clip")
                rank = jnp.where(
                    v,
                    jnp.zeros(n, jnp.int8),
                    jnp.asarray(-2 if k.nulls_first else 2, jnp.int8),
                )
                perm = perm[jnp.argsort(rank, stable=True)]
            continue
        rank, key = _key_with_null_order(col, k.ascending, k.nulls_first)
        order = jnp.argsort(key, stable=True)
        perm = perm[order]
        if rank is not None:
            perm = perm[jnp.argsort(rank[order], stable=True)]
    # dead rows last (most significant)
    dead = jnp.logical_not(jnp.take(batch.mask(), perm, mode="clip"))
    perm = perm[jnp.argsort(dead, stable=True)]
    return perm


def group_ids_from_sorted(batch: Batch, perm, key_channels):
    """Given a sort permutation over group keys, return (gid_sorted, ngroups,
    new_group_flags): group ids in sorted order, null-safe equality."""
    n = batch.capacity
    live = jnp.take(batch.mask(), perm, mode="clip")
    change = jnp.zeros(n, dtype=bool)
    for ch in key_channels:
        col = batch.columns[ch]
        d = jnp.take(col.data, perm, axis=0, mode="clip")
        prev = jnp.roll(d, 1, axis=0)
        neq = d != prev
        if neq.ndim > 1:  # long decimal limb planes: any limb differing
            neq = jnp.any(neq, axis=-1)
        if col.valid is not None:
            v = jnp.take(col.valid, perm, mode="clip")
            pv = jnp.roll(v, 1)
            neq = jnp.logical_or(jnp.logical_and(neq, jnp.logical_and(v, pv)), v != pv)
        change = jnp.logical_or(change, neq)
    first_live = jnp.logical_and(live, jnp.cumsum(live) == 1)
    new_group = jnp.logical_and(live, jnp.logical_or(change, first_live))
    new_group = jnp.logical_or(new_group, first_live)
    gid = jnp.cumsum(new_group) - 1
    gid = jnp.where(live, gid, n - 1)  # dead rows into last (masked) slot
    ngroups = jnp.sum(new_group)
    return gid, ngroups, new_group


def segment_reduce(values, gid, num_segments: int, kind: str, valid=None):
    """Null-skipping segmented reduction. kind: sum/min/max/count/any."""
    if kind == "count":
        w = jnp.ones_like(gid, dtype=jnp.int64)
        if valid is not None:
            w = jnp.where(valid, w, 0)
        return jax.ops.segment_sum(w, gid, num_segments)
    if valid is not None:
        if kind == "sum":
            values = jnp.where(valid, values, 0)
        elif kind == "min":
            values = jnp.where(valid, values, _max_sentinel(values.dtype))
        elif kind == "max":
            values = jnp.where(valid, values, _min_sentinel(values.dtype))
        elif kind == "any":
            pass
    if kind == "sum":
        return jax.ops.segment_sum(values, gid, num_segments)
    if kind == "min":
        return jax.ops.segment_min(values, gid, num_segments)
    if kind == "max":
        return jax.ops.segment_max(values, gid, num_segments)
    if kind == "any":
        # first VALID value per segment (any_value): min row index among valid
        n = values.shape[0]
        idx = jnp.arange(n, dtype=jnp.int64)
        if valid is not None:
            idx = jnp.where(valid, idx, n)
        first = jax.ops.segment_min(idx, gid, num_segments)
        return jnp.take(values, jnp.clip(first, 0, n - 1), mode="clip")
    raise ValueError(kind)


def _max_sentinel(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(jnp.inf, dtype)
    if jnp.dtype(dtype) == jnp.dtype(bool):
        return jnp.asarray(True, dtype)  # bool_and identity
    return jnp.asarray(jnp.iinfo(dtype).max, dtype)


def _min_sentinel(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(-jnp.inf, dtype)
    if jnp.dtype(dtype) == jnp.dtype(bool):
        return jnp.asarray(False, dtype)  # bool_or identity
    return jnp.asarray(jnp.iinfo(dtype).min, dtype)


def next_pow2(n: int, floor: int = 1024) -> int:
    c = floor
    while c < n:
        c <<= 1
    return c


def splitmix64(u):
    """The splitmix64 finalizer over uint64 arrays/scalars (works on numpy
    and traced jax values; uint64 wrap-around is the intended semantics).
    THE shared copy — serde/aggregation/generators still carry inline
    duplicates that compute the same bytes; new code should call this,
    and the duplicates can be folded into it at leisure."""
    import numpy as np

    with np.errstate(over="ignore"):
        u = (u ^ (u >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        u = (u ^ (u >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return u ^ (u >> np.uint64(31))
