"""Aggregation operator — sort-based grouped reduction.

Reference roles: HashAggregationOperator.java:49, AggregationOperator (global),
MultiChannelGroupByHash.java:216 (group ids), operator/aggregation/* (the
accumulator library).  TPU substitution (SURVEY.md §7): no per-row hash
probing — group ids come from a stable multi-key sort + key-change cumsum, and
accumulators are segmented reductions, all in one jitted finish step.

Modes mirror the reference's AggregationNode.Step:
  SINGLE  : raw rows -> final values
  PARTIAL : raw rows -> state columns (for exchange)
  FINAL   : state columns -> final values

`streaming=True` reduces every pushed batch immediately and keeps only the
per-batch group states (bounded memory for low-cardinality groupings like
TPC-H Q1); otherwise input is materialized and reduced once at finish.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.columnar import Batch, Column
from trino_tpu.columnar.batch import concat_batches
from trino_tpu.ops.common import (
    SortKey,
    group_ids_from_sorted,
    multi_key_sort_perm,
    next_pow2,
    segment_reduce,
)


#: process-level jitted-step cache: instances are per-query but configs
#: recur, so identical aggregation programs share one jit wrapper (the
#: AccumulatorCompiler class-cache analog)
_STEP_CACHE: dict = {}


@dataclass(frozen=True)
class AggSpec:
    """One SQL aggregate: name in {count, count_star, sum, min, max, avg,
    any_value, bool_and, bool_or, stddev_samp, stddev_pop, var_samp,
    var_pop, percentile}, arg = input channel (None for count_star)."""

    name: str
    arg: Optional[int]
    out_type: T.Type
    param: object = None  # percentile fraction
    arg2: Optional[int] = None  # second input channel (map_agg values)
    #: proof-licensed |partial sum| bound for decimal sum/avg (planner
    #: range certificate, plan.Aggregation.sum_bound): _sum128 compiles the
    #: single-plane i64 path with no runtime fits check when set
    sum_bound: Optional[int] = None


from trino_tpu.planner.functions import HOLISTIC_AGGS

#: collect subset of the holistic aggregates (padded-array group state)
COLLECT_AGGS = ("array_agg", "map_agg", "listagg")

#: moment family: grouped state is (sum, sum-of-squares, count)
MOMENT = ("stddev_samp", "stddev_pop", "var_samp", "var_pop")

#: two-input (y, x) regression family: state is the raw-sum sextuple
BIVARIATE = ("covar_samp", "covar_pop", "corr", "regr_slope", "regr_intercept")

#: checksum's NULL-row contribution (the reference's PRIME64 role)
CHECKSUM_NULL_PRIME = 0x9E3779B185EBCA87


def _group_ranks(varg, gid_c, cap: int, nseg: int):
    """(pos_in_group, counts) for the collect-style scatters: the 0-based
    rank of each kept row (varg) within its group in sorted order, and the
    per-group kept-row counts.  Shared by _collect_one and _minmax_by_n."""
    rank_incl = jnp.cumsum(varg.astype(jnp.int64))
    base = jax.ops.segment_min(
        jnp.where(varg, rank_incl - 1, cap + 1), gid_c, nseg
    )
    pos_in_group = rank_incl - 1 - jnp.take(base, gid_c, mode="clip")
    counts = jax.ops.segment_sum(varg.astype(jnp.int64), gid_c, nseg)
    return pos_in_group, counts


#: HyperLogLog registers per sketch: p=13 -> 8192 buckets, standard error
#: 1.04/sqrt(8192) ~= 1.15% (reference: ApproximateCountDistinctAggregation
#: defaults + state/HyperLogLogStateFactory.java:23)
HLL_P = 13
HLL_M = 1 << HLL_P


def _hll_hash(col: Column):
    """Per-row 64-bit hash of the column's VALUE — stable across workers
    (dictionary codes are producer-local, so dict values hash through a
    trace-time crc table, mirroring parallel/serde.stable_row_hash)."""
    import hashlib

    d = col.data
    if col.dictionary is not None:
        # full 64-bit value hash (blake2b/8): checksum() needs real 64-bit
        # entropy — a 32-bit crc birthday-collides at ~77k distinct values
        table = np.fromiter(
            (
                np.int64(
                    np.uint64(
                        int.from_bytes(
                            hashlib.blake2b(
                                v.encode() if isinstance(v, str) else bytes(v),
                                digest_size=8,
                            ).digest(),
                            "little",
                        )
                    )
                )
                for v in col.dictionary.values
            ),
            dtype=np.int64,
            count=len(col.dictionary.values),
        )
        h = jnp.take(jnp.asarray(table), jnp.asarray(d, jnp.int32), mode="clip")
    elif jnp.issubdtype(d.dtype, jnp.floating):
        # No float bitcasts (TPU x64-rewrite can't lower them) and no frexp
        # (it lowers THROUGH a bitcast): decompose via exp2/log2 instead.
        # The rounding at power-of-two boundaries is deterministic per value,
        # which is all a hash needs.  -0.0 collapses to 0.0, NaN to 0.
        f = d + 0.0
        f = jnp.where(jnp.isnan(f), jnp.float64(0.0), f)
        a = jnp.abs(f)
        expo = jnp.where(
            a > 0.0, jnp.floor(jnp.log2(jnp.where(a > 0.0, a, 1.0))), 0.0
        )
        expo = jnp.clip(expo, -1074.0, 1023.0)
        mant = jnp.where(a > 0.0, a * jnp.exp2(-expo), 0.0)  # in [1, 2)
        h = (
            (mant * (1 << 52)).astype(jnp.int64)
            ^ (expo.astype(jnp.int64) << 1)
            ^ jnp.where(f < 0.0, jnp.int64(1) << 62, jnp.int64(0))
        )
    else:
        h = d.astype(jnp.int64)
    # splitmix64 finalizer (python ints wrap via uint64 numpy constants)
    u = h.astype(jnp.uint64)
    u = (u ^ (u >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    u = (u ^ (u >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    u = u ^ (u >> np.uint64(31))
    return u


def _hll_registers(col: Column, valid) -> jnp.ndarray:
    """[HLL_M] int32 register vector over the valid rows of one column."""
    u = _hll_hash(col)
    bucket = (u >> np.uint64(64 - HLL_P)).astype(jnp.int64)
    rest = (u << np.uint64(HLL_P)) | np.uint64(1)  # sentinel stops rank at max
    # rank = leading zeros of `rest` + 1, via a branchless integer
    # bit-length cascade (pure shifts/compares — nothing the TPU
    # x64-rewrite can't lower, unlike frexp/bitcast)
    x = rest
    bitlen = jnp.zeros(rest.shape, jnp.int32)
    for s in (32, 16, 8, 4, 2, 1):
        y = x >> np.uint64(s)
        gt = y > 0
        bitlen = jnp.where(gt, bitlen + s, bitlen)
        x = jnp.where(gt, y, x)
    bitlen = bitlen + (x > 0).astype(jnp.int32)
    rank = 64 - bitlen + 1
    bucket = jnp.where(valid, bucket, HLL_M)
    return jax.ops.segment_max(
        jnp.where(valid, rank, 0), bucket, HLL_M + 1
    )[:HLL_M].astype(jnp.int32)


def _hll_estimate(registers) -> jnp.ndarray:
    """Registers [..., M] -> BIGINT cardinality (HLL raw estimator + the
    small-range linear-counting correction), vectorized over leading axes."""
    m = float(HLL_M)
    alpha = 0.7213 / (1.0 + 1.079 / m)
    r = jnp.maximum(registers.astype(jnp.float64), 0.0)
    z = jnp.sum(jnp.power(2.0, -r), axis=-1)
    raw = alpha * m * m / z
    v = jnp.sum(registers <= 0, axis=-1)
    linear = m * jnp.log(m / jnp.maximum(v, 1).astype(jnp.float64))
    est = jnp.where((raw <= 2.5 * m) & (v > 0), linear, raw)
    return jnp.round(est).astype(jnp.int64)


# primitive states per SQL aggregate (state kinds: sum/count/min/max/any)
def _primitives(spec: AggSpec):
    if spec.name == "approx_distinct":
        return [("hll", spec.arg)]
    if spec.name == "approx_percentile":
        # log-bucket quantile sketch (reference: qdigest states), merged by
        # elementwise count addition
        return [("qdigest", spec.arg)]
    if spec.name == "count_star":
        return [("count_star", None)]
    if spec.name == "count":
        return [("count", spec.arg)]
    if spec.name in ("sum", "avg"):
        return [("sum", spec.arg), ("count", spec.arg)]
    if spec.name in ("min", "bool_and"):
        return [("min", spec.arg), ("count", spec.arg)]
    if spec.name in ("max", "bool_or"):
        return [("max", spec.arg), ("count", spec.arg)]
    if spec.name == "any_value":
        return [("any", spec.arg), ("count", spec.arg)]
    if spec.name in MOMENT:
        # reference: operator/aggregation VarianceState (count/mean/m2 as
        # merged moments; here the raw-sum formulation merges by addition)
        return [("sum_f", spec.arg), ("sumsq", spec.arg), ("count", spec.arg)]
    if spec.name == "checksum":
        # order-independent wrapping sum of per-row value hashes
        # (reference: operator/aggregation/ChecksumAggregationFunction —
        # xor/sum of XXH64; ours sums 64-bit hashes, same contract: equal
        # multisets give equal checksums, mergeable by addition).  NULL rows
        # contribute a fixed prime (the reference's PRIME64), so NULL
        # placement changes the checksum and all-NULL input is non-null.
        return [("checksum", spec.arg), ("count_star", None)]
    if spec.name in BIVARIATE:
        # reference: operator/aggregation CovarianceState/CorrelationState —
        # raw-sum formulation, merged by addition; rows with EITHER side
        # null are skipped entirely (pairwise validity)
        return [
            ("bi_sum_1", spec.arg), ("bi_sum_2", spec.arg2),
            ("bi_sumsq_1", spec.arg), ("bi_sumsq_2", spec.arg2),
            ("bi_sum_12", spec.arg), ("bi_count", spec.arg),
        ]
    raise NotImplementedError(f"aggregate: {spec.name}")


def _state_types(spec: AggSpec, input_types) -> list[T.Type]:
    out = []
    for kind, arg in _primitives(spec):
        if kind == "hll":
            out.append(T.ArrayType(T.INTEGER))
        elif kind == "qdigest":
            out.append(T.ArrayType(T.BIGINT))
        elif kind in ("count", "count_star"):
            out.append(T.BIGINT)
        elif kind == "checksum":
            out.append(T.BIGINT)
        elif kind in ("sum_f", "sumsq")or kind.startswith("bi_sum"):
            out.append(T.DOUBLE)
        elif kind == "bi_count":
            out.append(T.BIGINT)
        elif kind == "sum":
            t = input_types[arg]
            if isinstance(t, T.DecimalType):
                # reference: DecimalSumAggregation — Int128 state, exact
                out.append(T.DecimalType(38, t.scale))
            elif t.name in ("double", "real"):
                out.append(T.DOUBLE)
            else:
                out.append(T.BIGINT)
        else:
            out.append(input_types[arg])
    return out


def _merge_primitives(spec: AggSpec):
    """How each state column merges in FINAL mode (state kind per column)."""
    prims = _primitives(spec)
    merged = []
    for kind, _ in prims:
        # counts and moment sums are already-reduced values: merge by adding;
        # HLL registers merge by elementwise max
        if kind in ("hll", "qdigest"):
            merged.append(kind)
        else:
            merged.append(
                "sum"
                if kind in ("count", "count_star", "sum_f", "sumsq", "checksum")
                or kind.startswith("bi_")
                else kind
            )
    return merged


def _reduce128(d, gid, nseg: int, kind: str, valid):
    """min/max/any over long-decimal limb planes -> [nseg, 2]."""
    from trino_tpu.types import int128 as i128

    if kind in ("min", "max"):
        h, l = i128.segment_minmax128(
            jnp.asarray(d[:, 0], jnp.int64),
            jnp.asarray(d[:, 1], jnp.int64),
            gid,
            nseg,
            valid,
            kind == "max",
        )
        return jnp.stack([h, l], axis=-1)
    if kind == "any":
        n = d.shape[0]
        idx = jnp.where(valid, jnp.arange(n, dtype=jnp.int64), n)
        first = jax.ops.segment_min(idx, gid, nseg)
        return jnp.take(d, jnp.clip(first, 0, n - 1), axis=0, mode="clip")
    raise NotImplementedError(f"long decimal {kind}")


def _note_fastpath(path: str) -> None:
    """Record the trace-time decimal-sum path choice (proven |
    runtime_check | limb).  Called while a kernel TRACES — the choice is
    static per compiled program, so warm replays add nothing and a warm
    run's zero runtime_check delta is a real guarantee (gated by
    tools/compare_bench.py over the bench.py --mesh Q1 section)."""
    from trino_tpu.telemetry.metrics import decimal_fastpath_counter

    decimal_fastpath_counter().labels(path).inc()


def _sum128(
    d, gid, nseg: int, valid, in_precision: int = None, sum_bound: int = None
):
    """Exact i128 segmented sum -> [nseg, 2] limb planes.  Input is either a
    short scaled-i64 column (1-D, widened) or long planes ([n, 2]).

    Fast paths, strongest proof first:

      * `sum_bound` — a range-certificate license (verify.numeric
        sum_certificate): every partial sum of every subset of contributing
        rows is statically bounded by |s| <= sum_bound < 2**63, from
        per-column generator stats / literal bounds x a sound total-row
        bound.  ONE i64 segment_sum is provably exact: values individually
        fit i64 (|v| <= sum_bound), so the high limb is pure sign
        extension and never needs summing.
      * declared-precision proof — 10**in_precision * rows < 2**63 (static
        per trace): the type's range contract alone bounds the batch.
      * otherwise a fused runtime fits probe picks narrow/wide per batch
        under lax.cond (exact either way, but the probe and the compiled
        wide branch are the cost the certificates exist to delete)."""
    from trino_tpu.types import int128 as i128

    rows = d.shape[0]
    #: per-row magnitude under which `rows` addends provably sum inside i64
    thr = ((1 << 63) - 1) // max(rows, 1)
    licensed = sum_bound is not None and sum_bound < (1 << 63) - 1
    if d.ndim == 2:
        h = jnp.asarray(d[:, 0], jnp.int64)
        l = jnp.asarray(d[:, 1], jnp.int64)
        if valid is not None:
            h = jnp.where(valid, h, 0)
            l = jnp.where(valid, l, 0)
        if licensed or (
            in_precision is not None
            and (10**in_precision) * rows < (1 << 63)
        ):
            # STATIC narrow proof for limb-plane inputs (the CPU fallback
            # of the one-hot matmul path): |v| is bounded inside i64 by the
            # range certificate or by 10**p — the high limb is pure sign
            # extension by that bound — and every partial sum provably
            # stays inside i64, so ONE i64 segment sum is exact with no
            # runtime fits scan and no lax.cond (a widened-but-narrow
            # column never pays the limb-plane cost).
            _note_fastpath("proven")
            return jnp.stack(
                i128.widen64(jax.ops.segment_sum(l, gid, nseg)), axis=-1
            )
        # Runtime-adaptive narrow path (the common TPC-H shape: a product
        # typed decimal(25+) whose actual values are ~10 digits).  One cheap
        # FUSED pass proves the batch's values are i64 (high limb == sign
        # extension) and small enough that `rows` of them can't overflow an
        # i64 accumulator; lax.cond then runs a single segment sum instead
        # of the 3-4 chunk-plane sums.  Exact either way — the check reads
        # the data, not the (over-wide) declared precision.  The per-row
        # conjunction folds the three reductions the old form paid
        # (all/max/min) into one elementwise pass + one all-reduce.
        _note_fastpath("runtime_check")
        fits = jnp.all(
            jnp.logical_and(
                h == (l >> 63),
                jnp.logical_and(l < thr, l > -thr),
            )
        )
        hi_direct = (
            in_precision is not None
            and ((10**in_precision >> 64) + 1) * rows < (1 << 62)
        )

        def _fast(_):
            return i128.widen64(jax.ops.segment_sum(l, gid, nseg))

        def _wide(_):
            return i128.segment_sum128(
                h, l, gid, nseg, valid=None, hi_direct=hi_direct
            )

        h, l = jax.lax.cond(fits, _fast, _wide, None)
    else:
        d = jnp.asarray(d, jnp.int64)
        if valid is not None:
            d = jnp.where(valid, d, 0)
        if licensed or (
            in_precision is not None
            and (10**in_precision) * rows < (1 << 63)
        ):
            _note_fastpath("proven")
            red = jax.ops.segment_sum(d, gid, nseg)
            h, l = i128.widen64(red)
        else:
            _note_fastpath("runtime_check")
            fits = jnp.logical_and(jnp.max(d) < thr, jnp.min(d) > -thr)

            def _fast(_):
                return i128.widen64(jax.ops.segment_sum(d, gid, nseg))

            def _wide(_):
                return i128.sum128_widened(d, gid, nseg, valid=None)

            h, l = jax.lax.cond(fits, _fast, _wide, None)
    return jnp.stack([h, l], axis=-1)


def _finalize(spec: AggSpec, states: list[Column]) -> Column:
    """Combine state columns into the SQL result column."""
    name = spec.name
    if name == "approx_distinct":
        return Column(_hll_estimate(states[0].data), T.BIGINT, None)
    if name == "approx_percentile":
        from trino_tpu.ops import qdigest as qd

        p = float(spec.param if spec.param is not None else 0.5)
        counts = states[0].data
        if counts.ndim == 2:
            counts = counts[0]
        val, total = qd.estimate(counts, p)
        out_t = spec.out_type
        if isinstance(out_t, T.DecimalType):
            if out_t.is_long:
                # float -> limb planes (values can exceed i64; same split
                # as the double->long-decimal cast)
                from trino_tpu.types.int128 import TWO64

                r = jnp.round(val * out_t.scale_factor)
                h = jnp.floor(r / float(TWO64)).astype(jnp.int64)
                lf = r - h.astype(jnp.float64) * float(TWO64)
                l = jnp.where(
                    lf >= float(1 << 63), lf - float(TWO64), lf
                ).astype(jnp.int64)
                return Column(
                    jnp.stack([h, l], axis=-1)[None, :],
                    out_t,
                    (total > 0)[None],
                )
            scaled = jnp.round(val * out_t.scale_factor).astype(jnp.int64)
            return Column(scaled[None], out_t, (total > 0)[None])
        return Column(
            val.astype(out_t.np_dtype)[None], out_t, (total > 0)[None]
        )
    if name in ("count", "count_star"):
        return Column(states[0].data, T.BIGINT, None)
    if name == "checksum":
        return Column(states[0].data, T.BIGINT, states[1].data > 0)
    if name in BIVARIATE:
        s1, s2 = states[0].data, states[1].data
        s11, s22 = states[2].data, states[3].data
        s12, cnt = states[4].data, states[5].data
        n = cnt.astype(jnp.float64)
        nn = jnp.maximum(n, 1.0)
        # raw-sum forms (reference: CovarianceState.getCovariance etc)
        co_m = s12 - s1 * s2 / nn  # n * covar_pop
        v1_m = jnp.maximum(s11 - s1 * s1 / nn, 0.0)
        v2_m = jnp.maximum(s22 - s2 * s2 / nn, 0.0)
        if name == "covar_pop":
            return Column(co_m / nn, T.DOUBLE, cnt > 0)
        if name == "covar_samp":
            return Column(co_m / jnp.maximum(n - 1.0, 1.0), T.DOUBLE, cnt > 1)
        if name == "corr":
            denom = jnp.sqrt(v1_m * v2_m)
            ok = jnp.logical_and(cnt > 1, denom > 0)
            return Column(co_m / jnp.where(ok, denom, 1.0), T.DOUBLE, ok)
        if name == "regr_slope":
            ok = jnp.logical_and(cnt > 1, v2_m > 0)
            return Column(co_m / jnp.where(ok, v2_m, 1.0), T.DOUBLE, ok)
        # regr_intercept = (sum_y - slope * sum_x) / n
        ok = jnp.logical_and(cnt > 1, v2_m > 0)
        slope = co_m / jnp.where(ok, v2_m, 1.0)
        return Column((s1 - slope * s2) / nn, T.DOUBLE, ok)
    if name in MOMENT:
        s, sq, cnt = states[0].data, states[1].data, states[2].data
        n = cnt.astype(jnp.float64)
        m2 = sq - jnp.where(cnt > 0, s * s / jnp.maximum(n, 1.0), 0.0)
        m2 = jnp.maximum(m2, 0.0)  # guard tiny negative rounding residue
        if name in ("var_pop", "stddev_pop"):
            var = m2 / jnp.maximum(n, 1.0)
            valid = cnt > 0
        else:
            var = m2 / jnp.maximum(n - 1.0, 1.0)
            valid = cnt > 1
        out = jnp.sqrt(var) if name.startswith("stddev") else var
        return Column(out, T.DOUBLE, valid)
    value, cnt = states[0], states[1]
    nonempty = cnt.data > 0
    valid = nonempty
    if name == "avg":
        if isinstance(spec.out_type, T.DecimalType) and value.data.ndim == 2:
            # Int128 sum state / count (reference: DecimalAverageAggregation,
            # divide via the schoolbook limb division in types/int128) —
            # count is data-dependent, so divide limb-wise by folding the
            # divisor in via float seeding is not exact; instead use the
            # exact path: q = divmod by count done in two 63-bit halves.
            from trino_tpu.types import int128 as i128

            h = value.data[:, 0]
            l = value.data[:, 1]
            den = jnp.where(nonempty, cnt.data, 1)
            qh, ql, r = i128.divmod128_by_vec(h, l, den)
            half = jnp.where(2 * jnp.abs(r) >= den, 1, 0)
            neg = h < 0
            bump = jnp.where(neg, -half, half)
            qh2, ql2 = i128.add128(qh, ql, bump >> 63, bump)
            if spec.out_type.is_long:
                data = jnp.stack([qh2, ql2], axis=-1)
            else:
                data = ql2  # avg of short input fits the short result
        elif isinstance(spec.out_type, T.DecimalType):
            num = value.data
            den = jnp.where(nonempty, cnt.data, 1)
            sign = jnp.sign(num)
            q = jnp.abs(num) // den
            r = jnp.abs(num) - q * den
            data = sign * (q + jnp.where(2 * r >= den, 1, 0))
        else:
            data = value.data.astype(jnp.float64) / jnp.where(nonempty, cnt.data, 1)
        return Column(data.astype(spec.out_type.np_dtype), spec.out_type, valid)
    # sum/min/max/any_value/bool_*
    data = value.data
    if data.ndim == 2 and isinstance(spec.out_type, T.DecimalType):
        if not spec.out_type.is_long:
            # caller declared a short result: values are asserted to fit,
            # so the low limb carries them exactly
            data = data[:, 1]
        elif (
            isinstance(value.type, T.DecimalType)
            and value.type.scale != spec.out_type.scale
        ):
            from trino_tpu.types import int128 as i128

            h, l = i128.rescale128(
                data[:, 0], data[:, 1], value.type.scale, spec.out_type.scale
            )
            data = jnp.stack([h, l], axis=-1)
    return Column(
        data.astype(spec.out_type.np_dtype),
        spec.out_type,
        valid,
        states[0].dictionary,
    )


def _logical_double(d, t: T.Type):
    """Raw device values -> logical float64 (decimal cents get descaled)."""
    out = d.astype(jnp.float64)
    if isinstance(t, T.DecimalType) and t.scale:
        out = out / (10.0 ** t.scale)
    return out


def _masked_reduce(data, valid, kind: str):
    """Whole-column null-skipping reduction to a scalar (global aggregation)."""
    from trino_tpu.ops.common import _max_sentinel, _min_sentinel

    if kind in ("count", "count_star"):
        return jnp.sum(valid, dtype=jnp.int64)
    if kind == "sum":
        return jnp.sum(jnp.where(valid, data, 0))
    if kind == "min":
        return jnp.min(jnp.where(valid, data, _max_sentinel(data.dtype)))
    if kind == "max":
        return jnp.max(jnp.where(valid, data, _min_sentinel(data.dtype)))
    if kind == "any":
        idx = jnp.argmax(valid)
        return data[idx]
    raise ValueError(kind)


def _pad_device(batch: Batch, cap: int) -> Batch:
    n = batch.capacity
    if n == cap:
        return batch
    pad = cap - n
    cols = []
    for c in batch.columns:
        if c.data.ndim > 1:  # array/map columns: pad rows, keep width
            data = jnp.concatenate(
                [c.data, jnp.zeros((pad, c.data.shape[1]), dtype=c.data.dtype)]
            )
        else:
            data = jnp.concatenate([c.data, jnp.zeros(pad, dtype=c.data.dtype)])
        valid = (
            None
            if c.valid is None
            else jnp.concatenate([c.valid, jnp.zeros(pad, dtype=bool)])
        )
        lengths = (
            None
            if c.lengths is None
            else jnp.concatenate([c.lengths, jnp.zeros(pad, jnp.int32)])
        )
        cols.append(Column(data, c.type, valid, c.dictionary, lengths))
    mask = jnp.concatenate([batch.mask(), jnp.zeros(pad, dtype=bool)])
    return Batch(cols, mask)


class MarkDistinctOperator:
    """Appends a boolean column that is True on the first live occurrence of
    each distinct key combination (reference: operator/MarkDistinctOperator
    .java + MarkDistinctHash).  TPU substitution: multi-key sort + key-change
    flags scattered back to row order — one static-shape program, no hash
    table."""

    def __init__(self, key_channels: Sequence[int]):
        self.key_channels = list(key_channels)
        self._acc: list[Batch] = []
        key = ("mark_distinct", tuple(self.key_channels))
        if key not in _STEP_CACHE:
            _STEP_CACHE[key] = jax.jit(self._mark_step)
        self._step = _STEP_CACHE[key]

    def _mark_step(self, batch: Batch) -> Batch:
        cap = batch.capacity
        perm = multi_key_sort_perm(
            batch, [SortKey(ch) for ch in self.key_channels]
        )
        _, _, new_group = group_ids_from_sorted(batch, perm, self.key_channels)
        pos = jnp.arange(cap, dtype=jnp.int64)
        inv = jnp.zeros(cap, dtype=jnp.int64).at[perm].set(pos)
        mark = jnp.take(new_group, inv, mode="clip")
        cols = list(batch.columns) + [Column(mark, T.BOOLEAN, None)]
        return Batch(cols, batch.row_mask)

    def process(self, stream):
        for b in stream:
            self._acc.append(b)
        if not self._acc:
            return
        big = self._acc[0] if len(self._acc) == 1 else concat_batches(self._acc)
        big = _pad_device(big, next_pow2(big.capacity, floor=1))
        yield self._step(big)


class AggregationOperator:
    def __init__(
        self,
        group_channels: Sequence[int],
        aggregates: Sequence[AggSpec],
        input_types: Sequence[T.Type],
        mode: str = "single",  # single | partial | final | merge
        streaming: bool = False,
        fold_every: Optional[int] = None,
        memory_ctx=None,
        use_pallas: bool = False,
        pre_step=None,
        pre_key=None,
        pre_jit=None,
    ):
        # merge: states in -> states out (used to combine partial outputs)
        assert mode in ("single", "partial", "final", "merge")
        if group_channels and any(
            s.name in ("approx_distinct", "approx_percentile")
            for s in aggregates
        ):
            # grouped sketches would need [groups, HLL_M] register state;
            # the planner rewrites grouped approx_distinct to exact DISTINCT
            # count instead, so this is unreachable from SQL
            raise NotImplementedError("grouped approx_distinct")
        self.group_channels = list(group_channels)
        self.aggregates = list(aggregates)
        self.input_types = list(input_types)
        self.mode = mode
        self.streaming = streaming
        self.fold_every = fold_every if fold_every is not None else self.FOLD_EVERY
        self.memory_ctx = memory_ctx
        #: opt-in Pallas MXU kernel for eligible direct-path aggregations
        #: (ops/pallas_agg.py); float32 accumulation, so restricted to
        #: DOUBLE/REAL sums + counts where f32 matmul precision is acceptable
        self.use_pallas = use_pallas
        #: fused upstream projection: applied INSIDE the jitted reduce step
        #: so projection outputs (e.g. decimal products) never round-trip
        #: through memory between the project and the partial aggregation
        self._pre = pre_step
        self._pre_key = pre_key
        #: jitted standalone projection (for paths that must materialize the
        #: projected batch OUTSIDE the fused reduce, e.g. the positional
        #: group path whose eligibility reads concrete key stats)
        self._pre_jit = pre_jit
        self._acc: list[Batch] = []
        self._per_batch: Optional["AggregationOperator"] = None
        self._unfused_twin: Optional["AggregationOperator"] = None
        key = (
            tuple(self.group_channels),
            tuple(self.aggregates),
            tuple(t.name for t in self.input_types),
            mode,
            use_pallas,
            pre_key,
        )
        cached = _STEP_CACHE.get(key)
        if cached is None:
            cached = jax.jit(self._reduce_step, static_argnames=("out_cap",))
            _STEP_CACHE[key] = cached
        self._step = cached

    # -- the jitted kernel ---------------------------------------------------

    #: group-domain cap for the sort-free direct path (positional segments)
    DIRECT_GROUP_LIMIT = 4096

    #: group-domain cap for the range-positional path (min/max-offset mixed
    #: radix).  Segment ops at 16M slots are ~0.2s-class; beyond that the
    #: sort path (or, later, aggregation waves) takes over.
    POSITIONAL_LIMIT = 1 << 24

    def _direct_group_info(self, batch: Batch, src_channels=None):
        """(sizes, prod) when every group key is a small-domain code column
        (dictionary or boolean) — the BigintGroupByHash analog: group id is
        the mixed-radix code index, no sort needed (reference:
        operator/BigintGroupByHash.java's dense small-domain fast path).

        `src_channels`: when the input projection is FUSED into this
        operator, the group keys' pre-projection channels in the raw batch
        (group projections are identity InputRefs in that case)."""
        sizes = []
        chans = src_channels if src_channels is not None else self.group_channels
        for ch in chans:
            c = batch.columns[ch]
            if c.dictionary is not None:
                n = len(c.dictionary.values)
            elif c.type is T.BOOLEAN:
                n = 2
            else:
                return None
            sizes.append(n + 1)  # one extra slot for NULL
        prod = 1
        for s in sizes:
            prod *= s
        if not 0 < prod <= self.DIRECT_GROUP_LIMIT:
            return None
        return sizes, prod

    def _direct_reduce(self, batch: Batch, sizes, prod: int) -> Batch:
        gch = self.group_channels
        cap = batch.capacity
        live = batch.mask()
        gid = jnp.zeros(cap, dtype=jnp.int64)
        for ch, size in zip(gch, sizes):
            c = batch.columns[ch]
            code = c.data.astype(jnp.int64)
            if c.valid is not None:
                code = jnp.where(c.valid, code, size - 1)
            gid = gid * size + jnp.clip(code, 0, size - 1)
        gid = jnp.where(live, gid, prod)
        nseg = prod + 1
        occupancy = jax.ops.segment_sum(live.astype(jnp.int64), gid, nseg)[:prod]
        out_live = occupancy > 0
        # decode positional slot -> group key codes
        idx = jnp.arange(prod, dtype=jnp.int64)
        divs = []
        d = 1
        for size in reversed(sizes):
            divs.append(d)
            d *= size
        divs.reverse()
        cols: list[Column] = []
        for (ch, size), div in zip(zip(gch, sizes), divs):
            c = batch.columns[ch]
            code = (idx // div) % size
            valid = None
            if c.valid is not None:
                valid = code < (size - 1)
            cols.append(
                Column(code.astype(c.data.dtype), c.type, valid, c.dictionary)
            )
        pallas_sums = None
        if self.use_pallas and self.mode == "single":
            pallas_sums = self._pallas_direct_sums(batch, live, gid, prod)
        if pallas_sums is not None:
            cols.extend(pallas_sums)
            return Batch(cols, out_live)
        matmul_states = self._matmul_direct_sums(batch, live, gid, prod)
        if matmul_states is not None:
            for spec, state_cols in zip(self.aggregates, matmul_states):
                if self.mode == "partial":
                    cols.extend(state_cols)
                else:
                    cols.append(_finalize(spec, state_cols))
            return Batch(cols, out_live)
        perm = jnp.arange(cap, dtype=jnp.int64)
        for spec in self.aggregates:
            state_cols = self._reduce_one(batch, spec, perm, live, gid, nseg, prod)
            if self.mode in ("partial", "merge"):
                cols.extend(state_cols)
            else:
                cols.append(_finalize(spec, state_cols))
        return Batch(cols, out_live)

    #: one-hot matmul path bounds: groups (one-hot width) and rows (chunk
    #: sums must stay exact in f64: 2**32 chunks * 2**21 rows = 2**53)
    MATMUL_GROUP_LIMIT = 32
    MATMUL_ROW_LIMIT = 1 << 21

    def _matmul_direct_sums(self, batch: Batch, live, gid, prod: int):
        """EXACT one-hot matmul aggregation (default on the direct path):
        every sum/count reduces in ONE dot — [cap, G] one-hot against a
        [cap, K] plane matrix — instead of K segmented scatter-adds.

        This is the MXU-native formulation (TPU: systolic-array matmul; CPU:
        a single GEMM) and it is exact: integer inputs split into 32-bit
        chunk planes, each chunk sum < 2**32 * 2**21 = 2**53 fits the f64
        mantissa, and the chunks recombine into i64/i128 with carries.
        Returns per-spec primitive STATE columns (same layout as
        _reduce_one) or None when ineligible.

        Reference role: the grouped-sum loop of operator/aggregation/
        DecimalSumAggregation + GroupedAccumulator, reshaped for hardware
        that prefers one big matmul over row-at-a-time accumulation."""
        cap = batch.capacity
        if prod > self.MATMUL_GROUP_LIMIT or cap > self.MATMUL_ROW_LIMIT:
            return None
        if self.mode not in ("single", "partial"):
            return None
        if not self.aggregates:
            return None  # pure dedupe (e.g. DISTINCT pre-aggregation)
        # the one-hot GEMM is the accelerator formulation; CPU's scalar
        # pipelines prefer the segmented scatter-adds
        import jax as _j

        if _j.default_backend() == "cpu" and not getattr(
            self, "force_matmul", False
        ):
            return None
        for spec in self.aggregates:
            if spec.name not in ("sum", "avg", "count", "count_star"):
                return None
            if spec.name in ("sum", "avg"):
                t = self.input_types[spec.arg]
                if not (
                    isinstance(t, T.DecimalType)
                    or t.name
                    in ("tinyint", "smallint", "integer", "bigint", "double", "real")
                ):
                    return None

        m32 = jnp.int64(0xFFFFFFFF)
        planes = []  # f64 [cap] arrays
        plan = []  # per spec: list of (prim_kind, chunk_layout, plane_idx..)

        def _valid_plane(col):
            v = live
            if col is not None and col.valid is not None:
                v = jnp.logical_and(v, col.valid)
            return v

        for spec in self.aggregates:
            prims = []
            if spec.name == "count_star":
                prims.append(("count", "count", (len(planes),)))
                planes.append(live.astype(jnp.float64))
            elif spec.name == "count":
                col = batch.columns[spec.arg]
                v = _valid_plane(col)
                prims.append(("count", "count", (len(planes),)))
                planes.append(v.astype(jnp.float64))
            else:  # sum / avg -> (sum, count) primitive states
                col = batch.columns[spec.arg]
                v = _valid_plane(col)
                vf = v.astype(jnp.float64)
                t = self.input_types[spec.arg]
                st = _state_types(spec, self.input_types)[0]
                if t.name in ("double", "real"):
                    d = jnp.where(v, col.data.astype(jnp.float64), 0.0)
                    prims.append(("sum", "f64", (len(planes),)))
                    planes.append(d)
                elif col.data.ndim == 2:  # long decimal input
                    h = jnp.where(v, col.data[:, 0], 0)
                    l = jnp.where(v, col.data[:, 1], 0)
                    i0 = len(planes)
                    planes.extend(
                        [
                            (l & m32).astype(jnp.float64),
                            ((l >> 32) & m32).astype(jnp.float64),
                            (h & m32).astype(jnp.float64),
                            (h >> 32).astype(jnp.float64),
                        ]
                    )
                    prims.append(("sum", "i128", (i0, i0 + 1, i0 + 2, i0 + 3)))
                else:
                    d = jnp.where(v, jnp.asarray(col.data, jnp.int64), 0)
                    i0 = len(planes)
                    planes.extend(
                        [
                            (d & m32).astype(jnp.float64),
                            (d >> 32).astype(jnp.float64),  # signed top chunk
                        ]
                    )
                    kind = (
                        "i128"
                        if isinstance(st, T.DecimalType) and st.is_long
                        else "i64"
                    )
                    prims.append(("sum", kind + "_2", (i0, i0 + 1)))
                prims.append(("count", "count", (len(planes),)))
                planes.append(vf)
            plan.append((spec, prims))

        onehot = jnp.logical_and(
            gid[:, None] == jnp.arange(prod, dtype=gid.dtype)[None, :],
            live[:, None],
        ).astype(jnp.float64)
        V = jnp.stack(planes, axis=1)  # [cap, K]
        S = jnp.einsum("ng,nk->gk", onehot, V)  # ONE matmul: [G, K]

        from trino_tpu.types import int128 as i128

        out_states: list = []
        for spec, prims in plan:
            state_cols = []
            sts = _state_types(spec, self.input_types)
            for (kind, layout, idx), st in zip(prims, sts):
                if layout == "count":
                    state_cols.append(
                        Column(S[:, idx[0]].astype(jnp.int64), T.BIGINT)
                    )
                elif layout == "f64":
                    state_cols.append(Column(S[:, idx[0]], st))
                elif layout == "i64_2":
                    s0 = S[:, idx[0]].astype(jnp.int64)
                    s1 = S[:, idx[1]].astype(jnp.int64)
                    state_cols.append(Column((s1 << 32) + s0, st))
                elif layout == "i128_2":
                    hi, lo = i128.recombine2(
                        S[:, idx[0]].astype(jnp.int64),
                        S[:, idx[1]].astype(jnp.int64),
                    )
                    state_cols.append(
                        Column(jnp.stack([hi, lo], axis=-1), st)
                    )
                else:  # i128 (4 chunk planes)
                    hi, lo = i128.recombine4(
                        S[:, idx[0]].astype(jnp.int64),
                        S[:, idx[1]].astype(jnp.int64),
                        S[:, idx[2]].astype(jnp.int64),
                        S[:, idx[3]].astype(jnp.int64),
                    )
                    state_cols.append(
                        Column(jnp.stack([hi, lo], axis=-1), st)
                    )
            out_states.append(state_cols)
        return out_states

    def _pallas_direct_sums(self, batch: Batch, live, gid, prod: int):
        """MXU one-hot-matmul fast path (ops/pallas_agg.py) when every
        aggregate is a float sum/avg or a count; returns finalized columns
        or None when ineligible."""
        for spec in self.aggregates:
            if spec.name in ("count_star", "count"):
                continue
            if spec.name in ("sum", "avg") and spec.arg is not None:
                if self.input_types[spec.arg].name in ("double", "real"):
                    continue
            return None
        cap = batch.capacity
        from trino_tpu.ops.pallas_agg import _BLOCK, grouped_sums_pallas

        block = min(_BLOCK, cap)
        # f32 accumulation: counts stay exact only below 2^24 increments, so
        # cap the eligible batch size (beyond it the sort-based path runs)
        if cap % block != 0 or prod > 512 or cap > (1 << 24):
            return None

        # value matrix: one column per needed quantity
        mats = []
        plan = []  # (spec, kind, col indices into mats)
        ones = None
        for spec in self.aggregates:
            if spec.name == "count_star":
                if ones is None:
                    ones = len(mats)
                    mats.append(jnp.ones(cap, jnp.float32))
                plan.append((spec, "count", (ones,)))
                continue
            c = batch.columns[spec.arg]
            v = c.valid_mask() if c.valid is not None else None
            data = c.data.astype(jnp.float32)
            if v is not None:
                data = jnp.where(v, data, 0.0)
            cnt_col = len(mats)
            mats.append(
                (v if v is not None else jnp.ones(cap, bool)).astype(jnp.float32)
            )
            if spec.name == "count":
                plan.append((spec, "count", (cnt_col,)))
                continue
            val_col = len(mats)
            mats.append(data)
            plan.append((spec, spec.name, (val_col, cnt_col)))
        values = jnp.stack(mats, axis=1)
        interpret = jax.default_backend() != "tpu"
        sums = grouped_sums_pallas(
            gid.astype(jnp.int32),
            live,
            values,
            n_groups=prod,
            interpret=interpret,
        )  # [prod, len(mats)]
        out = []
        for spec, kind, idx in plan:
            if kind == "count":
                out.append(
                    Column(sums[:, idx[0]].astype(jnp.int64), T.BIGINT)
                )
            elif kind == "sum":
                n = sums[:, idx[1]]
                out.append(
                    Column(
                        sums[:, idx[0]].astype(jnp.float64),
                        spec.out_type,
                        n > 0,
                    )
                )
            else:  # avg
                n = sums[:, idx[1]]
                out.append(
                    Column(
                        (sums[:, idx[0]] / jnp.maximum(n, 1.0)).astype(
                            jnp.float64
                        ),
                        spec.out_type,
                        n > 0,
                    )
                )
        return out

    # -- range-positional (sort-free) path -----------------------------------

    def _positional_static_eligible(self, batch: Batch) -> bool:
        """Static (type-level) eligibility for the range-positional path:
        every group key is an int-family scalar (ints, dates, decimals,
        dictionary codes, bools) — the generalized BigintGroupByHash dense
        path (reference: operator/BigintGroupByHash.java), with the dense
        domain discovered from data min/max instead of assumed."""
        if not self.group_channels:
            return False
        if any(s.name in HOLISTIC_AGGS for s in self.aggregates):
            # holistic aggregates need the sorted numbering (percentile,
            # collect) or joint key/value selection (min_by/max_by)
            return False
        for ch in self.group_channels:
            col = batch.columns[ch]
            if col.lengths is not None:
                return False
            if col.data.ndim > 1:
                return False  # long-decimal limb planes: sort path handles
            dt = col.data.dtype
            if not (jnp.issubdtype(dt, jnp.integer) or dt == jnp.bool_):
                return False
        return True

    def _key_stats(self, batch: Batch):
        """Jitted per-key (min, max) over live, non-null key values."""
        key = ("keystats", tuple(self.group_channels))
        step = _STEP_CACHE.get(key)
        if step is None:
            chans = tuple(self.group_channels)

            def stats(batch: Batch):
                live = batch.mask()
                mins, maxs = [], []
                for ch in chans:
                    col = batch.columns[ch]
                    d = col.data.astype(jnp.int64)
                    v = live
                    if col.valid is not None:
                        v = jnp.logical_and(v, col.valid)
                    big = jnp.iinfo(jnp.int64).max
                    mins.append(jnp.min(jnp.where(v, d, big)))
                    maxs.append(jnp.max(jnp.where(v, d, -big)))
                return jnp.stack(mins), jnp.stack(maxs)

            step = jax.jit(stats)
            _STEP_CACHE[key] = step
        return step(batch)

    def _positional_try(self, batch: Batch) -> Optional[Batch]:
        """Sort-free grouped reduction when the key domain is dense enough:
        gid = mixed-radix positional code from per-key (min, size), group
        values decoded back from the slot index.  One host sync for the key
        stats; sizes/mins stay traced so data changes do not retrace."""
        import numpy as np

        if not self._positional_static_eligible(batch):
            return None
        mins_d, maxs_d = self._key_stats(batch)
        mins = np.asarray(jax.device_get(mins_d))  # lint: allow(host-transfer)
        maxs = np.asarray(jax.device_get(maxs_d))  # lint: allow(host-transfer)
        prod = 1
        sizes = []
        for i, ch in enumerate(self.group_channels):
            nullable = batch.columns[ch].valid is not None
            size = int(maxs[i]) - int(mins[i]) + 1
            if size < 0:
                size = 0  # empty/all-null key: only the null slot remains
            size += 1 if nullable else 0
            if size <= 0:
                return None
            sizes.append(size)
            prod *= size
            if prod > self.POSITIONAL_LIMIT:
                return None
        # a domain much larger than the input wastes O(prod) segment slots
        if prod > max(1 << 16, 8 * batch.capacity):
            return None
        nseg = next_pow2(prod, floor=16)
        key = (
            "range",
            tuple(self.group_channels),
            tuple(self.aggregates),
            tuple(t.name for t in self.input_types),
            self.mode,
        )
        step = _STEP_CACHE.get(key)
        if step is None:
            step = jax.jit(self._range_step, static_argnames=("out_cap",))
            _STEP_CACHE[key] = step
        out = step(
            batch,
            jnp.asarray(mins),
            jnp.asarray(np.asarray(sizes, dtype=np.int64)),
            out_cap=int(nseg),
        )
        # positional output is sparse (occupancy-masked); compact when the
        # live groups are far below the domain so downstream sorts stay small
        ng = out.num_rows_host()
        cc = next_pow2(max(ng, 1), floor=16)
        if cc * 2 <= nseg:
            out = jax.jit(Batch.compact_device, static_argnames=("out_capacity",))(
                out, out_capacity=cc
            )
        return out

    def _range_step(self, batch: Batch, mins, sizes, out_cap: int) -> Batch:
        gch = self.group_channels
        cap = batch.capacity
        live = batch.mask()
        gid = jnp.zeros(cap, dtype=jnp.int64)
        for i, ch in enumerate(gch):
            col = batch.columns[ch]
            d = col.data.astype(jnp.int64)
            size_v = sizes[i] - (1 if col.valid is not None else 0)
            code = jnp.clip(d - mins[i], 0, jnp.maximum(size_v - 1, 0))
            if col.valid is not None:
                code = jnp.where(col.valid, code, size_v)
            gid = gid * sizes[i] + code
        gid = jnp.where(live, gid, out_cap)
        nseg = out_cap + 1
        occupancy = jax.ops.segment_sum(live.astype(jnp.int64), gid, nseg)[:out_cap]
        out_live = occupancy > 0
        # decode slot index -> group key values (traced div/mod chain)
        idx = jnp.arange(out_cap, dtype=jnp.int64)
        sizes_list = [sizes[i] for i in range(len(gch))]
        divs = []
        d = jnp.ones((), dtype=jnp.int64)
        for size in reversed(sizes_list):
            divs.append(d)
            d = d * size
        divs.reverse()
        cols: list[Column] = []
        for i, ch in enumerate(gch):
            col = batch.columns[ch]
            code = (idx // divs[i]) % sizes_list[i]
            valid = None
            if col.valid is not None:
                valid = code < (sizes_list[i] - 1)
            data = (code + mins[i]).astype(col.data.dtype)
            cols.append(Column(data, col.type, valid, col.dictionary))
        perm = jnp.arange(cap, dtype=jnp.int64)
        gid_c = jnp.minimum(gid, out_cap)
        for spec in self.aggregates:
            state_cols = self._reduce_one(batch, spec, perm, live, gid_c, nseg, out_cap)
            if self.mode in ("partial", "merge"):
                cols.extend(state_cols)
            else:
                cols.append(_finalize(spec, state_cols))
        return Batch(cols, out_live)

    def _reduce_full(self, big: Batch) -> Batch:
        """One-shot reduction of a batch: compact away dead slack first
        (join outputs / filtered feeds can be mostly dead), then the
        positional path if the key domain allows, else the sorted step."""
        n = big.num_rows_host()
        cap = next_pow2(max(n, 1), floor=1)
        if cap < big.capacity:
            big = jax.jit(Batch.compact_device, static_argnames=("out_capacity",))(
                big, out_capacity=cap
            )
        else:
            cap = next_pow2(big.capacity, floor=1)
            big = _pad_device(big, cap)
        # collect aggregates (array_agg/map_agg) need a data-dependent padded
        # width: run the step EAGERLY so the width sync is legal
        if any(s.name in COLLECT_AGGS for s in self.aggregates):
            return self._reduce_step(big, out_cap=cap)
        # the in-jit small-domain direct path needs no host sync; prefer it
        # when statically eligible (dict/bool keys).  A fused projection
        # (self._pre) means `big` is RAW input: the positional fallback
        # would inspect pre-projection channels, so skip it — _step applies
        # the projection inside its own trace.
        if (
            self._pre is None
            and self.group_channels
            and self._direct_group_info(big) is None
        ):
            out = self._positional_try(big)
            if out is not None:
                return out
        return self._step(big, out_cap=cap)

    def _reduce_step(self, batch: Batch, out_cap: int) -> Batch:
        if self._pre is not None:
            batch = self._pre(batch)
        gch = self.group_channels
        if not gch:
            return self._global_reduce(batch)
        direct = None
        if not any(
            s.name in HOLISTIC_AGGS
            for s in self.aggregates
        ):
            # holistic group ids must come from the sort-based numbering
            direct = self._direct_group_info(batch)
        if direct is not None:
            return self._direct_reduce(batch, *direct)
        perm = multi_key_sort_perm(batch, [SortKey(ch) for ch in gch])
        gid, ngroups, new_group = group_ids_from_sorted(batch, perm, gch)
        live = jnp.take(batch.mask(), perm, mode="clip")
        gid_c = jnp.minimum(gid, out_cap)
        nseg = out_cap + 1
        out_live = jnp.arange(out_cap, dtype=jnp.int64) < ngroups
        cols: list[Column] = []
        # group key columns: value at each group's first row
        first_idx = jnp.where(new_group, gid_c, out_cap)
        for ch in gch:
            col = batch.columns[ch]
            d = jnp.take(col.data, perm, axis=0, mode="clip")
            if d.ndim > 1:  # long-decimal limb planes: scatter rows
                key_out = (
                    jnp.zeros((nseg,) + d.shape[1:], dtype=col.data.dtype)
                    .at[first_idx]
                    .set(d, mode="drop")[:out_cap]
                )
            else:
                key_out = (
                    jnp.zeros(nseg, dtype=col.data.dtype)
                    .at[first_idx]
                    .set(d, mode="drop")[:out_cap]
                )
            valid = None
            if col.valid is not None:
                v = jnp.take(col.valid, perm, mode="clip")
                valid = (
                    jnp.zeros(nseg, dtype=bool)
                    .at[first_idx]
                    .set(v, mode="drop")[:out_cap]
                )
            cols.append(Column(key_out, col.type, valid, col.dictionary))
        # aggregate states/values
        for spec in self.aggregates:
            if spec.name in HOLISTIC_AGGS:
                if self.mode != "single":
                    raise NotImplementedError(
                        f"{spec.name} requires single-stage aggregation"
                    )
                if spec.name == "percentile":
                    cols.append(self._percentile_one(batch, spec, out_cap))
                elif spec.name == "listagg":
                    cols.append(self._listagg_one(batch, spec, out_cap))
                elif spec.name in ("min_by", "max_by"):
                    cols.append(
                        self._minmax_by_one(
                            batch, spec, perm, live, gid_c, nseg, out_cap
                        )
                    )
                else:
                    cols.append(
                        self._collect_one(batch, spec, perm, live, gid_c, nseg, out_cap)
                    )
                continue
            state_cols = self._reduce_one(
                batch, spec, perm, live, gid_c, nseg, out_cap
            )
            if self.mode in ("partial", "merge"):
                cols.extend(state_cols)
            else:
                cols.append(_finalize(spec, state_cols))
        return Batch(cols, out_live)

    def _collect_one(
        self, batch: Batch, spec: AggSpec, perm, live, gid_c, nseg, out_cap
    ) -> Column:
        """array_agg / map_agg: scatter each group's run into a padded
        rectangular array (reference: operator/aggregation/
        ArrayAggregationFunction + MapAggAggregationFunction group state).

        Runs EAGERLY (outside jit): the padded width K is the max group
        size, a data-dependent shape that costs one host sync.  NULL inputs
        are skipped — the rectangular layout tracks nulls per-array, not
        per-element (documented deviation; the reference keeps them)."""
        import numpy as np

        cap = batch.capacity
        col = batch.columns[spec.arg]
        if (
            spec.name == "array_agg"
            and spec.arg2 is not None
            and spec.param is not None
        ):
            # array_agg(x ORDER BY k): re-sort by (group keys, k) so the
            # scatter positions below follow the requested element order
            # (the _percentile_one re-sort pattern); group numbering is
            # unchanged because the group keys stay most significant
            asc, nf = spec.param
            keys = [SortKey(ch) for ch in self.group_channels] + [
                SortKey(spec.arg2, asc, nf)
            ]
            perm = multi_key_sort_perm(batch, keys)
            live = jnp.take(batch.mask(), perm, mode="clip")
            if self.group_channels:
                gid, _, _ = group_ids_from_sorted(
                    batch, perm, self.group_channels
                )
                gid_c = gid
            else:
                gid_c = jnp.zeros(cap, dtype=jnp.int64)
        d = jnp.take(col.data, perm, axis=0, mode="clip")
        varg = live
        if col.valid is not None:
            varg = jnp.logical_and(varg, jnp.take(col.valid, perm, mode="clip"))
        vcol = None
        dictionary = col.dictionary
        if spec.name == "map_agg":
            vcol = batch.columns[spec.arg2]
            vd = jnp.take(vcol.data, perm, axis=0, mode="clip")
            if vcol.valid is not None:
                varg = jnp.logical_and(
                    varg, jnp.take(vcol.valid, perm, mode="clip")
                )
            if col.dictionary is not None and vcol.dictionary is not None:
                from trino_tpu.columnar.dictionary import union_many

                dictionary, (tk, tv) = union_many(
                    [col.dictionary, vcol.dictionary]
                )
                if tk is not None:
                    d = jnp.take(jnp.asarray(tk), jnp.asarray(d, jnp.int32), mode="clip")
                if tv is not None:
                    vd = jnp.take(jnp.asarray(tv), jnp.asarray(vd, jnp.int32), mode="clip")
            elif vcol.dictionary is not None:
                dictionary = vcol.dictionary
        if jnp.ndim(d) > 1:
            raise NotImplementedError(
                f"{spec.name} over a long-decimal argument "
                "(cast to decimal(18,s) or double first)"
            )
        # within-group rank over kept rows
        pos_in_group, counts = _group_ranks(varg, gid_c, cap, nseg)
        kmax = int(np.asarray(jnp.max(counts[:out_cap])))  # the one host sync  # lint: allow(host-sync-asarray, host-sync-cast)
        k = next_pow2(max(kmax, 1), floor=1)
        scatter_g = jnp.where(varg, gid_c, nseg)  # drop non-kept rows
        scatter_p = jnp.clip(pos_in_group, 0, k - 1)
        lengths = counts[:out_cap].astype(jnp.int32)
        if spec.name == "array_agg":
            et = spec.out_type.element
            out = (
                jnp.zeros((nseg + 1, k), dtype=et.np_dtype)
                .at[scatter_g, scatter_p]
                .set(jnp.asarray(d, et.np_dtype), mode="drop")
            )
            return Column(
                out[:out_cap], spec.out_type, None, dictionary, lengths
            )
        mt = spec.out_type  # MapType: packed [out_cap, 2k]
        dt = mt.np_dtype
        keys = (
            jnp.zeros((nseg + 1, k), dtype=dt)
            .at[scatter_g, scatter_p]
            .set(jnp.asarray(d, dt), mode="drop")
        )
        vals = (
            jnp.zeros((nseg + 1, k), dtype=dt)
            .at[scatter_g, scatter_p]
            .set(jnp.asarray(vd, dt), mode="drop")
        )
        packed = jnp.concatenate([keys[:out_cap], vals[:out_cap]], axis=1)
        return Column(packed, mt, None, dictionary, lengths)

    def _listagg_one(self, batch: Batch, spec: AggSpec, out_cap: int) -> Column:
        """listagg(value, sep) WITHIN GROUP (ORDER BY k) — reference:
        operator/aggregation/listagg/.  Eager: rows sort by
        (group keys, order key) on device; the per-group string join is
        host work by nature (strings live in dictionaries)."""
        import numpy as np

        from trino_tpu.columnar.dictionary import StringDictionary

        gch = self.group_channels
        col = batch.columns[spec.arg]
        if col.dictionary is None:
            raise TypeError("listagg requires a varchar argument")
        sep, asc, nf = (
            spec.param
            if isinstance(spec.param, tuple)
            else (spec.param or "", True, False)
        )
        keys = [SortKey(ch) for ch in gch]
        if spec.arg2 is not None:
            keys.append(SortKey(spec.arg2, ascending=asc, nulls_first=nf))
        perm2 = multi_key_sort_perm(batch, keys)
        if gch:
            gid2, _, _ = group_ids_from_sorted(batch, perm2, gch)
            gid_h = np.asarray(jax.device_get(gid2))  # lint: allow(host-transfer)
        else:
            gid_h = np.zeros(batch.capacity, dtype=np.int64)
        live = jnp.take(batch.mask(), perm2, mode="clip")
        if col.valid is not None:
            live = jnp.logical_and(
                live, jnp.take(col.valid, perm2, mode="clip")
            )
        codes = jnp.take(col.data, perm2, mode="clip")
        live_h = np.asarray(jax.device_get(live))  # lint: allow(host-transfer)
        codes_h = np.asarray(jax.device_get(codes))  # lint: allow(host-transfer)
        sep = str(sep)
        values = col.dictionary.values
        joined = [""] * out_cap
        parts: dict = {}
        for i in np.flatnonzero(live_h):
            g = int(gid_h[i])
            if g < out_cap:
                parts.setdefault(g, []).append(values[int(codes_h[i])])
        valid_out = np.zeros(out_cap, dtype=bool)
        for g, vs in parts.items():
            joined[g] = sep.join(vs)
            valid_out[g] = True
        d = StringDictionary.from_unsorted(joined)
        out_codes = d.encode(joined)
        return Column(
            np.asarray(out_codes, dtype=np.int32),
            spec.out_type,
            valid_out if not valid_out.all() else None,
            d,
        )

    def _minmax_by_n(self, batch: Batch, spec: AggSpec, nseg, out_cap) -> Column:
        """min_by/max_by(value, key, n): the values at each group's n
        extreme keys, as a padded array in key order (reference:
        MinMaxByNAggregation's TypedHeap — a sort-based engine takes the
        first n of the key-sorted run instead).  NULL keys and NULL values
        are skipped (rectangular arrays carry no per-element nulls — the
        array_agg deviation)."""
        n = int(spec.param)
        want_min = spec.name == "min_by"
        cap = batch.capacity
        kcol = batch.columns[spec.arg2]
        vcol = batch.columns[spec.arg]
        keys = [SortKey(ch) for ch in self.group_channels] + [
            SortKey(spec.arg2, want_min)
        ]
        perm = multi_key_sort_perm(batch, keys)
        live = jnp.take(batch.mask(), perm, mode="clip")
        if self.group_channels:
            gid, _, _ = group_ids_from_sorted(batch, perm, self.group_channels)
            gid_c = gid
        else:
            gid_c = jnp.zeros(cap, dtype=jnp.int64)
        varg = live
        if kcol.valid is not None:
            varg = jnp.logical_and(varg, jnp.take(kcol.valid, perm, mode="clip"))
        if vcol.valid is not None:
            varg = jnp.logical_and(varg, jnp.take(vcol.valid, perm, mode="clip"))
        pos_in_group, counts = _group_ranks(varg, gid_c, cap, nseg)
        keep = jnp.logical_and(varg, pos_in_group < n)
        scatter_g = jnp.where(keep, gid_c, nseg)
        scatter_p = jnp.clip(pos_in_group, 0, n - 1)
        vd = jnp.take(vcol.data, perm, mode="clip")
        et = spec.out_type.element
        out = (
            jnp.zeros((nseg + 1, n), dtype=et.np_dtype)
            .at[scatter_g, scatter_p]
            .set(jnp.asarray(vd, et.np_dtype), mode="drop")
        )
        lengths = jnp.minimum(counts[:out_cap], n).astype(jnp.int32)
        return Column(
            out[:out_cap], spec.out_type, None, vcol.dictionary, lengths
        )

    def _minmax_by_one(
        self, batch: Batch, spec: AggSpec, perm, live, gid_c, nseg, out_cap
    ) -> Column:
        """min_by/max_by(value, key): the VALUE at each group's extreme KEY
        (reference: MinMaxByNAggregation, N=1).  Jit-safe: extreme key via
        segment reduce, then the first row achieving it selects the value.
        Rows with NULL keys are skipped; ties pick the first sorted row."""
        if spec.param is not None:
            return self._minmax_by_n(batch, spec, nseg, out_cap)
        from trino_tpu.ops.common import _max_sentinel, _min_sentinel

        cap = batch.capacity
        vcol = batch.columns[spec.arg]
        kcol = batch.columns[spec.arg2]
        kd = jnp.take(kcol.data, perm, mode="clip")
        vkey = live
        if kcol.valid is not None:
            vkey = jnp.logical_and(vkey, jnp.take(kcol.valid, perm, mode="clip"))
        want_min = spec.name == "min_by"
        sent = (
            _max_sentinel(kd.dtype) if want_min else _min_sentinel(kd.dtype)
        )
        keyed = jnp.where(vkey, kd, sent)
        if want_min and jnp.issubdtype(kd.dtype, jnp.floating):
            # NaN orders as largest (same rule the sort path uses), so for
            # min it must only win when every key is NaN — remap to +inf
            # instead of letting segment_min propagate it
            keyed = jnp.where(jnp.isnan(keyed), jnp.inf, keyed)
        red = jax.ops.segment_min if want_min else jax.ops.segment_max
        kext = red(keyed, gid_c, nseg)
        pos = jnp.arange(cap, dtype=jnp.int64)
        kext_g = jnp.take(kext, gid_c, mode="clip")
        match = keyed == kext_g
        if jnp.issubdtype(kd.dtype, jnp.floating):
            # segment min/max propagate NaN keys; NaN != NaN would then match
            # no row and silently select a padded one
            match = jnp.logical_or(
                match, jnp.logical_and(jnp.isnan(keyed), jnp.isnan(kext_g))
            )
        at_ext = jnp.logical_and(vkey, match)
        first = jax.ops.segment_min(jnp.where(at_ext, pos, cap), gid_c, nseg)
        idx = jnp.clip(first[:out_cap], 0, cap - 1)
        vd = jnp.take(vcol.data, perm, axis=0, mode="clip")
        out = jnp.take(vd, idx, axis=0, mode="clip")
        has_key = jax.ops.segment_sum(vkey.astype(jnp.int64), gid_c, nseg)[:out_cap] > 0
        valid = has_key
        if vcol.valid is not None:
            vvalid = jnp.take(
                jnp.take(vcol.valid, perm, mode="clip"), idx, mode="clip"
            )
            valid = jnp.logical_and(valid, vvalid)
        return Column(out, spec.out_type, valid, vcol.dictionary)

    def _percentile_one(self, batch: Batch, spec: AggSpec, out_cap: int) -> Column:
        """Exact per-group percentile: re-sort by (group keys, value) and
        pick the nearest-rank row of each group (reference role:
        ApproximateLongPercentileAggregations via qdigest — a sort-based
        engine computes the exact rank instead)."""
        gch = self.group_channels
        cap = batch.capacity
        col = batch.columns[spec.arg]
        keys = [SortKey(ch) for ch in gch] + [SortKey(spec.arg)]
        perm2 = multi_key_sort_perm(batch, keys)
        gid2, _, _ = group_ids_from_sorted(batch, perm2, gch)
        live2 = jnp.take(batch.mask(), perm2, mode="clip")
        varg = live2
        if col.valid is not None:
            varg = jnp.logical_and(varg, jnp.take(col.valid, perm2, mode="clip"))
        pos = jnp.arange(cap, dtype=jnp.int64)
        gid_c = jnp.minimum(gid2, out_cap)
        nseg = out_cap + 1
        # nulls sort last within the group: the group's first live row starts
        # the non-null run, whose length is the valid count
        start = jax.ops.segment_min(jnp.where(varg, pos, cap), gid_c, nseg)
        nvalid = jax.ops.segment_sum(varg.astype(jnp.int64), gid_c, nseg)
        p = float(spec.param if spec.param is not None else 0.5)
        target = start + jnp.round(
            p * jnp.maximum(nvalid - 1, 0).astype(jnp.float64)
        ).astype(jnp.int64)
        d_sorted = jnp.take(col.data, perm2, axis=0, mode="clip")
        val = jnp.take(
            d_sorted, jnp.clip(target[:out_cap], 0, cap - 1), axis=0, mode="clip"
        )
        return Column(val, spec.out_type, nvalid[:out_cap] > 0, col.dictionary)

    def _bivariate_series(self, batch, spec, kind, perm, live):
        """(per-row series, pairwise-valid mask) for one bi_* primitive."""
        cx = batch.columns[spec.arg]
        cy = batch.columns[spec.arg2]
        dx = _logical_double(jnp.take(cx.data, perm, mode="clip"), cx.type)
        dy = _logical_double(jnp.take(cy.data, perm, mode="clip"), cy.type)
        v = live
        if cx.valid is not None:
            v = jnp.logical_and(v, jnp.take(cx.valid, perm, mode="clip"))
        if cy.valid is not None:
            v = jnp.logical_and(v, jnp.take(cy.valid, perm, mode="clip"))
        series = {
            "bi_sum_1": dx,
            "bi_sum_2": dy,
            "bi_sumsq_1": dx * dx,
            "bi_sumsq_2": dy * dy,
            "bi_sum_12": dx * dy,
            "bi_count": jnp.ones(dx.shape, jnp.int64),
        }[kind]
        return series, v

    def _reduce_one(self, batch, spec, perm, live, gid, nseg, out_cap):
        if self.mode in ("final", "merge"):
            prims = list(zip(_merge_primitives(spec), _primitives(spec)))
            # state columns arrive as consecutive input channels starting at arg
            state_cols = []
            ch = spec.arg
            for kind, _ in prims:
                col = batch.columns[ch]
                d = jnp.take(col.data, perm, axis=0, mode="clip")
                v = live
                if col.valid is not None:
                    v = jnp.logical_and(v, jnp.take(col.valid, perm, mode="clip"))
                if (
                    kind == "sum"
                    and isinstance(col.type, T.DecimalType)
                    and col.type.is_long
                ):
                    # merging Int128 partial-sum states
                    red2 = _sum128(
                        d, gid, nseg, v, sum_bound=spec.sum_bound
                    )[:out_cap]
                    state_cols.append(Column(red2, col.type, None))
                    ch += 1
                    continue
                if (
                    d.ndim == 2
                    and isinstance(col.type, T.DecimalType)
                    and kind in ("min", "max", "any")
                ):
                    red2 = _reduce128(d, gid, nseg, kind, v)[:out_cap]
                    state_cols.append(Column(red2, col.type, None))
                    ch += 1
                    continue
                red = segment_reduce(d, gid, nseg, kind, valid=v)[:out_cap]
                state_cols.append(Column(red, col.type, None, col.dictionary))
                ch += 1
            return state_cols
        out = []
        for kind, arg in _primitives(spec):
            if kind == "count_star":
                red = segment_reduce(
                    jnp.ones(batch.capacity, jnp.int64), gid, nseg, "count", valid=live
                )[:out_cap]
                out.append(Column(red, T.BIGINT, None))
                continue
            if kind == "checksum":
                col = batch.columns[arg]
                h = _hll_hash(col).astype(jnp.int64)  # stable value hash
                h = jnp.take(h, perm, mode="clip")
                if col.valid is not None:
                    nullp = jnp.int64(np.int64(np.uint64(CHECKSUM_NULL_PRIME)))
                    h = jnp.where(
                        jnp.take(col.valid, perm, mode="clip"), h, nullp
                    )
                red = segment_reduce(
                    jnp.where(live, h, 0), gid, nseg, "sum", valid=live
                )[:out_cap]
                out.append(Column(red, T.BIGINT, None))
                continue
            if kind.startswith("bi_"):
                series, v = self._bivariate_series(batch, spec, kind, perm, live)
                if kind == "bi_count":
                    red = segment_reduce(series, gid, nseg, "count", valid=v)[:out_cap]
                    out.append(Column(red, T.BIGINT, None))
                else:
                    red = segment_reduce(series, gid, nseg, "sum", valid=v)[:out_cap]
                    out.append(Column(red, T.DOUBLE, None))
                continue
            col = batch.columns[arg]
            d = jnp.take(col.data, perm, axis=0, mode="clip")
            v = live
            if col.valid is not None:
                v = jnp.logical_and(v, jnp.take(col.valid, perm, mode="clip"))
            st = _state_types(spec, self.input_types)[len(out)]
            if kind in ("sum_f", "sumsq"):
                dl = _logical_double(d, col.type)
                if kind == "sumsq":
                    dl = dl * dl
                red = segment_reduce(dl, gid, nseg, "sum", valid=v)[:out_cap]
                out.append(Column(red, T.DOUBLE, None))
                continue
            if kind == "sum" and isinstance(st, T.DecimalType) and st.is_long:
                prec = (
                    col.type.precision
                    if isinstance(col.type, T.DecimalType)
                    else None
                )
                red2 = _sum128(
                    d, gid, nseg, v, in_precision=prec,
                    sum_bound=spec.sum_bound,
                )[:out_cap]
                out.append(Column(red2, st, None))
                continue
            if (
                d.ndim == 2
                and isinstance(col.type, T.DecimalType)
                and kind in ("min", "max", "any")
            ):
                red2 = _reduce128(d, gid, nseg, kind, v)[:out_cap]
                out.append(Column(red2, st, None))
                continue
            if kind == "sum":
                # widen BEFORE reducing: int32 inputs must accumulate in int64
                d = d.astype(st.np_dtype)
            red = segment_reduce(d, gid, nseg, kind, valid=v)[:out_cap]
            out.append(
                Column(red.astype(st.np_dtype), st, None, col.dictionary)
            )
        return out

    def _global_reduce(self, batch: Batch) -> Batch:
        """No group keys: one output row (present even for empty input)."""
        live = batch.mask()
        cols = []
        for spec in self.aggregates:
            if spec.name in ("min_by", "max_by"):
                if self.mode != "single":
                    raise NotImplementedError(
                        f"{spec.name} requires single-stage aggregation"
                    )
                cap0 = batch.capacity
                perm0 = jnp.arange(cap0, dtype=jnp.int64)
                gid0 = jnp.zeros(cap0, dtype=jnp.int64)
                cols.append(
                    self._minmax_by_one(batch, spec, perm0, live, gid0, 2, 1)
                )
                continue
            if spec.name in COLLECT_AGGS:
                if self.mode != "single":
                    raise NotImplementedError(
                        f"{spec.name} requires single-stage aggregation"
                    )
                if spec.name == "listagg":
                    cols.append(self._listagg_one(batch, spec, 1))
                    continue
                # one global group: reuse the grouped collect with gid=0
                cap = batch.capacity
                perm = jnp.arange(cap, dtype=jnp.int64)
                gid_c = jnp.zeros(cap, dtype=jnp.int64)
                cols.append(
                    self._collect_one(batch, spec, perm, live, gid_c, 2, 1)
                )
                continue
            if spec.name == "percentile":
                if self.mode != "single":
                    raise NotImplementedError(
                        "percentile requires single-stage aggregation"
                    )
                col = batch.columns[spec.arg]
                v = live
                if col.valid is not None:
                    v = jnp.logical_and(v, col.valid)
                # sort values with invalid rows last
                perm = multi_key_sort_perm(
                    Batch(list(batch.columns), v), [SortKey(spec.arg)]
                )
                n = jnp.sum(v)
                p = float(spec.param if spec.param is not None else 0.5)
                idx = jnp.round(
                    p * jnp.maximum(n - 1, 0).astype(jnp.float64)
                ).astype(jnp.int64)
                d_sorted = jnp.take(col.data, perm, axis=0, mode="clip")
                val = jnp.take(
                    d_sorted, jnp.clip(idx, 0, batch.capacity - 1), axis=0
                )
                cols.append(
                    Column(val[None], spec.out_type, (n > 0)[None], col.dictionary)
                )
                continue
            states = []
            if self.mode in ("final", "merge"):
                ch = spec.arg
                for kind in _merge_primitives(spec):
                    col = batch.columns[ch]
                    v = live
                    if col.valid is not None:
                        v = jnp.logical_and(v, col.valid)
                    if kind == "hll":
                        # elementwise max of register rows (mergeable state)
                        sent = jnp.iinfo(jnp.int32).min
                        regs = jnp.max(
                            jnp.where(v[:, None], col.data, sent), axis=0
                        )
                        states.append(
                            Column(
                                regs[None, :],
                                T.ArrayType(T.INTEGER),
                                None,
                                lengths=jnp.full(1, HLL_M, jnp.int32),
                            )
                        )
                        ch += 1
                        continue
                    if kind == "qdigest":
                        from trino_tpu.ops import qdigest as qd

                        counts = jnp.sum(
                            jnp.where(v[:, None], col.data, 0), axis=0
                        )
                        states.append(
                            Column(
                                counts[None, :],
                                T.ArrayType(T.BIGINT),
                                None,
                                lengths=jnp.full(1, qd.NBUCKETS, jnp.int32),
                            )
                        )
                        ch += 1
                        continue
                    if (
                        kind == "sum"
                        and isinstance(col.type, T.DecimalType)
                        and col.type.is_long
                    ):
                        gid0 = jnp.zeros(col.data.shape[0], dtype=jnp.int64)
                        states.append(
                            Column(
                                _sum128(
                                    col.data, gid0, 1, v,
                                    sum_bound=spec.sum_bound,
                                ),
                                col.type, None,
                            )
                        )
                        ch += 1
                        continue
                    if (
                        col.data.ndim == 2
                        and isinstance(col.type, T.DecimalType)
                        and kind in ("min", "max", "any")
                    ):
                        gid0 = jnp.zeros(col.data.shape[0], dtype=jnp.int64)
                        states.append(
                            Column(
                                _reduce128(col.data, gid0, 1, kind, v),
                                col.type,
                                None,
                            )
                        )
                        ch += 1
                        continue
                    states.append(
                        Column(
                            _masked_reduce(col.data, v, kind)[None],
                            col.type,
                            None,
                            col.dictionary,
                        )
                    )
                    ch += 1
            else:
                for kind, arg in _primitives(spec):
                    if kind == "count_star":
                        states.append(
                            Column(jnp.sum(live, dtype=jnp.int64)[None], T.BIGINT, None)
                        )
                        continue
                    if kind == "checksum":
                        col = batch.columns[arg]
                        h = _hll_hash(col).astype(jnp.int64)
                        if col.valid is not None:
                            nullp = jnp.int64(
                                np.int64(np.uint64(CHECKSUM_NULL_PRIME))
                            )
                            h = jnp.where(col.valid, h, nullp)
                        states.append(
                            Column(
                                jnp.sum(jnp.where(live, h, 0))[None],
                                T.BIGINT,
                                None,
                            )
                        )
                        continue
                    if kind.startswith("bi_"):
                        perm0 = jnp.arange(batch.capacity, dtype=jnp.int64)
                        series, v = self._bivariate_series(
                            batch, spec, kind, perm0, live
                        )
                        if kind == "bi_count":
                            states.append(
                                Column(
                                    jnp.sum(v, dtype=jnp.int64)[None],
                                    T.BIGINT,
                                    None,
                                )
                            )
                        else:
                            states.append(
                                Column(
                                    jnp.sum(jnp.where(v, series, 0.0))[None],
                                    T.DOUBLE,
                                    None,
                                )
                            )
                        continue
                    col = batch.columns[arg]
                    v = live
                    if col.valid is not None:
                        v = jnp.logical_and(v, col.valid)
                    if kind == "hll":
                        regs = _hll_registers(col, v)
                        states.append(
                            Column(
                                regs[None, :],
                                T.ArrayType(T.INTEGER),
                                None,
                                lengths=jnp.full(1, HLL_M, jnp.int32),
                            )
                        )
                        continue
                    if kind == "qdigest":
                        from trino_tpu.ops import qdigest as qd

                        if col.data.ndim == 2:  # long-decimal limb planes
                            from trino_tpu.types import int128 as i128

                            f = i128.to_float128(
                                col.data[:, 0], col.data[:, 1]
                            ) / float(col.type.scale_factor)
                        else:
                            f = _logical_double(col.data, col.type)
                        counts = qd.histogram(f, v)
                        states.append(
                            Column(
                                counts[None, :],
                                T.ArrayType(T.BIGINT),
                                None,
                                lengths=jnp.full(1, qd.NBUCKETS, jnp.int32),
                            )
                        )
                        continue
                    st = _state_types(spec, self.input_types)[len(states)]
                    d = col.data
                    if kind in ("sum_f", "sumsq"):
                        d = _logical_double(d, col.type)
                        if kind == "sumsq":
                            d = d * d
                        kind = "sum"
                    elif kind == "sum" and isinstance(st, T.DecimalType) and st.is_long:
                        gid0 = jnp.zeros(d.shape[0], dtype=jnp.int64)
                        prec = (
                            col.type.precision
                            if isinstance(col.type, T.DecimalType)
                            else None
                        )
                        states.append(
                            Column(
                                _sum128(
                                    d, gid0, 1, v, in_precision=prec,
                                    sum_bound=spec.sum_bound,
                                ),
                                st,
                                None,
                            )
                        )
                        continue
                    elif (
                        d.ndim == 2
                        and isinstance(col.type, T.DecimalType)
                        and kind in ("min", "max", "any")
                    ):
                        gid0 = jnp.zeros(d.shape[0], dtype=jnp.int64)
                        states.append(
                            Column(_reduce128(d, gid0, 1, kind, v), st, None)
                        )
                        continue
                    elif kind == "sum":
                        d = d.astype(st.np_dtype)  # widen before reducing
                    states.append(
                        Column(
                            _masked_reduce(d, v, kind)[None].astype(st.np_dtype),
                            st,
                            None,
                            col.dictionary,
                        )
                    )
            if self.mode in ("partial", "merge"):
                cols.extend(states)
            else:
                cols.append(_finalize(spec, states))
        return Batch(cols, jnp.ones(1, dtype=bool))

    # -- host-side streaming -------------------------------------------------

    def _batch_reducer(self) -> "AggregationOperator":
        """Per-batch operator for streaming: raw rows -> states, or (when this
        op's input is already states) states -> states."""
        per_mode = "merge" if self.mode in ("final", "merge") else "partial"
        op = AggregationOperator(
            self.group_channels,
            self.aggregates,
            self.input_types,
            mode=per_mode,
            pre_step=self._pre if per_mode == "partial" else None,
            pre_key=self._pre_key if per_mode == "partial" else None,
            pre_jit=self._pre_jit if per_mode == "partial" else None,
        )
        op._group_src_channels = getattr(self, "_group_src_channels", None)
        return op

    #: fold accumulated per-batch states after this many batches (bounds
    #: device memory at ~FOLD_EVERY batch capacities, the revoke analog)
    FOLD_EVERY = 8

    def reduce_batch(self, batch: Batch) -> Batch:
        """One input batch -> its partial-state batch.  Dict/bool
        small-domain keys take the in-jit direct path (no host syncs, the
        Q1 shape); otherwise _reduce_full compacts dead slack and tries the
        positional path (one scalar sync)."""
        if self._per_batch is None:
            self._per_batch = self._batch_reducer()
        per_batch = self._per_batch
        if per_batch._direct_group_info(
            batch, src_channels=getattr(per_batch, "_group_src_channels", None)
        ) is not None:
            return per_batch._step(batch, out_cap=batch.capacity)
        if per_batch._pre is not None and per_batch._pre_jit is not None:
            # non-direct group keys (e.g. bigint orderkeys): the positional
            # path needs the PROJECTED batch for key stats, so materialize
            # the projection once and reduce through an unfused twin.
            # group_channels/input_types/spec.arg all describe the
            # POST-projection layout already (the fused op applies pre
            # first inside its own step), so the twin's config is correct
            # for the projected batch it is fed.
            if self._unfused_twin is None:
                self._unfused_twin = AggregationOperator(
                    per_batch.group_channels,
                    per_batch.aggregates,
                    per_batch.input_types,
                    mode=per_batch.mode,
                )
            return self._unfused_twin._reduce_full(
                per_batch._pre_jit(batch)
            )
        return per_batch._reduce_full(batch)

    def push(self, batch: Batch) -> None:
        """Accumulate one input batch (streamed per-batch reduction when
        `streaming`)."""
        if self.streaming:
            self._acc.append(self.reduce_batch(batch))
            if len(self._acc) >= self.fold_every:
                self._fold_states()
        else:
            self._acc.append(batch)
        if self.memory_ctx is not None:
            from trino_tpu.runtime.memory import (
                ExceededMemoryLimitException,
                batches_bytes,
            )

            try:
                self.memory_ctx.set_bytes(batches_bytes(self._acc))
            except ExceededMemoryLimitException:
                # graceful-degradation hook: folding compacts accumulated
                # states to live groups, often freeing enough to fit; only
                # re-raise when pressure survives the fold (the wave
                # fallback's / killer's signal)
                if not self.streaming or len(self._acc) <= 1:
                    raise
                self._fold_states()
                self.memory_ctx.set_bytes(batches_bytes(self._acc))

    def state_bytes(self) -> int:
        from trino_tpu.runtime.memory import batches_bytes

        return batches_bytes(self._acc)

    def process(self, stream):
        for batch in stream:
            self.push(batch)
        out = self.finish()
        if self.memory_ctx is not None:
            self.memory_ctx.close()
        yield out

    def _fold_states(self) -> None:
        """Merge accumulated state batches into one, compacted to live size."""
        merged = self._combine(concat_batches(self._acc), "merge")
        n = merged.num_rows_host()
        self._acc = [merged.compact_device(next_pow2(max(n, 1), floor=1))]

    def finish(self) -> Batch:
        if not self._acc:
            empty = self._empty_input()
            if self._pre is not None:
                # _empty_input is in POST-projection layout; the fused pre
                # expects raw channels, so reduce with an unfused twin
                twin = AggregationOperator(
                    self.group_channels,
                    self.aggregates,
                    self.input_types,
                    mode=self.mode,
                )
                return twin.finish()
            if any(s.name in COLLECT_AGGS for s in self.aggregates):
                return self._reduce_step(empty, out_cap=max(1, empty.capacity))
            return self._step(empty, out_cap=max(1, empty.capacity))
        big = self._acc[0] if len(self._acc) == 1 else concat_batches(self._acc)
        if self.streaming:
            out_mode = "merge" if self.mode in ("partial", "merge") else "final"
            return self._combine(big, out_mode)
        return self._reduce_full(big)

    def _combine(self, states_batch: Batch, out_mode: str) -> Batch:
        """Re-reduce a batch of state rows (group keys + state columns)."""
        merger = AggregationOperator(
            list(range(len(self.group_channels))),
            [
                AggSpec(
                    s.name, self._state_channel(i), s.out_type,
                    param=s.param, sum_bound=s.sum_bound,
                )
                for i, s in enumerate(self.aggregates)
            ],
            [c.type for c in states_batch.columns],
            mode=out_mode,
        )
        return merger._reduce_full(states_batch)

    def _state_channel(self, agg_index: int) -> int:
        ch = len(self.group_channels)
        for s in self.aggregates[:agg_index]:
            ch += len(_primitives(s))
        return ch

    def _empty_input(self) -> Batch:
        import numpy as np

        cols = [
            Column(np.zeros(1, dtype=t.np_dtype), t, np.zeros(1, dtype=bool))
            for t in self.input_types
        ]
        return Batch(cols, np.zeros(1, dtype=bool))
