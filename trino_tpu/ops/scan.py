"""Table scan: connector pages -> padded device batches.

Reference role: operator/TableScanOperator.java:47 +
ScanFilterAndProjectOperator.java:68.  Host-side decode (the connector) feeds
shape-bucketed device batches; when a filter/projection is attached the scan
fuses them into the same jitted step (the ScanFilterAndProject analog), so a
page goes host->device once and is filtered/projected in one XLA program.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np

from trino_tpu.columnar import Batch, Column
from trino_tpu.connectors.api import Connector, Split
from trino_tpu.ops.common import next_pow2
from trino_tpu.types import Type


def page_to_batch(page, types: Sequence[Type], capacity: Optional[int] = None) -> Batch:
    """Pad host ColumnData to a pow2 capacity and build a host Batch."""
    n = len(page[0].values) if page else 0
    cap = capacity or next_pow2(n)
    cols = []
    for cd, t in zip(page, types):
        data = np.asarray(cd.values, dtype=t.np_dtype)
        if len(data) < cap:
            pad_shape = (cap - len(data),) + data.shape[1:]
            data = np.concatenate([data, np.zeros(pad_shape, dtype=t.np_dtype)])
        valid = None
        if cd.valid is not None:
            v = np.asarray(cd.valid, dtype=bool)
            valid = np.concatenate([v, np.zeros(cap - len(v), dtype=bool)])
        cols.append(Column(data, t, valid, cd.dictionary))
    mask = np.zeros(cap, dtype=bool)
    mask[:n] = True
    return Batch(cols, mask)


class ScanOperator:
    """Streams one split's pages as device batches.

    Immutable splits (connector.scan_version != None) are served through the
    two-tier buffer pool: repeated scans hit device-resident batches (no
    host→device transfer at all), second-best is padded host pages (no
    generation/decode).  Cold scans stream pages while filling both tiers.
    """

    def __init__(
        self,
        connector: Connector,
        split: Split,
        column_names: Sequence[str],
        column_types: Sequence[Type],
        page_rows: int = 1 << 17,
        device=None,
        use_cache: bool = True,
    ):
        self.connector = connector
        self.split = split
        self.column_names = list(column_names)
        self.column_types = list(column_types)
        self.page_rows = page_rows
        self.device = device
        self.use_cache = use_cache

    def _cache_key(self):
        if not self.use_cache:
            return None
        version = self.connector.scan_version(self.split.table)
        if version is None:
            return None
        from trino_tpu.runtime.buffer_pool import BufferPool

        return BufferPool.split_key(
            self.split, self.column_names, self.page_rows, version
        )

    def host_batches(self) -> list:
        """Padded host batches for this split, via the host cache tier."""
        from trino_tpu.runtime.buffer_pool import POOL

        key = self._cache_key()
        if key is not None:
            host = POOL.get_host(key)
            if host is not None:
                return host
        src = self.connector.page_source(
            self.split, self.column_names, max_rows_per_page=self.page_rows
        )
        host = [page_to_batch(p, self.column_types) for p in src.pages()]
        if key is not None:
            POOL.put_host(key, host)
        return host

    def batches(self):
        from trino_tpu.runtime.buffer_pool import POOL

        key = self._cache_key()
        if key is not None:
            cached = POOL.get_device(key)
            if cached is not None:
                yield from cached
                return
            host = POOL.get_host(key)
            if host is not None:
                dev = []
                for b in host:
                    d = jax.device_put(b, self.device)
                    dev.append(d)
                    yield d
                POOL.put_device(key, dev)
                return
        src = self.connector.page_source(
            self.split, self.column_names, max_rows_per_page=self.page_rows
        )
        host_acc, dev_acc = [], []
        for page in src.pages():
            b = page_to_batch(page, self.column_types)
            d = jax.device_put(b, self.device)
            if key is not None:
                host_acc.append(b)
                dev_acc.append(d)
            yield d
        if key is not None:
            POOL.put_host(key, host_acc)
            POOL.put_device(key, dev_acc)
