"""Window function operator (reference: operator/WindowOperator.java +
operator/window/* — rank family, value family, aggregate-over-frame).

TPU substitution: one materialized sort by (partition keys, order keys), then
every window function is a closed-form computation over partition/peer
boundary flags — prefix sums (`cumsum`), segment reductions, and shifted
gathers — a single static-shape XLA program instead of the reference's
per-partition imperative loops (WindowPartition.processNextRow).

Supported frames: the SQL default RANGE BETWEEN UNBOUNDED PRECEDING AND
CURRENT ROW (running, peer-inclusive), ROWS frames with unbounded or literal
row offsets (reference: operator/window/FrameInfo.java), and the
whole-partition frame (no ORDER BY, or UNBOUNDED..UNBOUNDED).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from trino_tpu import types as T
from trino_tpu.columnar import Batch, Column
from trino_tpu.columnar.batch import concat_batches
from trino_tpu.ops.aggregation import _pad_device
from trino_tpu.ops.common import (
    SortKey,
    _max_sentinel,
    _min_sentinel,
    multi_key_sort_perm,
    next_pow2,
)


@dataclass(frozen=True)
class WindowSpec:
    """One window function: rank family (no arg) or aggregate/value family
    (arg = input channel).  frame: 'range' (default running, peer-aware),
    'rows' (running, row-exact), 'full' (whole partition)."""

    name: str  # row_number | rank | dense_rank | ntile | percent_rank |
    #            cume_dist | lag | lead | first_value | last_value |
    #            sum | count | avg | min | max
    arg: Optional[int]
    out_type: T.Type
    offset: int = 1  # lag/lead offset (literal)
    default_channel: Optional[int] = None  # lag/lead default value column
    n_buckets: int = 1  # ntile
    frame: str = "range"
    # ROWS-frame bounds relative to the current row (None = unbounded on that
    # side); the default running frame is (None, 0).
    start_off: Optional[int] = None
    end_off: Optional[int] = 0
    # IGNORE NULLS for lag/lead/first_value/last_value (reference:
    # operator/window/LagFunction.java ignoreNulls handling)
    ignore_nulls: bool = False
    #: proof-licensed |frame sum| bound for decimal sum/avg (planner range
    #: certificate, plan.WindowFunction.sum_bound): a long-decimal input
    #: whose every frame sum provably fits int64 runs the single-plane
    #: prefix-sum kernel instead of limb-plane arithmetic
    sum_bound: Optional[int] = None


_WINDOW_STEP_CACHE: dict = {}


class WindowOperator:
    def __init__(
        self,
        partition_channels: Sequence[int],
        order_keys: Sequence[SortKey],
        specs: Sequence[WindowSpec],
    ):
        self.partition_channels = list(partition_channels)
        self.order_keys = list(order_keys)
        self.specs = list(specs)
        self._acc: list[Batch] = []
        # shared jitted step across per-query instances (wave execution
        # constructs one operator per wave; identical configs must not
        # re-trace — the _STEP_CACHE convention of ops/sort.py)
        key = (
            "window",
            tuple(self.partition_channels),
            tuple(self.order_keys),
            tuple(
                (
                    sp.name, sp.arg, sp.out_type.name, sp.offset,
                    sp.default_channel, sp.n_buckets, sp.frame,
                    sp.start_off, sp.end_off, sp.ignore_nulls,
                    sp.sum_bound,
                )
                for sp in self.specs
            ),
        )
        cached = _WINDOW_STEP_CACHE.get(key)
        if cached is None:
            cached = jax.jit(self._window_step)
            _WINDOW_STEP_CACHE[key] = cached
        self._step = cached

    # -- the jitted kernel ----------------------------------------------------

    def _window_step(self, batch: Batch) -> Batch:
        cap = batch.capacity
        keys = [SortKey(ch) for ch in self.partition_channels] + self.order_keys
        # always sort: even with no keys, multi_key_sort_perm moves dead
        # (filtered-out) rows last, so positional logic below only sees live
        # rows in the prefix — `row_number() over ()` must not count dead rows
        perm = multi_key_sort_perm(batch, keys)
        live = jnp.take(batch.mask(), perm, mode="clip")
        pos = jnp.arange(cap, dtype=jnp.int64)

        # partition boundaries (null-safe equality over partition keys)
        new_part = jnp.zeros(cap, dtype=bool)
        first_live = jnp.logical_and(live, jnp.cumsum(live) == 1)
        for ch in self.partition_channels:
            col = batch.columns[ch]
            d = jnp.take(col.data, perm, axis=0, mode="clip")
            neq = d != jnp.roll(d, 1, axis=0)
            if neq.ndim > 1:  # long-decimal limb planes
                neq = jnp.any(neq, axis=-1)
            if col.valid is not None:
                v = jnp.take(col.valid, perm, mode="clip")
                pv = jnp.roll(v, 1)
                neq = jnp.logical_or(
                    jnp.logical_and(neq, jnp.logical_and(v, pv)), v != pv
                )
            new_part = jnp.logical_or(new_part, neq)
        new_part = jnp.logical_or(jnp.logical_and(live, new_part), first_live)
        pid = jnp.cumsum(new_part) - 1  # partition id per sorted row
        pid = jnp.where(live, pid, cap)
        nseg = cap + 1
        part_start = jax.ops.segment_min(jnp.where(live, pos, cap), pid, nseg)
        part_size = jax.ops.segment_sum(live.astype(jnp.int64), pid, nseg)
        idx_in_part = pos - part_start[jnp.clip(pid, 0, cap)]

        # peer boundaries (order-key ties within a partition)
        new_peer = new_part
        for k in self.order_keys:
            col = batch.columns[k.channel]
            d = jnp.take(col.data, perm, axis=0, mode="clip")
            neq = d != jnp.roll(d, 1, axis=0)
            if neq.ndim > 1:  # long-decimal limb planes
                neq = jnp.any(neq, axis=-1)
            if col.valid is not None:
                v = jnp.take(col.valid, perm, mode="clip")
                pv = jnp.roll(v, 1)
                neq = jnp.logical_or(
                    jnp.logical_and(neq, jnp.logical_and(v, pv)), v != pv
                )
            new_peer = jnp.logical_or(new_peer, jnp.logical_and(live, neq))
        peer_gid = jnp.cumsum(new_peer) - 1
        peer_gid = jnp.where(live, peer_gid, cap)
        # last row index of each peer group (for RANGE running frames)
        peer_last = jax.ops.segment_max(jnp.where(live, pos, -1), peer_gid, nseg)

        out_cols = []
        for spec in self.specs:
            vals = self._compute(
                spec, batch, perm, live, pid, nseg, part_start, part_size,
                idx_in_part, new_peer, peer_gid, peer_last, pos, cap,
            )
            out_cols.append(vals)
        # scatter back to original row order
        inv = jnp.zeros(cap, dtype=jnp.int64).at[perm].set(pos)
        final_cols = list(batch.columns)
        for c in out_cols:
            data = jnp.take(c.data, inv, axis=0, mode="clip")
            valid = None if c.valid is None else jnp.take(c.valid, inv, mode="clip")
            final_cols.append(Column(data, c.type, valid, c.dictionary))
        return Batch(final_cols, batch.row_mask)

    @staticmethod
    def _valid_ranks(v, live, part_first, pos, cap):
        """(pref, pos_of) for IGNORE NULLS: pref[i] = count of non-null live
        rows at or before sorted row i WITHIN its partition; pos_of is a
        [cap+1] table mapping slot part_first + rank (0-based, per
        partition) -> the sorted-row index of that partition's rank-th
        non-null row (cap = no such row).  Slots of different partitions
        are disjoint because ranks never exceed the partition size."""
        vi = jnp.logical_and(live, v)
        c = jnp.cumsum(vi.astype(jnp.int64))
        base = jnp.where(
            part_first > 0,
            jnp.take(c, jnp.maximum(part_first - 1, 0), mode="clip"),
            0,
        )
        pref = c - base
        slot = jnp.where(vi, part_first + pref - 1, cap)
        pos_of = jnp.full(cap + 1, cap, jnp.int64).at[slot].set(
            pos, mode="drop"
        )
        return pref, pos_of

    def _compute(
        self, spec, batch, perm, live, pid, nseg, part_start, part_size,
        idx_in_part, new_peer, peer_gid, peer_last, pos, cap,
    ) -> Column:
        name = spec.name
        safe_pid = jnp.clip(pid, 0, cap)
        n_in_part = part_size[safe_pid]

        # frame bounds as sorted-row indices [lo, hi] per row (FrameInfo.java)
        part_first = part_start[safe_pid]
        part_last = part_first + n_in_part - 1
        whole = spec.frame == "full" or not self.order_keys
        if whole:
            lo, hi = part_first, part_last
        elif spec.frame == "rows":
            lo = (
                part_first
                if spec.start_off is None
                else jnp.maximum(part_first, pos + spec.start_off)
            )
            hi = (
                part_last
                if spec.end_off is None
                else jnp.minimum(part_last, pos + spec.end_off)
            )
        else:  # default RANGE running frame: start of partition .. last peer
            lo = part_first
            hi = peer_last[jnp.clip(peer_gid, 0, cap)]
        frame_n = jnp.maximum(hi - lo + 1, 0)

        if name == "row_number":
            return Column(idx_in_part + 1, T.BIGINT, None)
        if name in ("rank", "dense_rank", "percent_rank", "cume_dist", "ntile"):
            # rank = index of first peer row in partition + 1
            first_peer = jax.ops.segment_min(jnp.where(live, pos, cap), peer_gid, nseg)
            rank = first_peer[jnp.clip(peer_gid, 0, cap)] - part_start[safe_pid] + 1
            if name == "rank":
                return Column(rank, T.BIGINT, None)
            if name == "dense_rank":
                dense = jnp.cumsum(new_peer) - jnp.take(
                    jnp.cumsum(new_peer), part_start[safe_pid], mode="clip"
                ) + 1
                return Column(dense, T.BIGINT, None)
            if name == "percent_rank":
                den = jnp.maximum(n_in_part - 1, 1)
                return Column((rank - 1) / den, T.DOUBLE, None)
            if name == "cume_dist":
                last = peer_last[jnp.clip(peer_gid, 0, cap)]
                covered = last - part_start[safe_pid] + 1
                return Column(covered / jnp.maximum(n_in_part, 1), T.DOUBLE, None)
            if name == "ntile":
                n = spec.n_buckets
                sz = n_in_part
                base, rem = sz // n, sz % n
                big = (base + 1) * rem  # rows covered by the larger buckets
                in_big = idx_in_part < big
                bucket = jnp.where(
                    in_big,
                    idx_in_part // jnp.maximum(base + 1, 1),
                    rem + (idx_in_part - big) // jnp.maximum(base, 1),
                )
                return Column(bucket + 1, T.BIGINT, None)
        if name in ("lag", "lead"):
            col = batch.columns[spec.arg]
            d = jnp.take(col.data, perm, axis=0, mode="clip")
            v = jnp.take(col.valid, perm, mode="clip") if col.valid is not None else jnp.ones(cap, bool)
            if spec.ignore_nulls:
                # k-th non-null neighbour via per-partition valid-rank
                # indexing: rank positions scatter to a dense pos_of table
                # laid out at partition offsets, so one gather finds the row
                pref, pos_of = self._valid_ranks(
                    v, live, part_first, pos, cap
                )
                if name == "lag":
                    tgt = pref - v.astype(jnp.int64) - spec.offset
                    found = tgt >= 0
                else:
                    total = jnp.take(
                        pref, jnp.clip(part_last, 0, cap - 1), mode="clip"
                    )
                    tgt = pref + spec.offset - 1
                    found = pref + spec.offset <= total
                slot = jnp.where(found, part_first + tgt, cap)
                src_row = jnp.take(pos_of, jnp.clip(slot, 0, cap), mode="clip")
                data = jnp.take(d, jnp.clip(src_row, 0, cap - 1), axis=0, mode="clip")
                valid = jnp.logical_and(found, src_row < cap)
                if spec.default_channel is not None:
                    dc = batch.columns[spec.default_channel]
                    dd = jnp.take(dc.data, perm, axis=0, mode="clip")
                    dv = (
                        jnp.take(dc.valid, perm, mode="clip")
                        if dc.valid is not None
                        else jnp.ones(cap, bool)
                    )
                    data = jnp.where(valid, data, dd)
                    valid = jnp.where(valid, valid, dv)
                return Column(
                    data.astype(spec.out_type.np_dtype), spec.out_type,
                    valid, col.dictionary,
                )
            off = spec.offset if name == "lag" else -spec.offset
            src = pos - off
            in_part = jnp.logical_and(
                src >= part_start[safe_pid], src < part_start[safe_pid] + n_in_part
            )
            src_c = jnp.clip(src, 0, cap - 1)
            data = jnp.take(d, src_c, axis=0, mode="clip")
            valid = jnp.logical_and(in_part, jnp.take(v, src_c, mode="clip"))
            if spec.default_channel is not None:
                dc = batch.columns[spec.default_channel]
                dd = jnp.take(dc.data, perm, axis=0, mode="clip")
                dv = (
                    jnp.take(dc.valid, perm, mode="clip")
                    if dc.valid is not None
                    else jnp.ones(cap, bool)
                )
                data = jnp.where(in_part, data, dd)
                valid = jnp.where(in_part, valid, dv)
            return Column(data.astype(spec.out_type.np_dtype), spec.out_type, valid, col.dictionary)
        if name in ("first_value", "last_value", "nth_value"):
            col = batch.columns[spec.arg]
            d = jnp.take(col.data, perm, axis=0, mode="clip")
            v = jnp.take(col.valid, perm, mode="clip") if col.valid is not None else jnp.ones(cap, bool)
            if spec.ignore_nulls:
                # first/last/nth non-null row of the frame [lo, hi] via the
                # same valid-rank table: frame valid count = pref[hi]-pref[lo-1]
                pref, pos_of = self._valid_ranks(
                    v, live, part_first, pos, cap
                )
                before = jnp.where(
                    lo > part_first,
                    jnp.take(pref, jnp.clip(lo - 1, 0, cap - 1), mode="clip"),
                    0,
                )
                upto = jnp.where(
                    frame_n > 0,
                    jnp.take(pref, jnp.clip(hi, 0, cap - 1), mode="clip"),
                    before,
                )
                if name == "first_value":
                    found = upto > before
                    rank0 = before
                elif name == "last_value":
                    found = upto > before
                    rank0 = upto - 1
                else:  # nth_value(x, n): n-th non-null row of the frame
                    found = upto - before >= spec.offset
                    rank0 = before + spec.offset - 1
                slot = jnp.where(found, part_first + rank0, cap)
                src_row = jnp.take(pos_of, jnp.clip(slot, 0, cap), mode="clip")
                return Column(
                    jnp.take(d, jnp.clip(src_row, 0, cap - 1), axis=0, mode="clip")
                    .astype(spec.out_type.np_dtype),
                    spec.out_type,
                    jnp.logical_and(found, src_row < cap),
                    col.dictionary,
                )
            if name == "nth_value":
                src_raw = lo + spec.offset - 1
                in_frame = src_raw <= hi
                src = jnp.clip(src_raw, 0, cap - 1)
                return Column(
                    jnp.take(d, src, axis=0, mode="clip").astype(
                        spec.out_type.np_dtype
                    ),
                    spec.out_type,
                    jnp.logical_and(
                        jnp.logical_and(
                            jnp.take(v, src, mode="clip"), in_frame
                        ),
                        frame_n > 0,
                    ),
                    col.dictionary,
                )
            src = jnp.clip(lo if name == "first_value" else hi, 0, cap - 1)
            return Column(
                jnp.take(d, src, axis=0, mode="clip").astype(spec.out_type.np_dtype),
                spec.out_type,
                jnp.logical_and(jnp.take(v, src, mode="clip"), frame_n > 0),
                col.dictionary,
            )
        # aggregates over the frame
        if name == "count" and spec.arg is None:  # count(*) over (...)
            return Column(frame_n, T.BIGINT, None)
        col = batch.columns[spec.arg]
        d = jnp.take(col.data, perm, axis=0, mode="clip")
        v = live
        if col.valid is not None:
            v = jnp.logical_and(v, jnp.take(col.valid, perm, mode="clip"))
        if name in ("sum", "avg", "count"):
            if d.ndim > 1:
                if name != "count":
                    # long-decimal (two-limb) input: exact frame sums over
                    # limb planes — or, when the planner attached a range
                    # certificate proving every frame sum fits int64, the
                    # single-plane licensed kernel
                    return self._long_decimal_sum_avg(
                        spec, name, d, v, whole, pid, nseg, safe_pid,
                        lo, hi, frame_n, cap,
                    )
                # count reads only the validity mask: a 1-D surrogate keeps
                # the shared sum/count reduction below shape-correct
                d = jnp.zeros(d.shape[0], dtype=jnp.int64)
            dd = jnp.where(v, d, 0).astype(
                jnp.float64 if jnp.issubdtype(d.dtype, jnp.floating) else jnp.int64
            )
            cnt_inc = v.astype(jnp.int64)
            if whole:
                ssum = jax.ops.segment_sum(dd, pid, nseg)[safe_pid]
                scnt = jax.ops.segment_sum(cnt_inc, pid, nseg)[safe_pid]
            else:
                run = jnp.cumsum(dd)
                runc = jnp.cumsum(cnt_inc)
                run_at = lambda r, i: jnp.take(r, jnp.clip(i, 0, cap - 1), mode="clip")
                before = jnp.where(lo > 0, run_at(run, lo - 1), 0)
                beforec = jnp.where(lo > 0, run_at(runc, lo - 1), 0)
                ssum = jnp.where(frame_n > 0, run_at(run, hi) - before, 0)
                scnt = jnp.where(frame_n > 0, run_at(runc, hi) - beforec, 0)
            if name == "count":
                return Column(scnt, T.BIGINT, None)
            if name == "sum":
                return Column(
                    ssum.astype(spec.out_type.np_dtype), spec.out_type, scnt > 0, col.dictionary
                )
            if isinstance(spec.out_type, T.DecimalType):
                # exact integer half-away-from-zero, matching the grouped
                # aggregate's _finalize (jnp.round is half-to-even)
                den = jnp.maximum(scnt, 1)
                sign = jnp.sign(ssum)
                q = jnp.abs(ssum) // den
                r = jnp.abs(ssum) - q * den
                avg = sign * (q + jnp.where(2 * r >= den, 1, 0))
            else:
                avg = ssum.astype(jnp.float64) / jnp.maximum(scnt, 1)
            return Column(avg.astype(spec.out_type.np_dtype), spec.out_type, scnt > 0)
        if name in ("min", "max"):
            if d.ndim > 1:
                raise NotImplementedError(
                    "window min/max over a long-decimal input column "
                    "(cast to decimal(18,s) or double first)"
                )
            return self._minmax(
                spec, name, d, v, whole, pid, nseg, safe_pid, lo, hi,
                frame_n, cap, col,
            )
        raise NotImplementedError(f"window function {name}")

    def _long_decimal_sum_avg(
        self, spec, name, d, v, whole, pid, nseg, safe_pid, lo, hi,
        frame_n, cap,
    ) -> Column:
        """sum/avg over a long-decimal (limb-plane) input column.

        Validity contract: invalid rows are zeroed before every reduction
        (additive identity) and the output plane is scnt > 0 — NULLs can
        never resurface as values (the dropped-validity hazard the
        numeric verifier polices).

        Licensed path: the planner's range certificate (WindowSpec
        .sum_bound, from verify.numeric.license_decimal_sums) proves every
        value AND every frame sum lies inside int64, so the low limb IS
        the value (high limb pure sign extension) and one i64 prefix /
        segment sum is exact — no limb traffic, no runtime check.

        Limb path: exact i128 frame sums.  Whole-partition frames reduce
        via segment_sum128; running frames build prefix sums over the four
        32-bit chunk planes (each prefix stays under cap * 2**32 < 2**63,
        the recombine4 contract) and difference them per frame with a full
        128-bit borrow."""
        from trino_tpu.ops.aggregation import _note_fastpath
        from trino_tpu.types import int128 as i128

        h = jnp.asarray(d[:, 0], jnp.int64)
        l = jnp.asarray(d[:, 1], jnp.int64)
        h = jnp.where(v, h, 0)
        l = jnp.where(v, l, 0)
        cnt_inc = v.astype(jnp.int64)

        def run_at(r, i):
            return jnp.take(r, jnp.clip(i, 0, cap - 1), mode="clip")

        if whole:
            scnt = jax.ops.segment_sum(cnt_inc, pid, nseg)[safe_pid]
        else:
            runc = jnp.cumsum(cnt_inc)
            beforec = jnp.where(lo > 0, run_at(runc, lo - 1), 0)
            scnt = jnp.where(frame_n > 0, run_at(runc, hi) - beforec, 0)

        licensed = (
            spec.sum_bound is not None and spec.sum_bound < (1 << 63) - 1
        )
        if licensed:
            _note_fastpath("proven")
            # |value| <= sum_bound < 2**63: the low limb is the value
            if whole:
                ssum = jax.ops.segment_sum(l, pid, nseg)[safe_pid]
            else:
                run = jnp.cumsum(l)
                before = jnp.where(lo > 0, run_at(run, lo - 1), 0)
                ssum = jnp.where(frame_n > 0, run_at(run, hi) - before, 0)
            sh, sl = i128.widen64(ssum)
        else:
            _note_fastpath("limb")
            if whole:
                sh, sl = i128.segment_sum128(h, l, pid, nseg)
                sh = sh[safe_pid]
                sl = sl[safe_pid]
            else:
                mask32 = jnp.int64(0xFFFFFFFF)
                planes = (l & mask32, (l >> 32) & mask32, h & mask32, h >> 32)
                runs = [jnp.cumsum(p) for p in planes]

                def frame_at(i, present):
                    vals = [
                        jnp.where(present, run_at(r, i), 0) for r in runs
                    ]
                    return i128.recombine4(*vals)

                eh, el = frame_at(hi, frame_n > 0)
                bh, bl = frame_at(lo - 1, jnp.logical_and(frame_n > 0, lo > 0))
                sh, sl = i128.sub128(eh, el, bh, bl)

        if name == "sum":
            if spec.out_type.is_long:
                data = jnp.stack([sh, sl], axis=-1)
            else:
                # a short declared result asserts the values fit: the low
                # limb carries them exactly (same contract as _finalize)
                data = sl
            return Column(data, spec.out_type, scnt > 0)
        # avg: exact integer division, round half away from zero —
        # mirroring _finalize's DecimalAverageAggregation path bit for bit
        den = jnp.maximum(scnt, 1)
        qh, ql, r = i128.divmod128_by_vec(sh, sl, den)
        half = jnp.where(2 * jnp.abs(r) >= den, 1, 0)
        neg = sh < 0
        bump = jnp.where(neg, -half, half)
        qh2, ql2 = i128.add128(qh, ql, bump >> 63, bump)
        if spec.out_type.is_long:
            data = jnp.stack([qh2, ql2], axis=-1)
        else:
            data = ql2
        return Column(data, spec.out_type, scnt > 0)

    def _minmax(
        self, spec, name, d, v, whole, pid, nseg, safe_pid, lo, hi,
        frame_n, cap, col,
    ) -> Column:
        sent = _max_sentinel(d.dtype) if name == "min" else _min_sentinel(d.dtype)
        dd = jnp.where(v, d, sent)
        if whole:
            red = (
                jax.ops.segment_min(dd, pid, nseg)
                if name == "min"
                else jax.ops.segment_max(dd, pid, nseg)
            )[safe_pid]
            cnt = jax.ops.segment_sum(v.astype(jnp.int64), pid, nseg)[safe_pid]
            return Column(red, spec.out_type, cnt > 0, col.dictionary)
        op = jnp.minimum if name == "min" else jnp.maximum
        hi_c = jnp.clip(hi, 0, cap - 1)
        if spec.start_off is not None:
            # bounded sliding min/max: sparse-table range query
            # (O(n log n) build of power-of-two block minima, O(1)
            # two-block query per row — fully vectorized; the TPU-native
            # substitute for the reference's per-row frame re-scan)
            levels = [dd]
            width = 1
            while width < cap:
                prev = levels[-1]
                shifted = jnp.concatenate(
                    [prev[width:], jnp.full(width, sent, dd.dtype)]
                )
                levels.append(op(prev, shifted))
                width *= 2
            table = jnp.stack(levels)  # [L, cap]; level j covers 2^j rows
            length = jnp.maximum(hi - lo + 1, 1)
            j = (
                jnp.floor(jnp.log2(length.astype(jnp.float64)))
            ).astype(jnp.int64)
            j = jnp.clip(j, 0, len(levels) - 1)
            lo_c = jnp.clip(lo, 0, cap - 1)
            start2 = jnp.clip(hi - (jnp.int64(1) << j) + 1, 0, cap - 1)
            flat = table.reshape(-1)
            a_val = jnp.take(flat, j * cap + lo_c, mode="clip")
            b_val = jnp.take(flat, j * cap + start2, mode="clip")
            red = op(a_val, b_val)
        else:
            # running min/max: prefix scan reset at partition starts —
            # cummax over (partition-tagged) values via associative_scan
            def scan_fn(a, b):
                a_pid, a_val = a
                b_pid, b_val = b
                merged = jnp.where(a_pid == b_pid, op(a_val, b_val), b_val)
                return (b_pid, merged)

            _, red = jax.lax.associative_scan(scan_fn, (pid, dd))
            red = jnp.take(red, hi_c, mode="clip")
        runc = jnp.cumsum(v.astype(jnp.int64))
        before = jnp.where(
            lo > 0, jnp.take(runc, jnp.clip(lo - 1, 0, cap - 1), mode="clip"), 0
        )
        cnt = jnp.where(
            frame_n > 0, jnp.take(runc, hi_c, mode="clip") - before, 0
        )
        return Column(red, spec.out_type, cnt > 0, col.dictionary)

    # -- host-side ------------------------------------------------------------

    def _unify_default_dicts(self, batch: Batch) -> Batch:
        """lag/lead defaults must share the argument's dictionary: the kernel
        merges raw codes with jnp.where, so mixed dictionaries would decode
        wrongly (host-side recode, the DictionaryBlock-compaction analog)."""
        from trino_tpu.columnar.dictionary import union_many

        cols = list(batch.columns)
        for spec in self.specs:
            if spec.name not in ("lag", "lead") or spec.default_channel is None:
                continue
            a, d = cols[spec.arg], cols[spec.default_channel]
            if a.dictionary is None and d.dictionary is None:
                continue
            if a.dictionary is d.dictionary or a.dictionary == d.dictionary:
                continue
            if a.dictionary is None or d.dictionary is None:
                raise NotImplementedError(
                    "lag/lead default mixes dictionary and non-dictionary strings"
                )
            merged, (ta, td) = union_many([a.dictionary, d.dictionary])
            for ch, col, table in ((spec.arg, a, ta), (spec.default_channel, d, td)):
                if table is None:
                    cols[ch] = Column(col.data, col.type, col.valid, merged)
                else:
                    cols[ch] = Column(
                        jnp.take(
                            jnp.asarray(table), jnp.asarray(col.data, jnp.int64),
                            mode="clip",
                        ),
                        col.type, col.valid, merged,
                    )
        return batch.with_columns(cols)

    def process(self, stream):
        for b in stream:
            self._acc.append(b)
        if not self._acc:
            return
        big = self._acc[0] if len(self._acc) == 1 else concat_batches(self._acc)
        big = self._unify_default_dicts(big)
        big = _pad_device(big, next_pow2(big.capacity, floor=1))
        yield self._step(big)
