"""Row pattern recognition operator (MATCH_RECOGNIZE).

Reference roles: sql/planner/rowpattern/ (IrRowPattern + Parser),
operator/window/matcher/Matcher.java (the NFA program interpreter) and
PatternRecognitionPartition.

TPU-first split of the work: everything per-row and data-parallel — the
DEFINE predicates, including PREV/NEXT navigation (partition-masked shifts)
— is evaluated ON DEVICE over the whole sorted input in one vectorized pass
per variable.  Only the inherently sequential part (walking the
leftmost-greedy regex over each partition's classification bits) runs on
host, over packed boolean vectors, exactly the part the reference also runs
one-row-at-a-time on the CPU.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.columnar import Batch, Column
from trino_tpu.columnar.batch import device_get_async, concat_batches
from trino_tpu.columnar.dictionary import StringDictionary
from trino_tpu.expr import ExprCompiler
from trino_tpu.expr.compiler import Val, _and_valid
from trino_tpu.expr.functions import register
from trino_tpu.expr.ir import Call, Expr, InputRef, Literal, visit
from trino_tpu.ops.common import SortKey, multi_key_sort_perm, next_pow2


# -- pattern AST + parser ----------------------------------------------------
# grammar (SqlBase.g4 rowPattern, the concatenation/alternation/quantifier
# subset): alt := seq ('|' seq)* ; seq := factor+ ; factor := primary quant? ;
# primary := VAR | '(' alt ')' ; quant := '*' | '+' | '?' | '{' n [',' [m]] '}'


@dataclass
class PVar:
    name: str


@dataclass
class PSeq:
    parts: list


@dataclass
class PAlt:
    options: list


@dataclass
class PQuant:
    child: object
    lo: int
    hi: Optional[int]  # None = unbounded
    greedy: bool = True


def parse_pattern(text: str):
    tokens: list = []
    i = 0
    while i < len(text):
        c = text[i]
        if c.isspace():
            i += 1
        elif c in "()|*+?{}," or c.isdigit():
            tokens.append(c)
            i += 1
        elif c.isalpha() or c == "_":
            j = i
            while j < len(text) and (text[j].isalnum() or text[j] == "_"):
                j += 1
            tokens.append(text[i:j].lower())
            i = j
        else:
            raise ValueError(f"unsupported pattern token {c!r} in {text!r}")
    pos = [0]

    def peek():
        return tokens[pos[0]] if pos[0] < len(tokens) else None

    def take():
        t = peek()
        pos[0] += 1
        return t

    def alt():
        opts = [seq()]
        while peek() == "|":
            take()
            opts.append(seq())
        return opts[0] if len(opts) == 1 else PAlt(opts)

    def seq():
        parts = []
        while peek() is not None and peek() not in ")|":
            parts.append(factor())
        if not parts:
            raise ValueError(f"empty pattern branch in {text!r}")
        return parts[0] if len(parts) == 1 else PSeq(parts)

    def number():
        ds = ""
        while peek() is not None and peek().isdigit():
            ds += take()
        if not ds:
            raise ValueError(f"expected number in quantifier of {text!r}")
        return int(ds)

    def factor():
        t = take()
        if t == "(":
            node = alt()
            if take() != ")":
                raise ValueError(f"unbalanced parens in {text!r}")
        elif t is not None and (t[0].isalpha() or t[0] == "_"):
            node = PVar(t)
        else:
            raise ValueError(f"unexpected {t!r} in pattern {text!r}")
        q = peek()
        if q == "*":
            take()
            return PQuant(node, 0, None)
        if q == "+":
            take()
            return PQuant(node, 1, None)
        if q == "?":
            take()
            return PQuant(node, 0, 1)
        if q == "{":
            take()
            lo = number()
            hi: Optional[int] = lo
            if peek() == ",":
                take()
                hi = number() if peek() is not None and peek().isdigit() else None
            if take() != "}":
                raise ValueError(f"unbalanced {{}} in {text!r}")
            return PQuant(node, lo, hi)
        return node

    out = alt()
    if pos[0] != len(tokens):
        raise ValueError(f"trailing pattern input in {text!r}")
    return out


def pattern_variables(node, acc=None) -> list:
    if acc is None:
        acc = []
    if isinstance(node, PVar):
        if node.name not in acc:
            acc.append(node.name)
    elif isinstance(node, PSeq):
        for p in node.parts:
            pattern_variables(p, acc)
    elif isinstance(node, PAlt):
        for p in node.options:
            pattern_variables(p, acc)
    elif isinstance(node, PQuant):
        pattern_variables(node.child, acc)
    return acc


# -- matcher -----------------------------------------------------------------


def _match_from(node, i: int, end: int, ok, var_ix: dict, labels: list):
    """Generator of end positions for matching `node` starting at row i,
    in regex preference order (greedy quantifiers try longest first).
    `labels` accumulates the classifier per consumed row; generators restore
    it on backtrack."""
    if isinstance(node, PVar):
        v = var_ix[node.name]
        if i < end and ok[v, i]:
            labels.append(node.name)
            yield i + 1
            labels.pop()
        return
    if isinstance(node, PSeq):
        yield from _match_seq(node.parts, 0, i, end, ok, var_ix, labels)
        return
    if isinstance(node, PAlt):
        for opt in node.options:
            yield from _match_from(opt, i, end, ok, var_ix, labels)
        return
    if isinstance(node, PQuant):
        yield from _match_quant(node, i, end, ok, var_ix, labels, 0)
        return
    raise TypeError(node)


def _match_seq(parts, k, i, end, ok, var_ix, labels):
    if k == len(parts):
        yield i
        return
    for j in _match_from(parts[k], i, end, ok, var_ix, labels):
        mark = len(labels)
        yield from _match_seq(parts, k + 1, j, end, ok, var_ix, labels)
        del labels[mark:]


def _match_quant(node, i, end, ok, var_ix, labels, count):
    """Greedy: consume as many repetitions as possible first; `count` is
    repetitions consumed so far."""
    if node.hi is None or count < node.hi:
        for j in _match_from(node.child, i, end, ok, var_ix, labels):
            if j == i:
                break  # zero-width repetition guard
            mark = len(labels)
            yield from _match_quant(node, j, end, ok, var_ix, labels, count + 1)
            del labels[mark:]
    if count >= node.lo:
        yield i


# -- navigation functions (device) -------------------------------------------


@register("$nav_prev")
def _nav_prev(ctx, call, v, n, pid):
    k = int(np.asarray(n.data))
    cap = ctx.capacity
    data = jnp.broadcast_to(jnp.asarray(v.data), (cap,) + jnp.shape(v.data)[1:])
    idx = jnp.arange(cap, dtype=jnp.int64) - k
    src = jnp.clip(idx, 0, cap - 1)
    out = jnp.take(data, src, axis=0)
    same = jnp.logical_and(
        idx >= 0,
        jnp.take(jnp.asarray(pid.data), src) == jnp.asarray(pid.data),
    )
    valid = _and_valid(
        None if v.valid is None else jnp.take(jnp.asarray(v.valid), src), same
    )
    return Val(out, valid, call.type, v.dictionary)


@register("$nav_next")
def _nav_next(ctx, call, v, n, pid):
    k = int(np.asarray(n.data))
    cap = ctx.capacity
    data = jnp.broadcast_to(jnp.asarray(v.data), (cap,) + jnp.shape(v.data)[1:])
    idx = jnp.arange(cap, dtype=jnp.int64) + k
    src = jnp.clip(idx, 0, cap - 1)
    out = jnp.take(data, src, axis=0)
    same = jnp.logical_and(
        idx < cap,
        jnp.take(jnp.asarray(pid.data), src) == jnp.asarray(pid.data),
    )
    valid = _and_valid(
        None if v.valid is None else jnp.take(jnp.asarray(v.valid), src), same
    )
    return Val(out, valid, call.type, v.dictionary)


# -- operator ----------------------------------------------------------------


class PatternRecognitionOperator:
    """Materialize -> device sort -> device DEFINE bools -> host NFA ->
    host-built output batch."""

    def __init__(
        self,
        node,  # P.PatternRecognitionNode
        source_symbols: list,
    ):
        self.node = node
        self.source_symbols = list(source_symbols)
        self.pattern = parse_pattern(node.pattern)
        # variables without a DEFINE entry match any row (the reference's
        # implicit TRUE definition) — `ok` starts all-true in process()
        self.vars = pattern_variables(self.pattern)

    def _channel(self, name: str) -> int:
        for i, s in enumerate(self.source_symbols):
            if s.name == name:
                return i
        raise KeyError(name)

    def process(self, stream):
        batches = list(stream)
        if not batches:
            return
        big = concat_batches(batches) if len(batches) > 1 else batches[0]
        n = big.num_rows_host()
        if n == 0:
            return
        cap = next_pow2(n, floor=1)
        big = jax.jit(Batch.compact_device, static_argnames=("out_capacity",))(
            big, out_capacity=cap
        )
        node = self.node
        keys = [SortKey(self._channel(s.name)) for s in node.partition_by] + [
            SortKey(self._channel(s.name), ascending=asc, nulls_first=nf)
            for s, asc, nf in node.order_by
        ]
        if keys:
            perm = multi_key_sort_perm(big, keys)
            live = jnp.take(big.mask(), perm, mode="clip")
            big = big.gather(perm, valid=live)
        host = device_get_async(big)  # lint: allow(host-transfer)
        live_h = np.asarray(host.mask())[:n]
        # partition ids from sorted partition-key runs: a new partition
        # starts wherever ANY key's (value, validity) changes — collision
        # free, null-safe (the sorted-run analog of group_ids_from_sorted)
        change = np.zeros(n, dtype=bool)
        for s in node.partition_by:
            c = host.columns[self._channel(s.name)]
            d = np.asarray(c.data)[:n]
            change[1:] |= d[1:] != d[:-1]
            if c.valid is not None:
                v = np.asarray(c.valid)[:n]
                change[1:] |= v[1:] != v[:-1]
        pid = np.cumsum(change)
        # DEFINE bools on device: rewrite prev/next -> $nav calls with the
        # pid channel appended.  Padded dead slots get pid -1 so navigation
        # never treats them as in-partition (compact_device fills dead rows
        # with row 0's data).
        pid_col = Column(
            jnp.asarray(
                np.pad(pid, (0, cap - n), constant_values=-1)
            ),
            T.BIGINT,
        )
        dev = Batch(list(big.columns) + [pid_col], big.row_mask)
        pid_ch = len(big.columns)

        def rewrite_nav(e: Expr) -> Expr:
            def fn(x: Expr) -> Expr:
                if isinstance(x, Call) and x.name in ("prev", "next"):
                    arg = x.args[0]
                    k = (
                        x.args[1]
                        if len(x.args) > 1
                        else Literal(1, T.BIGINT)
                    )
                    return Call(
                        "$nav_prev" if x.name == "prev" else "$nav_next",
                        [arg, k, InputRef(pid_ch, T.BIGINT)],
                        x.type,
                    )
                return x

            return visit(e, fn)

        ok = np.ones((len(self.vars), n), dtype=bool)
        defines = dict(self.node.defines)
        compiler = ExprCompiler(dev)
        for vi, v in enumerate(self.vars):
            cond = defines.get(v)
            if cond is None:
                continue
            mask = compiler.filter_mask(rewrite_nav(cond))
            ok[vi] = np.asarray(device_get_async(mask))[:n]  # lint: allow(host-transfer)
        ok &= live_h[None, :]
        var_ix = {v: i for i, v in enumerate(self.vars)}
        # host NFA walk per partition
        yield from self._emit(host, n, pid, ok, var_ix)

    # -- matching + output ----------------------------------------------------

    def _emit(self, host: Batch, n: int, pid, ok, var_ix):
        node = self.node
        starts = np.flatnonzero(
            np.concatenate(([True], pid[1:] != pid[:-1]))
        ) if n else np.array([], dtype=np.int64)
        bounds = list(starts) + [n]
        matches = []  # (start, end, labels list, match_number)
        for b in range(len(bounds) - 1):
            lo, hi = bounds[b], bounds[b + 1]
            i = lo
            mno = 0  # MATCH_NUMBER() restarts per partition (SQL-2016)
            while i < hi:
                labels: list = []
                got = None
                for end in _match_from(
                    self.pattern, i, hi, ok, var_ix, labels
                ):
                    got = (end, list(labels))
                    break
                if got is not None and got[0] > i:
                    mno += 1
                    matches.append((i, got[0], got[1], mno))
                    i = got[0] if node.after_match == "past_last" else i + 1
                else:
                    i += 1
        yield self._build_output(host, matches)

    def _measure_values(self, host, s0, e0, labels, mno):
        out = []
        for _sym, m in self.node.measures:
            if m.kind == "match_number":
                out.append(mno)
                continue
            if m.kind == "classifier":
                out.append(labels[-1] if labels else None)
                continue
            if m.kind == "agg" and m.source is None:  # count(*)
                out.append(e0 - s0)
                continue
            rows = range(s0, e0)
            if m.var is not None:
                rows = [
                    r for r, lab in zip(range(s0, e0), labels) if lab == m.var
                ]
            ch = self._channel(m.source.name)
            col = host.columns[ch]
            data = np.asarray(col.data)
            valid = None if col.valid is None else np.asarray(col.valid)

            def decode(r):
                if valid is not None and not valid[r]:
                    return None
                v = data[r]
                if col.dictionary is not None:
                    return col.dictionary.values[int(v)]
                return v

            vals = [decode(r) for r in rows]
            if m.kind in ("first", "last"):
                ix = m.offset if m.kind == "first" else len(vals) - 1 - m.offset
                out.append(vals[ix] if 0 <= ix < len(vals) else None)
                continue
            live_vals = [v for v in vals if v is not None]
            if m.agg == "count":
                out.append(len(live_vals))
            elif not live_vals:
                out.append(None)
            elif m.agg == "sum":
                out.append(sum(live_vals))
            elif m.agg == "min":
                out.append(min(live_vals))
            elif m.agg == "max":
                out.append(max(live_vals))
            elif m.agg == "avg":
                out.append(float(sum(live_vals)) / len(live_vals))
            else:
                raise NotImplementedError(f"measure agg {m.agg}")
        return out

    def _build_output(self, host: Batch, matches) -> Batch:
        node = self.node
        one = node.rows_per_match == "one"
        rows_out: list = []  # parallel lists per output column
        out_syms = node.outputs
        per_col: list = [[] for _ in out_syms]
        for (s0, e0, labels, mno) in matches:
            measures = self._measure_values(host, s0, e0, labels, mno)
            if one:
                head = [
                    self._host_value(host, self._channel(s.name), s0)
                    for s in node.partition_by
                ]
                for ci, v in enumerate(head + measures):
                    per_col[ci].append(v)
            else:
                for off, r in enumerate(range(s0, e0)):
                    row_measures = list(measures)
                    # per-row classifier under ALL ROWS PER MATCH
                    for mi, (_s, m) in enumerate(node.measures):
                        if m.kind == "classifier":
                            row_measures[mi] = labels[off]
                    head = [
                        self._host_value(host, ci, r)
                        for ci in range(len(self.source_symbols))
                    ]
                    for ci, v in enumerate(head + row_measures):
                        per_col[ci].append(v)
        cols = []
        for sym, values in zip(out_syms, per_col):
            cols.append(_column_from_python(sym.type, values))
        cap = len(per_col[0]) if per_col else 0
        return Batch(cols, None if cap else np.zeros(0, dtype=bool))

    def _host_value(self, host: Batch, ch: int, row: int):
        col = host.columns[ch]
        if col.valid is not None and not np.asarray(col.valid)[row]:
            return None
        v = np.asarray(col.data)[row]
        if col.dictionary is not None:
            return col.dictionary.values[int(v)]
        return v


def _column_from_python(t: T.Type, values: list) -> Column:
    if T.is_string_kind(t):
        return Column.from_strings(values, t)
    arr = np.zeros(len(values), dtype=t.np_dtype)
    valid = np.ones(len(values), dtype=bool)
    for i, v in enumerate(values):
        if v is None:
            valid[i] = False
        else:
            arr[i] = v
    return Column(
        arr, t, None if valid.all() else valid, None
    )
