"""Pallas blocked gather-probe kernel for the join inner loop.

Reference role (SURVEY §7): Trino specializes its probe inner loop per
join signature with PagesHash bytecode generation; here the same
specialization is a Pallas kernel.  The lexicographically sorted build
canon stays resident across grid steps while each step runs the
lower/upper-bound binary search for one probe block — log2(cap_b)+1
fixed iterations, no data-dependent control flow, semantics identical to
`ops.join._locate_sorted` (the XLA probe), which stays the fallback and
the test oracle.

Scope: single-plane integer canon keys only — limb-coded (long-decimal)
keys keep the XLA path; the runner gates per join.  On non-TPU backends
the kernel runs in interpreter mode, so CPU meshes (tier-1) execute the
same program text without a TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

#: probe rows per grid step.  Probe capacities are pow2 buckets, so any
#: pow2 block evenly tiles them; 1024 keeps the per-step working set
#: (block state + whole build canon) comfortably VMEM-sized for the
#: build capacities the knob gate admits.
_BLOCK = 1024


def _probe_kernel(nm_ref, build_ref, probe_ref, nomatch_ref, start_ref,
                  count_ref, *, iters: int):
    nm = nm_ref[0]
    bk = build_ref[...]
    pk = probe_ref[...]
    n = pk.shape[0]

    def bounds(le: bool):
        lo0 = jnp.zeros(n, dtype=jnp.int64)
        hi0 = jnp.full(n, nm, dtype=jnp.int64)

        def body(_, st):
            lo, hi = st
            active = lo < hi
            mid = (lo + hi) >> 1
            bv = jnp.take(bk, mid, mode="clip")
            go_right = (bv <= pk) if le else (bv < pk)
            lo2 = jnp.where(go_right, mid + 1, lo)
            hi2 = jnp.where(go_right, hi, mid)
            return jnp.where(active, lo2, lo), jnp.where(active, hi2, hi)

        lo, _ = jax.lax.fori_loop(0, iters, body, (lo0, hi0))
        return lo

    lo = bounds(False)
    hi = bounds(True)
    nomatch = nomatch_ref[...]
    zero = jnp.zeros_like(lo)
    start_ref[...] = jnp.where(nomatch, zero, lo)
    count_ref[...] = jnp.where(nomatch, zero, hi - lo)


@functools.partial(jax.jit, static_argnames=("cap_b", "interpret", "block"))
def locate_sorted_pallas(build_canon, n_match, probe_canon, probe_nomatch,
                         cap_b: int, interpret: bool = False,
                         block: int = _BLOCK):
    """Drop-in for `ops.join._locate_sorted` on a SINGLE canon plane:
    per probe row, (start, count) of its matching run in sorted-build row
    space.  `build_canon`/`probe_canon` are the bare int64 plane arrays
    (not one-element lists)."""
    p_cap = probe_canon.shape[0]
    blk = min(block, p_cap)
    iters = max(1, int(cap_b).bit_length())
    nm = jnp.asarray(n_match, dtype=jnp.int64).reshape(1)
    start, count = pl.pallas_call(
        functools.partial(_probe_kernel, iters=iters),
        grid=(p_cap // blk,),
        in_specs=[
            pl.BlockSpec((1,), lambda i: (0,)),
            pl.BlockSpec((build_canon.shape[0],), lambda i: (0,)),
            pl.BlockSpec((blk,), lambda i: (i,)),
            pl.BlockSpec((blk,), lambda i: (i,)),
        ],
        out_specs=[
            pl.BlockSpec((blk,), lambda i: (i,)),
            pl.BlockSpec((blk,), lambda i: (i,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((p_cap,), jnp.int64),
            jax.ShapeDtypeStruct((p_cap,), jnp.int64),
        ],
        interpret=interpret,
    )(nm, build_canon, probe_canon, probe_nomatch)
    return start, count


def probe_kernel_eligible(build_canon, probe_canon) -> bool:
    """Single-plane integer canon on both sides (the kernel's scope)."""
    return (
        len(build_canon) == 1
        and len(probe_canon) == 1
        and build_canon[0].ndim == 1
        and probe_canon[0].ndim == 1
        and jnp.issubdtype(build_canon[0].dtype, jnp.integer)
        and jnp.issubdtype(probe_canon[0].dtype, jnp.integer)
    )
