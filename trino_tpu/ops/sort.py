"""Ordering operators (reference: OrderByOperator.java, TopNOperator.java:35,
LimitOperator.java, DistinctLimitOperator.java).

TopN keeps a bounded device state: each pushed batch is merged with the
current top-N candidates and re-truncated — the TPU analog of the reference's
TopNProcessor heap, with `lax.sort` doing the heap's job (SURVEY.md §7 maps
TopNOperator to top_k/sort).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from trino_tpu.columnar import Batch
from trino_tpu.columnar.batch import concat_batches
from trino_tpu.ops.common import SortKey, multi_key_sort_perm, next_pow2
from trino_tpu.ops.aggregation import _pad_device


#: shared jitted steps across per-query instances (see filter_project)
_STEP_CACHE: dict = {}


class OrderByOperator:
    """Full materialized sort; emits one sorted, compacted batch."""

    def __init__(self, keys: Sequence[SortKey], memory_ctx=None,
                 spill_factory=None, observer=None):
        self.keys = list(keys)
        self.memory_ctx = memory_ctx
        #: lazy filesystem-SPI spill store (runtime/spill.SpillManager)
        #: for over-budget runs; None / factory-returns-None = host RAM
        self._spill_factory = spill_factory
        self._spiller = None
        self._spiller_made = False
        self._spill_runs = 0
        self.observer = observer
        self._acc: list[Batch] = []
        key = ("orderby", tuple(keys))
        if key not in _STEP_CACHE:
            _STEP_CACHE[key] = jax.jit(self._sort_step)
        self._step = _STEP_CACHE[key]

    def _sort_step(self, batch: Batch) -> Batch:
        perm = multi_key_sort_perm(batch, self.keys)
        live = jnp.take(batch.mask(), perm, mode="clip")
        return batch.gather(perm, valid=live)

    def _get_spiller(self):
        if not self._spiller_made:
            self._spiller_made = True
            if self._spill_factory is not None:
                self._spiller = self._spill_factory()
        return self._spiller

    def _spill_chunk(self) -> object:
        """Compact the accumulated batches to live rows and move them OFF
        device as one spill run — to the filesystem SPI when a spiller is
        attached (reference: GenericSpiller in OrderByOperator.java's
        revoke path), host RAM otherwise.  Runs are NOT per-run sorted:
        the finish-time merge is a full host lexsort, so a per-run device
        sort would be thrown-away work; the single-run case re-sorts on
        device at finish.  Returns the host run, or an int disk-run id."""
        from trino_tpu.columnar.batch import device_get_async

        big = self._acc[0] if len(self._acc) == 1 else concat_batches(self._acc)
        self._acc.clear()
        n = big.num_rows_host()
        cap = next_pow2(max(n, 1), floor=1)
        ckey = ("spill_compact",)
        if ckey not in _STEP_CACHE:
            _STEP_CACHE[ckey] = jax.jit(
                Batch.compact_device, static_argnames=("out_capacity",)
            )
        compact = _STEP_CACHE[ckey](big, out_capacity=cap)
        host = device_get_async(compact)  # lint: allow(host-transfer)
        spiller = self._get_spiller()
        if spiller is None:
            return host
        run = self._spill_runs
        self._spill_runs += 1
        spiller.save("run", run, [host])
        return run

    def _load_runs(self, runs: list) -> list:
        """Rehydrate disk-run ids back to host batches (in-RAM runs pass
        through).  The merge is ONE vectorized host lexsort over all runs,
        so host-RAM peak at finish equals the in-RAM staging path — the
        SPI spill buys DEVICE residency (runs leave HBM as they form) and
        the object-store-ready storage seam, not a host peak reduction;
        an incremental k-way merge is the follow-up that would."""
        spiller = self._spiller
        return [
            spiller.load("run", r)[0] if isinstance(r, int) else r
            for r in runs
        ]

    def process(self, stream):
        """In-memory device sort; over budget, fall back to an EXTERNAL sort
        (reference: OrderingCompiler + spiller/ GenericSpiller usage in
        OrderByOperator.java — revoke memory by spilling runs, sort at
        finish).  Spill runs live UNSORTED in host RAM; the finish step is
        one vectorized host lexsort over all runs (the merge exchange's
        kernel), so device memory stays bounded by one chunk."""
        from trino_tpu.runtime.memory import (
            ExceededMemoryLimitException,
            batches_bytes,
        )

        runs: list = []
        try:
            for b in stream:
                self._acc.append(b)
                if self.memory_ctx is not None:
                    # recomputed over the accumulation so a dictionary
                    # shared by every batch is counted once, not per batch
                    try:
                        self.memory_ctx.set_bytes(batches_bytes(self._acc))
                    except ExceededMemoryLimitException:
                        runs.append(self._spill_chunk())
                        self.memory_ctx.set_bytes(0)
            if not self._acc and not runs:
                return
            if not runs:
                big = self._acc[0] if len(self._acc) == 1 else concat_batches(self._acc)
                big = _pad_device(big, next_pow2(big.capacity, floor=1))
                out = self._step(big)
                if self.memory_ctx is not None:
                    self.memory_ctx.close()
                yield out
                return
            if self._acc:
                runs.append(self._spill_chunk())
            if self.observer is not None:
                # external-sort waves: one run merged per pass slice
                self.observer.waves("sort", len(runs))
            runs = self._load_runs(runs)
            if len(runs) == 1:
                # one run = the budget tripped at the very end; a device sort
                # of the whole set is what the in-memory path would have done
                big = jax.device_put(runs[0])
                out = self._step(_pad_device(big, next_pow2(big.capacity, floor=1)))
                if self.memory_ctx is not None:
                    self.memory_ctx.close()
                yield out
                return
            from trino_tpu.ops.merge import merge_sorted_shards

            runs = _unify_host_dictionaries(runs)
            out = merge_sorted_shards(runs, self.keys)
            if self.memory_ctx is not None:
                self.memory_ctx.close()
            yield out
        finally:
            if self._spiller is not None:
                self._spiller.close()


class TopNOperator:
    def __init__(self, keys: Sequence[SortKey], n: int):
        self.keys = list(keys)
        self.n = n
        self._state: Optional[Batch] = None
        key = ("topn", tuple(keys), n)
        if key not in _STEP_CACHE:
            _STEP_CACHE[key] = jax.jit(self._merge_step, static_argnames=("out_cap",))
        self._step = _STEP_CACHE[key]

    def _merge_step(self, batch: Batch, out_cap: int) -> Batch:
        perm = multi_key_sort_perm(batch, self.keys)
        live = jnp.take(batch.mask(), perm, mode="clip")
        # keep only first n live rows
        rank = jnp.cumsum(live) - 1
        keep = jnp.logical_and(live, rank < self.n)
        out = batch.gather(perm, valid=keep)
        return _truncate(out, out_cap)

    def process(self, stream):
        out_cap = next_pow2(self.n, floor=1)
        for b in stream:
            if self._state is not None:
                b = concat_batches([self._state, b])
            b = _pad_device(b, next_pow2(b.capacity, floor=1))
            self._state = self._step(b, out_cap=out_cap)
        if self._state is not None:
            yield self._state


class LimitOperator:
    """LIMIT/OFFSET without ordering; truncates the stream host-side
    (reference: LimitOperator.java + OffsetOperator.java).  count=None
    means OFFSET-only (skip, keep the rest)."""

    def __init__(self, n, offset: int = 0):
        self.n = n
        self.offset = offset

    def process(self, stream):
        skip = self.offset
        remaining = self.n  # None = unlimited
        for b in stream:
            if remaining is not None and remaining <= 0:
                break
            cnt = b.num_rows_host()
            if skip >= cnt:
                skip -= cnt
                continue
            if skip > 0 or (remaining is not None and cnt - skip > remaining):
                live = b.mask()
                rank = jnp.cumsum(live) - 1
                keep = jnp.logical_and(live, rank >= skip)
                if remaining is not None:
                    keep = jnp.logical_and(keep, rank < skip + remaining)
                    remaining -= min(cnt - skip, remaining)
                yield b.filter(keep)
                skip = 0
            else:
                remaining = None if remaining is None else remaining - (cnt - skip)
                skip = 0
                yield b


def _unify_host_dictionaries(runs: list) -> list:
    """Spill runs from different scan batches may carry per-run
    dictionaries; recode every string channel into one union dictionary so
    the merge's code comparisons are rank comparisons again."""
    import numpy as np

    from trino_tpu.columnar import Column
    from trino_tpu.columnar.dictionary import union_many

    if not runs:
        return runs
    width = runs[0].width
    out = [list(r.columns) for r in runs]
    for ch in range(width):
        dicts = [r.columns[ch].dictionary for r in runs]
        if not any(d is not None for d in dicts):
            continue
        merged, tables = union_many(dicts)
        for i, table in enumerate(tables):
            c = out[i][ch]
            data = np.asarray(c.data)
            if table is not None:
                data = np.asarray(table)[np.clip(data.astype(np.int64), 0, len(table) - 1)]
            out[i][ch] = Column(data, c.type, c.valid, merged, c.lengths)
    return [Batch(cols, r.row_mask) for cols, r in zip(out, runs)]


def _truncate(batch: Batch, cap: int) -> Batch:
    """Slice the leading `cap` rows (used after sorts put keepers first)."""
    from trino_tpu.columnar import Column

    cols = [
        Column(
            c.data[:cap],
            c.type,
            None if c.valid is None else c.valid[:cap],
            c.dictionary,
            None if c.lengths is None else c.lengths[:cap],
        )
        for c in batch.columns
    ]
    return Batch(cols, batch.mask()[:cap])
