"""Join operators (reference: operator/join/* — HashBuilderOperator.java,
LookupJoinOperator.java + JoinProbe, NestedLoopJoinOperator.java,
HashSemiJoinOperator via SetBuilderOperator).

TPU substitution (SURVEY.md §7): no per-row open-addressing probe.  The build
side is materialized dense; each probe batch is joined by a *combined
lexicographic sort* of build+probe keys (side as the least-significant key so
build rows lead each key group), group-boundary detection, and a cumsum-based
row expansion — all static-shape XLA.  Output capacity is data-dependent, so
the match count is computed in a first jitted phase, pulled to host, bucketed
to a power of two, and the expansion phase is jitted per bucket (the analog of
the reference's page-size-bounded join output building).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.columnar import Batch, Column
from trino_tpu.columnar.batch import concat_batches
from trino_tpu.ops.common import SortKey, group_ids_from_sorted, multi_key_sort_perm, next_pow2


def _dense_build(batches: list[Batch], types: Sequence[T.Type]) -> tuple[Batch, int]:
    """Materialize the build side: concat + compact to pow2(live)."""
    if not batches:
        cols = [Column(np.zeros(1, dtype=t.np_dtype), t, np.zeros(1, dtype=bool)) for t in types]
        return Batch(cols, np.zeros(1, dtype=bool)), 0
    big = batches[0] if len(batches) == 1 else concat_batches(batches)
    n = big.num_rows_host()
    cap = next_pow2(max(n, 1), floor=1)
    return jax.jit(Batch.compact_device, static_argnames=("out_capacity",))(
        big, out_capacity=cap
    ), n


def _match_live(batch: Batch, key_channels) -> jnp.ndarray:
    """Rows eligible for equi-matching: live AND no NULL key (SQL `=` never
    matches NULL)."""
    live = batch.mask()
    for ch in key_channels:
        v = batch.columns[ch].valid
        if v is not None:
            live = jnp.logical_and(live, v)
    return live


#: process-level jitted-step cache (cross-query reuse; see filter_project).
#: CONTRACT: a cached step must read NO per-query state off `self` — only
#: configuration captured in its cache key; per-query data (the build batch,
#: null flags) is passed as explicit arguments.
_STEP_CACHE: dict = {}


def _jit_cached(key, factory):
    if key is None:
        return factory()
    if key not in _STEP_CACHE:
        _STEP_CACHE[key] = factory()
    return _STEP_CACHE[key]


class _CombinedSortJoinBase:
    """Shared machinery: locate, for every probe row, the contiguous run of
    matching build rows via one combined sort."""

    def __init__(self, probe_key_channels, build_key_channels):
        self.probe_keys = list(probe_key_channels)
        self.build_keys = list(build_key_channels)
        self._locate = _jit_cached(
            ("locate", len(self.build_keys)),
            lambda: jax.jit(self._locate_step, static_argnames=("cap_b",)),
        )

    def _combined_keys(self, build: Batch, probe: Batch) -> Batch:
        """Host-side: key columns of both sides under one (union) dictionary."""
        bk = Batch([build.columns[c] for c in self.build_keys], _match_live(build, self.build_keys))
        pk = Batch([probe.columns[c] for c in self.probe_keys], _match_live(probe, self.probe_keys))
        return concat_batches([bk, pk])

    def _locate_step(self, combined: Batch, cap_b: int):
        """Returns, per probe slot: (match_start, match_count) in combined
        space, plus the sort permutation mapping sorted pos -> combined row."""
        total = combined.capacity
        nkeys = len(self.build_keys)
        side = (jnp.arange(total, dtype=jnp.int64) >= cap_b).astype(jnp.int8)
        sortable = combined.append_column(Column(side, T.TINYINT, None))
        keys = [SortKey(i) for i in range(nkeys)] + [SortKey(nkeys)]
        perm = multi_key_sort_perm(sortable, keys)
        gid, _, _ = group_ids_from_sorted(combined, perm, list(range(nkeys)))
        live_sorted = jnp.take(combined.mask(), perm, mode="clip")
        is_build = jnp.logical_and(live_sorted, jnp.take(side, perm, mode="clip") == 0)
        pos = jnp.arange(total, dtype=jnp.int64)
        cnt_b = jax.ops.segment_sum(is_build.astype(jnp.int64), gid, total)
        first = jax.ops.segment_min(jnp.where(live_sorted, pos, total), gid, total)
        inv = jnp.zeros(total, dtype=jnp.int64).at[perm].set(pos)
        probe_pos = inv[cap_b:]
        g = gid[probe_pos]
        probe_live = combined.mask()[cap_b:]
        count = jnp.where(probe_live, cnt_b[g], 0)
        start = jnp.where(probe_live, first[g], 0)
        return start, count, perm


class HashJoinOperator(_CombinedSortJoinBase):
    """Equi join. Probe = left side (streamed), build = right (materialized);
    output columns = probe columns ++ build columns (reference: JoinNode output
    = left ++ right, build on right per LocalExecutionPlanner.visitJoin).

    kind: inner | left | full.  (right joins are planned as flipped left
    joins; cross joins use NestedLoopJoinOperator.)
    """

    def __init__(
        self,
        kind: str,
        probe_key_channels: Sequence[int],
        build_key_channels: Sequence[int],
        build_types: Sequence[T.Type],
        probe_types: Sequence[T.Type] = (),
        residual=None,
        residual_key=None,
    ):
        """`residual`: optional fn(candidate Batch: probe++build cols) -> bool
        mask, the non-equi join conjuncts (reference: JoinNode.filter /
        JoinFilterFunctionCompiler).  Outer-join semantics: a probe row whose
        matches all fail the residual still emits one null-padded row.
        `residual_key`: hashable identity of the residual (e.g. the expr key)
        enabling cross-query reuse of the jitted expand step."""
        assert kind in ("inner", "left", "full")
        super().__init__(probe_key_channels, build_key_channels)
        self.kind = kind
        self.build_types = list(build_types)
        self._probe_types_cache = list(probe_types)
        self.residual = residual
        self.build: Optional[Batch] = None
        self._build_rows = 0
        self._build_matched = None  # bool[cap_b], for full outer
        cache_key = None
        if residual is None or residual_key is not None:
            cache_key = (
                "expand", kind, tuple(self.probe_keys), tuple(self.build_keys),
                tuple(t.name for t in self.build_types), residual_key,
            )
        self._expand = _jit_cached(
            cache_key, lambda: jax.jit(
                self._expand_step, static_argnames=("out_cap", "cap_b")
            )
        )

    def set_build(self, batches: list[Batch]) -> None:
        self.build, self._build_rows = _dense_build(batches, self.build_types)
        if self.kind == "full":
            self._build_matched = jnp.zeros(self.build.capacity, dtype=bool)

    def _expand_step(
        self, probe: Batch, build: Batch, start, count, perm, build_matched,
        out_cap: int, cap_b: int, total_emit
    ):
        emit = count if self.kind == "inner" else jnp.where(probe.mask(), jnp.maximum(count, 1), 0)
        offsets = jnp.cumsum(emit) - emit
        cap_p = probe.capacity
        has = emit > 0
        seed = (
            jnp.zeros(out_cap, dtype=jnp.int64)
            .at[jnp.where(has, offsets, out_cap)]
            .max(jnp.arange(cap_p, dtype=jnp.int64), mode="drop")
        )
        ids = jax.lax.cummax(seed)  # out slot -> probe slot
        j = jnp.arange(out_cap, dtype=jnp.int64) - offsets[ids]
        matched = j < count[ids]
        build_pos = jnp.clip(start[ids] + j, 0, perm.shape[0] - 1)
        build_row = jnp.clip(perm[build_pos], 0, cap_b - 1)
        out_live = jnp.arange(out_cap, dtype=jnp.int64) < total_emit
        pcols = [
            Column(
                jnp.take(c.data, ids, mode="clip"),
                c.type,
                None if c.valid is None else jnp.take(c.valid, ids, mode="clip"),
                c.dictionary,
            )
            for c in probe.columns
        ]
        bvalid_base = jnp.logical_and(matched, out_live)
        bcols = [
            Column(
                jnp.take(c.data, build_row, mode="clip"),
                c.type,
                bvalid_base
                if c.valid is None
                else jnp.logical_and(bvalid_base, jnp.take(c.valid, build_row, mode="clip")),
                c.dictionary,
            )
            for c in build.columns
        ]
        keep_match = jnp.logical_and(matched, out_live)
        if self.residual is not None:
            candidate = Batch(list(pcols) + list(bcols), out_live)
            keep_match = jnp.logical_and(keep_match, self.residual(candidate))
            if self.kind == "inner":
                out_live = keep_match
            else:
                # probe rows with emitted matches but zero residual survivors
                # degrade their first slot to an unmatched (null-build) row
                surv = jax.ops.segment_sum(
                    keep_match.astype(jnp.int64), ids, probe.capacity
                )
                to_null = jnp.logical_and(
                    jnp.logical_and(j == 0, surv[ids] == 0), out_live
                )
                out_live = jnp.logical_and(out_live, jnp.logical_or(keep_match, to_null))
                bcols = [
                    Column(
                        c.data,
                        c.type,
                        jnp.logical_and(
                            keep_match, c.valid if c.valid is not None else True
                        ),
                        c.dictionary,
                    )
                    for c in bcols
                ]
        new_matched = None
        if self.kind == "full":
            new_matched = build_matched.at[
                jnp.where(keep_match, build_row, cap_b)
            ].set(True, mode="drop")
        return Batch(list(pcols) + list(bcols), out_live), new_matched

    def _join_batch(self, probe: Batch) -> Batch:
        cap_b = self.build.capacity
        combined = self._combined_keys(self.build, probe)
        start, count, perm = self._locate(combined, cap_b=cap_b)
        if self.kind == "inner":
            total = int(jnp.sum(count))
        else:
            total = int(jnp.sum(jnp.where(probe.mask(), jnp.maximum(count, 1), 0)))
        out_cap = next_pow2(max(total, 1), floor=1024)
        out, new_matched = self._expand(
            probe, self.build, start, count, perm, self._build_matched,
            out_cap=out_cap, cap_b=cap_b, total_emit=total,
        )
        if new_matched is not None:
            self._build_matched = new_matched
        return out

    def process(self, stream):
        assert self.build is not None, "set_build() before process()"
        for probe in stream:
            yield self._join_batch(probe)
        if self.kind == "full":
            yield self._unmatched_build()

    def _unmatched_build(self) -> Batch:
        """FULL OUTER tail: build rows never matched, probe columns NULL."""
        b = self.build
        live = jnp.logical_and(b.mask(), jnp.logical_not(self._build_matched))
        ncols = []
        for t in self._probe_types_cache:
            ncols.append(
                Column(
                    jnp.zeros(b.capacity, dtype=t.np_dtype),
                    t,
                    jnp.zeros(b.capacity, dtype=bool),
                    None,
                )
            )
        return Batch(ncols + list(b.columns), live)


class NestedLoopJoinOperator:
    """Cross join (reference: NestedLoopJoinOperator.java): every probe row ×
    every build row, via the same cumsum expansion with constant counts."""

    def __init__(self, build_types: Sequence[T.Type]):
        self.build_types = list(build_types)
        self.build: Optional[Batch] = None
        self._nb = 0
        self._step = _jit_cached(
            ("nested", tuple(t.name for t in self.build_types)),
            lambda: jax.jit(self._expand, static_argnames=("out_cap", "nb")),
        )

    def set_build(self, batches: list[Batch]) -> None:
        self.build, self._nb = _dense_build(batches, self.build_types)

    def _expand(self, probe: Batch, build: Batch, out_cap: int, nb: int, total_emit):
        cap_p = probe.capacity
        emit = jnp.where(probe.mask(), nb, 0)
        offsets = jnp.cumsum(emit) - emit
        has = emit > 0
        seed = (
            jnp.zeros(out_cap, dtype=jnp.int64)
            .at[jnp.where(has, offsets, out_cap)]
            .max(jnp.arange(cap_p, dtype=jnp.int64), mode="drop")
        )
        ids = jax.lax.cummax(seed)
        j = jnp.arange(out_cap, dtype=jnp.int64) - offsets[ids]
        out_live = jnp.arange(out_cap, dtype=jnp.int64) < total_emit
        pcols = [
            Column(
                jnp.take(c.data, ids, mode="clip"),
                c.type,
                None if c.valid is None else jnp.take(c.valid, ids, mode="clip"),
                c.dictionary,
            )
            for c in probe.columns
        ]
        bcols = [
            Column(
                jnp.take(c.data, j, mode="clip"),
                c.type,
                None if c.valid is None else jnp.take(c.valid, j, mode="clip"),
                c.dictionary,
            )
            for c in build.columns
        ]
        return Batch(list(pcols) + list(bcols), out_live)

    def process(self, stream):
        assert self.build is not None
        for probe in stream:
            if self._nb == 0:
                continue
            total = probe.num_rows_host() * self._nb
            out_cap = next_pow2(max(total, 1), floor=1024)
            yield self._step(
                probe, self.build, out_cap=out_cap, nb=self._nb, total_emit=total
            )


class SemiJoinOperator(_CombinedSortJoinBase):
    """Appends a boolean `mark` column: source key ∈ filtering-side keys.

    null_aware=True gives SQL IN null semantics — mark is NULL when the
    source key is NULL, or when there is no match but the filtering side
    contains a NULL (reference: HashSemiJoinOperator + SetBuilderOperator's
    containsNull handling).  null_aware=False is EXISTS: plain boolean.

    `residual`: optional fn(candidate Batch: source++filtering cols) -> bool
    mask for correlated EXISTS conjuncts (reference: the filter function of
    JoinNode produced for correlated exists, e.g. TPC-H Q21's
    l2.l_suppkey <> l1.l_suppkey); a row is marked iff some key-matching
    filtering row also passes the residual.
    """

    def __init__(
        self,
        source_key_channel: int,
        filtering_key_channel: int,
        filtering_types: Sequence[T.Type],
        null_aware: bool = True,
        residual=None,
        residual_key=None,
    ):
        super().__init__([source_key_channel], [filtering_key_channel])
        self.filtering_types = list(filtering_types)
        self.null_aware = null_aware
        self.residual = residual
        self.build: Optional[Batch] = None
        self._filter_has_null = False
        self._mark = _jit_cached(
            ("mark", null_aware, source_key_channel, filtering_key_channel),
            lambda: jax.jit(
                self._mark_step, static_argnames=("cap_b", "has_null")
            ),
        )
        res_key = (
            None
            if (residual is not None and residual_key is None)
            else ("mark_res", null_aware, source_key_channel, filtering_key_channel,
                  tuple(t.name for t in self.filtering_types), residual_key)
        )
        self._mark_res = _jit_cached(
            res_key,
            lambda: jax.jit(
                self._mark_residual_step,
                static_argnames=("cap_b", "out_cap", "has_null"),
            ),
        )

    def set_build(self, batches: list[Batch]) -> None:
        self.build, _ = _dense_build(batches, self.filtering_types)
        col = self.build.columns[self.build_keys[0]]
        if col.valid is not None:
            has_null = jnp.any(jnp.logical_and(self.build.mask(), jnp.logical_not(col.valid)))
            self._filter_has_null = bool(has_null)

    def _mark_from_matched(self, probe: Batch, matched, has_null: bool) -> Batch:
        key = probe.columns[self.probe_keys[0]]
        key_valid = key.valid if key.valid is not None else jnp.ones(probe.capacity, bool)
        if not self.null_aware:
            mark_valid = None
        elif has_null:
            mark_valid = jnp.logical_and(key_valid, matched)
        else:
            mark_valid = key_valid
        return probe.append_column(Column(matched, T.BOOLEAN, mark_valid))

    def _mark_step(
        self, probe: Batch, combined: Batch, cap_b: int, has_null: bool
    ) -> Batch:
        _, count, _ = self._locate_step(combined, cap_b)
        return self._mark_from_matched(probe, count > 0, has_null)

    def _mark_residual_step(
        self, probe: Batch, build: Batch, start, count, perm,
        cap_b: int, out_cap: int, total_emit, has_null: bool
    ) -> Batch:
        """Expand key-matching candidates, apply residual, any() per row."""
        offsets = jnp.cumsum(count) - count
        cap_p = probe.capacity
        has = count > 0
        seed = (
            jnp.zeros(out_cap, dtype=jnp.int64)
            .at[jnp.where(has, offsets, out_cap)]
            .max(jnp.arange(cap_p, dtype=jnp.int64), mode="drop")
        )
        ids = jax.lax.cummax(seed)
        j = jnp.arange(out_cap, dtype=jnp.int64) - offsets[ids]
        in_range = jnp.logical_and(
            j < count[ids], jnp.arange(out_cap, dtype=jnp.int64) < total_emit
        )
        build_pos = jnp.clip(start[ids] + j, 0, perm.shape[0] - 1)
        build_row = jnp.clip(perm[build_pos], 0, cap_b - 1)
        pcols = [
            Column(
                jnp.take(c.data, ids, mode="clip"),
                c.type,
                None if c.valid is None else jnp.take(c.valid, ids, mode="clip"),
                c.dictionary,
            )
            for c in probe.columns
        ]
        bcols = [
            Column(
                jnp.take(c.data, build_row, mode="clip"),
                c.type,
                in_range
                if c.valid is None
                else jnp.logical_and(in_range, jnp.take(c.valid, build_row, mode="clip")),
                c.dictionary,
            )
            for c in build.columns
        ]
        candidate = Batch(list(pcols) + list(bcols), in_range)
        keep = jnp.logical_and(in_range, self.residual(candidate))
        surv = jax.ops.segment_sum(keep.astype(jnp.int64), ids, cap_p)
        return self._mark_from_matched(probe, surv > 0, has_null)

    def process(self, stream):
        assert self.build is not None
        cap_b = self.build.capacity
        for probe in stream:
            combined = self._combined_keys(self.build, probe)
            if self.residual is None:
                yield self._mark(
                    probe, combined, cap_b=cap_b, has_null=self._filter_has_null
                )
            else:
                start, count, perm = self._locate(combined, cap_b=cap_b)
                total = int(jnp.sum(count))
                out_cap = next_pow2(max(total, 1), floor=1024)
                yield self._mark_res(
                    probe, self.build, start, count, perm,
                    cap_b=cap_b, out_cap=out_cap, total_emit=total,
                    has_null=self._filter_has_null,
                )
