"""Join operators (reference: operator/join/* — HashBuilderOperator.java,
LookupJoinOperator.java + JoinProbe, NestedLoopJoinOperator.java,
HashSemiJoinOperator via SetBuilderOperator).

TPU substitution (SURVEY.md §7): no per-row open-addressing probe.  The build
side is materialized dense and *sorted once* by its key columns in
``set_build`` — the analog of the reference's one-time PagesHash construction
(operator/join/PagesHash.java: addressing built once, probed many times).
Each probe batch then locates its contiguous run of matching build rows with a
vectorized lexicographic *binary search* over the sorted build keys
(O(P·log B) fully-parallel compares — the streamed-probe analog of
LookupJoinOperator.java), and a cumsum-based row expansion emits the joined
rows.  All static-shape XLA: the only host round-trip per probe batch is one
scalar (the match count) used to pick the pow2-bucketed output capacity, the
analog of the reference's page-size-bounded join output building.

Dictionary-encoded (varchar) keys: build and probe may carry different
dictionaries, whose codes are not directly comparable.  The probe codes are
recoded host-side into the build dictionary's code space through a cached
i32 table (absent values -> -1, which can never equal a build code, so they
simply match nothing) — the analog of DictionaryBlock id remapping.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.columnar import Batch, Column
from trino_tpu.columnar.batch import concat_batches
from trino_tpu.ops.common import next_pow2


def _dense_build(batches: list[Batch], types: Sequence[T.Type]) -> tuple[Batch, int]:
    """Materialize the build side: concat + compact to pow2(live)."""
    if not batches:
        cols = [Column(np.zeros(1, dtype=t.np_dtype), t, np.zeros(1, dtype=bool)) for t in types]
        return Batch(cols, np.zeros(1, dtype=bool)), 0
    big = batches[0] if len(batches) == 1 else concat_batches(batches)
    n = big.num_rows_host()
    cap = next_pow2(max(n, 1), floor=1)
    return jax.jit(Batch.compact_device, static_argnames=("out_capacity",))(
        big, out_capacity=cap
    ), n


def _canon_build_keys(build: Batch, key_channels: Sequence[int]):
    """Canonical key arrays + combined nomatch mask for a build side."""
    nomatch = jnp.logical_not(build.mask())
    canon = []
    for ch in key_channels:
        col = build.columns[ch]
        ds, nm = _canon_data(col)
        if col.valid is not None:
            nomatch = jnp.logical_or(nomatch, jnp.logical_not(col.valid))
        if nm is not None:
            nomatch = jnp.logical_or(nomatch, nm)
        canon.extend(ds)
    return canon, nomatch


def _lex_sort_perm(canon, nomatch, cap: int):
    """Stable lexicographic permutation: keys ascending, nomatch rows last."""
    perm = jnp.arange(cap, dtype=jnp.int64)
    for d in reversed(canon):
        order = jnp.argsort(jnp.take(d, perm, mode="clip"), stable=True)
        perm = perm[order]
    return perm[jnp.argsort(jnp.take(nomatch, perm, mode="clip"), stable=True)]


def _canon_data(col: Column):
    """([comparable-form arrays], extra-nomatch mask or None) for one key
    column.  Long decimals expand into TWO canon arrays (high limb, then
    low limb in unsigned order) so every downstream consumer — lex sort,
    binary search, composite packing — treats them as an extra key.

    SQL `=` never matches NULL, and float NaN keys never equal anything
    (reference DoubleOperators.equal is IEEE ==), so both are folded into the
    per-row `nomatch` flag instead of riding sentinel orderings.
    """
    d = col.data
    if isinstance(col.type, T.DecimalType) and col.type.is_long:
        sign = jnp.int64(np.int64(-(2**63)))
        if d.ndim == 1:
            # short-valued rows under a long type (e.g. a window sum):
            # widen so BOTH join sides contribute the same two canon arrays
            d64 = jnp.asarray(d, jnp.int64)
            return [d64 >> 63, d64 ^ sign], None
        return [d[:, 0], d[:, 1] ^ sign], None
    if d.dtype == jnp.bool_:
        d = d.astype(jnp.int8)
    nm = None
    if jnp.issubdtype(d.dtype, jnp.floating):
        nm = jnp.isnan(d)
        d = jnp.where(nm, jnp.zeros_like(d), d)
    return [d], nm


def _sort_build_device(build: Batch, key_channels: Sequence[int]):
    """Device-only build indexing (PagesHash-build analog; vmappable for the
    per-shard SPMD path).  Returns (sorted build Batch, sorted canonical key
    arrays, n_match device scalar).  Rows are physically reordered so that
    key-matchable rows (live, non-NULL, non-NaN keys) occupy [0, n_match)
    in lexicographic key order; everything else sorts after."""
    cap = build.capacity
    canon, nomatch = _canon_build_keys(build, key_channels)
    perm = _lex_sort_perm(canon, nomatch, cap)
    n_match = jnp.sum(jnp.logical_not(nomatch), dtype=jnp.int64)
    sorted_build = build.gather(perm)
    sorted_canon = [jnp.take(d, perm, mode="clip") for d in canon]
    return sorted_build, sorted_canon, n_match


def _canon_probe_device(probe: Batch, key_channels: Sequence[int], build_canon=None):
    """Device-only probe canonicalization WITHOUT dictionary recode (the
    caller guarantees directly comparable codes, e.g. after the SPMD path's
    up-front dictionary unification).  Returns (key arrays, nomatch mask)."""
    nomatch = jnp.logical_not(probe.mask())
    arrs = []
    for ch in key_channels:
        col = probe.columns[ch]
        if col.valid is not None:
            nomatch = jnp.logical_or(nomatch, jnp.logical_not(col.valid))
        ds, nm = _canon_data(col)
        if nm is not None:
            nomatch = jnp.logical_or(nomatch, nm)
        for d in ds:
            if build_canon is not None:
                bd = build_canon[len(arrs)]
                if d.dtype != bd.dtype:
                    # promoted dtype, never narrowing (see _probe_canonical)
                    d = d.astype(jnp.promote_types(d.dtype, bd.dtype))
            arrs.append(d)
    return arrs, nomatch


def _prepare_sorted_build(build: Batch, key_channels: Sequence[int]):
    """Host wrapper over the build sort: pulls n_match to host and records
    per-key build dictionaries for probe recoding.

    Fast path (host-only; set_build runs eagerly so a scalar sync is fine):
    when every canonical key is int-family and the combined (nomatch, keys)
    value range fits 62 bits, all sort keys pack into ONE composite int64 —
    one argsort instead of nkeys+1 stable passes."""
    cap = build.capacity
    canon, nomatch = _canon_build_keys(build, key_channels)
    perm = None
    table = None
    n_match = int(jnp.sum(jnp.logical_not(nomatch)))  # lint: allow(host-sync-cast)
    if all(jnp.issubdtype(d.dtype, jnp.integer) for d in canon):
        imax = jnp.iinfo(jnp.int64).max
        mins, widths = [], []
        total = 1
        for d in canon:
            d64 = d.astype(jnp.int64)
            # nomatch rows must not widen the packed range
            mn = int(jnp.min(jnp.where(nomatch, imax, d64)))  # lint: allow(host-sync-cast)
            mx = int(jnp.max(jnp.where(nomatch, -imax, d64)))  # lint: allow(host-sync-cast)
            mins.append(mn)
            widths.append(mx - mn + 1)
            total *= mx - mn + 1
        if 0 < total <= (1 << 62) and all(w > 0 for w in widths):
            composite = jnp.zeros(cap, dtype=jnp.int64)
            for d, mn, w in zip(canon, mins, widths):
                composite = composite * w + (d.astype(jnp.int64) - mn)
            composite = jnp.where(nomatch, total, composite)
            perm = jnp.argsort(composite, stable=True)
            if total <= TABLE_DOMAIN_LIMIT and total <= 64 * max(n_match, 1):
                # direct-addressed probe tables over the packed key domain:
                # start/count per composite code, O(1) gather per probe row
                # (the PagesHash open-addressing analog, but positional)
                tcap = next_pow2(total, floor=16)
                c_sorted = jnp.take(composite, perm, mode="clip")
                pos = jnp.arange(cap, dtype=jnp.int64)
                cs = jnp.minimum(c_sorted, tcap)
                start_t = jax.ops.segment_min(
                    jnp.where(c_sorted < total, pos, cap), cs, tcap + 1
                )[:tcap].astype(jnp.int32)
                count_t = jax.ops.segment_sum(
                    (c_sorted < total).astype(jnp.int32), cs, tcap + 1
                )[:tcap]
                table = (
                    jnp.asarray(np.asarray(mins, dtype=np.int64)),
                    jnp.asarray(np.asarray(widths, dtype=np.int64)),
                    start_t,
                    count_t,
                )
    if perm is None:
        perm = _lex_sort_perm(canon, nomatch, cap)
    sorted_build = build.gather(perm)
    sorted_canon = [jnp.take(d, perm, mode="clip") for d in canon]
    dicts = [build.columns[ch].dictionary for ch in key_channels]
    return sorted_build, sorted_canon, n_match, dicts, table


def _build_recode_table(probe_dict, build_dict) -> Optional[jnp.ndarray]:
    """i32[|probe_dict|] mapping probe codes -> build codes (-1 = absent).
    None means codes are already directly comparable."""
    if probe_dict is None or build_dict is None:
        return None
    if probe_dict is build_dict or probe_dict == build_dict:
        return None
    table = np.full(len(probe_dict), -1, dtype=np.int32)
    # iterate the smaller dictionary (PatternDictionary values are lazy and
    # potentially huge; code_of stays O(log n) on both kinds)
    if len(build_dict) <= len(probe_dict):
        for bc, v in enumerate(build_dict.values):
            pc = probe_dict.code_of(v)
            if pc >= 0:
                table[pc] = bc
    else:
        for pc, v in enumerate(probe_dict.values):
            table[pc] = build_dict.code_of(v)
    return jnp.asarray(table)


#: packed-domain cap for direct-addressed probe tables (2 i32 arrays)
TABLE_DOMAIN_LIMIT = 1 << 25


def _locate_table(probe_canon, probe_nomatch, mins, widths, start_t, count_t):
    """O(1)-per-row probe: composite code -> (start, count) table gather."""
    n = probe_canon[0].shape[0]
    code = jnp.zeros(n, dtype=jnp.int64)
    nomatch = probe_nomatch
    for i, pk in enumerate(probe_canon):
        k = pk.astype(jnp.int64) - mins[i]
        nomatch = jnp.logical_or(
            nomatch, jnp.logical_or(k < 0, k >= widths[i])
        )
        code = code * widths[i] + jnp.clip(k, 0, jnp.maximum(widths[i] - 1, 0))
    idx = jnp.clip(code, 0, start_t.shape[0] - 1)
    start = jnp.take(start_t, idx, mode="clip").astype(jnp.int64)
    count = jnp.where(
        nomatch, 0, jnp.take(count_t, idx, mode="clip").astype(jnp.int64)
    )
    return jnp.where(nomatch, 0, start), count


def _locate_sorted(build_canon, n_match, probe_canon, probe_nomatch, cap_b: int):
    """Per probe row: (start, count) of its matching run in sorted-build row
    space.  Two vectorized binary searches (lower/upper bound) over the
    lexicographically sorted [0, n_match) prefix; log2(cap_b)+1 fixed
    iterations, no data-dependent control flow."""
    P = probe_canon[0].shape[0]
    nm = jnp.asarray(n_match, dtype=jnp.int64)
    iters = max(1, int(cap_b).bit_length())

    def bounds(le: bool):
        lo0 = jnp.zeros(P, dtype=jnp.int64)
        hi0 = jnp.full(P, nm, dtype=jnp.int64)

        def body(_, st):
            lo, hi = st
            active = lo < hi
            mid = (lo + hi) >> 1
            lt = jnp.zeros(P, dtype=bool)
            eq = jnp.ones(P, dtype=bool)
            for bk, pk in zip(build_canon, probe_canon):
                bv = jnp.take(bk, mid, mode="clip")
                lt = jnp.logical_or(lt, jnp.logical_and(eq, bv < pk))
                eq = jnp.logical_and(eq, bv == pk)
            go_right = jnp.logical_or(lt, eq) if le else lt
            lo2 = jnp.where(go_right, mid + 1, lo)
            hi2 = jnp.where(go_right, hi, mid)
            return jnp.where(active, lo2, lo), jnp.where(active, hi2, hi)

        lo, _ = jax.lax.fori_loop(0, iters, body, (lo0, hi0))
        return lo

    lo = bounds(False)
    hi = bounds(True)
    count = jnp.where(probe_nomatch, 0, hi - lo)
    start = jnp.where(probe_nomatch, 0, lo)
    return start, count


#: process-level jitted-step cache (cross-query reuse; see filter_project).
#: CONTRACT: a cached step must read NO per-query state off `self` — only
#: configuration captured in its cache key; per-query data (the build batch,
#: null flags) is passed as explicit arguments.
_STEP_CACHE: dict = {}


def _jit_cached(key, factory):
    if key is None:
        return factory()
    if key not in _STEP_CACHE:
        _STEP_CACHE[key] = factory()
    return _STEP_CACHE[key]


class _SortedBuildJoinBase:
    """Shared machinery: build-once sorted index + binary-search probe."""

    def __init__(self, probe_key_channels, build_key_channels):
        self.probe_keys = list(probe_key_channels)
        self.build_keys = list(build_key_channels)
        self.build: Optional[Batch] = None
        self._build_canon = None
        self._n_match = 0
        self._key_dicts = [None] * len(self.build_keys)
        self._table = None
        self._recode: dict = {}  # key index -> {id(probe_dict): (dict, table)}
        self._locate = _jit_cached(
            ("locate", len(self.build_keys)),
            lambda: jax.jit(_locate_sorted, static_argnames=("cap_b",)),
        )
        self._locate_t = _jit_cached(
            ("locate_table", len(self.build_keys)),
            lambda: jax.jit(_locate_table),
        )

    def release_build(self) -> None:
        """Drop every device reference to the indexed build side (the
        memory-revocation hook, reference HashBuilderOperator
        .startMemoryRevoke: once the build has been spilled host-side the
        operator releases its HBM so the pool reservation it gave back is
        physically real).  The operator is unusable afterwards; callers
        switch to partition-wave execution against the spilled build."""
        self.build = None
        self._build_canon = None
        self._n_match = 0
        self._table = None
        self._recode = {}

    def _index_build(self, build: Batch) -> None:
        (
            self.build,
            self._build_canon,
            self._n_match,
            self._key_dicts,
            self._table,
        ) = _prepare_sorted_build(build, self.build_keys)
        self._recode = {}

    def _recode_for(self, i: int, probe_dict):
        cache = self._recode.setdefault(i, {})
        hit = cache.get(id(probe_dict))
        if hit is not None:
            return hit[1]
        table = _build_recode_table(probe_dict, self._key_dicts[i])
        cache[id(probe_dict)] = (probe_dict, table)  # pin dict: id stays valid
        return table

    def _probe_canonical(self, probe: Batch):
        """Probe key arrays in the build's comparable domain + nomatch mask.
        Runs eagerly (a handful of gathers) so dictionary recode tables stay
        out of jit cache keys."""
        nomatch = jnp.logical_not(probe.mask())
        arrs = []
        for i, ch in enumerate(self.probe_keys):
            col = probe.columns[ch]
            if col.valid is not None:
                nomatch = jnp.logical_or(nomatch, jnp.logical_not(col.valid))
            if col.dictionary is not None and self._key_dicts[i] is not None:
                table = self._recode_for(i, col.dictionary)
                d = col.data.astype(jnp.int32)
                if table is not None:
                    d = jnp.take(table, d, mode="clip")
                arrs.append(d)
                continue
            ds, nm = _canon_data(col)
            if nm is not None:
                nomatch = jnp.logical_or(nomatch, nm)
            for d in ds:
                # compare in the PROMOTED dtype: narrowing a wide probe key
                # to the build dtype would wrap out-of-range values onto
                # valid build keys (e.g. BIGINT 2^32+5 = INTEGER 5) and
                # fabricate matches
                bd = self._build_canon[len(arrs)]
                if d.dtype != bd.dtype:
                    d = d.astype(jnp.promote_types(d.dtype, bd.dtype))
                arrs.append(d)
        return arrs, nomatch

    def _locate_batch(self, probe: Batch):
        pc, pn = self._probe_canonical(probe)
        if self._table is not None:
            mins, widths, start_t, count_t = self._table
            return self._locate_t(pc, pn, mins, widths, start_t, count_t)
        return self._locate(
            self._build_canon, self._n_match, pc, pn, cap_b=self.build.capacity
        )


class HashJoinOperator(_SortedBuildJoinBase):
    """Equi join. Probe = left side (streamed), build = right (materialized);
    output columns = probe columns ++ build columns (reference: JoinNode output
    = left ++ right, build on right per LocalExecutionPlanner.visitJoin).

    kind: inner | left | full.  (right joins are planned as flipped left
    joins; cross joins use NestedLoopJoinOperator.)
    """

    def __init__(
        self,
        kind: str,
        probe_key_channels: Sequence[int],
        build_key_channels: Sequence[int],
        build_types: Sequence[T.Type],
        probe_types: Sequence[T.Type] = (),
        residual=None,
        residual_key=None,
    ):
        """`residual`: optional fn(candidate Batch: probe++build cols) -> bool
        mask, the non-equi join conjuncts (reference: JoinNode.filter /
        JoinFilterFunctionCompiler).  Outer-join semantics: a probe row whose
        matches all fail the residual still emits one null-padded row.
        `residual_key`: hashable identity of the residual (e.g. the expr key)
        enabling cross-query reuse of the jitted expand step."""
        assert kind in ("inner", "left", "full")
        super().__init__(probe_key_channels, build_key_channels)
        self.kind = kind
        self.build_types = list(build_types)
        self._probe_types_cache = list(probe_types)
        self.residual = residual
        self._build_rows = 0
        self._build_matched = None  # bool[cap_b], for full outer
        cache_key = None
        if residual is None or residual_key is not None:
            cache_key = (
                "expand", kind, tuple(self.probe_keys), tuple(self.build_keys),
                tuple(t.name for t in self.build_types), residual_key,
            )
        self._expand = _jit_cached(
            cache_key, lambda: jax.jit(
                self._expand_step, static_argnames=("out_cap", "cap_b")
            )
        )
        self._expand_unique = _jit_cached(
            None if cache_key is None else ("uniq",) + cache_key[1:],
            lambda: jax.jit(self._expand_unique_step, static_argnames=("cap_b",)),
        )

    def set_build(self, batches: list[Batch]) -> None:
        build, self._build_rows = _dense_build(batches, self.build_types)
        self._index_build(build)
        if self.kind == "full":
            self._build_matched = jnp.zeros(self.build.capacity, dtype=bool)

    def _expand_step(
        self, probe: Batch, build: Batch, start, count, build_matched,
        out_cap: int, cap_b: int, total_emit
    ):
        emit = count if self.kind == "inner" else jnp.where(probe.mask(), jnp.maximum(count, 1), 0)
        offsets = jnp.cumsum(emit) - emit
        cap_p = probe.capacity
        has = emit > 0
        seed = (
            jnp.zeros(out_cap, dtype=jnp.int64)
            .at[jnp.where(has, offsets, out_cap)]
            .max(jnp.arange(cap_p, dtype=jnp.int64), mode="drop")
        )
        ids = jax.lax.cummax(seed)  # out slot -> probe slot
        j = jnp.arange(out_cap, dtype=jnp.int64) - offsets[ids]
        matched = j < count[ids]
        build_row = jnp.clip(start[ids] + j, 0, cap_b - 1)
        out_live = jnp.arange(out_cap, dtype=jnp.int64) < total_emit
        pcols = [
            Column(
                jnp.take(c.data, ids, axis=0, mode="clip"),
                c.type,
                None if c.valid is None else jnp.take(c.valid, ids, mode="clip"),
                c.dictionary,
            )
            for c in probe.columns
        ]
        bvalid_base = jnp.logical_and(matched, out_live)
        bcols = [
            Column(
                jnp.take(c.data, build_row, axis=0, mode="clip"),
                c.type,
                bvalid_base
                if c.valid is None
                else jnp.logical_and(bvalid_base, jnp.take(c.valid, build_row, mode="clip")),
                c.dictionary,
            )
            for c in build.columns
        ]
        keep_match = jnp.logical_and(matched, out_live)
        if self.residual is not None:
            candidate = Batch(list(pcols) + list(bcols), out_live)
            keep_match = jnp.logical_and(keep_match, self.residual(candidate))
            if self.kind == "inner":
                out_live = keep_match
            else:
                # probe rows with emitted matches but zero residual survivors
                # degrade their first slot to an unmatched (null-build) row
                surv = jax.ops.segment_sum(
                    keep_match.astype(jnp.int64), ids, probe.capacity
                )
                to_null = jnp.logical_and(
                    jnp.logical_and(j == 0, surv[ids] == 0), out_live
                )
                out_live = jnp.logical_and(out_live, jnp.logical_or(keep_match, to_null))
                bcols = [
                    Column(
                        c.data,
                        c.type,
                        jnp.logical_and(
                            keep_match, c.valid if c.valid is not None else True
                        ),
                        c.dictionary,
                    )
                    for c in bcols
                ]
        new_matched = None
        if self.kind == "full":
            new_matched = build_matched.at[
                jnp.where(keep_match, build_row, cap_b)
            ].set(True, mode="drop")
        return Batch(list(pcols) + list(bcols), out_live), new_matched

    def _expand_unique_step(
        self, probe: Batch, build: Batch, start, count, build_matched, cap_b: int
    ):
        """FK->PK fast path: every probe row has at most one match, so output
        rows are the probe rows IN PLACE (no cumsum expansion, no probe
        gathers) and only build columns are gathered — the dominant join
        shape in TPC workloads (reference analog: PagesHash with single-row
        key runs probed by LookupJoinOperator)."""
        matched = jnp.logical_and(count > 0, probe.mask())
        build_row = jnp.clip(start, 0, cap_b - 1)
        bcols = [
            Column(
                jnp.take(c.data, build_row, axis=0, mode="clip"),
                c.type,
                matched
                if c.valid is None
                else jnp.logical_and(matched, jnp.take(c.valid, build_row, mode="clip")),
                c.dictionary,
            )
            for c in build.columns
        ]
        keep_match = matched
        out_live = probe.mask() if self.kind != "inner" else matched
        if self.residual is not None:
            candidate = Batch(list(probe.columns) + list(bcols), out_live)
            keep_match = jnp.logical_and(keep_match, self.residual(candidate))
            if self.kind == "inner":
                out_live = keep_match
            else:
                # non-matching residual degrades the row to null-build
                bcols = [
                    Column(
                        c.data,
                        c.type,
                        jnp.logical_and(
                            keep_match, c.valid if c.valid is not None else True
                        ),
                        c.dictionary,
                    )
                    for c in bcols
                ]
        new_matched = None
        if self.kind == "full":
            new_matched = build_matched.at[
                jnp.where(keep_match, build_row, cap_b)
            ].set(True, mode="drop")
        return Batch(list(probe.columns) + list(bcols), out_live), new_matched

    def _join_batch(self, probe: Batch) -> Batch:
        cap_b = self.build.capacity
        start, count = self._locate_batch(probe)
        maxc, total_inner, probe_live = (
            int(x) for x in jax.device_get(  # lint: allow(host-transfer)
                (jnp.max(count), jnp.sum(count), probe.count())
            )
        )
        if maxc <= 1:
            out, new_matched = self._expand_unique(
                probe, self.build, start, count, self._build_matched, cap_b=cap_b
            )
            if new_matched is not None:
                self._build_matched = new_matched
            n_out = total_inner if self.kind == "inner" else probe_live
            cc = next_pow2(max(n_out, 1), floor=1024)
            if cc * 2 <= out.capacity:
                # selective join: hand downstream a dense batch, not a
                # mostly-dead full-capacity one
                out = jax.jit(
                    Batch.compact_device, static_argnames=("out_capacity",)
                )(out, out_capacity=cc)
            return out
        if self.kind == "inner":
            total = total_inner
        else:
            total = int(jnp.sum(jnp.where(probe.mask(), jnp.maximum(count, 1), 0)))  # lint: allow(host-sync-cast)
        out_cap = next_pow2(max(total, 1), floor=1024)
        out, new_matched = self._expand(
            probe, self.build, start, count, self._build_matched,
            out_cap=out_cap, cap_b=cap_b, total_emit=total,
        )
        if new_matched is not None:
            self._build_matched = new_matched
        return out

    def process(self, stream):
        assert self.build is not None, "set_build() before process()"
        for probe in stream:
            yield self._join_batch(probe)
        if self.kind == "full":
            yield self._unmatched_build()

    def _unmatched_build(self) -> Batch:
        """FULL OUTER tail: build rows never matched, probe columns NULL."""
        b = self.build
        live = jnp.logical_and(b.mask(), jnp.logical_not(self._build_matched))
        ncols = []
        for t in self._probe_types_cache:
            ncols.append(
                Column(
                    jnp.zeros(b.capacity, dtype=t.np_dtype),
                    t,
                    jnp.zeros(b.capacity, dtype=bool),
                    None,
                )
            )
        return Batch(ncols + list(b.columns), live)


class NestedLoopJoinOperator:
    """Cross join (reference: NestedLoopJoinOperator.java): every probe row ×
    every build row, via the same cumsum expansion with constant counts."""

    def __init__(self, build_types: Sequence[T.Type]):
        self.build_types = list(build_types)
        self.build: Optional[Batch] = None
        self._nb = 0
        self._step = _jit_cached(
            ("nested", tuple(t.name for t in self.build_types)),
            lambda: jax.jit(self._expand, static_argnames=("out_cap", "nb")),
        )

    def set_build(self, batches: list[Batch]) -> None:
        self.build, self._nb = _dense_build(batches, self.build_types)

    def _expand(self, probe: Batch, build: Batch, out_cap: int, nb: int, total_emit):
        cap_p = probe.capacity
        emit = jnp.where(probe.mask(), nb, 0)
        offsets = jnp.cumsum(emit) - emit
        has = emit > 0
        seed = (
            jnp.zeros(out_cap, dtype=jnp.int64)
            .at[jnp.where(has, offsets, out_cap)]
            .max(jnp.arange(cap_p, dtype=jnp.int64), mode="drop")
        )
        ids = jax.lax.cummax(seed)
        j = jnp.arange(out_cap, dtype=jnp.int64) - offsets[ids]
        out_live = jnp.arange(out_cap, dtype=jnp.int64) < total_emit
        pcols = [
            Column(
                jnp.take(c.data, ids, axis=0, mode="clip"),
                c.type,
                None if c.valid is None else jnp.take(c.valid, ids, mode="clip"),
                c.dictionary,
            )
            for c in probe.columns
        ]
        bcols = [
            Column(
                jnp.take(c.data, j, axis=0, mode="clip"),
                c.type,
                None if c.valid is None else jnp.take(c.valid, j, mode="clip"),
                c.dictionary,
            )
            for c in build.columns
        ]
        return Batch(list(pcols) + list(bcols), out_live)

    def process(self, stream):
        assert self.build is not None
        for probe in stream:
            if self._nb == 0:
                continue
            total = probe.num_rows_host() * self._nb
            out_cap = next_pow2(max(total, 1), floor=1024)
            yield self._step(
                probe, self.build, out_cap=out_cap, nb=self._nb, total_emit=total
            )


class SemiJoinOperator(_SortedBuildJoinBase):
    """Appends a boolean `mark` column: source key ∈ filtering-side keys.

    null_aware=True gives SQL IN null semantics — mark is NULL when the
    source key is NULL, or when there is no match but the filtering side
    contains a NULL (reference: HashSemiJoinOperator + SetBuilderOperator's
    containsNull handling).  null_aware=False is EXISTS: plain boolean.

    `residual`: optional fn(candidate Batch: source++filtering cols) -> bool
    mask for correlated EXISTS conjuncts (reference: the filter function of
    JoinNode produced for correlated exists, e.g. TPC-H Q21's
    l2.l_suppkey <> l1.l_suppkey); a row is marked iff some key-matching
    filtering row also passes the residual.
    """

    def __init__(
        self,
        source_key_channel: int,
        filtering_key_channel: int,
        filtering_types: Sequence[T.Type],
        null_aware: bool = True,
        residual=None,
        residual_key=None,
    ):
        super().__init__([source_key_channel], [filtering_key_channel])
        self.filtering_types = list(filtering_types)
        self.null_aware = null_aware
        self.residual = residual
        self._filter_has_null = False
        self._mark = _jit_cached(
            ("mark", null_aware, source_key_channel, filtering_key_channel),
            lambda: jax.jit(self._mark_step, static_argnames=("has_null",)),
        )
        res_key = (
            None
            if (residual is not None and residual_key is None)
            else ("mark_res", null_aware, source_key_channel, filtering_key_channel,
                  tuple(t.name for t in self.filtering_types), residual_key)
        )
        self._mark_res = _jit_cached(
            res_key,
            lambda: jax.jit(
                self._mark_residual_step,
                static_argnames=("cap_b", "out_cap", "has_null"),
            ),
        )

    def set_build(self, batches: list[Batch]) -> None:
        build, _ = _dense_build(batches, self.filtering_types)
        col = build.columns[self.build_keys[0]]
        if col.valid is not None:
            has_null = jnp.any(
                jnp.logical_and(build.mask(), jnp.logical_not(col.valid))
            )
            self._filter_has_null = bool(has_null)
        self._index_build(build)

    def _mark_from_matched(self, probe: Batch, matched, has_null: bool) -> Batch:
        key = probe.columns[self.probe_keys[0]]
        key_valid = key.valid if key.valid is not None else jnp.ones(probe.capacity, bool)
        if not self.null_aware:
            mark_valid = None
        elif has_null:
            mark_valid = jnp.logical_and(key_valid, matched)
        else:
            mark_valid = key_valid
        return probe.append_column(Column(matched, T.BOOLEAN, mark_valid))

    def _mark_step(self, probe: Batch, count, has_null: bool) -> Batch:
        return self._mark_from_matched(probe, count > 0, has_null)

    def _mark_residual_step(
        self, probe: Batch, build: Batch, start, count,
        cap_b: int, out_cap: int, total_emit, has_null: bool
    ) -> Batch:
        """Expand key-matching candidates, apply residual, any() per row."""
        offsets = jnp.cumsum(count) - count
        cap_p = probe.capacity
        has = count > 0
        seed = (
            jnp.zeros(out_cap, dtype=jnp.int64)
            .at[jnp.where(has, offsets, out_cap)]
            .max(jnp.arange(cap_p, dtype=jnp.int64), mode="drop")
        )
        ids = jax.lax.cummax(seed)
        j = jnp.arange(out_cap, dtype=jnp.int64) - offsets[ids]
        in_range = jnp.logical_and(
            j < count[ids], jnp.arange(out_cap, dtype=jnp.int64) < total_emit
        )
        build_row = jnp.clip(start[ids] + j, 0, cap_b - 1)
        pcols = [
            Column(
                jnp.take(c.data, ids, axis=0, mode="clip"),
                c.type,
                None if c.valid is None else jnp.take(c.valid, ids, mode="clip"),
                c.dictionary,
            )
            for c in probe.columns
        ]
        bcols = [
            Column(
                jnp.take(c.data, build_row, axis=0, mode="clip"),
                c.type,
                in_range
                if c.valid is None
                else jnp.logical_and(in_range, jnp.take(c.valid, build_row, mode="clip")),
                c.dictionary,
            )
            for c in build.columns
        ]
        candidate = Batch(list(pcols) + list(bcols), in_range)
        keep = jnp.logical_and(in_range, self.residual(candidate))
        surv = jax.ops.segment_sum(keep.astype(jnp.int64), ids, cap_p)
        return self._mark_from_matched(probe, surv > 0, has_null)

    def process(self, stream):
        assert self.build is not None
        cap_b = self.build.capacity
        for probe in stream:
            start, count = self._locate_batch(probe)
            if self.residual is None:
                yield self._mark(probe, count, has_null=self._filter_has_null)
            else:
                total = int(jnp.sum(count))  # lint: allow(host-sync-cast)
                out_cap = next_pow2(max(total, 1), floor=1024)
                yield self._mark_res(
                    probe, self.build, start, count,
                    cap_b=cap_b, out_cap=out_cap, total_emit=total,
                    has_null=self._filter_has_null,
                )
