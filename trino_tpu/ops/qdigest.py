"""Log-bucketed quantile sketch for approx_percentile.

Reference: operator/aggregation/ApproximateLongPercentileAggregations.java
(qdigest) / TDigest — a FIXED-SIZE, MERGEABLE quantile state so global
approx_percentile never materializes whole groups on one node.  The
reference's qdigest is a sparse tree over value prefixes; the TPU-native
reshape is the same log-structured bucketing FLATTENED to a dense count
vector so building is one scatter-add and merging is elementwise addition —
both single XLA ops.

Buckets: sign x (256 octaves) x (32 sub-buckets per octave), plus a zero
bucket — 16385 slots, ordered ascending by value.  Relative value
resolution is 1/64 per bucket (~1.6%); the rank itself is exact within the
histogram, so the estimate is the true percentile's bucket representative.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

SUB_BITS = 5
SUBS = 1 << SUB_BITS  # 32 sub-buckets per octave
OCTAVES = 256  # exponents -128..127
HALF = OCTAVES * SUBS  # buckets per sign
NBUCKETS = 2 * HALF + 1  # negatives, zero, positives


def bucket_ids(f):
    """f64 values -> ascending-ordered bucket ids [0, NBUCKETS)."""
    f = jnp.asarray(f, jnp.float64)
    a = jnp.abs(f)
    m, e = jnp.frexp(a)  # a = m * 2**e, m in [0.5, 1)
    e = jnp.clip(e + 128, 0, OCTAVES - 1)
    sub = jnp.clip(
        ((m - 0.5) * (2 * SUBS)).astype(jnp.int32), 0, SUBS - 1
    )
    mag = e.astype(jnp.int32) * SUBS + sub  # ascending magnitude
    pos_idx = HALF + 1 + mag
    neg_idx = HALF - 1 - mag
    idx = jnp.where(f > 0, pos_idx, jnp.where(f < 0, neg_idx, HALF))
    return idx.astype(jnp.int32)


def _rep_table() -> np.ndarray:
    """Representative (midpoint) value per bucket, ascending."""
    e = np.arange(OCTAVES) - 128
    sub = np.arange(SUBS)
    m_mid = 0.5 + (sub[None, :] + 0.5) / (2 * SUBS)  # [oct, sub]
    # frexp convention: a = m * 2**e with m in [0.5, 1)
    mag = (m_mid * np.exp2(e[:, None])).reshape(-1)  # ascending
    table = np.empty(NBUCKETS, np.float64)
    table[HALF] = 0.0
    table[HALF + 1 :] = mag
    table[:HALF] = -mag[::-1]
    return table


REPS = _rep_table()


def histogram(f, valid, nbuckets: int = NBUCKETS):
    """Count vector [nbuckets] over the valid values (the partial state)."""
    ids = bucket_ids(f)
    w = valid.astype(jnp.int64)
    return jax.ops.segment_sum(w, ids.astype(jnp.int64), nbuckets)


def estimate(counts, p: float):
    """(value estimate f64, total count) from a merged count vector."""
    counts = jnp.asarray(counts, jnp.int64)
    total = jnp.sum(counts)
    target = jnp.floor(p * jnp.maximum(total - 1, 0).astype(jnp.float64)).astype(
        jnp.int64
    )
    cum = jnp.cumsum(counts)
    # first bucket whose cumulative count exceeds the target rank
    idx = jnp.argmax(cum > target)
    return jnp.take(jnp.asarray(REPS), idx), total
