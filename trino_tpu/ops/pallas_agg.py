"""Pallas TPU kernel: fused masked grouped aggregation.

Reference role: the generated accumulator loops of
operator/aggregation/GroupedAggregator + AccumulatorCompiler — the hottest
loop of the engine's Q1-shaped workload (low-cardinality GROUP BY over wide
fact scans).

TPU design: for a small group domain G, grouped sums ARE a matmul — the
one-hot group matrix [N, G] transposed against the value matrix [N, K] rides
the MXU instead of scatter hardware the TPU doesn't have.  The Pallas kernel
streams row blocks HBM->VMEM, builds the one-hot tile in-register, and
accumulates [G, K] partials in a VMEM scratch across grid steps — one pass
over the data, no re-materialized one-hot in HBM (which is what the
equivalent XLA formulation allocates when N is large).

Used by the engine as an optional fast path for sum/count aggregates with
small integer group ids (session property `pallas_agg`); everything else
takes the sort-based path in ops/aggregation.py.  On CPU (tests) the kernel
runs in interpreter mode.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_BLOCK = 2048  # rows per grid step (VMEM: 2048*K*4B + 2048*G*4B)


def _agg_kernel(gid_ref, mask_ref, val_ref, out_ref, acc_ref):
    import jax.experimental.pallas as pl

    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    gids = gid_ref[:]  # [B] int32
    mask = mask_ref[:]  # [B] bool
    vals = val_ref[:]  # [B, K] f32
    g = acc_ref.shape[0]
    # one-hot [B, G] with dead rows zeroed; built in VMEM, never in HBM
    onehot = (
        gids[:, None] == jax.lax.broadcasted_iota(jnp.int32, (1, g), 1)
    ) & mask[:, None]
    acc_ref[:] += jax.lax.dot_general(
        onehot.astype(jnp.float32),
        vals,
        (((0,), (0,)), ((), ())),  # contract over rows: [G, K]
        preferred_element_type=jnp.float32,
    )

    @pl.when(step == pl.num_programs(0) - 1)
    def _flush():
        out_ref[:] = acc_ref[:]


@functools.partial(jax.jit, static_argnames=("n_groups", "interpret"))
def grouped_sums_pallas(
    gids, mask, values, n_groups: int, interpret: bool = False
):
    """sum of values[:, k] per group (masked): [G, K] float32.

    gids int32 [N] in [0, n_groups); mask bool [N]; values float32 [N, K].
    N must be a multiple of the block size (pad with mask=False rows).
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, k = values.shape
    block = min(_BLOCK, n)
    assert n % block == 0, f"pad N={n} to a multiple of {block}"
    grid = (n // block,)
    return pl.pallas_call(
        _agg_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block,), lambda i: (i,)),
            pl.BlockSpec((block,), lambda i: (i,)),
            pl.BlockSpec((block, k), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((n_groups, k), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((n_groups, k), jnp.float32),
        scratch_shapes=[pltpu.VMEM((n_groups, k), jnp.float32)],
        interpret=interpret,
    )(
        gids.astype(jnp.int32),
        mask,
        values.astype(jnp.float32),
    )


def grouped_sums_xla(gids, mask, values, n_groups: int):
    """The XLA formulation of the same computation (segment-sum one-hot
    matmul) — the comparison baseline for the micro-bench."""
    onehot = jax.nn.one_hot(gids, n_groups, dtype=jnp.float32)
    onehot = onehot * mask[:, None].astype(jnp.float32)
    return onehot.T @ values.astype(jnp.float32)
