"""Physical operators (reference: core/trino-main/.../operator/** — 170 files).

TPU-first redesign (SURVEY.md §7): instead of a per-row pull loop with JIT'd
bytecode inner loops, each operator step is one jitted, shape-stable XLA
computation over whole columnar batches:

  ScanFilterAndProjectOperator  -> scan.ScanOperator + filter_project
  HashAggregationOperator +
  MultiChannelGroupByHash       -> aggregation (sort-based segmented reduce)
  HashBuilder/LookupJoinOperator-> join (sorted build + searchsorted probe)
  TopNOperator                  -> sort.TopNOperator (bounded sort-merge state)
  OrderByOperator               -> sort.OrderByOperator
  LimitOperator                 -> sort.LimitOperator
  ValuesOperator                -> values.ValuesOperator

Operators are host-side generators over Batch streams; all device math lives
in jitted step functions reused across batches (shape-bucketed capacities keep
the trace cache small).
"""

from trino_tpu.ops.common import (
    multi_key_sort_perm,
    SortKey,
)

__all__ = ["multi_key_sort_perm", "SortKey"]
