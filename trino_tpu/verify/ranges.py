"""Value-range lattice and range certificates for the numeric verifier.

The abstract domain of `verify.numeric` is a closed interval over exact
python integers, measured in the SCALED units of the value's own SQL type:
a `decimal(12,2)` literal 19.99 is the point interval [1999, 1999], an
`integer` column is its int32 dtype range, a DATE is day numbers.  Python
int arithmetic never wraps, so interval bounds computed here are sound for
the device's fixed-width kernels — an operation is proven wrap-free exactly
when its result interval fits the kernel's accumulator width.

Two artifacts come out of the domain:

  * `Interval` — the lattice element (None endpoint = unbounded on that
    side; TOP = (None, None), BOTTOM is not represented: unreachable code
    simply isn't analyzed).
  * `RangeCertificate` — a machine-checkable proof record that licenses a
    narrow kernel: per-row |scaled value| <= max_abs, over at most
    rows_bound contributing rows, so every partial sum of any subset stays
    inside [-max_abs*rows_bound, +max_abs*rows_bound].  The planner attaches
    one to an aggregation / window spec when `licensed_i64_sum_bound()`
    proves the whole reduction fits a single int64 plane; the kernels then
    compile the one-plane segment sum with NO runtime fits check and NO
    limb-plane traffic (the `_sum128` static-proof framework, generalized).

Provenance strings record where each bound came from (`stats:<column>`,
`literal`, `type:<name>`, `rows:<source>`), so a certificate can be audited
end to end: the proof is only as strong as its weakest source, and only
connector generator statistics (exact by construction) or declared type
precisions are admissible — never CBO estimates.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

from trino_tpu import types as T

#: the int64 accumulator's representable magnitude: a sum proven strictly
#: under this bound can never wrap a single-plane segment sum
I64_MAX = (1 << 63) - 1

#: dtype range of each integer-kind device representation
_INT_RANGES = {
    "tinyint": (-(1 << 7), (1 << 7) - 1),
    "smallint": (-(1 << 15), (1 << 15) - 1),
    "integer": (-(1 << 31), (1 << 31) - 1),
    "bigint": (-(1 << 63), (1 << 63) - 1),
}


@dataclass(frozen=True)
class Interval:
    """Closed integer interval; None = unbounded on that side."""

    lo: Optional[int] = None
    hi: Optional[int] = None

    # -- constructors ---------------------------------------------------------

    @staticmethod
    def top() -> "Interval":
        return Interval(None, None)

    @staticmethod
    def point(v: int) -> "Interval":
        return Interval(int(v), int(v))

    @property
    def bounded(self) -> bool:
        return self.lo is not None and self.hi is not None

    def max_abs(self) -> Optional[int]:
        """|v| bound, or None when either side is unbounded."""
        if not self.bounded:
            return None
        return max(abs(self.lo), abs(self.hi))

    # -- lattice --------------------------------------------------------------

    def union(self, other: "Interval") -> "Interval":
        lo = None if self.lo is None or other.lo is None else min(self.lo, other.lo)
        hi = None if self.hi is None or other.hi is None else max(self.hi, other.hi)
        return Interval(lo, hi)

    def intersect(self, other: "Interval") -> "Interval":
        """Meet: the values in BOTH intervals.  An empty meet (a filter
        that provably admits nothing) collapses to the empty-ish point
        convention [lo, lo]-crossed — callers only ever use the result as
        a sound superset of surviving values, so clamping hi >= lo keeps
        the lattice well-formed without a bottom element."""
        lo = self.lo if other.lo is None else (
            other.lo if self.lo is None else max(self.lo, other.lo)
        )
        hi = self.hi if other.hi is None else (
            other.hi if self.hi is None else min(self.hi, other.hi)
        )
        if lo is not None and hi is not None and hi < lo:
            hi = lo
        return Interval(lo, hi)

    def within(self, other: "Interval") -> bool:
        """self ⊆ other (unbounded `other` sides always contain)."""
        if other.lo is not None and (self.lo is None or self.lo < other.lo):
            return False
        if other.hi is not None and (self.hi is None or self.hi > other.hi):
            return False
        return True

    # -- arithmetic transfer functions ---------------------------------------

    def add(self, other: "Interval") -> "Interval":
        lo = None if self.lo is None or other.lo is None else self.lo + other.lo
        hi = None if self.hi is None or other.hi is None else self.hi + other.hi
        return Interval(lo, hi)

    def sub(self, other: "Interval") -> "Interval":
        return self.add(other.neg())

    def neg(self) -> "Interval":
        return Interval(
            None if self.hi is None else -self.hi,
            None if self.lo is None else -self.lo,
        )

    def mul(self, other: "Interval") -> "Interval":
        if not (self.bounded and other.bounded):
            return Interval.top()
        prods = [
            self.lo * other.lo, self.lo * other.hi,
            self.hi * other.lo, self.hi * other.hi,
        ]
        return Interval(min(prods), max(prods))

    def scale_pow10(self, k: int) -> "Interval":
        """Rescale by 10**k (k may be negative: truncating/rounding divide —
        conservative: magnitude never grows on downscale)."""
        if k == 0:
            return self
        if k > 0:
            f = 10 ** k
            return Interval(
                None if self.lo is None else self.lo * f,
                None if self.hi is None else self.hi * f,
            )
        f = 10 ** (-k)
        # rounding half-away divide: |result| <= (|v| + f/2) / f <= |v|/f + 1
        lo = None if self.lo is None else -(abs(self.lo) // f + 1)
        hi = None if self.hi is None else self.hi // f + 1
        if self.lo is not None and self.lo >= 0:
            lo = 0
        if self.hi is not None and self.hi <= 0:
            hi = 0
        return Interval(lo, hi)

    def truncdiv(self, other: "Interval") -> "Interval":
        """Truncate-toward-zero division: |q| <= |a| (divisor magnitude
        >= 1 whenever the result is non-null, and div-by-zero nulls)."""
        m = self.max_abs()
        if m is None:
            return Interval.top()
        return Interval(-m, m)

    def scaled_sum_bound(self, rows: int) -> Optional[int]:
        """|any partial sum of <= rows addends| bound."""
        m = self.max_abs()
        if m is None:
            return None
        return m * int(rows)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        lo = "-inf" if self.lo is None else str(self.lo)
        hi = "+inf" if self.hi is None else str(self.hi)
        return f"[{lo}, {hi}]"


TOP = Interval.top()

#: int64 device accumulator as an interval
I64_INTERVAL = Interval(-(1 << 63), I64_MAX)
#: two-limb i128 planes
I128_INTERVAL = Interval(-(1 << 127), (1 << 127) - 1)


def dtype_interval(t: T.Type) -> Interval:
    """The device representation's own range (what silent wrap is measured
    against), NOT the SQL-declared range."""
    if isinstance(t, T.DecimalType):
        return I128_INTERVAL if t.is_long else I64_INTERVAL
    r = _INT_RANGES.get(t.name)
    if r is not None:
        return Interval(*r)
    if t.name == "boolean":
        return Interval(0, 1)
    if t is T.DATE:
        return Interval(*_INT_RANGES["integer"])
    if t.np_dtype.kind == "i":
        return Interval(*_INT_RANGES["bigint"])
    return TOP


def type_interval(t: T.Type) -> Interval:
    """Widest value interval the DECLARED type admits, in scaled units:
    the fallback bound when no stats or literal narrows it."""
    if isinstance(t, T.DecimalType):
        m = 10 ** t.precision - 1
        return Interval(-m, m)
    r = _INT_RANGES.get(t.name)
    if r is not None:
        return Interval(*r)
    if t.name == "boolean":
        return Interval(0, 1)
    if t is T.DATE:
        # civil day numbers: comfortably within +-1e7 (year ~29379);
        # the generous bound keeps date arithmetic provably i64
        return Interval(-10_000_000, 10_000_000)
    if t.name in ("timestamp", "time", "interval day to second"):
        # microsecond encodings of civil instants: |v| < 2**55 keeps
        # +-256 additions provably inside i64
        return Interval(-(1 << 55), (1 << 55) - 1)
    if T.is_string_kind(t) or isinstance(t, T.VarbinaryType):
        # dictionary codes: int32 indices
        return Interval(0, (1 << 31) - 1)
    if t.np_dtype.kind == "i":
        return Interval(*_INT_RANGES["bigint"])
    return TOP  # floats / composites: no exact-range reasoning


def is_exact_type(t: T.Type) -> bool:
    """Types whose device representation is exact integer arithmetic."""
    return not (t.name in ("real", "double") or t.np_dtype.kind == "f")


def stats_interval(t: T.Type, low, high) -> Optional[Interval]:
    """Connector column statistics (logical-unit floats) -> a scaled-int
    interval, rounded OUTWARD with a one-unit cushion so float conversion
    error can never tighten a bound below the truth."""
    if low is None or high is None:
        return None
    if not is_exact_type(t):
        return None
    factor = t.scale_factor if isinstance(t, T.DecimalType) else 1
    try:
        lo = int(math.floor(float(low) * factor)) - 1
        hi = int(math.ceil(float(high) * factor)) + 1
    except (OverflowError, ValueError):
        return None
    return Interval(lo, hi)


# -- certificates --------------------------------------------------------------


@dataclass(frozen=True)
class RangeCertificate:
    """Machine-checkable proof that a reduction over a column fits i64.

    Contract: every contributing row's scaled value v satisfies
    |v| <= max_abs, and at most rows_bound rows ever contribute (across ALL
    batches/workers of the query — padding rows are masked to zero and do
    not count).  Then every partial sum of every subset, in any association
    order, lies in [-max_abs*rows_bound, +max_abs*rows_bound]: the licensed
    kernel is exact iff that bound is strictly inside int64.
    """

    max_abs: int
    scale: int
    rows_bound: Optional[int]
    provenance: tuple = field(default_factory=tuple)

    def sum_bound(self) -> Optional[int]:
        if self.rows_bound is None:
            return None
        return int(self.max_abs) * int(self.rows_bound)

    def licensed_i64_sum_bound(self) -> Optional[int]:
        """The static sum bound when it proves a one-plane i64 reduction,
        else None (caller falls back to runtime checks / limb planes)."""
        b = self.sum_bound()
        if b is not None and b < I64_MAX:
            return b
        return None

    def to_json(self) -> dict:
        return {
            "max_abs": int(self.max_abs),
            "scale": int(self.scale),
            "rows_bound": (
                None if self.rows_bound is None else int(self.rows_bound)
            ),
            "sum_bound": self.sum_bound(),
            "licenses_i64_sum": self.licensed_i64_sum_bound() is not None,
            "provenance": list(self.provenance),
        }


def certificate(
    interval: Interval,
    scale: int,
    rows_bound: Optional[int],
    provenance=(),
) -> Optional[RangeCertificate]:
    """Build a certificate from an analyzed value interval, or None when
    the interval is unbounded (no proof exists)."""
    m = interval.max_abs()
    if m is None:
        return None
    return RangeCertificate(
        max_abs=m,
        scale=scale,
        rows_bound=rows_bound,
        provenance=tuple(provenance),
    )
