"""Dynamic lock-order verification: record the acquisition-order graph at
runtime and fail on cycles.

The static lint (verify/concurrency.py) sees lexically nested `with`
statements — the cheap 80%.  What it cannot see is cross-function nesting:
thread A takes the engine lock and calls into the prewarm executor (which
takes its state lock); thread B, inside a state-locked section, kicks
something that waits on the engine lock.  Each call chain looks fine alone;
together they deadlock.  This module catches that class at TEST time:

  * `InstrumentedLock` wraps a real `threading.Lock`, reporting every
    acquire/release to a `LockGraph`.  Each thread's currently-held set is
    tracked; acquiring L while holding K records the edge K -> L with a
    witness call site.  Reentrant acquires of one lock never self-edge.
  * `LockGraph.assert_acyclic()` raises `LockOrderViolation` naming the
    cycle and the witness sites — an order inversion is a deadlock waiting
    for the right interleaving, so the graph test fails even when the run
    happened not to hang.
  * `capture()` monkeypatches `threading.Lock` for a scope so every lock
    *created inside it* is instrumented automatically, named by its
    allocation site — the chaos suite wraps its fixtures in this, which is
    how the engine's servers, runners, and registries all join the graph
    without per-class plumbing.
  * `instrument_attr(obj, "_lock", name)` wraps one existing lock in place
    (for process singletons created before the capture began);
    `instrument_singletons()` does it for the engine's well-known ones.

Everything is deterministic: the graph is about ORDER, not interleaving, so
a single thread acquiring A->B then B->A is enough to prove the hazard —
the seeded-deadlock test does exactly that, with zero sleeps.
"""

from __future__ import annotations

import _thread
import threading
from contextlib import contextmanager
from typing import Optional


class LockOrderViolation(Exception):
    """The recorded acquisition-order graph has a cycle."""


def _site(skip_internal: bool = True) -> str:
    """file:line of the acquiring frame (first frame outside this module)."""
    import sys

    f = sys._getframe(1)
    while f is not None and f.f_code.co_filename.endswith("lockgraph.py"):
        f = f.f_back
    if f is None:
        return "<unknown>"
    return f"{f.f_code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno}"


class LockGraph:
    """Thread-safe acquisition-order edge set over named locks."""

    def __init__(self):
        # raw _thread lock: the graph's own mutex must never be an
        # InstrumentedLock (capture() patches threading.Lock)
        self._mu = _thread.allocate_lock()
        #: (held, acquired) -> first witness "thread | site"
        self._edges: dict = {}
        self._local = threading.local()

    def _held(self) -> list:
        held = getattr(self._local, "held", None)
        if held is None:
            held = self._local.held = []
        return held

    # -- recording ------------------------------------------------------------

    def note_acquire(self, name: str) -> None:
        held = self._held()
        if name in held:  # reentrant / same-name: no self-edges
            held.append(name)
            return
        if held:
            site = _site()
            tname = threading.current_thread().name
            with self._mu:
                for h in held:
                    if h != name:
                        self._edges.setdefault(
                            (h, name), f"{tname} at {site}"
                        )
        held.append(name)

    def note_release(self, name: str) -> None:
        held = self._held()
        # release the most recent acquisition of this name (lock discipline
        # is not necessarily LIFO across different locks)
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return

    # -- queries ---------------------------------------------------------------

    def edges(self) -> dict:
        with self._mu:
            return dict(self._edges)

    def cycles(self) -> list:
        """Closed walks in the edge set, each as [a, b, ..., a]."""
        from trino_tpu.verify.concurrency import find_cycles

        return find_cycles([(a, b) for (a, b) in self.edges()])

    def assert_acyclic(self) -> None:
        cycles = self.cycles()
        if not cycles:
            return
        edges = self.edges()
        lines = []
        for cyc in cycles:
            pairs = list(zip(cyc, cyc[1:]))
            witness = "; ".join(
                f"{a} -> {b} ({edges.get((a, b), '?')})" for a, b in pairs
            )
            lines.append(" -> ".join(cyc) + f" [{witness}]")
        raise LockOrderViolation(
            "lock acquisition order has "
            f"{len(cycles)} cycle(s) — a deadlock waiting for the right "
            "interleaving:\n  " + "\n  ".join(lines)
        )


#: graph used when none is passed explicitly (tests usually scope their own)
DEFAULT_GRAPH = LockGraph()


class InstrumentedLock:
    """A threading.Lock wrapper reporting acquisition order to a LockGraph.
    Supports the full Lock protocol (context manager, blocking/timeout,
    locked) so it drops into any `with self._lock:` site unchanged."""

    def __init__(self, name: str, graph: Optional[LockGraph] = None,
                 inner=None):
        self._name = name
        self._graph = graph or DEFAULT_GRAPH
        self._inner = inner if inner is not None else _thread.allocate_lock()

    @property
    def name(self) -> str:
        return self._name

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if blocking and timeout == -1:
            # record the INTENT edge before an indefinite block: if this
            # acquire deadlocks, the graph already holds the evidence a
            # watchdog would need (a blocking acquire that returns False
            # cannot happen, so the edge is never spurious)
            self._graph.note_acquire(self._name)
            try:
                return self._inner.acquire(blocking)
            except BaseException:
                self._graph.note_release(self._name)
                raise
        # try-lock / bounded acquire: record only on SUCCESS — a FAILED
        # try-acquire backs off instead of waiting, so it can never
        # deadlock, and its edge would fabricate cycles for the standard
        # ordering-sidestep pattern (`if a.acquire(False): ... else: ...`)
        ok = (
            self._inner.acquire(blocking, timeout)
            if timeout != -1
            else self._inner.acquire(blocking)
        )
        if ok:
            self._graph.note_acquire(self._name)
        return ok

    def release(self) -> None:
        self._inner.release()
        self._graph.note_release(self._name)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self):
        return f"<InstrumentedLock {self._name} {self._inner!r}>"


def instrument_attr(obj, attr: str, name: Optional[str] = None,
                    graph: Optional[LockGraph] = None):
    """Wrap an existing lock attribute in place; returns a restore
    callable.  `obj` may be an object or a module."""
    inner = getattr(obj, attr)
    if isinstance(inner, InstrumentedLock):  # already wrapped
        return lambda: None
    label = name or f"{type(obj).__name__}.{attr}"
    setattr(obj, attr, InstrumentedLock(label, graph, inner=inner))

    def restore():
        setattr(obj, attr, inner)

    return restore


def instrument_singletons(graph: Optional[LockGraph] = None) -> list:
    """Wrap the engine's well-known process-wide locks (created at import
    time, before any capture() could see them).  Returns restore callables.
    Best-effort: a singleton that moved or lost its lock is skipped — the
    graph should never fail a test for structural drift here."""
    restores = []

    def _try(fn):
        try:
            restores.append(fn())
        except Exception:
            pass

    def _wrap(obj, attr, name):
        return lambda: instrument_attr(obj, attr, name, graph)

    from trino_tpu.parallel import spmd
    from trino_tpu.runtime import buffer_pool, lifecycle, retry
    from trino_tpu import config as cfg
    from trino_tpu.telemetry import compile_events, metrics

    _try(_wrap(spmd.TRACE_CACHE, "_lock", "TRACE_CACHE._lock"))
    _try(_wrap(buffer_pool.POOL, "lock", "POOL.lock"))
    _try(_wrap(retry.BREAKERS, "_lock", "BREAKERS._lock"))
    _try(_wrap(lifecycle, "_POOL_LOCK", "lifecycle:_POOL_LOCK"))
    _try(_wrap(cfg, "_LOCK", "config:_LOCK"))
    _try(_wrap(compile_events.OBSERVATORY, "_lock", "OBSERVATORY._lock"))
    _try(_wrap(metrics, "_SERIES_LOCK", "metrics:_SERIES_LOCK"))
    _try(_wrap(metrics.REGISTRY, "_lock", "REGISTRY._lock"))
    return restores


@contextmanager
def capture(graph: Optional[LockGraph] = None, singletons: bool = True):
    """Scope in which every `threading.Lock()` creation yields an
    InstrumentedLock named by its allocation site, feeding `graph` (a fresh
    LockGraph when None — yielded to the caller).  With `singletons`, the
    engine's import-time locks are wrapped for the scope too.

    The patch is process-global for the scope: locks created by OTHER
    threads during it are instrumented as well — which is the point, the
    engine's background threads are where the ordering bugs live."""
    g = graph or LockGraph()
    real_lock = threading.Lock

    def make_lock():
        return InstrumentedLock(f"lock@{_site()}", g, inner=real_lock())

    restores = instrument_singletons(g) if singletons else []
    threading.Lock = make_lock
    try:
        yield g
    finally:
        threading.Lock = real_lock
        for r in restores:
            try:
                r()
            except Exception:
                pass
