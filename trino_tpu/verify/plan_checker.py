"""Plan sanity checkers (reference: sql/planner/sanity/PlanSanityChecker.java
— the validator battery Trino runs after analysis and between optimizer
passes: ValidateDependenciesChecker, NoDuplicatePlanNodeIdsChecker,
TypeValidator, ValidateScaledWritersUsage...).

A bad rewrite should fail loudly at plan time, not produce wrong rows at run
time.  Three layers:

  * structural — every node id is unique and no node instance appears twice
    in the tree (a shared subtree silently breaks `with_children` rewrites);
  * dependencies — every symbol a node consumes is produced by a child, with
    a dtype consistent with the producer's declaration;
  * typing — a per-node-type rule table (NODE_TYPING_RULES) checks
    output-symbol dtypes across Filter/Project/Aggregation/Join/Window/
    Union/Exchange nodes, plus distributed invariants on exchange
    boundaries (partition symbols exist; join keys hash-compatibly).

Violations are structured `PlanViolation`s naming the failing node id and
rule; enforcement is controlled by the `verify_plan` session property
(strict | warn | off — default strict under pytest, warn elsewhere).
"""

from __future__ import annotations

import sys
import warnings
from typing import Optional

from trino_tpu import types as T
from trino_tpu.expr.ir import Expr, SymbolRef
from trino_tpu.planner import plan as P


class PlanViolation(Exception):
    """One failed sanity rule, naming the node and the rule."""

    def __init__(self, rule: str, node, message: str):
        self.rule = rule
        self.node_id = getattr(node, "id", 0)
        self.node_type = type(node).__name__
        super().__init__(
            f"[{rule}] {self.node_type}#{self.node_id}: {message}"
        )


MODES = ("strict", "warn", "off")

#: violations surfaced (not raised) by warn-mode enforcement, newest last —
#: kept so benches/tests can inspect what a non-strict run flagged
LAST_WARNINGS: list = []


def resolve_mode(mode: Optional[str] = None) -> str:
    """strict | warn | off; anything else resolves to the ambient default
    (strict when running under pytest, warn otherwise — a bench run should
    report, not die, while tests must fail loudly)."""
    if mode in MODES:
        return mode
    return "strict" if "pytest" in sys.modules else "warn"


def enforce(violations: list, mode: Optional[str] = None) -> None:
    mode = resolve_mode(mode)
    if mode == "off" or not violations:
        return
    if mode == "strict":
        raise violations[0]
    LAST_WARNINGS.extend(violations)
    del LAST_WARNINGS[:-200]  # bounded
    for v in violations:
        warnings.warn(f"plan verifier: {v}", RuntimeWarning, stacklevel=3)


# -- type compatibility -------------------------------------------------------


def _compat(a: T.Type, b: T.Type) -> bool:
    """Declared-vs-produced symbol dtype consistency: exact name match,
    UNKNOWN (NULL literal) wildcard, or string-family equivalence (varchar
    lengths are metadata; the device value is a dictionary code either way)."""
    if a is b or a.name == b.name:
        return True
    if a is T.UNKNOWN or b is T.UNKNOWN:
        return True
    if T.is_string_kind(a) and T.is_string_kind(b):
        return True
    return False


def _coercible(a: T.Type, b: T.Type) -> bool:
    """Union-branch compatibility: the branch type must coerce to the output
    type through the engine's coercion lattice."""
    if _compat(a, b):
        return True
    try:
        T.common_super_type(a, b)
        return True
    except TypeError:
        return False


#: integer-valued device representations that hash identically after the
#: exchange's .astype(int64) canonicalization (exchange._hash_rows)
_HASH_INT_NAMES = (
    "tinyint", "smallint", "integer", "bigint", "boolean",
    "date", "timestamp", "timestamp with time zone", "time",
    "interval day to second", "interval year to month",
)


def _hash_compat(a: T.Type, b: T.Type) -> bool:
    """Two key dtypes may meet at a hash-partitioned boundary only if equal
    logical values produce equal row hashes on both sides."""
    if _compat(a, b):
        return True
    if isinstance(a, T.DecimalType) and isinstance(b, T.DecimalType):
        # scaled-integer representation: same scale -> same device value
        return a.scale == b.scale and a.is_long == b.is_long
    if a.name in _HASH_INT_NAMES and b.name in _HASH_INT_NAMES:
        return True
    return False


# -- expression symbol collection ---------------------------------------------


def collect_symbol_refs(e: Expr, acc: Optional[list] = None, _seen=None) -> list:
    """All SymbolRef leaves of an expression DAG (each shared node once)."""
    if acc is None:
        acc = []
    if _seen is None:
        _seen = set()
    if id(e) in _seen:
        return acc
    _seen.add(id(e))
    if isinstance(e, SymbolRef):
        acc.append(e)
    for c in e.children():
        collect_symbol_refs(c, acc, _seen)
    return acc


# -- the checker --------------------------------------------------------------


class _Ctx:
    """One check run: accumulates violations instead of raising so a single
    pass reports every problem (the caller decides strict vs warn)."""

    def __init__(self):
        self.violations: list[PlanViolation] = []

    def fail(self, rule: str, node, message: str) -> None:
        self.violations.append(PlanViolation(rule, node, message))


def _available(node: P.PlanNode) -> dict:
    """name -> Symbol over all children's outputs (the dependency universe
    of a node's expressions)."""
    out: dict = {}
    for c in node.children:
        for s in c.outputs:
            out.setdefault(s.name, s)
    return out


def _check_refs(ctx: _Ctx, node, exprs, available: dict, what: str = "") -> None:
    """Dependency validator (reference: ValidateDependenciesChecker): every
    symbol an expression consumes must be produced by a child, with a
    consistent declared dtype."""
    for e in exprs:
        if not isinstance(e, Expr):
            continue
        for ref in collect_symbol_refs(e):
            prod = available.get(ref.name)
            if prod is None:
                ctx.fail(
                    "dangling-symbol", node,
                    f"{what}consumes symbol '{ref.name}' produced by no child",
                )
            elif not _compat(ref.type, prod.type):
                ctx.fail(
                    "symbol-type-mismatch", node,
                    f"{what}reads '{ref.name}' as {ref.type.name} but the "
                    f"child produces {prod.type.name}",
                )


def _check_symbols(ctx: _Ctx, node, symbols, available: dict, what: str) -> None:
    """Same dependency check for Symbol lists (group keys, orderings...)."""
    for s in symbols:
        prod = available.get(s.name)
        if prod is None:
            ctx.fail(
                "dangling-symbol", node,
                f"{what} symbol '{s.name}' produced by no child",
            )
        elif not _compat(s.type, prod.type):
            ctx.fail(
                "symbol-type-mismatch", node,
                f"{what} symbol '{s.name}' declared {s.type.name} but the "
                f"child produces {prod.type.name}",
            )


# -- per-node-type typing rules (the TypeValidator rule table) ----------------


def _t_TableScanNode(ctx: _Ctx, node: P.TableScanNode) -> None:
    own = {s.name: s for s, _ in node.assignments}
    if node.pushed_predicate is not None:
        _check_refs(
            ctx, node, [node.pushed_predicate], own, "pushed predicate "
        )
        if not _compat(node.pushed_predicate.type, T.BOOLEAN):
            ctx.fail(
                "predicate-not-boolean", node,
                f"pushed predicate has type {node.pushed_predicate.type.name}",
            )
    cols = {
        c.name: c.type for c in getattr(node.table_meta, "columns", ()) or ()
    }
    for s, cname in node.assignments:
        ct = cols.get(cname)
        if ct is not None and not _compat(s.type, ct):
            ctx.fail(
                "scan-column-type-mismatch", node,
                f"symbol '{s.name}' declared {s.type.name} but table column "
                f"'{cname}' is {ct.name}",
            )


def _t_FilterNode(ctx: _Ctx, node: P.FilterNode, avail: dict) -> None:
    _check_refs(ctx, node, [node.predicate], avail, "predicate ")
    if not _compat(node.predicate.type, T.BOOLEAN):
        ctx.fail(
            "predicate-not-boolean", node,
            f"filter predicate has type {node.predicate.type.name}",
        )


def _t_ProjectNode(ctx: _Ctx, node: P.ProjectNode, avail: dict) -> None:
    _check_refs(ctx, node, [e for _, e in node.assignments], avail)
    for s, e in node.assignments:
        if not _compat(s.type, e.type):
            ctx.fail(
                "project-type-mismatch", node,
                f"assignment '{s.name}' declared {s.type.name} but the "
                f"expression produces {e.type.name}",
            )


#: aggregate output dtypes the checker pins down (only rules that hold for
#: every input type land here; value-dependent ones stay unchecked)
_AGG_BIGINT_OUT = ("count", "count_star", "approx_distinct")
_AGG_ARG_TYPED_OUT = ("min", "max", "any_value", "arbitrary")
_AGG_BOOLEAN_OUT = ("bool_and", "bool_or", "every")


def _t_AggregationNode(ctx: _Ctx, node: P.AggregationNode, avail: dict) -> None:
    if node.step not in ("single", "partial", "final"):
        ctx.fail("bad-agg-step", node, f"unknown step '{node.step}'")
    _check_symbols(ctx, node, node.group_symbols, avail, "group")
    for out_sym, agg in node.aggregations:
        _check_refs(
            ctx, node, list(agg.args), avail, f"aggregate '{out_sym.name}' "
        )
        if agg.filter is not None:
            _check_refs(
                ctx, node, [agg.filter], avail,
                f"aggregate '{out_sym.name}' FILTER ",
            )
            if not _compat(agg.filter.type, T.BOOLEAN):
                ctx.fail(
                    "predicate-not-boolean", node,
                    f"aggregate '{out_sym.name}' FILTER has type "
                    f"{agg.filter.type.name}",
                )
        if agg.function in _AGG_BIGINT_OUT and not _compat(
            out_sym.type, T.BIGINT
        ):
            ctx.fail(
                "agg-type-mismatch", node,
                f"{agg.function} output '{out_sym.name}' declared "
                f"{out_sym.type.name}, expected bigint",
            )
        if agg.function in _AGG_BOOLEAN_OUT and not _compat(
            out_sym.type, T.BOOLEAN
        ):
            ctx.fail(
                "agg-type-mismatch", node,
                f"{agg.function} output '{out_sym.name}' declared "
                f"{out_sym.type.name}, expected boolean",
            )
        if (
            agg.function in _AGG_ARG_TYPED_OUT
            and agg.args
            and not _compat(out_sym.type, agg.args[0].type)
        ):
            ctx.fail(
                "agg-type-mismatch", node,
                f"{agg.function} output '{out_sym.name}' declared "
                f"{out_sym.type.name} but the argument is "
                f"{agg.args[0].type.name}",
            )


_JOIN_KINDS = ("inner", "left", "right", "full", "cross")


def _t_JoinNode(ctx: _Ctx, node: P.JoinNode, avail: dict) -> None:
    if node.kind not in _JOIN_KINDS:
        ctx.fail("bad-join-kind", node, f"unknown join kind '{node.kind}'")
    left = {s.name: s for s in node.left.outputs}
    right = {s.name: s for s in node.right.outputs}
    for l, r in node.criteria:
        _check_symbols(ctx, node, [l], left, "left join-key")
        _check_symbols(ctx, node, [r], right, "right join-key")
        if not _hash_compat(l.type, r.type):
            ctx.fail(
                "join-key-type-mismatch", node,
                f"criteria {l.name} = {r.name} compares {l.type.name} with "
                f"{r.type.name}, which do not hash compatibly",
            )
    if node.filter is not None:
        _check_refs(ctx, node, [node.filter], avail, "join filter ")
        if not _compat(node.filter.type, T.BOOLEAN):
            ctx.fail(
                "predicate-not-boolean", node,
                f"join filter has type {node.filter.type.name}",
            )


def _t_SemiJoinNode(ctx: _Ctx, node: P.SemiJoinNode, avail: dict) -> None:
    src = {s.name: s for s in node.source.outputs}
    filt = {s.name: s for s in node.filtering.outputs}
    _check_symbols(ctx, node, [node.source_key], src, "semi-join source")
    _check_symbols(ctx, node, [node.filtering_key], filt, "semi-join filtering")
    if not _hash_compat(node.source_key.type, node.filtering_key.type):
        ctx.fail(
            "join-key-type-mismatch", node,
            f"{node.source_key.name} in {node.filtering_key.name} compares "
            f"{node.source_key.type.name} with "
            f"{node.filtering_key.type.name}",
        )
    if not _compat(node.mark.type, T.BOOLEAN):
        ctx.fail(
            "mark-not-boolean", node,
            f"semi-join mark '{node.mark.name}' is {node.mark.type.name}",
        )
    if node.filter is not None:
        _check_refs(ctx, node, [node.filter], avail, "semi-join filter ")


#: window functions with an input-independent output dtype
_WINDOW_BIGINT_OUT = ("rank", "dense_rank", "row_number", "ntile", "count",
                      "count_star")
_WINDOW_DOUBLE_OUT = ("percent_rank", "cume_dist")
_WINDOW_ARG_TYPED_OUT = ("lag", "lead", "first_value", "last_value")


def _t_WindowNode(ctx: _Ctx, node: P.WindowNode, avail: dict) -> None:
    _check_symbols(ctx, node, node.partition_by, avail, "partition")
    _check_symbols(ctx, node, [s for s, _, _ in node.order_by], avail, "order")
    for out_sym, fn in node.functions:
        _check_refs(
            ctx, node, list(fn.args), avail, f"window '{out_sym.name}' "
        )
        if fn.name in _WINDOW_BIGINT_OUT and not _compat(
            out_sym.type, T.BIGINT
        ):
            ctx.fail(
                "window-type-mismatch", node,
                f"{fn.name} output '{out_sym.name}' declared "
                f"{out_sym.type.name}, expected bigint",
            )
        if fn.name in _WINDOW_DOUBLE_OUT and not _compat(
            out_sym.type, T.DOUBLE
        ):
            ctx.fail(
                "window-type-mismatch", node,
                f"{fn.name} output '{out_sym.name}' declared "
                f"{out_sym.type.name}, expected double",
            )
        if (
            fn.name in _WINDOW_ARG_TYPED_OUT
            and fn.args
            and not _compat(out_sym.type, fn.args[0].type)
        ):
            ctx.fail(
                "window-type-mismatch", node,
                f"{fn.name} output '{out_sym.name}' declared "
                f"{out_sym.type.name} but the argument is "
                f"{fn.args[0].type.name}",
            )


def _t_SortNode(ctx: _Ctx, node, avail: dict) -> None:
    _check_symbols(
        ctx, node, [s for s, _, _ in node.orderings], avail, "ordering"
    )


def _t_TopNNode(ctx: _Ctx, node: P.TopNNode, avail: dict) -> None:
    _t_SortNode(ctx, node, avail)
    if not isinstance(node.count, int) or node.count < 0:
        ctx.fail("bad-limit", node, f"TopN count {node.count!r}")


def _t_LimitNode(ctx: _Ctx, node: P.LimitNode, avail: dict) -> None:
    if node.count is not None and (
        not isinstance(node.count, int) or node.count < 0
    ):
        ctx.fail("bad-limit", node, f"limit count {node.count!r}")
    if not isinstance(node.offset, int) or node.offset < 0:
        ctx.fail("bad-limit", node, f"limit offset {node.offset!r}")


def _t_ValuesNode(ctx: _Ctx, node: P.ValuesNode, avail: dict) -> None:
    for i, row in enumerate(node.rows):
        if len(row) != len(node.symbols):
            ctx.fail(
                "values-arity", node,
                f"row {i} has {len(row)} values for {len(node.symbols)} "
                "symbols",
            )


def _t_UnionNode(ctx: _Ctx, node: P.UnionNode, avail: dict) -> None:
    if not node.source_symbols:
        return
    if len(node.source_symbols) != len(node.sources):
        ctx.fail(
            "union-arity", node,
            f"{len(node.source_symbols)} symbol mappings for "
            f"{len(node.sources)} sources",
        )
        return
    for i, (src, mapping) in enumerate(zip(node.sources, node.source_symbols)):
        if len(mapping) != len(node.symbols):
            ctx.fail(
                "union-arity", node,
                f"source {i} maps {len(mapping)} symbols for "
                f"{len(node.symbols)} outputs",
            )
            continue
        produced = {s.name: s for s in src.outputs}
        for out, branch in zip(node.symbols, mapping):
            _check_symbols(ctx, node, [branch], produced, f"source {i}")
            if not _coercible(branch.type, out.type):
                ctx.fail(
                    "union-type-mismatch", node,
                    f"source {i} column '{branch.name}' "
                    f"({branch.type.name}) does not coerce to output "
                    f"'{out.name}' ({out.type.name})",
                )


def _t_MarkDistinctNode(ctx: _Ctx, node: P.MarkDistinctNode, avail: dict) -> None:
    _check_symbols(ctx, node, node.key_symbols, avail, "distinct-key")
    if not _compat(node.mark.type, T.BOOLEAN):
        ctx.fail(
            "mark-not-boolean", node,
            f"mark '{node.mark.name}' is {node.mark.type.name}",
        )


def _t_UnnestNode(ctx: _Ctx, node: P.UnnestNode, avail: dict) -> None:
    _check_refs(ctx, node, [e for _, e in node.unnest], avail, "unnest ")


def _t_SampleNode(ctx: _Ctx, node: P.SampleNode, avail: dict) -> None:
    if not (0.0 <= float(node.ratio) <= 1.0):
        ctx.fail("bad-sample-ratio", node, f"ratio {node.ratio!r}")


def _t_OutputNode(ctx: _Ctx, node: P.OutputNode, avail: dict) -> None:
    _check_symbols(ctx, node, node.symbols, avail, "output")
    if len(node.column_names) != len(node.symbols):
        ctx.fail(
            "output-arity", node,
            f"{len(node.column_names)} names for {len(node.symbols)} symbols",
        )


_EXCHANGE_KINDS = ("repartition", "broadcast", "gather", "merge")


def _t_ExchangeNode(ctx: _Ctx, node: P.ExchangeNode, avail: dict) -> None:
    """Distributed invariants on a fragment boundary: the partitioning
    symbols must exist on the producing side with hashable declared dtypes
    (the consumer-side key compatibility is checked at the Join/Aggregation
    that required the repartition)."""
    if node.kind not in _EXCHANGE_KINDS:
        ctx.fail("bad-exchange-kind", node, f"unknown kind '{node.kind}'")
    _check_symbols(ctx, node, node.partition_symbols, avail, "partition")
    for s in node.partition_symbols:
        if isinstance(s.type, (T.ArrayType, T.MapType, T.RowType)):
            # packed composite layouts are not canonical per value (slot
            # order / tail padding): equal values can row-hash differently,
            # scattering one key group across workers
            ctx.fail(
                "exchange-key-not-hashable", node,
                f"partition symbol '{s.name}' has composite type "
                f"{s.type.name}, whose device layout does not hash "
                "canonically",
            )
    _check_symbols(
        ctx, node, [s for s, _, _ in node.orderings], avail, "merge-ordering"
    )


def _t_PatternRecognitionNode(ctx, node: P.PatternRecognitionNode, avail) -> None:
    _check_symbols(ctx, node, node.partition_by, avail, "partition")
    _check_symbols(ctx, node, [s for s, _, _ in node.order_by], avail, "order")
    for _, spec in node.measures:
        if spec.source is not None:
            _check_symbols(ctx, node, [spec.source], avail, "measure")


#: node type -> typing rule (reference: sanity/TypeValidator's visitor).
#: Nodes absent from the table get only the structural + generic checks.
NODE_TYPING_RULES = {
    P.FilterNode: _t_FilterNode,
    P.ProjectNode: _t_ProjectNode,
    P.AggregationNode: _t_AggregationNode,
    P.JoinNode: _t_JoinNode,
    P.SemiJoinNode: _t_SemiJoinNode,
    P.WindowNode: _t_WindowNode,
    P.SortNode: _t_SortNode,
    P.TopNNode: _t_TopNNode,
    P.LimitNode: _t_LimitNode,
    P.ValuesNode: _t_ValuesNode,
    P.UnionNode: _t_UnionNode,
    P.MarkDistinctNode: _t_MarkDistinctNode,
    P.UnnestNode: _t_UnnestNode,
    P.SampleNode: _t_SampleNode,
    P.OutputNode: _t_OutputNode,
    P.ExchangeNode: _t_ExchangeNode,
    P.PatternRecognitionNode: _t_PatternRecognitionNode,
}


def check_plan(root: P.PlanNode) -> list:
    """Run every sanity checker over a plan tree; returns violations
    (empty = clean).  Raising is the caller's decision via `enforce`."""
    ctx = _Ctx()
    seen_instances: set = set()
    seen_ids: dict = {}
    for node in P.walk(root):
        if id(node) in seen_instances:
            ctx.fail(
                "duplicate-node", node,
                "the same node instance appears twice in the tree "
                "(shared subtree breaks rewrites)",
            )
            continue
        seen_instances.add(id(node))
        nid = getattr(node, "id", 0)
        other = seen_ids.get(nid)
        if other is not None:
            ctx.fail(
                "duplicate-node-id", node,
                f"node id {nid} already used by {other}",
            )
        else:
            seen_ids[nid] = type(node).__name__
        if isinstance(node, P.TableScanNode):
            _t_TableScanNode(ctx, node)
            continue
        avail = _available(node)
        rule = NODE_TYPING_RULES.get(type(node))
        if rule is not None:
            rule(ctx, node, avail)
    return ctx.violations


def check_subplan(sub) -> list:
    """Fragment-level invariants after PlanFragmenter (reference:
    sanity-checking createSubPlans output): unique fragment ids, every
    RemoteSourceNode names an existing child fragment, and the declared
    remote symbols match the child fragment root's outputs name-for-name
    with consistent dtypes."""
    from trino_tpu.planner.fragmenter import RemoteSourceNode, SubPlan

    ctx = _Ctx()
    frags: dict = {}

    def register(s: SubPlan):
        if s.fragment.id in frags:
            ctx.fail(
                "duplicate-fragment-id", s.fragment.root,
                f"fragment id {s.fragment.id} appears twice",
            )
        else:
            frags[s.fragment.id] = s.fragment
        for c in s.children:
            register(c)

    register(sub)
    for fragment in frags.values():
        ctx.violations.extend(check_plan(fragment.root))
        for node in P.walk(fragment.root):
            if not isinstance(node, RemoteSourceNode):
                continue
            child = frags.get(node.fragment_id)
            if child is None:
                ctx.fail(
                    "dangling-remote-source", node,
                    f"references unknown fragment {node.fragment_id}",
                )
                continue
            child_out = child.root.outputs
            if [s.name for s in node.symbols] != [s.name for s in child_out]:
                ctx.fail(
                    "remote-symbol-mismatch", node,
                    f"declares {[s.name for s in node.symbols]} but fragment "
                    f"{node.fragment_id} outputs "
                    f"{[s.name for s in child_out]}",
                )
            else:
                for mine, theirs in zip(node.symbols, child_out):
                    if not _compat(mine.type, theirs.type):
                        ctx.fail(
                            "remote-symbol-mismatch", node,
                            f"'{mine.name}' declared {mine.type.name} but "
                            f"fragment {node.fragment_id} produces "
                            f"{theirs.type.name}",
                        )
            declared = {s.name for s in node.symbols}
            for s in node.partition_symbols:
                if s.name not in declared:
                    ctx.fail(
                        "exchange-key-missing", node,
                        f"partition symbol '{s.name}' not in the remote "
                        "source's outputs",
                    )
    return ctx.violations
