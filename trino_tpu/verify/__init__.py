"""Static-analysis verification subsystem (reference:
sql/planner/sanity/PlanSanityChecker.java plus the engine's own
device-residency contracts).

Three layers:

  * plan sanity checkers (`check_plan` / `check_subplan`) — structural,
    dependency, and per-node-type typing rules, run by the optimizer after
    analysis, after each fixpoint iteration, and after fragmentation;
  * kernel/SPMD verifier (`device_residency`, `cache_key_audit`) — replays
    a query and asserts the mesh pipeline's zero-host-round-trip and
    zero-warm-retrace contracts, and checks trace-cache key completeness
    against step-closure free variables;
  * AST lint (`tools/lint_tpu.py`) — flags host-sync hazards in device code
    at review time; wired into CI and the tier-1 test run.

Enforcement of the plan checkers follows the `verify_plan` session property
(strict | warn | off; default strict under pytest, warn in benches).
"""

from trino_tpu.verify.plan_checker import (
    LAST_WARNINGS,
    MODES,
    PlanViolation,
    check_plan,
    check_subplan,
    enforce,
    resolve_mode,
)
from trino_tpu.verify.partitioning import check_partitioning
from trino_tpu.verify.residency import (
    CacheKeyViolation,
    ResidencyViolation,
    cache_key_audit,
    closure_fingerprint,
    device_residency,
)

__all__ = [
    "LAST_WARNINGS",
    "MODES",
    "PlanViolation",
    "check_partitioning",
    "check_plan",
    "check_subplan",
    "enforce",
    "resolve_mode",
    "CacheKeyViolation",
    "ResidencyViolation",
    "cache_key_audit",
    "closure_fingerprint",
    "device_residency",
]
