"""Static-analysis verification subsystem (reference:
sql/planner/sanity/PlanSanityChecker.java plus the engine's own
device-residency contracts).

Three layers:

  * plan sanity checkers (`check_plan` / `check_subplan`) — structural,
    dependency, and per-node-type typing rules, run by the optimizer after
    analysis, after each fixpoint iteration, and after fragmentation;
  * kernel/SPMD verifier (`device_residency`, `cache_key_audit`) — replays
    a query and asserts the mesh pipeline's zero-host-round-trip and
    zero-warm-retrace contracts, and checks trace-cache key completeness
    against step-closure free variables;
  * AST lint (`tools/lint_tpu.py`) — flags host-sync hazards in device code
    at review time; wired into CI and the tier-1 test run;
  * concurrency analyzer (`concurrency.py`, run by the same lint tool) —
    guarded-state inference (`unguarded-state`), thread discipline, and
    static nested-with lock-order extraction, with a justified findings
    baseline in tools/lint_baseline.json;
  * dynamic lock-order verification (`lockgraph.py`) — instrumented locks
    record the acquisition-order graph during tests (chaos suite + seeded
    deadlock test) and fail on cycles;
  * collective-uniformity pass (`collectives.py`) — statically enumerates
    each distributed fragment's collective sequence, proves it
    divergence-free (never conditional on per-worker data), and records
    the signature `device_residency` holds warm replays to;
  * numeric-safety verifier (`numeric.py` + `ranges.py`) — abstract
    interpretation of (dtype, decimal precision/scale, value interval,
    nullability) over the expression IR: flags silent overflow wraps /
    scale mismatches / float contamination / dropped validity (sweep:
    `python -m trino_tpu.verify.numeric`, baseline in
    tools/lint_baseline.json `numeric_safety`), and emits range
    certificates that license provably-exact single-plane i64 decimal
    sum kernels (`license_decimal_sums`, run at the end of plan
    optimization); filter predicates refine the certificate facts
    (`refine_env`), extending the proofs to filter/join outputs;
  * capacity certificates (`capacity.py`) — sound join-cardinality
    proofs (build-key uniqueness from exact generator statistics +
    structural preservation, exact-filter row bounds, key-range proofs):
    a licensed join compiles its expand at a certified fixed capacity
    with NO sizing gather / overflow flag / speculative retry (sweep:
    `python -m trino_tpu.verify.capacity`; the verifier rule rejects any
    claim tighter than re-derivation proves);
  * collective-schedule licenses (`schedule.py`) — the divergence-freedom
    proof's scheduling consequence: independent, sync-free build-side
    fragments may pre-dispatch asynchronously, and `device_residency`
    verifies warm replays against the licensed schedule.

Enforcement of the plan checkers follows the `verify_plan` session property
(strict | warn | off; default strict under pytest, warn in benches).
"""

from trino_tpu.verify.plan_checker import (
    LAST_WARNINGS,
    MODES,
    PlanViolation,
    check_plan,
    check_subplan,
    enforce,
    resolve_mode,
)
from trino_tpu.verify.partitioning import check_partitioning
from trino_tpu.verify.capacity import (
    CapacityCertificate,
    GroupCapacityCertificate,
    check_capacity_certificates,
    derive_group_certificate,
    derive_join_certificate,
    license_join_capacities,
    multiplicity_bound,
    seal_licenses,
)
from trino_tpu.verify.schedule import ScheduleLicense, license_schedule
from trino_tpu.verify.collectives import (
    check_collective_uniformity,
    collective_signature,
    signature_problems,
)
from trino_tpu.verify.lockgraph import (
    InstrumentedLock,
    LockGraph,
    LockOrderViolation,
)
from trino_tpu.verify.residency import (
    CacheKeyViolation,
    ResidencyViolation,
    cache_key_audit,
    closure_fingerprint,
    device_residency,
)

__all__ = [
    "LAST_WARNINGS",
    "MODES",
    "PlanViolation",
    "check_partitioning",
    "check_plan",
    "check_subplan",
    "enforce",
    "resolve_mode",
    "CacheKeyViolation",
    "ResidencyViolation",
    "cache_key_audit",
    "closure_fingerprint",
    "device_residency",
    "check_collective_uniformity",
    "collective_signature",
    "signature_problems",
    "CapacityCertificate",
    "GroupCapacityCertificate",
    "check_capacity_certificates",
    "derive_group_certificate",
    "derive_join_certificate",
    "license_join_capacities",
    "multiplicity_bound",
    "seal_licenses",
    "ScheduleLicense",
    "license_schedule",
    "InstrumentedLock",
    "LockGraph",
    "LockOrderViolation",
]
