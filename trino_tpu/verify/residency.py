"""Kernel/SPMD static+dynamic verifier: device-residency and trace-cache
contracts as assertable facts.

PR 1 made the mesh fast by keeping fragment chains device-resident and
caching every compiled SPMD program in `spmd.TRACE_CACHE`; the proof was
counters (`host_restack`, `retraces`) that nothing asserted.  This module
turns them into contracts:

  * `device_residency(runner, sql)` replays a query on a warmed mesh and
    raises `ResidencyViolation` if a distributed fragment chain performs an
    unexpected host transfer (a host batch re-entering the mesh mid-query)
    or if a warm execution retraces any program;
  * `cache_key_audit()` wraps `spmd.TRACE_CACHE` and checks cache-key
    completeness: the step closure's free variables are fingerprinted and
    hashed against the declared cache key — two different closures arriving
    under one key means the key under-describes the program (the class of
    bug that silently serves a stale compiled program, e.g. a dynamic-filter
    range baked into a step but missing from its key).
"""

from __future__ import annotations

import hashlib
from contextlib import contextmanager
from typing import Optional

import numpy as np

from trino_tpu.parallel.spmd import TRACE_CACHE


class ResidencyViolation(Exception):
    """A device-residency or trace-cache contract failed."""


class CacheKeyViolation(ResidencyViolation):
    """Two distinct step closures arrived under one trace-cache key."""


# -- closure fingerprinting ---------------------------------------------------

_MAX_DEPTH = 5
_MAX_SEQ = 64
_MAX_ARRAY_BYTES = 1 << 16


def _array_fp(v) -> tuple:
    shape = tuple(getattr(v, "shape", ()))
    dtype = str(getattr(v, "dtype", ""))
    size = int(np.prod(shape)) if shape else 1
    if size * getattr(v, "itemsize", 8) <= _MAX_ARRAY_BYTES:
        try:
            digest = hashlib.sha1(np.asarray(v).tobytes()).hexdigest()[:16]
            return ("array", shape, dtype, digest)
        except Exception:
            pass
    return ("array", shape, dtype)


def _value_fp(v, depth: int) -> tuple:
    """Semantic fingerprint of one closure constant.  Primitives by value
    (the dynamic-filter-range class of key bugs), arrays by content hash
    when small, callables recursively, opaque objects by type name only —
    an operator instance's semantics are expected to live in the key
    already, and object identity would only produce false positives."""
    if depth > _MAX_DEPTH:
        return ("depth",)
    if v is None or isinstance(v, (bool, int, str, bytes)):
        return ("prim", v)
    if isinstance(v, float):
        return ("prim", repr(v))  # repr: NaN-stable
    if isinstance(v, (tuple, list)):
        return ("seq", type(v).__name__) + tuple(
            _value_fp(x, depth + 1) for x in v[:_MAX_SEQ]
        )
    if isinstance(v, dict):
        items = sorted(v.items(), key=lambda kv: repr(kv[0]))[:_MAX_SEQ]
        return ("map",) + tuple(
            (repr(k), _value_fp(x, depth + 1)) for k, x in items
        )
    if hasattr(v, "shape") and hasattr(v, "dtype"):
        return _array_fp(v)
    if callable(v):
        return ("fn", getattr(v, "__qualname__", type(v).__name__),
                closure_fingerprint(v, depth + 1))
    return ("obj", type(v).__name__)


def closure_fingerprint(fn, depth: int = 0) -> tuple:
    """Fingerprint of a callable's free variables (recursing through nested
    closures).  Two builders with equal fingerprints would compile
    equivalent programs for the purposes of the cache-key contract.
    Always returns a tuple of (name, value-fingerprint) pairs so
    fingerprints diff uniformly."""
    import functools

    code = getattr(fn, "__code__", None)
    if code is None:
        out = [("$type", ("prim", type(fn).__name__))]
        if isinstance(fn, functools.partial) and depth <= _MAX_DEPTH:
            out.append(("$partial.func", ("fn", getattr(fn.func, "__qualname__", ""),
                                          closure_fingerprint(fn.func, depth + 1))))
            out.append(("$partial.args", _value_fp(fn.args, depth + 1)))
            out.append(("$partial.kw", _value_fp(fn.keywords or {}, depth + 1)))
            return tuple(out)
        call = getattr(type(fn), "__call__", None)
        if (
            call is not None
            and getattr(call, "__code__", None) is not None
            and depth <= _MAX_DEPTH
        ):
            # callable object: fingerprint its __call__ closure plus its
            # instance dict (the state a builder object would bake in)
            out.append(("$call", ("fn", type(fn).__name__,
                                  closure_fingerprint(call, depth + 1))))
            inst = getattr(fn, "__dict__", None)
            if inst:
                out.append(("$self", _value_fp(inst, depth + 1)))
        return tuple(out)
    out = [("$code", (code.co_filename, code.co_firstlineno))]
    cells = getattr(fn, "__closure__", None) or ()
    for name, cell in zip(code.co_freevars, cells):
        try:
            val = cell.cell_contents
        except ValueError:  # not yet filled
            out.append((name, ("empty",)))
            continue
        out.append((name, _value_fp(val, depth)))
    defaults = getattr(fn, "__defaults__", None) or ()
    for i, d in enumerate(defaults):
        out.append((f"$default{i}", _value_fp(d, depth)))
    return tuple(out)


class CacheKeyAuditor:
    """Records key -> closure fingerprint across TRACE_CACHE traffic and
    raises when one key arrives with two different closures."""

    def __init__(self):
        self.seen: dict = {}
        self.checked = 0

    def __call__(self, key, build) -> None:
        fp = closure_fingerprint(build)
        self.checked += 1
        prev = self.seen.get(key)
        if prev is None:
            self.seen[key] = fp
            return
        if prev != fp:
            diffs = _fp_diff(prev, fp)
            raise CacheKeyViolation(
                "trace-cache key is incomplete: two step closures with "
                f"different free variables share key {key!r}; differing "
                f"free variables: {diffs}"
            )


def _fp_diff(a: tuple, b: tuple) -> list:
    try:
        da, db = dict(a), dict(b)
    except (TypeError, ValueError):  # defensive: irregular fingerprint shape
        return ["<unstructured fingerprint>"]
    names = sorted(set(da) | set(db))
    return [n for n in names if da.get(n) != db.get(n)]


@contextmanager
def cache_key_audit():
    """Enable the trace-cache key-completeness audit for a scope."""
    auditor = CacheKeyAuditor()
    prev = TRACE_CACHE.audit
    TRACE_CACHE.audit = auditor
    try:
        yield auditor
    finally:
        TRACE_CACHE.audit = prev


# -- device residency ---------------------------------------------------------


def _collective_problems(runner, prof, prev_seq) -> list:
    """The warm run's per-fragment mesh-collective sequence must equal the
    previous run's (replays issue the recorded sequence) and match the
    static signature the uniformity pass enumerated at planning time."""
    problems = []
    seq = prof.collective_sequences()
    if prev_seq is not None and seq != prev_seq:
        for fid in sorted(set(seq) | set(prev_seq)):
            a, b = prev_seq.get(fid, ()), seq.get(fid, ())
            if a != b:
                problems.append(
                    f"fragment {fid} issued a different collective "
                    f"sequence on the warm run: {b} (previous run: {a})"
                )
    expected = getattr(runner, "last_collective_signature", None)
    if expected is not None:
        from trino_tpu.verify.collectives import signature_problems

        problems.extend(signature_problems(expected, seq))
    # collective-schedule license (verify/schedule.py): a licensed query's
    # warm replay must issue exactly the LICENSED per-fragment schedule —
    # async pre-dispatch may reorder ACROSS independent fragments, never
    # within one, so the per-fragment witness comparison still holds.
    # The license is normally stamped from the same subplan as
    # last_collective_signature (already checked above); only compare
    # again when the two witnesses actually differ.
    lic = getattr(runner, "last_schedule_license", None)
    if lic is not None and lic.fragments != expected:
        from trino_tpu.verify.collectives import signature_problems

        problems.extend(
            f"[licensed schedule] {p}"
            for p in signature_problems(lic.fragments, seq)
        )
    return problems

#: mesh-profile counters that are LEGITIMATE host boundaries: explicit
#: gathers at SINGLE-fragment/result edges, the batched dynamic-filter sync,
#: scan-cache bookkeeping, and FTE spooling.  `host_restack` is deliberately
#: absent: a host batch re-entering the mesh between distributed fragments
#: is the hidden round-trip this contract exists to catch.
ALLOWED_COUNTERS = (
    "result_gather",
    "host_gather",
    "state_gather",
    "scan_cache_hit",
    "scan_cache_miss",
    "scan_bucketize",
    "dynamic_filter_sync",
    "spool_read",
    "spool_write",
    # partitioning-aware execution: elision bookkeeping is not a transfer,
    # and the speculative join's post-hoc [W] overflow-flag read is a
    # declared tiny boundary.  `join_capacity_sync` (the speculative-off
    # blocking match-count sync) and `join_speculative_retry` are
    # deliberately ABSENT: a warm partitioned join must neither block on
    # capacities nor retry its expand.
    "exchange_elided",
    "repartition_collective",
    "join_overflow_check",
    # proof-licensed execution (verify/capacity.py + verify/schedule.py):
    # bookkeeping, not transfers — a licensed join compiled at its
    # certified fixed capacity, and a schedule-licensed child fragment
    # pre-dispatched asynchronously
    "join_capacity_proven",
    "collective_async",
)


def device_residency(
    runner,
    sql: str,
    warmups: int = 1,
    allowed_counters: tuple = ALLOWED_COUNTERS,
    audit_cache_keys: bool = True,
    check_collectives: bool = True,
) -> dict:
    """Replay `sql` on a warmed mesh and assert the device-residency
    contracts of the distributed pipeline:

      * zero retraces — every compiled SPMD program came out of the trace
        cache (a warm retrace means a cache key misses shape/semantic
        state);
      * zero unexpected host transfers — no counter outside
        `allowed_counters` fires, in particular `host_restack` (a host
        batch re-entering the mesh between distributed fragments);
      * collective-sequence stability — the warm run issues exactly the
        per-fragment mesh-collective sequence the previous run issued AND
        the sequence the static uniformity pass recorded
        (`runner.last_collective_signature`, verify/collectives.py): an
        extra, missing, or reordered collective on a warm replay is a
        divergence hazard even when nothing hung this time;
      * (optional) cache-key completeness over the replay's cache traffic.

    Returns a report dict on success; raises ResidencyViolation on failure.
    `runner` is a DistributedQueryRunner (anything with .execute and
    .last_mesh_profile).
    """
    auditor: Optional[CacheKeyAuditor] = None
    ctx = cache_key_audit() if audit_cache_keys else None
    prev_seq = None
    try:
        if ctx is not None:
            auditor = ctx.__enter__()
        for _ in range(max(0, warmups)):
            runner.execute(sql)
            prev = getattr(runner, "last_mesh_profile", None)
            if prev is not None:
                prev_seq = prev.collective_sequences()
        runner.execute(sql)
    finally:
        if ctx is not None:
            ctx.__exit__(None, None, None)
    prof = runner.last_mesh_profile
    if prof is None:
        raise ResidencyViolation(
            "query produced no mesh profile — not a distributed execution"
        )
    problems = []
    if prof.retraces:
        problems.append(
            f"warm execution retraced {prof.retraces} SPMD program(s) "
            "(trace-cache key misses shape or semantic state)"
        )
    for name, n in sorted(prof.counters.items()):
        if n and name not in allowed_counters:
            problems.append(
                f"unexpected host transfer: counter '{name}' fired {n}x "
                "on the warm run"
            )
    if check_collectives:
        problems.extend(_collective_problems(runner, prof, prev_seq))
    if problems:
        raise ResidencyViolation(
            f"device residency violated for {sql!r}: " + "; ".join(problems)
        )
    # telemetry contract: the report records whether span tracing was live
    # during the verified replay — a residency pass with tracing_enabled
    # proves the tracer added no host syncs (spans time host wall only)
    props = getattr(runner, "properties", None)
    tracing = bool(props is not None and props.get("query_trace"))
    trace = getattr(runner, "last_trace", None)
    return {
        "sql": sql,
        "retraces": prof.retraces,
        "trace_hits": prof.trace_hits,
        "trace_misses": prof.trace_misses,
        "counters": dict(prof.counters),
        "cache_keys_checked": auditor.checked if auditor else 0,
        "tracing_enabled": tracing,
        "spans": len(trace["traceEvents"]) if (tracing and trace) else 0,
    }
