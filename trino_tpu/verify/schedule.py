"""Collective-schedule licenses: proof-gated asynchronous dispatch of
independent fragments' collectives.

PR 9 proved every distributed fragment's collective sequence
divergence-free and statically known (`verify.collectives` —
`FragmentStats.collective_seq` is the runtime witness).  That proof has a
scheduling consequence this module cashes in: when the per-fragment
sequences are fixed by plan structure and never conditional on per-worker
data, the COORDINATOR may choose any interleaving of *independent*
fragments' programs and every worker still observes identical, uniform
dispatch (single-controller SPMD: workers run whole compiled programs in
the coordinator's issue order — there is no per-worker reordering to
diverge).  So independent fragments' collectives can be dispatched
asynchronously, back to back, letting exchange traffic overlap host-side
compute instead of serializing behind it.

A `ScheduleLicense` is emitted per query at fragmentation time and
records:

  * the per-fragment mesh-collective witness (the PR 9 signature) the warm
    replay is held to — `verify.residency` asserts a licensed query's warm
    replays issue EXACTLY the licensed schedule;
  * `async_children`: for each consumer fragment, the child fragments the
    executor may PRE-DISPATCH eagerly before executing the consumer's
    body.  Licensed children are the build-side feeds on the body's
    FIRST-EVALUATED spine — the feeds the lazy executor would run first
    anyway, before any of the body's dynamic filters register — so
    pre-dispatch preserves dynamic-filter ordering by construction.
    Probe-side feeds, and build feeds the lazy order evaluates only
    AFTER a sibling join's filters register (e.g. nested in a probe
    subtree), are deliberately NOT licensed: executing one early would
    run its scans unpruned.

Licensing preconditions (all statically checked; no license otherwise):

  * every fragment passes `check_collective_uniformity` — the divergence
    proof is what makes coordinator-chosen interleavings uniform;
  * each licensed child fragment is itself distributed and SYNC-FREE: its
    enumerated sequence contains no unconditional `gather` (host-pull)
    entries, so its dispatch cannot block the queue on a host round-trip.
    Capacity-certified joins (verify/capacity.py) satisfy this — their
    sizing gather is deleted — which is how the two license families
    compose: the capacity proof removes the sync, the schedule license
    then authorizes overlapping the freed dispatch.

The executor bumps `collective_async_total` per licensed pre-dispatch;
`tools/compare_bench.py check_licenses` gates the counter alongside the
join-capacity counters.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from trino_tpu.planner import plan as P
from trino_tpu.planner.fragmenter import RemoteSourceNode, SubPlan
from trino_tpu.verify.collectives import (
    _DIST_KINDS,
    check_collective_uniformity,
    collective_signature,
    fragment_collectives,
)


@dataclass
class ScheduleLicense:
    """Per-query authorization for asynchronous collective dispatch."""

    #: {fragment id: ((kind, purpose, elidable), ...)} — the statically
    #: recorded mesh-collective schedule warm replays must issue
    fragments: dict = field(default_factory=dict)
    #: {consumer fragment id: (child fragment ids licensed for eager
    #: pre-dispatch, in build order)}
    async_children: dict = field(default_factory=dict)
    #: mesh width the license was issued for
    mesh_w: int = 0

    def licensed_count(self) -> int:
        return sum(len(v) for v in self.async_children.values())

    def to_json(self) -> dict:
        return {
            "fragments": {
                int(k): [list(c) for c in v]
                for k, v in self.fragments.items()
            },
            "async_children": {
                int(k): list(v) for k, v in self.async_children.items()
            },
            "mesh_w": int(self.mesh_w),
        }


def _sync_free(sub: SubPlan) -> bool:
    """A fragment whose statically enumerated sequence contains no
    unconditional host-pull: its dispatch never blocks the device queue on
    a sizing round-trip.  Elidable gathers (capacity-certified joins,
    runtime-elided sizing) are licensed absences, not syncs."""
    cols, violations = fragment_collectives(sub)
    if violations:
        return False
    return not any(c.kind == "gather" and not c.elidable for c in cols)


def _subtree_registers_filters(node) -> bool:
    """Whether lazily evaluating `node` can register dynamic filters
    (inner joins do, after their build side returns)."""
    if isinstance(node, RemoteSourceNode):
        return False
    if isinstance(node, (P.JoinNode, P.SemiJoinNode)):
        return True  # conservative: any join family counts
    return any(_subtree_registers_filters(c) for c in node.children)


def _build_side_children(sub: SubPlan) -> tuple:
    """Child fragment ids safe to PRE-DISPATCH: the feeds on the fragment
    body's first-evaluated spine, which the lazy executor would run
    before any of this fragment's dynamic filters register.

    Collection STOPS at the first join whose build feed completes — the
    executor registers that join's dynamic filters next (inner joins,
    `_register_dynamic_filters`), so a feed the lazy order evaluates
    later (e.g. a build feed nested in the probe subtree) must stay lazy:
    pre-dispatching it would run its scans before the filters that prune
    them.  Semi-joins evaluate their SOURCE side first, so the filtering
    feed is licensed only when the source subtree provably registers no
    filters ahead of it."""
    order: list = []

    def first(node) -> None:
        if isinstance(node, P.JoinNode):
            # executor evaluates the build (right) side first; filters
            # register before the probe side is ever pulled
            if isinstance(node.right, RemoteSourceNode):
                order.append(node.right.fragment_id)
            else:
                first(node.right)
            return
        if isinstance(node, P.SemiJoinNode):
            if isinstance(
                node.filtering, RemoteSourceNode
            ) and not _subtree_registers_filters(node.source):
                order.append(node.filtering.fragment_id)
            return
        # single-input operators preserve evaluation order; multi-input
        # nodes (unions) have no statically safe prefix — stop there
        if len(node.children) == 1 and not isinstance(
            node.children[0], RemoteSourceNode
        ):
            first(node.children[0])

    first(sub.fragment.root)
    # preserve first-reference order, drop duplicates
    seen: set = set()
    out = []
    for fid in order:
        if fid not in seen:
            seen.add(fid)
            out.append(fid)
    return tuple(out)


def license_schedule(sub: SubPlan, n_workers: int):
    """-> ScheduleLicense, or None when the divergence-freedom
    precondition fails (a fragment with an unproven collective sequence
    must keep strictly lazy, order-conservative dispatch)."""
    if check_collective_uniformity(sub):
        return None
    by_fid: dict = {}

    def index(s: SubPlan) -> None:
        by_fid[s.fragment.id] = s
        for c in s.children:
            index(c)

    index(sub)
    async_children: dict = {}
    for fid, s in by_fid.items():
        if s.fragment.partitioning.kind not in _DIST_KINDS:
            continue
        licensed = tuple(
            cfid
            for cfid in _build_side_children(s)
            if cfid in by_fid
            and by_fid[cfid].fragment.partitioning.kind in _DIST_KINDS
            and _sync_free(by_fid[cfid])
        )
        if licensed:
            async_children[fid] = licensed
    return ScheduleLicense(
        fragments=collective_signature(sub),
        async_children=async_children,
        mesh_w=int(n_workers),
    )
