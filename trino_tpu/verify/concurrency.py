"""Static concurrency analyzer: guarded-state inference, thread discipline,
and static lock-order extraction (stdlib `ast` only).

PRs 5-8 made the engine genuinely concurrent — heartbeat probe loops,
prewarm replay threads, drain waiters, breaker registries, and an engine
lock now span ~20 `threading.Lock`/`Thread` sites — but nothing checked
lock discipline: the next "stale state read bricks the runner" bug would be
found by chaos luck, not analysis.  This module is the analysis.  Three
passes, all wired into `tools/lint_tpu.py` (and through it into CI and
tests/test_verify.py):

  * **Guarded-state inference** (`unguarded-state`).  Per class, the
    analyzer learns which `self._x` attributes are lock-guarded — any
    attribute accessed at least once inside a `with self._lock:` block of
    that class — and flags every read or write of the same attribute
    outside any lock.  `__init__` is exempt (construction precedes
    publication), attribute *calls* (`self.clock()`) are treated as
    behavior, not state, and only attributes the class mutates after
    construction are flaggable (immutable config can't race).  Simple
    self-aliases (`worker = self`; the nested-HTTP-handler idiom) are
    followed, including into nested functions and classes — exactly where
    the cross-thread accesses live.
  * **Thread discipline** (`thread-discipline`).  Every
    `threading.Thread(...)` in engine code must pass `name=` AND an
    explicit `daemon=`: unnamed threads made the PR 7/8 drain and prewarm
    bugs hard to attribute in stack dumps.
  * **Static lock-order extraction** (`lock-order-cycle`).  Nested
    `with <lock>:` statements contribute edges to a repo-wide
    acquisition-order graph over canonical lock names (`Class._lock`,
    `module:NAME`); a cycle is a potential deadlock, reported at every
    witness site.  This is the cheap-80% static half; the dynamic half
    (cross-function nesting, real thread interleavings) is
    `trino_tpu.verify.lockgraph`.

Suppression: the same `# lint: allow(<rule>)` line/def/class comments the
device lint uses.  `unguarded-state` findings additionally triage through a
checked-in baseline (tools/lint_baseline.json, key "unguarded_state"):
every surviving finding must have a `file:Class.attr` entry whose value is
a one-line justification, so each deliberate unguarded access is a
reviewed decision with a recorded why.  New findings outside the baseline
fail the lint.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

#: threading factory names whose result is a lock object
_LOCK_FACTORIES = frozenset({"Lock", "RLock"})

#: method calls that MUTATE their receiver (a `self._x.append(...)` is a
#: write to the guarded collection, not a read)
_MUTATORS = frozenset({
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "add", "discard", "update", "setdefault", "sort", "reverse",
})

#: keep in sync with tools/lint_tpu.py — the grammar is duplicated ON
#: PURPOSE: the device lint must stay a self-contained stdlib script that
#: works even when this package file is absent (partial checkouts), while
#: this module must import without the tools/ directory
_ALLOW_RE = re.compile(r"#\s*lint:\s*allow\(([^)]*)\)")


@dataclass
class Finding:
    file: str
    line: int
    rule: str
    message: str
    #: baseline key for unguarded-state findings ("file:Class.attr")
    key: str = ""

    def __str__(self):
        return f"{self.file}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class Access:
    line: int
    cls: str
    method: str
    attr: str
    kind: str  # "read" | "write"
    guarded: bool
    locks_held: tuple = ()


@dataclass
class ClassReport:
    """Per-class lock/state summary the inference runs over."""

    name: str
    file: str
    line: int
    locks: set = field(default_factory=set)
    accesses: list = field(default_factory=list)

    def guarded_attrs(self) -> set:
        """Attributes accessed at least once under one of this class's own
        locks — the inferred lock-guarded state."""
        return {a.attr for a in self.accesses if a.guarded}

    def mutated_attrs(self) -> set:
        """Attributes written outside __init__ somewhere in the class —
        only these can race (construction-frozen config cannot)."""
        return {
            a.attr
            for a in self.accesses
            if a.kind == "write" and a.method != "__init__"
        }


def _allowances(source: str) -> dict:
    out: dict = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _ALLOW_RE.search(text)
        if m:
            out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


def _is_lock_factory_call(node: ast.AST) -> bool:
    """Does this expression (sub)tree construct a threading lock?"""
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            fn = n.func
            if isinstance(fn, ast.Attribute) and fn.attr in _LOCK_FACTORIES:
                return True
            if isinstance(fn, ast.Name) and fn.id in _LOCK_FACTORIES:
                return True
    return False


class _ClassAnalyzer(ast.NodeVisitor):
    """Walk one ClassDef recording self-attribute accesses and the lexical
    with-lock nesting around them.  `self` aliases assigned inside methods
    (`worker = self`) are tracked class-wide: nested handler classes and
    waiter closures access state through them from OTHER threads, which is
    exactly the surface this analysis exists for."""

    def __init__(self, cls: ast.ClassDef, path: str):
        self.report = ClassReport(cls.name, path, cls.lineno)
        self._cls = cls
        #: names that refer to the instance ("self" + aliases)
        self._selves = {"self"}
        #: current method name (top-level def within the class)
        self._method = "?"
        #: stack of lock attr names currently held (lexical with-blocks)
        self._held: list = []
        #: attrs assigned a lock object (first pass)
        self._find_locks()

    # -- pass 1: which attributes hold locks ----------------------------------

    def _find_locks(self) -> None:
        for node in ast.walk(self._cls):
            if isinstance(node, ast.Assign) and _is_lock_factory_call(
                node.value
            ):
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        self.report.locks.add(t.attr)
            # adopted locks (`self._engine_lock = lock`): the name says lock
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                        and t.attr.lower().endswith("lock")
                    ):
                        self.report.locks.add(t.attr)

    # -- pass 2: accesses ------------------------------------------------------

    def run(self) -> ClassReport:
        for stmt in self._cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._method = stmt.name
                self.generic_visit(stmt)
        return self.report

    def _is_self(self, node: ast.AST) -> bool:
        return isinstance(node, ast.Name) and node.id in self._selves

    def _self_attr(self, node: ast.AST):
        if isinstance(node, ast.Attribute) and self._is_self(node.value):
            return node.attr
        return None

    def visit_Assign(self, node: ast.Assign) -> None:
        # alias tracking: `worker = self`
        if self._is_self(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self._selves.add(t.id)
        for t in node.targets:
            self._mark_target(t)
        self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._mark_target(node.target, aug=True)
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self._mark_target(node.target)
        if node.value is not None:
            self.visit(node.value)

    def _mark_target(self, t: ast.AST, aug: bool = False) -> None:
        attr = self._self_attr(t)
        if attr is not None:
            self._record(t.lineno, attr, "write")
            return
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._mark_target(e)
            return
        if isinstance(t, (ast.Subscript, ast.Attribute)) and not isinstance(
            t, ast.Name
        ):
            # self._tasks[k] = v / self._x.y = v: mutation THROUGH the attr
            attr = self._self_attr(t.value)
            if attr is not None:
                self._record(t.value.lineno, attr, "write")
            else:
                self.visit(t.value)
            if isinstance(t, ast.Subscript):
                self.visit(t.slice)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            base = (
                t.value if isinstance(t, (ast.Subscript, ast.Attribute)) else t
            )
            attr = self._self_attr(base)
            if attr is not None:
                self._record(base.lineno, attr, "write")
            else:
                self.visit(t)

    def visit_With(self, node: ast.With) -> None:
        acquired = []
        for item in node.items:
            expr = item.context_expr
            # `with self._lock:` (Call form `with self._lock.acquire():`
            # never appears; Lock context managers are bare attributes)
            attr = self._self_attr(expr)
            if attr is not None and attr in self.report.locks:
                acquired.append(attr)
            else:
                self.visit(expr)
        self._held.extend(acquired)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self._held.pop()

    def visit_Call(self, node: ast.Call) -> None:
        # `self.clock()` — calling an attribute is behavior, not state: the
        # callable itself is construction-frozen config in this codebase
        attr = self._self_attr(node.func)
        if attr is None:
            # `self._x.append(v)` mutates the guarded collection
            fn = node.func
            if isinstance(fn, ast.Attribute):
                base_attr = self._self_attr(fn.value)
                if base_attr is not None:
                    kind = "write" if fn.attr in _MUTATORS else "read"
                    self._record(fn.value.lineno, base_attr, kind)
                    for a in node.args:
                        self.visit(a)
                    for k in node.keywords:
                        self.visit(k.value)
                    return
            self.visit(node.func)
        for a in node.args:
            self.visit(a)
        for k in node.keywords:
            self.visit(k.value)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = self._self_attr(node)
        if attr is not None:
            self._record(node.lineno, attr, "read")
            return
        self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # nested defs (waiter closures) run on other threads with the SAME
        # lexical held-set view: a `with self._lock:` wrapping a def does
        # not guard the def's eventual execution, so reset the held stack
        saved, self._held = self._held, []
        self.generic_visit(node)
        self._held = saved

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        # nested class (the HTTP Handler idiom): its methods access state
        # via a self-alias; held locks never span into them
        saved, self._held = self._held, []
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                method, self._method = self._method, f"{node.name}.{stmt.name}"
                self.generic_visit(stmt)
                self._method = method
        self._held = saved

    def _record(self, line: int, attr: str, kind: str) -> None:
        if attr in self.report.locks or attr.startswith("__"):
            return
        self.report.accesses.append(
            Access(
                line,
                self.report.name,
                self._method,
                attr,
                kind,
                guarded=bool(self._held),
                locks_held=tuple(self._held),
            )
        )


# -- module-level lock discovery (for the static lock-order graph) ------------


def _module_locks(tree: ast.Module) -> set:
    out = set()
    for node in tree.body:
        if isinstance(node, ast.Assign) and _is_lock_factory_call(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


class _OrderExtractor(ast.NodeVisitor):
    """Collect (outer lock, inner lock) edges from nested with-statements.
    Lock names are canonical: `Class.attr` for instance locks (the class
    the with appears in), `module:NAME` for module-level locks."""

    def __init__(self, path: str, class_locks: dict, module_locks: set,
                 modname: str):
        self.path = path
        self.class_locks = class_locks  # class name -> lock attr set
        self.module_locks = module_locks
        self.modname = modname
        self.edges: list = []  # (outer, inner, line)
        self._cls: list = []
        self._held: list = []

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._cls.append(node.name)
        self.generic_visit(node)
        self._cls.pop()

    def _lock_name(self, expr: ast.AST):
        if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
            for cls in reversed(self._cls):
                if expr.attr in self.class_locks.get(cls, ()):
                    return f"{cls}.{expr.attr}"
            # self._lock in a class we did not map (alias receiver): accept
            # when the attr is lock-named and we are inside a class
            if self._cls and expr.attr.lower().endswith("lock"):
                return f"{self._cls[-1]}.{expr.attr}"
            return None
        if isinstance(expr, ast.Name) and expr.id in self.module_locks:
            return f"{self.modname}:{expr.id}"
        return None

    def visit_With(self, node: ast.With) -> None:
        acquired = []
        for item in node.items:
            name = self._lock_name(item.context_expr)
            if name is not None:
                for outer in self._held:
                    if outer != name:
                        self.edges.append((outer, name, item.context_expr.lineno))
                acquired.append(name)
        self._held.extend(acquired)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self._held.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # a nested def's body executes later, outside the lexical with
        saved, self._held = self._held, []
        self.generic_visit(node)
        self._held = saved

    visit_AsyncFunctionDef = visit_FunctionDef


def find_cycles(edges) -> list:
    """Cycles in a directed graph given as (a, b[, witness]) edges; returns
    a list of node-name lists, each a closed walk a -> ... -> a."""
    adj: dict = {}
    for e in edges:
        a, b = e[0], e[1]
        adj.setdefault(a, set()).add(b)
    cycles = []
    seen_cycles = set()
    # DFS with a recursion stack; report each back-edge cycle once
    state: dict = {}  # 0 unvisited / 1 on stack / 2 done

    def dfs(u, stack):
        state[u] = 1
        stack.append(u)
        for v in adj.get(u, ()):
            if state.get(v, 0) == 0:
                dfs(v, stack)
            elif state.get(v) == 1:
                cyc = stack[stack.index(v):] + [v]
                key = frozenset(cyc)
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    cycles.append(cyc)
        stack.pop()
        state[u] = 2

    for n in list(adj):
        if state.get(n, 0) == 0:
            dfs(n, [])
    return cycles


# -- file / tree analysis ------------------------------------------------------


def analyze_source(path: str, source: str):
    """-> (class reports, thread findings, lock-order edges).  Pure AST; the
    caller applies suppressions and the baseline."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [], [Finding(path, e.lineno or 0, "syntax-error", str(e))], []
    reports = []
    class_locks: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            rep = _ClassAnalyzer(node, path).run()
            if rep.locks:
                reports.append(rep)
            class_locks[rep.name] = rep.locks
    thread_findings = _thread_discipline(path, tree)
    modname = os.path.basename(path).rsplit(".", 1)[0]
    extractor = _OrderExtractor(
        path, class_locks, _module_locks(tree), modname
    )
    extractor.visit(tree)
    return reports, thread_findings, extractor.edges


def _thread_discipline(path: str, tree: ast.Module) -> list:
    """`threading.Thread(...)` without name= or an explicit daemon=."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        is_thread = (
            isinstance(fn, ast.Attribute)
            and fn.attr == "Thread"
            and isinstance(fn.value, ast.Name)
            and fn.value.id == "threading"
        ) or (isinstance(fn, ast.Name) and fn.id == "Thread")
        if not is_thread:
            continue
        kw = {k.arg for k in node.keywords}
        missing = [k for k in ("name", "daemon") if k not in kw]
        if missing:
            out.append(
                Finding(
                    path, node.lineno, "thread-discipline",
                    "threading.Thread without explicit "
                    f"{' and '.join(missing)}= — unnamed/implicit-daemon "
                    "threads made the drain and prewarm bugs hard to "
                    "attribute in stack dumps",
                )
            )
    return out


def unguarded_findings(reports) -> list:
    """Apply the inference over class reports: accesses of lock-guarded
    attributes outside any lock, excluding __init__ and attributes never
    mutated after construction."""
    out = []
    for rep in reports:
        guarded = rep.guarded_attrs() & rep.mutated_attrs()
        if not guarded:
            continue
        for a in rep.accesses:
            if a.guarded or a.attr not in guarded:
                continue
            if a.method == "__init__":
                continue
            out.append(
                Finding(
                    rep.file, a.line, "unguarded-state",
                    f"{a.kind} of {rep.name}.{a.attr} outside any lock, but "
                    "the same attribute is accessed under a with-lock "
                    "elsewhere in the class — take the lock, or record a "
                    "justified baseline entry / # lint: allow(unguarded-state)",
                    key=f"{rep.file}:{rep.name}.{a.attr}",
                )
            )
    return out


def analyze_paths(paths, root: str = "."):
    """Analyze every .py under `paths` (relative to root).  Returns
    (findings, lock-order edges); findings cover unguarded-state and
    thread-discipline with `# lint: allow(...)` already applied, plus any
    lock-order-cycle findings over the whole path set."""
    findings: list = []
    all_edges: list = []  # (outer, inner, "file:line")
    files = []
    for p in paths:
        full = os.path.join(root, p)
        if os.path.isfile(full):
            files.append(full)
            continue
        for dirpath, _, names in os.walk(full):
            files.extend(
                os.path.join(dirpath, n) for n in names if n.endswith(".py")
            )
    for f in sorted(files):
        with open(f, "r", encoding="utf-8") as fh:
            source = fh.read()
        rel = os.path.relpath(f, root).replace(os.sep, "/")
        reports, threads, edges = analyze_source(rel, source)
        allow = _allowances(source)
        scopes = _scope_index(source)
        raw = unguarded_findings(reports) + threads
        for fd in raw:
            if not _suppressed(fd, allow, scopes):
                findings.append(fd)
        all_edges.extend((a, b, f"{rel}:{ln}") for a, b, ln in edges)
    for cyc in find_cycles(all_edges):
        pairs = set(zip(cyc, cyc[1:]))
        witnesses = sorted(
            w for a, b, w in all_edges if (a, b) in pairs
        )
        findings.append(
            Finding(
                witnesses[0].rsplit(":", 1)[0] if witnesses else "<repo>",
                int(witnesses[0].rsplit(":", 1)[1]) if witnesses else 0,
                "lock-order-cycle",
                "inconsistent lock acquisition order "
                + " -> ".join(cyc)
                + f" (witness sites: {', '.join(witnesses)})",
            )
        )
    return findings, all_edges


def _scope_index(source: str):
    """[(start, end)] line ranges of defs/classes, for def-level allows."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []
    out = []
    for node in ast.walk(tree):
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            out.append((node.lineno, node.end_lineno or node.lineno))
    return out


def _suppressed(fd: Finding, allow: dict, scopes) -> bool:
    lines = [fd.line] + [s for s, e in scopes if s <= fd.line <= e]
    for at in lines:
        rules = allow.get(at)
        if rules and (fd.rule in rules or "*" in rules):
            return True
    return False


# -- baseline ------------------------------------------------------------------


def apply_baseline(findings, baseline: dict):
    """Split unguarded-state findings by the baseline map
    ({"file:Class.attr": justification}).  Returns (new findings that FAIL
    the lint, stale baseline keys with no live finding — the ratchet
    reminder)."""
    keys = {fd.key for fd in findings if fd.rule == "unguarded-state"}
    new = [
        fd
        for fd in findings
        if fd.rule != "unguarded-state" or fd.key not in baseline
    ]
    stale = sorted(k for k in baseline if k not in keys)
    return new, stale
