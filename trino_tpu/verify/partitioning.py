"""Partitioning invariants for exchange-placed plans.

The exchange placer may ELIDE a repartition when property derivation says
the data is already placed (bucketed layout or upstream exchange).  These
checks re-derive the properties independently and fail the plan when a
node claims a placement nothing produces — the class of bug where an
elided exchange silently turns a distributed join into a per-shard join of
mis-placed rows (wrong results, no crash).

Rules:

  * partitioning-unproduced — a JoinNode with distribution 'colocated'
    whose sides do NOT share an aligned derived placement;
  * partitioning-misaligned — a partitioned JoinNode where one side is a
    repartition exchange but the other side is neither an exchange nor
    placed on keys aligned with that exchange's partition symbols.
"""

from __future__ import annotations

from trino_tpu.planner import plan as P
from trino_tpu.verify.plan_checker import PlanViolation


def _violation(rule: str, node, message: str) -> PlanViolation:
    return PlanViolation(rule, node, message)


def _is_repartition(node) -> bool:
    return (
        isinstance(node, P.ExchangeNode) and node.kind == "repartition"
    ) or (
        hasattr(node, "exchange_kind") and node.exchange_kind == "repartition"
    )


def _aligned(placements, criteria, left_side: bool, coding=None):
    """Placement tuples of one side expressible in its join keys, with the
    opposite-side image: -> list of (own names, other names).  Only
    dictionary-independent keys count — integer kinds, plus string pairs
    whose two sides share one versioned GLOBAL dictionary assignment
    (`coding`) — the same restriction the placer applies, so a colocated
    claim on producer-local string keys is flagged."""
    from trino_tpu.partitioning import hash_aligned_criteria

    usable = hash_aligned_criteria(criteria, coding)
    if left_side:
        m = {l.name: r.name for l, r in usable}
    else:
        m = {r.name: l.name for l, r in usable}
    out = []
    for t in placements:
        if t and all(n in m for n in t):
            out.append((t, tuple(m[n] for n in t)))
    return out


def check_partitioning(root: P.PlanNode, resolver, n_workers: int) -> list:
    from trino_tpu.partitioning import (
        derive_dictionary_coding,
        derive_partitioning,
    )

    violations: list = []
    for node in P.walk(root):
        if not isinstance(node, P.JoinNode) or not node.criteria:
            continue
        # the verifier re-derives the SAME dictionary-version gate the
        # placer used: a string-key claim passes only when both sides
        # share one (key, version) global assignment
        coding = dict(derive_dictionary_coding(node.left, resolver))
        coding.update(derive_dictionary_coding(node.right, resolver))
        if node.distribution == "colocated":
            lprops = derive_partitioning(node.left, resolver, n_workers)
            rprops = derive_partitioning(node.right, resolver, n_workers)
            pairs = _aligned(lprops, node.criteria, True, coding)
            if not any(other in rprops for _, other in pairs):
                violations.append(
                    _violation(
                        "partitioning-unproduced", node,
                        "join claims colocated but no aligned placement is "
                        f"produced by both sides (left={lprops}, "
                        f"right={rprops})",
                    )
                )
        elif node.distribution == "partitioned":
            l_ex = _is_repartition(node.left)
            r_ex = _is_repartition(node.right)
            if l_ex and r_ex:
                continue
            if not l_ex and not r_ex:
                violations.append(
                    _violation(
                        "partitioning-unproduced", node,
                        "partitioned join has no repartition exchange on "
                        "either side and does not claim colocated",
                    )
                )
                continue
            placed, ex_side = (
                (node.left, node.right) if r_ex else (node.right, node.left)
            )
            props = derive_partitioning(placed, resolver, n_workers)
            pairs = _aligned(props, node.criteria, r_ex, coding)
            ex_names = tuple(
                s.name for s in getattr(ex_side, "partition_symbols", ())
            )
            if not any(other == ex_names for _, other in pairs):
                violations.append(
                    _violation(
                        "partitioning-misaligned", node,
                        "one join side skips its repartition but holds no "
                        f"placement aligned with the exchange keys "
                        f"{ex_names} (placements={props})",
                    )
                )
    return violations
