"""Capacity certificates: sound join-cardinality proofs that delete the
runtime sizing round-trip from the mesh join hot path.

The speculative join (partitioning/speculative.py + parallel/runner.py
`_sized_expansion`) sizes its expand program's static output capacity with a
runtime protocol: either a blocking match-count sync (cold) or a fused
launch guarded by an on-device overflow flag whose post-hoc [W] read is a
`gather/capacity_sizing` collective plus a `join_overflow_check` per run —
PR 14's drift observatory measured warm mesh-8 Q3 carrying two of them on
every execution.  The runtime check exists because the emitted-row count is
data-dependent: each probe row may match any number of build rows.

This pass removes the data dependence with a PROOF instead of a guess (the
PR 10 pattern: a sound static certificate deletes a runtime check).  The
key fact is build-side key uniqueness: when every non-NULL value of the
build-side join key provably occurs at most once, every probe row matches
at most one build row, so a worker's emitted total is bounded by its live
probe rows — which is bounded by the probe batch's STATIC trailing
capacity.  The expand program then compiles at that certified fixed
capacity with no sizing gather, no overflow flag, and no speculative retry.

Admissible proof sources (never estimates):

  * connector generator statistics — exact by construction for the builtin
    tpch/tpcds catalogs: `distinct_count == row_count` with zero null
    fraction proves a scanned column unique;
  * plan structure — aggregation group keys are unique by definition;
    `EnforceSingleRow` / `LIMIT 1` bound a subtree to one row; VALUES with
    distinct literals is unique by inspection;
  * uniqueness PRESERVATION — filters/sorts/limits keep row subsets;
    projections rename; a join multiplies a side by at most 1 when the
    OTHER side's key is unique, so uniqueness survives chains of
    key-unique joins (Q3: o_orderkey stays unique through orders x
    customer because c_custkey is unique);
  * exact filter selectivity — `key = literal` on a unique column admits at
    most 1 row; `key IN (k literals)` at most k; integer range predicates
    on a unique column admit at most the range width (a key-RANGE proof:
    each integer value occurs at most once).  Selectivity FRACTIONS (CBO
    estimates) are never admitted.

Artifacts:

  * `CapacityCertificate` — the machine-checkable proof record attached to
    a `JoinNode` (`capacity_cert`) by `license_join_capacities` at the end
    of plan optimization.  It carries the proven per-probe-row fanout
    bound, sound build/probe row bounds, and — after `seal_licenses` — the
    mesh width it was sealed for.  The runner consults `valid_for(W)`
    before compiling the licensed program: a certificate sealed for W is
    INVALID on any other mesh (a mid-query shrink to W-1 re-plans; a stage
    replaying an old subplan against a shrunk mesh must fall back to the
    runtime sizing path).
  * `check_capacity_certificates` — the verifier rule: re-derives every
    attached certificate from admissible sources and rejects any claim
    TIGHTER than provable (`capacity-unsound` PlanViolation).  A sound
    bound may only ever be looser than the best proof, never tighter.
  * `python -m trino_tpu.verify.capacity` — the CI sweep: plans every
    TPC-H + TPC-DS query, licenses, and verifies every certificate;
    unproven joins are reported (they fall back to the runtime sizing
    path — the escape hatch), unsound certificates fail.

`rows_bound` here supersedes `verify.numeric.row_upper_bound` for join
nodes: with a proven build-key uniqueness fact, a join's output is bounded
by its probe side instead of the |L|x|R| structural product — which is what
lets range certificates (PR 10) license decimal sums ABOVE joins (Q3's
revenue sum compiles the single-plane i64 kernel).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from trino_tpu.expr.ir import Call, Form, Literal, SpecialForm, SymbolRef
from trino_tpu.verify.plan_checker import PlanViolation

#: catalogs whose table_statistics are EXACT generator parameters (the same
#: admissibility rule as verify.numeric._EXACT_STATS_CATALOGS)
_EXACT_STATS_CATALOGS = ("tpch", "tpcds")

#: integer-kind device types admissible for key-range width proofs
_RANGE_KINDS = ("tinyint", "smallint", "integer", "bigint")


@dataclass
class CapacityCertificate:
    """Proof that a join's per-worker emitted-row total is statically
    bounded, licensing a fixed-capacity expand program with no runtime
    sizing.

    Contract: every probe row matches at most `fanout_bound` build rows
    (NULL keys match nothing), so a worker holding `p` live probe rows
    emits at most `fanout_bound * p` rows (left/full joins emit
    max(matches, 1) <= max(fanout_bound, 1) per row).  With the probe
    batch's static per-worker capacity `cap_p`, the licensed expand
    capacity `licensed_out_cap(cap_p)` can therefore never overflow —
    the overflow flag and its [W] host read are deleted, not skipped.

    `mesh_w` is stamped by `seal_licenses` when the plan is fragmented for
    a concrete mesh; `valid_for(W)` fails on any other width so a stage
    executing against a shrunk/grown mesh falls back to the runtime
    sizing path instead of trusting a certificate sealed elsewhere."""

    #: proven max build matches per probe row (1 = build key unique)
    fanout_bound: int
    #: sound bound on TOTAL build-side rows (None = unproven)
    build_rows_bound: Optional[int] = None
    #: sound bound on TOTAL probe-side rows (None = unproven)
    probe_rows_bound: Optional[int] = None
    #: INNER joins only: proven max probe rows per distinct join-key value
    #: (a generator multiplicity fact, e.g. lineitem holds <= 7 rows per
    #: l_orderkey).  Grouping a worker's probe rows by key value bounds
    #: its emitted total by multiplicity x live build rows:
    #: sum_k probe_w(k) * build_w(k) <= m * sum_k build_w(k) <= m * B.
    #: Outer kinds additionally emit unmatched rows, so the fact is only
    #: derived (and only applied) for kind == inner.
    probe_multiplicity_bound: Optional[int] = None
    #: build-side key symbol names the uniqueness proof covers
    key: tuple = ()
    #: audit trail: where each fact came from (stats:/structure:/filter:)
    provenance: tuple = field(default_factory=tuple)
    #: mesh width the license was sealed for (None = not yet sealed)
    mesh_w: Optional[int] = None

    def licensed_out_cap(self, cap_p: int) -> int:
        """Sound per-worker expand capacity for a probe batch of static
        per-worker capacity `cap_p`."""
        b = int(cap_p)
        if self.probe_rows_bound is not None:
            b = min(b, int(self.probe_rows_bound))
        cap = int(self.fanout_bound) * b
        if (
            self.probe_multiplicity_bound is not None
            and self.build_rows_bound is not None
        ):
            # inner-join alternative bound (see field comment): often far
            # tighter than fanout x cap_p when the build side is filtered
            cap = min(
                cap,
                int(self.probe_multiplicity_bound)
                * int(self.build_rows_bound),
            )
        return max(1, cap)

    def valid_for(self, n_workers: int) -> bool:
        return self.mesh_w is not None and int(self.mesh_w) == int(n_workers)

    def to_json(self) -> dict:
        return {
            "fanout_bound": int(self.fanout_bound),
            "build_rows_bound": (
                None if self.build_rows_bound is None
                else int(self.build_rows_bound)
            ),
            "probe_rows_bound": (
                None if self.probe_rows_bound is None
                else int(self.probe_rows_bound)
            ),
            "probe_multiplicity_bound": (
                None if self.probe_multiplicity_bound is None
                else int(self.probe_multiplicity_bound)
            ),
            "key": list(self.key),
            "provenance": list(self.provenance),
            "mesh_w": self.mesh_w,
        }


@dataclass
class GroupCapacityCertificate:
    """Proof that a grouped aggregation produces at most `group_bound`
    distinct groups, licensing the fused exchange's per-destination slot
    capacity without the [W, W] counts gather.

    Contract: the partial aggregation emits at most one state row per
    group per worker, so any worker sends at most `group_bound` rows to
    any destination — `min(group_bound, cap_states)` is a sound slot
    capacity.  `group_bound` counts NULL group-key combinations (GROUP BY
    treats NULL as a value), so it is `prod(ndv_i + nullable_i)` over the
    group keys, intersected with the source's proven row bound."""

    #: proven max distinct group-key combinations (NULL counted as a value)
    group_bound: int
    #: group-key symbol names the proof covers
    key: tuple = ()
    #: audit trail: where each fact came from (stats:/rows:)
    provenance: tuple = field(default_factory=tuple)
    #: mesh width the license was sealed for (None = not yet sealed)
    mesh_w: Optional[int] = None

    def valid_for(self, n_workers: int) -> bool:
        return self.mesh_w is not None and int(self.mesh_w) == int(n_workers)

    def to_json(self) -> dict:
        return {
            "group_bound": int(self.group_bound),
            "key": list(self.key),
            "provenance": list(self.provenance),
            "mesh_w": self.mesh_w,
        }


# -- plan walking --------------------------------------------------------------


class _Ctx:
    """One analysis context per plan: the uniqueness / row-bound / stats
    derivations are mutually recursive (a join's row bound consults the
    other side's uniqueness, which consults row bounds), so they MUST
    share memo tables — per-call memos made deep TPC-DS join trees
    exponential."""

    def __init__(self, catalogs):
        self.catalogs = catalogs
        self.uniq: dict = {}
        self.rows: dict = {}
        self.stats: dict = {}
        self.mult: dict = {}


def _ctx_for(catalogs, ctx) -> "_Ctx":
    return ctx if isinstance(ctx, _Ctx) else _Ctx(catalogs)



def _walk(node, _seen=None):
    if _seen is None:
        _seen = set()
    if id(node) in _seen:
        return
    _seen.add(id(node))
    yield node
    for c in node.children:
        yield from _walk(c, _seen)


def _table_stats(node, catalogs):
    """(TableStatistics, exact) for a scan, or (None, False)."""
    try:
        if catalogs is None or node.handle.catalog not in _EXACT_STATS_CATALOGS:
            return None, False
        conn = catalogs.get(node.handle.catalog)
        ts = conn.metadata().table_statistics(
            node.handle.schema, node.handle.table
        )
        return ts, True
    except Exception:
        return None, False


# -- column statistics resolution (value-range facts for filter proofs) --------


def stats_env(node, catalogs=None, _ctx=None) -> dict:
    """{symbol name -> ColumnStatistics} resolved through rename/subset
    chains down to exact-catalog scans.  Low/high claims stay sound through
    every admitted node: filters/sorts/limits take row subsets, projections
    rename, joins/unions merge disjoint symbol namespaces, aggregations
    keep group-key VALUES drawn from their input."""
    from trino_tpu.planner import plan as P

    ctx = _ctx_for(catalogs, _ctx)
    _memo = ctx.stats
    hit = _memo.get(id(node))
    if hit is not None:
        return hit
    _memo[id(node)] = {}  # cycle guard
    out: dict = {}
    if isinstance(node, P.TableScanNode):
        ts, exact = _table_stats(node, catalogs)
        if exact and ts is not None:
            for sym, col in node.assignments:
                cs = (ts.columns or {}).get(col)
                if cs is not None:
                    out[sym.name] = cs
    elif isinstance(node, P.ProjectNode):
        src = stats_env(node.source, catalogs, ctx)
        for sym, e in node.assignments:
            if isinstance(e, SymbolRef) and e.name in src:
                out[sym.name] = src[e.name]
    elif isinstance(node, P.AggregationNode):
        src = stats_env(node.source, catalogs, ctx)
        for g in node.group_symbols:
            if g.name in src:
                out[g.name] = src[g.name]
    elif isinstance(
        node,
        (
            P.FilterNode, P.SortNode, P.TopNNode, P.LimitNode, P.SampleNode,
            P.MarkDistinctNode, P.ExchangeNode, P.EnforceSingleRowNode,
            P.OutputNode, P.WindowNode, P.SemiJoinNode, P.JoinNode,
        ),
    ):
        for c in node.children:
            out.update(stats_env(c, catalogs, ctx))
    _memo[id(node)] = out
    return out


# -- uniqueness derivation -----------------------------------------------------


def _covers(unique_sets_of_node, cols: frozenset) -> bool:
    """Is the column set proven unique?  Any proven subset suffices: if
    (a) holds each non-null value at most once, so does (a, b)."""
    return any(u <= cols for u in unique_sets_of_node)


def _dictionary_unique_scan(handle, column: str, t, catalogs, rows) -> bool:
    """A `unique` global dictionary entry whose size equals the table's
    exact row count is a NULL-FREE BIJECTION (code space == row space):
    a STRUCTURAL exact-distinct witness, which is how capacity
    certificates reach varchar dimension keys (the business keys the
    benchmark generators mint densely, e.g. TPC-DS `*_id`)."""
    from trino_tpu import types as T

    if not T.is_string_kind(t):
        return False
    from trino_tpu.runtime.dictionary_service import DICTIONARY_SERVICE

    ent = DICTIONARY_SERVICE.lookup(handle, column, catalogs)
    return (
        ent is not None
        and ent.unique
        and len(ent.dictionary.values) == int(rows)
    )


def unique_sets(node, catalogs=None, _ctx=None) -> frozenset:
    """Minimal symbol-name sets proven NON-NULL-UNIQUE on the node's
    output: every non-NULL value combination of the set occurs in at most
    one row.  (NULLs are excluded deliberately: an equi-join key never
    matches NULL, so non-null uniqueness is exactly the fanout fact.)
    `frozenset()` as a member means the node provably emits at most one
    row (every column set is then unique)."""
    from trino_tpu.planner import plan as P

    ctx = _ctx_for(catalogs, _ctx)
    _memo = ctx.uniq
    hit = _memo.get(id(node))
    if hit is not None:
        return hit
    _memo[id(node)] = frozenset()  # cycle guard
    out: set = set()
    if isinstance(node, P.TableScanNode):
        ts, exact = _table_stats(node, catalogs)
        rows = ts.row_count if (exact and ts is not None) else None
        if rows is not None and rows <= 1:
            out.add(frozenset())
        elif rows is not None:
            for sym, col in node.assignments:
                cs = (ts.columns or {}).get(col)
                if (
                    cs is not None
                    and cs.distinct_count is not None
                    and int(cs.distinct_count) >= int(rows)
                    and not cs.null_fraction
                    # estimates and probabilistic bounds never prove
                    # uniqueness: a random FK on a 2-row table claims
                    # ndv == rows and can still collide.  Only counts the
                    # connector marks STRUCTURALLY exact (dense surrogate
                    # keys) are admissible fanout witnesses.
                    and getattr(cs, "exact_distinct", False)
                ):
                    out.add(frozenset({sym.name}))
                    continue
                if _dictionary_unique_scan(
                    node.handle, col, sym.type, catalogs, rows
                ):
                    out.add(frozenset({sym.name}))
    elif isinstance(node, P.ValuesNode):
        if len(node.rows) <= 1:
            out.add(frozenset())
        else:
            for i, sym in enumerate(node.outputs):
                vals = [r[i] if i < len(r) else None for r in node.rows]
                try:
                    distinct = (
                        all(v is not None for v in vals)
                        and len(set(vals)) == len(vals)
                    )
                except TypeError:  # unhashable literals: no claim
                    distinct = False
                if distinct:
                    out.add(frozenset({sym.name}))
    elif isinstance(node, P.EnforceSingleRowNode):
        out.add(frozenset())
    elif isinstance(node, (P.LimitNode, P.TopNNode)):
        out |= unique_sets(node.source, catalogs, ctx)
        if node.count is not None and int(node.count) <= 1:
            out.add(frozenset())
    elif isinstance(node, P.AggregationNode):
        out.add(frozenset(g.name for g in node.group_symbols))
    elif isinstance(node, P.WindowNode):
        out |= unique_sets(node.source, catalogs, ctx)
        if not node.partition_by:
            for sym, fn in node.functions:
                if fn.name == "row_number":
                    out.add(frozenset({sym.name}))
    elif isinstance(node, P.ProjectNode):
        src = unique_sets(node.source, catalogs, ctx)
        rename: dict = {}
        for sym, e in node.assignments:
            if isinstance(e, SymbolRef) and e.name not in rename:
                rename[e.name] = sym.name
        for u in src:
            if all(n in rename for n in u):
                out.add(frozenset(rename[n] for n in u))
    elif isinstance(node, P.JoinNode):
        l_u = unique_sets(node.left, catalogs, ctx)
        r_u = unique_sets(node.right, catalogs, ctx)
        # a side's uniqueness survives iff the join multiplies each of its
        # rows by at most one: the OTHER side's key is unique, or the
        # other side provably holds at most one row (covers cross joins).
        # Outer kinds only ADD null-extended rows, which never carry
        # non-null values of the preserved side's columns beyond their one
        # match — non-null uniqueness is unaffected.
        lkeys = frozenset(l.name for l, _ in node.criteria)
        rkeys = frozenset(r.name for _, r in node.criteria)
        r_bound = rows_bound(node.right, catalogs, ctx)
        l_bound = rows_bound(node.left, catalogs, ctx)
        if (node.criteria and _covers(r_u, rkeys)) or (
            r_bound is not None and r_bound <= 1
        ):
            out |= l_u
        if (node.criteria and _covers(l_u, lkeys)) or (
            l_bound is not None and l_bound <= 1
        ):
            out |= r_u
    elif isinstance(node, P.SemiJoinNode):
        out |= unique_sets(node.source, catalogs, ctx)
    elif isinstance(
        node,
        (
            P.FilterNode, P.SortNode, P.SampleNode, P.MarkDistinctNode,
            P.ExchangeNode, P.OutputNode,
        ),
    ):
        for c in node.children:
            out |= unique_sets(c, catalogs, ctx)
    # Union/Unnest/PatternRecognition/RemoteSource/default: no claim
    res = frozenset(out)
    _memo[id(node)] = res
    return res


# -- multiplicity derivation ---------------------------------------------------

#: (catalog, table, column) -> max rows holding any one distinct value of
#: the column — STRUCTURAL facts of the benchmark generators (the same
#: admissibility rule as exact_distinct: these are spec-mandated
#: parameters of the data, never estimates).  TPC-H 3.0 spec: each order
#: generates 1..7 lineitems (clause 4.2.5); each part gets exactly 4
#: partsupp suppliers (clause 4.2.3).
_GENERATOR_MULTIPLICITY = {
    ("tpch", "lineitem", "l_orderkey"): 7,
    ("tpch", "partsupp", "ps_partkey"): 4,
    ("tpch", "partsupp", "ps_suppkey"): 80,  # P/S = 200000/10000 per SF
}


def multiplicity_bound(node, cols: frozenset, catalogs=None, _ctx=None) -> Optional[int]:
    """Sound upper bound on how many output rows of `node` can hold any
    ONE non-NULL distinct value combination of the symbol-name set
    `cols`, or None when no admissible proof exists.  A proven-unique set
    has multiplicity 1; generator facts bound scan columns; row-subset
    nodes can only shrink a value's row count; a superset of a bounded
    column set is at least as selective, so any single-column fact in
    `cols` bounds the whole set."""
    from trino_tpu.planner import plan as P

    ctx = _ctx_for(catalogs, _ctx)
    memo_key = (id(node), cols)
    if memo_key in ctx.mult:
        return ctx.mult[memo_key]
    ctx.mult[memo_key] = None  # cycle guard
    candidates = []
    if _covers(unique_sets(node, catalogs, ctx), cols):
        candidates.append(1)
    rb = rows_bound(node, catalogs, ctx)
    if rb is not None:
        candidates.append(int(rb))
    if isinstance(node, P.TableScanNode):
        _, exact = _table_stats(node, catalogs)
        if exact:
            h = node.handle
            for sym, col in node.assignments:
                if sym.name not in cols:
                    continue
                m = _GENERATOR_MULTIPLICITY.get((h.catalog, h.table, col))
                if m is not None:
                    candidates.append(int(m))
    elif isinstance(node, P.ProjectNode):
        # reverse every col through its rename; a non-rename assignment
        # for a member admits no claim through this path
        back = {
            sym.name: e.name
            for sym, e in node.assignments
            if isinstance(e, SymbolRef)
        }
        if all(n in back for n in cols):
            m = multiplicity_bound(
                node.source, frozenset(back[n] for n in cols), catalogs, ctx
            )
            if m is not None:
                candidates.append(m)
    elif isinstance(node, P.JoinNode):
        # a side's multiplicity survives when the join multiplies each of
        # its rows by at most one (same condition as unique_sets): the
        # other side's key is unique, or it holds at most one row.  Outer
        # null-extensions carry NULL key values, which non-NULL
        # multiplicity excludes by definition.
        lkeys = frozenset(l.name for l, _ in node.criteria)
        rkeys = frozenset(r.name for _, r in node.criteria)
        r_one = (
            bool(node.criteria)
            and _covers(unique_sets(node.right, catalogs, ctx), rkeys)
        ) or (
            (b := rows_bound(node.right, catalogs, ctx)) is not None and b <= 1
        )
        l_one = (
            bool(node.criteria)
            and _covers(unique_sets(node.left, catalogs, ctx), lkeys)
        ) or (
            (b := rows_bound(node.left, catalogs, ctx)) is not None and b <= 1
        )
        if r_one:
            m = multiplicity_bound(node.left, cols, catalogs, ctx)
            if m is not None:
                candidates.append(m)
        if l_one:
            m = multiplicity_bound(node.right, cols, catalogs, ctx)
            if m is not None:
                candidates.append(m)
    elif isinstance(node, P.SemiJoinNode):
        m = multiplicity_bound(node.source, cols, catalogs, ctx)
        if m is not None:
            candidates.append(m)
    elif isinstance(
        node,
        (
            P.FilterNode, P.SortNode, P.TopNNode, P.LimitNode, P.SampleNode,
            P.MarkDistinctNode, P.ExchangeNode, P.EnforceSingleRowNode,
            P.OutputNode, P.WindowNode,
        ),
    ) and len(node.children) == 1:
        # row-subset / row-preserving: no value combination gains rows
        m = multiplicity_bound(node.children[0], cols, catalogs, ctx)
        if m is not None:
            candidates.append(m)
    out = min(candidates) if candidates else None
    ctx.mult[memo_key] = out
    return out


# -- sound row bounds with exact-filter refinement -----------------------------


def conjuncts(expr):
    """Flatten an AND tree into its conjuncts (any non-AND node is one
    conjunct).  Shared with `verify.numeric.refine_env`: both admissible
    proof-source passes must agree on what counts as a conjunct."""
    if isinstance(expr, SpecialForm) and expr.form == Form.AND:
        for a in expr.args:
            yield from conjuncts(a)
    else:
        yield expr


#: operand swap for sym/literal comparisons: `lit OP sym == sym
#: FLIPPED_CMP[OP] lit` — shared with verify.numeric so both passes flip
#: identically
FLIPPED_CMP = {
    "$eq": "$eq", "$lt": "$gt", "$le": "$ge", "$gt": "$lt", "$ge": "$le"
}


def _lit_value(e):
    """The python value of a non-null Literal, else None."""
    if isinstance(e, Literal) and e.value is not None:
        return e.value
    return None


def _int_lit(e):
    v = _lit_value(e)
    if isinstance(v, bool) or not isinstance(v, int):
        return None
    return int(v)


def _range_kind(sym: SymbolRef) -> bool:
    t = getattr(sym, "type", None)
    name = getattr(t, "name", "")
    return name in _RANGE_KINDS or name == "date"


def _conjunct_rows(c, uniq, stats) -> Optional[int]:
    """Sound row bound admitted by ONE filter conjunct, or None.  Only
    exact proofs: equality/IN/range on a proven-unique column (each
    admitted value occurs at most once, so the bound is the count of
    admitted integer values)."""

    def unique_sym(e) -> Optional[SymbolRef]:
        if isinstance(e, SymbolRef) and _covers(uniq, frozenset({e.name})):
            return e
        return None

    if isinstance(c, Call) and c.name == "$eq" and len(c.args) == 2:
        a, b = c.args
        for s, lit in ((a, b), (b, a)):
            if unique_sym(s) is not None and _lit_value(lit) is not None:
                return 1
    if isinstance(c, SpecialForm) and c.form == Form.IN and len(c.args) >= 2:
        s = unique_sym(c.args[0])
        if s is not None and all(
            _lit_value(x) is not None for x in c.args[1:]
        ):
            return len(c.args) - 1
    if (
        isinstance(c, SpecialForm)
        and c.form == Form.BETWEEN
        and len(c.args) == 3
    ):
        s = unique_sym(c.args[0])
        lo, hi = _int_lit(c.args[1]), _int_lit(c.args[2])
        if s is not None and _range_kind(s) and lo is not None and hi is not None:
            return max(0, hi - lo + 1)
    if isinstance(c, Call) and c.name in ("$lt", "$le", "$gt", "$ge") and len(c.args) == 2:
        a, b = c.args
        sym, lit, op = None, None, c.name
        if isinstance(a, SymbolRef) and _int_lit(b) is not None:
            sym, lit = a, _int_lit(b)
        elif isinstance(b, SymbolRef) and _int_lit(a) is not None:
            sym, lit = b, _int_lit(a)
            op = FLIPPED_CMP[op]
        if sym is None or unique_sym(sym) is None or not _range_kind(sym):
            return None
        cs = stats.get(sym.name)
        if cs is None or cs.low is None or cs.high is None:
            return None
        try:
            low, high = int(cs.low), int(cs.high)
        except (TypeError, ValueError):
            return None
        # admitted integer range under the predicate, intersected with the
        # column's exact [low, high]; each value occurs at most once
        if op == "$lt":
            return max(0, min(high, lit - 1) - low + 1)
        if op == "$le":
            return max(0, min(high, lit) - low + 1)
        if op == "$gt":
            return max(0, high - max(low, lit + 1) + 1)
        return max(0, high - max(low, lit) + 1)
    return None


def _predicate_rows(pred, source, catalogs, ctx) -> Optional[int]:
    uniq = unique_sets(source, catalogs, ctx)
    if not uniq:
        return None
    stats = stats_env(source, catalogs, ctx)
    best: Optional[int] = None
    for c in conjuncts(pred):
        b = _conjunct_rows(c, uniq, stats)
        if b is not None:
            best = b if best is None else min(best, b)
    return best


def rows_bound(node, catalogs=None, _ctx=None) -> Optional[int]:
    """A SOUND upper bound on the rows `node` can produce, or None.
    Extends `verify.numeric.row_upper_bound` with the two facts this
    module proves: exact filter selectivity on unique columns, and
    fanout-aware join bounds (a join whose build key is unique emits at
    most its probe side, not the |L|x|R| structural product)."""
    from trino_tpu.planner import plan as P

    ctx = _ctx_for(catalogs, _ctx)
    _memo = ctx.rows
    key = id(node)
    if key in _memo:
        return _memo[key]
    _memo[key] = None  # cycle guard
    out: Optional[int] = None
    if isinstance(node, P.TableScanNode):
        ts, exact = _table_stats(node, catalogs)
        if exact and ts is not None and ts.row_count is not None:
            out = int(ts.row_count)
        if node.pushed_predicate is not None:
            pb = _predicate_rows(node.pushed_predicate, node, catalogs, ctx)
            if pb is not None:
                out = pb if out is None else min(out, pb)
    elif isinstance(node, P.FilterNode):
        out = rows_bound(node.source, catalogs, ctx)
        pb = _predicate_rows(node.predicate, node.source, catalogs, ctx)
        if pb is not None:
            out = pb if out is None else min(out, pb)
    elif isinstance(node, P.ValuesNode):
        out = len(node.rows)
    elif isinstance(node, (P.LimitNode, P.TopNNode)):
        child = rows_bound(node.source, catalogs, ctx)
        n = None if node.count is None else int(node.count)
        if n is not None:
            out = n if child is None else min(n, child)
        else:
            out = child
    elif isinstance(node, P.EnforceSingleRowNode):
        out = 1
    elif isinstance(node, P.JoinNode):
        out = _join_rows_bound(node, catalogs, ctx)
    elif isinstance(node, P.UnionNode):
        kids = [rows_bound(c, catalogs, ctx) for c in node.children]
        if all(k is not None for k in kids):
            out = sum(kids)
    elif isinstance(node, (P.UnnestNode, P.PatternRecognitionNode)):
        out = None  # row-expanding
    elif len(node.children) == 1:
        # structure-preserving / row-subset nodes (filter handled above):
        # project, aggregation, sort, window, sample, output, exchange,
        # mark-distinct — none emits more rows than its input
        out = rows_bound(node.children[0], catalogs, ctx)
    elif isinstance(node, P.SemiJoinNode):
        out = rows_bound(node.source, catalogs, ctx)
    _memo[key] = out
    return out


def _join_rows_bound(node, catalogs, ctx) -> Optional[int]:
    from trino_tpu.planner import plan as P

    assert isinstance(node, P.JoinNode)
    l = rows_bound(node.left, catalogs, ctx)
    r = rows_bound(node.right, catalogs, ctx)
    lkeys = frozenset(x.name for x, _ in node.criteria)
    rkeys = frozenset(x.name for _, x in node.criteria)
    r_unique = bool(node.criteria) and _covers(
        unique_sets(node.right, catalogs, ctx), rkeys
    )
    l_unique = bool(node.criteria) and _covers(
        unique_sets(node.left, catalogs, ctx), lkeys
    )
    candidates = []
    if l is not None and r is not None:
        candidates.append(l * r + l + r)  # structural, outer rows included
    # fanout-aware: with a unique key on one side, each OTHER-side row
    # emits at most max(1, matches) = 1 row.  A join kind that PRESERVES
    # the unique side additionally emits its unmatched rows, so that
    # side's own bound must be KNOWN and added — an unknown (None)
    # preserved side admits no claim (never treat unknown as zero).
    if r_unique and l is not None:
        if node.kind in ("inner", "left"):
            candidates.append(l)  # left joins emit match-or-null per row
        elif r is not None:  # right/full also preserve the right side
            candidates.append(l + r)
    if l_unique and r is not None:
        if node.kind in ("inner", "right"):
            candidates.append(r)
        elif l is not None:  # left/full also preserve the left side
            candidates.append(r + l)
    if not candidates:
        return None
    return min(candidates)


# -- the license ---------------------------------------------------------------


def derive_join_certificate(node, catalogs=None, _ctx=None) -> Optional[CapacityCertificate]:
    """Re-derivable proof for one JoinNode, or None when no admissible
    proof exists.  The licensed fanout is 1 when the build key is proven
    unique, else the build side's proven key multiplicity (a generator
    fact like lineitem's <= 7 rows per l_orderkey) — both exactly the
    cases whose runtime sizing the runner deletes.  Inner joins
    additionally carry the PROBE side's key multiplicity, which bounds
    the emitted total by `multiplicity x build_rows_bound` (see the
    `probe_multiplicity_bound` field contract)."""
    from trino_tpu.planner import plan as P

    if not isinstance(node, P.JoinNode) or not node.criteria:
        return None
    if node.kind not in ("inner", "left", "full"):
        # 'right' flips sides at exchange placement; licensing it here
        # would describe the wrong build side
        return None
    ctx = _ctx_for(catalogs, _ctx)
    rkeys = frozenset(r.name for _, r in node.criteria)
    r_u = unique_sets(node.right, catalogs, ctx)
    prov = []
    if _covers(r_u, rkeys):
        fanout = 1
        witness = min(
            (u for u in r_u if u <= rkeys), key=lambda u: (len(u), sorted(u))
        )
        prov.append(
            "unique:build[%s]" % ",".join(sorted(witness) or ("<single-row>",))
        )
    else:
        fanout = multiplicity_bound(node.right, rkeys, catalogs, ctx)
        if fanout is None:
            return None
        prov.append(f"multiplicity:build<={fanout}/key")
    build_rows = rows_bound(node.right, catalogs, ctx)
    probe_rows = rows_bound(node.left, catalogs, ctx)
    probe_mult = None
    if node.kind == "inner" and build_rows is not None:
        lkeys = frozenset(l.name for l, _ in node.criteria)
        probe_mult = multiplicity_bound(node.left, lkeys, catalogs, ctx)
        if probe_mult is not None:
            prov.append(f"multiplicity:probe<={probe_mult}/key")
    if build_rows is not None:
        prov.append(f"rows:build<={build_rows}")
    if probe_rows is not None:
        prov.append(f"rows:probe<={probe_rows}")
    return CapacityCertificate(
        fanout_bound=fanout,
        build_rows_bound=build_rows,
        probe_rows_bound=probe_rows,
        probe_multiplicity_bound=probe_mult,
        key=tuple(sorted(rkeys)),
        provenance=tuple(prov),
    )


def derive_group_certificate(node, catalogs=None, _ctx=None) -> Optional["GroupCapacityCertificate"]:
    """Re-derivable group-count proof for one grouped AggregationNode, or
    None.  Admissible sources: the product of exact distinct counts over
    the group keys (each key's NULL adds one value — GROUP BY groups
    NULLs), and the source's proven row bound."""
    from trino_tpu.planner import plan as P

    if not isinstance(node, P.AggregationNode) or not node.group_symbols:
        return None
    ctx = _ctx_for(catalogs, _ctx)
    stats = stats_env(node.source, catalogs, ctx)
    prov = []
    candidates = []
    prod = 1
    for g in node.group_symbols:
        cs = stats.get(g.name)
        if (
            cs is None
            or cs.distinct_count is None
            or not getattr(cs, "exact_distinct", False)
        ):
            prod = None
            break
        dc = int(cs.distinct_count) + (1 if cs.null_fraction else 0)
        prod *= max(1, dc)
    if prod is not None:
        candidates.append(prod)
        prov.append(f"stats:distinct<={prod}")
    rb = rows_bound(node.source, catalogs, ctx)
    if rb is not None:
        candidates.append(max(1, int(rb)))
        prov.append(f"rows:source<={rb}")
    if not candidates:
        return None
    return GroupCapacityCertificate(
        group_bound=min(candidates),
        key=tuple(sorted(g.name for g in node.group_symbols)),
        provenance=tuple(prov),
    )


def license_join_capacities(plan, catalogs=None) -> int:
    """The planner-facing licensing pass: attach a `capacity_cert` to every
    join with an admissible fanout proof and to every grouped aggregation
    with an admissible group-count proof.  Runs at the end of
    `optimize()` — before exchange placement and fragmentation, which both
    carry the field through reconstruction.  Proof-only: never changes
    plan shape or results.  Returns the number licensed."""
    from trino_tpu.planner import plan as P

    n = 0
    ctx = _Ctx(catalogs)
    for node in _walk(plan):
        if isinstance(node, P.JoinNode):
            cert = derive_join_certificate(node, catalogs, ctx)
        elif isinstance(node, P.AggregationNode):
            cert = derive_group_certificate(node, catalogs, ctx)
        else:
            continue
        if cert is not None:
            node.capacity_cert = cert
            n += 1
    return n


def seal_licenses(root, n_workers: int) -> int:
    """Stamp every attached certificate with the mesh width the plan was
    fragmented for.  The runner's `valid_for(W)` check then rejects a
    certificate on any OTHER mesh (e.g. a mid-query shrink to W-1 running
    an old subplan) and falls back to the runtime sizing path.  Returns
    the number sealed."""
    n = 0
    for node in _walk(root):
        cert = getattr(node, "capacity_cert", None)
        if cert is not None:
            cert.mesh_w = int(n_workers)
            n += 1
    return n


# -- the verifier rule ---------------------------------------------------------


def check_capacity_certificates(plan, catalogs=None) -> list:
    """Re-derive every attached certificate and reject unsound claims.
    Soundness is one-directional: a certificate may claim LOOSER bounds
    than provable (a weaker true statement), never tighter — a fanout or
    row bound below what admissible sources support licenses an expand
    capacity the data can overflow, which is silent corruption on the
    checked path.  Returns PlanViolations (`capacity-unsound`)."""
    from trino_tpu.planner import plan as P

    violations = []
    ctx = _Ctx(catalogs)

    def bad(node, msg):
        violations.append(PlanViolation("capacity-unsound", node, msg))

    for node in _walk(plan):
        cert = getattr(node, "capacity_cert", None)
        if cert is None:
            continue
        if isinstance(node, P.AggregationNode):
            if not isinstance(cert, GroupCapacityCertificate):
                bad(node, "aggregation carries a non-group certificate")
                continue
            if int(cert.group_bound) < 1:
                bad(node, f"group_bound {cert.group_bound} < 1 is vacuous")
                continue
            gd = derive_group_certificate(node, catalogs, ctx)
            if gd is None:
                bad(
                    node,
                    "no admissible group-count proof exists for group keys "
                    f"{cert.key} — the certificate asserts <= "
                    f"{cert.group_bound} groups without a witness",
                )
            elif int(cert.group_bound) < int(gd.group_bound):
                bad(
                    node,
                    f"group_bound {cert.group_bound} is tighter than the "
                    f"provable bound {gd.group_bound}",
                )
            continue
        if not isinstance(node, P.JoinNode):
            bad(node, "capacity_cert attached to a non-join node")
            continue
        if isinstance(cert, GroupCapacityCertificate):
            bad(node, "join carries a group certificate")
            continue
        if int(cert.fanout_bound) < 1:
            bad(node, f"fanout_bound {cert.fanout_bound} < 1 is vacuous")
            continue
        derived = derive_join_certificate(node, catalogs, ctx)
        if derived is None:
            bad(
                node,
                "no admissible proof exists for this join's build key "
                f"{cert.key} — the certificate asserts fanout <= "
                f"{cert.fanout_bound} without a uniqueness witness",
            )
            continue
        if int(cert.fanout_bound) < int(derived.fanout_bound):
            bad(
                node,
                f"fanout_bound {cert.fanout_bound} is tighter than the "
                f"provable bound {derived.fanout_bound}",
            )
        for name in (
            "build_rows_bound", "probe_rows_bound", "probe_multiplicity_bound",
        ):
            claimed = getattr(cert, name, None)
            provable = getattr(derived, name)
            if claimed is None:
                continue
            if provable is None or int(claimed) < int(provable):
                bad(
                    node,
                    f"{name} {claimed} is tighter than admissible sources "
                    f"prove ({provable})",
                )
    return violations


# -- CLI: sweep every TPC-H + TPC-DS plan --------------------------------------


def verify_benchmarks(verbose: bool = False) -> dict:
    """Plan every TPC-H + TPC-DS query, run the licensing pass (it already
    ran inside optimize(); this re-derives), and verify every attached
    certificate.  Returns {joins, licensed, violations}; unsound
    certificates raise."""
    from trino_tpu.planner import plan as P
    from trino_tpu.runtime.runner import LocalQueryRunner

    def _varchar_keyed(n) -> bool:
        from trino_tpu import types as T

        return any(
            T.is_string_kind(l.type) or T.is_string_kind(r.type)
            for l, r in n.criteria
        )

    totals = {
        "queries": 0, "joins": 0, "licensed": 0, "agg_licensed": 0,
        "varchar_licensed": 0, "violations": 0,
    }

    def _sweep(r, catalog: str, q: str, sql: str) -> None:
        plan = r.create_plan(sql)
        totals["queries"] += 1
        joins = [n for n in _walk(plan) if isinstance(n, P.JoinNode)]
        licensed = [
            n for n in joins
            if getattr(n, "capacity_cert", None) is not None
        ]
        totals["joins"] += len(joins)
        totals["licensed"] += len(licensed)
        totals["varchar_licensed"] += sum(
            1 for n in licensed if _varchar_keyed(n)
        )
        totals["agg_licensed"] += sum(
            1
            for n in _walk(plan)
            if isinstance(n, P.AggregationNode)
            and getattr(n, "capacity_cert", None) is not None
        )
        violations = check_capacity_certificates(plan, r.catalogs)
        totals["violations"] += len(violations)
        if violations:
            raise violations[0]
        if verbose:
            for n in licensed:
                print(
                    f"{catalog} {q}: licensed join on {n.capacity_cert.key} "
                    f"({', '.join(n.capacity_cert.provenance)})"
                )

    suites = (
        ("tpch", "tiny", "trino_tpu.connectors.tpch.queries"),
        ("tpcds", "tiny", "trino_tpu.connectors.tpcds.queries"),
    )
    for catalog, schema, mod in suites:
        import importlib

        queries = importlib.import_module(mod).QUERIES
        r = LocalQueryRunner(catalog=catalog, schema=schema)
        for q in sorted(queries):
            _sweep(r, catalog, q, queries[q])
    # varchar-key probes: dictionary-backed `unique` business keys
    # (null-free bijections) must license joins the same way dense
    # integer surrogates do — the global dictionary service's capacity
    # reach, asserted by `python -m trino_tpu.verify.capacity`
    probes = (
        ("tpcds", "tiny", "varchar:c_customer_id",
         "SELECT count(*) FROM customer c1 JOIN customer c2 "
         "ON c1.c_customer_id = c2.c_customer_id"),
        ("tpcds", "tiny", "varchar:d_date_id",
         "SELECT count(*) FROM date_dim d1 JOIN date_dim d2 "
         "ON d1.d_date_id = d2.d_date_id"),
    )
    for catalog, schema, q, sql in probes:
        r = LocalQueryRunner(catalog=catalog, schema=schema)
        _sweep(r, catalog, q, sql)
    return totals


def main() -> int:  # pragma: no cover - CLI entry
    import argparse

    ap = argparse.ArgumentParser(
        description="capacity-certificate sweep over all TPC-H + TPC-DS "
        "plans: license joins with sound cardinality proofs and verify "
        "every attached certificate against re-derivation"
    )
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args()
    t = verify_benchmarks(args.verbose)
    print(
        f"capacity: {t['queries']} plans, {t['joins']} joins — "
        f"{t['licensed']} LICENSED (runtime sizing deleted), "
        f"{t['joins'] - t['licensed']} runtime-check fallback, "
        f"{t['varchar_licensed']} varchar-keyed licensed "
        "(dictionary-backed uniqueness), "
        f"{t['agg_licensed']} group-count licensed aggregation(s), "
        f"{t['violations']} VIOLATION(s)"
    )
    if not t["varchar_licensed"]:
        print(
            "capacity: FAIL — no varchar-keyed join licensed; the global "
            "dictionary service's exact_distinct reach is broken"
        )
        return 1
    return 1 if t["violations"] else 0


if __name__ == "__main__":  # pragma: no cover
    import sys

    # `python -m` loads this file as `__main__`, a SECOND copy of the
    # module — its certificate classes would then differ from the ones
    # optimize() attached and every isinstance re-derivation check would
    # miscompare.  Delegate to the canonical import instead.
    from trino_tpu.verify import capacity as _canonical

    sys.exit(_canonical.main())
